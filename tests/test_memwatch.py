"""Compile- and memory-plane observability (obs/memwatch.py) and its
wiring: AOT compile accounting on real jitted CPU executables, the
executable-cache recompile watch, the three mem-plane anomaly rules
(recompile_storm / device_mem_leak / hbm_headroom), the reshape fault
that manufactures a deterministic retrace, and the report/registry
round-trips of the new fields.

Extraction is pinned against a real ``lower().compile()`` so the keys
track jax's actual API shapes (cost_analysis returns a LIST of dicts on
CPU; memory_analysis a CompiledMemoryStats); the rules are pinned with
synthetic streams so their streak/latch semantics are checked against
known inputs, never against themselves. CPU has no memory_stats, which
doubles as the degraded-backend case the watch must survive.
"""

import copy
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.obs import HALT_EXIT_CODE
from gtopkssgd_tpu.obs import registry as obs_registry
from gtopkssgd_tpu.obs import report as obs_report
from gtopkssgd_tpu.obs.events import AnomalyHalt, AnomalyMonitor, Thresholds
from gtopkssgd_tpu.obs.memwatch import (
    CompileWatch,
    MemWatch,
    batch_shape_key,
    compile_record,
    compiled_flops,
    cost_summary,
    device_memory_summary,
    live_array_summary,
    memory_summary,
)
from gtopkssgd_tpu.resilience import FaultInjector
from gtopkssgd_tpu.utils.metrics import MetricsLogger


def _records(out_dir):
    path = os.path.join(out_dir, "metrics.jsonl")
    return [json.loads(line) for line in open(path)]


# -------------------------------------------------------------- extraction

def test_extraction_roundtrip_on_jitted_step():
    """cost/memory summaries off a real compiled executable: identifier-
    safe keys, the peak-HBM decomposition identity, and compiled_flops
    as the one flop path (benchmark.py aliases it for MFU)."""
    x = jnp.arange(16, dtype=jnp.float32)
    compiled = jax.jit(lambda v: (v * 2.0 + 1.0).sum()).lower(x).compile()
    cost = cost_summary(compiled)
    assert set(cost) <= {"flops", "bytes_accessed"}
    assert compiled_flops(compiled) == cost.get("flops")
    mem = memory_summary(compiled)
    assert mem, "CPU memory_analysis produced nothing"
    assert mem["argument_bytes"] >= x.nbytes
    assert mem["output_bytes"] >= 4
    expect = (mem.get("argument_bytes", 0) + mem.get("output_bytes", 0)
              + mem.get("temp_bytes", 0)
              + mem.get("generated_code_bytes", 0)
              - mem.get("alias_bytes", 0))
    assert mem["peak_hbm_bytes"] == max(expect, 0)
    rec = compile_record(compiled, shape_key="k", lower_s=0.5, compile_s=2)
    assert rec["shape_key"] == "k"
    assert rec["lower_s"] == 0.5 and rec["compile_s"] == 2.0
    assert rec["peak_hbm_bytes"] == mem["peak_hbm_bytes"]


def test_batch_shape_key_identity_and_digest():
    a = {"x": np.zeros((4, 3), np.float32)}
    assert batch_shape_key(a) == "4x3:float32"
    assert batch_shape_key(a) == batch_shape_key(
        {"x": jax.ShapeDtypeStruct((4, 3), jnp.float32)}), \
        "abstract and concrete leaves must hit the same memo entry"
    assert batch_shape_key({"x": np.zeros((2, 3), np.float32)}) \
        != batch_shape_key(a)
    # a train-state-sized tree collapses to a digest, not a page
    big = [np.zeros((i + 1,), np.float32) for i in range(64)]
    key = batch_shape_key(big)
    assert key.startswith("sha1:") and key.endswith(":64leaves")
    assert len(key) <= 160


# ---------------------------------------------------------- recompile watch

def test_compile_watch_adopts_baseline_then_detects_growth():
    fn = jax.jit(lambda v: v + 1.0)
    fn(jnp.zeros((4,), jnp.float32))
    watch = CompileWatch(fn, use_monitoring=False)
    assert watch.poll() is None      # first poll adopts, never fires
    assert watch.poll() is None      # stable cache
    fn(jnp.zeros((8,), jnp.float32))  # new shape -> retrace
    grown, size = watch.poll()
    assert grown == 1 and size == watch.last
    assert watch.poll() is None      # growth reported exactly once
    watch.close()


def test_recompile_warmup_arms_before_firing():
    """Arm-before-update: growth during the first recompile_warmup polls
    is warm-up compilation, not a storm."""
    mon = AnomalyMonitor(thresholds=Thresholds(recompile_warmup=2))
    assert mon.observe_compile(1, cache_size=1, grew=False) == []
    assert mon.observe_compile(2, cache_size=2, grew=True) == []
    fired = mon.observe_compile(3, cache_size=3, grew=True)
    assert [e["rule"] for e in fired] == ["recompile_storm"]


def test_recompile_storm_record_before_halt(tmp_path):
    """The full chain on a real jitted fn: cache growth -> fsync'd
    recompile record -> recompile_storm -> AnomalyHalt under
    halt_on=warn, with the record durably written BEFORE the halt."""
    out = str(tmp_path)
    metrics = MetricsLogger(out)
    mon = AnomalyMonitor(metrics=metrics, halt_on="warn",
                         thresholds=Thresholds(recompile_warmup=0))
    mw = MemWatch(metrics=metrics, monitor=mon, mem_interval=10_000)
    fn = jax.jit(lambda v: v * 2.0)
    fn(jnp.zeros((4,), jnp.float32))
    mw.attach(fn)
    mw.poll(1)                        # adopts the baseline — no fire
    fn(jnp.zeros((8,), jnp.float32))  # drifting dispatch shape
    with pytest.raises(AnomalyHalt):
        mw.poll(2)
    assert mw.recompile_count == 1
    mw.close()
    metrics.close()
    recs = _records(out)
    recompiles = [r for r in recs if r["kind"] == "compile"
                  and r.get("event") == "recompile"]
    assert len(recompiles) == 1
    assert recompiles[0]["recompile_count"] == 1
    assert recompiles[0]["step"] == 2
    storms = [r for r in recs if r["kind"] == "event"
              and r["rule"] == "recompile_storm"]
    assert len(storms) == 1
    assert recs.index(recompiles[0]) < recs.index(storms[0])


# ------------------------------------------------------- compile accounting

def test_memwatch_accounts_once_per_shape(tmp_path):
    out = str(tmp_path)
    metrics = MetricsLogger(out)
    mw = MemWatch(metrics=metrics, mem_interval=10_000)
    fn = jax.jit(lambda v: (v * 2.0).sum())
    x = jnp.zeros((16,), jnp.float32)
    r1 = mw.account(fn, x, step=0)
    r2 = mw.account(fn, x, step=5)   # memoized: same record, no relog
    assert r1 is r2 and r1["shape_index"] == 0
    assert mw.peak_hbm_bytes == r1["peak_hbm_bytes"]
    r3 = mw.account(fn, jnp.zeros((32,), jnp.float32), step=6)
    assert r3["shape_index"] == 1 and r3["step"] == 6
    metrics.close()
    comps = [r for r in _records(out) if r["kind"] == "compile"]
    assert len(comps) == 2
    assert {c["shape_key"] for c in comps} == set(mw.shapes)
    assert all(c["compile_s"] >= 0 and c["lower_s"] >= 0 for c in comps)


# ------------------------------------------------------------ memory plane

def test_device_mem_leak_fires_once_per_monotonic_run():
    mon = AnomalyMonitor(thresholds=Thresholds(mem_leak_windows=3))
    stream = [100, 200, 300, 400, 500,   # run 1: fires at the 3rd growth
              500,                       # plateau: streak + latch reset
              600, 700, 800, 900]        # run 2: fires again
    fired = []
    for step, live in enumerate(stream):
        fired += mon.observe_memory(step, live_bytes=live)
    assert [e["rule"] for e in fired] == ["device_mem_leak"] * 2
    assert [e["step"] for e in fired] == [3, 8]


def test_hbm_headroom_fires_on_crossing_and_rearms():
    mon = AnomalyMonitor(thresholds=Thresholds(hbm_headroom_frac=0.9))
    assert mon.observe_memory(1, bytes_in_use=80, bytes_limit=100) == []
    fired = mon.observe_memory(2, bytes_in_use=95, bytes_limit=100)
    assert [e["rule"] for e in fired] == ["hbm_headroom"]
    assert fired[0]["value"] == pytest.approx(0.95)
    # latched while it stays over; re-arms after dropping below
    assert mon.observe_memory(3, bytes_in_use=96, bytes_limit=100) == []
    assert mon.observe_memory(4, bytes_in_use=50, bytes_limit=100) == []
    fired = mon.observe_memory(5, bytes_in_use=99, bytes_limit=100)
    assert [e["rule"] for e in fired] == ["hbm_headroom"]


def test_missing_memory_stats_degrades_to_live_arrays():
    """CPU backends report no memory_stats: the watch must sample
    live_arrays alone, with no device fields and no headroom rule."""
    assert device_memory_summary() == {}
    la = live_array_summary()
    assert la["live_count"] >= 0 and la["live_bytes"] >= 0
    mw = MemWatch(mem_interval=1)
    rec = mw.sample(step=7)
    assert rec["step"] == 7 and rec["recompile_count"] == 0
    assert "live_bytes" in rec
    assert "bytes_in_use" not in rec and "headroom_frac" not in rec
    mw.close()


# ------------------------------------------------------------ reshape fault

def test_reshape_inject_halves_batch_axis_once():
    inj = FaultInjector("reshape@3")
    batch = {"x": np.zeros((2, 1, 4, 8), np.float32),
             "y": np.zeros((2, 1, 4), np.int32)}
    out = inj.reshape_batch(batch, 2, 3)
    assert out["x"].shape == (2, 1, 2, 8) and out["y"].shape == (2, 1, 2)
    # a point fault is consumed: the next dispatch is back to canonical
    again = inj.reshape_batch(batch, 3, 4)
    assert again["x"].shape == (2, 1, 4, 8)
    assert inj.summary() == {"reshape": 1}


def test_reshape_inject_noop_on_singleton_batch():
    inj = FaultInjector("reshape@1")
    batch = {"x": np.zeros((2, 1, 1, 8), np.float32)}
    out = inj.reshape_batch(batch, 0, 1)
    assert out["x"].shape == (2, 1, 1, 8)   # cannot halve 1: recorded no-op
    assert inj.summary() == {"reshape": 1}


# ------------------------------------------------------ report + registry

def _synthetic_run(tmp_path):
    out = str(tmp_path / "run")
    with MetricsLogger(out) as m:
        m.log("manifest", flush=True, config_hash="cfg0", git_sha="abcd",
              peak_hbm_bytes=1000)
        m.log("train", step=1, loss=2.0)
        m.log("train", step=2, loss=1.5)
        m.log("compile", flush=True, shape_key="4x3:float32", step=0,
              shape_index=0, flops=100.0, bytes_accessed=400.0,
              temp_bytes=600, argument_bytes=300, output_bytes=100,
              generated_code_bytes=0, peak_hbm_bytes=1000,
              lower_s=0.1, compile_s=0.2)
        m.log("compile", flush=True, event="recompile", step=3,
              cache_size=2, recompile_count=1, compile_events=2)
        m.log("event", flush=True, rule="recompile_storm",
              severity="warn", step=3, value=2.0, threshold=0.0,
              message="synthetic")
        m.log("mem", step=2, live_bytes=500, live_count=5,
              live_bytes_float32=500, recompile_count=0)
        m.log("mem", step=4, live_bytes=520, live_count=5,
              live_bytes_float32=520, recompile_count=1)
    return out


def test_report_compile_and_mem_subcommands(tmp_path, capsys):
    out = _synthetic_run(tmp_path)
    assert obs_report.main(["compile", out]) == 0
    text = capsys.readouterr().out
    assert "1 distinct dispatch shape" in text
    assert "recompile_count=1" in text and "recompile_storm events=1" in text
    assert "manifest peak_hbm_bytes=1000" in text
    assert obs_report.main(["mem", out]) == 0
    text = capsys.readouterr().out
    assert "2 sample(s)" in text and "float32" in text
    assert "no memory_stats" in text          # synthetic run has none
    assert "recompile_storm=1" in text
    comp = obs_report.summarize_compile(_records(out))
    assert comp["peak_hbm_bytes"] == 1000
    assert comp["recompile_count"] == 1 and comp["storm_events"] == 1
    mem = obs_report.summarize_mem(_records(out))
    assert mem["samples"] == 2 and mem["live_bytes_last"] == 520
    assert mem["by_dtype"] == {"float32": 520}
    assert mem["rules"] == {"recompile_storm": 1}


def test_exporter_and_watch_surface_mem_gauges(tmp_path):
    """Satellite: the space-plane gauges flow through the OpenMetrics
    exporter (generic numeric-field ingest — no exporter change needed,
    pin the family names so a field rename can't silently drop them)
    and ``report watch`` prints them on its per-rank summary line."""
    import io

    from gtopkssgd_tpu.obs.exporter import MetricsExporter

    exp = MetricsExporter()          # observe/scrape need no HTTP server
    exp.observe({"kind": "mem", "step": 4, "live_bytes": 520,
                 "bytes_in_use": 900, "peak_bytes_in_use": 1100,
                 "recompile_count": 1})
    text = exp.scrape()
    for family in ("gtopk_mem_live_bytes 520",
                   "gtopk_mem_bytes_in_use 900",
                   "gtopk_mem_peak_bytes_in_use 1100",
                   "gtopk_mem_recompile_count 1"):
        assert family.split()[0] in text and family.replace(
            " ", '{rank="0"} ', 1) in text
    out = _synthetic_run(tmp_path)
    buf = io.StringIO()
    assert obs_report.run_watch([out], interval=0.0, iterations=1,
                                out=buf) == 0
    line = buf.getvalue()
    assert "live_bytes=520" in line and "recompile_count=1" in line


def test_registry_and_regress_carry_mem_fields(tmp_path):
    entry = obs_registry.run_summary(_records(_synthetic_run(tmp_path)))
    assert entry["stats"]["peak_hbm_bytes"] == 1000
    assert entry["stats"]["recompile_count"] == 1
    _, fails = obs_registry.regress(entry, entry)
    assert fails == 0
    # recompile_count is an exact-match check: ANY drift fails
    cur = copy.deepcopy(entry)
    cur["stats"]["recompile_count"] = 2
    _, fails = obs_registry.regress(cur, entry)
    assert fails == 1
    # peak-HBM tolerates 10%; +20% is a program-size regression
    cur = copy.deepcopy(entry)
    cur["stats"]["peak_hbm_bytes"] = 1200
    _, fails = obs_registry.regress(cur, entry)
    assert fails == 1


def test_registry_recompile_count_absent_without_memwatch():
    """Runs without --obs-mem must not grow a vacuous 0 — absent on both
    sides means not-applicable to regress."""
    records = [{"kind": "manifest", "time": 1.0, "config_hash": "c"},
               {"kind": "train", "step": 1, "time": 1.0, "loss": 1.0}]
    entry = obs_registry.run_summary(records)
    assert "recompile_count" not in entry["stats"]
    assert "peak_hbm_bytes" not in entry["stats"]


# ------------------------------------------------------------- trainer e2e

def test_trainer_obs_mem_accounts_and_stays_stable(tmp_path):
    """End-to-end on the 2-device CPU mesh (canonical gate-smoke config,
    cached executable): --obs-mem stamps peak_hbm_bytes into the
    manifest, logs exactly one compile record for the one dispatch
    shape, samples mem windows with recompile_count pinned at 0, and the
    new fields round-trip through report and the registry."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    out = str(tmp_path / "run")
    reg = str(tmp_path / "reg")
    cfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                      compression="gtopk_layerwise", density=0.01,
                      seed=42, max_epochs=1, log_interval=1,
                      obs_interval=1, eval_batches=1, out_dir=out,
                      obs_mem=True, obs_mem_interval=1, registry=reg)
    with Trainer(cfg) as t:
        assert t.memwatch is not None
        t.train(4)
        assert t.memwatch.recompile_count == 0
        assert len(t.memwatch.shapes) == 1
    recs = _records(out)
    assert recs[0]["kind"] == "manifest"
    assert recs[0]["peak_hbm_bytes"] > 0
    comps = [r for r in recs if r["kind"] == "compile"]
    assert len(comps) == 1 and comps[0].get("event") is None
    assert comps[0]["peak_hbm_bytes"] == recs[0]["peak_hbm_bytes"]
    mems = [r for r in recs if r["kind"] == "mem"]
    assert mems and all(r["recompile_count"] == 0 for r in mems)
    live = [r["live_bytes"] for r in mems]
    assert max(live) - min(live) <= 0.5 * min(live), \
        "live bytes should be stable over a 4-step CPU run"
    assert not any(r["kind"] == "event" for r in recs)
    assert obs_report.main(["mem", out]) == 0
    assert obs_report.main(["compile", out]) == 0
    assert obs_report.main(["plan", out]) == 0
    entries, bad = obs_registry.load_registry(reg)
    assert len(entries) == 1 and bad == 0
    assert entries[0]["stats"]["recompile_count"] == 0
    assert entries[0]["stats"]["peak_hbm_bytes"] == \
        recs[0]["peak_hbm_bytes"]
    assert obs_report.main(["regress", out, "--registry", reg]) == 0


@pytest.mark.slow  # compiles the halved-batch executable cold (~1 min);
# the tier-1 equivalent is the gate smoke's storm leg (run_mem_smoke)
def test_reshape_storm_halts_dist_trainer_with_exit_44(tmp_path):
    """The acceptance chain through the CLI: an injected second dispatch
    shape retraces the step, recompile_count lands at exactly 1, the
    storm fires with warmup 0, and --obs-halt-on warn exits 44 — with
    the recompile record durably on disk before the halt."""
    from gtopkssgd_tpu import dist_trainer

    out = str(tmp_path / "run")
    rc = dist_trainer.main([
        "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
        "--obs-interval", "1", "--num-iters", "5",
        "--obs-mem", "--obs-mem-interval", "1",
        "--obs-recompile-warmup", "0", "--obs-halt-on", "warn",
        "--inject", "reshape@3", "--out-dir", out])
    assert rc == HALT_EXIT_CODE
    recs = _records(out)
    assert [r["fault"] for r in recs if r["kind"] == "inject"] == \
        ["reshape"]
    recompiles = [r for r in recs if r["kind"] == "compile"
                  and r.get("event") == "recompile"]
    assert len(recompiles) == 1
    assert recompiles[0]["recompile_count"] == 1
    storms = [r for r in recs if r["kind"] == "event"
              and r["rule"] == "recompile_storm"]
    assert len(storms) == 1
    assert recs.index(recompiles[0]) < recs.index(storms[0])
    # both dispatch shapes got their compile accounting
    shapes = [r for r in recs if r["kind"] == "compile"
              and r.get("event") is None]
    assert len(shapes) == 2
    assert obs_report.main(["compile", out]) == 0
