"""Distributed gTop-k optimizer: invariants + SPMD equivalences on 8 devices.

What the reference could only validate by training a full model to accuracy
(SURVEY.md §4 "convergence-as-test"), we pin down as unit invariants:

  * dense mode == plain optax SGD (single device and 8-way replicated);
  * error-feedback mass conservation: applied + residual' == grad + residual;
  * gtopk at density=1.0 == dense allreduce (the tree is lossless when k=N);
  * gtopk at low density still drives a least-squares loss down with
    bit-identical replicated params on every device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.ops import scatter_add_dense
from gtopkssgd_tpu.optimizer import GTopKSGDState, gtopk_sgd
from gtopkssgd_tpu.parallel import make_mesh

PDEV = 8


def quad_params():
    return {"w": jnp.arange(1.0, 7.0), "b": jnp.ones((3,))}


def test_dense_mode_matches_plain_sgd():
    params = quad_params()
    grads = jax.tree.map(lambda p: 0.1 * p + 1.0, params)
    tx = gtopk_sgd(0.5, momentum=0.9, weight_decay=0.01, compression="dense",
                   axis_name=None)
    ref = optax.chain(optax.add_decayed_weights(0.01), optax.sgd(0.5, momentum=0.9))
    s, rs = tx.init(params), ref.init(params)
    for _ in range(3):
        u, s = tx.update(grads, s, params)
        ru, rs = ref.update(grads, rs, params)
        jax.tree.map(np.testing.assert_allclose, u, ru)


def test_error_feedback_mass_conservation():
    # applied update mass + new residual == accumulated gradient, elementwise.
    n, density = 64, 0.125
    params = {"w": jnp.zeros((n,))}
    tx = gtopk_sgd(1.0, momentum=0.0, compression="gtopk", density=density,
                   axis_name=None)
    state = tx.init(params)
    rng = np.random.default_rng(1)
    residual_before = np.asarray(state.residual)
    for step in range(4):
        g = rng.standard_normal(n).astype(np.float32)
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        # momentum=0, lr=1 => -update is exactly the applied dense gradient.
        applied = -np.asarray(updates["w"])
        acc = g + residual_before
        np.testing.assert_allclose(
            applied + np.asarray(state.residual), acc, rtol=1e-5, atol=1e-6
        )
        # exactly k entries applied
        assert (np.abs(applied) > 0).sum() == int(np.ceil(density * n))
        residual_before = np.asarray(state.residual)


def _spmd_step(tx, mesh):
    def step(params, state, grads):
        grads = jax.tree.map(lambda g: g[0], grads)  # drop the shard dim
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        return params, state

    return jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def test_gtopk_density1_equals_dense_psum():
    n = 40
    params = {"w": jnp.zeros((n,))}
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(2)
    grads = rng.standard_normal((PDEV, n)).astype(np.float32)

    outs = {}
    for mode, density in [("dense", 1.0), ("gtopk", 1.0), ("allgather", 1.0)]:
        tx = gtopk_sgd(0.1, momentum=0.0, compression=mode, density=density,
                       axis_name="dp", axis_size=PDEV)
        state = jax.jit(tx.init)(params)
        step = _spmd_step(tx, mesh)
        p, _ = step(params, state, {"w": jnp.asarray(grads)})
        outs[mode] = np.asarray(p["w"])

    np.testing.assert_allclose(outs["gtopk"], outs["dense"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs["allgather"], outs["dense"], rtol=1e-5, atol=1e-6)
    want = -0.1 * grads.mean(axis=0)
    np.testing.assert_allclose(outs["dense"], want, rtol=1e-5, atol=1e-6)


def test_gtopk_spmd_least_squares_converges_replicated():
    # P devices each hold a data shard of the same least-squares problem;
    # gtop-k at 10% density must still drive the global loss down and keep
    # params bit-identical on all devices (SPMD replica consistency — the
    # property the reference's global-topk broadcast exists to guarantee).
    n, per_dev = 32, 16
    rng = np.random.default_rng(3)
    w_true = rng.standard_normal(n).astype(np.float32)
    X = rng.standard_normal((PDEV, per_dev, n)).astype(np.float32)
    y = X @ w_true

    mesh = make_mesh(PDEV)
    tx = gtopk_sgd(0.03, momentum=0.5, compression="gtopk", density=0.1,
                   axis_name="dp", axis_size=PDEV)
    params = {"w": jnp.zeros((n,))}
    state = jax.jit(tx.init)(params)

    def loss_fn(params, xb, yb):
        pred = xb @ params["w"]
        return jnp.mean((pred - yb) ** 2)

    def step(params, state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(params, xb[0], yb[0])
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        return params, state, jax.lax.pmean(loss, "dp")

    spmd = jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )
    losses = []
    for _ in range(100):
        params, state, loss = spmd(params, state, jnp.asarray(X), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0], losses[::10]


def test_clip_before_compression():
    n = 16
    params = {"w": jnp.zeros((n,))}
    tx = gtopk_sgd(1.0, momentum=0.0, compression="gtopk", density=1.0,
                   clip_grad_norm=1.0, axis_name=None)
    state = tx.init(params)
    g = np.zeros(n, np.float32)
    g[0] = 100.0
    updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
    # clipped to unit norm before compression: applied grad ~ [1, 0, ...]
    np.testing.assert_allclose(-np.asarray(updates["w"])[0], 1.0, rtol=1e-4)


def test_state_is_checkpointable_pytree():
    # The residual must live in ordinary optimizer state (the reference lost
    # residuals on resume because they sat in a class attribute).
    params = quad_params()
    tx = gtopk_sgd(0.1, compression="gtopk", density=0.5, axis_name=None)
    state = tx.init(params)
    assert isinstance(state, GTopKSGDState)
    leaves = jax.tree.leaves(state)
    assert any(l.size == 9 for l in leaves)  # residual over 9 params
    # round-trips through flatten/unflatten (what Orbax does)
    flat, treedef = jax.tree.flatten(state)
    state2 = jax.tree.unflatten(treedef, flat)
    g = jax.tree.map(jnp.ones_like, params)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, state2, params)
    jax.tree.map(np.testing.assert_array_equal, u1, u2)


def test_dense_warmup_matches_dense_then_switches():
    """warmup_dense_steps=W (reference C6 warm-up trick): the first W steps
    of a sparse mode are bit-equal to the dense baseline with the residual
    untouched (zeros); step W switches to the sparse pipeline and error
    feedback begins."""
    n, W = 40, 2
    params = {"w": jnp.zeros((n,))}
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(7)
    grads = {"w": jnp.asarray(
        rng.standard_normal((PDEV, n)).astype(np.float32))}

    tx_w = gtopk_sgd(0.1, momentum=0.9, compression="gtopk", density=0.1,
                     axis_name="dp", axis_size=PDEV, warmup_dense_steps=W)
    tx_d = gtopk_sgd(0.1, momentum=0.9, compression="dense",
                     axis_name="dp", axis_size=PDEV)
    sw, sd = jax.jit(tx_w.init)(params), jax.jit(tx_d.init)(params)
    step_w, step_d = _spmd_step(tx_w, mesh), _spmd_step(tx_d, mesh)

    pw, pd = params, params
    for i in range(W):
        pw, sw = step_w(pw, sw, grads)
        pd, sd = step_d(pd, sd, grads)
        np.testing.assert_allclose(np.asarray(pw["w"]), np.asarray(pd["w"]),
                                   rtol=1e-6, atol=1e-7)
        assert not np.any(np.asarray(sw.residual)), f"residual dirty at {i}"

    # Step W: sparse pipeline activates. With momentum the dense-phase
    # buffer keeps every coordinate moving, so the sparse selection is
    # asserted via the residual: k = 10% of n coords selected => at least
    # the other 90% of the accumulated gradient mass lands in the residual.
    pw, sw = step_w(pw, sw, grads)
    assert np.any(np.asarray(sw.residual)), "error feedback never started"
    assert (np.abs(np.asarray(sw.residual)) > 0).sum() >= n - int(n * 0.1)


def test_warmup_rejected_for_negative():
    import pytest

    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", warmup_dense_steps=-1)


def test_dense_warmup_hier_matches_dense_scale():
    """Regression: in gtopk_hier mode the warm-up dense branch receives the
    SLICE-SUMMED gradient, so a full-axis psum over-counts by ici_size —
    the warm-up step must still equal the plain dense baseline exactly."""
    n = 40
    params = {"w": jnp.zeros((n,))}
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(11)
    grads = {"w": jnp.asarray(
        rng.standard_normal((PDEV, n)).astype(np.float32))}

    tx_h = gtopk_sgd(0.1, momentum=0.0, compression="gtopk_hier",
                     density=0.1, axis_name="dp", axis_size=PDEV,
                     hier_ici_size=4, warmup_dense_steps=1)
    tx_d = gtopk_sgd(0.1, momentum=0.0, compression="dense",
                     axis_name="dp", axis_size=PDEV)
    sh, sd = jax.jit(tx_h.init)(params), jax.jit(tx_d.init)(params)
    ph, _ = _spmd_step(tx_h, mesh)(params, sh, grads)
    pd, _ = _spmd_step(tx_d, mesh)(params, sd, grads)
    np.testing.assert_allclose(np.asarray(ph["w"]), np.asarray(pd["w"]),
                               rtol=1e-5, atol=1e-6)


def test_effective_density_layerwise_counts_per_leaf_ceil():
    """effective_density must report the COMMUNICATED density: for
    layerwise modes per-leaf ceil rounding (k_l = ceil(rho*n_l) >= 1)
    pushes it well above rho whenever small leaves exist, and calling
    without leaf sizes raises instead of silently underestimating."""
    import pytest

    from gtopkssgd_tpu.optimizer import effective_density

    assert effective_density("dense", 0.001) == 1.0
    assert effective_density("gtopk", 0.001) == 0.001
    # 3 leaves of 10 elements at rho=0.001: k_l = 1 each -> 3/30 = 0.1,
    # a 100x blow-up over the flat rho.
    d = effective_density("gtopk_layerwise", 0.001, leaf_sizes=(10, 10, 10))
    np.testing.assert_allclose(d, 0.1)
    # one big leaf dominates: sum(ceil) ~ rho*N and the blow-up vanishes
    d = effective_density("gtopk_layerwise", 0.001, leaf_sizes=(100_000,))
    np.testing.assert_allclose(d, 0.001)
    with pytest.raises(ValueError, match="leaf_sizes"):
        effective_density("gtopk_layerwise", 0.001)
