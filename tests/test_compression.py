"""Error-feedback compressor invariants (SURVEY.md §4 test strategy)."""

import jax.numpy as jnp
import numpy as np

from gtopkssgd_tpu.compression import (
    NoneCompressor,
    TopKCompressor,
    get_compressor,
)
from gtopkssgd_tpu.ops import scatter_add_dense


def test_registry():
    assert isinstance(get_compressor(None), NoneCompressor)
    assert isinstance(get_compressor("none"), NoneCompressor)
    c = get_compressor("topk", density=0.01)
    assert isinstance(c, TopKCompressor) and c.density == 0.01
    c = get_compressor("gtopk", density=0.001)
    assert isinstance(c, TopKCompressor)


def test_mass_conservation(rng):
    """Invariant: sent + residual == acc, elementwise (no gradient mass is
    created or destroyed by compression)."""
    n = 4096
    comp = TopKCompressor(density=0.01, method="exact")
    grad = rng.standard_normal(n).astype(np.float32)
    residual = comp.init_residual(n)
    acc = comp.accumulate(jnp.asarray(grad), residual)
    vals, idx, new_res = comp.compress(acc)
    sent = scatter_add_dense(n, idx, vals)
    np.testing.assert_allclose(
        np.asarray(sent + new_res), np.asarray(acc), rtol=1e-6, atol=1e-7
    )
    # Selected slots are zeroed in the residual.
    assert np.all(np.asarray(new_res)[np.asarray(idx)] == 0.0)


def test_residual_accumulates_over_steps(rng):
    """Unselected gradient mass must build up and eventually win selection —
    the error-feedback property that preserves convergence at rho=1e-3."""
    n = 1000
    comp = TopKCompressor(density=0.001, method="exact")  # k = 1
    residual = comp.init_residual(n)
    small = np.full(n, 0.001, np.float32)
    small[7] = 1.0  # dominant coordinate wins first
    acc = comp.accumulate(jnp.asarray(small), residual)
    vals, idx, residual = comp.compress(acc)
    assert int(idx[0]) == 7
    # Feed zero grads; residual mass alone must get selected (any non-7 slot
    # has accumulated 0.001 and slot 7 has 0).
    acc = comp.accumulate(jnp.zeros(n), residual)
    vals2, idx2, residual = comp.compress(acc)
    assert int(idx2[0]) != 7
    assert abs(float(vals2[0]) - 0.001) < 1e-6


def test_repair_returns_rejected_mass(rng):
    n = 256
    comp = TopKCompressor(density=0.05, method="exact")
    grad = rng.standard_normal(n).astype(np.float32)
    acc = comp.accumulate(jnp.asarray(grad), comp.init_residual(n))
    vals, idx, res = comp.compress(acc)
    # Pretend the global top-k kept only the first half of our local picks.
    k = vals.shape[0]
    global_idx = idx[: k // 2]
    repaired = comp.repair(res, vals, idx, global_idx)
    r = np.asarray(repaired)
    li, lv = np.asarray(idx), np.asarray(vals)
    kept = set(np.asarray(global_idx).tolist())
    for i in range(k):
        if li[i] in kept:
            assert r[li[i]] == 0.0
        else:
            np.testing.assert_allclose(r[li[i]], lv[i], rtol=1e-6)
    # After repair: residual + globally-applied == acc (global mass view).
    applied = scatter_add_dense(n, global_idx, vals[: k // 2])
    np.testing.assert_allclose(
        np.asarray(applied + repaired), np.asarray(acc), rtol=1e-6, atol=1e-7
    )


def test_none_compressor_passthrough(rng):
    n = 64
    comp = NoneCompressor()
    g = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    acc = comp.accumulate(g, comp.init_residual(n))
    vals, idx, res = comp.compress(acc)
    np.testing.assert_array_equal(np.asarray(vals), np.asarray(g))
    np.testing.assert_array_equal(np.asarray(idx), np.arange(n))
    assert res.shape == (0,)


def test_compress_by_threshold_matches_exact_topk_partition(rng):
    """With the exact kernel and no ties, the threshold mask IS the top-k
    set, and (keep, residual) partition acc exactly."""
    n = 257
    comp = TopKCompressor(density=0.05, method="exact")
    acc = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    keep, res, tau = comp.compress_by_threshold(acc)
    vals, idx, res_idx_form = comp.compress(acc)
    # Reported tau is the smallest kept magnitude.
    assert float(tau) == float(np.abs(np.asarray(vals)).min())
    # Same selected set (random floats: ties have measure zero).
    mask = np.zeros(n, bool)
    mask[np.asarray(idx)] = True
    np.testing.assert_array_equal(np.asarray(keep), mask)
    # Same residual, bit-for-bit partition: keep*acc + residual == acc.
    np.testing.assert_array_equal(np.asarray(res), np.asarray(res_idx_form))
    recon = np.where(np.asarray(keep), np.asarray(acc), 0.0) + np.asarray(res)
    np.testing.assert_array_equal(recon, np.asarray(acc))


def test_compress_by_threshold_ties_all_pass():
    """Magnitude ties at tau are all selected (count may exceed k), and the
    partition invariant still holds exactly."""
    acc = jnp.asarray([3.0, -3.0, 3.0, 1.0, -1.0, 0.5] + [0.0] * 10)
    comp = TopKCompressor(density=2 / 16, method="exact")  # k = 2
    keep, res, tau = comp.compress_by_threshold(acc)
    assert float(tau) == 3.0
    k = np.asarray(keep)
    assert k[:3].all() and not k[3:].any()  # all three |3.0| ties pass
    assert int(k.sum()) == 3 > comp.k(16)
    np.testing.assert_array_equal(
        np.where(k, np.asarray(acc), 0.0) + np.asarray(res), np.asarray(acc)
    )


def test_compress_by_threshold_tau_zero_keeps_only_nonzeros():
    """Degenerate tau == 0 (fewer than k nonzeros): |x| >= 0 is vacuously
    true, so an unguarded mask would select EVERY coordinate — under
    momentum correction that zeroes the whole velocity buffer for the
    leaf. The guard masks zeros out: only the actual nonzeros pass, and
    the partition invariant still holds exactly. (Round-3 advisor.)"""
    n = 64
    comp = TopKCompressor(density=8 / 64, method="exact")  # k = 8
    acc = jnp.zeros(n).at[3].set(2.0).at[17].set(-1.0)  # 2 nonzeros < k
    keep, res, tau = comp.compress_by_threshold(acc)
    # tau follows the kept set (smallest kept magnitude), not the kernel's
    # zero-padded report.
    assert float(tau) == 1.0
    k = np.asarray(keep)
    assert int(k.sum()) == 2 and k[3] and k[17]
    np.testing.assert_array_equal(
        np.where(k, np.asarray(acc), 0.0) + np.asarray(res), np.asarray(acc)
    )


def test_compress_by_threshold_superset_of_kernel_selection(rng):
    """For ANY selection kernel, the threshold mask contains every index the
    kernel itself returned (tau = min |kernel vals|), so threshold recall
    >= kernel recall — the documented approx-kernel guarantee."""
    n = 4096
    comp = TopKCompressor(density=0.01, method="blockwise")
    acc = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    keep, _, _ = comp.compress_by_threshold(acc)
    _, idx = __import__("gtopkssgd_tpu.ops", fromlist=["select_topk"]).select_topk(
        acc, comp.k(n), comp.method
    )
    assert np.asarray(keep)[np.asarray(idx)].all()


def test_compress_by_threshold_select_tau_partition_parity(rng):
    """compress_by_threshold's tau now comes from the tau-only API
    (ops.select_tau — no (vals, idx) set, no gather); per method the
    keep/residual partition must be IDENTICAL to the legacy formulation
    that built the mask from min|vals| of the corresponding select_topk."""
    from gtopkssgd_tpu.ops import select_topk

    n = 8192
    acc = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    for method in ("exact", "blockwise", "approx", "threshold"):
        comp = TopKCompressor(density=0.01, method=method)
        keep, res, kept_tau = comp.compress_by_threshold(acc)
        vals, _ = select_topk(acc, comp.k(n), method)
        tau_ref = float(np.abs(np.asarray(vals)).min())
        want = (np.abs(np.asarray(acc)) >= tau_ref) & (
            np.abs(np.asarray(acc)) > 0.0)
        np.testing.assert_array_equal(np.asarray(keep), want, err_msg=method)
        np.testing.assert_array_equal(
            np.where(want, 0.0, np.asarray(acc)), np.asarray(res),
            err_msg=method)


def test_compress_by_threshold_fused_operands_same_partition(rng):
    """Passing the unfused operands (grad, residual with
    acc == grad + residual) must yield the exact same partition as the
    materialized-accumulator call — the fused path changes WHERE the
    accumulate happens, never the selected set."""
    n = 4096
    g = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    r = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    acc = g + r
    comp = TopKCompressor(density=0.01, method="exact")
    keep_a, res_a, tau_a = comp.compress_by_threshold(acc)
    keep_b, res_b, tau_b = comp.compress_by_threshold(
        acc, grad=g, residual=r)
    np.testing.assert_array_equal(np.asarray(keep_a), np.asarray(keep_b))
    np.testing.assert_array_equal(np.asarray(res_a), np.asarray(res_b))
    assert float(tau_a) == float(tau_b)


def test_compress_by_threshold_twostage_superset_of_exact(rng):
    """twostage tau is the k-th largest CANDIDATE magnitude <= the exact
    tau, so its keep mask contains the ENTIRE exact top-k — the property
    behind the audited recall floor of 1.0 at p=1."""
    from gtopkssgd_tpu.ops import topk_abs

    n = 100_000
    acc = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    comp = TopKCompressor(density=0.001, method="twostage")
    keep, res, _ = comp.compress_by_threshold(acc)
    _, exact_idx = topk_abs(acc, comp.k(n))
    assert np.asarray(keep)[np.asarray(exact_idx)].all()
    np.testing.assert_array_equal(
        np.where(np.asarray(keep), 0.0, np.asarray(acc)), np.asarray(res))
