"""Layer-wise gTop-k (`compression='gtopk_layerwise'`): unit invariants.

TPU extension (arXiv:1911.08772 layer-wise-top-k lineage; the reference
always flattens — SURVEY.md §3.1 "flatten all param.grads into one
vector"). The mode keeps selection + error feedback per layer so the flat
[N] gradient never materializes; the collective is the unchanged gTop-k
hypercube over the concatenated per-layer sets. These tests pin:

  * per-leaf k_l = ceil(rho * n_l) selections at p=1, against a numpy
    per-leaf top-k oracle (including error-feedback mass conservation);
  * density=1.0 degenerates to the dense-psum mean (8-way);
  * 8-way SPMD: replicas stay bit-identical and a least-squares loss falls;
  * the dense warm-up phase bit-equals the dense baseline;
  * Trainer integration: per-device tuple residual, checkpoint round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.ops import k_for_density
from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.parallel import make_mesh

PDEV = 8


def tree_params():
    return {
        "conv": jnp.zeros((4, 8)),   # 32 elems -> k=4 at rho=0.125
        "bias": jnp.zeros((5,)),     # 5 elems  -> k=1
        "bn": jnp.zeros((2, 3)),     # 6 elems  -> k=1
    }


def rand_grads(rng, params, lead=()):
    return jax.tree.map(
        lambda p: jnp.asarray(
            rng.standard_normal(lead + p.shape), jnp.float32), params
    )


def test_layerwise_p1_matches_per_leaf_topk_oracle():
    density = 0.125
    params = tree_params()
    tx = gtopk_sgd(1.0, momentum=0.0, compression="gtopk_layerwise",
                   density=density, axis_name=None)
    state = tx.init(params)
    # residual is a pytree: one flat buffer per leaf, in tree.flatten order
    leaves = jax.tree.leaves(params)
    assert isinstance(state.residual, tuple)
    assert [r.shape for r in state.residual] == [(l.size,) for l in leaves]

    rng = np.random.default_rng(0)
    res_before = [np.zeros(l.size, np.float32) for l in leaves]
    upd = jax.jit(tx.update)
    for _ in range(3):
        grads = rand_grads(rng, params)
        updates, state = upd(grads, state, params)
        g_leaves = [np.asarray(g).reshape(-1) for g in jax.tree.leaves(grads)]
        u_leaves = [np.asarray(u).reshape(-1)
                    for u in jax.tree.leaves(updates)]
        for g, u, res, res_new in zip(
                g_leaves, u_leaves, res_before, state.residual):
            n = g.size
            k = k_for_density(n, density)
            acc = g + res
            applied = -u  # momentum=0, lr=1
            # exactly this leaf's k entries applied, and they are the
            # top-k of |acc| with their exact acc values
            nz = np.flatnonzero(np.abs(applied) > 0)
            assert len(nz) == k
            want_idx = np.argsort(-np.abs(acc))[:k]
            assert set(nz) == set(want_idx)
            np.testing.assert_allclose(applied[nz], acc[nz], rtol=1e-6)
            # error-feedback mass conservation per leaf
            np.testing.assert_allclose(
                applied + np.asarray(res_new), acc, rtol=1e-5, atol=1e-6)
        res_before = [np.asarray(r) for r in state.residual]


def _spmd_step(tx, mesh):
    def step(params, state, grads):
        grads = jax.tree.map(lambda g: g[0], grads)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        return params, state

    return jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def test_layerwise_density1_equals_dense_mean():
    params = tree_params()
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(2)
    grads = rand_grads(rng, params, lead=(PDEV,))
    tx = gtopk_sgd(0.1, momentum=0.0, compression="gtopk_layerwise",
                   density=1.0, axis_name="dp", axis_size=PDEV)
    state = jax.jit(tx.init)(params)
    p2, _ = _spmd_step(tx, mesh)(params, state, grads)
    for leaf, g in zip(jax.tree.leaves(p2), jax.tree.leaves(grads)):
        want = -0.1 * np.asarray(g).mean(axis=0)
        np.testing.assert_allclose(np.asarray(leaf), want,
                                   rtol=1e-5, atol=1e-6)


def test_layerwise_spmd_converges_replicated():
    # Two-leaf least-squares; rho low enough that each step is genuinely
    # sparse. Replica consistency = the property the global broadcast of
    # the reference exists to guarantee (SURVEY.md §2 parallelism).
    n1, n2, per_dev = 24, 8, 16
    rng = np.random.default_rng(3)
    w_true = rng.standard_normal(n1 + n2).astype(np.float32)
    X = rng.standard_normal((PDEV, per_dev, n1 + n2)).astype(np.float32)
    y = X @ w_true

    params = {"a": jnp.zeros((n1,)), "b": jnp.zeros((n2,))}
    mesh = make_mesh(PDEV)
    tx = gtopk_sgd(0.03, momentum=0.5, compression="gtopk_layerwise",
                   density=0.1, axis_name="dp", axis_size=PDEV)
    state = jax.jit(tx.init)(params)

    def loss_grads(params, Xs, ys):
        def loss(p):
            w = jnp.concatenate([p["a"], p["b"]])
            r = Xs @ w - ys
            return 0.5 * jnp.mean(r * r)
        return jax.grad(loss)(params)

    def step(params, state, Xs, ys):
        grads = loss_grads(params, Xs[0], ys[0])
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    smapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P()),
        check_vma=False,
    ))

    def global_loss(params):
        w = np.concatenate([np.asarray(params["a"]), np.asarray(params["b"])])
        r = X.reshape(-1, n1 + n2) @ w - y.reshape(-1)
        return 0.5 * float(np.mean(r * r))

    l0 = global_loss(params)
    for _ in range(60):
        params, state = smapped(params, state, jnp.asarray(X), jnp.asarray(y))
    assert global_loss(params) < 0.3 * l0
    # error feedback is live: some rejected mass sits in the residual
    res = [np.asarray(r) for r in state.residual]
    assert any((r != 0).any() for r in res)
    # replica consistency: every device holds bit-identical params
    for leaf in jax.tree.leaves(params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_layerwise_warmup_phase_bit_equals_dense():
    params = tree_params()
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(4)
    grads = rand_grads(rng, params, lead=(PDEV,))

    tx_lw = gtopk_sgd(0.1, momentum=0.9, compression="gtopk_layerwise",
                      density=0.05, axis_name="dp", axis_size=PDEV,
                      warmup_dense_steps=2)
    tx_d = gtopk_sgd(0.1, momentum=0.9, compression="dense",
                     axis_name="dp", axis_size=PDEV)
    s_lw = jax.jit(tx_lw.init)(params)
    s_d = jax.jit(tx_d.init)(params)
    step_lw, step_d = _spmd_step(tx_lw, mesh), _spmd_step(tx_d, mesh)
    p_lw = p_d = params
    for i in range(3):
        p_lw, s_lw = step_lw(p_lw, s_lw, grads)
        p_d, s_d = step_d(p_d, s_d, grads)
        # Warmup steps compute the same math but not always the same BITS:
        # once the momentum trace is nonzero (step >= 1), XLA:CPU may
        # contract mu*trace + g into an FMA in one program and not the
        # other (the layerwise program carries a live lax.cond sparse
        # branch, so fusion decisions differ), a 1-ULP divergence
        # (observed 7.5e-9 on f32 params). So: warmup agrees to ULP-scale
        # tolerance, the first sparse step diverges by orders of
        # magnitude more.
        diff = max(
            float(np.abs(np.asarray(a) - np.asarray(b)).max())
            for a, b in zip(jax.tree.leaves(p_lw), jax.tree.leaves(p_d))
        )
        if i < 2:
            assert diff <= 1e-6, f"warmup step {i}: diff {diff}"
        else:
            assert diff > 1e-3, f"step {i}: sparse phase did not engage"


@pytest.mark.slow  # ~27 s: LSTM compile + 4 steps + eval. The layerwise
# selection semantics stay tier-1 via the oracle/density1/warmup tests
# above; the LSTM trainer path (carry + ppl eval) via
# test_ptb_trainer_carry_and_ppl; clip resolution is config-level and
# cheap to re-check there.
def test_layerwise_lstm_clip_before_compress_trains():
    """PTB/LSTM path under layerwise: per-leaf selection composes with the
    clip-BEFORE-compress ordering (SURVEY.md §3.4 — the global norm is a
    sum of per-leaf sums, no concatenation) and the BPTT carry."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    t = Trainer(TrainConfig(
        dnn="lstm", batch_size=4, nworkers=1, log_interval=5,
        eval_batches=2, max_epochs=1, compression="gtopk_layerwise",
        density=0.05,
    ))
    stats = t.train(4)
    assert np.isfinite(stats["loss"])
    ev = t.test()
    assert "val_ppl" in ev and ev["val_ppl"] > 1.0
    # the lstm config resolves to a clip threshold, so the clip branch
    # genuinely traced
    assert t.cfg.resolved().clip_grad_norm is not None


def test_layerwise_never_materializes_flat_gradient():
    """The mode's design claim, pinned mechanically: the compiled p=1
    update program contains NO tensor of the flat [N] shape — selection,
    error feedback, and the update all stay per-leaf — while the flat
    gtopk program is full of them (ravel/acc/residual/scatter). This is
    the property that lets XLA fuse each leaf's compress chain into that
    leaf's backward epilogue instead of serializing behind a whole-model
    concatenation (the measured p=1 serial tail of the flat path)."""
    from gtopkssgd_tpu.models import get_model

    model, _ = get_model("resnet20")
    variables = model.init({"params": jax.random.PRNGKey(0)},
                           jnp.zeros((1, 32, 32, 3)))
    params = variables["params"]
    n = sum(l.size for l in jax.tree.leaves(params))
    grads = jax.tree.map(jnp.ones_like, params)
    flat_shape = f"f32[{n}]"

    counts = {}
    for mode in ("gtopk", "gtopk_layerwise"):
        tx = gtopk_sgd(0.1, compression=mode, density=0.001, axis_name=None)
        st = jax.jit(tx.init)(params)
        hlo = jax.jit(tx.update).lower(grads, st, params).compile().as_text()
        counts[mode] = hlo.count(flat_shape)
    assert counts["gtopk"] > 0  # sanity: the flat path does materialize [N]
    assert counts["gtopk_layerwise"] == 0, counts


def test_layerwise_trainer_checkpoint_roundtrip(tmp_path):
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    cfg = TrainConfig(
        dnn="resnet20", batch_size=4, nworkers=4, log_interval=5,
        eval_batches=2, max_epochs=1, compression="gtopk_layerwise",
        density=0.05, out_dir=str(tmp_path / "run"),
    )
    t = Trainer(cfg)
    t.train(5)
    res = t.state.opt_state.residual
    assert isinstance(res, tuple) and len(res) == len(
        jax.tree.leaves(t.state.params))
    big = [np.asarray(r) for r in res if r.size]
    assert all(r.shape[0] == 4 for r in big)
    assert any((r[0] != r[i]).any() for r in big for i in range(1, 4))
    # params replicated bit-identically
    leaf = jax.tree.leaves(t.state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    t.save()
    t2 = Trainer(cfg)
    assert t2.restore()
    for a, b in zip(res, t2.state.opt_state.residual):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    t2.train(2)
    assert int(t2.state.step) == 7


def test_layerwise_breakdown_has_phase_evidence():
    """Round-2 verdict weak #7: measure_breakdown used to REFUSE layerwise
    modes, leaving the perf-thesis mode with no phase-level evidence path.
    It now returns the per-phase split (fwd+bwd / per-leaf compress / comm
    / apply) with the layerwise wire K; structural check on the CI mesh —
    the committed artifact comes from the chip."""
    from gtopkssgd_tpu.benchmark import BenchConfig, measure_breakdown
    from gtopkssgd_tpu.ops import k_for_density

    cfg = BenchConfig(dnn="resnet20", batch_size=4, steps=2,
                      dtype="float32", nworkers=8)
    res = measure_breakdown(cfg, "gtopk_layerwise", 0.01)
    for phase in ("forward_backward", "compress_per_leaf", "comm", "apply"):
        assert res[phase] > 0.0, res
    assert res["sum"] >= max(res["forward_backward"], res["comm"])
    import jax
    import jax.numpy as jnp

    from gtopkssgd_tpu.models import get_model

    model, _ = get_model("resnet20", dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 32, 32, 3)))["params"]
    expect_k = sum(k_for_density(int(a.size), 0.01)
                   for a in jax.tree.leaves(params))
    assert res["k_total"] == expect_k
