"""Trainer: end-to-end smoke on every workload family, 8-way SPMD gtopk
training, checkpoint round-trip with residual preservation, CLI parsing.

The reference's only integration test was "train to accuracy" (SURVEY.md
§4); these are the cheap equivalents: loss falls on synthetic data in a few
steps, replicated state stays consistent, resume is exact.
"""

import jax
import numpy as np
import pytest

from gtopkssgd_tpu.dist_trainer import build_argparser, config_from_args
from gtopkssgd_tpu.trainer import TrainConfig, Trainer


def small_cfg(**kw):
    base = dict(
        dnn="resnet20", batch_size=8, nworkers=1, log_interval=5,
        eval_batches=2, max_epochs=1,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_single_worker_dense_loss_falls():
    t = Trainer(small_cfg())
    stats = t.train(15)
    first = t.metrics  # smoke: metrics object exists
    assert np.isfinite(stats["loss"])
    ev = t.test()
    assert "val_top1" in ev and 0.0 <= ev["val_top1"] <= 1.0
    assert "val_top5" in ev and ev["val_top5"] >= ev["val_top1"]


@pytest.mark.slow  # ~62 s: 15 8-way steps on the serial box. The 8-way
# SPMD mesh stays tier-1 via test_prefetch / test_hier / test_sharded_eval
# (all nworkers=8) and the gtopk trainer path via the 2-way tests here;
# multi-step loss behavior rides test_convergence.
def test_spmd_gtopk_8way_trains():
    t = Trainer(small_cfg(
        nworkers=8, compression="gtopk", density=0.01, batch_size=4, lr=0.05,
    ))
    s0 = t.train(3)
    s1 = t.train(12)
    assert np.isfinite(s1["loss"])
    assert s1["loss"] < s0["loss"] * 1.5  # no blow-up; usually falls
    assert int(t.state.step) == 15


def test_gradient_accumulation_steps():
    t = Trainer(small_cfg(nsteps_update=2, batch_size=4))
    stats = t.train(4)
    assert int(t.state.step) == 4
    assert np.isfinite(stats["loss"])


@pytest.mark.slow  # ~28 s: trains both arms 8 steps each. The spd guard
# rails stay tier-1 (test_steps_per_dispatch_rejects_ragged_num_iters,
# test_s2d_cli_flag_and_guard); bitwise spd-vs-per-step equivalence is
# the slow-tier property this pins.
def test_steps_per_dispatch_matches_per_step_path():
    """spd > 1 (lax.scan inside the dispatch) must train IDENTICALLY to
    the per-step path: same seed + same data stream -> same params. The
    per-step RNG folds state.step, which increments inside the scan, so
    dropout/selection draws line up step for step. Covers the sparse
    path (gtopk) + multi-worker collectives + error-feedback residual
    state threading through the scan."""
    kw = dict(nworkers=2, compression="gtopk", density=0.01,
              batch_size=4, lr=0.05, prefetch=0)
    a = Trainer(small_cfg(**kw))
    a.train(8)
    b = Trainer(small_cfg(steps_per_dispatch=4, **kw))
    b.train(8)
    assert int(b.state.step) == 8
    pa = jax.tree.leaves(a.state.params)
    pb = jax.tree.leaves(b.state.params)
    for la, lb in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=2e-6)
    ra = np.asarray(jax.tree.leaves(a.state.opt_state.residual)[0])
    rb = np.asarray(jax.tree.leaves(b.state.opt_state.residual)[0])
    np.testing.assert_allclose(ra, rb, rtol=2e-5, atol=2e-6)


def test_steps_per_dispatch_rejects_ragged_num_iters():
    t = Trainer(small_cfg(steps_per_dispatch=4))
    with pytest.raises(ValueError, match="multiple of"):
        t.train(6)


def test_ptb_trainer_carry_and_ppl():
    t = Trainer(small_cfg(dnn="lstm", batch_size=4, compression="gtopk",
                          density=0.05, eval_batches=2))
    stats = t.train(4)
    assert np.isfinite(stats["loss"])
    ev = t.test()
    assert "val_ppl" in ev and ev["val_ppl"] > 1.0


@pytest.mark.slow  # ~143 s: LSTM CTC compile + 2 steps on the 1-core host
def test_an4_trainer_ctc():
    t = Trainer(small_cfg(dnn="lstman4", batch_size=4, eval_batches=1))
    stats = t.train(2)
    assert np.isfinite(stats["loss"])
    ev = t.test()
    assert "val_cer" in ev and ev["val_cer"] >= 0.0
    assert "val_wer" in ev and ev["val_wer"] >= 0.0


@pytest.mark.slow  # ~308 s: 8-way LSTM steps with accumulation on 1 core
def test_an4_distributed_accumulated_shapes_stack():
    # Regression: AN4 batches must have fixed shapes so nworkers>1 and
    # nsteps_update>1 can stack them (variable per-batch padding used to
    # crash np.stack in _stack_shard_batches).
    t = Trainer(small_cfg(dnn="lstman4", batch_size=2, nworkers=2,
                          nsteps_update=2, compression="gtopk",
                          density=0.05, eval_batches=1))
    stats = t.train(2)
    assert np.isfinite(stats["loss"])


def test_train_zero_iters_is_noop():
    t = Trainer(small_cfg())
    stats = t.train(0)
    assert stats["throughput"] == 0.0 and int(t.state.step) == 0


def test_checkpoint_roundtrip_preserves_residual(tmp_path):
    cfg = small_cfg(compression="gtopk", density=0.05,
                    out_dir=str(tmp_path / "run"))
    t = Trainer(cfg)
    t.train(5)
    t.save()
    residual = np.asarray(t.state.opt_state.residual)
    assert (residual != 0).any()  # error feedback accumulated something
    t2 = Trainer(cfg)
    assert t2.restore()
    np.testing.assert_array_equal(
        np.asarray(t2.state.opt_state.residual), residual
    )
    assert int(t2.state.step) == 5
    # resumed training continues without error
    t2.train(2)
    assert int(t2.state.step) == 7


def test_residual_sharding_multiworker_roundtrip(tmp_path):
    """The error-feedback residual is per-device state: it must be carried
    as a [P, N] leaf (not collapsed to device 0's copy), genuinely differ
    across devices, and survive a checkpoint round-trip in full — while the
    params stay bit-identical on every device (replica consistency)."""
    cfg = small_cfg(nworkers=4, batch_size=4, compression="gtopk",
                    density=0.05, out_dir=str(tmp_path / "run"))
    t = Trainer(cfg)
    t.train(5)
    res = np.asarray(t.state.opt_state.residual)
    assert res.shape[0] == 4 and res.shape[1] == t.num_params
    # each device sees different data, so residuals must differ...
    assert any((res[0] != res[i]).any() for i in range(1, 4))
    # ...while the replicated params are bit-identical on every device
    leaf = jax.tree.leaves(t.state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    t.save()
    t2 = Trainer(cfg)
    assert t2.restore()
    np.testing.assert_array_equal(np.asarray(t2.state.opt_state.residual), res)
    t2.train(2)
    assert int(t2.state.step) == 7


def test_cli_flags_match_reference_names():
    args = build_argparser().parse_args([
        "--dnn", "vgg16", "--density", "0.001", "--compression", "gtopk",
        "--nworkers", "4", "--batch-size", "16", "--nsteps-update", "2",
        "--max-epochs", "3",
    ])
    cfg = config_from_args(args)
    assert cfg.dnn == "vgg16" and cfg.density == 0.001
    assert cfg.compression == "gtopk" and cfg.nworkers == 4
    assert cfg.nsteps_update == 2 and cfg.max_epochs == 3


def test_per_dataset_defaults_resolve():
    cfg = TrainConfig(dnn="lstm").resolved()
    assert cfg.dataset == "ptb" and cfg.clip_grad_norm == 0.25
    cfg = TrainConfig(dnn="resnet50").resolved()
    assert cfg.dataset == "imagenet" and cfg.lr == 0.1


@pytest.mark.slow  # ~60 s: one real ResNet-50 compile+step. The uint8
# pipeline dtype contract stays tier-1 in tests/test_data.py and the
# on-device normalization consumer in test_real_data's decode tests;
# ResNet-50 shapes stay covered by test_models.
def test_imagenet_uint8_wire_trains_one_step():
    """End-to-end through the uint8 wire format: the ImageNet pipeline
    ships raw pixels, the jitted step normalizes on device — one real
    ResNet-50 step + eval must produce finite losses. (The pipelines'
    dtype is pinned in tests/test_data.py; this pins the consumer.)"""
    import numpy as np

    with Trainer(TrainConfig(
        dnn="resnet50", batch_size=2, nworkers=1, compression="gtopk",
        density=0.01, max_epochs=1, log_interval=1, eval_batches=1,
    )) as t:
        stats = t.train(1)
        assert np.isfinite(stats["loss"]), stats
        ev = t.test()
        assert np.isfinite(ev["val_loss"]) and "val_top5" in ev


@pytest.mark.slow  # ~200 s: trains across the warmup boundary on 1 core
def test_dense_warmup_and_lr_ramp_cross_boundary():
    """Warm-up knobs (reference C6 settings.py): dense-communication phase
    for the first N epochs of a sparse run, plus a linear LR ramp — one
    jitted step covers both phases (no recompile at the switch), and the
    residual stays zeros until the sparse phase begins."""
    t = Trainer(small_cfg(
        nworkers=4, compression="gtopk", density=0.01, batch_size=4,
        dense_warmup_epochs=1, warmup_epochs=1, max_epochs=4,
    ))
    spe = t.steps_per_epoch
    # LR ramp: base/10 at step 0, base at the end of warmup.
    sched = t._lr_schedule()
    base = t.cfg.lr
    np.testing.assert_allclose(float(sched(0)), 0.1 * base, rtol=1e-5)
    assert float(sched(spe // 2)) < base
    np.testing.assert_allclose(float(sched(spe)), base, rtol=1e-5)

    # Train across the warmup boundary in one Trainer (same jit).
    t.train(spe)  # dense-communication phase
    res_warm = np.asarray(t.state.opt_state.residual)
    assert not res_warm.any(), "residual must stay zero during dense warmup"
    stats = t.train(2)  # sparse phase begins
    assert np.isfinite(stats["loss"])
    assert np.asarray(t.state.opt_state.residual).any(), (
        "error feedback should start after warmup"
    )


def test_warmup_cli_flags():
    args = build_argparser().parse_args([
        "--warmup-epochs", "2", "--dense-warmup-epochs", "3",
    ])
    cfg = config_from_args(args)
    assert cfg.warmup_epochs == 2 and cfg.dense_warmup_epochs == 3


@pytest.mark.slow  # ~42 s: multi-epoch fit() loop; the checkpoint
# save/resume contract stays tier-1 via
# test_checkpoint_roundtrip_preserves_residual and the layerwise/
# momentum-correction roundtrip tests
def test_fit_epoch_loop_with_checkpoint(tmp_path, monkeypatch):
    """fit() (reference dist_trainer main loop): epoch-driven train + eval +
    checkpoint each epoch; a fresh Trainer resumes into the NEXT epoch."""
    from gtopkssgd_tpu.data import cifar

    # Shrink the synthetic corpus so an epoch is 8 optimizer steps; a
    # distinct seed keeps the lru_cached full-size corpus of other tests,
    # and clearing the cache afterwards keeps the 128-sample corpus from
    # leaking to any later test that happens to share the seed.
    monkeypatch.setattr(cifar, "SYNTH_TRAIN", 128)
    cifar._synthetic.cache_clear()
    try:
        _run_fit(tmp_path)
    finally:
        cifar._synthetic.cache_clear()


def _run_fit(tmp_path):
    cfg = small_cfg(
        nworkers=4, batch_size=4, compression="gtopk", density=0.01,
        max_epochs=2, eval_batches=1, out_dir=str(tmp_path), seed=123,
    )
    with Trainer(cfg) as t:
        spe = t.steps_per_epoch
        assert spe == 8
        stats = t.fit()
        assert int(t.state.step) == 2 * spe
        assert np.isfinite(stats["loss"]) and "val_top1" in stats
    with Trainer(cfg) as t2:
        assert t2.restore()
        assert int(t2.state.step) == 2 * spe
        # fit() from a fully-trained checkpoint is a no-op, not a retrain.
        t2.fit()
        assert int(t2.state.step) == 2 * spe


def test_sharded_eval_matches_sequential_and_batches_groups():
    """test() on a p>1 mesh shards the val stream P('dp') (TPU-first eval
    — the reference evaluated rank-0-only, SURVEY.md §3.5): metrics must
    equal the sequential single-device path exactly (same batches, same
    host-side weighting, pad shards of a partial tail group excluded),
    and the number of device dispatches must be ceil(nbatches / P) — the
    structural 1/P walltime property, asserted without timing flakiness.
    eval_batches=5 on an 8-way mesh exercises the pad path (one group,
    3 pad shards)."""
    cfg8 = small_cfg(nworkers=8, batch_size=4, eval_batches=5,
                     compression="gtopk", density=0.01)
    cfg1 = small_cfg(nworkers=1, batch_size=4, eval_batches=5)
    t8, t1 = Trainer(cfg8), Trainer(cfg1)
    assert t8._eval_sharded and not t1._eval_sharded

    calls = {"n": 0}
    inner = t8._eval_step

    def counting(*a):
        calls["n"] += 1
        return inner(*a)

    t8._eval_step = counting
    ev8, ev1 = t8.test(), t1.test()
    assert calls["n"] == 1  # ceil(5 / 8)
    for k in ("val_loss", "val_top1", "val_top5"):
        np.testing.assert_allclose(ev8[k], ev1[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)

    # two full groups + a partial one
    t8.cfg.eval_batches = 17
    t1.cfg.eval_batches = 17
    calls["n"] = 0
    ev8, ev1 = t8.test(), t1.test()
    assert calls["n"] == 3  # ceil(17 / 8)
    np.testing.assert_allclose(ev8["val_loss"], ev1["val_loss"],
                               rtol=1e-5, atol=1e-6)


def test_sharded_eval_an4_cer_path():
    """AN4 eval (CER/WER via greedy decode) rides the sharded path too:
    per-shard logits come back [P, B, T, V] and the host-side error
    counting sees only the real (non-pad) shards."""
    t = Trainer(small_cfg(dnn="lstman4", batch_size=2, nworkers=2,
                          compression="gtopk", density=0.05,
                          eval_batches=3))
    assert t._eval_sharded
    ev = t.test()
    assert np.isfinite(ev["val_loss"])
    assert 0.0 <= ev["val_cer"] and ev["val_wer"] >= 0.0


def test_ptb_eval_stays_sequential():
    """The PTB LSTM threads a BPTT carry through the val stream in order
    — semantically serial, so it must keep the sequential eval path even
    on a multi-device mesh."""
    t = Trainer(small_cfg(dnn="lstm", batch_size=4, nworkers=4,
                          compression="gtopk", density=0.05,
                          eval_batches=2))
    assert not t._eval_sharded
    ev = t.test()
    assert ev["val_ppl"] > 1.0


def test_s2d_cli_flag_and_guard():
    """--s2d plumbs to TrainConfig.space_to_depth; a non-resnet50 model
    rejects it with a clean error instead of a constructor TypeError."""
    args = build_argparser().parse_args(
        ["--dnn", "resnet50", "--s2d", "--nworkers", "1"])
    cfg = config_from_args(args)
    assert cfg.space_to_depth
    bad = small_cfg(space_to_depth=True)  # dnn=resnet20
    with pytest.raises(ValueError, match="resnet50 stem"):
        Trainer(bad)
