"""time_to_quality.py: the composed time-to-quality projection
(round-3 verdict missing #5 — BASELINE.md's time-to-76% row in the only
form one chip permits)."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "benchmarks", "time_to_quality.py")


def _load():
    spec = importlib.util.spec_from_file_location("ttq", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_density_filter_excludes_other_rho(tmp_path):
    """A rho=0.01 arm must not leak into a rho=0.001 composition (it
    converges far faster and would fake a cheap sparse row)."""
    ttq = _load()
    art = tmp_path / "convergence_fake.jsonl"
    report = {
        "kind": "report", "steps": 600,
        "modes": [
            {"mode": "dense", "density": 1.0,
             "steps_to_0.9_of_dense_drop": 400},
            {"mode": "gtopk", "density": 0.01,
             "steps_to_0.9_of_dense_drop": 100},
            {"mode": "gtopk+warmup", "density": 0.001,
             "steps_to_0.9_of_dense_drop": 500},
        ],
    }
    art.write_text(json.dumps(report) + "\n")
    steps = ttq.steps_to_quality([str(art)], "0.9", 0.001)
    assert set(steps) == {"dense", "gtopk+warmup"}
    assert steps["dense"]["steps"] == 400
    assert steps["gtopk+warmup"]["steps"] == 500
    # the sparse row carries its own artifact's dense arm for fair ratios
    assert steps["gtopk+warmup"]["dense_steps"] == 400


def test_longest_horizon_wins(tmp_path):
    ttq = _load()
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text(json.dumps({
        "kind": "report", "steps": 600,
        "modes": [{"mode": "dense", "density": 1.0,
                   "steps_to_0.9_of_dense_drop": 400}]}) + "\n")
    b.write_text(json.dumps({
        "kind": "report", "steps": 1200,
        "modes": [{"mode": "dense", "density": 1.0,
                   "steps_to_0.9_of_dense_drop": 450}]}) + "\n")
    steps = ttq.steps_to_quality([str(a), str(b)], "0.9", 0.001)
    assert steps["dense"]["steps"] == 450
    assert steps["dense"]["src"] == "b.jsonl"


@pytest.mark.skipif(
    not os.path.exists(os.path.join(
        REPO, "benchmarks", "results",
        "convergence_resnet20_warmup1200_cpu_mesh8.jsonl")),
    reason="committed convergence artifacts required")
def test_composes_from_committed_artifacts(tmp_path):
    """End-to-end over the committed artifacts: every row multiplies a
    measured step count by a projected step time, dense rows are the
    1.0 reference, and sparse beats dense only where comm dominates."""
    out = tmp_path / "ttq.json"
    subprocess.run(
        [sys.executable, SCRIPT, "--out", str(out), "--ps", "8", "32"],
        check=True, cwd=REPO, capture_output=True)
    rep = json.loads(out.read_text())
    assert rep["factors"]["compute_ms_measured"] > 0
    table = rep["table"]
    assert {r["p"] for r in table} == {8, 32}
    for r in table:
        # time_to_quality_min is rounded to 2 decimals in the artifact
        assert r["time_to_quality_min"] == pytest.approx(
            r["steps_to_quality"] * r["step_ms_projected"] / 1e3 / 60,
            abs=0.006)
    dense = {r["p"]: r for r in table if r["mode"] == "dense"}
    for p, r in dense.items():
        assert r["vs_dense_time"] == 1.0
    # At P=32 (crossing DCN in the model) dense pays the O(N) transfer;
    # any measured sparse mode must beat it on projected step time.
    sparse32 = [r for r in table if r["p"] == 32 and r["mode"] != "dense"]
    assert sparse32, "no sparse rows composed"
    for r in sparse32:
        assert r["step_ms_projected"] < dense[32]["step_ms_projected"]


def test_alpha_bracket_fields():
    """Round-5 verdict #8: the composed artifact must carry the
    contention-bounded alpha bracket and a conservative per-row quote =
    min(anchor, alpha0) — never silently the favorable end."""
    import json
    import os

    out = os.path.join(REPO, "benchmarks", "results",
                       "time_to_quality_composed.json")
    assert os.path.exists(out), "composed artifact missing"
    with open(out) as fh:
        d = json.load(fh)
    br = d["factors"]["dcn_alpha_bracket"]
    assert br["floor_alpha0"] == 0.0
    # The bracket's measured ENDPOINTS come from the dcn_probe artifacts;
    # on a checkout without them (fresh clone, probe not run on this
    # host), the composed artifact may carry nulls there. The structural
    # guarantees below (conservative = min) hold regardless.
    probes = [os.path.join(REPO, "benchmarks", "results",
                           f"dcn_probe_{np}proc.json") for np in (2, 4)]
    if all(os.path.exists(q) for q in probes):
        assert br["anchor_2proc_ms"] and br["contended_4proc_ms"]
        assert br["contended_4proc_ms"] > 2 * br["anchor_2proc_ms"]  # 6x gap
    for row in d["table"]:
        vs, vs0 = row["vs_dense_time"], row["vs_dense_time_alpha0"]
        assert row["vs_dense_time_conservative"] == min(vs, vs0)


def test_conflict_records_carry_regime_context(tmp_path):
    """Two same-horizon artifacts that disagree on steps: the losing side
    must land in conflicts WITH its worker regime (nworkers/batch_size),
    and the winner's regime must be readable from its record — so a
    450-vs-1100-style disagreement is classifiable as regime-vs-
    measurement without opening the source artifacts."""
    import json

    ttq = _load()

    def write(name, nworkers, batch, steps_to_q, arms):
        rows = []
        modes = []
        for m, s in steps_to_q.items():
            modes.append({"mode": m, "density": 1.0 if m == "dense"
                          else 0.001, "steps_to_0.9_of_dense_drop": s})
        rows.append({"kind": "report", "dnn": "resnet20", "steps": 1200,
                     "batch_size": batch, "nworkers": nworkers,
                     "modes": modes[:arms]})
        p = tmp_path / name
        with open(p, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        return str(p)

    a = write("a_mesh2.jsonl", 2, 16,
              {"dense": 300, "gtopk+warmup": 450}, arms=2)
    b = write("b_mesh8.jsonl", 8, 4,
              {"dense": 450, "gtopk+warmup": 1100}, arms=2)
    out = ttq.steps_to_quality([a, b], "0.9", 0.001)
    w = out["gtopk+warmup"]
    # same horizon + same arm count: first-seen wins, other side recorded
    assert w["steps"] == 450 and w["nworkers"] == 2 and w["batch_size"] == 16
    # CROSS-regime disagreement classifies as a regime VARIANT (the
    # re-measured-and-reproduced 450-vs-1100 case), not a conflict
    assert w["conflicts"] == []
    assert w["regime_variants"] == [{"steps": 1100, "src": "b_mesh8.jsonl",
                                     "horizon": 1200, "nworkers": 8,
                                     "batch_size": 4}]
    # SAME-regime disagreement stays a real conflict
    c = write("c_mesh2.jsonl", 2, 16,
              {"dense": 310, "gtopk+warmup": 700}, arms=2)
    out2 = ttq.steps_to_quality([a, c], "0.9", 0.001)
    w2 = out2["gtopk+warmup"]
    assert w2["regime_variants"] == []
    assert [e["steps"] for e in w2["conflicts"]] == [700]

    # Supersede re-classifies inherited entries against the NEW winner:
    # a+c disagree same-regime (2x16); a longer-horizon 8x4 artifact d
    # then wins, and BOTH inherited 2x16 entries must re-land as regime
    # variants of d (not stay labeled conflicts of a 2x16 winner)
    import json as _json
    rows = [{"kind": "report", "dnn": "resnet20", "steps": 2000,
             "batch_size": 4, "nworkers": 8,
             "modes": [{"mode": "dense", "density": 1.0,
                        "steps_to_0.9_of_dense_drop": 500},
                       {"mode": "gtopk+warmup", "density": 0.001,
                        "steps_to_0.9_of_dense_drop": 1500}]}]
    dpath = tmp_path / "d_mesh8_long.jsonl"
    with open(dpath, "w") as fh:
        for r in rows:
            fh.write(_json.dumps(r) + "\n")
    out3 = ttq.steps_to_quality([a, c, str(dpath)], "0.9", 0.001)
    w3 = out3["gtopk+warmup"]
    assert w3["steps"] == 1500 and w3["nworkers"] == 8
    assert w3["conflicts"] == []
    assert sorted(e["steps"] for e in w3["regime_variants"]) == [450, 700]
