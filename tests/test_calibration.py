"""Comm-model calibration (obs/calib.py) and the cross-run registry
(obs/registry.py).

The fit is pinned against synthetic ground truth — samples generated from
the exact alpha-beta decomposition the ledger prices, with injected
straggler outliers — so the robust estimator's recovery is checked
against known constants, never against itself. The closed loop (ISSUE
acceptance) is demonstrated end-to-end: a calibrated artifact whose alpha
differs from the committed probe flips the planner's chosen schedule at
the tree/balanced crossover with no code change, only the artifact.
"""

import json
import os

import pytest

from gtopkssgd_tpu.obs.calib import (
    CommCalibrator,
    fit_alpha_beta,
    load_fit_file,
    message_count,
)
from gtopkssgd_tpu.obs.events import AnomalyHalt, AnomalyMonitor, Thresholds
from gtopkssgd_tpu.obs.ledger import _tree_rounds_fallback, load_alpha_beta
from gtopkssgd_tpu.obs import registry as obs_registry
from gtopkssgd_tpu.obs import report as obs_report
from gtopkssgd_tpu.utils.metrics import MetricsLogger

# Ground truth for the synthetic streams: a fast fabric, far from the
# committed ~22 ms loopback-TCP probe fit so drift is unambiguous.
TRUE_ALPHA, TRUE_BETA = 4.0, 2.0


def _stream(wire_mode="gtopk", p=4, n=32, alpha=TRUE_ALPHA,
            beta=TRUE_BETA, straggler_every=0, straggler_x=5.0):
    """(msgs, wire_bytes, t_comm_ms) samples from the exact model the
    calibrator inverts, bytes swept over 8 distinct levels; every
    ``straggler_every``-th sample inflated by ``straggler_x``."""
    msgs = message_count(wire_mode, p)
    out = []
    for i in range(n):
        b = 200_000 + 40_000 * (i % 8)
        t = msgs * (alpha + (b / msgs) * 8e-6 / beta)
        if straggler_every and i % straggler_every == 0:
            t *= straggler_x
        out.append((msgs, b, t))
    return out


# ------------------------------------------------------------------ fit

def test_message_count_matches_ledger_decomposition():
    # the alpha multipliers of predict_comm_ms's schedules
    assert message_count("dense", 4) == 6            # 2(p-1)
    assert message_count("gtopk_balanced", 4) == 6   # 2(p-1)
    assert message_count("allgather", 4) == 3        # p-1
    assert message_count("gtopk", 8) == _tree_rounds_fallback(8)
    assert message_count("gtopk_hier", 8, ici_size=4) == \
        _tree_rounds_fallback(2)
    assert message_count("gtopk", 1) == 0            # nothing on the wire


def test_fit_recovers_ground_truth_exactly():
    fit = fit_alpha_beta(_stream())
    assert fit["identifiable"] == "alpha_beta"
    assert fit["alpha_ms"] == pytest.approx(TRUE_ALPHA, rel=1e-9)
    assert fit["beta_gbps"] == pytest.approx(TRUE_BETA, rel=1e-9)
    assert fit["resid_ms"] == pytest.approx(0.0, abs=1e-9)


def test_fit_robust_to_ten_percent_stragglers():
    """The ISSUE's pinned property: 10% of samples inflated 5x (a
    straggling rank) must not drag the fit — Theil-Sen medians ride
    through where least squares would be pulled arbitrarily far."""
    fit = fit_alpha_beta(_stream(n=40, straggler_every=10))
    assert fit["alpha_ms"] == pytest.approx(TRUE_ALPHA, rel=0.05)
    assert fit["beta_gbps"] == pytest.approx(TRUE_BETA, rel=0.05)
    # the outliers show up where they should: the residual spread
    assert fit["resid_ms"] >= 0.0


def test_fit_alpha_only_when_bytes_constant():
    """The live-run degenerate case: a fixed-k run ships near-constant
    bytes, the slope is unidentifiable, and the fit must hold beta at
    the baseline instead of hallucinating a bandwidth."""
    msgs = message_count("gtopk", 4)
    b = 400_000
    samples = [(msgs, b, msgs * (TRUE_ALPHA + (b / msgs) * 8e-6 / 25.0))
               for _ in range(12)]
    fit = fit_alpha_beta(samples, baseline_beta_gbps=25.0)
    assert fit["identifiable"] == "alpha_only"
    assert fit["beta_gbps"] == pytest.approx(25.0)
    assert fit["alpha_ms"] == pytest.approx(TRUE_ALPHA, rel=1e-6)


def test_fit_needs_two_usable_samples():
    assert fit_alpha_beta([]) is None
    assert fit_alpha_beta([(2, 1000.0, 5.0)]) is None
    # non-finite / non-positive samples are discarded, not fatal
    assert fit_alpha_beta([(2, -1.0, 5.0), (0, 1000.0, 5.0)]) is None


# ----------------------------------------------------------- calibrator

def test_refit_window_cadence_and_calib_records(tmp_path):
    """One 'calib' record per completed refit window, durably written
    through MetricsLogger (kind registration included)."""
    out = str(tmp_path)
    with MetricsLogger(out) as m:
        c = CommCalibrator("gtopk", 4, metrics=m, refit_interval=8,
                           min_samples=4)
        recs = [r for i, (msgs, b, t) in enumerate(_stream(n=32))
                if (r := c.observe(i, b, t)) is not None]
    assert len(recs) == 4                    # 32 samples / window of 8
    assert [r["n_samples"] for r in recs] == [8, 16, 24, 32]
    assert recs[-1]["alpha_fit_ms"] == pytest.approx(TRUE_ALPHA)
    assert recs[-1]["beta_fit_gbps"] == pytest.approx(TRUE_BETA)
    # drift vs the startup fit appears from the second refit on
    assert "drift_alpha_startup_x" not in recs[0]
    assert recs[1]["drift_alpha_startup_x"] == pytest.approx(1.0)
    logged = [json.loads(l) for l in
              open(os.path.join(out, "metrics.jsonl"))]
    assert [r["kind"] for r in logged] == ["calib"] * 4
    assert logged[-1]["alpha_fit_ms"] == pytest.approx(TRUE_ALPHA)


def test_drift_rule_fires_after_warmup():
    """Baseline = the committed ~22 ms probe fit, live fabric 4 ms: a
    >4x divergence in alpha. The rule arms only after comm_drift_warmup
    refits, then fires on every refit."""
    mon = AnomalyMonitor(halt_on=None)
    c = CommCalibrator(
        "gtopk", 4,
        baseline={"alpha_ms": 21.8594, "beta_gbps": 0.6,
                  "fit_source": "dcn_probe_4proc.json"},
        monitor=mon, refit_interval=8, min_samples=4)
    for i, (msgs, b, t) in enumerate(_stream(n=32)):
        c.observe(i, b, t)
    # 4 refits, warmup 2 -> fires on refits 3 and 4
    assert mon.summary() == {"comm_model_drift": 2}
    ev = mon.events[0]
    assert ev["severity"] == "warn"
    assert ev["value"] == pytest.approx(21.8594 / TRUE_ALPHA, rel=1e-4)
    assert "dcn_probe_4proc.json" in ev["message"]


def test_drift_rule_quiet_when_fit_matches_baseline():
    mon = AnomalyMonitor(halt_on=None)
    c = CommCalibrator(
        "gtopk", 4,
        baseline={"alpha_ms": TRUE_ALPHA, "beta_gbps": TRUE_BETA},
        monitor=mon, refit_interval=4, min_samples=4)
    for i, (msgs, b, t) in enumerate(_stream(n=24)):
        c.observe(i, b, t)
    assert mon.summary() == {}


def test_drift_rule_honors_halt_on_after_durable_record(tmp_path):
    """--obs-halt-on warn semantics: the halt propagates out of
    observe(), and the triggering calib record is already on disk when
    it does (record-then-raise, like every monitor rule)."""
    out = str(tmp_path)
    m = MetricsLogger(out)
    mon = AnomalyMonitor(metrics=m, halt_on="warn",
                         thresholds=Thresholds(comm_drift_warmup=0))
    c = CommCalibrator(
        "gtopk", 4, baseline={"alpha_ms": 21.8594, "beta_gbps": 0.6},
        metrics=m, monitor=mon, refit_interval=4, min_samples=4)
    with pytest.raises(AnomalyHalt) as exc:
        for i, (msgs, b, t) in enumerate(_stream(n=8)):
            c.observe(i, b, t)
    m.close()
    assert exc.value.event["rule"] == "comm_model_drift"
    recs = [json.loads(l) for l in
            open(os.path.join(out, "metrics.jsonl"))]
    kinds = [r["kind"] for r in recs]
    # the calib record that diagnosed the drift precedes the event
    assert kinds.index("calib") < kinds.index("event")


def test_calibrator_quarantines_overlapped_samples():
    """PR 15: samples measured under the overlapped pipeline report the
    EXPOSED comm span (part of the wire time hidden under selection), so
    the per-message alpha-beta inversion does not hold for them. They
    must never enter the serial fit — here every overlapped sample is
    corrupted to a third of the true time, and the fit still recovers
    the ground truth exactly."""
    c = CommCalibrator("gtopk", 4, refit_interval=8, min_samples=4,
                       fit_window=8, max_samples=8)
    rec = None
    for i, (msgs, b, t) in enumerate(_stream(n=16)):
        # an overlapped twin of every serial sample, 3x too fast
        assert c.observe(i, b, t / 3.0, overlapped=True) is None
        rec = c.observe(i, b, t) or rec
    assert len(c.samples) == 8                    # trimmed to max_samples
    assert len(c.overlap_samples) == 8            # quarantined AND trimmed
    assert all(s[2] < min(x[2] for x in c.samples)
               for s in c.overlap_samples)        # the fast twins, apart
    assert rec is not None
    assert rec["n_overlap_excluded"] == 8
    assert rec["alpha_fit_ms"] == pytest.approx(TRUE_ALPHA, rel=1e-9)
    assert rec["beta_fit_gbps"] == pytest.approx(TRUE_BETA, rel=1e-9)
    # overlapped observes never advance the refit window: 16 tagged
    # samples alone produce no fit at all
    c2 = CommCalibrator("gtopk", 4, refit_interval=4, min_samples=4)
    for i, (msgs, b, t) in enumerate(_stream(n=16)):
        assert c2.observe(i, b, t, overlapped=True) is None
    assert c2.samples == [] and c2.fits == []


# ------------------------------------------- artifact + the closed loop

def test_artifact_roundtrips_through_planner_inputs(tmp_path):
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    c = CommCalibrator("gtopk", 4, refit_interval=8)
    for i, (msgs, b, t) in enumerate(_stream(n=16)):
        c.observe(i, b, t)
    d = str(tmp_path / "probe")
    path = c.write_artifact(d, manifest={
        "config_hash": "abc123", "git_sha": "deadbee",
        "compression": "gtopk", "nworkers": 4})
    assert os.path.basename(path) == "calib_fit_4proc.json"
    doc = json.load(open(path))
    assert doc["provenance"]["config_hash"] == "abc123"
    assert doc["alpha_beta_fit"]["alpha_ms"] == pytest.approx(TRUE_ALPHA)
    # ledger.load_alpha_beta reads the dcn_probe-compatible payload
    fit = load_alpha_beta(search_dir=d)
    assert fit["alpha_ms"] == pytest.approx(TRUE_ALPHA)
    assert fit["source"] == "calib_fit_4proc.json"
    inputs = planner_inputs(d)
    assert inputs["fit_source"] == "calib_fit_4proc.json"
    assert inputs["beta_gbps"] == pytest.approx(TRUE_BETA)


def test_artifact_none_without_samples(tmp_path):
    c = CommCalibrator("gtopk", 4)
    assert c.write_artifact(str(tmp_path)) is None


def test_calib_artifact_flips_planner_schedule(tmp_path):
    """ISSUE acceptance: the closed obs->planner loop. At (p=32,
    n=25.6M, k=256k) the committed ~22 ms probe alpha prices the
    hypercube tree cheapest; a run calibrated on a fast fabric
    (alpha ~ 0.1 ms) writes an artifact that — with NO code change —
    makes the same planner call pick the balanced schedule."""
    from gtopkssgd_tpu.parallel.planner import build_decision, resolve_plan

    shape = dict(p=32, n=25_557_032, k=255_571)
    committed = build_decision("gtopk", **shape)
    assert committed.plan.name == "tree"

    c = CommCalibrator("gtopk", 32, refit_interval=8)
    for i, (msgs, b, t) in enumerate(
            _stream(wire_mode="gtopk", p=32, n=16, alpha=0.1)):
        c.observe(i, b, t)
    d = str(tmp_path / "calibrated")
    c.write_artifact(d)

    calibrated = build_decision("gtopk", probe_dir=d, **shape)
    assert calibrated.inputs["fit_source"] == "calib_fit_32proc.json"
    assert calibrated.inputs["alpha_ms"] == pytest.approx(0.1, rel=0.05)
    assert calibrated.plan.name == "balanced"
    # the optimizer's memoized trace-time entry point flips identically
    # (fresh tmp dirs -> distinct lru_cache keys)
    plan = resolve_plan("gtopk", shape["p"], shape["n"], shape["k"],
                        "fp32", 1, "auto", d)
    assert plan.name == "balanced"


def test_load_alpha_beta_numeric_proc_sort(tmp_path):
    """Satellite regression: lexicographic basename sort ranked 8proc
    over 16proc; the numeric sort honors the docstring's "largest proc
    count present wins", and a calib_fit outranks a dcn_probe at equal
    proc count."""
    d = str(tmp_path)
    for n in (2, 8, 16):
        with open(os.path.join(d, f"dcn_probe_{n}proc.json"), "w") as fh:
            json.dump({"procs": n, "alpha_beta_fit":
                       {"alpha_ms": float(n), "beta_gbps": 1.0}}, fh)
    fit = load_alpha_beta(search_dir=d)
    assert fit["source"] == "dcn_probe_16proc.json"
    assert fit["alpha_ms"] == 16.0
    # explicit nprocs still pins the exact count
    assert load_alpha_beta(search_dir=d, nprocs=8)["alpha_ms"] == 8.0
    # in-situ calibration beats the synthetic probe at the same P
    with open(os.path.join(d, "calib_fit_16proc.json"), "w") as fh:
        json.dump({"procs": 16, "alpha_beta_fit":
                   {"alpha_ms": 99.0, "beta_gbps": 2.0}}, fh)
    assert load_alpha_beta(search_dir=d)["source"] == "calib_fit_16proc.json"


def test_load_fit_file_rejects_malformed(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as fh:
        json.dump({"alpha_beta_fit": {"alpha_ms": 1.0}}, fh)  # no beta
    with pytest.raises(ValueError):
        load_fit_file(p)
    good = str(tmp_path / "calib_fit_2proc.json")
    with open(good, "w") as fh:
        json.dump({"alpha_beta_fit":
                   {"alpha_ms": 1.5, "beta_gbps": 3.0}}, fh)
    fit = load_fit_file(good)
    assert fit == {"alpha_ms": 1.5, "beta_gbps": 3.0,
                   "source": "calib_fit_2proc.json"}


# ------------------------------------------------------------- registry

def _entry(config_hash="cfg0", git_sha="aaaa", **stats):
    base = dict(steps_per_sec=2.0, loss_last=1.5, alpha_ms=4.0,
                beta_gbps=2.0, wire_bytes_per_step=1e6)
    base.update(stats)
    return {"time": 0.0, "config_hash": config_hash, "git_sha": git_sha,
            "stats": base}


def _run_records(config_hash="cfg0", loss=1.5, with_calib=True):
    recs = [{"kind": "manifest", "time": 100.0, "rank": 0,
             "config_hash": config_hash, "git_sha": "bbbb",
             "dnn": "resnet20", "compression": "gtopk", "nworkers": 2},
            {"kind": "train", "time": 101.0, "rank": 0, "step": 1,
             "loss": 2.0},
            {"kind": "train", "time": 103.0, "rank": 0, "step": 5,
             "loss": loss},
            {"kind": "obs", "time": 102.0, "rank": 0, "step": 2,
             "wire_bytes": 1e6, "audit_recall": 0.93},
            {"kind": "obs", "time": 102.5, "rank": 0, "step": 4,
             "wire_bytes": 1e6, "audit_recall": 0.97},
            {"kind": "attr", "time": 102.6, "rank": 0,
             "t_comm_us": 200.0, "t_total_us": 1000.0}]
    if with_calib:
        recs.append({"kind": "calib", "time": 103.5, "rank": 0,
                     "step": 5, "alpha_fit_ms": 4.0,
                     "beta_fit_gbps": 2.0, "n_samples": 8})
    return recs


def test_run_summary_distills_the_stream():
    s = obs_registry.run_summary(_run_records())
    assert s["config_hash"] == "cfg0"
    st = s["stats"]
    assert st["steps_per_sec"] == pytest.approx(2.0)   # 4 steps / 2 s
    assert st["loss_last"] == pytest.approx(1.5)
    assert st["mean_comm_ratio"] == pytest.approx(0.2)
    assert st["alpha_ms"] == pytest.approx(4.0)
    assert st["recall_floor"] == pytest.approx(0.93)
    assert st["wire_bytes_per_step"] == pytest.approx(1e6)
    # no manifest -> nothing to key on
    assert obs_registry.run_summary(_run_records()[1:]) is None


def test_run_summary_carries_pipeline_shape():
    """PR 15 plan-shape stats: pipeline from the plan record (the
    decision as executed), n_buckets from the manifest's bucket_ks, and
    overlap_frac averaged over the attr records."""
    recs = _run_records()
    recs[0]["bucket_ks"] = [120, 80, 56]
    recs.insert(1, {"kind": "plan", "time": 100.5, "rank": 0,
                    "name": "tree", "pipeline": "overlap"})
    for rec, f in zip([r for r in recs if r.get("kind") == "attr"],
                      (0.5,)):
        rec["overlap_frac"] = f
    recs.append({"kind": "attr", "time": 102.8, "rank": 0,
                 "t_comm_us": 100.0, "t_total_us": 1000.0,
                 "overlap_frac": 0.7})
    st = obs_registry.run_summary(recs)["stats"]
    assert st["pipeline"] == "overlap"
    assert st["n_buckets"] == 3
    assert st["overlap_frac"] == pytest.approx(0.6)
    # no plan record -> the manifest stamp is the fallback
    plain = _run_records()
    plain[0]["pipeline"] = "serial"
    st2 = obs_registry.run_summary(plain)["stats"]
    assert st2["pipeline"] == "serial"
    assert "overlap_frac" not in st2 and "n_buckets" not in st2
    # the history table prints the three new columns for every entry
    entry = obs_registry.run_summary(recs)
    (row,) = obs_registry.history_rows([entry])
    assert len(row) == len(obs_registry.HISTORY_HEADER)
    hdr = obs_registry.HISTORY_HEADER
    assert row[hdr.index("pipeline")] == "overlap"
    assert row[hdr.index("B")] == "3"
    assert row[hdr.index("ovl_frac")] == "0.6000"
    (row2,) = obs_registry.history_rows([obs_registry.run_summary(plain)])
    assert row2[hdr.index("pipeline")] == "serial"
    assert row2[hdr.index("B")] == "-"


def test_regress_pins_pipeline_and_bucket_shape():
    """The exact-string loop: a pipeline flipped serial<->overlap under
    the same config is a plan regression; overlap_frac gets a purely
    absolute 0.1 slack so a serial 0.0 baseline still bounds the run;
    n_buckets is exact."""
    base = _entry(pipeline="overlap", n_buckets=4, overlap_frac=0.6)

    def _status(cur, field):
        rows, failures = obs_registry.regress(cur, base)
        return {r[0]: r[4] for r in rows}[field], failures

    same = _entry(pipeline="overlap", n_buckets=4, overlap_frac=0.62)
    st, fails = _status(same, "pipeline")
    assert st == "ok" and fails == 0
    # pipeline silently collapsed back to serial -> FAIL
    st, fails = _status(
        _entry(pipeline="serial", n_buckets=4, overlap_frac=0.62),
        "pipeline")
    assert st == "FAIL" and fails >= 1
    # pipeline vanished entirely -> MISSING
    st, fails = _status(_entry(n_buckets=4, overlap_frac=0.62), "pipeline")
    assert st == "MISSING" and fails >= 1
    # overlap collapsed past the 0.1 absolute slack -> FAIL
    st, _ = _status(
        _entry(pipeline="overlap", n_buckets=4, overlap_frac=0.45),
        "overlap_frac")
    assert st == "FAIL"
    # the DP re-deciding B under the same config -> FAIL (exact)
    st, _ = _status(
        _entry(pipeline="overlap", n_buckets=5, overlap_frac=0.6),
        "n_buckets")
    assert st == "FAIL"
    # new instrumentation on the current side is not a regression
    rows, fails = obs_registry.regress(
        _entry(pipeline="overlap"), _entry())
    assert {r[0]: r[4] for r in rows}["pipeline"] == "new"
    assert fails == 0
    # serial baseline 0.0 bounds a mildly-overlapped current run
    rows, fails = obs_registry.regress(
        _entry(overlap_frac=0.08), _entry(overlap_frac=0.0))
    assert {r[0]: r[4] for r in rows}["overlap_frac"] == "ok"
    assert fails == 0


def test_registry_append_history_and_torn_lines(tmp_path, capsys):
    d = str(tmp_path / "reg")
    obs_registry.append_run(d, _entry())
    obs_registry.append_run(d, _entry(git_sha="cccc", steps_per_sec=2.2))
    # a run killed mid-append leaves a torn line; it must be skipped
    with open(obs_registry.registry_path(d), "a") as fh:
        fh.write('{"time": 1.0, "config_')
    entries, bad = obs_registry.load_registry(d)
    assert len(entries) == 2 and bad == 1
    rows = obs_registry.history_rows(entries)
    assert len(rows) == 2
    assert obs_registry.history_rows(entries, config_hash="nope") == []
    # the offline CLI contract: exit 0 with entries, 1 without
    assert obs_report.main(["history", d]) == 0
    assert "2 run(s)" in capsys.readouterr().out
    assert obs_report.main(["history", str(tmp_path / "empty")]) == 1


def _run_dir(tmp_path, name, **kw):
    d = tmp_path / name
    d.mkdir()
    with open(d / "metrics.jsonl", "w") as fh:
        for rec in _run_records(**kw):
            fh.write(json.dumps(rec) + "\n")
    return str(d)


def test_regress_exit_contract(tmp_path):
    """Exit codes follow the gate contract: 0 within tolerance, 1 on
    drift (or a vanished stat), 2 on usage (empty registry / no
    same-config baseline without --allow-mismatch)."""
    reg = str(tmp_path / "reg")
    run = _run_dir(tmp_path, "run")
    # 2: registry empty
    assert obs_report.main(["regress", run, "--registry", reg]) == 2
    obs_registry.append_run(
        reg, obs_registry.run_summary(_run_records()))
    # 0: identical stats
    assert obs_report.main(["regress", run, "--registry", reg]) == 0
    # 1: loss regressed far past 25% rtol
    worse = _run_dir(tmp_path, "worse", loss=15.0)
    assert obs_report.main(["regress", worse, "--registry", reg]) == 1
    # 1: a stat the baseline had (alpha_ms) vanished from the run
    gone = _run_dir(tmp_path, "gone", with_calib=False)
    assert obs_report.main(["regress", gone, "--registry", reg]) == 1
    # 2 unless --allow-mismatch: different config_hash
    other = _run_dir(tmp_path, "other", config_hash="cfg1")
    assert obs_report.main(["regress", other, "--registry", reg]) == 2
    assert obs_report.main(["regress", other, "--registry", reg,
                            "--allow-mismatch"]) == 0


def test_regress_picks_latest_same_config_baseline(tmp_path):
    reg = str(tmp_path / "reg")
    obs_registry.append_run(reg, _entry(config_hash="cfgX"))
    obs_registry.append_run(reg, _entry(steps_per_sec=9.0))
    entries, _ = obs_registry.load_registry(reg)
    cur = obs_registry.run_summary(_run_records())
    base = obs_registry.pick_baseline(cur, entries)
    assert base["stats"]["steps_per_sec"] == 9.0   # newest cfg0 entry
    rows, failures = obs_registry.regress(cur, base)
    # 2.0 vs 9.0 steps/sec is far outside 25%
    assert failures >= 1


# -------------------------------------------------- trainer integration

def test_trainer_calibrates_and_writes_artifact(tmp_path):
    """End-to-end on the 2-device CPU mesh: --obs-calib captures real
    profiler-attributed dispatches, logs calib records, stamps fit
    provenance into the manifest, writes the end-of-run artifact, and
    appends a registry line the regress CLI can read."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    out = str(tmp_path / "run")
    reg = str(tmp_path / "reg")
    cfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                      compression="gtopk_layerwise", density=0.01,
                      seed=42, max_epochs=1, log_interval=1,
                      obs_interval=1, eval_batches=1, out_dir=out,
                      obs_calib=True, obs_calib_interval=1,
                      registry=reg)
    with Trainer(cfg) as t:
        assert t.calib is not None
        t.train(5)
        assert len(t.calib.samples) >= 4, \
            "profiler attribution produced no usable comm samples"
    recs = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    man = next(r for r in recs if r["kind"] == "manifest")
    assert man["comm_fit_source"]          # provenance stamped
    calibs = [r for r in recs if r["kind"] == "calib"]
    assert calibs, "no calib record logged"
    assert calibs[0]["wire_mode"] and calibs[0]["p"] == 2
    assert calibs[0]["n_samples"] >= 4
    # end-of-run artifact closes the loop for the NEXT run
    art = os.path.join(out, "calib_fit_2proc.json")
    assert os.path.exists(art)
    assert json.load(open(art))["provenance"]["config_hash"] == \
        man["config_hash"]
    assert planner_inputs(out)["fit_source"] == "calib_fit_2proc.json"
    # registry got this run's line; regress against itself passes
    entries, bad = obs_registry.load_registry(reg)
    assert len(entries) == 1 and bad == 0
    assert entries[0]["config_hash"] == man["config_hash"]
    assert obs_report.main(["regress", out, "--registry", reg]) == 0
    # provenance lines print from the shards alone
    assert obs_report.main(["plan", out]) == 0
    assert obs_report.main(["ledger", out]) == 0


def test_trainer_comm_model_fit_flag(tmp_path):
    """--comm-model-fit: an explicit artifact prices the plan decision,
    its filename lands in manifest + plan record, and the decided
    schedule is pinned through to the optimizer."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    fit_path = str(tmp_path / "calib_fit_2proc.json")
    with open(fit_path, "w") as fh:
        json.dump({"procs": 2, "alpha_beta_fit":
                   {"alpha_ms": 7.25, "beta_gbps": 3.5}}, fh)
    out = str(tmp_path / "run")
    cfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                      compression="gtopk_layerwise", density=0.01,
                      seed=42, max_epochs=1, log_interval=1,
                      eval_batches=1, out_dir=out,
                      comm_model_fit=fit_path)
    with Trainer(cfg) as t:
        d = t._plan_decision
        assert d.inputs["fit_source"] == "calib_fit_2proc.json"
        assert d.inputs["alpha_ms"] == pytest.approx(7.25)
        assert t._comm_plan_pin == d.plan.name
    recs = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    man = next(r for r in recs if r["kind"] == "manifest")
    assert man["comm_fit_source"] == "calib_fit_2proc.json"
    assert man["comm_fit_alpha_ms"] == pytest.approx(7.25)
    plan = next(r for r in recs if r["kind"] == "plan")
    assert plan["fit_source"] == "calib_fit_2proc.json"
    # a malformed artifact fails at startup, never silently falls back
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as fh:
        fh.write("{}")
    with pytest.raises(ValueError):
        Trainer(TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                            compression="gtopk_layerwise", density=0.01,
                            seed=42, comm_model_fit=bad))
