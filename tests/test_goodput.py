"""Goodput ledger (gtopkssgd_tpu.obs.goodput): the badput taxonomy, the
conservation invariant, the live cursor ledger, the offline fold, and
every surface the decomposition threads through (fleet join, report
CLI, registry, exporter, timeline, the goodput_collapse rule, and the
abnormal-exit registry paths).

The unit layer runs on a fake clock and the committed 3-rank fixture
(tests/fixtures/goodput — regenerate with make_goodput_fixture.py),
whose category seconds are hand-chosen so every join is exactly
computable: per-rank goodput_frac (0.8, 0.6, 0.4), fleet 0.6 over 30.0
rank-seconds, and advise() naming rank 2 ("wait", 2.0 recoverable s).
The e2e layer drives the real trainer on the canonical 2-way CPU mesh
config through the 43/44/45 exit paths and asserts each still lands a
final goodput record and its registry line.
"""

import json
import os

import pytest

from gtopkssgd_tpu.obs import goodput as gp
from gtopkssgd_tpu.obs.events import (
    RULES,
    AnomalyHalt,
    AnomalyMonitor,
)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "goodput")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Same model/flags as benchmarks/obs_gate_smoke.py and test_resilience
# so the e2e runs below reuse the persistent-cache XLA executable.
CANON = [
    "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
    "--compression", "gtopk_layerwise", "--density", "0.01",
    "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
    "--obs-interval", "1",
]


def _records(out_dir):
    path = os.path.join(out_dir, "metrics.jsonl")
    return [json.loads(line) for line in open(path)]


def _fixture_shards():
    from gtopkssgd_tpu.obs import fleet
    shards = fleet.resolve_targets([FIXTURE])
    records_by_rank, bad = fleet.load_shards(shards)
    assert bad == 0
    return records_by_rank


# ------------------------------------------------------- decomposition

def test_taxonomy_is_closed_and_ordered():
    assert gp.CATEGORIES[0] == gp.GOODPUT
    assert gp.CATEGORIES == (gp.GOODPUT,) + gp.BADPUT
    # "other" is derived, never a category of its own
    assert "other" not in gp.CATEGORIES
    assert len(set(gp.CATEGORIES)) == len(gp.CATEGORIES)


def test_decomposition_conservation_and_fracs():
    rec = gp.decomposition({"goodput": 6.0, "wait": 3.0}, 10.0, step=7,
                           n_wasted_steps=1)
    assert rec["step"] == 7 and rec["n_wasted_steps"] == 1
    assert rec["goodput_s"] == 6.0 and rec["wait_s"] == 3.0
    assert rec["other_s"] == 1.0 and rec["other_frac"] == 0.1
    assert rec["goodput_frac"] == 0.6
    assert gp.conservation_error(rec) < 1e-9
    fr = gp.category_fracs(rec)
    assert fr["goodput"] == 0.6 and fr["wait"] == 0.3 and fr["other"] == 0.1


def test_decomposition_surfaces_negative_other():
    # Caller double-counting must be VISIBLE (other_s < 0), not clamped.
    rec = gp.decomposition({"goodput": 8.0, "comm": 4.0}, 10.0)
    assert rec["other_s"] == -2.0 and rec["other_frac"] == -0.2
    assert gp.conservation_error(rec) < 1e-9


def test_decomposition_zero_wall_is_safe():
    rec = gp.decomposition({}, 0.0)
    assert rec["goodput_frac"] == 0.0 and rec["other_frac"] == 0.0


def test_dominant_badput_tiebreak_and_none():
    # select/comm tie -> BADPUT order prefers select; no badput -> None;
    # a pure accounting gap (other) never wins.
    assert gp.dominant_badput(
        {"select_s": 0.5, "comm_s": 0.5, "wall_s": 2.0}) == "select"
    assert gp.dominant_badput({"goodput_s": 5.0, "other_s": 3.0}) is None
    assert gp.dominant_badput(
        {"wait_s": 1.0, "wasted_s": 2.0}) == "wasted"


# --------------------------------------------------------- live ledger

def _fake_clock():
    t = [0.0]

    def clock():
        return t[0]
    return t, clock


def test_ledger_mark_attributes_spans_once():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    t[0] = 1.5
    assert led.mark("select") == 1.5
    t[0] = 2.0
    assert led.mark("comm") == 0.5
    # zero-width span is a no-op; unknown category raises
    assert led.mark("select") == 0.0
    with pytest.raises(ValueError):
        t[0] = 3.0
        led.mark("no_such_category")
    assert led.seconds["select"] == 1.5 and led.seconds["comm"] == 0.5
    rec = led.snapshot(step=1)
    assert gp.conservation_error(rec) < 1e-9


def test_ledger_train_started_once_then_other():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    t[0] = 2.0
    led.train_started()
    assert led.seconds["startup"] == 2.0
    t[0] = 3.0
    led.train_started()                      # fit() re-entry: not startup
    assert led.seconds["startup"] == 2.0
    rec = led.snapshot(step=0)
    assert rec["other_s"] == 1.0             # the re-entry span


def test_ledger_step_split_follows_critpath_fracs():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    led.note_stage_fracs({"t_compute_us": 600.0, "t_select_us": 200.0,
                          "t_comm_wire_us": 100.0, "t_wait_us": 100.0})
    t[0] = 1.0
    led.step_mark(begin=True)
    assert abs(led.seconds["goodput"] - 0.6) < 1e-9
    assert abs(led.seconds["select"] - 0.2) < 1e-9
    assert abs(led.seconds["comm"] - 0.1) < 1e-9
    assert abs(led.seconds["wait"] - 0.1) < 1e-9
    # a zero-total critpath record is ignored, fracs kept
    led.note_stage_fracs({"t_compute_us": 0.0})
    assert led._fracs is not None


def test_ledger_step_defaults_to_goodput_without_critpath():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    t[0] = 2.0
    led.step_mark(begin=True)
    assert led.seconds["goodput"] == 2.0


def test_ledger_wasted_step_reclassifies_current_step():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    t[0] = 1.0
    led.step_mark(begin=True)
    assert led.seconds["goodput"] == 1.0
    reclassified = led.wasted_step()
    assert reclassified == 1.0
    assert led.seconds["goodput"] == 0.0
    assert led.seconds["wasted"] == 1.0 and led.n_wasted_steps == 1
    # conservation still holds after the move
    assert gp.conservation_error(led.snapshot(step=1)) < 1e-9


def test_ledger_degraded_charges_only_the_excess():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(clock=clock)
    t[0] = 1.0
    led.step_mark(begin=True)                # clean step, 1.0 s
    t[0] = 2.0
    led.step_mark(begin=True)                # closes it -> EWMA = 1.0
    assert led._step_ewma == 1.0
    t[0] = 5.0
    led.step_mark(begin=True, degraded=True)  # 3.0 s: 2.0 excess
    assert abs(led.seconds["degraded"] - 2.0) < 1e-9
    assert abs(led.seconds["goodput"] - 3.0) < 1e-9   # 1 + 1 + clamped 1
    t[0] = 6.0
    led.step_mark(begin=True)
    # the degraded step must NOT have fed the clean-step EWMA
    assert led._step_ewma == 1.0
    assert gp.conservation_error(led.snapshot(step=4)) < 1e-9


def test_ledger_tick_arms_then_logs_on_cadence():
    t, clock = _fake_clock()
    led = gp.GoodputLedger(interval=2, clock=clock)
    assert led.tick(0) is None               # first tick only arms
    assert led.tick(1) is None
    t[0] = 1.0
    rec = led.tick(2)
    assert rec is not None and rec["final"] == 0
    assert led.tick(3) is None               # cadence resets
    assert gp.GoodputLedger(interval=0, clock=clock).tick(5) is None


def test_ledger_log_record_feeds_monitor_except_final():
    class Mon:
        def __init__(self):
            self.calls = []

        def observe_goodput(self, step, *, goodput_frac=None):
            self.calls.append((step, goodput_frac))
            return []

    t, clock = _fake_clock()
    mon = Mon()
    led = gp.GoodputLedger(monitor=mon, clock=clock)
    t[0] = 2.0
    led.mark("goodput")
    led.log_record(5)
    led.log_record(7, final=True)            # the run is already ending
    assert [c[0] for c in mon.calls] == [5]
    assert mon.calls[0][1] == 1.0


# -------------------------------------------------------- offline fold

def test_fold_last_goodput_record_wins():
    recs = [
        {"kind": "manifest", "time": 0.0, "rank": 0},
        {"kind": "goodput", "time": 5.0, "rank": 0, "step": 5,
         "goodput_s": 2.0, "wall_s": 5.0, "goodput_frac": 0.4,
         "final": 0},
        {"kind": "goodput", "time": 10.0, "rank": 0, "step": 10,
         "goodput_s": 8.0, "wall_s": 10.0, "goodput_frac": 0.8,
         "final": 1},
    ]
    out = gp.fold(recs)
    assert out["step"] == 10 and out["goodput_frac"] == 0.8
    assert out["source"] == "ledger"
    assert "kind" not in out and "time" not in out and "rank" not in out


def test_synthesize_from_evidence_records():
    # manifest at t=100; steps 1..5 at 103..107 (median cadence 1.0);
    # compile 1.25 s carved out of the 2.0 s startup; one skip priced
    # at the cadence -> wasted 1.0; the stepped remainder is goodput.
    recs = [{"kind": "manifest", "time": 100.0}]
    recs += [{"kind": "obs", "step": s, "time": 102.0 + s}
             for s in range(1, 6)]
    recs.append({"kind": "compile", "lower_s": 0.5, "compile_s": 0.75})
    recs.append({"kind": "recovery", "action": "skip", "step": 3})
    out = gp.synthesize(recs)
    assert out["source"] == "folded" and out["final"] == 1
    assert out["wall_s"] == 7.0
    assert abs(out["compile_s"] - 1.25) < 1e-6
    assert abs(out["startup_s"] - 0.75) < 1e-6
    assert abs(out["wasted_s"] - 1.0) < 1e-6 and out["n_wasted_steps"] == 1
    assert abs(out["goodput_s"] - 4.0) < 1e-6
    assert gp.conservation_error(out) < 1e-6
    # no timed steps at all -> nothing to synthesize
    assert gp.synthesize([{"kind": "manifest", "time": 1.0}]) is None
    assert gp.fold([{"kind": "manifest", "time": 1.0}]) is None


# ---------------------------------------------- fixture joins (exact)

def test_fixture_fold_shards_exact_decompositions():
    decomp = gp.fold_shards(_fixture_shards())
    assert sorted(decomp) == [0, 1, 2]
    assert [decomp[r]["goodput_frac"] for r in (0, 1, 2)] == [0.8, 0.6, 0.4]
    assert [gp.dominant_badput(decomp[r]) for r in (0, 1, 2)] == \
        ["select", "wasted", "wait"]
    for r in (0, 1, 2):
        assert decomp[r]["wall_s"] == 10.0
        assert decomp[r]["other_s"] == 0.0
        assert decomp[r]["final"] == 1       # the final record won
        assert gp.conservation_error(decomp[r]) < 1e-9
    assert decomp[1]["n_wasted_steps"] == 2
    assert decomp[1]["ckpt_s"] == 0.8
    assert decomp[2]["wait_s"] == 4.8


def test_fixture_fleet_decomposition_is_wall_weighted():
    decomp = gp.fold_shards(_fixture_shards())
    fleet_rec = gp.fleet_decomposition(decomp)
    assert fleet_rec["n_ranks"] == 3
    assert fleet_rec["wall_s"] == 30.0
    assert fleet_rec["goodput_s"] == 18.0
    assert fleet_rec["goodput_frac"] == 0.6
    assert fleet_rec["n_wasted_steps"] == 2
    assert fleet_rec["source"] == "fleet"
    assert gp.fleet_decomposition({}) is None


def test_fixture_advise_names_the_straggler():
    decomp = gp.fold_shards(_fixture_shards())
    hint = gp.advise(decomp)
    assert hint["rank"] == 2
    assert hint["goodput_frac"] == 0.4
    assert hint["fleet_median_frac"] == 0.6
    assert hint["dominant_badput"] == "wait"
    assert abs(hint["recoverable_s"] - 2.0) < 1e-6
    # healthy fleet (everyone within margin) and single rank -> None
    assert gp.advise({0: decomp[0], 1: decomp[0]}) is None
    assert gp.advise({2: decomp[2]}) is None


def test_format_goodput_renders_table_bars_compare_hint():
    decomp = gp.fold_shards(_fixture_shards())
    fleet_rec = gp.fleet_decomposition(decomp)
    clean = {0: decomp[0]}
    text = gp.format_goodput(decomp, fleet=fleet_rec, compare=clean,
                             hint=gp.advise(decomp))
    assert "r2 goodput [" in text and "worst badput: wait" in text
    assert "fleet (3 ranks): goodput 60.0%" in text
    assert "vs compare run" in text
    assert "advise: evict/replace rank 2" in text
    assert "~2.0 rank-seconds" in text
    empty = gp.format_goodput({})
    assert "no goodput decomposition" in empty


def test_fleet_merge_carries_goodput_and_straggler_badput():
    from gtopkssgd_tpu.obs import fleet

    merged = fleet.merge([FIXTURE])
    rows = merged["goodput"]
    assert [r["rank"] for r in rows] == [0, 1, 2]
    assert [r["badput"] for r in rows] == ["select", "wasted", "wait"]
    assert all(r["src"] == "goodput" for r in rows)
    assert merged["goodput_fleet"]["goodput_frac"] == 0.6
    # the straggler table's badput column: rank 2 is the slowest rank
    # at every step, and its decomposition says WHERE the time goes
    stragglers = merged["stragglers"]
    assert stragglers and all(
        r["slowest_rank"] == 2 for r in stragglers)
    assert all(r["badput"] == "wait" for r in stragglers)
    assert all(abs(r["badput_frac"] - 0.48) < 1e-6 for r in stragglers)
    # the 2.5 s lag (> 2.0 x the 1.0 s cadence) goes persistent after
    # the monitor's warmup
    assert any(r["persistent"] for r in stragglers)


def test_report_goodput_cli_on_fixture(capsys):
    from gtopkssgd_tpu.obs import report

    assert report.main(["goodput", FIXTURE, "--advise"]) == 0
    out = capsys.readouterr().out
    assert "goodput: ranks=[0, 1, 2]" in out
    assert "advise: evict/replace rank 2" in out


def test_report_goodput_cli_empty_and_missing(tmp_path, capsys):
    from gtopkssgd_tpu.obs import report

    # a shard with a manifest but nothing to fold or synthesize -> 1
    bare = tmp_path / "bare"
    bare.mkdir()
    (bare / "metrics.jsonl").write_text(json.dumps(
        {"kind": "manifest", "time": 1.0, "rank": 0,
         "config_hash": "x"}) + "\n")
    assert report.main(["goodput", str(bare)]) == 1
    # unreadable target -> usage contract 2
    assert report.main(["goodput", str(tmp_path / "missing")]) == 2


# ------------------------------------------------- registry & exporter

def test_registry_summary_and_regress_pin_goodput_frac():
    from gtopkssgd_tpu.obs import registry

    shards = _fixture_shards()
    entry = registry.run_summary(shards[1])
    assert entry is not None
    assert entry["stats"]["goodput_frac"] == 0.6
    assert entry["stats"]["other_frac"] == 0.0
    rows = registry.history_rows([entry])
    assert rows and rows[0][registry.HISTORY_HEADER.index("goodput")] \
        == "0.6000"
    # the regress check: +-0.10 absolute on goodput_frac
    assert ("goodput_frac", 0.0, 0.10) in registry.REGRESS_CHECKS
    base = {"stats": {"goodput_frac": 0.9}}
    ok = {"stats": {"goodput_frac": 0.85}}
    bad = {"stats": {"goodput_frac": 0.7}}
    _, failures = registry.regress(ok, base)
    assert failures == 0
    _, failures = registry.regress(bad, base)
    assert failures == 1


def test_exporter_serves_goodput_gauges():
    from gtopkssgd_tpu.obs.exporter import MetricsExporter

    ex = MetricsExporter(port=0)
    ex.observe({"kind": "goodput", "rank": 1, "goodput_frac": 0.8,
                "wait_s": 0.25, "wall_s": 10.0, "source": "ledger"})
    body = ex.scrape()
    assert "# TYPE gtopk_goodput_goodput_frac gauge" in body
    assert 'gtopk_goodput_goodput_frac{rank="1",source="ledger"} 0.8' \
        in body
    assert "gtopk_goodput_wait_s" in body


def test_timeline_gains_badput_track():
    from gtopkssgd_tpu.obs.timeline import timeline_from_records

    records = [json.loads(line) for line in
               open(os.path.join(FIXTURE, "metrics.rank1.jsonl"))]
    doc = timeline_from_records(records)
    counters = [ev for ev in doc["traceEvents"] if ev.get("ph") == "C"]
    goodput_counters = [ev for ev in counters if ev["name"] == "goodput"]
    badput_counters = [ev for ev in counters if ev["name"] == "badput_s"]
    assert goodput_counters and badput_counters
    assert goodput_counters[-1]["args"]["goodput_frac"] == 0.6
    # the stacked badput counter carries every nonzero category
    assert badput_counters[-1]["args"]["wasted"] == 1.5


# -------------------------------------------------- goodput_collapse

def test_goodput_collapse_warmup_fire_and_rearm():
    m = AnomalyMonitor()
    assert m.observe_goodput(1, goodput_frac=0.8) == []
    assert m.observe_goodput(2, goodput_frac=0.8) == []      # warmup
    assert m.observe_goodput(3, goodput_frac=0.1) == []      # streak 1
    assert m.observe_goodput(4, goodput_frac=0.1) == []      # streak 2
    fired = m.observe_goodput(5, goodput_frac=0.1)           # streak 3
    assert [ev["rule"] for ev in fired] == ["goodput_collapse"]
    assert fired[0]["severity"] == "warn" and fired[0]["step"] == 5
    # re-armed: the very next collapsed record does not re-fire
    assert m.observe_goodput(6, goodput_frac=0.1) == []
    assert m.summary()["goodput_collapse"] == 1


def test_goodput_collapse_recovery_resets_streak():
    m = AnomalyMonitor()
    for step, frac in ((1, 0.8), (2, 0.8), (3, 0.1), (4, 0.1)):
        assert m.observe_goodput(step, goodput_frac=frac) == []
    # a recovered record resets the below-threshold streak
    assert m.observe_goodput(5, goodput_frac=0.8) == []
    assert m.observe_goodput(6, goodput_frac=0.1) == []
    assert m.observe_goodput(7, goodput_frac=0.1) == []
    assert m._gp_streak == 2                 # rebuilt from zero
    # non-finite fractions are ignored entirely
    assert m.observe_goodput(8, goodput_frac=None) == []
    assert m._gp_streak == 2


def test_goodput_collapse_honors_halt_on_warn():
    m = AnomalyMonitor(halt_on="warn")
    for step, frac in ((1, 0.8), (2, 0.8), (3, 0.1), (4, 0.1)):
        m.observe_goodput(step, goodput_frac=frac)
    with pytest.raises(AnomalyHalt) as ei:
        m.observe_goodput(5, goodput_frac=0.1)
    assert ei.value.event["rule"] == "goodput_collapse"


def test_emit_rejects_unregistered_rule():
    assert "goodput_collapse" in RULES
    m = AnomalyMonitor()
    with pytest.raises(ValueError, match="unregistered anomaly rule"):
        m._emit([{"rule": "not_a_rule", "severity": "warn", "step": 1}])


# ------------------------------------------------------------ doc drift

def test_readme_event_table_covers_registered_rules():
    """The README event table and obs.events.RULES must be the same
    set — a rule added without documentation (or a documented rule that
    no longer exists) fails tier-1, not review."""
    import re

    readme = open(os.path.join(REPO, "README.md"), encoding="utf-8").read()
    documented = set(re.findall(
        r"^\s*\|\s*`(\w+)`\s*\|\s*(?:warn|error)\s*\|", readme,
        flags=re.MULTILINE))
    assert documented == set(RULES), (
        f"README event table drifted from obs.events.RULES: "
        f"undocumented={sorted(set(RULES) - documented)} "
        f"stale={sorted(documented - set(RULES))}")


# --------------------------------------- abnormal-exit registry paths
# Satellite contract: every abnormal exit (43 stall / 44 halt / 45
# preempt) still lands the run's final goodput record AND its registry
# line, with the right final_status.

def _registry_entries(reg_dir):
    path = os.path.join(reg_dir, "runs.jsonl")
    return [json.loads(line) for line in open(path)]


def test_halt_exit_path_appends_registry_line(tmp_path):
    """Unclaimed NaN with --obs-halt-on error -> exit 44; the run's
    registry line says 'halted' and carries the goodput stats from the
    final ledger record __exit__ wrote on the way down."""
    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs import HALT_EXIT_CODE

    out = str(tmp_path / "run")
    reg = str(tmp_path / "registry")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "4", "--inject", "nan_grad@2",
        "--obs-halt-on", "error", "--registry", reg, "--out-dir", out])
    assert rc == HALT_EXIT_CODE
    finals = [r for r in _records(out) if r["kind"] == "goodput"
              and r.get("final")]
    assert len(finals) == 1
    assert gp.conservation_error(finals[0]) < 1e-6
    entries = _registry_entries(reg)
    assert len(entries) == 1
    assert entries[0]["stats"]["final_status"] == "halted"
    assert entries[0]["stats"]["goodput_frac"] == \
        finals[0]["goodput_frac"]


@pytest.mark.slow  # a second full dist_trainer run beyond the halt one
def test_preempt_exit_path_appends_registry_line(tmp_path):
    """Injected SIGTERM -> emergency save -> exit 45; same contract."""
    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.resilience import PREEMPT_EXIT_CODE

    out = str(tmp_path / "run")
    reg = str(tmp_path / "registry")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "4", "--inject", "preempt@2",
        "--registry", reg, "--out-dir", out])
    assert rc == PREEMPT_EXIT_CODE
    finals = [r for r in _records(out) if r["kind"] == "goodput"
              and r.get("final")]
    assert len(finals) == 1 and finals[0]["ckpt_s"] > 0
    entries = _registry_entries(reg)
    assert len(entries) == 1
    assert entries[0]["stats"]["final_status"] == "preempted"
    assert entries[0]["stats"]["goodput_frac"] == \
        finals[0]["goodput_frac"]


def test_stall_exit_path_appends_registry_line(tmp_path, monkeypatch):
    """The watchdog path cannot run __exit__ (os._exit skips it), so
    _on_stall itself must land the stall record, the final goodput
    record, the 'stalled' summary, and the registry line. Driven by
    calling the trainer's stall hook directly with the hard-exit
    half neutered — the real firing condition is pinned in test_obs."""
    import gtopkssgd_tpu.trainer as trainer_mod
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    exits = []
    monkeypatch.setattr(trainer_mod, "_default_on_stall",
                        lambda record: exits.append(record))
    out = str(tmp_path / "run")
    reg = str(tmp_path / "registry")
    cfg = TrainConfig(
        dnn="resnet20", batch_size=4, nworkers=2,
        compression="gtopk_layerwise", density=0.01, seed=42,
        log_interval=1, obs_interval=1, eval_batches=1, max_epochs=1,
        out_dir=out, registry=reg)
    with Trainer(cfg) as t:
        t.train(2)
        t._on_stall({"kind": "stall", "step": 2, "armed_phase":
                     "dispatch", "stalled_s": 12.5})
        assert len(exits) == 1               # would have os._exit(43)'d
    recs = _records(out)
    stalls = [r for r in recs if r["kind"] == "stall"]
    assert len(stalls) == 1 and stalls[0]["stalled_s"] == 12.5
    finals = [r for r in recs if r["kind"] == "goodput"
              and r.get("final")]
    assert len(finals) == 1 and finals[0]["step"] == 2
    assert gp.conservation_error(finals[0]) < 1e-6
    summaries = [r for r in recs if r["kind"] == "recovery"
                 and r.get("action") == "summary"]
    assert summaries and summaries[-1]["final_status"] == "stalled"
    # _on_stall closed metrics and appended its line; the context exit
    # above must not have crashed on the closed logger (its own append
    # re-reads the same stream, so every entry agrees on the status)
    entries = _registry_entries(reg)
    assert entries and all(
        e["stats"]["final_status"] == "stalled" for e in entries)
