"""measure_throughput stays runnable off-chip: the bench.py path compiles
and measures every mode the on-chip queue invokes, so a tracing/shape
regression surfaces in CI instead of burning a tunnel window (the tunnel
has died mid-round two rounds running — any bench.py breakage discovered
on-chip costs a scarce uptime window to diagnose).
"""

import pytest

from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput


@pytest.mark.parametrize("mode,density", [
    ("dense", 1.0),
    ("gtopk", 0.05),
    ("gtopk_layerwise", 0.05),
])
def test_measure_throughput_runs_every_bench_mode(mode, density):
    cfg = BenchConfig(dnn="resnet20", batch_size=4, min_seconds=0.05)
    stats = measure_throughput(cfg, mode, density)
    assert stats["sec_per_step"] > 0
    assert stats["images_per_sec_per_chip"] > 0
    assert stats["steps_timed"] >= 1


@pytest.mark.slow  # ~21 s: compiles two extra bench arms. The bench.py
# compile/measure path for every mode stays tier-1 via
# test_measure_throughput_runs_every_bench_mode; the dense x correction
# ValueError guard itself is pinned in test_momentum_correction.
def test_measure_throughput_momentum_correction_both_arms():
    """The corr queue stage measures BOTH arms from one cfg: the sparse
    arm gets the DGC recursion, the dense baseline arm must not trip
    gtopk_sgd's dense x correction ValueError."""
    cfg = BenchConfig(dnn="resnet20", batch_size=4, min_seconds=0.05,
                      momentum_correction=True)
    sparse = measure_throughput(cfg, "gtopk", 0.05)
    dense = measure_throughput(cfg, "dense", 1.0)
    assert sparse["images_per_sec_per_chip"] > 0
    assert dense["images_per_sec_per_chip"] > 0


def test_measure_throughput_s2d_resnet50_traces():
    """The s2d queue stage must at least trace+lower off-chip; full
    XLA:CPU compilation of ResNet-50 is minutes on this 1-core host, so
    stop at lowering — tracing is where a bad reshape/kwarg would die."""
    import jax
    import optax
    from jax import numpy as jnp

    from gtopkssgd_tpu.benchmark import _setup

    cfg = BenchConfig(dnn="resnet50", batch_size=2, s2d=True)
    model, spec, variables, tx, shape = _setup(cfg, "gtopk", 0.001)

    def step(params, opt_state, x, y):
        def loss_fn(p):
            out, _ = model.apply(
                {"params": p, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                out, y).mean()

        grads = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    params = variables["params"]
    opt0 = tx.init(params)
    x = jnp.zeros((2, 224, 224, 3))
    y = jnp.zeros((2,), jnp.int32)
    lowered = jax.jit(step).lower(params, opt0, x, y)
    assert "module" in lowered.as_text()[:200]  # produced StableHLO


def test_mfu_ablation_rung_measures_off_chip():
    """One rung of the MFU ablation ladder end-to-end on a tiny model:
    the measurement dict must carry the ladder's analysis fields and an
    XLA-counted FLOPs number (the chip run reuses exactly this path)."""
    from tests.conftest import load_benchmark_module

    _measure_rung = load_benchmark_module("mfu_ablation")._measure_rung

    row = _measure_rung("fwd_bwd", 4, 0.05, dnn="resnet20")
    assert row["rung"] == "fwd_bwd" and row["batch_size"] == 4
    assert row["flops_per_step"] and row["flops_per_step"] > 0
    assert row["sec_per_step"] > 0
    assert row["steps_timed"] >= 8

    full = _measure_rung("full", 4, 0.05, dnn="resnet20")
    # backward ~2x forward FLOPs; full adds only the elementwise update,
    # so full >= fwd_bwd — on real accelerators. XLA:CPU's cost_analysis
    # runs on the post-optimization module and reports the full rung at
    # ~0.90x fwd_bwd (the donated in-place update changes fusion and the
    # cost model's attribution), so on cpu we can only pin the counts to
    # the same ballpark; the strict ordering is asserted where the cost
    # model is trustworthy.
    import jax

    if jax.default_backend() == "cpu":
        assert full["flops_per_step"] >= 0.85 * row["flops_per_step"]
    else:
        assert full["flops_per_step"] >= row["flops_per_step"]
