"""graftlint (gtopkssgd_tpu.analysis) — rule fixtures + the tree gate.

Layout per rule: a positive fixture (the rule fires), a negative one
(it stays quiet), plus suppression and baseline behavior on shared
fixtures. The final tests are the enforcement gate: the shipped tree
must lint clean against the committed repo baseline, and each rule must
return nonzero through the real CLI on its positive fixture.

No jax import anywhere in this file — the analyzer's contract is that
linting never initializes a backend, and this suite would catch an
accidental jax dependency by simply becoming slow/backend-bound.
"""

import json
import os
import textwrap

from gtopkssgd_tpu.analysis import engine
from gtopkssgd_tpu.analysis.__main__ import main as lint_main
from gtopkssgd_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files: dict) -> str:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def _run(root, rule, files=None, baseline=None):
    return engine.run(
        [os.path.join(root, f) for f in files] if files else [root],
        rules=ALL_RULES, rule_names={rule}, baseline=baseline, root=root)


def _rules_of(result):
    return [(f.rule, f.line) for f in result.findings]


# ------------------------------------------------------------ host-sync


HOST_SYNC_POS = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        y = jnp.sum(x * x)
        return float(y)
"""

HOST_SYNC_NEG = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x, density):
        k = int(x.shape[0])        # static metadata: no sync
        d = float(density)          # parameter, not a jnp product
        return jnp.sum(x) * d, k

    def host_loop(x):
        return float(x)             # not jit-reachable at all
"""


def test_host_sync_positive(tmp_path):
    root = _tree(tmp_path, {"mod.py": HOST_SYNC_POS})
    res = _run(root, "host-sync-in-jit")
    assert [f.rule for f in res.findings] == ["host-sync-in-jit"]
    assert "float" in res.findings[0].message
    assert res.findings[0].symbol == "step"


def test_host_sync_item_and_device_get(tmp_path):
    root = _tree(tmp_path, {"mod.py": """\
        import jax

        @jax.jit
        def step(x):
            a = x.item()
            b = jax.device_get(x)
            return a, b
    """})
    res = _run(root, "host-sync-in-jit")
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 2
    assert any(".item()" in m for m in msgs)
    assert any("device_get" in m for m in msgs)


def test_host_sync_negative(tmp_path):
    root = _tree(tmp_path, {"mod.py": HOST_SYNC_NEG})
    res = _run(root, "host-sync-in-jit")
    assert res.findings == []


def test_host_sync_wrapper_call_site_entry(tmp_path):
    # jax.jit(f) / shard_map(step, ...) entries, not just decorators.
    root = _tree(tmp_path, {"mod.py": """\
        import jax
        import jax.numpy as jnp

        def build():
            def step(x):
                return float(jnp.sum(x))
            return jax.jit(step)
    """})
    res = _run(root, "host-sync-in-jit")
    assert [f.symbol for f in res.findings] == ["build.step"]


def test_host_sync_suppressed(tmp_path):
    root = _tree(tmp_path, {"mod.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            y = jnp.sum(x)
            # graftlint: disable=host-sync-in-jit
            return float(y)
    """})
    res = _run(root, "host-sync-in-jit")
    assert res.findings == [] and len(res.suppressed) == 1


def test_host_sync_baselined(tmp_path):
    root = _tree(tmp_path, {"mod.py": HOST_SYNC_POS})
    raw = _run(root, "host-sync-in-jit")
    baseline = {f.baseline_key: {"reason": "fixture"}
                for f in raw.findings}
    res = _run(root, "host-sync-in-jit", baseline=baseline)
    assert res.findings == [] and len(res.baselined) == 1
    assert res.stale_baseline == []


def test_baseline_key_survives_line_drift(tmp_path):
    root = _tree(tmp_path, {"mod.py": HOST_SYNC_POS})
    key = _run(root, "host-sync-in-jit").findings[0].baseline_key
    shifted = _tree(tmp_path / "v2",
                    {"mod.py": "# a new header comment\n"
                               + textwrap.dedent(HOST_SYNC_POS)})
    res = _run(shifted, "host-sync-in-jit",
               baseline={key: {"reason": "fixture"}})
    assert res.findings == [] and len(res.baselined) == 1


# ----------------------------------------------------------- metric-kind


METRICS_FIXTURE = """\
    KINDS = frozenset({"train", "event"})
"""


def test_metric_kind_unregistered_literal(tmp_path):
    # Regression for the deleted grep test
    # (test_every_logged_kind_literal_is_registered): a typo'd literal
    # kind at a .log( call site must be caught statically.
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            def f(m):
                m.log("tpyo_kind", step=1)
        """})
    res = _run(root, "metric-kind")
    assert [f.rule for f in res.findings] == ["metric-kind"]
    assert "tpyo_kind" in res.findings[0].message


def test_metric_kind_negative_literal_and_bound_name(tmp_path):
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            KIND = "event"

            def f(m):
                m.log("train", step=1)
                m.log(KIND, step=2)
        """})
    assert _run(root, "metric-kind").findings == []


def test_metric_kind_fstring_is_a_finding(tmp_path):
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            def f(m, i):
                m.log(f"train_{i}", step=1)
        """})
    res = _run(root, "metric-kind")
    assert len(res.findings) == 1
    assert "f-string" in res.findings[0].message


def test_metric_kind_ignores_numeric_and_logger_log(tmp_path):
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            import numpy as np
            import math

            def f(logger, x):
                np.log(x)
                math.log(x)
                logger.log(30, "a stdlib-logging message")
        """})
    assert _run(root, "metric-kind").findings == []


# ------------------------------------------------------------- exit-code


EXIT_FIXTURE = """\
    EXIT_OK = 0
    EXIT_WEDGED = 7
"""


def test_exit_code_unregistered_literal(tmp_path):
    root = _tree(tmp_path, {
        "pkg/exit_codes.py": EXIT_FIXTURE,
        "pkg/mod.py": """\
            import sys

            def f():
                sys.exit(8)
        """})
    res = _run(root, "exit-code")
    assert [f.rule for f in res.findings] == ["exit-code"]
    assert "8" in res.findings[0].message


def test_exit_code_registered_literals_pass(tmp_path):
    root = _tree(tmp_path, {
        "pkg/exit_codes.py": EXIT_FIXTURE,
        "pkg/mod.py": """\
            import os
            import sys

            def f(bad):
                if bad:
                    raise SystemExit(7)
                os._exit(0)
                sys.exit("a message is rc 1, not a literal code")
        """})
    assert _run(root, "exit-code").findings == []


def test_exit_code_collision_and_minted_constant(tmp_path):
    root = _tree(tmp_path, {
        "pkg/exit_codes.py": EXIT_FIXTURE + "    EXIT_CLASH = 7\n",
        "pkg/mod.py": "WEDGE_EXIT_CODE = 9\n"})
    res = _run(root, "exit-code")
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 2
    assert any("collision" in m for m in msgs)
    assert any("WEDGE_EXIT_CODE" in m for m in msgs)


# ------------------------------------------------------------ codec-wire


def test_codec_wire_raw_sparse_gather(tmp_path):
    root = _tree(tmp_path, {"pkg/parallel/coll.py": """\
        from jax import lax

        def bad(vals, idx, axis_name):
            av = lax.all_gather(vals, axis_name, tiled=True)
            ai = lax.all_gather(idx, axis_name, tiled=True)
            return av, ai
    """})
    res = _run(root, "codec-wire")
    assert [f.rule for f in res.findings] == ["codec-wire"] * 2
    assert all(f.symbol == "bad" for f in res.findings)


def test_codec_wire_encoded_and_dense_pass(tmp_path):
    root = _tree(tmp_path, {"pkg/parallel/coll.py": """\
        from jax import lax

        def good(vals, idx, axis_name, codec, n):
            wire = codec.encode(vals, idx, n=n)
            pwire = tuple(lax.ppermute(w, axis_name, [(0, 1)])
                          for w in wire)
            return codec.decode(pwire, k=2, n=n)

        def dense_ok(x, axis_name):
            return lax.psum(x, axis_name)
    """})
    assert _run(root, "codec-wire").findings == []


def test_codec_wire_all_to_all_and_nonleading_payload(tmp_path):
    # The balanced-schedule extension: all_to_all is a wire collective
    # too, and a sparse payload in ANY positional slot (not just the
    # leading one) must be codec-mediated.
    root = _tree(tmp_path, {"pkg/parallel/coll.py": """\
        from jax import lax

        def bad_a2a(vals, axis_name):
            return lax.all_to_all(vals, axis_name, 0, 0)

        def bad_tail(mask, vals, axis_name):
            return lax.ppermute(mask * vals, axis_name, [(0, 1)])

        def good_a2a(vals, idx, axis_name, codec, n):
            wire = codec.encode(vals, idx, n=n)
            swire = tuple(lax.all_to_all(w, axis_name, 0, 0)
                          for w in wire)
            return codec.decode(swire, k=2, n=n)
    """})
    res = _run(root, "codec-wire")
    assert sorted(f.symbol for f in res.findings) == [
        "bad_a2a", "bad_tail"]


def test_codec_wire_scoped_to_parallel(tmp_path):
    root = _tree(tmp_path, {"pkg/other.py": """\
        from jax import lax

        def elsewhere(vals, axis_name):
            return lax.all_gather(vals, axis_name, tiled=True)
    """})
    assert _run(root, "codec-wire").findings == []


# ---------------------------------------------------------- durable-event


def test_durable_event_requires_flush(tmp_path):
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            def f(m, extra):
                m.log("event", what="anomaly")
                m.log("event", flush=extra)
        """})
    res = _run(root, "durable-event")
    assert [f.rule for f in res.findings] == ["durable-event"] * 2


def test_durable_event_flush_true_passes(tmp_path):
    root = _tree(tmp_path, {
        "pkg/utils/metrics.py": METRICS_FIXTURE,
        "pkg/mod.py": """\
            def f(m):
                m.log("event", flush=True, what="anomaly")
                m.log("train", step=1)  # non-durable: flush optional
        """})
    assert _run(root, "durable-event").findings == []


# ------------------------------------------------------------ event-rule


EVENTS_FIXTURE = """\
    RULES = frozenset({"nan_loss", "goodput_collapse"})
"""


def test_event_rule_unregistered_names(tmp_path):
    # Both static emit-site shapes: the "rule" key of a record dict and
    # the first argument of a local fire(...) helper.
    root = _tree(tmp_path, {
        "pkg/obs/events.py": EVENTS_FIXTURE,
        "pkg/mod.py": """\
            def f(fire):
                ev = {"rule": "tpyo_rule", "severity": "warn"}
                fire("also_unregistered", step=1)
                return ev
        """})
    res = _run(root, "event-rule")
    assert [f.rule for f in res.findings] == ["event-rule"] * 2
    assert "tpyo_rule" in res.findings[0].message
    assert "also_unregistered" in res.findings[1].message


def test_event_rule_registered_and_dynamic_pass(tmp_path):
    root = _tree(tmp_path, {
        "pkg/obs/events.py": EVENTS_FIXTURE,
        "pkg/mod.py": """\
            def f(fire, name):
                ev = {"rule": "goodput_collapse", "severity": "warn"}
                fire("nan_loss", step=1)
                fire(name, step=2)           # dynamic: runtime _emit's job
                other = {"rule": name}       # non-constant value: ignored
                return ev, other
        """})
    assert _run(root, "event-rule").findings == []


# ------------------------------------------------------- syntax handling


def test_unparseable_file_is_its_own_finding(tmp_path):
    root = _tree(tmp_path, {"pkg/broken.py": "def f(:\n"})
    res = engine.run([root], rules=ALL_RULES, root=root)
    assert [f.rule for f in res.findings] == ["syntax"]


# ------------------------------------------------------------- the gate


def _positive_fixture_for(rule_name):
    return {
        "host-sync-in-jit": {"mod.py": HOST_SYNC_POS},
        "metric-kind": {
            "pkg/utils/metrics.py": METRICS_FIXTURE,
            "pkg/mod.py": 'def f(m):\n    m.log("nope", step=1)\n'},
        "exit-code": {
            "pkg/exit_codes.py": EXIT_FIXTURE,
            "pkg/mod.py": "import sys\nsys.exit(8)\n"},
        "codec-wire": {
            "pkg/parallel/coll.py":
                "from jax import lax\n\n"
                "def bad(vals, axis_name):\n"
                "    return lax.all_gather(vals, axis_name)\n"},
        "durable-event": {
            "pkg/utils/metrics.py": METRICS_FIXTURE,
            "pkg/mod.py": 'def f(m):\n    m.log("event", what="x")\n'},
        "event-rule": {
            "pkg/obs/events.py": EVENTS_FIXTURE,
            "pkg/mod.py":
                'def f(fire):\n    fire("nope_rule", step=1)\n'},
    }[rule_name]


def test_cli_nonzero_on_every_rule_fixture(tmp_path):
    for i, rule in enumerate(RULES_BY_NAME):
        root = _tree(tmp_path / f"fix{i}", _positive_fixture_for(rule))
        rc = lint_main([root, "--no-baseline", "--rule", rule])
        assert rc == 1, f"rule {rule} did not fire through the CLI"


def test_cli_rejects_unknown_rule_and_path(tmp_path):
    assert lint_main([str(tmp_path), "--rule", "no-such-rule"]) == 2
    assert lint_main([str(tmp_path / "missing")]) == 2


def test_shipped_tree_lints_clean():
    """The tier-1 enforcement gate: any non-baselined finding in the
    shipped package or benchmarks fails this test. Fix the finding,
    suppress it with a justification comment, or (last resort)
    grandfather it into graftlint_baseline.json with a reason."""
    rc = lint_main([
        os.path.join(REPO, "gtopkssgd_tpu"),
        os.path.join(REPO, "benchmarks"),
        "--baseline", os.path.join(REPO, "graftlint_baseline.json")])
    assert rc == 0, (
        "graftlint found non-baselined findings — run "
        "`python -m gtopkssgd_tpu.analysis gtopkssgd_tpu/ benchmarks/` "
        "for the report")


def test_committed_baseline_entries_have_reasons():
    baseline = engine.load_baseline(
        os.path.join(REPO, "graftlint_baseline.json"))
    for key, entry in baseline.items():
        reason = entry.get("reason", "")
        assert reason and "TODO" not in reason, (
            f"baseline entry {key} lacks a real justification")


def test_analysis_package_never_imports_jax():
    """Contract: linting must work with a dead accelerator tunnel and
    must not pay backend init. Import the analyzer in a clean
    subprocess and assert jax was never pulled in."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import gtopkssgd_tpu.analysis.rules\n"
        "import gtopkssgd_tpu.analysis.__main__\n"
        "assert 'jax' not in sys.modules, 'analysis imported jax'\n"
        "print('ok')\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_lint_gate_record_shape(tmp_path):
    """The gate-smoke lint record (benchmarks/obs_gate_smoke.py)
    carries the counts the committed obs gate baseline pins."""
    import importlib
    import sys

    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    try:
        smoke = importlib.import_module("obs_gate_smoke")
        rec = smoke.run_lint_smoke()
    finally:
        sys.path.pop(0)
    assert rec["non_baselined"] == 0
    assert rec["files_scanned"] > 50
    assert set(rec) == {"files_scanned", "non_baselined", "baselined",
                        "suppressed", "stale_baseline"}

    baseline = json.load(open(os.path.join(
        REPO, "benchmarks", "results", "obs_gate_baseline_cpu.json")))
    lint_checks = [c for c in baseline["checks"]
                   if c.get("kind") == "lint"]
    assert lint_checks == [{"kind": "lint", "field": "non_baselined",
                            "stat": "last", "expect": 0.0, "atol": 0.0}]
