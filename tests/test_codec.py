"""Wire codec (parallel/codec.py): packed-buffer roundtrips, byte
accounting, partner symmetry through the hypercube under quantization,
and the error-feedback fold/repair composition.

The reference shipped fp32 values + int32 indices over MPI; the codec
layer replaces that payload with block-scaled 8-bit values and
Elias-Fano bitpacked indices while preserving the merge oracle's
bitwise-agreement contract (both partners decode identical sets because
encode is deterministic). These tests pin exactly that contract — plus
the fp32 identity, so the historical byte formula stays the default.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.compression import TopKCompressor
from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    get_codec,
    gtopk_allreduce,
    hier_gtopk_allreduce,
    make_mesh,
    roundtrip_aligned,
    topk_allgather,
    tree_rounds,
)

K = 8
N = 300


def make_sets(rng, p, k=K, n=N, sentinels=0):
    vals = np.zeros((p, k), np.float32)
    idxs = np.full((p, k), n, np.int32)
    for d in range(p):
        kk = k - sentinels
        idxs[d, :kk] = rng.choice(n, size=kk, replace=False)
        vals[d, :kk] = rng.standard_normal(kk).astype(np.float32) * 5
    return vals, idxs


def run_collective(fn, mesh, vals, idxs):
    """shard_map a per-device (vals, idx) collective over the dp axis and
    return host arrays stacked [p, ...]."""
    body = jax.shard_map(
        lambda v, i: jax.tree.map(lambda x: x[None], fn(v[0], i[0])),
        mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"),
        check_rep=False)
    return jax.tree.map(np.asarray, jax.jit(body)(jnp.asarray(vals),
                                                  jnp.asarray(idxs)))


# ---------------------------------------------------------------------------
# Satellite: fp32-codec bytes pin the pre-codec hardcoded formula.


def test_fp32_codec_bytes_match_legacy_formula():
    """Regression: the default (fp32) codec must reproduce the old
    hardcoded 4-byte-values + 4-byte-indices accounting exactly, for
    every mode comm_bytes_per_step models."""
    n, k = 272_474, 2_725
    assert get_codec("fp32").wire_set_bytes(k, n) == 8 * k
    # gtopk: 8k per round x tree rounds (pow2 and ragged)
    assert comm_bytes_per_step("gtopk", n, k, 32) == 8 * k * 5
    assert comm_bytes_per_step("gtopk", n, k, 6) == 8 * k * 4
    assert comm_bytes_per_step("gtopk", n, k, 12) == 8 * k * 5
    # hier: dense 4n on ICI + 8k per cross-slice round
    assert comm_bytes_per_step("gtopk_hier", n, k, 12, ici_size=4) == (
        4 * n + 8 * k * tree_rounds(3))
    # allgather union: every device pulls p sets
    assert comm_bytes_per_step("allgather", n, k, 32) == 8 * k * 32
    # dense is codec-independent
    assert comm_bytes_per_step("dense", n, k, 32) == 4 * n
    assert comm_bytes_per_step("dense", n, k, 32, codec="int8") == 4 * n


def test_quantized_codec_bytes_hit_reduction_targets():
    """The acceptance numbers: at ResNet-20 scale the int8 wire is
    >= 3x smaller than fp32 at rho=0.001 and under the 0.30 gate bound
    at rho=0.01 (Elias-Fano index bits shrink as k grows)."""
    n = 272_474
    for name in ("int8", "fp8"):
        c = get_codec(name)
        k1 = max(1, -(-n // 1000))   # ceil(0.001 * n)
        k2 = max(1, -(-n // 100))    # ceil(0.01 * n)
        assert c.wire_set_bytes(k1, n) * 3 <= 8 * k1
        assert c.wire_set_bytes(k2, n) <= 0.30 * 8 * k2
        # comm model composes the same set bytes per round
        assert comm_bytes_per_step("gtopk", n, k1, 8, codec=name) == (
            c.wire_set_bytes(k1, n) * 3)


def test_get_codec_grammar():
    assert get_codec("fp32") is get_codec("fp32")
    assert get_codec("int8").block == 64
    assert get_codec("int8:128").block == 128
    assert get_codec("fp8:32").name == "fp8:32"
    c = get_codec("int8")
    assert get_codec(c) is c  # instance passthrough
    with pytest.raises(ValueError):
        get_codec("int4")
    with pytest.raises(ValueError):
        get_codec("int8:7")  # block must be a multiple of 4


# ---------------------------------------------------------------------------
# Roundtrip: indices lossless, values bounded by the block quant step.


@pytest.mark.parametrize("name", ["int8", "fp8", "int8:32", "fp8:128"])
@pytest.mark.parametrize("sentinels", [0, 3])
def test_roundtrip_lossless_indices_bounded_values(rng, name, sentinels):
    c = get_codec(name)
    k, n = 13, 1_000
    idx = np.full(k, n, np.int32)
    vals = np.zeros(k, np.float32)
    kk = k - sentinels
    idx[:kk] = rng.choice(n, size=kk, replace=False)
    vals[:kk] = rng.standard_normal(kk).astype(np.float32) * 10
    perm = rng.permutation(k)
    idx, vals = idx[perm], vals[perm]

    dv, di = jax.jit(
        lambda v, i: c.decode(c.encode(v, i, n=n), k=k, n=n)
    )(jnp.asarray(vals), jnp.asarray(idx))
    dv, di = np.asarray(dv), np.asarray(di)

    # Index coding is exactly lossless (as a sorted multiset).
    np.testing.assert_array_equal(np.sort(idx), np.sort(di))
    # Values come back index-sorted; error bounded by ~1 quant step of
    # the block max (int8) or the e4m3 relative precision (fp8).
    order = np.argsort(idx, kind="stable")
    sv = vals[order]
    qmax = 127.0 if name.startswith("int8") else 448.0
    bound = np.abs(sv).max() / qmax * 2.2 + 0.07 * np.abs(sv).max()
    assert np.abs(dv - sv).max() <= bound
    # Wire buffer size matches the byte accounting exactly.
    (wire,) = c.encode(jnp.asarray(vals), jnp.asarray(idx), n=n)
    assert wire.size * 4 == c.wire_set_bytes(k, n)
    # roundtrip_aligned returns the SAME dequantized values in the
    # ORIGINAL slot order (the optimizer's residual-fold contract).
    ra = np.asarray(roundtrip_aligned(
        c, jnp.asarray(vals), jnp.asarray(idx), n=n))
    np.testing.assert_array_equal(ra[order], dv)


def test_fp32_roundtrip_is_identity(rng):
    c = get_codec("fp32")
    vals = rng.standard_normal(K).astype(np.float32)
    idx = rng.choice(N, size=K, replace=False).astype(np.int32)
    dv, di = c.decode(c.encode(jnp.asarray(vals), jnp.asarray(idx), n=N),
                      k=K, n=N)
    np.testing.assert_array_equal(np.asarray(dv), vals)
    np.testing.assert_array_equal(np.asarray(di), idx)
    ra = roundtrip_aligned(c, jnp.asarray(vals), jnp.asarray(idx), n=N)
    np.testing.assert_array_equal(np.asarray(ra), vals)


# ---------------------------------------------------------------------------
# Partner symmetry through the tree: every rank decodes the bit-identical
# merged set, including non-pow2 masked folds and the hier ICI/DCN split.


@pytest.mark.parametrize("p", [3, 5, 6, 7])
@pytest.mark.parametrize("codec", ["int8", "fp8"])
def test_partner_symmetry_nonpow2(rng, p, codec):
    vals, idxs = make_sets(rng, p)
    mesh = make_mesh(p)
    gv, gi = run_collective(
        functools.partial(gtopk_allreduce, k=K, n=N, axis_name="dp",
                          axis_size=p, codec=codec),
        mesh, vals, idxs)
    for r in range(1, p):
        np.testing.assert_array_equal(gv[0], gv[r])
        np.testing.assert_array_equal(gi[0], gi[r])
    # Semantics survive quantization: the scattered result is close to
    # the fp32-wire result of the same inputs.
    fv, fi = run_collective(
        functools.partial(gtopk_allreduce, k=K, n=N, axis_name="dp",
                          axis_size=p, codec="fp32"),
        mesh, vals, idxs)
    got = np.zeros(N + 1, np.float32)
    np.add.at(got, gi[0], gv[0])
    want = np.zeros(N + 1, np.float32)
    np.add.at(want, fi[0], fv[0])
    # same support up to quantization-induced tau ties; compare values
    # only where both selected
    both = (got[:N] != 0) & (want[:N] != 0)
    assert both.sum() >= K - 2
    np.testing.assert_allclose(got[:N][both], want[:N][both],
                               rtol=0.15, atol=0.2)


@pytest.mark.parametrize("codec", ["int8", "fp8"])
@pytest.mark.parametrize("p,ici", [(8, 4), (6, 2)])
def test_hier_split_partner_symmetry(rng, codec, p, ici):
    """ICI/DCN split: slice-identical inputs (the ici_dense_psum
    precondition), quantized cross-slice tree — all p ranks must end
    bit-identical, pow2 and ragged slice counts alike."""
    n_slices = p // ici
    sv, si = make_sets(rng, n_slices)
    vals = np.repeat(sv, ici, axis=0)
    idxs = np.repeat(si, ici, axis=0)
    mesh = make_mesh(p)
    gv, gi = run_collective(
        functools.partial(hier_gtopk_allreduce, k=K, n=N, axis_name="dp",
                          axis_size=p, ici_size=ici, codec=codec),
        mesh, vals, idxs)
    for r in range(1, p):
        np.testing.assert_array_equal(gv[0], gv[r])
        np.testing.assert_array_equal(gi[0], gi[r])


@pytest.mark.parametrize("codec", ["fp32", "int8"])
def test_allgather_union_bit_identical(rng, codec):
    p = 8
    vals, idxs = make_sets(rng, p)
    mesh = make_mesh(p)
    dense = run_collective(
        functools.partial(topk_allgather, k=K, n=N, axis_name="dp",
                          axis_size=p, codec=codec),
        mesh, vals, idxs)
    for r in range(1, p):
        np.testing.assert_array_equal(dense[0], dense[r])


def test_fp32_codec_reproduces_precodec_tree(rng):
    """The fp32 identity must leave the tree bit-for-bit unchanged:
    explicit codec="fp32" equals the default-argument path on ragged p."""
    p = 6
    vals, idxs = make_sets(rng, p)
    mesh = make_mesh(p)
    a = run_collective(
        functools.partial(gtopk_allreduce, k=K, n=N, axis_name="dp",
                          axis_size=p),
        mesh, vals, idxs)
    b = run_collective(
        functools.partial(gtopk_allreduce, k=K, n=N, axis_name="dp",
                          axis_size=p, codec="fp32"),
        mesh, vals, idxs)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


# ---------------------------------------------------------------------------
# Error accounting: fold + repair compose to exact restoration.


def test_fold_wire_error_then_repair_restores_exact_value(rng):
    """A locally-picked, globally-rejected coordinate must find its FULL
    original value in the residual: the wire fold banks (vals - vq)
    before the collective, the repair banks vq after — their sum is the
    pre-quantization selection exactly (no codec error leaks)."""
    n, k = 64, 6
    comp = TopKCompressor(density=k / n)
    c = get_codec("int8:4")
    vals = (rng.standard_normal(k).astype(np.float32) * 3).astype(np.float32)
    idx = rng.choice(n, size=k, replace=False).astype(np.int32)
    vq = np.asarray(roundtrip_aligned(c, jnp.asarray(vals),
                                      jnp.asarray(idx), n=n))
    residual = jnp.zeros(n, jnp.float32)
    residual = comp.fold_wire_error(residual, jnp.asarray(idx),
                                    jnp.asarray(vals - vq))
    # Global set rejects the first three local picks.
    gidx = np.full(k, n, np.int32)
    gidx[:k - 3] = idx[3:]
    repaired = comp.repair(residual, jnp.asarray(vq), jnp.asarray(idx),
                           jnp.asarray(gidx))
    repaired = np.asarray(repaired)
    np.testing.assert_allclose(repaired[idx[:3]], vals[:3], rtol=1e-6)
    # Delivered picks keep only the (small) folded quant error.
    qstep = np.abs(vals).max() / 127.0
    assert np.abs(repaired[idx[3:]]).max() <= qstep * 1.1


# ---------------------------------------------------------------------------
# Satellite: convergence A/B — int8 wire tracks fp32 within tolerance.


def test_convergence_ab_int8_vs_fp32_wire(tmp_path, monkeypatch):
    """convergence_run.py arm suffix "+int8wire" trains, labels the arm,
    and lands within tolerance of the fp32 wire at identical seed/steps
    (codec error is absorbed by the error-feedback residual)."""
    import json
    import sys

    from tests.conftest import load_benchmark_module

    mod = load_benchmark_module("convergence_run")
    out = tmp_path / "conv_codec.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "4",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--density", "0.01",
        "--modes", "gtopk,gtopk+int8wire",
        "--out", str(out),
    ])
    mod.main()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    summary = {s["mode"]: s for s in rows[-1]["modes"]}
    assert set(summary) == {"gtopk", "gtopk+int8wire"}
    fp32_loss = summary["gtopk"]["final_loss"]
    int8_loss = summary["gtopk+int8wire"]["final_loss"]
    assert abs(int8_loss - fp32_loss) <= 0.15, (fp32_loss, int8_loss)
