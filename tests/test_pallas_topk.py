"""Threshold top-k + the Pallas counting kernel (interpret mode on CPU).

Exactness oracle: numpy argsort. The threshold method must match exactly on
continuous-valued inputs; adversarial ties are checked by selected-mass
equivalence (tie-breaking may differ, total selected magnitude may not).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.ops import threshold_topk_abs, topk_abs
from gtopkssgd_tpu.ops.pallas_topk import (
    NUM_THRESHOLDS,
    multi_threshold_count,
    pallas_topk_abs,
)


def np_topk_set(x, k):
    idx = np.argsort(-np.abs(x), kind="stable")[:k]
    return set(idx.tolist())


@pytest.mark.parametrize("n,k", [(1000, 10), (65536, 64), (100_000, 1000),
                                 (1 << 20, 100)])
def test_threshold_topk_exact_on_continuous(rng, n, k):
    x = rng.standard_normal(n).astype(np.float32)
    vals, idx = jax.jit(lambda a: threshold_topk_abs(a, k))(jnp.asarray(x))
    got = set(np.asarray(idx).tolist())
    want = np_topk_set(x, k)
    assert got == want
    np.testing.assert_allclose(
        np.sort(np.asarray(vals)), np.sort(x[list(want)]), rtol=1e-6
    )


def test_threshold_topk_heavy_tail(rng):
    # gradient-like: a few huge entries, many tiny
    n, k = 200_000, 200
    x = (rng.standard_normal(n) ** 5).astype(np.float32)
    vals, idx = threshold_topk_abs(jnp.asarray(x), k)
    assert set(np.asarray(idx).tolist()) == np_topk_set(x, k)


def test_threshold_topk_ties_mass_equivalent(rng):
    # adversarial: the boundary value repeated many times — tie-breaking may
    # differ from argsort but the selected mass must match.
    n, k = 10_000, 100
    x = np.zeros(n, np.float32)
    x[:50] = 10.0          # definite members
    x[50:5000] = 1.0       # 4950-way tie across the boundary
    vals, idx = threshold_topk_abs(jnp.asarray(x), k)
    v = np.asarray(vals)
    assert (v == 10.0).sum() == 50
    assert (v == 1.0).sum() == 50
    assert len(set(np.asarray(idx).tolist())) == k


def test_multi_threshold_count_kernel_interpret(rng):
    mag = np.abs(rng.standard_normal(70_000)).astype(np.float32)
    thr = np.quantile(mag, [0.999, 0.99, 0.9, 0.7, 0.5, 0.3, 0.1, 0.01]
                      ).astype(np.float32)
    counts = multi_threshold_count(
        jnp.asarray(mag), jnp.asarray(thr), interpret=True
    )
    want = [(mag >= t).sum() for t in thr]
    np.testing.assert_array_equal(np.asarray(counts), want)
    assert counts.shape == (NUM_THRESHOLDS,)


def test_pallas_topk_interpret_matches_exact(rng):
    n, k = 300_000, 300
    x = rng.standard_normal(n).astype(np.float32)
    vals, idx = pallas_topk_abs(jnp.asarray(x), k, interpret=True)
    ev, ei = topk_abs(jnp.asarray(x), k)
    assert set(np.asarray(idx).tolist()) == set(np.asarray(ei).tolist())


def test_threshold_topk_all_zero():
    vals, idx = threshold_topk_abs(jnp.zeros(5000), 8)
    assert np.all(np.asarray(vals) == 0.0)
