"""Threshold top-k + the Pallas counting kernel (interpret mode on CPU).

Exactness oracle: numpy argsort. The threshold method must match exactly on
continuous-valued inputs; adversarial ties are checked by selected-mass
equivalence (tie-breaking may differ, total selected magnitude may not).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.ops import (
    bucketize_counts,
    threshold_topk_abs,
    topk_abs,
    twostage_topk_abs,
)
from gtopkssgd_tpu.ops.pallas_topk import (
    NUM_THRESHOLDS,
    fused_multi_threshold_count,
    fused_stage1_candidates,
    multi_threshold_count,
    pallas_topk_abs,
)


def np_topk_set(x, k):
    idx = np.argsort(-np.abs(x), kind="stable")[:k]
    return set(idx.tolist())


@pytest.mark.parametrize("n,k", [(1000, 10), (65536, 64), (100_000, 1000),
                                 (1 << 20, 100)])
def test_threshold_topk_exact_on_continuous(rng, n, k):
    x = rng.standard_normal(n).astype(np.float32)
    vals, idx = jax.jit(lambda a: threshold_topk_abs(a, k))(jnp.asarray(x))
    got = set(np.asarray(idx).tolist())
    want = np_topk_set(x, k)
    assert got == want
    np.testing.assert_allclose(
        np.sort(np.asarray(vals)), np.sort(x[list(want)]), rtol=1e-6
    )


def test_threshold_topk_heavy_tail(rng):
    # gradient-like: a few huge entries, many tiny
    n, k = 200_000, 200
    x = (rng.standard_normal(n) ** 5).astype(np.float32)
    vals, idx = threshold_topk_abs(jnp.asarray(x), k)
    assert set(np.asarray(idx).tolist()) == np_topk_set(x, k)


def test_threshold_topk_ties_mass_equivalent(rng):
    # adversarial: the boundary value repeated many times — tie-breaking may
    # differ from argsort but the selected mass must match.
    n, k = 10_000, 100
    x = np.zeros(n, np.float32)
    x[:50] = 10.0          # definite members
    x[50:5000] = 1.0       # 4950-way tie across the boundary
    vals, idx = threshold_topk_abs(jnp.asarray(x), k)
    v = np.asarray(vals)
    assert (v == 10.0).sum() == 50
    assert (v == 1.0).sum() == 50
    assert len(set(np.asarray(idx).tolist())) == k


def test_multi_threshold_count_kernel_interpret(rng):
    mag = np.abs(rng.standard_normal(70_000)).astype(np.float32)
    thr = np.quantile(mag, [0.999, 0.99, 0.9, 0.7, 0.5, 0.3, 0.1, 0.01]
                      ).astype(np.float32)
    counts = multi_threshold_count(
        jnp.asarray(mag), jnp.asarray(thr), interpret=True
    )
    want = [(mag >= t).sum() for t in thr]
    np.testing.assert_array_equal(np.asarray(counts), want)
    assert counts.shape == (NUM_THRESHOLDS,)


def test_pallas_topk_interpret_matches_exact(rng):
    n, k = 300_000, 300
    x = rng.standard_normal(n).astype(np.float32)
    vals, idx = pallas_topk_abs(jnp.asarray(x), k, interpret=True)
    ev, ei = topk_abs(jnp.asarray(x), k)
    assert set(np.asarray(idx).tolist()) == set(np.asarray(ei).tolist())


def test_threshold_topk_all_zero():
    vals, idx = threshold_topk_abs(jnp.zeros(5000), 8)
    assert np.all(np.asarray(vals) == 0.0)


# ------------------- fused two-stage stage-1 kernel family (ISSUE 6)
#
# Same interpret-mode-on-CPU discipline as the counting kernel above.
# Exactness oracle stays numpy; the two-stage select is approximate by
# design, so recall is asserted against its documented floor.


def test_bucketize_counts_matches_naive(rng):
    """The single-pass XLA count_fn (searchsorted + histogram + suffix
    sum) must agree with the literal 8-reduction it replaced, including
    unsorted thresholds and exact-boundary magnitudes."""
    mag = np.abs(rng.standard_normal(50_000)).astype(np.float32)
    mag[:100] = 1.25  # exact hits on a threshold: >= must include them
    thr = np.array([1.25, 0.01, 2.0, 0.5, 3.0, 0.9, 0.1, 1.7], np.float32)
    counts = jax.jit(bucketize_counts)(jnp.asarray(mag), jnp.asarray(thr))
    np.testing.assert_array_equal(
        np.asarray(counts), [(mag >= t).sum() for t in thr])


def test_bucketize_counts_single_logical_pass():
    """The committed one-pass claim, asserted from the compiled HLO: the
    largest op in the bucketize formulation is ~1xN while the vmapped
    8-reduction it replaced materializes an 8xN intermediate."""
    from benchmarks.topk_bench import one_pass_evidence

    ev = one_pass_evidence(70_000)
    assert ev["single_pass"]
    assert ev["bucketize_max_op_elems"] <= 2 * 70_000
    assert ev["vmap8_max_op_elems"] >= 8 * 70_000


def test_fused_count_with_residual_matches_reference(rng):
    """fused_multi_threshold_count folds acc = grad + residual into the
    counting pass; counts must match numpy's counts over |grad+residual|
    on a non-multiple-of-block n (padding must not count)."""
    n = 300_001
    g = rng.standard_normal(n).astype(np.float32)
    r = rng.standard_normal(n).astype(np.float32)
    acc = np.abs(g + r)
    thr = np.quantile(acc, [0.999, 0.99, 0.9, 0.7, 0.5, 0.3, 0.1, 0.01]
                      ).astype(np.float32)
    counts = fused_multi_threshold_count(
        jnp.asarray(g), jnp.asarray(thr), jnp.asarray(r), interpret=True)
    np.testing.assert_array_equal(
        np.asarray(counts), [(acc >= t).sum() for t in thr])


def test_fused_stage1_candidates_structure(rng):
    """One launch yields per-bucket argmax candidates AND the 8 counts.
    Candidate values must be read from acc = grad + residual at the
    candidate's own index; padding buckets are marked idx >= n, value 0;
    counts match the same pass's reference."""
    n = 300_001  # forces a ragged second block + padded tail
    g = rng.standard_normal(n).astype(np.float32)
    r = rng.standard_normal(n).astype(np.float32)
    acc = g + r
    thr = np.quantile(np.abs(acc), [0.999, 0.99, 0.9, 0.7, 0.5, 0.3, 0.1,
                                    0.01]).astype(np.float32)
    cand_val, cand_idx, counts = fused_stage1_candidates(
        jnp.asarray(g), thresholds=jnp.asarray(thr),
        residual=jnp.asarray(r), groups=8, interpret=True)
    cv, ci = np.asarray(cand_val), np.asarray(cand_idx)
    real = ci < n
    assert real.any() and (~real).any()  # both populations present
    np.testing.assert_allclose(cv[real], acc[ci[real]], rtol=1e-6)
    np.testing.assert_array_equal(cv[~real], 0.0)
    np.testing.assert_array_equal(
        np.asarray(counts), [(np.abs(acc) >= t).sum() for t in thr])


def test_twostage_kernel_recall_floor(rng):
    """Interpret-mode fused kernel end to end (stage 1 + exact reselect)
    on a gradient-scale accumulator: recall vs exact top-k must clear
    the 0.95 audit floor (expected ~1 - k/(2*oversample*k) ~= 0.97)."""
    n, k = 300_000, 300
    g = rng.standard_normal(n).astype(np.float32)
    r = rng.standard_normal(n).astype(np.float32)
    vals, idx = twostage_topk_abs(
        jnp.asarray(g), k, residual=jnp.asarray(r),
        use_pallas=True, interpret=True)
    got = set(np.asarray(idx).tolist())
    want = np_topk_set(g + r, k)
    recall = len(got & want) / k
    assert recall >= 0.95, recall
    # returned values are read from acc at the returned indices
    acc = g + r
    np.testing.assert_allclose(
        np.asarray(vals), acc[np.asarray(idx)], rtol=1e-6)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_twostage_boundary_ties_mass_equivalent(use_pallas):
    """Boundary-tie discipline matches the threshold kernel's: candidate
    sets may break ties differently from argsort, but selected mass may
    not change (50 definite members + a tie crossing the boundary)."""
    n, k = 10_000, 100
    x = np.zeros(n, np.float32)
    x[:50] = 10.0
    x[50:5000] = 1.0
    vals, idx = twostage_topk_abs(
        jnp.asarray(x), k, use_pallas=use_pallas,
        interpret=use_pallas or None)
    v = np.asarray(vals)
    assert (v == 10.0).sum() == 50
    assert (v == 1.0).sum() == 50
    assert len(set(np.asarray(idx).tolist())) == k


@pytest.mark.parametrize("use_pallas", [False, True])
def test_twostage_k_exceeds_n_degenerate(use_pallas):
    """k > n: every element selected, slots padded with (idx=n, val=0) —
    the sentinel convention every sparse consumer relies on."""
    x = jnp.asarray([1.0, -2.0, 3.0])
    vals, idx = twostage_topk_abs(
        x, 5, use_pallas=use_pallas, interpret=use_pallas or None)
    np.testing.assert_array_equal(np.asarray(idx), [2, 1, 0, 3, 3])
    np.testing.assert_array_equal(np.asarray(vals), [3.0, -2.0, 1.0, 0, 0])
