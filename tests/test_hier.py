"""Hierarchical ICI-dense / DCN-gtopk mode vs numpy oracles, 8-way.

The hierarchical two-level reduction is a TPU-idiom EXTENSION, not
reference parity (SURVEY.md §5 names it as the natural design option for
pod-scale runs: dense psum inside an ICI slice where bandwidth is cheap,
gTop-k across slices where the DCN hop makes sparsity pay). Semantics
contract tested here: `gtopk_hier` over P devices in slices of size S is
EXACTLY `gtopk` over the P/S slice-sum "super workers".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    hier_gtopk_allreduce,
    ici_dense_psum,
    make_mesh,
)
from tests.test_collectives import make_local_sets, np_gtopk, np_topk

PDEV = 8
K = 8
N = 300


def _run_hier(vals, idxs, *, p, k, n, ici):
    def body(v, i):
        gv, gi = hier_gtopk_allreduce(
            v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p, ici_size=ici
        )
        return gv[None], gi[None]

    mesh = make_mesh(p)
    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    return np.asarray(gv), np.asarray(gi)


def _dense_of(vals, idxs, n):
    out = np.zeros(n + 1, np.float32)
    np.add.at(out, idxs, vals)
    return out[:n]


def test_ici_dense_psum_slice_sums(rng):
    x = rng.standard_normal((PDEV, 17)).astype(np.float32)

    def body(v):
        return ici_dense_psum(v, axis_name="dp", axis_size=PDEV, ici_size=2)

    mesh = make_mesh(PDEV)
    out = np.asarray(jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(jnp.asarray(x)))
    for s in range(PDEV // 2):
        want = x[2 * s] + x[2 * s + 1]
        np.testing.assert_allclose(out[2 * s], want, rtol=1e-6)
        np.testing.assert_allclose(out[2 * s + 1], want, rtol=1e-6)


@pytest.mark.parametrize("p,ici", [(8, 4), (6, 2), (6, 3), (5, 5)])
def test_ici_dense_psum_bitwise_identical_within_slice(rng, p, ici):
    """Determinism contract: slice members must hold the BITWISE-identical
    sum (top-k is discontinuous; a 1-ulp difference would let devices of
    one slice compress different index sets and silently diverge). Covers
    the power-of-two hypercube and the non-pow2 fold-in path."""
    x = rng.standard_normal((p, 33)).astype(np.float32)

    def body(v):
        return ici_dense_psum(v, axis_name="dp", axis_size=p, ici_size=ici)

    mesh = make_mesh(p)
    out = np.asarray(jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(jnp.asarray(x)))
    for s in range(p // ici):
        grp = slice(s * ici, (s + 1) * ici)
        np.testing.assert_allclose(
            out[grp][0], x[grp].astype(np.float64).sum(0), rtol=1e-5,
            atol=1e-6,
        )
        for j in range(1, ici):
            np.testing.assert_array_equal(out[s * ici], out[s * ici + j])


@pytest.mark.parametrize("ici", [1, 2, 4])
def test_hier_tree_matches_slice_level_oracle(rng, ici):
    """With within-slice-identical inputs (the optimizer guarantees this via
    ici_dense_psum before compression), the cross-slice tree must equal the
    plain recursive-doubling oracle over the n_slices distinct sets."""
    n_slices = PDEV // ici
    svals, sidxs = make_local_sets(rng, p=n_slices, k=K, n=N)
    # replicate each slice's set to all of its devices
    vals = np.repeat(svals, ici, axis=0)
    idxs = np.repeat(sidxs, ici, axis=0)

    gv, gi = _run_hier(vals, idxs, p=PDEV, k=K, n=N, ici=ici)

    # identical on every device (including across slices)
    for d in range(1, PDEV):
        np.testing.assert_array_equal(gi[0], gi[d])
        np.testing.assert_allclose(gv[0], gv[d], rtol=1e-6)

    if n_slices == 1:
        np.testing.assert_array_equal(gi[0], sidxs[0])
        np.testing.assert_allclose(gv[0], svals[0], rtol=1e-6)
        return
    ov, oi = np_gtopk(list(svals), list(sidxs), K, N)
    np.testing.assert_allclose(
        _dense_of(gv[0], gi[0], N), _dense_of(ov[0], oi[0], N),
        rtol=1e-5, atol=1e-6,
    )


def test_hier_non_pow2_slice_count_masked_tree(rng):
    """p=6, ici=2 -> 3 slices: the ragged slice count runs the same masked
    tree as the flat mode (was a grouped-allgather exact reselect before
    round 5) — oracle is the fold/hypercube/unfold numpy simulator over
    the slice sets, and every device (both members of all 3 slices) must
    agree bitwise."""
    from tests.test_collectives import np_gtopk_ragged

    p, ici, k, n = 6, 2, 5, 100
    n_slices = p // ici
    svals, sidxs = make_local_sets(rng, p=n_slices, k=k, n=n)
    vals = np.repeat(svals, ici, axis=0)
    idxs = np.repeat(sidxs, ici, axis=0)

    gv, gi = _run_hier(vals, idxs, p=p, k=k, n=n, ici=ici)
    for d in range(1, p):
        np.testing.assert_array_equal(gi[0], gi[d])
        np.testing.assert_array_equal(gv[0], gv[d])
    ov, oi = np_gtopk_ragged(list(svals), list(sidxs), k, n)
    np.testing.assert_allclose(
        _dense_of(gv[0], gi[0], n), _dense_of(ov[0], oi[0], n),
        rtol=1e-5, atol=1e-6,
    )


def test_optimizer_hier_equals_gtopk_over_slice_sums(rng):
    """End-to-end contract: gtopk_hier on 8 devices (ici=2) produces the
    same global sparse set and per-slice residuals as plain gtopk on 4
    devices whose local gradients are the slice sums. Updates differ only
    by the 1/P averaging factor (1/8 vs 1/4), which we scale out."""
    from gtopkssgd_tpu.optimizer import gtopk_sgd

    n_param = 64
    density = 0.125  # k = 8
    grads8 = rng.standard_normal((PDEV, n_param)).astype(np.float32)
    grads4 = np.stack([
        grads8[2 * s] + grads8[2 * s + 1] for s in range(4)
    ])

    def run(mode, p, grads, ici=1):
        tx = gtopk_sgd(
            1.0, momentum=0.0, weight_decay=0.0, compression=mode,
            density=density, axis_name="dp", hier_ici_size=ici,
        )
        params = jnp.zeros((n_param,))
        state0 = tx.init(params)
        res0 = jnp.zeros((p,) + state0.residual.shape)

        def body(g, res):
            st = state0._replace(residual=res[0])
            upd, st2 = tx.update(g[0], st, params)
            return upd[None], st2.residual[None]

        mesh = make_mesh(p)
        upd, res = jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P("dp"), P("dp")),
                out_specs=(P("dp"), P("dp")), check_vma=False,
            )
        )(jnp.asarray(grads), res0)
        return np.asarray(upd), np.asarray(res)

    upd_h, res_h = run("gtopk_hier", PDEV, grads8, ici=2)
    upd_p, res_p = run("gtopk", 4, grads4)

    # updates: same sparse set, averaged over 8 vs 4 contributions
    for d in range(PDEV):
        np.testing.assert_allclose(
            upd_h[d] * 8.0, upd_p[d // 2] * 4.0, rtol=1e-5, atol=1e-6
        )
    # residuals: per-slice, equal to the 4-way run's per-device residuals
    for s in range(4):
        np.testing.assert_allclose(res_h[2 * s], res_h[2 * s + 1], rtol=1e-6)
        np.testing.assert_allclose(res_h[2 * s], res_p[s], rtol=1e-5,
                                   atol=1e-6)


def test_hier_rejects_bad_config():
    from gtopkssgd_tpu.optimizer import gtopk_sgd

    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", hier_ici_size=2)
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk_hier", hier_ici_size=0)


def test_comm_model_hier():
    n, k = 10_000_000, 10_000
    # 32 devices in slices of 4 -> 8 slices: dense O(N) on ICI + 3 sparse
    # rounds on DCN.
    assert comm_bytes_per_step("gtopk_hier", n, k, 32, ici_size=4) == (
        4 * n + 8 * k * 3
    )
    # ici_size=1 degenerates to plain gtopk volume
    assert comm_bytes_per_step("gtopk_hier", n, k, 32, ici_size=1) == (
        comm_bytes_per_step("gtopk", n, k, 32)
    )
    # the DCN hop (what the hierarchy minimizes) is log2(P/ici) sparse
    # rounds vs log2(P) for flat gtopk
    dcn_hier = 8 * k * 3
    dcn_flat = comm_bytes_per_step("gtopk", n, k, 32)
    assert dcn_hier < dcn_flat


def test_trainer_hier_one_step():
    """Full train step with gtopk_hier over the 8-device mesh: runs, loss
    finite, residual identical within each slice (the ici psum guarantee)."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    t = Trainer(TrainConfig(
        dnn="resnet20", batch_size=2, nworkers=8, compression="gtopk_hier",
        hier_ici=2, density=0.01, max_epochs=1, log_interval=1,
        eval_batches=1,
    ))
    stats = t.train(2)
    assert np.isfinite(stats["loss"])
    res = np.asarray(
        jax.device_get(t.state.opt_state.residual)
    )
    assert res.shape[0] == 8
    assert np.abs(res).max() > 0  # error feedback is actually accumulating
    for s in range(4):
        np.testing.assert_allclose(res[2 * s], res[2 * s + 1], rtol=1e-6)
