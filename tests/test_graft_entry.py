"""The driver contract: __graft_entry__ must work as invoked by the driver.

Round-1 regression: dryrun_multichip asserted on jax.device_count() instead
of provisioning virtual devices, so the driver's MULTICHIP check failed on
the 1-chip machine. These tests run the entry exactly the way the driver
does — `python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"`
from the repo root — including from a parent process that only sees ONE
device, which forces the subprocess self-provisioning path.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _driver_env(n_parent_devices: int) -> dict:
    """Env for a parent process that sees n CPU devices (no TPU grab)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Strip any inherited forced-device-count so the parent sees exactly n.
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_parent_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def test_dryrun_multichip_self_provisions_from_one_device():
    """Parent sees 1 device -> dryrun_multichip(8) must still pass (the
    exact failure mode of MULTICHIP_r01)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
        env=_driver_env(1), cwd=REPO, capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "one gtopk step OK" in proc.stdout


@pytest.mark.slow  # ~43 s subprocess; the self-provisioning variant
# below exercises the same dryrun step plus the re-exec path, so this
# direct-path twin is the redundant half of the pair
def test_dryrun_multichip_direct_path():
    """Parent already has >= 8 devices -> runs in-process."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
        env=_driver_env(8), cwd=REPO, capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "one gtopk step OK" in proc.stdout
