"""The on-hardware convergence runner (benchmarks/convergence_run.py) stays
runnable: tiny end-to-end invocation on the CI mesh, artifact shape checked.

The real artifact is produced on the bench chip
(benchmarks/results/convergence_*.jsonl); this test only pins the harness
so the committed results remain reproducible.
"""

import json
import sys

from tests.conftest import load_benchmark_module


def _load_runner():
    return load_benchmark_module("convergence_run")


def test_convergence_runner_end_to_end(tmp_path, monkeypatch):
    mod = _load_runner()
    out = tmp_path / "conv.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "4",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--modes", "dense,gtopk",
        "--out", str(out),
    ])
    mod.main()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    report = rows[-1]
    modes = {s["mode"] for s in report["modes"]}
    assert modes == {"dense", "gtopk"}
    for s in report["modes"]:
        assert "final_loss" in s and "val_top1" in s
        assert "final_loss_vs_dense" in s
    curve = [r for r in rows[:-1] if r.get("kind") != "summary"]
    assert {r["step"] for r in curve if r["mode"] == "dense"} == {2, 4}


def test_convergence_runner_arm_suffixes(tmp_path, monkeypatch):
    """Arm syntax "<mode>+warmup" / "<mode>+corr" (VERDICT round-2 #4's
    arm set) resolves to the right TrainConfig knobs and flows through to
    the artifact rows under the full arm label."""
    mod = _load_runner()
    out = tmp_path / "conv.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "2",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--modes", "gtopk+corr",
        "--out", str(out),
    ])
    mod.main()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows[-1]["modes"][0]["mode"] == "gtopk+corr"

    import pytest

    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--modes", "gtopk+bogus", "--steps", "2",
        "--nworkers", "2", "--batch-size", "4", "--out", str(out),
    ])
    with pytest.raises(SystemExit, match="bogus"):
        mod.main()
