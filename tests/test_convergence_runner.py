"""The on-hardware convergence runner (benchmarks/convergence_run.py) stays
runnable: tiny end-to-end invocation on the CI mesh, artifact shape checked.

The real artifact is produced on the bench chip
(benchmarks/results/convergence_*.jsonl); this test only pins the harness
so the committed results remain reproducible.
"""

import json
import sys

from tests.conftest import load_benchmark_module


def _load_runner():
    return load_benchmark_module("convergence_run")


def test_convergence_runner_end_to_end(tmp_path, monkeypatch):
    mod = _load_runner()
    out = tmp_path / "conv.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "4",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--modes", "dense,gtopk",
        "--out", str(out),
    ])
    mod.main()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    report = rows[-1]
    modes = {s["mode"] for s in report["modes"]}
    assert modes == {"dense", "gtopk"}
    for s in report["modes"]:
        assert "final_loss" in s and "val_top1" in s
        assert "final_loss_vs_dense" in s
    # First row is the run-manifest provenance header (same schema as the
    # metrics.jsonl header); curve rows are the untagged ones.
    assert rows[0].get("kind") == "manifest" and "config_hash" in rows[0]
    curve = [r for r in rows[:-1]
             if r.get("kind") not in ("summary", "manifest")]
    assert {r["step"] for r in curve if r["mode"] == "dense"} == {2, 4}


def test_convergence_runner_arm_suffixes(tmp_path, monkeypatch):
    """Arm syntax "<mode>+warmup" / "<mode>+corr" (VERDICT round-2 #4's
    arm set) resolves to the right TrainConfig knobs and flows through to
    the artifact rows under the full arm label."""
    mod = _load_runner()
    out = tmp_path / "conv.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "2",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--modes", "gtopk+corr",
        "--out", str(out),
    ])
    mod.main()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows[-1]["modes"][0]["mode"] == "gtopk+corr"

    # selection-kernel arm (weak #4's exact-vs-approx A/B): forces the
    # approx kernel below the 2^20-param auto threshold and trains
    out2 = tmp_path / "conv_approx.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--dnn", "resnet20", "--steps", "2",
        "--chunk", "2", "--batch-size", "4", "--eval-batches", "1",
        "--nworkers", "2", "--modes", "gtopk+approx",
        "--out", str(out2),
    ])
    mod.main()
    rows2 = [json.loads(l) for l in out2.read_text().splitlines()]
    assert rows2[-1]["modes"][0]["mode"] == "gtopk+approx"

    import pytest

    monkeypatch.setattr(sys, "argv", [
        "convergence_run.py", "--modes", "gtopk+bogus", "--steps", "2",
        "--nworkers", "2", "--batch-size", "4", "--out", str(out),
    ])
    with pytest.raises(SystemExit, match="bogus"):
        mod.main()


def test_recompute_rebuilds_thresholds_preserving_measured_fields(tmp_path):
    """--recompute replaces steps_to_* from stored curves (both the
    absolute family and the dense-drop family) and keeps measured fields
    and provenance rows byte-identical."""
    mod = load_benchmark_module("convergence_run")
    path = tmp_path / "conv.jsonl"
    rows = []
    for mode, losses in (("dense", [4.0, 2.0, 1.0, 1.0]),
                         ("gtopk", [4.0, 3.0, 2.0, 1.0])):
        rows += [{"mode": mode, "density": 1.0, "step": 10 * (i + 1),
                  "loss": l, "throughput": 1.0}
                 for i, l in enumerate(losses)]
    rows.append({"note": "provenance", "kind": "note"})
    # final_loss follows the runner's convention: the rolling-3 tail
    # mean of the curve (mean(2,1,1) = 1.3333 for dense).
    rows.append({"mode": "dense", "density": 1.0, "final_loss": 1.33333,
                 "val_top1": 0.9, "steps_to_0.5x_ref": 123,
                 "kind": "summary"})
    rows.append({"mode": "gtopk", "density": 0.001, "final_loss": 2.0,
                 "val_top1": 0.8, "kind": "summary"})
    rows.append({"dnn": "resnet20", "steps": 40, "batch_size": 4,
                 "device_kind": "cpu", "nworkers": 1,
                 "threshold_reference_loss": 0.0, "modes": [],
                 "kind": "report"})
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")

    report = mod.recompute_report(str(path))
    dense, gtopk = report["modes"]
    # Stale absolute key replaced: the rolling-3 mean first clears
    # 0.5*ref=2.0 at sample 4 (mean(2,1,1)=1.33; sample 3's mean(4,2,1)
    # = 2.33 misses), so the stale 123 must become 40.
    assert dense["steps_to_0.5x_ref"] == 40
    # dense drop = 4.0-1.3333 = 2.6667; the 98% target 1.3867 is first
    # cleared by dense's rolling mean 1.3333 at step 40.
    assert dense["steps_to_0.98_of_dense_drop"] == 40
    # gtopk's rolling-3 mean bottoms at 2.0 > the 1.3867 target: the
    # full-window rule must report None (a truncated window would not).
    assert gtopk["steps_to_0.98_of_dense_drop"] is None
    # Measured fields preserved.
    assert dense["val_top1"] == 0.9 and gtopk["val_top1"] == 0.8
    assert gtopk["final_loss_vs_dense"] == 1.5
    # Provenance row survives the rewrite.
    kept = [json.loads(l) for l in open(path)]
    assert any(r.get("kind") == "note" for r in kept)
    assert any(r.get("kind") == "report" and "recomputed" in r for r in kept)
