"""Model zoo: forward shapes, param counts, and train/eval mode plumbing.

Param-count pins are the strongest cheap parity check against the reference's
PyTorch models (SURVEY.md C7): matching counts means matching architecture.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.models import available_models, get_model


def n_params(variables):
    return sum(x.size for x in jax.tree.leaves(variables["params"]))


def init_and_apply(model, spec, batch=2, **apply_kw):
    rng = jax.random.PRNGKey(0)
    if spec.name == "lstm":
        x = jnp.zeros((batch,) + tuple(spec.example_shape), jnp.int32)
    else:
        x = jnp.zeros((batch,) + tuple(spec.example_shape), jnp.float32)
    variables = model.init({"params": rng, "dropout": rng}, x)
    out = model.apply(variables, x, **apply_kw)
    return variables, out


def test_registry_lists_reference_workloads():
    # The six paper workloads' model families must all be buildable.
    assert {"vgg16", "resnet20", "resnet50", "alexnet", "lstm", "lstman4"} <= set(
        available_models()
    )
    with pytest.raises(ValueError):
        get_model("not-a-model")


@pytest.mark.parametrize(
    "name,expected_params,tol",
    [
        ("resnet20", 272_474, 0.02),   # He et al. CIFAR ResNet-20 ~0.27M
        ("resnet56", 855_770, 0.02),   # ~0.85M
        ("vgg16", 15_000_000, 0.07),   # CIFAR VGG-16+BN ~14.7-15.3M
        ("alexnet", 61_100_840, 0.001),  # torchvision AlexNet exactly
        ("resnet50", 25_557_032, 0.02),  # ~25.5M
    ],
)
def test_vision_param_counts(name, expected_params, tol):
    # Shape-only: eval_shape traces without compiling/executing, so the big
    # ImageNet models cost milliseconds here instead of minutes.
    model, spec = get_model(name)
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1,) + tuple(spec.example_shape), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init({"params": rng, "dropout": rng}, x)
    )
    got = n_params(variables)
    assert abs(got - expected_params) / expected_params <= tol, got
    out = jax.eval_shape(lambda v: model.apply(v, x), variables)
    classes = 10 if spec.dataset == "cifar10" else 1000
    assert out.shape == (1, classes)
    assert out.dtype == jnp.float32


@pytest.mark.parametrize("name", ["vgg16", "resnet20"])
def test_train_mode_updates_batch_stats(name):
    model, spec = get_model(name)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (4,) + tuple(spec.example_shape))
    variables = model.init({"params": rng, "dropout": rng}, x)
    out, mutated = model.apply(
        variables, x, train=True,
        rngs={"dropout": rng}, mutable=["batch_stats"],
    )
    # running stats must actually move in train mode
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(before, after)
    )


def test_ptb_lstm_shapes_and_carry():
    model, spec = get_model("lstm")
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (3, 35), 0, 10000)
    variables = model.init({"params": rng}, tokens)
    (logits, carry), _ = model.apply(variables, tokens, mutable=[])
    assert logits.shape == (3, 35, 10000)
    assert len(carry) == 2 and len(carry[0]) == 2
    # carry threads across windows: different carry -> different logits
    logits2, carry2 = model.apply(variables, tokens, carry)
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))
    # Zaremba "medium" ~ 19.8M params
    got = n_params(variables)
    assert abs(got - 19_800_000) / 19_800_000 < 0.05, got


def test_an4_shapes_and_output_length():
    model, spec = get_model("lstman4")
    rng = jax.random.PRNGKey(0)
    for t in (100, 101, 57):
        x = jax.random.normal(rng, (2, t, 161))
        variables = model.init({"params": rng}, x)
        logits = model.apply(variables, x)
        assert logits.shape[0] == 2 and logits.shape[2] == 29
        assert logits.shape[1] == model.output_length(t), (
            t, logits.shape, model.output_length(t)
        )


def test_bfloat16_forward():
    model, spec = get_model("resnet20", dtype=jnp.bfloat16)
    variables, out = init_and_apply(model, spec, batch=2)
    # params stay f32, output cast back to f32
    assert all(
        v.dtype == jnp.float32 for v in jax.tree.leaves(variables["params"])
    )
    assert out.dtype == jnp.float32


def test_resnet_bf16_forward_tracks_f32():
    """BatchNorm now emits activations in the compute dtype; flax still
    reduces the statistics in f32 (force_float32_reductions), so a bf16
    forward must stay close to the f32 one — this pins the numerics the
    round-3 BN-dtype change relies on."""
    import numpy as np

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (4, 32, 32, 3), jnp.float32)
    outs = {}
    for dt in (jnp.float32, jnp.bfloat16):
        model, _ = get_model("resnet20", dtype=dt)
        vars_ = model.init({"params": rng}, x[:1])
        logits, _ = model.apply(vars_, x, train=True, mutable=["batch_stats"])
        outs[dt] = np.asarray(logits, np.float32)
        assert np.isfinite(outs[dt]).all()
    # bf16 has ~3 decimal digits; logits of an untrained net are O(1).
    np.testing.assert_allclose(outs[jnp.bfloat16], outs[jnp.float32],
                               atol=0.15, rtol=0.15)


def test_space_to_depth_stem_equivalence():
    """The s2d stem ([B,115,115,12] conv 4x4/VALID) computes the same
    linear map as the 7x7/2 pad-3 stem when its kernel is the 7x7 kernel
    embedded in the zero-padded 8x8 block layout — pinning that the
    opt-in MXU-friendly stem is the SAME architecture, not a different
    one."""
    import numpy as np

    rng = jax.random.PRNGKey(3)
    x = jax.random.normal(rng, (2, 224, 224, 3), jnp.float32)

    std, _ = get_model("resnet50")
    s2d, _ = get_model("resnet50", space_to_depth=True)
    vs = std.init({"params": rng}, x[:1])
    vd = s2d.init({"params": rng}, x[:1])

    # Embed the 7x7 kernel into 8x8 (zero LAST row/col: the pad-3+3
    # window covers rows -3..+4 about each even center) and regroup into
    # the 2x2-block channel layout used by the s2d reshape.
    w7 = np.asarray(vs["params"]["Conv_0"]["kernel"])        # [7,7,3,64]
    w8 = np.zeros((8, 8, 3, 64), np.float32)
    w8[:7, :7] = w7
    w4 = w8.reshape(4, 2, 4, 2, 3, 64).transpose(0, 2, 1, 3, 4, 5)
    w4 = w4.reshape(4, 4, 12, 64)

    vd = jax.tree.map(lambda a: a, vd)  # unfreeze-by-copy (plain dicts)
    vd["params"]["Conv_0"]["kernel"] = jnp.asarray(w4)
    # Same downstream weights so the full forwards must agree.
    for name in vs["params"]:
        if name != "Conv_0":
            vd["params"][name] = vs["params"][name]

    ys = std.apply(vs, x, train=False)
    yd = s2d.apply(vd, x, train=False)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yd),
                               atol=2e-4, rtol=2e-4)


def test_space_to_depth_param_count():
    """s2d trades the 7x7x3 stem (9408) for 4x4x12 (12288): +2880 params,
    all other shapes unchanged."""
    std, _ = get_model("resnet50")
    s2d, _ = get_model("resnet50", space_to_depth=True)
    x = jnp.zeros((1, 224, 224, 3))
    rng = jax.random.PRNGKey(0)
    n_std = sum(a.size for a in jax.tree.leaves(std.init({"params": rng}, x)["params"]))
    n_s2d = sum(a.size for a in jax.tree.leaves(s2d.init({"params": rng}, x)["params"]))
    assert n_s2d - n_std == 12288 - 9408 == 2880
