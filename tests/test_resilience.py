"""Resilience subsystem (gtopkssgd_tpu.resilience): fault injection,
recovery policies, preemption-safe checkpointing, and their trainer
wiring.

Grammar/budget/guard semantics are pinned with pure unit tests;
checkpoint integrity with real orbax round-trips of tiny pytrees; the
trainer paths end to end on the 2-way CPU mesh with the canonical
gate-smoke config (resnet20/bs4/gtopk_layerwise/rho=0.01/seed 42 — one
compiled step shared across tests via the persistent compile cache).
The error-feedback invariant under test throughout: a recovery must
never drop, zero, or double-count the residual (arXiv:1911.08772 ties
convergence to its dynamics), so skip restores it bit-identically and
resume-after-preempt reproduces the uninterrupted loss trace exactly.
"""

import json
import os
import signal
import time

import jax
import numpy as np
import pytest

from gtopkssgd_tpu.obs import HALT_EXIT_CODE
from gtopkssgd_tpu.resilience import (
    PREEMPT_EXIT_CODE,
    FaultInjector,
    InjectedLoaderError,
    PreemptionGuard,
    RecoveryManager,
    describe_policy,
    parse_inject,
    parse_policy,
    retry_call,
)
from gtopkssgd_tpu.resilience.inject import LATEST, corrupt_checkpoint_dir
from gtopkssgd_tpu.utils.checkpoint import (
    CheckpointManager,
    CheckpointMismatch,
    state_digest,
)

# The canonical tiny run (same model/flags as benchmarks/obs_gate_smoke.py
# so every dist_trainer e2e below reuses one cached XLA executable).
CANON = [
    "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
    "--compression", "gtopk_layerwise", "--density", "0.01",
    "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
    "--obs-interval", "1",
]


def _records(out_dir):
    path = os.path.join(out_dir, "metrics.jsonl")
    return [json.loads(line) for line in open(path)]


def _train_losses(out_dir):
    return {r["step"]: r["loss"] for r in _records(out_dir)
            if r["kind"] == "train"}


# ------------------------------------------------------- inject grammar

def test_parse_inject_grammar():
    faults = parse_inject(
        "nan_grad@120, slow_rank:2:2.5s@50-60, corrupt_ckpt@latest,"
        "preempt@200,loader_raise@75")
    by_kind = {f.kind: f for f in faults}
    assert len(faults) == 5
    assert by_kind["nan_grad"].start == by_kind["nan_grad"].end == 120
    assert by_kind["nan_grad"].point
    sr = by_kind["slow_rank"]
    assert (sr.start, sr.end, sr.args) == (50, 60, ("2", "2.5s"))
    assert not sr.point
    assert by_kind["corrupt_ckpt"].start == LATEST
    # spec() round-trips through the parser
    for f in faults:
        assert parse_inject(f.spec())[0].spec() == f.spec()


@pytest.mark.parametrize("bad", [
    "nan_grad",                 # no @WHEN
    "frobnicate@3",             # unknown kind
    "nan_grad@latest",          # latest is corrupt_ckpt-only
    "corrupt_ckpt@5",           # corrupt_ckpt is restore-keyed
    "nan_grad@0",               # steps are 1-based
    "nan_grad@9-5",             # inverted window
    "nan_grad@x",               # non-numeric step
    "slow_rank:1@5",            # missing duration arg
    "slow_rank:1:-2s@5",        # negative duration
    "nan_grad:7@5",             # args on an argless kind
    " , ",                      # empty spec
])
def test_parse_inject_rejects(bad):
    with pytest.raises(ValueError):
        parse_inject(bad)


def test_fault_window_point_consumed_range_refires():
    point = parse_inject("nan_grad@3")[0]
    assert point.window(0, 2) is None        # window is (prev, new]
    assert point.window(2, 3) == 3
    point.fired = 1
    # a skip rewinds the step counter; a consumed point fault must not
    # re-fire when the same window is dispatched again
    assert point.window(2, 3) is None
    rng = parse_inject("nan_grad@2-4")[0]
    assert rng.window(0, 1) is None
    for prev in (1, 2, 3):
        rng.fired += 1
        assert rng.window(prev, prev + 1) == prev + 1
    assert rng.window(4, 5) is None


def test_injector_loader_raise_consumed():
    inj = FaultInjector("loader_raise@2")
    inj.check_loader(0, 1)                   # step 1: inert
    with pytest.raises(InjectedLoaderError):
        inj.check_loader(1, 2)
    inj.check_loader(1, 2)                   # consumed: the retry succeeds
    assert inj.summary() == {"loader_raise": 1}


# ------------------------------------------------------- policy grammar

def test_parse_policy_grammar_and_defaults():
    pol = parse_policy("nan_loss=skip, loss_spike=rollback:4:0.25,"
                       "density_collapse=degrade")
    assert pol["nan_loss"].budget == 3 and pol["nan_loss"].param == 0.0
    assert pol["loss_spike"].budget == 4 and pol["loss_spike"].param == 0.25
    assert pol["density_collapse"].param == 50.0
    desc = describe_policy("loss_spike=rollback:4:0.25")
    assert "backoff=0.25s" in desc
    assert describe_policy(None).startswith("none")


@pytest.mark.parametrize("bad", [
    "nan_loss",                     # no '='
    "typo_rule=skip",               # unknown rule
    "nan_loss=retry",               # unknown action
    "nan_loss=skip,nan_loss=skip",  # rule mapped twice
    "nan_loss=skip:0",              # budget < 1
    "nan_loss=skip:x",              # non-int budget
    "nan_loss=skip:1:2:3",          # extra ':' parts
    ",",                            # empty
])
def test_parse_policy_rejects(bad):
    with pytest.raises(ValueError):
        parse_policy(bad)


def test_recovery_manager_budgets():
    rec = RecoveryManager(parse_policy(
        "nan_loss=skip:2,loss_spike=rollback:1,density_collapse=degrade:1"))
    assert not rec.claim({"rule": "residual_blowup"})   # unmapped rule
    # skip: budget bounds CONSECUTIVE skips, a clean step resets
    assert rec.claim({"rule": "nan_loss"})
    rec.consecutive_skips = 2                # as the trainer's apply would
    assert not rec.claim({"rule": "nan_loss"})
    rec.note_ok()
    assert rec.claim({"rule": "nan_loss"})
    # rollback: per-rule total budget
    assert rec.claim({"rule": "loss_spike"})
    rec.rollback_uses["loss_spike"] = 1
    assert not rec.claim({"rule": "loss_spike"})
    # degrade: claims while already degraded stand but queue nothing
    assert rec.claim({"rule": "density_collapse"})
    n_pending = len(rec.pending)
    rec.degraded = True
    assert rec.claim({"rule": "density_collapse"})
    assert len(rec.pending) == n_pending
    assert [spec.action for _, spec in rec.pop_pending()] == \
        ["skip", "skip", "rollback", "degrade"]
    assert rec.pending == []


# ------------------------------------------------------ guard and retry

def test_preemption_guard_flag_and_restore():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert g.install() is g              # idempotent
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not g.triggered and time.time() < deadline:
            time.sleep(0.01)                 # delivery is async
        assert g.triggered and g.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == before


def test_retry_call_backoff_and_reraise():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, delay=0.0) == "ok"
    assert len(calls) == 3

    def always():
        calls.append(1)
        raise KeyError("hard")

    calls.clear()
    with pytest.raises(KeyError):
        retry_call(always, retries=2, delay=0.0)
    assert len(calls) == 3                   # 1 try + 2 retries

    calls.clear()
    with pytest.raises(ValueError):          # not in the retry filter
        retry_call(lambda: (_ for _ in ()).throw(ValueError("no")),
                   retries=3, delay=0.0, exceptions=(IOError,))


# ------------------------------------------------- checkpoint integrity

def _tiny_state(scale=1.0):
    return {
        "w": np.arange(64, dtype=np.float32).reshape(8, 8) * scale,
        "step": np.asarray(int(scale), np.int32),
    }


def test_checkpoint_integrity_roundtrip_and_mismatch(tmp_path):
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, config_hash="aaaa")
    mgr.save(1, _tiny_state(1.0))
    mgr.save(2, _tiny_state(2.0))
    assert mgr.all_steps() == [1, 2]
    assert os.path.exists(os.path.join(d, "integrity-2.json"))
    mgr.close()

    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        _tiny_state())
    # clean restore: latest step, verified
    same = CheckpointManager(d, config_hash="aaaa")
    got = same.restore(template)
    assert same.last_restored_step == 2
    np.testing.assert_array_equal(got["w"], _tiny_state(2.0)["w"])
    same.close()
    # config mismatch: refused with the escape hatch named, no fallback
    other = CheckpointManager(d, config_hash="bbbb")
    with pytest.raises(CheckpointMismatch, match="allow-ckpt-mismatch"):
        other.restore(template)
    got = other.restore(template, allow_mismatch=True)
    assert np.asarray(got["step"]) == 2
    other.close()
    # structure mismatch: a different treedef/shape is refused too
    bad_template = {"w": jax.ShapeDtypeStruct((4, 4), np.float32),
                    "step": jax.ShapeDtypeStruct((), np.int32)}
    assert state_digest(bad_template) != state_digest(template)
    strict = CheckpointManager(d, config_hash="aaaa")
    with pytest.raises(CheckpointMismatch, match="digest"):
        strict.restore(bad_template)
    strict.close()


def test_corrupt_latest_falls_back_to_previous_step(tmp_path):
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, config_hash="aaaa")
    mgr.save(1, _tiny_state(1.0))
    mgr.save(2, _tiny_state(2.0))
    mgr.close()
    assert corrupt_checkpoint_dir(os.path.join(d, "2")) > 0

    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        _tiny_state())
    mgr = CheckpointManager(d, config_hash="aaaa")
    got = mgr.restore(template)              # torn latest -> previous
    assert mgr.last_restored_step == 1
    np.testing.assert_array_equal(got["w"], _tiny_state(1.0)["w"])
    # with EVERY step corrupt there is nothing to fall back to
    corrupt_checkpoint_dir(os.path.join(d, "1"))
    fresh = CheckpointManager(d, config_hash="aaaa")
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        fresh.restore(template)
    fresh.close()
    mgr.close()


def test_injector_corrupts_latest_step_dir_once(tmp_path):
    d = str(tmp_path / "ckpt")
    for step, size in ((3, 256), (7, 256)):
        os.makedirs(os.path.join(d, str(step)))
        with open(os.path.join(d, str(step), "data.bin"), "wb") as fh:
            fh.write(b"x" * size)
    inj = FaultInjector("corrupt_ckpt@latest")
    assert inj.maybe_corrupt_ckpt(d)
    assert os.path.getsize(os.path.join(d, "7", "data.bin")) == 16
    assert os.path.getsize(os.path.join(d, "3", "data.bin")) == 256
    assert not inj.maybe_corrupt_ckpt(d)     # @latest fires once
    assert inj.summary() == {"corrupt_ckpt": 1}


# --------------------------------------------------- trainer e2e (mesh)

def test_nan_skip_restores_state_bit_identical(tmp_path):
    """An injected NaN at step 2 claimed by nan_loss=skip must leave the
    trainer EXACTLY at its post-step-1 state: params, momentum, step
    counter, and the error-feedback residual all bit-identical to a run
    that never dispatched step 2 at all."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    base = dict(
        dnn="resnet20", batch_size=4, nworkers=2,
        compression="gtopk_layerwise", density=0.01, seed=42,
        log_interval=1, obs_interval=1, eval_batches=1, max_epochs=1,
    )
    with Trainer(TrainConfig(**base)) as a:
        a.train(1)
        clean = jax.device_get((a.state.params, a.state.opt_state))
    out = str(tmp_path / "chaos")
    with Trainer(TrainConfig(**base, obs_halt_on="error",
                             inject="nan_grad@2",
                             recover_policy="nan_loss=skip",
                             out_dir=out)) as b:
        b.train(2)                           # dispatch 2 is poisoned+skipped
        assert int(b.state.step) == 1
        assert b.recovery.n_recoveries == 1
        chaos = jax.device_get((b.state.params, b.state.opt_state))
        b.finalize_resilience("completed")
    for la, lb in zip(jax.tree.leaves(clean), jax.tree.leaves(chaos)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    kinds = [r["kind"] for r in _records(out)]
    assert "inject" in kinds and "recovery" in kinds


@pytest.mark.slow  # 3 full dist_trainer runs (~30 s on the 1-core host)
def test_preempt_emergency_save_then_exact_resume(tmp_path):
    """Injected SIGTERM after step 2 -> emergency save -> exit 45; a
    --resume run (note: WITHOUT --inject — resilience knobs are excluded
    from checkpoint identity, or no chaos run could ever be resumed
    cleanly) replays steps 3-4 with losses bit-identical to the
    uninterrupted trace."""
    from gtopkssgd_tpu import dist_trainer

    ref = str(tmp_path / "ref")
    assert dist_trainer.main(
        CANON + ["--num-iters", "4", "--out-dir", ref]) == 0
    run = str(tmp_path / "run")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "4", "--inject", "preempt@2", "--out-dir", run])
    assert rc == PREEMPT_EXIT_CODE
    recs = _records(run)
    saves = [r for r in recs if r["kind"] == "recovery"
             and r.get("action") == "emergency_save"]
    assert [r["step"] for r in saves] == [2]
    assert any(r.get("final_status") == "preempted" for r in recs)
    assert dist_trainer.main(
        CANON + ["--num-iters", "2", "--resume", "--out-dir", run]) == 0
    ref_loss, run_loss = _train_losses(ref), _train_losses(run)
    for step in (3, 4):
        assert run_loss[step] == ref_loss[step]


def test_skip_budget_exhaustion_halts_and_reports(tmp_path):
    """A PERSISTENT fault (NaN every step) burns the consecutive-skip
    budget and then falls through to the existing halt semantics: the
    run must NOT limp forever. ``report recovery`` renders the record
    trail of the dead run."""
    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs import report

    out = str(tmp_path / "run")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "5", "--inject", "nan_grad@1-99",
        "--recover-policy", "nan_loss=skip:2", "--obs-halt-on", "error",
        "--out-dir", out])
    assert rc == HALT_EXIT_CODE
    recs = _records(out)
    skips = [r for r in recs if r["kind"] == "recovery"
             and r.get("action") == "skip"]
    assert [r["consecutive"] for r in skips] == [1, 2]
    summary = [r for r in recs if r.get("action") == "summary"]
    assert summary and summary[-1]["final_status"] == "halted"
    assert report.main(["recovery", out]) == 0


@pytest.mark.slow  # 2 full dist_trainer runs; the tier-1 equivalents are
# the gate smoke's chaos sub-run (exit 0 + structure, via test_obs) and
# test_skip_budget_exhaustion (claim-refusal -> exit 44)
def test_chaos_run_completes_only_with_policy(tmp_path):
    """The acceptance pair: the same injected NaN exits 0 when a skip
    policy claims it and HALT_EXIT_CODE when no policy is configured."""
    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs.report import summarize_recovery

    good = str(tmp_path / "good")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "3", "--inject", "nan_grad@2",
        "--recover-policy", "nan_loss=skip", "--obs-halt-on", "error",
        "--out-dir", good])
    assert rc == 0
    s = summarize_recovery(_records(good))
    assert s["final_status"] == "completed" and s["n_recoveries"] == 1
    assert s["events_claimed"] == 1 and s["events_unclaimed"] == 0
    bare = str(tmp_path / "bare")
    rc = dist_trainer.main(CANON + [
        "--num-iters", "3", "--inject", "nan_grad@2",
        "--obs-halt-on", "error", "--out-dir", bare])
    assert rc == HALT_EXIT_CODE


@pytest.mark.slow  # compiles the dense-fallback executable (~1 min cold)
def test_degrade_swaps_to_dense_and_resumes_sparse(tmp_path):
    """degrade flips the train step to the dense-allreduce fallback (the
    warm-up branch of the same update treedef) and re-enters sparse after
    the cooldown; the run keeps training throughout."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    out = str(tmp_path / "run")
    cfg = TrainConfig(
        dnn="resnet20", batch_size=4, nworkers=2,
        compression="gtopk_layerwise", density=0.01, seed=42,
        log_interval=1, obs_interval=1, eval_batches=1, max_epochs=1,
        obs_halt_on="error", recover_policy="density_collapse=degrade:1:2",
        out_dir=out)
    with Trainer(cfg) as t:
        t.train(1)
        # fire the policy through the real monitor hook (the rule's
        # trigger condition itself is pinned by test_obs)
        assert t.monitor.recovery({"rule": "density_collapse", "step": 1})
        t.train(2)                           # applies degrade, trains dense
        assert t._degraded
        t.train(3)                           # cooldown of 2 steps expires
        assert not t._degraded
        assert int(t.state.step) == 6
        t.finalize_resilience("completed")
    actions = [r.get("action") for r in _records(out)
               if r["kind"] == "recovery"]
    assert "degrade" in actions and "sparse_resume" in actions
