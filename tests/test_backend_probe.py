"""utils.init_backend_with_deadline: the hang-guard both driver entry
points use (bench.py, __graft_entry__.dryrun_multichip).

The hung-init (False) branch was validated live against a real dead
relay — it cannot be reproduced hermetically in CI; what CI pins is the
healthy path: already-initialized backends answer immediately, in-process,
with no subprocess contending for an exclusive device.
"""

import time

import jax

from gtopkssgd_tpu.utils import init_backend_with_deadline


def test_initialized_backend_answers_immediately():
    jax.devices()  # ensure initialized (conftest pins the CPU platform)
    t0 = time.perf_counter()
    assert init_backend_with_deadline(timeout_s=30.0)
    # Cached init: no subprocess, no re-init — this is effectively free.
    assert time.perf_counter() - t0 < 5.0


def test_repeated_calls_stay_cheap():
    t0 = time.perf_counter()
    for _ in range(3):
        assert init_backend_with_deadline(timeout_s=30.0)
    assert time.perf_counter() - t0 < 5.0


def test_dead_tunnel_note_names_latest_onchip_artifact():
    """When bench.py refuses on a dead tunnel it must point the driver's
    log tail at the round's committed on-chip artifact (round-3 verdict
    weak #2): the newest benchmarks/results/bench_r*.json plus its
    headline driver-format fields."""
    import bench

    note = bench._latest_onchip_artifact_note()
    assert "benchmarks/results/bench_r" in note
    assert "images/sec/chip" in note  # headline unit made it into the note
    assert "vs_baseline" in note
