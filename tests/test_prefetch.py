"""Background host-batch prefetcher: ordering, failure, trainer equivalence.

The reference's data pipelines got async batch assembly from torch
DataLoader worker processes (SURVEY.md C8); here one daemon thread
overlaps numpy assembly with the device step. The contract that matters:
the batch stream is EXACTLY the synchronous stream (determinism), and
worker exceptions surface at the consumer.
"""

import time

import numpy as np
import pytest

from gtopkssgd_tpu.utils import Prefetcher


def test_order_preserved():
    src = iter(range(100))
    pf = Prefetcher(lambda: next(src), depth=3)
    got = [next(pf) for _ in range(50)]
    pf.close()
    assert got == list(range(50))


def test_worker_exception_propagates():
    def produce():
        raise ValueError("boom")

    pf = Prefetcher(produce, depth=2)
    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        next(pf)
    pf.close()


def test_next_after_failure_keeps_raising():
    def produce():
        raise ValueError("boom")

    pf = Prefetcher(produce, depth=2)
    for _ in range(3):  # every call fails; none may block
        with pytest.raises(RuntimeError, match="prefetch worker failed"):
            next(pf)
    pf.close()


def test_close_unblocks_full_queue():
    pf = Prefetcher(lambda: 1, depth=1)
    time.sleep(0.2)  # let the worker fill the queue and block on put
    pf.close()       # must not hang
    assert not pf._thread.is_alive()


def test_bad_depth():
    with pytest.raises(ValueError):
        Prefetcher(lambda: 1, depth=0)


def test_next_after_close_raises():
    pf = Prefetcher(lambda: 1, depth=1)
    pf.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(pf)


def test_trainer_train_after_close_raises():
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    t = Trainer(TrainConfig(
        dnn="resnet20", batch_size=2, nworkers=1, compression=None,
        max_epochs=1, eval_batches=1,
    ))
    t.close()
    with pytest.raises(RuntimeError, match="closed"):
        t.train(1)


def test_trainer_stream_identical_with_and_without_prefetch():
    """Two trainers, same seed, prefetch on vs off: identical loss
    trajectory — the prefetcher must not reorder, drop, or duplicate
    batches."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    def losses(prefetch):
        with Trainer(TrainConfig(
            dnn="resnet20", batch_size=2, nworkers=8, compression="gtopk",
            density=0.01, max_epochs=1, log_interval=1, eval_batches=1,
            prefetch=prefetch,
        )) as t:
            return [float(t.train(1)["loss"]) for _ in range(3)]

    a = losses(0)
    b = losses(2)
    np.testing.assert_array_equal(a, b)
