"""Test harness: run all tests on a virtual 8-device CPU mesh.

The reference (hclhkbu/gtopkssgd) had no test suite at all — multi-node
behavior could only be exercised with a real `mpirun` launch. JAX lets us run
real 8-way SPMD collectives in one process: force 8 host CPU devices before
jax initializes (SURVEY.md §4).
"""

import os

# Must happen before jax initializes its backends; the shared helper also
# defeats the sitecustomize JAX_PLATFORMS override (see its docstring).
from gtopkssgd_tpu.utils.settings import (  # noqa: E402
    _default_cache_dir,
    force_cpu_mesh,
)

force_cpu_mesh(8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
# Persistent compilation cache: the suite's cost is dominated by XLA:CPU
# compiles of model train steps (this host has ONE core); caching them on
# disk makes repeated runs (and identical HLO across tests) fast. The dir
# is repo-local (gitignored) because /tmp is wiped between sessions.
jax.config.update("jax_compilation_cache_dir", _default_cache_dir())
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def load_benchmark_module(name: str):
    """Import benchmarks/<name>.py by path (benchmarks/ is not a package
    on sys.path for the test run). Shared by the tests that pin the
    benchmark harnesses so the loader boilerplate cannot drift."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
