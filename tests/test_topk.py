"""Unit tests for ops.topk against numpy oracles."""

import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.ops import (
    blockwise_topk_abs,
    k_for_density,
    membership_mask,
    merge_sparse_sets,
    scatter_add_dense,
    select_topk,
    topk_abs,
)


def np_topk_abs(x, k):
    idx = np.argsort(-np.abs(x), kind="stable")[:k]
    return x[idx], idx


def test_k_for_density():
    assert k_for_density(1000, 0.001) == 1
    assert k_for_density(1001, 0.001) == 2
    assert k_for_density(10, 1.0) == 10
    assert k_for_density(5, 1e-9) == 1


@pytest.mark.parametrize("n,k", [(100, 5), (1000, 37), (65536 * 3 + 17, 100)])
@pytest.mark.parametrize("method", ["exact", "blockwise"])
def test_topk_matches_oracle(rng, n, k, method):
    x = rng.standard_normal(n).astype(np.float32)
    vals, idx = select_topk(jnp.asarray(x), k, method)
    vals, idx = np.asarray(vals), np.asarray(idx)
    ov, oi = np_topk_abs(x, k)
    # Same magnitude multiset (tie order may differ between implementations).
    np.testing.assert_allclose(
        np.sort(np.abs(vals)), np.sort(np.abs(ov)), rtol=1e-6
    )
    # Selected values really live at the claimed indices.
    np.testing.assert_array_equal(x[idx], vals)
    assert len(set(idx.tolist())) == k


def test_topk_signed_values(rng):
    x = rng.standard_normal(256).astype(np.float32)
    vals, idx = topk_abs(jnp.asarray(x), 16)
    np.testing.assert_array_equal(np.asarray(vals), x[np.asarray(idx)])


def test_blockwise_handles_padding(rng):
    # n not divisible by block count; top element near the padded tail.
    n = 1000003
    x = rng.standard_normal(n).astype(np.float32) * 0.1
    x[n - 1] = 50.0
    x[0] = -49.0
    vals, idx = blockwise_topk_abs(jnp.asarray(x), 4)
    idx = np.asarray(idx)
    assert n - 1 in idx and 0 in idx
    assert np.all(idx < n)


def test_merge_sparse_sets_oracle(rng):
    n = 500
    for _ in range(10):
        k = 16
        ia = rng.choice(n, size=k, replace=False).astype(np.int32)
        ib = rng.choice(n, size=k, replace=False).astype(np.int32)
        va = rng.standard_normal(k).astype(np.float32)
        vb = rng.standard_normal(k).astype(np.float32)
        mv, mi = merge_sparse_sets(
            jnp.asarray(va), jnp.asarray(ia), jnp.asarray(vb), jnp.asarray(ib), k, n
        )
        dense = np.zeros(n, np.float32)
        np.add.at(dense, ia, va)
        np.add.at(dense, ib, vb)
        got = np.zeros(n, np.float32)
        np.add.at(got, np.asarray(mi) % (n + 1), np.asarray(mv))
        got = got[:n] if got.shape[0] == n else got
        ov, oi = np_topk_abs(dense, k)
        want = np.zeros(n, np.float32)
        want[oi] = ov
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_merge_is_order_symmetric(rng):
    # Both ppermute partners must compute the identical merged set.
    n, k = 200, 8
    ia = rng.choice(n, size=k, replace=False).astype(np.int32)
    ib = rng.choice(n, size=k, replace=False).astype(np.int32)
    va = rng.standard_normal(k).astype(np.float32)
    vb = rng.standard_normal(k).astype(np.float32)
    mv1, mi1 = merge_sparse_sets(
        jnp.asarray(va), jnp.asarray(ia), jnp.asarray(vb), jnp.asarray(ib), k, n
    )
    mv2, mi2 = merge_sparse_sets(
        jnp.asarray(vb), jnp.asarray(ib), jnp.asarray(va), jnp.asarray(ia), k, n
    )
    np.testing.assert_array_equal(np.asarray(mi1), np.asarray(mi2))
    np.testing.assert_allclose(np.asarray(mv1), np.asarray(mv2), rtol=1e-6)


def test_merge_with_sentinel_padding():
    # Sentinel slots (idx == n, val 0) may repeat; they must never displace
    # real mass.
    n, k = 50, 4
    ia = np.array([1, 2, n, n], np.int32)
    va = np.array([1.0, -2.0, 0.0, 0.0], np.float32)
    ib = np.array([2, 3, n, n], np.int32)
    vb = np.array([5.0, 0.5, 0.0, 0.0], np.float32)
    mv, mi = merge_sparse_sets(
        jnp.asarray(va), jnp.asarray(ia), jnp.asarray(vb), jnp.asarray(ib), k, n
    )
    dense = np.asarray(scatter_add_dense(n, mi, mv))
    want = np.zeros(n, np.float32)
    want[1], want[2], want[3] = 1.0, 3.0, 0.5
    np.testing.assert_allclose(dense, want, rtol=1e-6)


def test_scatter_drops_sentinel():
    out = scatter_add_dense(
        4, jnp.array([0, 4, 2], jnp.int32), jnp.array([1.0, 9.0, 2.0])
    )
    np.testing.assert_allclose(np.asarray(out), [1.0, 0.0, 2.0, 0.0])


def test_membership_mask():
    q = jnp.array([3, 7, 1, 9], jnp.int32)
    s = jnp.array([9, 3, 5], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(membership_mask(q, s)), [True, False, False, True]
    )


class TestSimrecall:
    """ops.topk.simrecall_topk_abs — the CPU-runnable pessimistic model of
    approx_max_k selection (round-4 verdict missing #2). These pin the
    properties the convergence A/B leans on: real-but-imperfect recall,
    backfill from the next ranks, and exact determinism per input."""

    def test_valid_sparse_set(self, rng):
        from gtopkssgd_tpu.ops import simrecall_topk_abs

        x = rng.standard_normal(5000).astype(np.float32)
        vals, idx = simrecall_topk_abs(jnp.asarray(x), 100)
        vals, idx = np.asarray(vals), np.asarray(idx)
        assert len(set(idx.tolist())) == 100  # unique, no sentinels needed
        np.testing.assert_array_equal(x[idx], vals)

    def test_recall_near_target(self, rng):
        from gtopkssgd_tpu.ops import simrecall_topk_abs

        x = rng.standard_normal(20000).astype(np.float32)
        k = 1000
        _, idx = simrecall_topk_abs(jnp.asarray(x), k)
        true_k = set(np.argsort(-np.abs(x), kind="stable")[:k].tolist())
        hit = len(true_k & set(np.asarray(idx).tolist())) / k
        # Binomial(k=1000, p=0.95): std ~0.7%; 4 sigma on either side,
        # and strictly below 1.0 — the selector must actually drop.
        assert 0.91 <= hit <= 0.99

    def test_backfill_comes_from_next_ranks(self, rng):
        from gtopkssgd_tpu.ops import simrecall_topk_abs

        x = rng.standard_normal(20000).astype(np.float32)
        k = 1000
        _, idx = simrecall_topk_abs(jnp.asarray(x), k)
        order = np.argsort(-np.abs(x), kind="stable")
        ranks = np.empty(len(x), np.int64)
        ranks[order] = np.arange(len(x))
        got = ranks[np.asarray(idx)]
        # Every selected element sits within the exact top-(k+pad) pool.
        pad = max(16, int(np.ceil(k * 0.05 * 4)))
        assert got.max() < k + pad

    def test_deterministic_per_input(self, rng):
        from gtopkssgd_tpu.ops import simrecall_topk_abs

        x = jnp.asarray(rng.standard_normal(4000).astype(np.float32))
        v1, i1 = simrecall_topk_abs(x, 200)
        v2, i2 = simrecall_topk_abs(x, 200)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
        # ...but the drop pattern is data-dependent: a different gradient
        # drops a different set (mirrors approx misses moving step to step).
        _, i3 = simrecall_topk_abs(x * 1.7 + 0.01, 200)
        assert not np.array_equal(np.asarray(i1), np.asarray(i3))

    def test_jit_and_dispatch(self, rng):
        import jax

        x = jnp.asarray(rng.standard_normal(3000).astype(np.float32))
        f = jax.jit(lambda x: select_topk(x, 50, "simrecall"))
        vals, idx = f(x)
        assert vals.shape == (50,) and idx.shape == (50,)
        assert idx.dtype == jnp.int32
