"""Native C++ data-prep library vs its numpy fallback (bit-identical by
construction: randomness is drawn on the numpy side in both paths).
"""

import numpy as np
import pytest

from gtopkssgd_tpu import native


def numpy_reference_augment(images, ys, xs, flips):
    padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    out = np.empty_like(images)
    for i in range(images.shape[0]):
        crop = padded[i, ys[i]:ys[i] + 32, xs[i]:xs[i] + 32]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def test_native_builds_here():
    # The toolchain is present in this image; the library must build.
    assert native.available()


def test_augment_matches_numpy_reference(rng):
    b = 16
    images = rng.integers(0, 256, (b, 32, 32, 3), dtype=np.uint8)
    ys = rng.integers(0, 9, b).astype(np.int32)
    xs = rng.integers(0, 9, b).astype(np.int32)
    flips = rng.random(b) < 0.5
    got = native.cifar_augment_batch(images, ys, xs, flips)
    want = numpy_reference_augment(images, ys, xs, flips)
    assert got.dtype == np.uint8
    np.testing.assert_array_equal(got, want)


def test_augment_edge_offsets(rng):
    # extreme crops (0 and 8) exercise the reflect-pad boundary logic
    b = 4
    images = rng.integers(0, 256, (b, 32, 32, 3), dtype=np.uint8)
    ys = np.array([0, 8, 0, 8], np.int32)
    xs = np.array([8, 0, 0, 8], np.int32)
    flips = np.array([True, False, True, False])
    got = native.cifar_augment_batch(images, ys, xs, flips)
    want = numpy_reference_augment(images, ys, xs, flips)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("a,b,d", [
    ([], [], 0),
    ([1, 2, 3], [], 3),
    ([1, 2, 3], [1, 2, 3], 0),
    ([1, 2, 3], [1, 3], 1),
    ([1, 2, 3, 4], [2, 3, 5], 2),
    ([5, 5, 5], [5], 2),
])
def test_edit_distance(a, b, d):
    assert native.edit_distance(a, b) == d
    assert native.edit_distance(b, a) == d
