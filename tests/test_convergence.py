"""Convergence micro-test (SURVEY.md §4: gtopk at low density must track
the dense loss curve — the reference's only correctness gate, shrunk to CI
size). ResNet-20 on synthetic CIFAR, 4-way DP, 60 steps: the gtopk run at
rho=0.01 must end within a modest factor of the dense run, and allgather
(DGC union) likewise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.models import get_model
from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.parallel import make_mesh

PDEV, BATCH, STEPS = 4, 8, 40


def run_mode(mode, density, seed=0, steps=STEPS, topk_method="auto",
             hier_ici=1):
    model, spec = get_model("resnet20")
    rng = jax.random.PRNGKey(seed)
    variables = model.init({"params": rng}, jnp.zeros((1, 32, 32, 3)))
    params, bstats = variables["params"], variables["batch_stats"]
    tx = gtopk_sgd(0.05, momentum=0.9, compression=mode, density=density,
                   axis_name="dp", topk_method=topk_method,
                   hier_ici_size=hier_ici)
    mesh = make_mesh(PDEV)

    npr = np.random.default_rng(1)
    X = jnp.asarray(npr.standard_normal((PDEV, BATCH, 32, 32, 3)), jnp.float32)
    Y = jnp.asarray(npr.integers(0, 10, (PDEV, BATCH)), jnp.int32)

    def step(params, bstats, opt_state, x, y):
        x, y = x[0], y[0]

        def loss_fn(params):
            out, mut = model.apply(
                {"params": params, "batch_stats": bstats}, x, train=True,
                mutable=["batch_stats"],
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                out, y).mean(), mut["batch_stats"]

        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        nbs = jax.tree.map(lambda a: lax.pmean(a, "dp"), nbs)
        return params, nbs, opt_state, lax.pmean(loss, "dp")

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P()), check_vma=False,
    ))
    opt_state = jax.jit(tx.init)(params)
    losses = []
    for _ in range(steps):
        params, bstats, opt_state, loss = fn(params, bstats, opt_state, X, Y)
        losses.append(float(loss))
    return losses


@pytest.fixture(scope="module")
def dense_losses():
    return run_mode("dense", 1.0)


def test_dense_overfits(dense_losses):
    assert dense_losses[-1] < 0.35 * dense_losses[0], dense_losses[::10]


def test_gtopk_tracks_dense(dense_losses):
    gtopk = run_mode("gtopk", 0.01)
    # error feedback at 1% density: slower but must clearly converge
    assert gtopk[-1] < 0.5 * gtopk[0], gtopk[::10]
    assert gtopk[-1] < dense_losses[0]


def test_allgather_tracks_dense(dense_losses):
    dgc = run_mode("allgather", 0.01)
    assert dgc[-1] < 0.5 * dgc[0], dgc[::10]


def test_hier_tracks_dense(dense_losses):
    """The hierarchical two-level mode (dense within 2-device slices,
    gtopk across) must converge like plain gtopk — its global set is the
    gTop-k of slice sums, an intermediate point between local and exact
    top-k selection."""
    hier = run_mode("gtopk_hier", 0.01, hier_ici=2)
    assert hier[-1] < 0.5 * hier[0], hier[::10]
    assert hier[-1] < dense_losses[0]


def test_gtopk_converges_under_approx_selection(dense_losses):
    """Production 'auto' selects lax.approx_max_k (recall 0.95) above 2^20
    params; ResNet-20 sits below that threshold, so the other convergence
    arms all exercise EXACT selection. This arm forces the approx code
    path end-to-end through the optimizer.

    Honest scope note: on the CPU CI mesh XLA lowers ApproxTopK to an
    exact fallback, so recall here is 1 and this test pins the CALL PATH,
    not the recall<1 convergence claim itself. The recall<1 argument
    (missed elements stay in the residual and win a later round — the
    same error-feedback argument that justifies top-k sparsification,
    arXiv:1911.08772) is backed on real hardware by the selection-quality
    numbers in benchmarks/results/topk_bench_TPU_v5_lite.json; a TPU-run
    convergence arm would be the full pin."""
    approx = run_mode("gtopk", 0.01, topk_method="approx")
    assert approx[-1] < 0.5 * approx[0], approx[::10]
    assert approx[-1] < dense_losses[0]


@pytest.mark.slow  # ~127 s: long-horizon run at rho=0.001; the short-
# horizon operating-point coverage stays tier-1 via
# test_gtopk_tracks_dense / test_gtopk_converges_under_approx_selection
def test_gtopk_rho001_long_horizon():
    """The paper's operating point (rho=0.001, k=273 of 272k) over a long
    horizon. Calibrated on this exact setup (seed-pinned, CPU): the 300-step
    loss curve is [2.96, 2.25, 0.79, 0.21, 0.061, 0.018] at steps
    [0,50,...,250] with final 0.0069 = 0.0023x initial — the thresholds
    below carry >=7x margin over those measurements while still requiring
    real convergence (the round-1 criterion of 0.5x initial would pass
    after <100 of the 300 steps).

    Why NOT the "gtopk final <= 1.2x dense final" form: on this overfit
    micro-task dense reaches 1.5e-4 (pure memorization); ratio-to-dense at
    the asymptote measures memorization speed, not tracking. And why there
    is no disable-repair ablation: calibration showed the sign of the
    repair effect flips with regime (no-repair converged FASTER here at
    rho=0.001 and on an anisotropic least-squares testbed, slower at other
    settings) — short-horizon loss is not a reliable detector of the
    repair path. Repair's contract (rejected mass returns to the residual,
    bit-exactly) is pinned deterministically in
    tests/test_compression.py::test_repair_returns_rejected_mass and the
    optimizer-level mass-conservation invariant instead.
    """
    gtopk = run_mode("gtopk", 0.001, steps=300)
    assert gtopk[150] < 0.5 * gtopk[0], gtopk[::25]
    assert gtopk[-1] < 0.05 * gtopk[0], gtopk[::25]
