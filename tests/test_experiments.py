"""Experiment grid (reference C9): registry well-formed, runner launches."""

import numpy as np
import pytest

from experiments import EXPERIMENTS
from experiments.run import main
from gtopkssgd_tpu.models import get_model
from gtopkssgd_tpu.modes import ALL_MODES


def test_registry_covers_all_six_workloads():
    dnns = {spec["dnn"] for spec in EXPERIMENTS.values()}
    assert {"vgg16", "resnet20", "resnet50", "alexnet",
            "lstm", "lstman4"} <= dnns


def test_registry_entries_are_valid_configs():
    from gtopkssgd_tpu.trainer import TrainConfig

    for name, spec in EXPERIMENTS.items():
        clean = {k: v for k, v in spec.items() if not k.startswith("_")}
        cfg = TrainConfig(**clean).resolved()
        assert cfg.compression in ALL_MODES, name
        get_model(cfg.dnn)  # resolves or raises
        assert 0 < cfg.density <= 1.0, name
        assert spec["_desc"] and spec["_baseline"], name


def test_runner_list(capsys):
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "cifar10_resnet20_gtopk" in out and "resnet50_density_sweep" in out


def test_runner_launches_ci_scale():
    rc = main(["cifar10_resnet20_gtopk", "--nworkers", "2",
               "--batch-size", "4", "--num-iters", "2",
               "--eval-batches", "1", "--log-interval", "1"])
    assert rc == 0


def test_runner_rejects_unknown():
    with pytest.raises(SystemExit):
        main(["no_such_experiment"])
