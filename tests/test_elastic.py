"""Elastic fleet: resize the dp mesh without losing a step.

Pins the elastic tentpole (resilience/elastic.py + the trainer's resize
hooks): the resize inject grammar, the exit-46 / metrics-kind / lint
registrations, the residual re-partitioning's EXACT conservation of
pending gradient mass (arXiv:1911.08772 ties convergence to the
residual dynamics — a resize that drops or duplicates mass is silently
wrong), the lineage file contract, the eviction decision, the registry
lineage join, and — slow-marked — the full 2-proc -> 1-proc dist_trainer
loop whose post-resize loss trace is bit-identical across two resumes
of the same resize checkpoint (restore + fold is deterministic).
"""

import json
import os
import shutil

import numpy as np
import pytest

from gtopkssgd_tpu.resilience import parse_inject
from gtopkssgd_tpu.resilience.elastic import (
    eviction_decision,
    load_lineage,
    mint_lineage_id,
    repartition_buffer,
    repartition_residual,
    surviving_ranks,
    write_lineage,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Same model/flags as benchmarks/obs_gate_smoke.py and test_goodput so
# the e2e runs below reuse the persistent-cache XLA executable.
CANON = [
    "--dnn", "resnet20", "--batch-size", "4",
    "--compression", "gtopk_layerwise", "--density", "0.01",
    "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
    "--obs-interval", "1",
]


def _records(out_dir):
    path = os.path.join(out_dir, "metrics.jsonl")
    return [json.loads(line) for line in open(path)]


# --------------------------------------------------------- inject grammar

def test_resize_spec_parses_and_roundtrips():
    (f,) = parse_inject("resize@300:4")
    assert f.kind == "resize"
    assert (f.start, f.end) == (300, 300)      # point fault
    assert f.args == ("4",)
    assert f.spec() == "resize@300:4"          # canonical round-trip


def test_evict_rank_spec_parses_and_roundtrips():
    (f,) = parse_inject("evict_rank:2@300")
    assert f.kind == "evict_rank"
    assert (f.start, f.end) == (300, 300)
    assert f.args == ("2",)
    assert f.spec() == "evict_rank:2@300"


@pytest.mark.parametrize("bad", [
    "resize@300",          # missing :NEWP
    "resize@300:0",        # new_p < 1
    "resize@300:x",        # non-integer new_p
    "resize:4@300",        # args ride WHEN, not the head
    "resize@1-5:2",        # range, not a point
    "evict_rank@300",      # missing rank
    "evict_rank:2@1-5",    # range, not a point
    "evict_rank:-1@300",   # negative rank
])
def test_malformed_resize_specs_rejected(bad):
    with pytest.raises(ValueError):
        parse_inject(bad)


def test_resize_faults_are_consumed_on_fire():
    from gtopkssgd_tpu.resilience import FaultInjector

    inj = FaultInjector("resize@3:1,evict_rank:0@5")
    assert inj.pending_resize(0, 2) is None
    assert inj.pending_resize(2, 3) == 1
    assert inj.pending_resize(2, 3) is None    # consumed
    assert inj.pending_evict(4, 5) == 0
    assert inj.pending_evict(4, 5) is None


# ----------------------------------------------------------- registrations

def test_exit_46_registered():
    from gtopkssgd_tpu.exit_codes import (EXIT_RESIZE_RESTART, REGISTRY,
                                          describe)

    assert EXIT_RESIZE_RESTART == 46
    assert EXIT_RESIZE_RESTART in REGISTRY
    assert "resize" in describe(EXIT_RESIZE_RESTART)


def test_resize_kind_registered_and_durable():
    from gtopkssgd_tpu.analysis.rules import DurableEventRule
    from gtopkssgd_tpu.utils.metrics import KINDS

    assert "resize" in KINDS
    assert "resize" in DurableEventRule.DURABLE_KINDS


# ------------------------------------------------------- re-partitioning
# Exactness strategy: integer-valued fp32 buffers — every fold add is
# exact in fp32, so conservation asserts == not approx.

def _int_valued(shape, rng, signed=True):
    lo = -100 if signed else 0
    return rng.integers(lo, 100, size=shape).astype(np.float32)


@pytest.mark.parametrize("old_p,new_p", [
    (4, 2),    # pow2 -> pow2 shrink
    (4, 3),    # pow2 -> non-pow2 shrink
    (2, 1),    # shrink to a single worker
    (3, 2),    # non-pow2 shrink
    (2, 4),    # grow
    (3, 3),    # identity
])
def test_repartition_conserves_pending_mass_exactly(old_p, new_p, rng):
    buf = _int_valued((old_p, 64), rng, signed=False)
    out = repartition_buffer(buf, new_p)
    assert out.shape == (new_p, 64) and out.dtype == buf.dtype
    # Non-negative integer-valued fp32: sum(|residual|) conserved EXACTLY.
    assert float(np.abs(out).sum()) == float(np.abs(buf).sum())


@pytest.mark.parametrize("old_p,new_p", [(4, 2), (4, 3), (3, 2), (2, 1)])
def test_repartition_column_sums_exact_signed(old_p, new_p, rng):
    buf = _int_valued((old_p, 33), rng, signed=True)
    out = repartition_buffer(buf, new_p)
    # The fold adds orphaned rows into survivors: each COLUMN's total
    # pending mass (signed) is conserved exactly.
    np.testing.assert_array_equal(out.sum(axis=0), buf.sum(axis=0))


def test_grow_then_shrink_back_is_identity(rng):
    buf = _int_valued((2, 17), rng)
    grown = repartition_buffer(buf, 4)
    np.testing.assert_array_equal(grown[:2], buf)       # copied rows
    assert not grown[2:].any()                          # zero rows
    back = repartition_buffer(grown, 2)
    np.testing.assert_array_equal(back, buf)            # exact round-trip


def test_shrink_fold_matches_masked_fold_semantics(rng):
    # out[r % new_p] += buf[r] for each orphaned row — spelled out.
    buf = _int_valued((5, 8), rng)
    out = repartition_buffer(buf, 2)
    want = buf[:2].copy()
    for r in range(2, 5):
        want[r % 2] += buf[r]
    np.testing.assert_array_equal(out, want)


def test_repartition_rejects_bad_inputs():
    with pytest.raises(ValueError):
        repartition_buffer(np.float32(3.0), 2)          # no [P] dim
    with pytest.raises(ValueError):
        repartition_buffer(np.zeros((2, 4), np.float32), 0)


def test_repartition_residual_all_layouts(rng):
    flat = _int_valued((4, 16), rng)                    # gtopk
    tup = (_int_valued((4, 8), rng), _int_valued((4, 3), rng))
    dct = {"v": _int_valued((4, 8), rng),               # momentum corr.
           "u": _int_valued((4, 8), rng)}
    out_flat = repartition_residual(flat, 2)
    out_tup = repartition_residual(tup, 2)
    out_dct = repartition_residual(dct, 2)
    np.testing.assert_array_equal(out_flat, repartition_buffer(flat, 2))
    for a, b in zip(out_tup, tup):
        np.testing.assert_array_equal(a, repartition_buffer(b, 2))
    for key in dct:
        np.testing.assert_array_equal(out_dct[key],
                                      repartition_buffer(dct[key], 2))


# ----------------------------------------------------------------- lineage

def test_lineage_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_lineage(d) is None                      # fresh start
    lid = mint_lineage_id()
    assert len(lid) == 16
    write_lineage(d, lineage_id=lid, resize_epoch=0, p=2)
    rec = load_lineage(d)
    assert rec == {"lineage_id": lid, "resize_epoch": 0, "p": 2}
    write_lineage(d, lineage_id=lid, resize_epoch=1, p=1,
                  prev_p=2, reason="inject")
    assert load_lineage(d)["resize_epoch"] == 1


def test_lineage_malformed_reads_as_none(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "elastic.json"), "w") as fh:
        fh.write("{torn")
    assert load_lineage(d) is None                      # never kills resume
    with open(os.path.join(d, "elastic.json"), "w") as fh:
        fh.write("{}")
    assert load_lineage(d) is None                      # no lineage_id
    assert load_lineage(None) is None


def test_surviving_ranks_renumber():
    assert surviving_ranks(4, [1]) == [0, 2, 3]
    assert surviving_ranks(4, []) == [0, 1, 2, 3]


# ---------------------------------------------------------------- eviction

def _merged(fracs, stragglers=()):
    return {
        "goodput_by_rank": {
            r: {"goodput_frac": f, "wall_s": 100.0,
                "goodput_s": 100.0 * f, "wait_s": 100.0 * (1 - f)}
            for r, f in fracs.items()},
        "stragglers": list(stragglers),
    }


def test_eviction_names_the_outlier_rank():
    merged = _merged({0: 0.45, 1: 0.92, 2: 0.95},
                     [{"slowest_rank": 0, "persistent": True}])
    d = eviction_decision(merged, p=3, min_fleet=1, margin=0.1)
    assert d is not None
    assert d["rank"] == 0 and d["new_p"] == 2
    assert d["reason"] == "evict"
    assert d["persistent_straggler"] is True
    assert d["dominant_badput"] == "wait"


def test_eviction_refuses_below_min_fleet():
    merged = _merged({0: 0.45, 1: 0.95})
    assert eviction_decision(merged, p=2, min_fleet=2) is None
    # p=1: nothing to evict into, regardless of floor.
    assert eviction_decision(_merged({0: 0.4}), p=1, min_fleet=1) is None


def test_eviction_none_for_healthy_fleet():
    merged = _merged({0: 0.93, 1: 0.92, 2: 0.95})
    assert eviction_decision(merged, p=3, min_fleet=1, margin=0.1) is None


def test_eviction_without_corroborating_straggler_row():
    merged = _merged({0: 0.45, 1: 0.92, 2: 0.95})
    d = eviction_decision(merged, p=3, min_fleet=1, margin=0.1)
    assert d is not None and d["persistent_straggler"] is False


# ---------------------------------------------------------- registry join

def test_registry_lineage_join():
    from gtopkssgd_tpu.obs import registry as _registry

    lid = "a" * 16
    entries = [
        {"config_hash": "h2", "lineage_id": lid, "resize_epoch": 0,
         "stats": {"loss_last": 2.0}},
        {"config_hash": "hx", "stats": {}},              # unrelated run
        {"config_hash": "h1", "lineage_id": lid, "resize_epoch": 1,
         "stats": {"loss_last": 1.5}},
    ]
    # pick_baseline: no hash match, but the lineage joins the segments.
    base = _registry.pick_baseline(entries[-1], entries[:-1])
    assert base is entries[0]
    # history under the PRE-resize hash keeps the post-resize segment.
    rows = _registry.history_rows(entries, config_hash="h2")
    assert len(rows) == 2
    lineage_col = _registry.HISTORY_HEADER.index("lineage")
    assert rows[0][lineage_col] == f"{lid[:8]}:0"
    assert rows[1][lineage_col] == f"{lid[:8]}:1"
    for row in rows:
        assert len(row) == len(_registry.HISTORY_HEADER)
    # Non-elastic entries render "-" and are filtered as before.
    assert _registry.history_rows(entries, config_hash="hx")[0][
        lineage_col] == "-"


# ------------------------------------------------------------------- e2e

@pytest.mark.slow  # three full dist_trainer runs + jit compiles
def test_resize_e2e_shrink_and_deterministic_resume(tmp_path):
    """The chaos resize loop end to end: 2-proc run resizes to 1 at
    step 3 (exit 46, durable resize record, lineage), and TWO resumes
    from the same resize checkpoint produce bit-identical loss traces —
    the restore + residual fold is deterministic, so the post-resize
    trajectory is well-defined (the elastic analog of the preempt
    path's exact-resume pin)."""
    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.exit_codes import EXIT_RESIZE_RESTART

    a = str(tmp_path / "pre")
    rc = dist_trainer.main(CANON + [
        "--nworkers", "2", "--elastic", "--inject", "resize@3:1",
        "--num-iters", "6", "--out-dir", a])
    assert rc == EXIT_RESIZE_RESTART
    resizes = [r for r in _records(a) if r["kind"] == "resize"]
    assert len(resizes) == 1
    rz = resizes[0]
    assert rz["old_p"] == 2 and rz["new_p"] == 1
    assert rz["reason"] == "inject" and rz["drained_step"] == 3
    assert rz["lineage_id"] and rz["resize_epoch"] == 1

    def _resume(name):
        d = str(tmp_path / name)
        os.makedirs(d)
        shutil.copytree(os.path.join(a, "ckpt"), os.path.join(d, "ckpt"))
        shutil.copy2(os.path.join(a, "elastic.json"),
                     os.path.join(d, "elastic.json"))
        rc = dist_trainer.main(CANON + [
            "--nworkers", "1", "--elastic", "--resume",
            "--num-iters", "3", "--out-dir", d])
        assert rc == 0
        trace = [(r["step"], r["loss"]) for r in _records(d)
                 if r["kind"] == "train"]
        assert trace and trace[0][0] == 4       # continues, no lost step
        lineage = json.load(open(os.path.join(d, "elastic.json")))
        assert lineage["lineage_id"] == rz["lineage_id"]
        return trace

    assert _resume("post1") == _resume("post2")   # bit-identical traces
