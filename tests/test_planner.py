"""Comm planner + Ok-Topk balanced schedule vs numpy oracles.

Balanced-schedule contracts (ISSUE 9 acceptance): all ranks bit-identical
on ragged and pow2 meshes, fold+repair restores rejected picks exactly,
per-rank wire volume <= the tree's at p >= 8. Planner contracts: monotone
in beta, respects a --comm-plan pin, falls back sanely with no probe
artifact, and auto-selects the hand-picked historical schedule in every
regime the scaling model already covers (no silent behavior change at
defaults).
"""

import numpy as np
import pytest
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.compression import get_compressor
from gtopkssgd_tpu.modes import default_schedule
from gtopkssgd_tpu.parallel import (
    balanced_cap,
    build_decision,
    candidate_plans,
    comm_bytes_per_step,
    make_mesh,
    resolve_plan,
    sparse_allreduce,
    validate_pin,
)
from gtopkssgd_tpu.parallel.planner import (
    PLANNER_DEFAULT_ALPHA_MS,
    CommPlan,
    score_plan,
)

K = 8
N = 300


def make_local_sets(rng, p, k=K, n=N):
    """Random fixed-k local sets with unique indices + sentinel padding
    (same layout as test_collectives)."""
    vals = np.zeros((p, k), np.float32)
    idxs = np.full((p, k), n, np.int32)
    for d in range(p):
        kk = int(rng.integers(k // 2, k + 1))
        ii = rng.choice(n, size=kk, replace=False)
        vals[d, :kk] = rng.normal(size=kk).astype(np.float32)
        idxs[d, :kk] = ii
    return vals, idxs


def np_balanced(vals, idxs, k, n, p):
    """Independent numpy simulator of the balanced schedule: per-dest
    capped largest-|v| scatter, owner-range reduce, owner top-cap,
    global top-k over the (disjoint-index) union. Returns {idx: val}."""
    chunk = -(-n // p)
    cap = balanced_cap(k, p, n)
    acc = np.zeros((p, chunk), np.float64)
    for r in range(p):
        v, i = vals[r], idxs[r]
        real = i < n
        owner = np.minimum(i // chunk, p - 1)
        for s in range(p):
            dest = (r + s) % p
            dmask = real & (owner == dest)
            if s == 0:
                sv, si = np.where(dmask, v, 0.0), i
            else:
                mag = np.where(dmask, np.abs(v), -1.0)
                pos = np.argsort(-mag, kind="stable")[:cap]
                sel = mag[pos] >= 0.0
                sv, si = np.where(sel, v[pos], 0.0), np.where(
                    sel, i[pos], n)
            loc = si - dest * chunk
            ok = (si < n) & (loc >= 0) & (loc < chunk)
            np.add.at(acc[dest], loc[ok], sv[ok])
    cand = {}
    for d in range(p):
        pos = np.argsort(-np.abs(acc[d]), kind="stable")[:cap]
        for q in pos:
            if abs(acc[d][q]) > 0:
                cand[d * chunk + q] = acc[d][q]
    top = sorted(cand.items(), key=lambda kv: -abs(kv[1]))[:k]
    return dict(top)


def _run_balanced(vals, idxs, p, k=K, n=N, codec="fp32"):
    mesh = make_mesh(p)
    def body(v, i):
        gv, gi = sparse_allreduce(
            "gtopk", v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p,
            codec=codec, plan=CommPlan("balanced", "gtopk",
                                       "balanced", codec=codec))[:2]
        return gv[None], gi[None]

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"))))
    gv, gi = fn(vals, idxs)  # (p, k): one row per shard, v[0] -> (k,)
    return np.asarray(gv), np.asarray(gi)


@pytest.mark.parametrize("p", [3, 5, 8])
def test_balanced_all_ranks_identical_and_matches_oracle(p):
    rng = np.random.default_rng(17 + p)
    vals, idxs = make_local_sets(rng, p)
    gv, gi = _run_balanced(vals, idxs, p)
    for d in range(1, p):
        assert np.array_equal(gv[0], gv[d])  # bit-identical
        assert np.array_equal(gi[0], gi[d])
    got = {int(i): float(v) for v, i in zip(gv[0], gi[0]) if i < N}
    want = np_balanced(vals, idxs, K, N, p)
    assert set(got) == set(want)
    for i, v in got.items():
        assert np.isclose(v, want[i], rtol=1e-6), (i, v, want[i])


@pytest.mark.parametrize("p", [3, 5, 8])
def test_balanced_ranks_identical_under_lossy_codec(p):
    # Determinism survives quantization: every rank decodes the same
    # allgathered owner sets, so the reselect agrees bitwise.
    rng = np.random.default_rng(29 + p)
    vals, idxs = make_local_sets(rng, p)
    gv, gi = _run_balanced(vals, idxs, p, codec="int8:64")
    for d in range(1, p):
        assert np.array_equal(gv[0], gv[d])
        assert np.array_equal(gi[0], gi[d])


@pytest.mark.parametrize("p", [3, 5, 8])
def test_balanced_repair_restores_rejected_exactly(p):
    # A pick that never lands in gidx (capped out in scatter, lost the
    # owner top-cap, or rejected by the final reselect) must come back
    # into the residual EXACTLY — bitwise, since the fp32 codec is the
    # identity and repair adds the original local value.
    rng = np.random.default_rng(43 + p)
    vals, idxs = make_local_sets(rng, p)
    _, gi = _run_balanced(vals, idxs, p)
    gidx = jnp.asarray(gi[0])
    comp = get_compressor("gtopk", density=K / N, method="exact")
    for r in range(p):
        res = comp.repair(jnp.zeros(N), jnp.asarray(vals[r]),
                          jnp.asarray(idxs[r]), gidx)
        res = np.asarray(res)
        delivered = set(int(i) for i in gi[0] if i < N)
        for v, i in zip(vals[r], idxs[r]):
            if i >= N:
                continue
            if int(i) in delivered:
                assert res[i] == 0.0
            else:
                assert res[i] == v  # exact, not approx


def test_balanced_bytes_beat_tree_at_scale():
    # Acceptance: per-rank wire bytes <= tree's at p >= 8 for realistic
    # k (k >> p; at pathological k ~ p the 2p-1 message framing costs
    # more than log2(p) full sets and the PLANNER keeps the tree).
    n, k = 10_000_000, 10_000
    for p in (8, 12, 16, 32, 64):
        bal = comm_bytes_per_step("gtopk", n, k, p, schedule="balanced")
        tree = comm_bytes_per_step("gtopk", n, k, p)
        assert bal <= tree, (p, bal, tree)
    # and the balanced volume is O(k): grows ~not at all from p=8->64
    b8 = comm_bytes_per_step("gtopk", n, k, 8, schedule="balanced")
    b64 = comm_bytes_per_step("gtopk", n, k, 64, schedule="balanced")
    assert b64 < 1.2 * b8


def test_balanced_cap_bounds():
    assert balanced_cap(10_000, 8, 10_000_000) == 1875
    assert balanced_cap(8, 8, 300) == 2      # ceil(1.5*8/8)
    assert balanced_cap(8, 64, 300) == 1     # floor of 1
    assert balanced_cap(100, 2, 60) == 30    # chunk clamp: ceil(n/p)
    assert balanced_cap(5, 2, 1000) == 4     # <= k clamp inactive here
    assert balanced_cap(5, 1, 1000) == 5     # k clamp at p=1


# ------------------------------------------------------------ planner


def test_planner_auto_matches_historical_at_defaults():
    # No silent behavior change: with the repo's committed dcn_probe
    # artifact (and its ~22 ms alpha), every regime the scaling model's
    # default grid covers keeps the hand-picked historical schedule.
    n = 25_557_032
    for mode, ici in (("gtopk", 1), ("gtopk_layerwise", 1),
                      ("allgather", 1), ("gtopk_hier", 16),
                      ("dense", 1)):
        for p in (1, 4, 16, 32, 64, 256):
            for rho in (0.001, 0.01):
                k = max(1, int(np.ceil(rho * n)))
                d = build_decision(mode, p=p, n=n, k=k, ici_size=ici)
                assert d.plan.schedule == default_schedule(mode), (
                    mode, p, rho, d.candidates)
                assert d.record()["plan_is_default"] == 1.0


def test_planner_fallback_without_probe_artifact(tmp_path):
    # Empty probe dir -> documented fallback constants, and the default
    # regime still keeps the tree (the nonzero alpha floor exists
    # precisely so the degenerate bandwidth-only model cannot flip the
    # schedule silently).
    d = build_decision("gtopk", p=32, n=25_557_032, k=25_558,
                      probe_dir=str(tmp_path))
    assert d.inputs["fit_source"] == "fallback-defaults"
    assert d.inputs["alpha_ms"] == PLANNER_DEFAULT_ALPHA_MS
    assert d.plan.name == "tree"


def test_planner_monotone_in_beta():
    # More slow-link bandwidth can only help; comm_ms strictly falls.
    plan = candidate_plans("gtopk")[1]
    assert plan.name == "balanced"
    last = float("inf")
    for beta in (0.1, 1.0, 10.0, 100.0):
        ms = score_plan(plan, 32, n=25_557_032, k=255_571,
                        alpha_ms=0.0, beta_gbps=beta, ici_gbps=1600.0)
        assert ms < last
        last = ms


def test_planner_balanced_wins_bandwidth_bound_regime():
    # The regime the schedule exists for: latency-free fabric, dense-ish
    # sparse sets, many ranks -> O(k) beats O(k log p).
    d = build_decision("gtopk", p=32, n=25_557_032, k=255_571,
                       alpha_ms=0.0)
    assert d.plan.name == "balanced"
    by_name = {c["name"]: c for c in d.candidates}
    assert by_name["balanced"]["comm_ms"] < by_name["tree"]["comm_ms"]
    assert by_name["balanced"]["wire_bytes"] < by_name["tree"]["wire_bytes"]


def test_planner_respects_pin_and_rejects_bad_pin():
    d = build_decision("gtopk", p=4, n=10_000, k=100, pin="balanced")
    assert d.plan.name == "balanced"  # despite tree scoring cheaper
    assert d.pin == "balanced"
    with pytest.raises(ValueError, match="does not realize"):
        validate_pin("balanced", "dense")
    with pytest.raises(ValueError, match="does not realize"):
        build_decision("allgather", p=4, n=10_000, k=100, pin="tree")
    assert validate_pin(None, "gtopk") == "auto"


def test_planner_candidates_are_semantics_preserving():
    assert [c.name for c in candidate_plans("gtopk")] == [
        "tree", "balanced"]
    assert [c.name for c in candidate_plans("gtopk_layerwise")] == [
        "tree", "balanced"]
    assert [c.name for c in candidate_plans("dense")] == ["dense"]
    assert [c.name for c in candidate_plans(None)] == ["dense"]
    assert [c.name for c in candidate_plans("allgather")] == ["allgather"]
    assert [c.name for c in candidate_plans("gtopk_hier",
                                            ici_size=4)] == ["hier"]


def test_planner_carries_pipeline_and_span_columns():
    """PR 15: the decision record carries the RESOLVED pipeline, and
    every candidate row prices the step-span both execution orders would
    expose — B>1 with nonzero select cost makes the overlapped span
    strictly cheaper, B=1 makes them equal (nothing to overlap)."""
    buckets = ((1_000_000, 1_000),) * 4
    d = build_decision("gtopk_layerwise", p=8, n=4_000_000, k=4_000,
                       alpha_ms=0.1, beta_gbps=0.6, bucketing="b4",
                       buckets=buckets, pipeline="overlap")
    assert d.plan.pipeline == "overlap"
    rec = d.record()
    assert rec["pipeline"] == "overlap"
    for c in d.candidates:
        assert c["span_serial_ms"] > 0
        assert c["span_overlap_ms"] > 0
        assert c["span_overlap_ms"] < c["span_serial_ms"], c["name"]
    # the schedule choice itself stays a comm_ms decision; the spans are
    # evidence, recorded per candidate
    assert {c["name"] for c in d.candidates} == {"tree", "balanced"}
    # an unbucketed wire is one bucket of the full (n, k): both orders
    # expose the same span, and the default pipeline is serial
    d1 = build_decision("gtopk", p=8, n=4_000_000, k=4_000,
                        alpha_ms=0.1, beta_gbps=0.6)
    assert d1.plan.pipeline == "serial"
    assert d1.record()["pipeline"] == "serial"
    for c in d1.candidates:
        assert c["span_overlap_ms"] == pytest.approx(c["span_serial_ms"])
    # pipeline rides only the gtopk-family candidates — a dense wire has
    # no select/merge chain to reorder
    (dense,) = candidate_plans("dense", pipeline="overlap")
    assert dense.pipeline == "serial"


def test_resolve_plan_memo_keys_on_pipeline():
    buckets = ((5_000, 50), (5_000, 50))
    a = resolve_plan("gtopk_layerwise", 8, 10_000, 100, "fp32", 1,
                     "auto", None, "b2", buckets, "serial")
    b = resolve_plan("gtopk_layerwise", 8, 10_000, 100, "fp32", 1,
                     "auto", None, "b2", buckets, "overlap")
    assert a is not b
    assert a.pipeline == "serial" and b.pipeline == "overlap"
    assert a.schedule == b.schedule            # order, not wire choice
    assert resolve_plan("gtopk_layerwise", 8, 10_000, 100, "fp32", 1,
                        "auto", None, "b2", buckets, "serial") is a


def test_resolve_plan_memoizes():
    a = resolve_plan("gtopk", 8, 10_000, 100)
    b = resolve_plan("gtopk", 8, 10_000, 100)
    assert a is b
    assert a.schedule == "tree"


def test_sparse_allreduce_rejects_unknown_schedule():
    with pytest.raises(ValueError, match="supports schedules"):
        sparse_allreduce("gtopk", jnp.zeros(4), jnp.zeros(4, jnp.int32),
                         k=4, n=10, axis_name="dp", axis_size=2,
                         plan="ring")
