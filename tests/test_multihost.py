"""Multi-host path: 2 real processes over jax.distributed on CPU.

Until round 2 the multi-host code (`jax.distributed.initialize`, the
`make_array_from_process_local_data` batch assembly in
Trainer._device_batch, per-process shard iterators) was dead code in every
test. This launches TWO actual processes, each owning one CPU device of a
2-device mesh, and runs distributed gtopk training steps across them —
the closest single-machine analogue of the reference's `mpirun -np 2`
smoke (SURVEY.md §4). Skipped cleanly if the jax build lacks CPU
cross-process collectives.

Also covers the profiler flag (VERDICT #9) in the single-process path.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
sys.path.insert(0, sys.argv[3])  # repo root (script itself lives in tmp)
import jax
jax.config.update("jax_platforms", "cpu")
from gtopkssgd_tpu.utils.settings import _default_cache_dir
jax.config.update("jax_compilation_cache_dir", _default_cache_dir())
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
coord, pid = sys.argv[1], int(sys.argv[2])
try:
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=pid)
except Exception as e:  # unsupported build -> tell the parent to skip
    print("DISTRIBUTED-UNSUPPORTED:", e)
    raise SystemExit(99)
assert jax.device_count() == 2 and jax.local_device_count() == 1
# initialize() succeeding only proves the COORDINATION service works; the
# pinned jaxlib CPU wheel can still lack cross-process XLA computations
# ("Multiprocess computations aren't implemented on the CPU backend",
# raised from the first collective — observed from orbax's directory-sync
# broadcast inside Trainer.__init__). Probe one tiny collective up front
# so unsupported builds hit the parent's skip path instead of failing
# deep inside training.
try:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("capability probe")
except Exception as e:
    print("DISTRIBUTED-UNSUPPORTED:", e)
    raise SystemExit(99)
import numpy as np
from gtopkssgd_tpu.trainer import TrainConfig, Trainer

cfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                  compression="gtopk", density=0.01, max_epochs=1,
                  log_interval=1, eval_batches=1, out_dir=sys.argv[4])
t = Trainer(cfg)
stats = t.train(2)
assert int(t.state.step) == 2
assert np.isfinite(stats["loss"]), stats
# multi-host checkpoint: every process participates (sharded residual)
t.save()
res_before = np.asarray(
    t.state.opt_state.residual.addressable_shards[0].data)
t2 = Trainer(cfg)
assert t2.restore() and int(t2.state.step) == 2
res_after = np.asarray(
    t2.state.opt_state.residual.addressable_shards[0].data)
np.testing.assert_array_equal(res_before, res_after)
t2.train(1)
assert int(t2.state.step) == 3
t.close(); t2.close()

# Hierarchical mode across the PROCESS boundary: with 2 processes x 1
# device and hier_ici=2 there is ONE slice spanning both processes, so the
# intra-slice dense psum itself crosses DCN-analogue transport — the
# degenerate-but-real case (cross-slice tree empty, level-1 psum does all
# the reducing) that no single-process test can exercise.
hcfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                   compression="gtopk_hier", hier_ici=2, density=0.01,
                   max_epochs=1, log_interval=1, eval_batches=1)
with Trainer(hcfg) as th:
    hstats = th.train(1)
    assert np.isfinite(hstats["loss"]), hstats

# Layer-wise mode across the process boundary: the residual is a PER-LEAF
# pytree each sharded P('dp') — state assembly/donation over real
# cross-process transport is a different code path from the flat [N]
# residual the gtopk step above exercised.
lcfg = TrainConfig(dnn="resnet20", batch_size=4, nworkers=2,
                   compression="gtopk_layerwise", density=0.01,
                   max_epochs=1, log_interval=1, eval_batches=1)
with Trainer(lcfg) as tl:
    lstats = tl.train(1)
    assert np.isfinite(lstats["loss"]), lstats
print(f"MULTIHOST-OK pid={pid} loss={stats['loss']:.4f} "
      f"hier_loss={hstats['loss']:.4f} lw_loss={lstats['loss']:.4f}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distributed_gtopk(tmp_path):
    port = _free_port()
    coord = f"localhost:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out_dir = str(tmp_path / "run")
    procs = [
        subprocess.Popen([sys.executable, str(script), coord, str(pid),
                          REPO, out_dir],
                         env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=850)
        outs.append((p.returncode, out))
    if any(rc == 99 for rc, _ in outs):
        pytest.skip("jax build lacks CPU cross-process collectives: "
                    + outs[0][1].splitlines()[-1])
    for rc, out in outs:
        assert rc == 0, out
        assert "MULTIHOST-OK" in out


def test_profile_dir_writes_trace(tmp_path):
    from gtopkssgd_tpu.dist_trainer import main

    prof = tmp_path / "prof"
    rc = main(["--dnn", "resnet20", "--batch-size", "4", "--nworkers", "1",
               "--num-iters", "1", "--eval-batches", "1",
               "--profile-dir", str(prof), "--profile-steps", "2"])
    assert rc == 0
    # The trace lands under <dir>/plugins/profile/<run>/ with a .trace.json.gz
    found = [f for f in prof.rglob("*") if f.is_file()]
    assert any("trace" in f.name for f in found), found
