"""Sparse collectives vs numpy oracles on an 8-device virtual mesh.

This is exactly what the reference could not do without `mpirun -np 8`:
run real 8-way SPMD collectives in one test process.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    dense_allreduce,
    gtopk_allreduce,
    make_mesh,
    topk_allgather,
)

PDEV = 8
K = 8
N = 300


def np_topk(x, k):
    idx = np.argsort(-np.abs(x), kind="stable")[:k]
    return x[idx].astype(np.float32), idx.astype(np.int32)


def np_merge(va, ia, vb, ib, k, n):
    dense = np.zeros(n + 1, np.float64)
    np.add.at(dense, ia, va)
    np.add.at(dense, ib, vb)
    dense[n] = 0.0
    v, i = np_topk(dense[:n], k)
    i = np.where(v == 0, n, i).astype(np.int32)
    return v, i


def np_gtopk(local_vals, local_idx, k, n):
    """Numpy simulator of recursive-doubling gtopk (independent oracle)."""
    p = len(local_vals)
    vals = [v.copy() for v in local_vals]
    idxs = [i.copy() for i in local_idx]
    r = 1
    while r < p:
        nv, ni = [None] * p, [None] * p
        for d in range(p):
            q = d ^ r
            nv[d], ni[d] = np_merge(vals[d], idxs[d], vals[q], idxs[q], k, n)
        vals, idxs = nv, ni
        r <<= 1
    return vals, idxs


def make_local_sets(rng, p=PDEV, k=K, n=N):
    vals = np.zeros((p, k), np.float32)
    idxs = np.zeros((p, k), np.int32)
    for d in range(p):
        i = rng.choice(n, size=k, replace=False).astype(np.int32)
        v = rng.standard_normal(k).astype(np.float32)
        vals[d], idxs[d] = v, i
    return vals, idxs


def test_gtopk_matches_numpy_simulator(rng):
    vals, idxs = make_local_sets(rng)

    def body(v, i):
        gv, gi = gtopk_allreduce(
            v[0], i[0], k=K, n=N, axis_name="dp", axis_size=PDEV
        )
        return gv[None], gi[None]

    mesh = make_mesh(PDEV)
    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    gv, gi = np.asarray(gv), np.asarray(gi)

    # 1) Identical result on every device (the SPMD-symmetry claim).
    for d in range(1, PDEV):
        np.testing.assert_array_equal(gi[0], gi[d])
        np.testing.assert_allclose(gv[0], gv[d], rtol=1e-6)

    # 2) Matches the independent numpy recursive-doubling oracle, compared
    #    as dense vectors (slot order may differ on magnitude ties).
    ov, oi = np_gtopk(list(vals), list(idxs), K, N)
    want = np.zeros(N + 1, np.float32)
    np.add.at(want, oi[0], ov[0])
    got = np.zeros(N + 1, np.float32)
    np.add.at(got, gi[0], gv[0])
    np.testing.assert_allclose(got[:N], want[:N], rtol=1e-5, atol=1e-6)


def test_gtopk_exact_when_k_covers_union(rng):
    # With k >= total distinct indices the hierarchy is lossless: result must
    # equal the exact dense sum of all contributions.
    p, k, n = 8, 32, 64
    vals = np.zeros((p, k), np.float32)
    idxs = np.full((p, k), n, np.int32)
    dense = np.zeros(n, np.float64)
    for d in range(p):
        i = rng.choice(16, size=4, replace=False).astype(np.int32)  # overlap heavy
        v = rng.standard_normal(4).astype(np.float32)
        idxs[d, :4] = i
        vals[d, :4] = v
        np.add.at(dense, i, v)

    def body(v, i):
        gv, gi = gtopk_allreduce(v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p)
        return gv[None], gi[None]

    mesh = make_mesh(p)
    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    got = np.zeros(n + 1, np.float32)
    np.add.at(got, np.asarray(gi[0]), np.asarray(gv[0]))
    np.testing.assert_allclose(got[:n], dense.astype(np.float32), rtol=1e-5, atol=1e-6)


def np_gtopk_ragged(local_vals, local_idx, k, n):
    """Numpy simulator of the masked-hypercube ragged-P tree (independent
    oracle for collectives._merge_tree at non-pow2 q): fold extras into
    [0, e), hypercube over the 2^m block, broadcast back to extras."""
    p = len(local_vals)
    m = 1 << (p.bit_length() - 1)
    e = p - m
    vals = [v.copy() for v in local_vals]
    idxs = [i.copy() for i in local_idx]
    for t in range(e):
        vals[t], idxs[t] = np_merge(
            vals[t], idxs[t], vals[m + t], idxs[m + t], k, n)
    sub_v, sub_i = np_gtopk(vals[:m], idxs[:m], k, n)
    out_v = [sub_v[d % m] for d in range(p)]
    out_i = [sub_i[d % m] for d in range(p)]
    return out_v, out_i


def _run_gtopk(vals, idxs, p, k, n):
    mesh = make_mesh(p)

    def body(v, i):
        gv, gi = gtopk_allreduce(
            v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p)
        return gv[None], gi[None]

    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    return np.asarray(gv), np.asarray(gi)


@pytest.mark.parametrize("p", [3, 5, 6, 7])
def test_gtopk_ragged_tree_matches_oracle(rng, p):
    """Non-pow2 P runs the masked hypercube in-tree (round-4 verdict
    missing #5 — was an O(kP) allgather fallback). Checks: bit-identical
    on every rank INCLUDING the folded extras, and equal to the
    independent numpy simulator of the same fold/hypercube/unfold tree."""
    k, n = 5, 100
    vals, idxs = make_local_sets(rng, p=p, k=k, n=n)
    gv, gi = _run_gtopk(vals, idxs, p, k, n)
    for d in range(1, p):
        np.testing.assert_array_equal(gi[0], gi[d])
        np.testing.assert_array_equal(gv[0], gv[d])
    ov, oi = np_gtopk_ragged(list(vals), list(idxs), k, n)
    want = np.zeros(n + 1, np.float32)
    np.add.at(want, oi[0], ov[0])
    got = np.zeros(n + 1, np.float32)
    np.add.at(got, gi[0], gv[0])
    np.testing.assert_allclose(got[:n], want[:n], rtol=1e-5, atol=1e-6)


def test_gtopk_ragged_exact_when_k_covers_union(rng):
    """p=6 with k covering every distinct index: the ragged tree must be
    lossless, i.e. reproduce the exact dense sum — the semantics anchor
    that survives any tree shape."""
    p, k, n = 6, 32, 64
    vals = np.zeros((p, k), np.float32)
    idxs = np.full((p, k), n, np.int32)
    dense = np.zeros(n, np.float64)
    for d in range(p):
        i = rng.choice(16, size=4, replace=False).astype(np.int32)
        v = rng.standard_normal(4).astype(np.float32)
        idxs[d, :4] = i
        vals[d, :4] = v
        np.add.at(dense, i, v)
    gv, gi = _run_gtopk(vals, idxs, p, k, n)
    got = np.zeros(n + 1, np.float32)
    np.add.at(got, gi[0], gv[0])
    np.testing.assert_allclose(got[:n], dense.astype(np.float32),
                               rtol=1e-5, atol=1e-6)


def test_gtopk_ragged_p12_subprocess(tmp_path):
    """P=12 (the verdict's named size — above this suite's 8-device mesh):
    run the same oracle check in a child interpreter forced to 12 virtual
    CPU devices. One extra jax init (~30 s cold, cached after)."""
    import os
    import subprocess
    import sys
    import textwrap

    script = tmp_path / "ragged12.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        from gtopkssgd_tpu.utils import force_cpu_mesh
        force_cpu_mesh(12)
        # conftest's persistent compile cache does not reach a child
        # interpreter; opt in so re-runs skip the 12-way XLA compile.
        from gtopkssgd_tpu.utils import enable_compilation_cache
        enable_compilation_cache()
        import sys
        sys.path.insert(0, %r)
        from test_collectives import (
            _run_gtopk, make_local_sets, np_gtopk_ragged)
        rng = np.random.default_rng(7)
        p, k, n = 12, 5, 100
        vals, idxs = make_local_sets(rng, p=p, k=k, n=n)
        gv, gi = _run_gtopk(vals, idxs, p, k, n)
        for d in range(1, p):
            np.testing.assert_array_equal(gi[0], gi[d])
        ov, oi = np_gtopk_ragged(list(vals), list(idxs), k, n)
        want = np.zeros(n + 1, np.float32)
        np.add.at(want, oi[0], ov[0])
        got = np.zeros(n + 1, np.float32)
        np.add.at(got, gi[0], gv[0])
        np.testing.assert_allclose(got[:n], want[:n], rtol=1e-5, atol=1e-6)
        print("OK-P12")
    """ % os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK-P12" in out.stdout


def test_topk_allgather_union(rng):
    vals, idxs = make_local_sets(rng)
    dense = np.zeros(N, np.float64)
    for d in range(PDEV):
        np.add.at(dense, idxs[d], vals[d])

    def body(v, i):
        out = topk_allgather(v[0], i[0], k=K, n=N, axis_name="dp", axis_size=PDEV)
        return out[None]

    mesh = make_mesh(PDEV)
    out = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp")
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    out = np.asarray(out)
    for d in range(PDEV):
        np.testing.assert_allclose(
            out[d], dense.astype(np.float32), rtol=1e-5, atol=1e-6
        )


def test_dense_allreduce(rng):
    x = rng.standard_normal((PDEV, 17)).astype(np.float32)

    def body(v):
        return dense_allreduce(v, axis_name="dp")

    mesh = make_mesh(PDEV)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(jnp.asarray(x))
    want = x.sum(axis=0)
    for d in range(PDEV):
        np.testing.assert_allclose(np.asarray(out)[d], want, rtol=1e-5)


def test_comm_model():
    n, k = 10_000_000, 10_000
    assert comm_bytes_per_step("gtopk", n, k, 32) == 8 * k * 5
    # ragged P: masked tree = fold + hypercube over 2^floor(log2 P) + unfold
    assert comm_bytes_per_step("gtopk", n, k, 6) == 8 * k * (2 + 2)
    assert comm_bytes_per_step("gtopk", n, k, 12) == 8 * k * (3 + 2)
    # hier with a ragged slice count rides the same masked tree across DCN
    assert comm_bytes_per_step("gtopk_hier", n, k, 12, ici_size=4) == (
        4 * n + 8 * k * (1 + 2))
    assert comm_bytes_per_step("allgather", n, k, 32) == 8 * k * 32
    assert comm_bytes_per_step("dense", n, k, 32) == 4 * n
    assert comm_bytes_per_step("gtopk", n, k, 32) < comm_bytes_per_step(
        "allgather", n, k, 32
    ) < comm_bytes_per_step("dense", n, k, 32)
