"""Sparse collectives vs numpy oracles on an 8-device virtual mesh.

This is exactly what the reference could not do without `mpirun -np 8`:
run real 8-way SPMD collectives in one test process.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    dense_allreduce,
    gtopk_allreduce,
    make_mesh,
    topk_allgather,
)

PDEV = 8
K = 8
N = 300


def np_topk(x, k):
    idx = np.argsort(-np.abs(x), kind="stable")[:k]
    return x[idx].astype(np.float32), idx.astype(np.int32)


def np_merge(va, ia, vb, ib, k, n):
    dense = np.zeros(n + 1, np.float64)
    np.add.at(dense, ia, va)
    np.add.at(dense, ib, vb)
    dense[n] = 0.0
    v, i = np_topk(dense[:n], k)
    i = np.where(v == 0, n, i).astype(np.int32)
    return v, i


def np_gtopk(local_vals, local_idx, k, n):
    """Numpy simulator of recursive-doubling gtopk (independent oracle)."""
    p = len(local_vals)
    vals = [v.copy() for v in local_vals]
    idxs = [i.copy() for i in local_idx]
    r = 1
    while r < p:
        nv, ni = [None] * p, [None] * p
        for d in range(p):
            q = d ^ r
            nv[d], ni[d] = np_merge(vals[d], idxs[d], vals[q], idxs[q], k, n)
        vals, idxs = nv, ni
        r <<= 1
    return vals, idxs


def make_local_sets(rng, p=PDEV, k=K, n=N):
    vals = np.zeros((p, k), np.float32)
    idxs = np.zeros((p, k), np.int32)
    for d in range(p):
        i = rng.choice(n, size=k, replace=False).astype(np.int32)
        v = rng.standard_normal(k).astype(np.float32)
        vals[d], idxs[d] = v, i
    return vals, idxs


def test_gtopk_matches_numpy_simulator(rng):
    vals, idxs = make_local_sets(rng)

    def body(v, i):
        gv, gi = gtopk_allreduce(
            v[0], i[0], k=K, n=N, axis_name="dp", axis_size=PDEV
        )
        return gv[None], gi[None]

    mesh = make_mesh(PDEV)
    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    gv, gi = np.asarray(gv), np.asarray(gi)

    # 1) Identical result on every device (the SPMD-symmetry claim).
    for d in range(1, PDEV):
        np.testing.assert_array_equal(gi[0], gi[d])
        np.testing.assert_allclose(gv[0], gv[d], rtol=1e-6)

    # 2) Matches the independent numpy recursive-doubling oracle, compared
    #    as dense vectors (slot order may differ on magnitude ties).
    ov, oi = np_gtopk(list(vals), list(idxs), K, N)
    want = np.zeros(N + 1, np.float32)
    np.add.at(want, oi[0], ov[0])
    got = np.zeros(N + 1, np.float32)
    np.add.at(got, gi[0], gv[0])
    np.testing.assert_allclose(got[:N], want[:N], rtol=1e-5, atol=1e-6)


def test_gtopk_exact_when_k_covers_union(rng):
    # With k >= total distinct indices the hierarchy is lossless: result must
    # equal the exact dense sum of all contributions.
    p, k, n = 8, 32, 64
    vals = np.zeros((p, k), np.float32)
    idxs = np.full((p, k), n, np.int32)
    dense = np.zeros(n, np.float64)
    for d in range(p):
        i = rng.choice(16, size=4, replace=False).astype(np.int32)  # overlap heavy
        v = rng.standard_normal(4).astype(np.float32)
        idxs[d, :4] = i
        vals[d, :4] = v
        np.add.at(dense, i, v)

    def body(v, i):
        gv, gi = gtopk_allreduce(v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p)
        return gv[None], gi[None]

    mesh = make_mesh(p)
    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    got = np.zeros(n + 1, np.float32)
    np.add.at(got, np.asarray(gi[0]), np.asarray(gv[0]))
    np.testing.assert_allclose(got[:n], dense.astype(np.float32), rtol=1e-5, atol=1e-6)


def test_gtopk_non_pow2_fallback(rng):
    # axis_size=6 -> allgather+reselect path; oracle = exact topk of sparse sum.
    p, k, n = 6, 5, 100
    vals = np.zeros((p, k), np.float32)
    idxs = np.zeros((p, k), np.int32)
    dense = np.zeros(n, np.float64)
    for d in range(p):
        i = rng.choice(n, size=k, replace=False).astype(np.int32)
        v = rng.standard_normal(k).astype(np.float32)
        vals[d], idxs[d] = v, i
        np.add.at(dense, i, v)

    mesh = make_mesh(p)

    def body(v, i):
        gv, gi = gtopk_allreduce(v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p)
        return gv[None], gi[None]

    gv, gi = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    got = np.zeros(n + 1, np.float32)
    np.add.at(got, np.asarray(gi[0]), np.asarray(gv[0]))
    ov, oi = np_topk(dense.astype(np.float32), k)
    want = np.zeros(n, np.float32)
    want[oi] = ov
    np.testing.assert_allclose(got[:n], want, rtol=1e-5, atol=1e-6)


def test_topk_allgather_union(rng):
    vals, idxs = make_local_sets(rng)
    dense = np.zeros(N, np.float64)
    for d in range(PDEV):
        np.add.at(dense, idxs[d], vals[d])

    def body(v, i):
        out = topk_allgather(v[0], i[0], k=K, n=N, axis_name="dp", axis_size=PDEV)
        return out[None]

    mesh = make_mesh(PDEV)
    out = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp")
        )
    )(jnp.asarray(vals), jnp.asarray(idxs))
    out = np.asarray(out)
    for d in range(PDEV):
        np.testing.assert_allclose(
            out[d], dense.astype(np.float32), rtol=1e-5, atol=1e-6
        )


def test_dense_allreduce(rng):
    x = rng.standard_normal((PDEV, 17)).astype(np.float32)

    def body(v):
        return dense_allreduce(v, axis_name="dp")

    mesh = make_mesh(PDEV)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(jnp.asarray(x))
    want = x.sum(axis=0)
    for d in range(PDEV):
        np.testing.assert_allclose(np.asarray(out)[d], want, rtol=1e-5)


def test_comm_model():
    n, k = 10_000_000, 10_000
    assert comm_bytes_per_step("gtopk", n, k, 32) == 8 * k * 5
    assert comm_bytes_per_step("allgather", n, k, 32) == 8 * k * 32
    assert comm_bytes_per_step("dense", n, k, 32) == 4 * n
    assert comm_bytes_per_step("gtopk", n, k, 32) < comm_bytes_per_step(
        "allgather", n, k, 32
    ) < comm_bytes_per_step("dense", n, k, 32)
