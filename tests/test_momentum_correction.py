"""DGC momentum correction (`momentum_correction=True`): unit invariants.

TPU extension (arXiv:1712.01887 §3.1-3.2 — not reference parity: the
reference runs torch momentum-SGD on the sparse GLOBAL update). Velocity
``u = m*u + g`` accumulates locally BEFORE selection, the accumulated
velocity ``v += u`` is what top-k reads, and momentum factor masking
zeroes u at the LOCAL selection (while the error-feedback repair returns
a globally-rejected pick's VALUE to v — the measured semantics; see
test_correction_masks_at_local_selection). Pinned here:

  * 3-step numpy oracle of the v/u recursions + masking at p=1;
  * the dense warm-up phase is ALGEBRAICALLY classic momentum-SGD on the
    mean gradient (mean is linear in u) — bit-comparable to the dense
    baseline until the phase switch, for flat and layerwise alike;
  * 8-way replica consistency + convergence at low density;
  * construction-time rejection of meaningless combinations;
  * Trainer integration: the {"v","u"} residual dict rides the per-device
    plumbing and survives a checkpoint round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import pytest

from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.parallel import make_mesh

PDEV = 8


def small_params():
    return {"w": jnp.zeros((32,)), "b": jnp.zeros((5,))}


def test_correction_p1_matches_dgc_oracle():
    n, density, m = 37, 0.2, 0.5
    params = small_params()
    tx = gtopk_sgd(1.0, momentum=m, compression="gtopk", density=density,
                   axis_name=None, momentum_correction=True)
    state = tx.init(params)
    assert set(state.residual.keys()) == {"v", "u"}

    rng = np.random.default_rng(0)
    v, u = np.zeros(n), np.zeros(n)
    k = int(np.ceil(density * n))
    upd = jax.jit(tx.update)
    for _ in range(3):
        g = rng.standard_normal(n).astype(np.float32)
        grads = {"w": jnp.asarray(g[:32]), "b": jnp.asarray(g[32:])}
        updates, state = upd(grads, state, params)
        # tree.flatten order is b, w
        gg = np.concatenate([g[32:], g[:32]])
        u = m * u + gg
        acc = v + u
        sel = np.argsort(-np.abs(acc))[:k]
        applied = np.zeros(n)
        applied[sel] = acc[sel]
        v = acc.copy()
        v[sel] = 0.0
        u[sel] = 0.0  # momentum factor masking
        got = -np.concatenate(
            [np.asarray(updates["b"]), np.asarray(updates["w"])])
        np.testing.assert_allclose(got, applied, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(state.residual["v"]), v,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(state.residual["u"]), u,
                                   rtol=1e-5, atol=1e-6)


def _spmd_step(tx, mesh):
    def step(params, state, grads):
        grads = jax.tree.map(lambda g: g[0], grads)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    return jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P()), check_vma=False))


@pytest.mark.parametrize("mode", ["gtopk", "gtopk_layerwise"])
def test_correction_warmup_phase_is_classic_momentum(mode):
    """mean_i(m*u_i + g_i) == m*mean(u) + mean(g): the correction's dense
    warm-up phase IS momentum-SGD on the mean gradient, so it must track
    the dense baseline until the phase switch and diverge after."""
    params = small_params()
    mesh = make_mesh(PDEV)
    rng = np.random.default_rng(4)
    grads = jax.tree.map(
        lambda p: jnp.asarray(
            rng.standard_normal((PDEV,) + p.shape), jnp.float32), params)

    tx_c = gtopk_sgd(0.1, momentum=0.9, compression=mode, density=0.05,
                     axis_name="dp", axis_size=PDEV, warmup_dense_steps=2,
                     momentum_correction=True)
    tx_d = gtopk_sgd(0.1, momentum=0.9, compression="dense",
                     axis_name="dp", axis_size=PDEV)
    s_c = jax.jit(tx_c.init)(params)
    s_d = jax.jit(tx_d.init)(params)
    step_c, step_d = _spmd_step(tx_c, mesh), _spmd_step(tx_d, mesh)
    p_c = p_d = params
    for i in range(3):
        p_c, s_c = step_c(p_c, s_c, grads)
        p_d, s_d = step_d(p_d, s_d, grads)
        same = all(
            np.allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
            for a, b in zip(jax.tree.leaves(p_c), jax.tree.leaves(p_d)))
        assert same == (i < 2), f"step {i}: warm-up phase mismatch"


def test_correction_spmd_converges_replicated():
    n, per_dev = 32, 16
    rng = np.random.default_rng(3)
    w_true = rng.standard_normal(n).astype(np.float32)
    X = rng.standard_normal((PDEV, per_dev, n)).astype(np.float32)
    y = X @ w_true

    params = {"w": jnp.zeros((n,))}
    mesh = make_mesh(PDEV)
    tx = gtopk_sgd(0.03, momentum=0.5, compression="gtopk", density=0.1,
                   axis_name="dp", axis_size=PDEV, momentum_correction=True)
    state = jax.jit(tx.init)(params)

    def step(params, state, Xs, ys):
        def loss(p):
            r = Xs[0] @ p["w"] - ys[0]
            return 0.5 * jnp.mean(r * r)
        grads = jax.grad(loss)(params)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    smapped = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P()), check_vma=False))

    def global_loss(params):
        r = X.reshape(-1, n) @ np.asarray(params["w"]) - y.reshape(-1)
        return 0.5 * float(np.mean(r * r))

    l0 = global_loss(params)
    for _ in range(60):
        params, state = smapped(params, state, jnp.asarray(X), jnp.asarray(y))
    assert global_loss(params) < 0.3 * l0
    for leaf in jax.tree.leaves(params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def _mask_semantics_fixture():
    """8-way construction with a known global accept set: device d's
    gradient peaks at coords {2d, 2d+1} with magnitude growing in d, so
    the global top-2 is {14, 15} (device 7's picks) and every other
    device's picks are globally rejected. Tie-free by construction."""
    n, k_density = 16, 2 / 16
    params = {"w": jnp.zeros((n,))}
    mesh = make_mesh(PDEV)
    g = np.zeros((PDEV, n), np.float32)
    for d in range(PDEV):
        g[d, 2 * d] = 10.0 + 2 * d
        g[d, 2 * d + 1] = 9.0 + 2 * d
    return n, k_density, params, mesh, g


def _run_one_masked_step(params, mesh, g, tx):
    state = jax.jit(tx.init)(params)

    def step(grads, state):
        _, s2 = tx.update({"w": grads[0]}, state, params)
        return s2.residual["v"][None], s2.residual["u"][None]

    v_all, u_all = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P()),
        out_specs=(P("dp"), P("dp")), check_vma=False))(
            jnp.asarray(g), state)
    return np.asarray(v_all), np.asarray(u_all)


def test_correction_masks_at_local_selection():
    """Pins the SHIPPED masking semantics (optimizer.py, measured design
    decision): the momentum factor mask follows the LOCAL selection, not
    the global accept set. A locally-picked but globally-rejected
    coordinate keeps its VALUE in the residual v (error-feedback repair)
    while its velocity u stays masked — restoring u as well double-tracks
    the same mass and diverges (restore_rejected_u_ablation entry of
    benchmarks/results/warmup_ab_cpu_mesh8.json)."""
    n, k_density, params, mesh, g = _mask_semantics_fixture()
    tx = gtopk_sgd(0.1, momentum=0.9, compression="gtopk",
                   density=k_density, axis_name="dp", axis_size=PDEV,
                   momentum_correction=True)
    v_all, u_all = _run_one_masked_step(params, mesh, g, tx)
    # device 7's picks {14, 15} ARE the global set: delivered, so both
    # the velocity and the residual slot are consumed.
    assert u_all[7, 14] == 0.0 and u_all[7, 15] == 0.0
    assert v_all[7, 14] == 0.0 and v_all[7, 15] == 0.0
    # device 0's picks {0, 1} were globally REJECTED: the repair returns
    # their VALUE to v (u = m*0 + g = g on step 1, and v selects from u),
    # but u is masked at the local selection and stays masked.
    np.testing.assert_allclose(v_all[0, :2], g[0, :2], rtol=1e-6)
    np.testing.assert_array_equal(u_all[0, :2], np.zeros(2))
    # un-picked coordinates are untouched everywhere (no stray masking):
    # device 0 never selected {14, 15} and contributed 0 mass there.
    assert v_all[0, 14] == 0.0 and u_all[0, 14] == 0.0


def test_correction_restore_u_ablation_flag_restores_rejected_velocity():
    """The _restore_rejected_u ablation knob (used to generate the
    warmup_ab ablation entry) implements the OTHER semantics — velocity
    survives for globally-rejected picks — so the A/B between the two is
    reproducible. Also pins that the knob is correction-only."""
    n, k_density, params, mesh, g = _mask_semantics_fixture()
    tx = gtopk_sgd(0.1, momentum=0.9, compression="gtopk",
                   density=k_density, axis_name="dp", axis_size=PDEV,
                   momentum_correction=True, _restore_rejected_u=True)
    v_all, u_all = _run_one_masked_step(params, mesh, g, tx)
    # globally-accepted picks (device 7) are still fully consumed
    assert u_all[7, 14] == 0.0 and u_all[7, 15] == 0.0
    assert v_all[7, 14] == 0.0 and v_all[7, 15] == 0.0
    # globally-rejected picks (device 0) keep BOTH value and velocity
    np.testing.assert_allclose(v_all[0, :2], g[0, :2], rtol=1e-6)
    np.testing.assert_allclose(u_all[0, :2], g[0, :2], rtol=1e-6)

    with pytest.raises(ValueError, match="ablation"):
        gtopk_sgd(0.1, momentum=0.9, compression="gtopk",
                  density=k_density, axis_name=None,
                  _restore_rejected_u=True)


def test_correction_rejects_meaningless_combinations():
    for kw, msg in (
        (dict(compression="dense"), "sparse"),
        (dict(compression="gtopk", momentum=0.0), "momentum"),
        (dict(compression="gtopk", nesterov=True), "nesterov"),
    ):
        with pytest.raises(ValueError, match=msg):
            gtopk_sgd(0.1, momentum=kw.pop("momentum", 0.9),
                      axis_name=None, momentum_correction=True, **kw)


def test_correction_trainer_checkpoint_roundtrip(tmp_path):
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    cfg = TrainConfig(
        dnn="resnet20", batch_size=4, nworkers=4, log_interval=5,
        eval_batches=2, max_epochs=1, compression="gtopk", density=0.05,
        momentum_correction=True, out_dir=str(tmp_path / "run"),
    )
    t = Trainer(cfg)
    t.train(5)
    res = t.state.opt_state.residual
    assert set(res.keys()) == {"v", "u"}
    v, u = np.asarray(res["v"]), np.asarray(res["u"])
    assert v.shape[0] == 4 and u.shape == v.shape
    assert (u != 0).any() and (v != 0).any()
    t.save()
    t2 = Trainer(cfg)
    assert t2.restore()
    np.testing.assert_array_equal(
        np.asarray(t2.state.opt_state.residual["v"]), v)
    np.testing.assert_array_equal(
        np.asarray(t2.state.opt_state.residual["u"]), u)
    t2.train(2)
    assert int(t2.state.step) == 7


def test_correction_layerwise_combination_warns():
    """The layerwise x correction combination is measured worse than
    either parent and the round-3 masking ablations rule out a semantics
    fix (warmup_ab artifact: 0.250 combo vs 0.734/0.281 alone; restore-u
    collapses it to 0.094) — construction warns, citing the artifact."""
    with pytest.warns(UserWarning, match="warmup_ab"):
        gtopk_sgd(0.1, momentum=0.9, compression="gtopk_layerwise",
                  density=0.01, axis_name=None, momentum_correction=True)


def test_spike_recovery_via_error_feedback():
    """Regression pin for the observed in-vivo self-heal (round-4 VGG CPU
    probe, convergence_vgg16_cpu_mesh2.jsonl step 40->160: the corr arm
    blew up to loss 27.7 after a gradient spike and error feedback pulled
    it back to dense tracking). Synthetic reproduction: gtopk+corr SGD on
    a least-squares objective; one step receives a 100x gradient spike.
    Asserts (a) the spike visibly damages the iterate, (b) the run
    re-converges to match the clean run's loss within a bounded number of
    steps — the repair/EF path absorbing the injected mass rather than
    replaying it forever.
    """
    n, density, steps, spike_at = 256, 0.1, 200, 40
    rng = np.random.default_rng(3)
    target = rng.standard_normal(n).astype(np.float32)
    # the poison is a RANDOM direction (a corrupted batch), not a scaled
    # true gradient — on a deterministic quadratic a same-direction spike
    # is merely a beneficial overshoot
    spike_vec = 100.0 * np.random.default_rng(9).standard_normal(
        n).astype(np.float32)

    def run(spike: bool):
        params = {"w": jnp.zeros((n,))}
        # lr inside the EF-delay stability region: with density 0.1 a
        # coordinate waits ~10 steps between selections, and momentum
        # amplifies the batched replay by 1/(1-m) — lr*2*10/(1-0.9) must
        # stay < 2 or the CLEAN run diverges (observed at lr=0.05)
        tx = gtopk_sgd(0.003, momentum=0.9, compression="gtopk",
                       density=density, axis_name=None,
                       momentum_correction=True)
        state = tx.init(params)
        upd = jax.jit(tx.update)
        losses = []
        for t in range(steps):
            g = 2.0 * (np.asarray(params["w"]) - target)
            if spike and t == spike_at:
                g = g + spike_vec
            updates, state = upd({"w": jnp.asarray(g)}, state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(((np.asarray(params["w"]) - target) ** 2)
                                .mean()))
        return losses

    clean = run(False)
    spiked = run(True)
    # (a) the spike did real damage in the window after injection
    window = range(spike_at + 1, spike_at + 30)
    assert max(spiked[i] / clean[i] for i in window) > 2.0
    # (b) recovery: by the end the spiked run tracks the clean run again
    assert spiked[-1] < 2.0 * clean[-1] + 1e-4, (spiked[-1], clean[-1])
    # (c) the worst post-spike loss occurs near the spike, not at the end
    worst = max(range(spike_at, steps), key=lambda i: spiked[i])
    assert worst < spike_at + 30
