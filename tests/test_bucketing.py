"""Byte-balanced gradient bucketing (`--buckets`): unit invariants.

The bucketed layerwise path (parallel/bucketing.py + the optimizer's
bucketed branch) partitions the param leaves into B contiguous buckets,
concatenates each bucket's (grad, residual) leaves, and runs ONE fused
selection + ONE codec-framed merge per bucket. These tests pin the
degenerate ends exactly and the DP against brute force:

  * grammar: parse_buckets accepts concat|leaf|auto|int and rejects junk;
  * DP optimality: optimal_boundaries matches exhaustive search over all
    contiguous partitions (pinned B and auto), on random leaf lists;
  * manifest round-trip: BucketPlan -> to_manifest -> JSON ->
    from_manifest preserves the (n_b, k_b) pricing structure;
  * B=L at p=1 bit-equals the historical concat layerwise (selection is
    per-leaf in both; no merge exists at p=1);
  * B=1 bit-equals flat gtopk at p in {2,3,5} — updates AND residuals —
    including under the lossy int8 codec (error-feedback scatter-back
    exactness);
  * B=L at p>1 bit-equals one independent flat gtopk pipeline per leaf;
  * pinned B=2 bit-equals two independent flat pipelines over the
    bucket-concatenated arrays (the scatter-back is exactly a reshape);
  * collective_count telemetry: leaf counts L merges, auto at the
    committed ~22 ms alpha collapses to B=1.
"""

import itertools
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.parallel import BucketPlan, make_mesh, parse_buckets
from gtopkssgd_tpu.parallel import bucketing


def tree_params():
    return {
        "conv": jnp.zeros((4, 8)),   # 32 elems
        "bias": jnp.zeros((5,)),     # 5 elems
        "bn": jnp.zeros((2, 3)),     # 6 elems
        "head": jnp.zeros((3, 4)),   # 12 elems
    }


def rand_grads(rng, params, lead=()):
    return jax.tree.map(
        lambda p: jnp.asarray(
            rng.standard_normal(lead + p.shape), jnp.float32), params)


# ------------------------------------------------------------------ grammar

def test_parse_buckets_grammar():
    assert parse_buckets("concat") == "concat"
    assert parse_buckets("leaf") == "leaf"
    assert parse_buckets("auto") == "auto"
    assert parse_buckets("4") == 4
    assert parse_buckets(3) == 3
    for bad in ("0", "-1", "tree", "", 0, -2, 1.5, True, None):
        with pytest.raises((ValueError, TypeError)):
            parse_buckets(bad)


def test_non_layerwise_mode_rejects_buckets():
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", density=0.1, buckets="auto")
    # concat is the no-op default and composes with every mode
    gtopk_sgd(0.1, compression="gtopk", density=0.1, buckets="concat")


# ----------------------------------------------------------- DP vs brute

def _brute_force(sizes, density, n_buckets, **kw):
    """Cheapest contiguous partition by exhaustive enumeration."""
    L = len(sizes)
    best = (np.inf, None)
    rng = (range(n_buckets - 1, n_buckets) if n_buckets is not None
           else range(0, L))
    for b_minus_1 in rng:
        for cuts in itertools.combinations(range(1, L), b_minus_1):
            bounds = (0,) + cuts + (L,)
            plan = BucketPlan(
                bounds, tuple(sizes),
                tuple(bucketing.k_for_density(sum(sizes[lo:hi]), density)
                      for lo, hi in zip(bounds, bounds[1:])),
                spec="auto")
            cost = bucketing.partition_cost_ms(plan, **kw)
            if cost < best[0] - 1e-12:
                best = (cost, bounds)
    return best


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n_buckets", [None, 2, 3])
def test_dp_matches_brute_force(seed, n_buckets):
    rng = np.random.default_rng(seed)
    L = int(rng.integers(3, 8))
    sizes = tuple(int(s) for s in rng.integers(4, 400, size=L))
    density = 0.05
    kw = dict(p=int(rng.integers(2, 6)), codec="int8:16", schedule=None,
              alpha_ms=float(rng.uniform(0.01, 5.0)), beta_gbps=0.6,
              mode="gtopk_layerwise")
    bounds = bucketing.optimal_boundaries(
        sizes, density, n_buckets=n_buckets, **kw)
    plan = BucketPlan(
        bounds, sizes,
        tuple(bucketing.k_for_density(sum(sizes[lo:hi]), density)
              for lo, hi in zip(bounds, bounds[1:])), spec="auto")
    got = bucketing.partition_cost_ms(plan, **kw)
    want, _ = _brute_force(sizes, density, n_buckets, **kw)
    assert got == pytest.approx(want, rel=1e-9)
    if n_buckets is not None:
        assert len(bounds) == min(n_buckets, L) + 1


# ------------------------------------------------------ manifest roundtrip

def test_manifest_roundtrip():
    sizes = (32, 5, 6, 12)
    plan = bucketing.plan_buckets(sizes, 0.125, buckets=2, p=4,
                                  alpha_ms=1.0, beta_gbps=0.6)
    man = json.loads(json.dumps(plan.to_manifest()))
    back = BucketPlan.from_manifest(man)
    assert back is not None
    assert back.pairs() == plan.pairs()
    assert back.n_buckets == plan.n_buckets
    assert back.k_total == plan.k_total
    # non-bucketed manifests reconstruct to None
    assert BucketPlan.from_manifest({"buckets": "concat"}) is None
    assert BucketPlan.from_manifest({}) is None


# -------------------------------------------------------- degenerate ends

def _p1_run(buckets, steps=3, codec="fp32"):
    params = tree_params()
    tx = gtopk_sgd(0.5, momentum=0.9, compression="gtopk_layerwise",
                   density=0.125, buckets=buckets, wire_codec=codec,
                   axis_name=None)
    state = jax.jit(tx.init)(params)
    upd = jax.jit(tx.update)
    rng = np.random.default_rng(7)
    outs = []
    for _ in range(steps):
        grads = rand_grads(rng, params)
        updates, state = upd(grads, state, params)
        outs.append(updates)
    return outs, state


def test_leaf_p1_bit_equals_concat():
    # At p=1 both paths select per leaf and never merge, so B=L must be
    # BIT-identical to the historical concat layerwise — updates and
    # error-feedback residuals alike.
    u_leaf, s_leaf = _p1_run("leaf")
    u_cat, s_cat = _p1_run("concat")
    for a, b in zip(u_leaf, u_cat):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for ra, rb in zip(s_leaf.residual, s_cat.residual):
        np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))


def _spmd_run(tx, params, mesh, p, steps, seed):
    def step(params, state, grads):
        grads = jax.tree.map(lambda g: g[0], grads)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), updates, state

    smapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))
    state = jax.jit(tx.init)(params)
    rng = np.random.default_rng(seed)
    ups = []
    for _ in range(steps):
        grads = rand_grads(rng, params, lead=(p,))
        params, updates, state = smapped(params, state, grads)
        ups.append(updates)
    return ups, state


@pytest.mark.parametrize("p", [2, 3, 5])
@pytest.mark.parametrize("codec", ["fp32", "int8:16"])
def test_b1_bit_equals_flat_gtopk(p, codec):
    # B=1 concatenates every leaf into one buffer and runs the flat
    # pipeline verbatim: select_topk over grad+residual, identical
    # residual/update masking, same codec fold, one sparse_allreduce.
    # ravel order == concat of per-leaf ravels, so updates AND residuals
    # are bit-identical to compression='gtopk' — including the int8
    # codec's error scatter-back into the residual.
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, wire_codec=codec,
              axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=1, **kw)
    tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
    u_b, s_b = _spmd_run(tx_b, params, mesh, p, 3, seed=11)
    u_f, s_f = _spmd_run(tx_f, params, mesh, p, 3, seed=11)
    for a, b in zip(u_b, u_f):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # bucketed residual is per-leaf; flat's is one [N] buffer in the
    # same tree-flatten order
    res_b = np.concatenate([np.asarray(r).ravel() for r in s_b.residual])
    res_f = np.concatenate([np.asarray(r).ravel() for r in
                            jax.tree.leaves(s_f.residual)])
    np.testing.assert_array_equal(res_b, res_f)


@pytest.mark.parametrize("p", [2, 3])
def test_leaf_bit_equals_per_leaf_flat_pipelines(p):
    # B=L runs the flat pipeline once per leaf over that leaf's own index
    # space — so it must bit-equal L INDEPENDENT flat-gtopk optimizers,
    # one per single-leaf pytree.
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets="leaf",
                     **kw)
    u_b, _ = _spmd_run(tx_b, params, mesh, p, 2, seed=13)
    for name in params:
        sub = {name: params[name]}
        tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
        # same grads: regenerate the full-tree stream and slice the leaf
        rng = np.random.default_rng(13)
        state = jax.jit(tx_f.init)(sub)
        sub_p = sub
        smapped = None
        for step_i in range(2):
            grads = rand_grads(rng, params, lead=(p,))
            sub_g = {name: grads[name]}
            if smapped is None:
                def stepf(params, state, grads):
                    grads = jax.tree.map(lambda g: g[0], grads)
                    updates, state = tx_f.update(grads, state, params)
                    return (optax.apply_updates(params, updates),
                            updates, state)
                smapped = jax.jit(jax.shard_map(
                    stepf, mesh=mesh,
                    in_specs=(P(), P(), P("dp")),
                    out_specs=(P(), P(), P()),
                    check_vma=False,
                ))
            sub_p, upd, state = smapped(sub_p, state, sub_g)
            np.testing.assert_array_equal(
                np.asarray(upd[name]), np.asarray(u_b[step_i][name]))


def test_pinned_b2_bit_equals_two_flat_pipelines():
    # A pinned B=2 concatenates each bucket's leaves; running the flat
    # pipeline over each bucket's own concatenated array must reproduce
    # it exactly (the leaf scatter-back is a pure reshape).
    p = 2
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=2, **kw)
    u_b, _ = _spmd_run(tx_b, params, mesh, p, 2, seed=17)

    names = sorted(params)  # jax flattens dicts in sorted-key order
    sizes = tuple(int(params[n].size) for n in names)
    plan = bucketing.plan_buckets(sizes, 0.125, buckets=2, p=p)
    assert plan.n_buckets == 2

    rng = np.random.default_rng(17)
    grads_stream = [rand_grads(rng, params, lead=(p,)) for _ in range(2)]
    for b in range(2):
        lo, hi = plan.leaf_range(b)
        bnames = names[lo:hi]
        sub = {"x": jnp.concatenate(
            [params[n].reshape(-1) for n in bnames])}
        tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
        state = jax.jit(tx_f.init)(sub)

        def stepf(params, state, grads):
            grads = jax.tree.map(lambda g: g[0], grads)
            updates, state = tx_f.update(grads, state, params)
            return optax.apply_updates(params, updates), updates, state

        smapped = jax.jit(jax.shard_map(
            stepf, mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))
        sub_p = sub
        for step_i, grads in enumerate(grads_stream):
            sub_g = {"x": jnp.concatenate(
                [grads[n].reshape(p, -1) for n in bnames], axis=1)}
            sub_p, upd, state = smapped(sub_p, state, sub_g)
            want = np.concatenate(
                [np.asarray(u_b[step_i][n]).reshape(-1)
                 for n in bnames])
            np.testing.assert_array_equal(np.asarray(upd["x"]), want)


# ---------------------------------------------------------- telemetry

def test_collective_count_telemetry():
    p = 2
    params = tree_params()
    mesh = make_mesh(p)
    L = len(jax.tree.leaves(params))
    for buckets, want in (("leaf", L), ("auto", 1), (3, 3), ("concat", 1)):
        tx = gtopk_sgd(0.5, compression="gtopk_layerwise", density=0.125,
                       buckets=buckets, axis_name="dp", axis_size=p,
                       telemetry=True)
        _, state = _spmd_run(tx, params, mesh, p, 1, seed=19)
        assert float(state.telemetry["collective_count"]) == want, buckets


# ----------------------------------------------------------- pipeline

def test_parse_pipeline_grammar():
    assert bucketing.parse_pipeline("serial") == "serial"
    assert bucketing.parse_pipeline("overlap") == "overlap"
    assert bucketing.parse_pipeline(" Auto ") == "auto"
    for bad in ("", "pipelined", "concat", None, 1, 1.5):
        with pytest.raises(ValueError):
            bucketing.parse_pipeline(bad)


def test_overlap_requires_bucketed_wire():
    # One concatenated merge has nothing to overlap with: fail loudly at
    # build time instead of silently running serial.
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk_layerwise", density=0.1,
                  buckets="concat", pipeline="overlap")
    # non-layerwise modes force concat, so overlap is rejected there too
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", density=0.1,
                  pipeline="overlap")
    # 'auto' degrades to serial on the concat wire (nothing to compare)
    gtopk_sgd(0.1, compression="gtopk_layerwise", density=0.1,
              buckets="concat", pipeline="auto")
    gtopk_sgd(0.1, compression="gtopk_layerwise", density=0.1,
              buckets=2, pipeline="overlap")


def test_plan_rejects_unresolved_pipeline():
    # A constructed plan must carry a RESOLVED order — 'auto' is a spec
    # word for plan_buckets, never a plan state.
    with pytest.raises(ValueError):
        BucketPlan((0, 2, 4), (32, 5, 6, 12), (1, 1), pipeline="auto")


def test_manifest_carries_pipeline():
    sizes = (32, 5, 6, 12)
    plan = bucketing.plan_buckets(sizes, 0.125, buckets=2, p=4,
                                  alpha_ms=1.0, beta_gbps=0.6,
                                  pipeline="overlap")
    assert plan.pipeline == "overlap"
    back = BucketPlan.from_manifest(
        json.loads(json.dumps(plan.to_manifest())))
    assert back.pipeline == "overlap"
    # pre-pipeline manifests default to the historical serial order
    man = plan.to_manifest()
    del man["pipeline"]
    assert BucketPlan.from_manifest(man).pipeline == "serial"


def test_stage_cost_and_span_formulas():
    kw = dict(p=4, codec="fp32", schedule=None, alpha_ms=1.0,
              beta_gbps=0.6, mode="gtopk_layerwise")
    n_b, k_b = 2_000_000, 2_000
    merge = bucketing.bucket_cost_ms(n_b, k_b, **kw)
    sel = bucketing.select_cost_ms(n_b)
    assert sel == pytest.approx(2.0)  # 1 ms/Melem * 2 Melem
    assert bucketing.stage_cost_ms(
        n_b, k_b, pipeline="serial", **kw) == pytest.approx(merge)
    assert bucketing.stage_cost_ms(
        n_b, k_b, pipeline="overlap", **kw) == pytest.approx(
            max(sel, merge))
    # span: serial is the paper's sequential sum; overlap is fill +
    # interior maxes + drain. Hand-compute over a pinned 3-bucket plan.
    sizes = (1_000_000, 3_000_000, 2_000_000)
    plan = BucketPlan(
        (0, 1, 2, 3), sizes,
        tuple(bucketing.k_for_density(s, 0.001) for s in sizes),
        pipeline="overlap")
    sels = [bucketing.select_cost_ms(s) for s in sizes]
    merges = [bucketing.bucket_cost_ms(n, k, **kw)
              for n, k in plan.pairs()]
    want_serial = sum(sels) + sum(merges)
    want_overlap = (sels[0] + max(sels[1], merges[0])
                    + max(sels[2], merges[1]) + merges[2])
    assert bucketing.pipeline_span_ms(
        plan, pipeline="serial", **kw) == pytest.approx(want_serial)
    assert bucketing.pipeline_span_ms(plan, **kw) == pytest.approx(
        want_overlap)  # defaults to the plan's own order
    assert want_overlap < want_serial


def test_dp_crossover_overlap_opens_buckets():
    # The acceptance crossover, pinned on synthetic leaves: at ICI-class
    # alpha the overlap-priced DP opens B > 1 (per-stage max lets the
    # fixed select cost absorb extra per-bucket latency) while serial
    # pricing keeps the single merge; 'auto' takes the overlapped order
    # because its true modeled span is strictly smaller.
    sizes = (1_000_000,) * 8
    kw = dict(p=8, codec="fp32", alpha_ms=0.1, beta_gbps=0.6)
    serial = bucketing.plan_buckets(sizes, 0.001, buckets="auto",
                                    pipeline="serial", **kw)
    overlap = bucketing.plan_buckets(sizes, 0.001, buckets="auto",
                                     pipeline="overlap", **kw)
    auto = bucketing.plan_buckets(sizes, 0.001, buckets="auto",
                                  pipeline="auto", **kw)
    assert serial.n_buckets == 1
    assert overlap.n_buckets == 8
    assert auto.pipeline == "overlap" and auto.n_buckets == 8
    assert (bucketing.pipeline_span_ms(overlap, **kw)
            < bucketing.pipeline_span_ms(serial, **kw))
    # latency-bound regime: both orders collapse to B=1, the spans tie,
    # and 'auto' keeps the historical serial order.
    kw22 = dict(kw, alpha_ms=22.0)
    auto22 = bucketing.plan_buckets(sizes, 0.001, buckets="auto",
                                    pipeline="auto", **kw22)
    assert auto22.pipeline == "serial" and auto22.n_buckets == 1


def test_describe_rows_carry_stage_terms():
    sizes = (32, 5, 6, 12)
    kw = dict(p=4, alpha_ms=1.0, beta_gbps=0.6)
    for pipe in ("serial", "overlap"):
        plan = bucketing.plan_buckets(sizes, 0.125, buckets=2,
                                      pipeline=pipe, **kw)
        for r in bucketing.describe(plan, **kw):
            assert r["select_ms"] == pytest.approx(
                bucketing.select_cost_ms(r["elems"]))
            want = (max(r["select_ms"], r["modeled_ms"])
                    if pipe == "overlap" else r["modeled_ms"])
            assert r["stage_ms"] == pytest.approx(want)


@pytest.mark.parametrize("p", [2, 3, 5])
@pytest.mark.parametrize("codec", ["fp32", "int8:16"])
@pytest.mark.parametrize("plan", ["tree", "balanced"])
def test_overlap_bit_equals_serial(p, codec, plan):
    # THE pipeline contract: optimization_barrier is the identity, so
    # reordering the stage issue order must change NOTHING — updates,
    # error-feedback residuals (codec error scatter-back included), and
    # telemetry counters bit-equal across 3 steps, for both schedules.
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, wire_codec=codec,
              comm_plan=plan, axis_name="dp", axis_size=p,
              telemetry=True)
    tx_s = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=2,
                     pipeline="serial", **kw)
    tx_o = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=2,
                     pipeline="overlap", **kw)
    u_s, s_s = _spmd_run(tx_s, params, mesh, p, 3, seed=23)
    u_o, s_o = _spmd_run(tx_o, params, mesh, p, 3, seed=23)
    for a, b in zip(u_s, u_o):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for ra, rb in zip(s_s.residual, s_o.residual):
        np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))
    for key in s_s.telemetry:
        np.testing.assert_array_equal(
            np.asarray(s_s.telemetry[key]), np.asarray(s_o.telemetry[key]))


def test_overlap_first_step_matches_numpy_oracle():
    # Independent numpy simulation of one bucketed step (residuals start
    # at zero, momentum off): per-rank exact top-k per bucket, the
    # recursive-doubling gtopk merge oracle, dense-average, -lr scale.
    # The overlapped pipeline must land on the same dense update.
    p, lr, density = 2, 0.5, 0.125
    params = tree_params()
    mesh = make_mesh(p)
    tx = gtopk_sgd(lr, momentum=0.0, compression="gtopk_layerwise",
                   buckets=2, pipeline="overlap", density=density,
                   axis_name="dp", axis_size=p)
    ups, _ = _spmd_run(tx, params, mesh, p, 1, seed=29)

    names = sorted(params)
    sizes = tuple(int(params[n].size) for n in names)
    plan = bucketing.plan_buckets(sizes, density, buckets=2, p=p)
    rng = np.random.default_rng(29)
    grads = rand_grads(rng, params, lead=(p,))

    def np_topk(x, k):
        idx = np.argsort(-np.abs(x), kind="stable")[:k]
        return x[idx].astype(np.float32), idx.astype(np.int32)

    def np_merge(va, ia, vb, ib, k, n):
        dense = np.zeros(n + 1, np.float64)
        np.add.at(dense, ia, va)
        np.add.at(dense, ib, vb)
        dense[n] = 0.0
        v, i = np_topk(dense[:n], k)
        return v, np.where(v == 0, n, i).astype(np.int32)

    got = np.concatenate([np.asarray(ups[0][n]).reshape(-1)
                          for n in names])
    want = np.zeros(sum(sizes), np.float64)
    for b, (n_b, k_b) in enumerate(plan.pairs()):
        lo, hi = plan.leaf_range(b)
        off = sum(sizes[:lo])
        picks = []
        for d in range(p):
            flat = np.concatenate(
                [np.asarray(grads[n][d]).reshape(-1)
                 for n in names[lo:hi]]).astype(np.float32)
            picks.append(np_topk(flat, k_b))
        (v0, i0), (v1, i1) = picks
        gv, gi = np_merge(v0, i0, v1, i1, k_b, n_b)
        dense = np.zeros(n_b + 1, np.float64)
        np.add.at(dense, gi, gv)
        want[off:off + n_b] = -lr * dense[:n_b] / p
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)
