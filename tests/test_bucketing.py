"""Byte-balanced gradient bucketing (`--buckets`): unit invariants.

The bucketed layerwise path (parallel/bucketing.py + the optimizer's
bucketed branch) partitions the param leaves into B contiguous buckets,
concatenates each bucket's (grad, residual) leaves, and runs ONE fused
selection + ONE codec-framed merge per bucket. These tests pin the
degenerate ends exactly and the DP against brute force:

  * grammar: parse_buckets accepts concat|leaf|auto|int and rejects junk;
  * DP optimality: optimal_boundaries matches exhaustive search over all
    contiguous partitions (pinned B and auto), on random leaf lists;
  * manifest round-trip: BucketPlan -> to_manifest -> JSON ->
    from_manifest preserves the (n_b, k_b) pricing structure;
  * B=L at p=1 bit-equals the historical concat layerwise (selection is
    per-leaf in both; no merge exists at p=1);
  * B=1 bit-equals flat gtopk at p in {2,3,5} — updates AND residuals —
    including under the lossy int8 codec (error-feedback scatter-back
    exactness);
  * B=L at p>1 bit-equals one independent flat gtopk pipeline per leaf;
  * pinned B=2 bit-equals two independent flat pipelines over the
    bucket-concatenated arrays (the scatter-back is exactly a reshape);
  * collective_count telemetry: leaf counts L merges, auto at the
    committed ~22 ms alpha collapses to B=1.
"""

import itertools
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.parallel import BucketPlan, make_mesh, parse_buckets
from gtopkssgd_tpu.parallel import bucketing


def tree_params():
    return {
        "conv": jnp.zeros((4, 8)),   # 32 elems
        "bias": jnp.zeros((5,)),     # 5 elems
        "bn": jnp.zeros((2, 3)),     # 6 elems
        "head": jnp.zeros((3, 4)),   # 12 elems
    }


def rand_grads(rng, params, lead=()):
    return jax.tree.map(
        lambda p: jnp.asarray(
            rng.standard_normal(lead + p.shape), jnp.float32), params)


# ------------------------------------------------------------------ grammar

def test_parse_buckets_grammar():
    assert parse_buckets("concat") == "concat"
    assert parse_buckets("leaf") == "leaf"
    assert parse_buckets("auto") == "auto"
    assert parse_buckets("4") == 4
    assert parse_buckets(3) == 3
    for bad in ("0", "-1", "tree", "", 0, -2, 1.5, True, None):
        with pytest.raises((ValueError, TypeError)):
            parse_buckets(bad)


def test_non_layerwise_mode_rejects_buckets():
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", density=0.1, buckets="auto")
    # concat is the no-op default and composes with every mode
    gtopk_sgd(0.1, compression="gtopk", density=0.1, buckets="concat")


# ----------------------------------------------------------- DP vs brute

def _brute_force(sizes, density, n_buckets, **kw):
    """Cheapest contiguous partition by exhaustive enumeration."""
    L = len(sizes)
    best = (np.inf, None)
    rng = (range(n_buckets - 1, n_buckets) if n_buckets is not None
           else range(0, L))
    for b_minus_1 in rng:
        for cuts in itertools.combinations(range(1, L), b_minus_1):
            bounds = (0,) + cuts + (L,)
            plan = BucketPlan(
                bounds, tuple(sizes),
                tuple(bucketing.k_for_density(sum(sizes[lo:hi]), density)
                      for lo, hi in zip(bounds, bounds[1:])),
                spec="auto")
            cost = bucketing.partition_cost_ms(plan, **kw)
            if cost < best[0] - 1e-12:
                best = (cost, bounds)
    return best


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n_buckets", [None, 2, 3])
def test_dp_matches_brute_force(seed, n_buckets):
    rng = np.random.default_rng(seed)
    L = int(rng.integers(3, 8))
    sizes = tuple(int(s) for s in rng.integers(4, 400, size=L))
    density = 0.05
    kw = dict(p=int(rng.integers(2, 6)), codec="int8:16", schedule=None,
              alpha_ms=float(rng.uniform(0.01, 5.0)), beta_gbps=0.6,
              mode="gtopk_layerwise")
    bounds = bucketing.optimal_boundaries(
        sizes, density, n_buckets=n_buckets, **kw)
    plan = BucketPlan(
        bounds, sizes,
        tuple(bucketing.k_for_density(sum(sizes[lo:hi]), density)
              for lo, hi in zip(bounds, bounds[1:])), spec="auto")
    got = bucketing.partition_cost_ms(plan, **kw)
    want, _ = _brute_force(sizes, density, n_buckets, **kw)
    assert got == pytest.approx(want, rel=1e-9)
    if n_buckets is not None:
        assert len(bounds) == min(n_buckets, L) + 1


# ------------------------------------------------------ manifest roundtrip

def test_manifest_roundtrip():
    sizes = (32, 5, 6, 12)
    plan = bucketing.plan_buckets(sizes, 0.125, buckets=2, p=4,
                                  alpha_ms=1.0, beta_gbps=0.6)
    man = json.loads(json.dumps(plan.to_manifest()))
    back = BucketPlan.from_manifest(man)
    assert back is not None
    assert back.pairs() == plan.pairs()
    assert back.n_buckets == plan.n_buckets
    assert back.k_total == plan.k_total
    # non-bucketed manifests reconstruct to None
    assert BucketPlan.from_manifest({"buckets": "concat"}) is None
    assert BucketPlan.from_manifest({}) is None


# -------------------------------------------------------- degenerate ends

def _p1_run(buckets, steps=3, codec="fp32"):
    params = tree_params()
    tx = gtopk_sgd(0.5, momentum=0.9, compression="gtopk_layerwise",
                   density=0.125, buckets=buckets, wire_codec=codec,
                   axis_name=None)
    state = jax.jit(tx.init)(params)
    upd = jax.jit(tx.update)
    rng = np.random.default_rng(7)
    outs = []
    for _ in range(steps):
        grads = rand_grads(rng, params)
        updates, state = upd(grads, state, params)
        outs.append(updates)
    return outs, state


def test_leaf_p1_bit_equals_concat():
    # At p=1 both paths select per leaf and never merge, so B=L must be
    # BIT-identical to the historical concat layerwise — updates and
    # error-feedback residuals alike.
    u_leaf, s_leaf = _p1_run("leaf")
    u_cat, s_cat = _p1_run("concat")
    for a, b in zip(u_leaf, u_cat):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for ra, rb in zip(s_leaf.residual, s_cat.residual):
        np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))


def _spmd_run(tx, params, mesh, p, steps, seed):
    def step(params, state, grads):
        grads = jax.tree.map(lambda g: g[0], grads)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), updates, state

    smapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))
    state = jax.jit(tx.init)(params)
    rng = np.random.default_rng(seed)
    ups = []
    for _ in range(steps):
        grads = rand_grads(rng, params, lead=(p,))
        params, updates, state = smapped(params, state, grads)
        ups.append(updates)
    return ups, state


@pytest.mark.parametrize("p", [2, 3, 5])
@pytest.mark.parametrize("codec", ["fp32", "int8:16"])
def test_b1_bit_equals_flat_gtopk(p, codec):
    # B=1 concatenates every leaf into one buffer and runs the flat
    # pipeline verbatim: select_topk over grad+residual, identical
    # residual/update masking, same codec fold, one sparse_allreduce.
    # ravel order == concat of per-leaf ravels, so updates AND residuals
    # are bit-identical to compression='gtopk' — including the int8
    # codec's error scatter-back into the residual.
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, wire_codec=codec,
              axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=1, **kw)
    tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
    u_b, s_b = _spmd_run(tx_b, params, mesh, p, 3, seed=11)
    u_f, s_f = _spmd_run(tx_f, params, mesh, p, 3, seed=11)
    for a, b in zip(u_b, u_f):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # bucketed residual is per-leaf; flat's is one [N] buffer in the
    # same tree-flatten order
    res_b = np.concatenate([np.asarray(r).ravel() for r in s_b.residual])
    res_f = np.concatenate([np.asarray(r).ravel() for r in
                            jax.tree.leaves(s_f.residual)])
    np.testing.assert_array_equal(res_b, res_f)


@pytest.mark.parametrize("p", [2, 3])
def test_leaf_bit_equals_per_leaf_flat_pipelines(p):
    # B=L runs the flat pipeline once per leaf over that leaf's own index
    # space — so it must bit-equal L INDEPENDENT flat-gtopk optimizers,
    # one per single-leaf pytree.
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets="leaf",
                     **kw)
    u_b, _ = _spmd_run(tx_b, params, mesh, p, 2, seed=13)
    for name in params:
        sub = {name: params[name]}
        tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
        # same grads: regenerate the full-tree stream and slice the leaf
        rng = np.random.default_rng(13)
        state = jax.jit(tx_f.init)(sub)
        sub_p = sub
        smapped = None
        for step_i in range(2):
            grads = rand_grads(rng, params, lead=(p,))
            sub_g = {name: grads[name]}
            if smapped is None:
                def stepf(params, state, grads):
                    grads = jax.tree.map(lambda g: g[0], grads)
                    updates, state = tx_f.update(grads, state, params)
                    return (optax.apply_updates(params, updates),
                            updates, state)
                smapped = jax.jit(jax.shard_map(
                    stepf, mesh=mesh,
                    in_specs=(P(), P(), P("dp")),
                    out_specs=(P(), P(), P()),
                    check_vma=False,
                ))
            sub_p, upd, state = smapped(sub_p, state, sub_g)
            np.testing.assert_array_equal(
                np.asarray(upd[name]), np.asarray(u_b[step_i][name]))


def test_pinned_b2_bit_equals_two_flat_pipelines():
    # A pinned B=2 concatenates each bucket's leaves; running the flat
    # pipeline over each bucket's own concatenated array must reproduce
    # it exactly (the leaf scatter-back is a pure reshape).
    p = 2
    params = tree_params()
    mesh = make_mesh(p)
    kw = dict(momentum=0.9, density=0.125, axis_name="dp", axis_size=p)
    tx_b = gtopk_sgd(0.5, compression="gtopk_layerwise", buckets=2, **kw)
    u_b, _ = _spmd_run(tx_b, params, mesh, p, 2, seed=17)

    names = sorted(params)  # jax flattens dicts in sorted-key order
    sizes = tuple(int(params[n].size) for n in names)
    plan = bucketing.plan_buckets(sizes, 0.125, buckets=2, p=p)
    assert plan.n_buckets == 2

    rng = np.random.default_rng(17)
    grads_stream = [rand_grads(rng, params, lead=(p,)) for _ in range(2)]
    for b in range(2):
        lo, hi = plan.leaf_range(b)
        bnames = names[lo:hi]
        sub = {"x": jnp.concatenate(
            [params[n].reshape(-1) for n in bnames])}
        tx_f = gtopk_sgd(0.5, compression="gtopk", **kw)
        state = jax.jit(tx_f.init)(sub)

        def stepf(params, state, grads):
            grads = jax.tree.map(lambda g: g[0], grads)
            updates, state = tx_f.update(grads, state, params)
            return optax.apply_updates(params, updates), updates, state

        smapped = jax.jit(jax.shard_map(
            stepf, mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))
        sub_p = sub
        for step_i, grads in enumerate(grads_stream):
            sub_g = {"x": jnp.concatenate(
                [grads[n].reshape(p, -1) for n in bnames], axis=1)}
            sub_p, upd, state = smapped(sub_p, state, sub_g)
            want = np.concatenate(
                [np.asarray(u_b[step_i][n]).reshape(-1)
                 for n in bnames])
            np.testing.assert_array_equal(np.asarray(upd["x"]), want)


# ---------------------------------------------------------- telemetry

def test_collective_count_telemetry():
    p = 2
    params = tree_params()
    mesh = make_mesh(p)
    L = len(jax.tree.leaves(params))
    for buckets, want in (("leaf", L), ("auto", 1), (3, 3), ("concat", 1)):
        tx = gtopk_sgd(0.5, compression="gtopk_layerwise", density=0.125,
                       buckets=buckets, axis_name="dp", axis_size=p,
                       telemetry=True)
        _, state = _spmd_run(tx, params, mesh, p, 1, seed=19)
        assert float(state.telemetry["collective_count"]) == want, buckets
