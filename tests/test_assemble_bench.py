"""assemble_bench_artifact.py: queue outputs -> committed artifact."""

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "benchmarks", "assemble_bench_artifact.py")


def _load():
    spec = importlib.util.spec_from_file_location("asm", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_load_stage_tolerates_warning_lines_and_garbage(tmp_path):
    asm = _load()
    good = tmp_path / "good.json"
    good.write_text("WARNING: axon is experimental\n"
                    '{"value": 1927.4, "device_kind": "TPU v5 lite"}\n')
    assert asm.load_stage(str(good))["value"] == 1927.4
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert asm.load_stage(str(empty)) is None
    assert asm.load_stage(str(tmp_path / "absent.json")) is None


def test_assembles_partial_drain(tmp_path, monkeypatch):
    """A drain where only two stages survived still yields an artifact,
    with the dead stages named in `what`."""
    asm = _load()
    qd = tmp_path / "queue"
    qd.mkdir()
    block = {"metric": "m", "value": 1900.0, "unit": "images/sec/chip",
             "vs_baseline": 0.95, "device_kind": "TPU v5 lite"}
    (qd / "bench_bs128.json").write_text(json.dumps(block) + "\n")
    (qd / "bench_bs128_corr.json").write_text(
        json.dumps({**block, "value": 1850.0}) + "\n")
    monkeypatch.setattr(asm, "RESULTS", str(tmp_path / "results"))
    monkeypatch.setattr(sys, "argv", [
        "assemble", "--round", "99", "--queue-dir", str(qd)])
    asm.main()
    out = tmp_path / "results" / "bench_r99_TPU_v5_lite.json"
    art = json.loads(out.read_text())
    assert art["bs128"]["value"] == 1900.0
    assert art["bs128_corr"]["value"] == 1850.0
    assert "bench_bs256.json" in art["what"]  # missing stage is named
    # a later pass adds the reading without losing blocks
    monkeypatch.setattr(sys, "argv", [
        "assemble", "--round", "99", "--queue-dir", str(qd),
        "--reading", "numbers inspected"])
    asm.main()
    art2 = json.loads(out.read_text())
    assert art2["reading"] == "numbers inspected"
    assert art2["bs128"]["value"] == 1900.0


def test_empty_queue_dir_fails_loud(tmp_path, monkeypatch):
    asm = _load()
    monkeypatch.setattr(sys, "argv", [
        "assemble", "--round", "99", "--queue-dir", str(tmp_path)])
    import pytest

    with pytest.raises(SystemExit, match="no parseable bench stage"):
        asm.main()


def test_stale_stages_from_previous_drain_excluded(tmp_path, monkeypatch):
    """A wedged drain leaves old stage files behind; anything much older
    than the newest stage is a leftover from a previous drain and must
    not be folded into this round's artifact."""
    asm = _load()
    qd = tmp_path / "queue"
    qd.mkdir()
    block = {"metric": "m", "value": 2000.0, "unit": "images/sec/chip",
             "vs_baseline": 1.0, "device_kind": "TPU v5 lite"}
    fresh = qd / "bench_bs128.json"
    old = qd / "bench_bs512.json"
    fresh.write_text(json.dumps(block) + "\n")
    old.write_text(json.dumps({**block, "value": 1.0}) + "\n")
    past = time.time() - 10 * 3600
    os.utime(old, (past, past))
    monkeypatch.setattr(asm, "RESULTS", str(tmp_path / "results"))
    monkeypatch.setattr(sys, "argv", [
        "assemble", "--round", "99", "--queue-dir", str(qd)])
    asm.main()
    art = json.loads(
        (tmp_path / "results" / "bench_r99_TPU_v5_lite.json").read_text())
    assert "bs128" in art and "bs512" not in art
    assert "bench_bs512.json" in art["what"]  # named as stale


def test_round_derivation(tmp_path, monkeypatch):
    """--round omitted: N+1 past the newest committed artifact, but the
    SAME round when that artifact was assembled from this queue dir
    (re-assembly after --reading or a resumed drain)."""
    asm = _load()
    qd = tmp_path / "queue"
    qd.mkdir()
    results = tmp_path / "results"
    results.mkdir()
    (results / "bench_r3_TPU_v5_lite.json").write_text(json.dumps(
        {"what": "hand-written round 3", "provenance": "manual"}) + "\n")
    monkeypatch.setattr(asm, "RESULTS", str(results))
    assert asm.derive_round(str(qd)) == 4
    block = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
             "device_kind": "TPU v5 lite"}
    (qd / "bench_bs128.json").write_text(json.dumps(block) + "\n")
    monkeypatch.setattr(sys, "argv", ["assemble", "--queue-dir", str(qd)])
    asm.main()
    assert (results / "bench_r4_TPU_v5_lite.json").exists()
    # second assembly from the same dir stays round 4
    assert asm.derive_round(str(qd)) == 4
