"""Regenerate the committed critpath fixture shards in this directory.

Three rank shards of one synthetic run (shared config_hash), steps 1-6
at a 1.0 s cadence, each step carrying a durable "critpath" record
(ordered {stage, t0_us, t1_us} segments, obs/critpath.py) with the
shapes the fleet joiner must handle baked in deterministically:

  steps 1-3  compute-bound: rank 0 computes wall-to-wall
             (compute [0,900] + comm [900,1000], zero wait) while
             rank 1 is WAIT-dominated (wait 500 us of a 1000 us step)
             and rank 2 sits between — the global chain runs entirely
             through rank 0 and the critical stage is "compute".
  steps 4-6  a barrier stall: EVERY rank is compute [0,100] +
             wait [100,900] + comm [900,1000], so no rank has busy
             work covering the middle of the step and the wait itself
             joins the chain — the critical stage shifts to "wait".
             Three consecutive shifted steps = exactly the default
             critpath_shift_windows, so a monitor fed these shards
             fires critpath_shift at step 6 (from compute to wait).
  rank 2     arrives 2.5 s late at EVERY step (obs records) — a
             persistent straggler, so straggler rows exist and carry
             rank 2's LOCAL critical stage ("compute" then "wait").

Values are hand-chosen, not sampled, so test assertions are exact:
the expected global chain is hand-computable (see test_critpath.py).

Run from anywhere:  python tests/fixtures/critpath/make_critpath_fixture.py
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

BASE_TIME = 1700000000.0
STEP_S = 1.0          # wall-clock cadence of the synthetic run
LAG_RANK = 2
LAG_S = 2.5           # > 2.0 x STEP_S => persistent under the defaults
CONFIG_HASH = "critfix0001beef"
N_RANKS, STEPS = 3, (1, 2, 3, 4, 5, 6)
NUM_PARAMS = 10000
DENSITY = 0.01

# Per-rank stage segments, µs inside the step window. Steps 1-3 use the
# skewed layout; steps 4-6 the barrier-stall layout (same on all ranks).
SKEWED = {
    0: [("compute", 0.0, 900.0), ("comm", 900.0, 1000.0)],
    1: [("compute", 0.0, 400.0), ("comm", 400.0, 500.0),
        ("wait", 500.0, 1000.0)],
    2: [("compute", 0.0, 600.0), ("comm", 600.0, 700.0),
        ("wait", 700.0, 1000.0)],
}
STALLED = [("compute", 0.0, 100.0), ("wait", 100.0, 900.0),
           ("comm", 900.0, 1000.0)]


def manifest(rank: int) -> dict:
    return {
        "kind": "manifest", "time": BASE_TIME, "rank": rank,
        "config_hash": CONFIG_HASH,
        "dnn": "resnet20", "dataset": "cifar10",
        "compression": "gtopk", "density": DENSITY,
        "nworkers": N_RANKS, "batch_size": 4, "seed": 42,
        "num_params": NUM_PARAMS,
        "process_count": N_RANKS, "process_index": rank,
        "coordinator_address": "127.0.0.1:9999",
    }


def obs_record(rank: int, step: int) -> dict:
    lag = LAG_S if rank == LAG_RANK else 0.0
    return {
        "kind": "obs", "time": BASE_TIME + step * STEP_S + lag,
        "rank": rank, "step": step,
        "loss": round(2.0 - 0.1 * step + 0.01 * rank, 6),
        "achieved_density": DENSITY,
        "wire_bytes": 2400,
    }


def critpath_record(rank: int, step: int) -> dict:
    """Mirror obs/critpath.py build_record arithmetic on the
    hand-chosen segments (kept inline so the fixture regenerates
    without importing the package)."""
    layout = SKEWED[rank] if step <= 3 else STALLED
    segs = [{"stage": s, "t0_us": a, "t1_us": b} for s, a, b in layout]
    tot = {"compute": 0.0, "select": 0.0, "comm": 0.0, "wait": 0.0}
    for s, a, b in layout:
        tot[s] += b - a
    wall = max(b for _, _, b in layout)
    # Local dominant stage, ties broken in STAGES order.
    order = ("compute", "select", "comm", "wait")
    crit = max(order, key=lambda s: (tot[s], -order.index(s)))
    lag = LAG_S if rank == LAG_RANK else 0.0
    return {
        "kind": "critpath", "time": BASE_TIME + step * STEP_S + lag,
        "rank": rank, "step": step,
        "wall_us": wall,
        "t_compute_us": tot["compute"], "t_select_us": tot["select"],
        "t_comm_wire_us": tot["comm"], "t_wait_us": tot["wait"],
        "wait_frac": round(tot["wait"] / wall, 6),
        "crit_stage": crit,
        "segments": segs,
    }


def main() -> None:
    for rank in range(N_RANKS):
        path = os.path.join(HERE, f"metrics.rank{rank}.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps(manifest(rank)) + "\n")
            for step in STEPS:
                fh.write(json.dumps(obs_record(rank, step)) + "\n")
                fh.write(json.dumps(critpath_record(rank, step)) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
