"""Regenerate the committed fleet fixture shards in this directory.

Three rank shards of one synthetic run (shared config_hash), steps 1-4
at a 1.0 s cadence, with the two defects the fleet merger must handle
baked in deterministically:

  rank 2   arrives 2.5 s late at EVERY step — a persistent straggler
           (2.5 s > the auto threshold straggler_lag_x=2 x the 1.0 s
           step duration, and constant, so the EWMA pins at 2.5 s).
  rank 1   is missing step 3 entirely — a ragged shard (crashed logger,
           thinned interval); the merger must keep going with
           n_ranks=2 at that step.

Values are hand-chosen, not sampled, so test assertions are exact:
loss at step s on rank r is 2.0 - 0.1*s + 0.01*r and wire_bytes is
2400 on every rank (zero skew on that field, nonzero on loss).

Run from anywhere:  python tests/fixtures/fleet/make_fleet_fixture.py
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

BASE_TIME = 1700000000.0
STEP_S = 1.0          # wall-clock cadence of the synthetic run
LAG_RANK = 2
LAG_S = 2.5           # > 2.0 x STEP_S => persistent under the defaults
MISSING = (1, 3)      # (rank, step) dropped to make shard 1 ragged
CONFIG_HASH = "fleetfix0001beef"
N_RANKS, STEPS = 3, (1, 2, 3, 4)
NUM_PARAMS = 10000
DENSITY = 0.01


def manifest(rank: int) -> dict:
    return {
        "kind": "manifest", "time": BASE_TIME, "rank": rank,
        "config_hash": CONFIG_HASH,
        "dnn": "resnet20", "dataset": "cifar10",
        "compression": "gtopk", "density": DENSITY,
        "nworkers": N_RANKS, "batch_size": 4, "seed": 42,
        "num_params": NUM_PARAMS,
        "process_count": N_RANKS, "process_index": rank,
        "coordinator_address": "127.0.0.1:9999",
    }


def obs_record(rank: int, step: int) -> dict:
    lag = LAG_S if rank == LAG_RANK else 0.0
    return {
        "kind": "obs", "time": BASE_TIME + step * STEP_S + lag,
        "rank": rank, "step": step,
        "loss": round(2.0 - 0.1 * step + 0.01 * rank, 6),
        "achieved_density": DENSITY,
        "wire_bytes": 2400,
    }


def main() -> None:
    for rank in range(N_RANKS):
        path = os.path.join(HERE, f"metrics.rank{rank}.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps(manifest(rank)) + "\n")
            for step in STEPS:
                if (rank, step) == MISSING:
                    continue
                fh.write(json.dumps(obs_record(rank, step)) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
