"""Regenerate the committed linkmap fixture shards in this directory.

Four rank shards of one synthetic p=4 gtopk-tree run (shared
config_hash), steps 1-4 at a 1.0 s cadence, each step carrying a
durable "linkmap" record produced by the REAL obs/linkmap.py LinkMap
(imported, not mirrored — the carve arithmetic is the thing under
test) from hand-chosen spans:

  clean ranks (2, 3)    observe exactly their modeled span, so their
                        links' EWMAs equal the per-round model price
                        t0 = alpha + (wire/2) * 8e-6 / beta
                        (0.164 ms at the carve defaults)
  degraded pair (0, 1)  both endpoints of a slow link measure the
                        stall, so ranks 0 and 1 observe +DELAY_MS.
                        The carve spreads each rank's inflation over
                        its 2 tree rounds, so after the endpoint-mean
                        merge dcn:0-1 sits at t0 + d/2 (1.164 ms),
                        the adjacent pairs 0-2 and 1-3 at t0 + d/4
                        (0.664 ms), and 2-3 at t0 — the worst link is
                        EXACTLY the degraded pair, at 1.753x the
                        fleet median (1.164 / 0.664).

Spans repeat every step, so the EWMAs are constant and every number
above is exact — test assertions in tests/test_linkmap.py pin them.

Run from anywhere:  python tests/fixtures/linkmap/make_linkmap_fixture.py
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(os.path.dirname(HERE)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from gtopkssgd_tpu.obs.linkmap import (  # noqa: E402
    LinkMap, rank_rounds, round_peers, round_weights)

BASE_TIME = 1700000000.0
STEP_S = 1.0          # wall-clock cadence of the synthetic run
CONFIG_HASH = "linkfix0001beef"
P, STEPS = 4, (1, 2, 3, 4)
WIRE_BYTES = 400_000.0
DELAY_MS = 2.0
DEGRADED = (0, 1)     # the hand-degraded peer pair


def manifest(rank: int) -> dict:
    return {
        "kind": "manifest", "time": BASE_TIME, "rank": rank,
        "config_hash": CONFIG_HASH,
        "dnn": "resnet20", "dataset": "cifar10",
        "compression": "gtopk", "density": 0.01,
        "nworkers": P, "batch_size": 4, "seed": 42,
        "process_count": P, "process_index": rank,
        "coordinator_address": "127.0.0.1:9999",
    }


def main() -> None:
    for rank in range(P):
        mine = rank_rounds(round_peers("gtopk", P), rank)
        span = sum(round_weights(mine, WIRE_BYTES))
        lm = LinkMap("gtopk", P, rank=rank)
        path = os.path.join(HERE, f"metrics.rank{rank}.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps(manifest(rank)) + "\n")
            for step in STEPS:
                t = span + (DELAY_MS if rank in DEGRADED else 0.0)
                rec = lm.observe(step, t_comm_ms=t,
                                 wire_bytes=WIRE_BYTES)
                fh.write(json.dumps({
                    "kind": "linkmap",
                    "time": BASE_TIME + step * STEP_S,
                    "rank": rank, **rec}) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
