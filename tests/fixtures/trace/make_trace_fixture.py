"""Regenerate cpu_smoke.trace.json.gz — the committed chrome-trace
fixture behind tests/test_obs_attr.py.

The fixture is a REAL ``jax.profiler`` capture (via
obs.trace_attr.capture, Python tracer off) of a tiny 2-device CPU-mesh
program built to exercise every attribution bucket with a handful of
events:

  compute — a jitted matmul chain (dot ops / fusions)
  select  — lax.top_k over a vector (lowers to sort on XLA:CPU)
  comm    — shard_map psum + ppermute (all-reduce / collective-permute)

each dispatched inside the Tracer-style TraceAnnotation scopes the
trainer emits (train/step, train/step/compress, train/step/comm), so the
fixture also carries host-lane annotation events. After capture, events
are FILTERED to metadata + XLA op events + the train/* annotations —
full traces carry tens of thousands of runtime bookkeeping events that
would bloat a committed fixture without adding coverage.

Run from the repo root (the fixture is deterministic enough for the
tests, which assert structure and bucket presence, not exact times):

  python tests/fixtures/trace/make_trace_fixture.py
"""

from __future__ import annotations

import functools
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "cpu_smoke.trace.json.gz")


def build_and_capture(trace_dir: str) -> None:
    from gtopkssgd_tpu.utils import force_cpu_mesh

    force_cpu_mesh(2)

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import gtopkssgd_tpu  # noqa: F401  (jax.shard_map compat shim)
    from gtopkssgd_tpu.obs.trace_attr import capture
    from gtopkssgd_tpu.parallel import make_mesh

    mesh = make_mesh(2)

    @jax.jit
    def compute(x):
        for _ in range(4):
            x = jnp.tanh(x @ x)
        return x

    @jax.jit
    def select(v):
        # lax.sort, not lax.top_k: this jaxlib's CPU top-k lowers to a
        # reduce-window scheme, while the repo's production selection
        # (threshold/blockwise tau search) shows up as sort ops in real
        # trainer traces — which is what the classifier keys on.
        s = jax.lax.sort(v)
        return s[-64:].sum()

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False)
    def comm(v):
        s = jax.lax.psum(v, "dp")
        return jax.lax.ppermute(s, "dp", [(0, 1), (1, 0)])

    x = jnp.ones((128, 128), jnp.float32) * 0.01
    v = jnp.linspace(-1.0, 1.0, 32768)
    vs = jnp.ones((2, 4096), jnp.float32)

    # Warm pass: compilation must stay out of the trace.
    jax.block_until_ready((compute(x), select(v), comm(vs)))

    with capture(trace_dir):
        for _ in range(3):
            with jax.profiler.TraceAnnotation("train/step"):
                jax.block_until_ready(compute(x))
                with jax.profiler.TraceAnnotation("train/step/compress"):
                    jax.block_until_ready(select(v))
                with jax.profiler.TraceAnnotation("train/step/comm"):
                    jax.block_until_ready(comm(vs))


def shrink(trace_dir: str, out_path: str) -> dict:
    from gtopkssgd_tpu.obs.trace_attr import find_trace_file

    with gzip.open(find_trace_file(trace_dir), "rt") as fh:
        doc = json.load(fh)
    kept = []
    for e in doc.get("traceEvents", []):
        name = str(e.get("name", ""))
        if name in ("process_name", "thread_name", "process_sort_index"):
            kept.append(e)
        elif "hlo_op" in e.get("args", {}):
            kept.append(e)
        elif e.get("ph") == "X" and name.startswith("train/"):
            kept.append(e)
    slim = {"traceEvents": kept,
            "displayTimeUnit": doc.get("displayTimeUnit", "ms")}
    with gzip.open(out_path, "wt") as fh:
        json.dump(slim, fh)
    return slim


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="trace_fixture_") as tmp:
        build_and_capture(tmp)
        slim = shrink(tmp, OUT)

    from gtopkssgd_tpu.obs.trace_attr import attribute, format_attr

    rec = attribute(OUT)
    print(f"wrote {OUT}: {len(slim['traceEvents'])} events, "
          f"{os.path.getsize(OUT)} bytes")
    print(format_attr(rec))
    ok = all(rec[f"frac_{t}"] > 0 for t in ("compute", "select", "comm"))
    if not ok:
        print("FIXTURE BAD: some bucket is empty — do not commit")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
