"""Regenerate the committed forecast fixture shard in this directory.

One rank-0 shard of a synthetic p=4 gtopk run (steps 1-4 at a 1.0 s
cadence) carrying every record kind the OFFLINE forecast path
(obs/forecast.py summarize_forecast, source "stream") composes:

  manifest      compression=gtopk, nworkers=4, num_params=1_000_000,
                density=0.01 (k = 10_000), wire_codec=fp32,
                comm_plan_schedule=tree
  calib         alpha_fit_ms=0.5, beta_fit_gbps=8.0, resid_ms=0.02 —
                the run's own refit, so fit_source is "calib-record"
  linkmap       links [1, 1, 1, 2] ms -> degrade_factor = mean/median
                = 1.25 (the one degraded link priced at its multiple)
  critpath x4   t_compute_us=10_000, t_select_us=2_000,
                wall_us=14_795 every capture

All hand arithmetic, chosen so the hindcast is EXACT:

  comm  = tree_rounds(4)=2 DCN rounds x (alpha 0.5 ms + 80_000 set
          bytes / (8 Gbps -> 1e6 B/ms) = 0.08 ms) = 1.16 ms
  pred  = 10 + 2 + 1.16 x 1.25 = 13.45 ms
  meas  = 14.795 ms  ->  err_x = 14.795 / 13.45 = 1.1 exactly

Test assertions in tests/test_forecast.py pin these numbers.

Run from anywhere:  python tests/fixtures/forecast/make_forecast_fixture.py
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

BASE_TIME = 1700000000.0
STEP_S = 1.0
CONFIG_HASH = "forecastfix01beef"
P, STEPS = 4, (1, 2, 3, 4)

COMPUTE_US = 10_000.0
SELECT_US = 2_000.0
WALL_US = 14_795.0   # 1.1x the modeled 13.45 ms step
ALPHA_MS, BETA_GBPS, RESID_MS = 0.5, 8.0, 0.02


def records():
    yield {
        "kind": "manifest", "time": BASE_TIME, "rank": 0,
        "config_hash": CONFIG_HASH,
        "dnn": "resnet20", "dataset": "cifar10",
        "compression": "gtopk", "density": 0.01,
        "num_params": 1_000_000,
        "nworkers": P, "batch_size": 4, "seed": 42,
        "wire_codec": "fp32", "comm_plan_schedule": "tree",
        "process_count": P, "process_index": 0,
    }
    yield {
        "kind": "calib", "time": BASE_TIME + 0.5, "rank": 0,
        "step": 1, "alpha_fit_ms": ALPHA_MS,
        "beta_fit_gbps": BETA_GBPS, "resid_ms": RESID_MS,
        "n_samples": 8,
    }
    yield {
        "kind": "linkmap", "time": BASE_TIME + 0.5, "rank": 0,
        "step": 1, "wire_mode": "gtopk", "p": P, "n_links": 4,
        "links": [
            {"link": "dcn:0-1", "axis": "dcn", "src": 0, "dst": 1,
             "ewma_ms": 1.0, "n": 1},
            {"link": "dcn:0-2", "axis": "dcn", "src": 0, "dst": 2,
             "ewma_ms": 1.0, "n": 1},
            {"link": "dcn:1-3", "axis": "dcn", "src": 1, "dst": 3,
             "ewma_ms": 1.0, "n": 1},
            {"link": "dcn:2-3", "axis": "dcn", "src": 2, "dst": 3,
             "ewma_ms": 2.0, "n": 1},
        ],
    }
    for step in STEPS:
        yield {
            "kind": "critpath", "time": BASE_TIME + step * STEP_S,
            "rank": 0, "step": step,
            "wall_us": WALL_US,
            "t_compute_us": COMPUTE_US,
            "t_select_us": SELECT_US,
            "t_comm_us": WALL_US - COMPUTE_US - SELECT_US,
        }


def main() -> None:
    path = os.path.join(HERE, "metrics.rank0.jsonl")
    with open(path, "w") as fh:
        for rec in records():
            fh.write(json.dumps(rec) + "\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
