"""Deterministically (re)generate the committed real-format data fixtures.

The fixtures prove the REAL-data parsing paths (pickle batches, ImageFolder
JPEGs, PTB text, wav + manifest) actually parse their formats — every other
test runs synthetic. They are tiny (a few hundred KB total) and committed;
this script documents exactly how they were made and lets them be rebuilt:

    python tests/fixtures/make_fixtures.py
"""

from __future__ import annotations

import os
import pickle

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
RNG = np.random.default_rng(1234)


def make_cifar():
    """8 images per batch file, standard cifar-10-batches-py pickle layout
    (uint8 [N, 3072] row-major CHW + byte-keyed dict)."""
    root = os.path.join(HERE, "cifar", "cifar-10-batches-py")
    os.makedirs(root, exist_ok=True)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        data = RNG.integers(0, 256, (8, 3072), dtype=np.uint8)
        labels = RNG.integers(0, 10, 8).tolist()
        with open(os.path.join(root, name), "wb") as f:
            pickle.dump({b"data": data, b"labels": labels,
                         b"batch_label": name.encode()}, f)


def make_ptb():
    """Tiny word-level corpus, one sentence per line (the loader maps
    newline -> <eos>). Train repeats a small vocabulary so the vocab builder
    and <unk> mapping are both exercised (valid/test contain an OOV word)."""
    root = os.path.join(HERE, "ptb")
    os.makedirs(root, exist_ok=True)
    sents = [
        "the quick brown fox jumps over the lazy dog",
        "a stitch in time saves nine",
        "all that glitters is not gold",
    ]
    with open(os.path.join(root, "ptb.train.txt"), "w") as f:
        for i in range(8):
            f.write(sents[i % 3] + "\n")
    with open(os.path.join(root, "ptb.valid.txt"), "w") as f:
        f.write("the quick zebra jumps over gold\n" * 4)
    with open(os.path.join(root, "ptb.test.txt"), "w") as f:
        f.write("a lazy fox saves the dog\n" * 4)


def make_an4():
    """Two 0.5 s 16 kHz mono wavs (distinct tones + noise), transcripts,
    and train/val manifests with manifest-relative paths."""
    import scipy.io.wavfile as wavfile

    root = os.path.join(HERE, "an4")
    os.makedirs(root, exist_ok=True)
    sr = 16000
    t = np.arange(int(0.5 * sr)) / sr
    for name, freq, text in [("hello", 440.0, "HELLO"),
                             ("world", 880.0, "WORLD")]:
        audio = 0.4 * np.sin(2 * np.pi * freq * t)
        audio += 0.05 * RNG.standard_normal(t.shape)
        wavfile.write(os.path.join(root, f"{name}.wav"), sr,
                      (audio * 32767).astype(np.int16))
        with open(os.path.join(root, f"{name}.txt"), "w") as f:
            f.write(text + "\n")
    with open(os.path.join(root, "an4_train_manifest.csv"), "w") as f:
        f.write("hello.wav,hello.txt\nworld.wav,world.txt\n")
    with open(os.path.join(root, "an4_val_manifest.csv"), "w") as f:
        f.write("world.wav,world.txt\nhello.wav,hello.txt\n")


def make_imagenet():
    """2 classes x 3 train (+2 val) tiny JPEGs in ImageFolder layout."""
    from PIL import Image

    root = os.path.join(HERE, "imagenet")
    for split, n in (("train", 3), ("val", 2)):
        for wnid in ("n01440764", "n01443537"):
            d = os.path.join(root, split, wnid)
            os.makedirs(d, exist_ok=True)
            for i in range(n):
                arr = RNG.integers(0, 256, (48, 64, 3), dtype=np.uint8)
                Image.fromarray(arr).save(
                    os.path.join(d, f"img_{i}.jpg"), quality=90)


if __name__ == "__main__":
    make_cifar()
    make_ptb()
    make_an4()
    make_imagenet()
    print("fixtures written under", HERE)
