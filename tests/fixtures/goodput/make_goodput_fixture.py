"""Regenerate the committed goodput fixture shards in this directory.

Three rank shards of one synthetic run (shared config_hash), each rank
10.0 s of wall clock with a hand-chosen badput split (obs/goodput.py
taxonomy) baked in so every join a test makes is exactly computable:

  rank 0  clean: goodput 8.0 s of 10.0 (select 0.5, comm 0.5, wait 0.2,
          data 0.3, startup 0.5) -> goodput_frac 0.8, no dominant
          badput worth naming (select/comm tie broken to "select").
  rank 1  chaos: a skip and a rollback (recovery records included)
          wasted 1.5 s over 2 steps, plus a 0.8 s checkpoint ->
          goodput_frac 0.6, dominant badput "wasted".
  rank 2  straggler: 4.8 s blocked at collectives (wait) ->
          goodput_frac 0.4, dominant badput "wait"; its obs records
          arrive 2.5 s late every step (persistent under the fleet
          defaults), so straggler rows exist AND carry the goodput
          column ("wait" at every step).

Fleet joins these to: wall 30.0, goodput 18.0 -> fleet goodput_frac
0.6; per-rank fracs (0.8, 0.6, 0.4) give median 0.6, so advise() at
the default margin 0.1 names rank 2, dominant "wait", recoverable
(0.6 - 0.4) * 10.0 = 2.0 s.

Each rank logs a mid-run cumulative record at step 5 (exactly half of
every category) and the final record at step 10 — fold() must pick the
final one. Values are hand-chosen, not sampled, so test assertions are
exact (see test_goodput.py).

Run from anywhere:  python tests/fixtures/goodput/make_goodput_fixture.py
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

BASE_TIME = 1700000000.0
STEP_S = 1.0
LAG_RANK = 2
LAG_S = 2.5           # > 2.0 x STEP_S => persistent under the defaults
CONFIG_HASH = "goodfix0001beef"
N_RANKS, STEPS = 3, (1, 2, 3, 4, 5, 6, 7, 8, 9, 10)
WALL_S = 10.0

# Per-rank final category seconds; every row sums to WALL_S exactly, so
# other_s is 0.0 and conservation holds with zero remainder.
CATEGORY_SECONDS = {
    0: {"goodput": 8.0, "select": 0.5, "comm": 0.5, "wait": 0.2,
        "data": 0.3, "startup": 0.5},
    1: {"goodput": 6.0, "select": 0.4, "comm": 0.4, "wait": 0.2,
        "ckpt": 0.8, "wasted": 1.5, "data": 0.2, "startup": 0.5},
    2: {"goodput": 4.0, "select": 0.3, "comm": 0.3, "wait": 4.8,
        "data": 0.1, "startup": 0.5},
}
N_WASTED = {0: 0, 1: 2, 2: 0}
ALL_CATEGORIES = ("goodput", "select", "comm", "wait", "compile",
                  "ckpt", "wasted", "degraded", "data", "startup")


def manifest(rank: int) -> dict:
    return {
        "kind": "manifest", "time": BASE_TIME, "rank": rank,
        "config_hash": CONFIG_HASH,
        "dnn": "resnet20", "dataset": "cifar10",
        "compression": "gtopk", "density": 0.01,
        "nworkers": N_RANKS, "batch_size": 4, "seed": 42,
        "num_params": 10000,
        "process_count": N_RANKS, "process_index": rank,
        "coordinator_address": "127.0.0.1:9999",
    }


def obs_record(rank: int, step: int) -> dict:
    lag = LAG_S if rank == LAG_RANK else 0.0
    return {
        "kind": "obs", "time": BASE_TIME + step * STEP_S + lag,
        "rank": rank, "step": step,
        "loss": round(2.0 - 0.1 * step + 0.01 * rank, 6),
        "achieved_density": 0.01,
        "wire_bytes": 2400,
    }


def goodput_record(rank: int, step: int, scale: float,
                   final: bool) -> dict:
    """Mirror obs/goodput.py decomposition arithmetic on the hand-chosen
    seconds (kept inline so the fixture regenerates without importing
    the package)."""
    secs = CATEGORY_SECONDS[rank]
    wall = WALL_S * scale
    rec = {
        "kind": "goodput",
        "time": BASE_TIME + step * STEP_S,
        "rank": rank, "step": step,
    }
    total = 0.0
    for cat in ALL_CATEGORIES:
        s = secs.get(cat, 0.0) * scale
        total += s
        rec[f"{cat}_s"] = round(s, 6)
    rec["wall_s"] = round(wall, 6)
    rec["other_s"] = round(wall - total, 6)
    rec["goodput_frac"] = round(secs["goodput"] * scale / wall, 6)
    rec["other_frac"] = round((wall - total) / wall, 6)
    rec["n_wasted_steps"] = int(round(N_WASTED[rank] * scale))
    rec["final"] = int(final)
    rec["source"] = "ledger"
    return rec


def recovery_records(rank: int) -> list:
    if rank != 1:
        return []
    return [
        {"kind": "recovery", "time": BASE_TIME + 3 * STEP_S,
         "rank": rank, "step": 3, "action": "skip", "rule": "nan_loss",
         "consecutive": 1},
        {"kind": "recovery", "time": BASE_TIME + 6 * STEP_S,
         "rank": rank, "step": 6, "action": "rollback",
         "rule": "loss_spike", "restore_step": 5},
    ]


def main() -> None:
    for rank in range(N_RANKS):
        path = os.path.join(HERE, f"metrics.rank{rank}.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps(manifest(rank)) + "\n")
            recov = {r["step"]: r for r in recovery_records(rank)}
            for step in STEPS:
                fh.write(json.dumps(obs_record(rank, step)) + "\n")
                if step in recov:
                    fh.write(json.dumps(recov[step]) + "\n")
                if step == 5:
                    fh.write(json.dumps(goodput_record(
                        rank, step, 0.5, final=False)) + "\n")
            fh.write(json.dumps(goodput_record(
                rank, STEPS[-1], 1.0, final=True)) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
