"""The multi-chip projection tool stays runnable and directionally sane.

It is a bandwidth-only projection (clearly labeled as such); what CI can
pin is the structural conclusions its committed artifact narrative rests
on — not any absolute number.
"""

import math

from tests.conftest import load_benchmark_module


def _load():
    return load_benchmark_module("scaling_model")


KW = dict(n=25_557_032, k=25_558, compute_ms=60.0, overhead_ms=5.4,
          ici_gbps=1600.0, dcn_gbps=25.0, ici_size=16, batch=128)


def test_p1_is_compute_bound_no_comm():
    m = _load()
    for mode in ("dense", "gtopk", "allgather", "gtopk_hier"):
        r = m.project(mode, 1, **KW)
        assert r["comm_ms"] < 1.0, r
    # ...and at p=1 dense beats every sparse mode (no network to compress
    # against; the measured fused-variants artifact says the same).
    dense = m.project("dense", 1, **KW)
    for mode in ("gtopk", "allgather"):
        assert (m.project(mode, 1, **KW)["images_per_sec_per_chip"]
                < dense["images_per_sec_per_chip"])


def test_dense_wins_inside_ici_sparse_wins_over_dcn():
    m = _load()
    # Within one ICI slice: dense psum is cheap; gtopk's fixed overhead
    # makes it slower.
    d16, g16 = m.project("dense", 16, **KW), m.project("gtopk", 16, **KW)
    assert d16["images_per_sec_per_chip"] > g16["images_per_sec_per_chip"]
    # Crossing DCN at scale: the O(N) dense reduction collapses and the
    # O(k log P) tree wins by a wide margin.
    d256, g256 = m.project("dense", 256, **KW), m.project("gtopk", 256, **KW)
    assert g256["images_per_sec_per_chip"] > 1.5 * d256["images_per_sec_per_chip"]


def test_hier_beats_flat_gtopk_at_multislice_scale():
    m = _load()
    # The hierarchical mode keeps the O(N) hop on ICI and sends only the
    # sparse set over DCN, so it should never lose badly to flat gtopk
    # (which pays log2(P) DCN rounds) and should beat dense outright.
    g, h = m.project("gtopk", 256, **KW), m.project("gtopk_hier", 256, **KW)
    d = m.project("dense", 256, **KW)
    assert h["step_ms"] <= g["step_ms"] * 1.1
    assert h["images_per_sec_per_chip"] > d["images_per_sec_per_chip"]


def test_allgather_scales_worse_than_gtopk():
    m = _load()
    # O(kP) vs O(k log P): by P=256 the DGC allgather pays ~32x the bytes.
    g, a = m.project("gtopk", 256, **KW), m.project("allgather", 256, **KW)
    assert a["comm_ms"] > 10 * g["comm_ms"]


def test_comm_complexity_classes():
    m = _load()
    # Slice-aware model (ici_size=16): the DCN phase dominates at these
    # link ratios, so gtopk comm grows ~log2(n_slices) and allgather
    # ~(p - s) — the cross-DCN byte counts, not the single-link
    # whole-collective counts of the pre-round-4 model.
    g64 = m.project("gtopk", 64, **KW)["comm_ms"]
    g256 = m.project("gtopk", 256, **KW)["comm_ms"]
    assert math.isclose(
        g256 / g64, math.log2(256 // 16) / math.log2(64 // 16),
        rel_tol=0.05)
    a64 = m.project("allgather", 64, **KW)["comm_ms"]
    a256 = m.project("allgather", 256, **KW)["comm_ms"]
    assert math.isclose(a256 / a64, (256 - 16) / (64 - 16), rel_tol=0.05)
