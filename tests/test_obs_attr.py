"""Step-time attribution (obs.trace_attr), the host timeline exporter
(obs.timeline), and their report-CLI surfaces.

The attribution parser is pinned against a COMMITTED chrome trace
(tests/fixtures/trace/cpu_smoke.trace.json.gz — a real jax.profiler
capture of a tiny program built to exercise every bucket; regeneration
script sits next to it), plus synthetic traces where the expected self
times are computable by hand. The timeline recorder round-trips through
its own schema validator — the same one ``report timeline`` runs.
"""

import gzip
import json
import os

import pytest

from gtopkssgd_tpu.obs import report as obs_report
from gtopkssgd_tpu.obs.timeline import (
    TimelineRecorder,
    timeline_from_records,
    validate_timeline,
)
from gtopkssgd_tpu.obs.trace_attr import (
    _interval_union,
    _intersection_us,
    attribute,
    classify_op,
    classify_span,
    find_trace_file,
    format_attr,
    host_span_means,
    op_ranking,
    overlap_fraction,
    self_durations_us,
)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "trace", "cpu_smoke.trace.json.gz")


# ----------------------------------------------------------- classifiers

def test_classify_op_buckets():
    assert classify_op("sort.17") == "select"
    assert classify_op("Sort.2") == "select"
    assert classify_op("top-k.3") == "select"
    assert classify_op("all-reduce.1") == "comm"
    assert classify_op("all-gather-start") == "comm"
    assert classify_op("collective-permute.4") == "comm"
    assert classify_op("reduce-scatter.9") == "comm"
    assert classify_op("fusion.12") == "compute"
    assert classify_op("convolution.3") == "compute"
    assert classify_op("dot.1") == "compute"
    # reduce-window is pooling, NOT top-k — the documented near-miss
    assert classify_op("reduce-window.5") == "compute"
    # TPU fusion naming carries the root op
    assert classify_op("fusion.sort.2") == "select"
    assert classify_op("fusion.all-reduce.7") == "comm"


def test_classify_span_buckets():
    assert classify_span("bench/compress") == "select"
    assert classify_span("bench/compress_per_leaf") == "select"
    assert classify_span("bench/comm") == "comm"
    assert classify_span("train/step") == "compute"
    assert classify_span("bench/forward_backward") == "compute"
    # unmatched host phases stay OUT of the three-term split
    assert classify_span("io") is None
    assert classify_span("obs_read") is None


# ------------------------------------------------------------ self times

def _ev(name, ts, dur, pid=1, tid=1, **args):
    e = {"ph": "X", "name": name, "ts": ts, "dur": dur,
         "pid": pid, "tid": tid}
    if args:
        e["args"] = args
    return e


def test_self_durations_subtract_nested_children():
    # while [0,100) wraps two children; sibling [120,150) is flat
    events = [
        _ev("while.1", 0, 100),
        _ev("collective-permute.1", 10, 30),
        _ev("fusion.1", 50, 20),
        _ev("dot.1", 120, 30),
    ]
    selfs = self_durations_us(events)
    assert selfs == [50.0, 30.0, 20.0, 30.0]


def test_self_durations_deep_nesting_and_shared_start():
    # grandchild nests inside child; a same-start pair resolves longest
    # first (the (ts, -end) sort)
    events = [
        _ev("call.1", 0, 80),
        _ev("while.1", 0, 60),
        _ev("sort.1", 10, 20),
    ]
    selfs = self_durations_us(events)
    assert selfs == [20.0, 40.0, 20.0]


# ---------------------------------------------------- committed fixture

def test_fixture_attribution_roundtrip():
    rec = attribute(FIXTURE, mode="fixture")
    assert rec["mode"] == "fixture"
    assert rec["source"] == "ops"        # CPU trace: annotations are host-side
    assert rec["n_op_events"] > 0
    for t in ("compute", "select", "comm"):
        assert rec[f"t_{t}_us"] > 0, f"bucket {t} empty in fixture"
        assert 0 < rec[f"frac_{t}"] < 1
    total = sum(rec[f"t_{t}_us"] for t in ("compute", "select", "comm"))
    assert rec["t_total_us"] == pytest.approx(total, abs=0.5)
    fracs = sum(rec[f"frac_{t}"] for t in ("compute", "select", "comm"))
    assert fracs == pytest.approx(1.0, abs=1e-4)
    # the fixture's known op mix lands where the classifier says
    assert "sort" in rec["top_select_ops"]
    assert ("all-reduce" in rec["top_comm_ops"]
            or "collective-permute" in rec["top_comm_ops"])
    table = format_attr(rec)
    for line in ("T_compute", "T_select", "T_comm", "source=ops"):
        assert line in table


def test_fixture_carries_host_annotations():
    means = host_span_means(FIXTURE)
    assert any(n.startswith("train/step") for n in means)
    assert all(v >= 0 for v in means.values())


def test_find_trace_file_resolution(tmp_path):
    assert find_trace_file(FIXTURE) == FIXTURE       # file passthrough
    nested = tmp_path / "plugins" / "profile" / "run1"
    nested.mkdir(parents=True)
    target = nested / "host.trace.json.gz"
    with gzip.open(target, "wt") as fh:
        json.dump({"traceEvents": []}, fh)
    assert find_trace_file(str(tmp_path)) == str(target)
    with pytest.raises(FileNotFoundError):
        find_trace_file(str(tmp_path / "empty"))


def test_op_ranking_shared_parser(tmp_path):
    rank = op_ranking(os.path.dirname(FIXTURE))
    for key in ("trace_file", "steps_lane", "attributed_op_us_total",
                "hlo_category_us", "top_ops"):
        assert key in rank
    assert rank["steps_lane"]["executions"] >= 0
    with pytest.raises(SystemExit):
        op_ranking(str(tmp_path))            # no trace -> usage error


# ------------------------------------------------- synthetic source choice

def _synthetic_trace(span_us, op_us):
    """Device pid 7 with an annotated lane and an op lane; host pid 0."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "annotations"}},
    ]
    t = 0.0
    for name, us in op_us:
        events.append(_ev(name, t, us, pid=7, tid=1, hlo_op=name))
        t += us
    t = 0.0
    for name, us in span_us:
        events.append(_ev(name, t, us, pid=7, tid=2))
        t += us
    return {"traceEvents": events}


def test_attribute_prefers_annotated_device_spans():
    trace = _synthetic_trace(
        span_us=[("train/step", 60.0), ("train/step/compress", 30.0),
                 ("train/step/comm", 10.0)],
        op_us=[("fusion.1", 50.0), ("sort.1", 30.0), ("all-reduce.1", 20.0)])
    rec = attribute(trace)
    assert rec["source"] == "spans"
    assert rec["t_compute_us"] == pytest.approx(60.0)
    assert rec["t_select_us"] == pytest.approx(30.0)
    assert rec["t_comm_us"] == pytest.approx(10.0)


def test_attribute_falls_back_to_ops_on_thin_span_coverage():
    trace = _synthetic_trace(
        span_us=[("train/step", 5.0)],     # < half the op total
        op_us=[("fusion.1", 50.0), ("sort.1", 30.0), ("all-reduce.1", 20.0)])
    rec = attribute(trace)
    assert rec["source"] == "ops"
    assert rec["frac_select"] == pytest.approx(0.3)
    assert rec["frac_comm"] == pytest.approx(0.2)


def test_attribute_mixes_sources_per_class():
    # Only the comm scope propagated onto the device lanes: its span
    # (15µs) covers ≥ half of comm's op time (20µs), while compute and
    # select have no spans at all. The per-class choice keeps span truth
    # for comm and the op classifier for the rest — before PR 15 the
    # thin global coverage dragged ALL three onto ops.
    trace = _synthetic_trace(
        span_us=[("train/step/comm", 15.0)],
        op_us=[("fusion.1", 50.0), ("sort.1", 30.0), ("all-reduce.1", 20.0)])
    rec = attribute(trace)
    assert rec["source"] == "mixed"
    assert rec["source_comm"] == "spans"
    assert rec["source_compute"] == "ops"
    assert rec["source_select"] == "ops"
    assert rec["t_comm_us"] == pytest.approx(15.0)
    assert rec["t_compute_us"] == pytest.approx(50.0)
    assert rec["t_select_us"] == pytest.approx(30.0)
    # the report table prints the per-class pick, not just the label
    table = format_attr(rec)
    assert "source=mixed" in table
    assert "spans" in table and "ops" in table


def test_attribute_thin_span_class_falls_to_ops():
    # A comm span UNDER the coverage floor (5 < 0.5 * 20) must not win:
    # every class lands on ops and the label stays "ops", not "mixed".
    trace = _synthetic_trace(
        span_us=[("train/step/comm", 5.0)],
        op_us=[("fusion.1", 50.0), ("sort.1", 30.0), ("all-reduce.1", 20.0)])
    rec = attribute(trace)
    assert rec["source"] == "ops"
    assert rec["source_comm"] == "ops"
    assert rec["t_comm_us"] == pytest.approx(20.0)


# ---------------------------------------------------- overlap measurement

def test_interval_union_merges_and_drops_degenerate():
    assert _interval_union([]) == []
    assert _interval_union([(5.0, 5.0), (3.0, 1.0)]) == []   # degenerate
    assert _interval_union([(0.0, 2.0), (1.0, 3.0), (3.0, 4.0),
                            (10.0, 11.0)]) == [(0.0, 4.0), (10.0, 11.0)]


def test_intersection_of_disjoint_unions():
    a = [(0.0, 10.0), (20.0, 30.0)]
    b = [(5.0, 25.0), (29.0, 40.0)]
    # [5,10) + [20,25) + [29,30)
    assert _intersection_us(a, b) == pytest.approx(11.0)
    assert _intersection_us(a, []) == 0.0


def test_overlap_fraction_bounds():
    assert overlap_fraction([], [(0.0, 5.0)]) == 0.0           # no comm
    assert overlap_fraction([(0.0, 4.0)], []) == 0.0           # no other
    assert overlap_fraction([(0.0, 4.0)], [(0.0, 4.0)]) == 1.0  # hidden
    assert overlap_fraction([(0.0, 4.0)], [(2.0, 6.0)]) == 0.5


def _two_lane_op_trace(lane1, lane2):
    """Two executor op lanes (args.hlo_op marks op events) so comm on one
    lane can be wall-clock concurrent with compute on the other."""
    events = []
    for tid, ops in ((1, lane1), (2, lane2)):
        for name, ts, dur in ops:
            events.append(_ev(name, ts, dur, pid=3, tid=tid, hlo_op=name))
    return {"traceEvents": events}


def test_attribute_measures_cross_lane_comm_overlap():
    # comm [0,100) on lane 1, compute [50,150) on lane 2: half the comm
    # window is hidden under compute.
    trace = _two_lane_op_trace(
        [("all-reduce.1", 0.0, 100.0)],
        [("fusion.1", 50.0, 100.0)])
    rec = attribute(trace)
    assert rec["overlap_frac"] == pytest.approx(0.5)
    # a strictly serial schedule measures exactly zero
    serial = _two_lane_op_trace(
        [("all-reduce.1", 0.0, 100.0)],
        [("fusion.1", 100.0, 100.0)])
    assert attribute(serial)["overlap_frac"] == 0.0
    # format_attr surfaces the measurement
    assert "overlap_frac=0.5000" in format_attr(rec)


# ------------------------------------------------------ timeline recorder

def test_timeline_recorder_roundtrip(tmp_path):
    tl = TimelineRecorder(rank=0, label="test")
    import time
    t0 = time.perf_counter()
    tl.span_sink("train/io", t0, 0.002)
    tl.span_sink("train/dispatch", t0 + 0.002, 0.005)
    tl.instant("event:nan_loss", args={"rule": "nan_loss",
                                       "severity": "error", "step": 3})
    tl.counter("train", {"loss": 2.5, "throughput": 100.0})
    doc = tl.to_doc()
    assert validate_timeline(doc) == []
    names = [e["name"] for e in doc["traceEvents"]]
    assert "process_name" in names and "thread_name" in names
    assert "train/io" in names and "event:nan_loss" in names
    # write() appends timeline.json to a directory target
    path = tl.write(str(tmp_path))
    assert path == str(tmp_path / "timeline.json")
    with open(path) as fh:
        assert validate_timeline(json.load(fh)) == []


def test_timeline_counter_drops_nan_and_bools():
    tl = TimelineRecorder()
    tl.counter("train", {"loss": float("nan"), "flag": True})
    assert all(e.get("ph") != "C" for e in tl.to_doc()["traceEvents"])
    tl.counter("train", {"loss": 1.0, "bad": float("nan")})
    (c,) = [e for e in tl.to_doc()["traceEvents"] if e.get("ph") == "C"]
    assert c["args"] == {"loss": 1.0}


def test_timeline_from_records_markers_and_counters():
    records = [
        {"kind": "manifest", "time": 0.5, "compression": "gtopk"},
        {"kind": "train", "time": 1.0, "step": 10, "loss": 2.5,
         "throughput": 50.0},
        {"kind": "obs", "time": 1.5, "step": 10, "achieved_density": 0.01,
         "tau": 0.5},
        {"kind": "event", "time": 2.0, "rule": "nan_loss",
         "severity": "error", "step": 11, "message": "boom"},
        {"kind": "stall", "time": 3.0, "label": "train"},
        {"kind": "train", "step": 12, "loss": 2.0},   # no time -> skipped
    ]
    doc = timeline_from_records(records, label="runX")
    assert validate_timeline(doc) == []
    body = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    assert [e["ph"] for e in body] == ["C", "C", "i", "i"]
    marker = body[2]
    assert marker["name"] == "event:nan_loss"
    assert marker["args"]["severity"] == "error"
    assert body[3]["name"] == "stall"


def test_validate_timeline_rejects_bad_docs():
    assert validate_timeline({}) == ["traceEvents is not a list"]
    bad_x = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 0, "ts": 1.0}]}        # no dur
    assert any("without dur" in p for p in validate_timeline(bad_x))
    non_mono = {"traceEvents": [
        {"ph": "i", "name": "a", "pid": 0, "ts": 5.0},
        {"ph": "i", "name": "b", "pid": 0, "ts": 1.0}]}
    assert any("not monotonic" in p for p in validate_timeline(non_mono))


# ------------------------------------------------------ report CLI smokes

def _write_run(path, rows):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "metrics.jsonl"), "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")


def test_report_attr_from_trace_and_run(tmp_path, capsys):
    # straight from the committed fixture trace
    assert obs_report.main(["attr", FIXTURE, "--mode", "fixture"]) == 0
    out = capsys.readouterr().out
    assert "T_compute" in out and "T_select" in out and "T_comm" in out
    # from a run's logged attr record (what the gate smoke writes)
    run = str(tmp_path / "run")
    _write_run(run, [
        {"kind": "attr", "time": 1.0, "rank": 0, "source": "ops",
         "t_compute_us": 900.0, "t_select_us": 80.0, "t_comm_us": 20.0,
         "t_total_us": 1000.0, "frac_compute": 0.9, "frac_select": 0.08,
         "frac_comm": 0.02, "n_op_events": 10},
    ])
    json_out = str(tmp_path / "attr.json")
    assert obs_report.main(["attr", run, "--json", json_out]) == 0
    assert "0.9000" in capsys.readouterr().out
    assert json.load(open(json_out))["frac_compute"] == 0.9
    # a run without attr records is a soft failure, not a crash
    empty = str(tmp_path / "empty")
    _write_run(empty, [{"kind": "train", "time": 1.0, "loss": 2.0}])
    assert obs_report.main(["attr", empty]) == 1
    capsys.readouterr()
    assert obs_report.main(["attr", str(tmp_path / "missing_dir")]) == 2
    capsys.readouterr()


def test_report_events_summarizes_per_rule(tmp_path, capsys):
    run = str(tmp_path / "run")
    _write_run(run, [
        {"kind": "train", "time": 1.0, "step": 1, "loss": 2.0},
        {"kind": "event", "time": 1.1, "rule": "density_collapse",
         "severity": "warn", "step": 2, "value": 0.0001,
         "threshold": 0.001, "message": "collapsed"},
        {"kind": "event", "time": 1.2, "rule": "density_collapse",
         "severity": "warn", "step": 5, "value": 0.0002,
         "threshold": 0.001, "message": "still collapsed"},
        {"kind": "event", "time": 1.3, "rule": "nan_loss",
         "severity": "error", "step": 7, "message": "boom"},
    ])
    json_out = str(tmp_path / "events.json")
    assert obs_report.main(["events", run, "--json", json_out]) == 0
    out = capsys.readouterr().out
    assert "density_collapse" in out and "nan_loss" in out
    summary = json.load(open(json_out))
    dc = summary["density_collapse"]
    assert dc["count"] == 2
    assert dc["first_step"] == 2 and dc["last_step"] == 5
    assert dc["last_value"] == 0.0002
    # an event-free run reads as a clean bill, exit 0
    clean = str(tmp_path / "clean")
    _write_run(clean, [{"kind": "train", "time": 1.0, "loss": 2.0}])
    assert obs_report.main(["events", clean]) == 0
    assert "none recorded" in capsys.readouterr().out


def test_report_timeline_writes_and_validates(tmp_path, capsys):
    run = str(tmp_path / "run")
    _write_run(run, [
        {"kind": "train", "time": 1.0, "step": 2, "loss": 2.5,
         "throughput": 10.0},
        {"kind": "event", "time": 1.5, "rule": "loss_spike",
         "severity": "warn", "step": 3, "value": 7.0, "threshold": 6.0,
         "message": "spiked"},
    ])
    assert obs_report.main(["timeline", run]) == 0
    out = capsys.readouterr().out
    assert "timeline" in out
    path = os.path.join(run, "timeline.json")
    assert os.path.exists(path)
    doc = json.load(open(path))
    assert validate_timeline(doc) == []
    assert any(e.get("name") == "event:loss_spike"
               for e in doc["traceEvents"])
