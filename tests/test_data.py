"""Data pipelines: sharding disjointness, shapes, determinism, synthetic
fallbacks. The reference had no pipeline tests at all (SURVEY.md §4) —
sharding bugs there would surface only as wrong convergence curves.
"""

import numpy as np
import pytest

from gtopkssgd_tpu.data import (
    available_datasets,
    get_dataset,
    partition_indices,
)


def test_partition_disjoint_and_covering():
    n, p = 103, 4
    shards = [partition_indices(n, r, p, seed=1, epoch=2) for r in range(p)]
    allidx = np.concatenate(shards)
    assert len(allidx) == n
    assert len(set(allidx.tolist())) == n  # disjoint cover
    # deterministic across calls, different across epochs
    again = partition_indices(n, 2, p, seed=1, epoch=2)
    np.testing.assert_array_equal(shards[2], again)
    other_epoch = partition_indices(n, 2, p, seed=1, epoch=3)
    assert not np.array_equal(shards[2], other_epoch)
    with pytest.raises(ValueError):
        partition_indices(n, 4, p)


def test_registry():
    assert {"cifar10", "imagenet", "ptb", "an4"} <= set(available_datasets())
    with pytest.raises(ValueError):
        get_dataset("mnist")


def test_cifar_synthetic_batches():
    ds = get_dataset("cifar10", batch_size=16, rank=0, nworkers=2)
    assert ds.synthetic
    batch = next(iter(ds))
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["image"].dtype == np.uint8  # wire format: raw pixels,
    assert batch["label"].shape == (16,) and batch["label"].dtype == np.int32
    assert ds.steps_per_epoch() > 0         # normalization is on-device


def test_cifar_rank_shards_disjoint_same_epoch():
    a = get_dataset("cifar10", batch_size=8, rank=0, nworkers=2, augment=False)
    b = get_dataset("cifar10", batch_size=8, rank=1, nworkers=2, augment=False)
    ia = a.partitioner.indices(0)
    ib = b.partitioner.indices(0)
    assert not set(ia.tolist()) & set(ib.tolist())


def test_cifar_eval_deterministic():
    ds = get_dataset("cifar10", split="test", batch_size=8)
    b1 = next(iter(ds))
    b2 = next(iter(get_dataset("cifar10", split="test", batch_size=8)))
    np.testing.assert_array_equal(b1["image"], b2["image"])


def test_imagenet_synthetic():
    ds = get_dataset("imagenet", batch_size=4, num_classes=50)
    batch = next(iter(ds))
    assert batch["image"].shape == (4, 224, 224, 3)
    assert batch["image"].dtype == np.uint8  # wire format: raw pixels,
    assert batch["label"].max() < 50         # normalization is on-device


def test_ptb_bptt_windows_and_carry_layout():
    ds = get_dataset("ptb", batch_size=4, bptt=35)
    it = iter(ds)
    b1, b2 = next(it), next(it)
    assert b1["tokens"].shape == (4, 35)
    # targets are tokens shifted by one within the stream
    np.testing.assert_array_equal(b1["targets"][:, :-1], b1["tokens"][:, 1:])
    # consecutive windows are temporally contiguous (carry validity)
    np.testing.assert_array_equal(b2["tokens"][:, 0], b1["targets"][:, -1])
    assert ds.vocab_size == 10000


def test_ptb_rank_rows_disjoint():
    a = get_dataset("ptb", batch_size=4, rank=0, nworkers=2)
    b = get_dataset("ptb", batch_size=4, rank=1, nworkers=2)
    assert not np.array_equal(a.inputs, b.inputs)
    assert a.inputs.shape == b.inputs.shape


def test_an4_synthetic_ctc_batches():
    ds = get_dataset("an4", batch_size=4)
    batch = next(iter(ds))
    b, t, f = batch["spectrogram"].shape
    assert (b, f) == (4, 161) and t % 16 == 0
    assert batch["labels"].shape[0] == 4
    assert (batch["input_lengths"] <= t).all()
    assert (batch["label_lengths"] > 0).all()
    assert (batch["labels"] < ds.num_chars).all()


def test_synthetic_class_signal_shared_across_splits():
    """Train and held-out synthetic data must carry the SAME class signal —
    otherwise eval on synthetic runs is structurally chance-level (the bug
    this pins: offsets/signatures were drawn from split-specific streams).
    """
    from gtopkssgd_tpu.data.an4 import _synth_utterances
    from gtopkssgd_tpu.data.cifar import _synthetic

    # CIFAR: per-class mean color of train vs test must agree per class.
    for seed in (0, 7):
        tr_img, tr_lab = _synthetic("train", seed)
        te_img, te_lab = _synthetic("test", seed)
        tr_mean = np.stack([
            tr_img[tr_lab == c].mean(axis=(0, 1, 2)) for c in range(10)
        ])  # [10, 3]
        te_mean = np.stack([
            te_img[te_lab == c].mean(axis=(0, 1, 2)) for c in range(10)
        ])
        # Every class's train-mean color is closest to the SAME class's
        # test-mean color.
        d = np.linalg.norm(tr_mean[:, None, :] - te_mean[None, :, :], axis=-1)
        assert (d.argmin(axis=1) == np.arange(10)).all()

    # ImageNet: the class-offset table itself must be identical.
    from gtopkssgd_tpu.data.imagenet import ImageNetDataset

    tr = ImageNetDataset(split="train", batch_size=2, num_classes=16,
                         image_size=32, seed=3)
    te = ImageNetDataset(split="val", batch_size=2, num_classes=16,
                         image_size=32, seed=3)
    assert tr.synthetic and te.synthetic
    np.testing.assert_array_equal(tr._offsets, te._offsets)

    # AN4: per-char spectral signature direction must correlate across
    # splits (utterance noise differs; the char->spectrum mapping must not).
    tr_utts = _synth_utterances("train", 5, 29)
    te_utts = _synth_utterances("test", 5, 29)

    def char_means(utts):
        acc = {c: [] for c in range(1, 29)}
        for u in utts[:64]:
            L = len(u["labels"])
            fp = u["spec"].shape[0] // L
            for j, ch in enumerate(u["labels"]):
                acc[int(ch)].append(u["spec"][j * fp:(j + 1) * fp].mean(0))
        return {c: np.mean(v, axis=0) for c, v in acc.items() if v}

    tm, em = char_means(tr_utts), char_means(te_utts)
    common = sorted(set(tm) & set(em))
    assert len(common) >= 20
    cos = [
        float(np.dot(tm[c], em[c])
              / (np.linalg.norm(tm[c]) * np.linalg.norm(em[c]) + 1e-9))
        for c in common
    ]
    assert np.mean(cos) > 0.5, np.mean(cos)


class TestSynthHard:
    """The discriminative synthetic-CIFAR variant (data/cifar.py::_synthetic
    hard=True): weak spatial class patterns + train-only label noise."""

    def test_train_label_noise_rate(self):
        from gtopkssgd_tpu.data.cifar import _synthetic

        _, easy = _synthetic("train", seed=7)
        _, hard = _synthetic("train", seed=7, hard=True)
        flipped = (easy != hard).mean()
        # 10% resampled uniformly over 10 classes -> ~9% actually differ
        assert 0.05 < flipped < 0.14, flipped

    def test_test_split_labels_clean_and_signal_shared(self):
        from gtopkssgd_tpu.data.cifar import _synthetic

        imgs_a, lab_a = _synthetic("test", seed=7, hard=True)
        # test-split labels must be CLEAN (noise is train-only)
        import numpy as _np
        _np.testing.assert_array_equal(lab_a, _synthetic("test", seed=7)[1])
        # class signal must be split-independent: average image of one
        # class in train and test must correlate (shared pattern), while
        # two different classes must not
        timgs, tlab = _synthetic("train", seed=7, hard=True)
        import numpy as np

        def class_mean(imgs, lab, c):
            m = imgs[lab == c].astype(np.float32).mean(0)
            return (m - m.mean()).ravel()

        same = np.corrcoef(class_mean(imgs_a, lab_a, 3),
                           class_mean(timgs, tlab, 3))[0, 1]
        diff = np.corrcoef(class_mean(imgs_a, lab_a, 3),
                           class_mean(timgs, tlab, 4))[0, 1]
        assert same > 0.3 and abs(diff) < 0.2, (same, diff)

    def test_signal_is_spatial_not_flat(self):
        from gtopkssgd_tpu.data.partition import signal_rng
        import numpy as np

        pat = signal_rng(7).standard_normal((10, 32, 32, 3)) * 0.07
        # per-class pattern varies across pixels (a flat offset would not)
        assert np.std(pat[0], axis=(0, 1)).min() > 0.01

    def test_trainer_plumbing(self):
        from gtopkssgd_tpu.trainer import TrainConfig

        cfg = TrainConfig(dnn="resnet20", synth_hard=True).resolved()
        assert cfg.synth_hard and cfg.dataset == "cifar10"
