"""Observability subsystem (gtopkssgd_tpu.obs): on-device counters,
tracing spans, the stall watchdog, and the report CLI.

Counter semantics are pinned on tiny models where the expected values are
computable by hand; the watchdog is driven with a deliberately-stalled
armed region (never a real wedged backend); the report CLI round-trips a
synthetic metrics.jsonl.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gtopkssgd_tpu.obs import (
    HALT_EXIT_CODE,
    TELEMETRY_FIELDS,
    AnomalyHalt,
    AnomalyMonitor,
    StallWatchdog,
    Thresholds,
    Tracer,
    counters as obs_counters,
)
from gtopkssgd_tpu.obs import report as obs_report
from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.ops import k_for_density
from gtopkssgd_tpu.utils.metrics import MetricsLogger


def _tiny_params():
    return {
        "w": jnp.arange(1, 101, dtype=jnp.float32).reshape(10, 10) / 100,
        "b": jnp.ones((7,), jnp.float32),
    }


def _tiny_grads(params):
    # strictly nonzero, globally distinct magnitudes -> top-k has no ties
    # and the threshold path keeps exactly k elements
    leaves, treedef = jax.tree.flatten(params)
    total = sum(x.size for x in leaves)
    flat = jnp.arange(1, total + 1, dtype=jnp.float32) * 1e-3
    out, off = [], 0
    for x in leaves:
        out.append(flat[off:off + x.size].reshape(x.shape))
        off += x.size
    return jax.tree.unflatten(treedef, out)


# ------------------------------------------------------------- counters

def test_gtopk_counters_single_worker():
    params = _tiny_params()
    grads = _tiny_grads(params)
    n = sum(x.size for x in jax.tree.leaves(params))
    rho = 0.05
    tx = gtopk_sgd(0.1, compression="gtopk", density=rho, axis_name=None,
                   telemetry=True)
    state = tx.init(params)
    # init telemetry is the zero struct with the full field set
    assert set(state.telemetry) == set(TELEMETRY_FIELDS)
    _, state = jax.jit(tx.update)(grads, state, params)
    tel = {k: float(v) for k, v in state.telemetry.items()}

    k = k_for_density(n, rho)
    # achieved density within one element of the requested rho
    assert abs(tel["sent_elems"] - k) <= 1
    assert abs(tel["achieved_density"] - k / n) <= 1.0 / n
    assert tel["tau"] > 0
    assert tel["residual_norm"] > 0          # error feedback accumulated
    assert tel["grad_norm_pre"] > 0
    assert 0 < tel["grad_norm_post"] < tel["grad_norm_pre"]
    assert tel["wire_bytes"] == 8 * k        # p=1: one (f32, i32) set


def test_dense_counters_single_worker():
    params = _tiny_params()
    grads = _tiny_grads(params)
    n = sum(x.size for x in jax.tree.leaves(params))
    tx = gtopk_sgd(0.1, compression="dense", axis_name=None, telemetry=True)
    state = tx.init(params)
    _, state = jax.jit(tx.update)(grads, state, params)
    tel = {k: float(v) for k, v in state.telemetry.items()}
    assert tel["achieved_density"] == 1.0
    assert tel["sent_elems"] == n
    assert tel["residual_norm"] == 0.0       # dense mode: no error feedback
    assert tel["tau"] == 0.0
    assert tel["grad_norm_post"] == pytest.approx(tel["grad_norm_pre"],
                                                  rel=1e-6)
    assert tel["wire_bytes"] == 4 * n


def test_layerwise_counters_respect_per_leaf_quota():
    params = _tiny_params()
    grads = _tiny_grads(params)
    rho = 0.05
    tx = gtopk_sgd(0.1, compression="gtopk_layerwise", density=rho,
                   axis_name=None, telemetry=True)
    state = tx.init(params)
    _, state = jax.jit(tx.update)(grads, state, params)
    tel = {k: float(v) for k, v in state.telemetry.items()}
    k_total = sum(k_for_density(int(x.size), rho)
                  for x in jax.tree.leaves(params))
    assert abs(tel["sent_elems"] - k_total) <= 1
    assert tel["tau"] > 0 and tel["residual_norm"] > 0


def test_telemetry_off_keeps_state_empty():
    params = _tiny_params()
    tx = gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None)
    state = tx.init(params)
    assert state.telemetry == ()
    _, state = jax.jit(tx.update)(_tiny_grads(params), state, params)
    assert state.telemetry == ()


def test_warmup_phase_reads_as_dense_then_sparse():
    params = _tiny_params()
    grads = _tiny_grads(params)
    tx = gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None,
                   warmup_dense_steps=1, telemetry=True)
    state = tx.init(params)
    _, state = jax.jit(tx.update)(grads, state, params)
    assert float(state.telemetry["achieved_density"]) == pytest.approx(
        1.0, rel=1e-6)                                        # warm-up step
    _, state = jax.jit(tx.update)(grads, state, params)
    assert float(state.telemetry["achieved_density"]) < 0.1   # sparse now


def test_counters_replicated_under_spmd_mesh():
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from gtopkssgd_tpu.optimizer import (
        GTopKSGDState,
        expand_residual_per_device,
    )

    p = 8
    mesh = Mesh(np.array(jax.devices()[:p]), ("dp",))
    params = _tiny_params()
    n = sum(x.size for x in jax.tree.leaves(params))
    rho = 0.05
    tx = gtopk_sgd(0.1, compression="gtopk", density=rho, axis_name="dp",
                   telemetry=True)
    state = expand_residual_per_device(jax.jit(tx.init)(params), p, mesh)
    spec = GTopKSGDState(count=P(), residual=P("dp"), inner=P(),
                         telemetry=P())

    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), spec, P()),
             out_specs=(P(), spec), check_vma=False)
    def step(grads, st, prms):
        g = jax.tree.map(lambda x: x[0], grads)
        s = st._replace(residual=jax.tree.map(lambda r: r[0], st.residual))
        upd, s2 = tx.update(g, s, prms)
        return upd, s2._replace(
            residual=jax.tree.map(lambda r: r[None], s2.residual))

    base = _tiny_grads(params)
    grads = jax.tree.map(
        lambda x: jnp.stack([x * (1.0 + 0.1 * i) for i in range(p)]), base)
    _, state = jax.jit(step)(grads, state, params)
    tel = {k: float(v) for k, v in state.telemetry.items()}
    k = k_for_density(n, rho)
    assert abs(tel["sent_elems"] - k) <= 1    # pmean of identical counts
    assert tel["tau"] > 0 and tel["residual_norm"] > 0
    # wire model: gtopk hypercube sends k pairs per round, log2(p) rounds
    assert tel["wire_bytes"] == 8 * k * int(np.log2(p))


def test_counter_helpers_edge_cases():
    assert float(obs_counters.tree_l2(())) == 0.0
    assert float(obs_counters.selected_tau(jnp.zeros(4))) == 0.0
    vals = jnp.array([0.0, -0.5, 2.0, 0.0])
    assert float(obs_counters.selected_tau(vals)) == 0.5
    assert float(obs_counters.sent_count(vals)) == 2.0
    keep = jnp.array([False, True, True, False])
    acc = jnp.array([9.0, -3.0, 1.0, 9.0])
    assert float(obs_counters.keep_tau(keep, acc)) == 1.0
    assert float(obs_counters.keep_tau(jnp.zeros(4, bool), acc)) == 0.0
    # residual_l2 reads v (not u) under momentum correction
    res = {"v": jnp.array([3.0, 4.0]), "u": jnp.array([100.0, 100.0])}
    assert float(obs_counters.residual_l2(res)) == 5.0


# --------------------------------------------------------------- spans

def test_span_nesting_builds_paths():
    tr = Tracer()
    with tr.span("train"):
        with tr.span("io"):
            pass
        with tr.span("dispatch"):
            pass
    with tr.span("eval"):
        pass
    summary = tr.stats.summary()
    assert set(summary) == {"train", "train/io", "train/dispatch", "eval"}
    assert all(sec >= 0 for sec in summary.values())
    assert tr.current_path == ""             # stack fully unwound


def test_span_nesting_is_per_thread():
    tr = Tracer()
    seen = {}
    release = threading.Event()

    def worker():
        with tr.span("worker_phase"):
            seen["inside"] = tr.current_path
            release.wait(2.0)

    with tr.span("main_phase"):
        t = threading.Thread(target=worker)
        t.start()
        while "inside" not in seen and t.is_alive():
            time.sleep(0.01)
        # the worker's open span must not nest under main's
        assert seen["inside"] == "worker_phase"
        release.set()
        t.join()
    assert "main_phase/worker_phase" not in tr.stats.summary()


def test_span_flush_logs_one_record_and_resets(tmp_path):
    with MetricsLogger(str(tmp_path)) as metrics:
        tr = Tracer(metrics=metrics)
        with tr.span("io"):
            pass
        summary = tr.flush(step=7)
        assert "io" in summary
        assert tr.stats.summary() == {}      # reset after flush
        assert tr.flush(step=8) == {}        # empty window -> no record
    recs = [json.loads(l) for l in
            open(os.path.join(tmp_path, "metrics.jsonl"))]
    spans = [r for r in recs if r["kind"] == "spans"]
    assert len(spans) == 1 and spans[0]["step"] == 7 and "io" in spans[0]


def test_disabled_tracer_and_decorator():
    tr = Tracer(enabled=False)
    with tr.span("x"):
        pass
    assert tr.stats.summary() == {}
    tr2 = Tracer()

    @tr2.annotate()
    def compute():
        return 41 + 1

    assert compute() == 42
    assert "compute" in tr2.stats.summary()


# ------------------------------------------------------------ watchdog

def test_watchdog_fires_on_stalled_region():
    fired = []
    wd = StallWatchdog(0.15, poll_s=0.03, on_stall=fired.append,
                       diagnostics=lambda: {"phase_means_s": {"io": 1.5}})
    try:
        wd.arm("train_step", step=12)
        wd.heartbeat(step=12)
        deadline = time.monotonic() + 3.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.02)                 # the "stalled" main thread
        assert wd.fired
        (rec,) = fired
        assert rec["kind"] == "stall"
        assert rec["label"] == "train_step"
        assert rec["armed_step"] == 12
        assert rec["last_completed_step"] == 12
        assert rec["waited_s"] >= 0.15
        assert rec["phase_means_s"] == {"io": 1.5}
        assert "device" in rec
    finally:
        wd.close()


def test_watchdog_heartbeat_prevents_firing():
    fired = []
    wd = StallWatchdog(0.25, poll_s=0.03, on_stall=fired.append)
    try:
        wd.arm("train", step=0)
        for s in range(8):                   # 0.4s total, never 0.25s idle
            time.sleep(0.05)
            wd.heartbeat(step=s)
        wd.disarm()
        time.sleep(0.3)                      # disarmed: silence
        assert not wd.fired and fired == []
    finally:
        wd.close()


def test_watchdog_fires_once_and_validates():
    with pytest.raises(ValueError):
        StallWatchdog(0.0)
    fired = []
    wd = StallWatchdog(0.05, poll_s=0.02, on_stall=fired.append)
    try:
        with wd.watch("region"):
            time.sleep(0.4)                  # several deadlines deep
        time.sleep(0.1)
        assert len(fired) == 1               # one diagnostic, not a storm
    finally:
        wd.close()


def test_watchdog_diagnostics_failure_is_contained():
    fired = []

    def bad_diag():
        raise RuntimeError("host state gone")

    wd = StallWatchdog(0.05, poll_s=0.02, on_stall=fired.append,
                       diagnostics=bad_diag)
    try:
        wd.arm("x")
        deadline = time.monotonic() + 2.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fired and "diagnostics_error" in fired[0]
    finally:
        wd.close()


# ----------------------------------------------------------- report CLI

def _write_run(path, rows):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "metrics.jsonl"), "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")


def test_report_roundtrips_synthetic_run(tmp_path, capsys):
    run = str(tmp_path / "runA")
    _write_run(run, [
        {"kind": "train", "time": 1.0, "rank": 0, "step": 10, "loss": 2.5},
        {"kind": "train", "time": 2.0, "rank": 0, "step": 20, "loss": 2.0},
        {"kind": "obs", "time": 2.0, "rank": 0, "step": 20,
         "achieved_density": 0.001, "wire_bytes": 21800.0},
    ])
    # torn final line (the watchdog hard-exit case) must not be fatal
    with open(os.path.join(run, "metrics.jsonl"), "a") as fh:
        fh.write('{"kind": "train", "loss": 1.')
    assert obs_report.main([run]) == 0
    out = capsys.readouterr().out
    assert "skipped 1 malformed line" in out
    assert "[train]" in out and "[obs]" in out
    assert "achieved_density" in out and "loss" in out
    summary = obs_report.summarize(obs_report.load_records(run)[0])
    assert summary["train"]["loss"] == {
        "count": 2, "mean": 2.25, "min": 2.0, "max": 2.5, "last": 2.0}


def test_report_compares_two_runs(tmp_path, capsys):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    _write_run(a, [{"kind": "obs", "time": 1.0, "rank": 0,
                    "wire_bytes": 100.0, "achieved_density": 0.001}])
    _write_run(b, [{"kind": "obs", "time": 1.0, "rank": 0,
                    "wire_bytes": 300.0, "achieved_density": 0.001}])
    json_out = str(tmp_path / "diff.json")
    assert obs_report.main([a, b, "--json", json_out]) == 0
    out = capsys.readouterr().out
    assert "wire_bytes" in out and "+200" in out
    with open(json_out) as fh:
        payload = json.load(fh)
    d = payload["diff"]["obs"]["wire_bytes"]
    assert d["delta"] == 200.0 and d["delta_pct"] == pytest.approx(200.0)


def test_report_compare_zero_baseline_prints_dash(tmp_path, capsys):
    # a counter that was 0 in the baseline has no meaningful percent
    # change — the report must print "—", not "+nan%"/"+inf%"
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    _write_run(a, [{"kind": "obs", "time": 1.0, "rank": 0,
                    "wire_bytes": 0.0}])
    _write_run(b, [{"kind": "obs", "time": 1.0, "rank": 0,
                    "wire_bytes": 300.0}])
    json_out = str(tmp_path / "diff.json")
    assert obs_report.main([a, b, "--json", json_out]) == 0
    out = capsys.readouterr().out
    assert "—" in out
    assert "nan%" not in out and "inf%" not in out
    d = json.load(open(json_out))["diff"]["obs"]["wire_bytes"]
    assert d["delta"] == 300.0 and d["delta_pct"] is None


def test_report_errors_are_exit_code_2(tmp_path, capsys):
    assert obs_report.main([str(tmp_path / "missing")]) == 2
    capsys.readouterr()


# ----------------------------------------------- metrics logger lifecycle

def test_metrics_logger_context_manager(tmp_path):
    with MetricsLogger(str(tmp_path)) as m:
        m.log("train", step=1, loss=2.0)
        m.log("eval", step=1, top1=0.5)
    assert m._fh is None                     # guaranteed close on exit
    recs = [json.loads(l) for l in
            open(os.path.join(tmp_path, "metrics.jsonl"))]
    assert [r["kind"] for r in recs] == ["train", "eval"]
    m.log("train", step=2, loss=1.0)         # post-close: no crash, no write
    assert len(open(os.path.join(tmp_path, "metrics.jsonl")).readlines()) == 2


def test_metrics_logger_rank_nonzero_writes_nothing(tmp_path):
    with MetricsLogger(str(tmp_path / "r1"), rank=1) as m:
        m.log("train", step=1, loss=2.0)
    assert not os.path.exists(str(tmp_path / "r1" / "metrics.jsonl"))


def test_metrics_logger_flush_is_durable_and_kind_validated(tmp_path):
    m = MetricsLogger(str(tmp_path))
    try:
        m.log("event", flush=True, rule="nan_loss", severity="error", step=3)
        # flush=True fsyncs: the record is on disk while the logger is
        # still open (what keeps a diagnosis through a hard kill)
        recs = [json.loads(l) for l in
                open(os.path.join(tmp_path, "metrics.jsonl"))]
        assert recs[-1]["rule"] == "nan_loss"
        with pytest.raises(ValueError):
            m.log("", step=1)
        with pytest.raises(ValueError):
            m.log(None, step=1)
    finally:
        m.close()


# ------------------------------------------------------- anomaly monitor

def test_monitor_nan_loss_fires_error_event():
    mon = AnomalyMonitor(rho=0.01)
    (ev,) = mon.observe(3, loss=float("nan"))
    assert ev["rule"] == "nan_loss" and ev["severity"] == "error"
    assert ev["step"] == 3 and ev["value"] is None
    (ev,) = mon.observe(4, loss=float("inf"))
    assert ev["rule"] == "nan_loss"
    assert mon.summary() == {"nan_loss": 2}


def test_monitor_loss_spike_needs_warmup_and_variance():
    mon = AnomalyMonitor(thresholds=Thresholds(loss_warmup=3))
    for step, loss in enumerate([1.0, 1.02, 0.98, 1.0, 1.01]):
        assert mon.observe(step, loss=loss) == []
    (ev,) = mon.observe(9, loss=50.0)          # many sigma above the EWMA
    assert ev["rule"] == "loss_spike" and ev["severity"] == "warn"
    assert ev["value"] > ev["threshold"] == 6.0
    # a steady loss after the spike decays back to silence
    assert mon.observe(10, loss=1.0) == []


def test_monitor_density_collapse_requires_rho():
    mon = AnomalyMonitor(rho=0.01)
    (ev,) = mon.observe(1, loss=1.0,
                        telemetry={"achieved_density": 0.0001})
    assert ev["rule"] == "density_collapse"
    assert ev["threshold"] == pytest.approx(0.001)
    # healthy density: silent
    assert mon.observe(2, loss=1.0,
                       telemetry={"achieved_density": 0.01}) == []
    # dense runs (rho None) never evaluate the rule
    dense = AnomalyMonitor(rho=None)
    assert dense.observe(1, loss=1.0,
                         telemetry={"achieved_density": 0.0}) == []


def test_monitor_residual_blowup_and_age_runaway():
    mon = AnomalyMonitor(rho=0.01, thresholds=Thresholds(loss_warmup=3))
    for step in range(4):
        assert mon.observe(step, telemetry={"residual_norm": 1.0}) == []
    (ev,) = mon.observe(9, telemetry={"residual_norm": 100.0})
    assert ev["rule"] == "residual_blowup"
    # auto age threshold is 100/rho = 1e4 steps
    assert Thresholds().age_max(0.01) == pytest.approx(1e4)
    assert Thresholds(residual_age_max=5.0).age_max(0.01) == 5.0
    (ev,) = AnomalyMonitor(rho=0.01).observe(1, max_residual_age=2e4)
    assert ev["rule"] == "residual_age_runaway"
    assert AnomalyMonitor(rho=None).observe(1, max_residual_age=1e9) == []


def test_monitor_halt_severity_ordering(tmp_path):
    with pytest.raises(ValueError):
        AnomalyMonitor(halt_on="fatal")
    # error-level halt ignores warns but trips on nan_loss — and the
    # event record is durably written BEFORE the raise
    with MetricsLogger(str(tmp_path)) as metrics:
        mon = AnomalyMonitor(metrics=metrics, rho=0.01, halt_on="error")
        assert [e["rule"] for e in mon.observe(
            1, loss=1.0, telemetry={"achieved_density": 0.0})] \
            == ["density_collapse"]
        with pytest.raises(AnomalyHalt) as exc:
            mon.observe(2, loss=float("nan"))
        assert exc.value.event["rule"] == "nan_loss"
        recs = [json.loads(l) for l in
                open(os.path.join(tmp_path, "metrics.jsonl"))]
        assert [r["rule"] for r in recs if r["kind"] == "event"] \
            == ["density_collapse", "nan_loss"]
    # warn-level halt trips on the first warn
    mon = AnomalyMonitor(rho=0.01, halt_on="warn")
    with pytest.raises(AnomalyHalt):
        mon.observe(1, loss=1.0, telemetry={"achieved_density": 0.0})


# ------------------------------------------------------- trainer smoke

def test_trainer_emits_obs_records_and_report_reads_them(tmp_path):
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    out = str(tmp_path / "run")
    with Trainer(TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=1, compression="gtopk",
            density=0.01, log_interval=2, eval_batches=1, max_epochs=1,
            out_dir=out)) as t:
        t.train(2)
    recs = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    # provenance header is the FIRST record of every metrics.jsonl
    man = recs[0]
    assert man["kind"] == "manifest"
    assert man["compression"] == "gtopk"
    assert man["mesh_shape"] == {"dp": 1}
    assert man["jax_version"] == jax.__version__
    assert "config_hash" in man and "git_sha" in man
    obs = [r for r in recs if r["kind"] == "obs"]
    assert len(obs) == 2                     # obs_interval=1 -> per step
    for r in obs:
        for field in ("achieved_density", "tau", "residual_norm",
                      "wire_bytes", "grad_norm_pre", "grad_norm_post",
                      "sent_elems", "step"):
            assert field in r
    assert any(r["kind"] == "spans" for r in recs)  # tracer flushed
    # the report CLI aggregates what the trainer wrote
    summary = obs_report.summarize(recs)
    assert summary["obs"]["achieved_density"]["count"] == 2


# ------------------------------------------------- per-layer telemetry

def _layer_tel(state):
    return {f: np.asarray(v) for f, v in state.telemetry["layers"].items()}


@pytest.mark.parametrize(
    "mode", ["gtopk", "allgather", "gtopk_hier", "gtopk_layerwise"])
def test_per_layer_telemetry_sparse_modes(mode):
    params = _tiny_params()
    grads = _tiny_grads(params)
    rho = 0.05
    tx = gtopk_sgd(0.1, compression=mode, density=rho, axis_name=None,
                   telemetry=True, telemetry_layers=True)
    state = tx.init(params)
    sizes = np.array([x.size for x in jax.tree.leaves(params)])
    lay = _layer_tel(state)
    assert set(lay) == set(obs_counters.LAYER_FIELDS)
    assert all(v.shape == (len(sizes),) for v in lay.values())
    tel_def = jax.tree.structure(state.telemetry)

    _, state = jax.jit(tx.update)(grads, state, params)
    lay = _layer_tel(state)
    # per-layer sent counts reassemble the whole-model counter exactly
    sent = lay["density"] * sizes
    assert np.allclose(sent.sum(), float(state.telemetry["sent_elems"]),
                       atol=1.0)
    assert (lay["grad_norm_pre"] > 0).all()
    # flat modes may legitimately starve a small layer (all its coords
    # below the global tau -> m_k 0); mass ratios stay in [0, 1] and at
    # least one layer captures mass
    assert ((lay["m_k"] >= 0) & (lay["m_k"] <= 1 + 1e-6)).all()
    assert lay["m_k"].max() > 0
    # the whole-model mass ratio is an acc-mass-weighted mean of the
    # per-layer ones, so it must land inside their range
    m = float(state.telemetry["m_k"])
    assert lay["m_k"].min() - 1e-6 <= m <= lay["m_k"].max() + 1e-6
    # treedef is stable across steps (lax.cond/scan compatibility)
    _, state = jax.jit(tx.update)(grads, state, params)
    assert jax.tree.structure(state.telemetry) == tel_def


def test_per_layer_telemetry_dense_noop():
    params = _tiny_params()
    grads = _tiny_grads(params)   # strictly nonzero -> every coord ships
    tx = gtopk_sgd(0.1, compression="dense", axis_name=None,
                   telemetry=True, telemetry_layers=True)
    state = tx.init(params)
    _, state = jax.jit(tx.update)(grads, state, params)
    lay = _layer_tel(state)
    assert np.allclose(lay["density"], 1.0)
    assert np.allclose(lay["m_k"], 1.0)
    assert np.allclose(lay["tau"], 0.0)
    assert np.allclose(lay["residual_norm"], 0.0)  # no error feedback
    assert np.allclose(lay["residual_age"], 0.0)   # everything delivered


def test_residual_age_monotonic():
    params = _tiny_params()
    grads = _tiny_grads(params)
    tx = gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None,
                   telemetry=True, telemetry_layers=True)
    state = tx.init(params)
    ages = [np.asarray(state.telemetry["age"])]
    step = jax.jit(tx.update)
    for _ in range(3):
        _, state = step(grads, state, params)
        ages.append(np.asarray(state.telemetry["age"]))
    for i, (prev, cur) in enumerate(zip(ages, ages[1:]), start=1):
        # every coordinate either shipped (age resets to 0) or aged by 1
        assert np.all((cur == 0) | (cur == prev + 1))
        assert cur.max() <= i
    # constant grads + error feedback: the small-magnitude tail keeps
    # losing the selection, so SOME coordinate is older than one step
    assert ages[-1].max() >= 2
    # and the per-layer mean age reported matches the raw buffer
    lay = _layer_tel(state)
    off, means = 0, []
    for x in jax.tree.leaves(params):
        means.append(ages[-1][off:off + x.size].mean())
        off += x.size
    assert np.allclose(lay["residual_age"], means, rtol=1e-5)


def test_recall_audit_sampling():
    params = _tiny_params()
    grads = _tiny_grads(params)
    tx = gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None,
                   telemetry=True, telemetry_audit_interval=2)
    state = tx.init(params)
    assert float(state.telemetry["audit_recall"]) == -1.0  # never audited
    step = jax.jit(tx.update)
    _, state = step(grads, state, params)      # count=0 -> audited
    r1 = float(state.telemetry["audit_recall"])
    # exact threshold selection on all-distinct magnitudes IS the top-k
    assert r1 == pytest.approx(1.0)
    _, state = step(grads, state, params)      # count=1 -> carries value
    assert float(state.telemetry["audit_recall"]) == pytest.approx(r1)


def test_audit_flags_require_telemetry():
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None,
                  telemetry_layers=True)
    with pytest.raises(ValueError):
        gtopk_sgd(0.1, compression="gtopk", density=0.05, axis_name=None,
                  telemetry_audit_interval=2)


# ------------------------------------------------------------- manifest

def test_manifest_roundtrip_and_hash_stability(tmp_path):
    from gtopkssgd_tpu.obs.manifest import config_hash, git_sha, run_manifest

    cfg = {"dnn": "resnet20", "density": 0.01, "nworkers": 2,
           "batch_size": 4, "seed": 42, "compression": "gtopk"}
    man = run_manifest(cfg, extra_field="x")
    # json round-trip (what MetricsLogger does) preserves everything
    back = json.loads(json.dumps(man))
    assert back == man
    assert back["config_hash"] == config_hash(cfg)
    assert back["extra_field"] == "x"
    for key in ("dnn", "density", "nworkers", "batch_size", "seed"):
        assert back[key] == cfg[key]
    # hash is insertion-order independent and value sensitive
    assert config_hash(dict(reversed(list(cfg.items())))) == config_hash(cfg)
    assert config_hash({**cfg, "density": 0.02}) != config_hash(cfg)
    sha = git_sha()
    assert sha is None or isinstance(sha, str)


# ------------------------------------------------------- report gate

def _synthetic_run(tmp_path, sent=100.0):
    run = tmp_path / "run"
    run.mkdir(exist_ok=True)
    recs = [
        {"kind": "manifest", "compression": "gtopk", "nworkers": 2},
        {"kind": "obs", "step": 1, "sent_elems": sent, "tau": 0.5},
        {"kind": "obs", "step": 2, "sent_elems": sent, "tau": 0.7},
        {"kind": "layers", "step": 2, "layer": "w", "density": 0.05},
        {"kind": "layers", "step": 2, "layer": "b", "density": 0.10},
    ]
    with open(run / "metrics.jsonl", "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")
    return str(run)


def _baseline(tmp_path, **overrides):
    base = {
        "manifest": {"compression": "gtopk"},
        "checks": [
            {"kind": "obs", "field": "sent_elems", "stat": "mean",
             "expect": 100.0, "rtol": 0.05},
            {"kind": "obs", "field": "tau", "stat": "last",
             "expect": 0.7, "atol": 0.01},
            {"kind": "layers", "layer": "w", "field": "density",
             "stat": "mean", "expect": 0.05, "rtol": 0.1},
        ],
    }
    base.update(overrides)
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(base))
    return str(path)


def test_report_gate_passes_within_tolerance(tmp_path):
    run = _synthetic_run(tmp_path)
    assert obs_report.run_gate(run, _baseline(tmp_path)) == 0


def test_report_gate_fails_on_drift(tmp_path):
    run = _synthetic_run(tmp_path, sent=120.0)   # > 5% off the baseline
    assert obs_report.run_gate(run, _baseline(tmp_path)) == 1


def test_report_gate_fails_on_missing_field_and_manifest(tmp_path):
    run = _synthetic_run(tmp_path)
    base = _baseline(tmp_path, checks=[
        {"kind": "obs", "field": "vanished", "expect": 1.0, "rtol": 0.5}])
    assert obs_report.run_gate(run, base) == 1     # silently-gone counter
    base = _baseline(tmp_path, manifest={"compression": "dense"})
    assert obs_report.run_gate(run, base) == 1     # provenance mismatch


def test_report_gate_usage_errors(tmp_path):
    run = _synthetic_run(tmp_path)
    assert obs_report.run_gate(run, str(tmp_path / "nope.json")) == 2
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"checks": []}))
    assert obs_report.run_gate(run, str(bad)) == 2


def test_report_gate_write_restamps_expectations(tmp_path):
    run = _synthetic_run(tmp_path, sent=120.0)
    base = _baseline(tmp_path)
    out = str(tmp_path / "new_baseline.json")
    assert obs_report.run_gate(run, base, write=out) == 1
    regen = json.loads(open(out).read())
    by_field = {c["field"]: c for c in regen["checks"]}
    assert by_field["sent_elems"]["expect"] == pytest.approx(120.0)
    assert by_field["sent_elems"]["rtol"] == 0.05   # spec preserved
    assert obs_report.run_gate(run, out) == 0       # regenerated -> green


@pytest.mark.slow  # ~193 s: full smoke (train + mem + chaos + overlap +
# critpath arms). run_gate's check math, write-restamp and failure modes
# stay tier-1 via the test_report_gate_* tests above; drift against the
# committed baseline is enforced per-commit by regenerating with
# --write-baseline and on the slow tier.
def test_gate_smoke_matches_committed_baseline(tmp_path):
    """The drift gate: the canonical tiny gtopk_layerwise run must
    stay inside the committed baseline's tolerances. If an INTENTIONAL
    change moves a counter, regenerate with
    `python benchmarks/obs_gate_smoke.py --write-baseline` in the same
    commit."""
    from benchmarks.obs_gate_smoke import BASELINE, run_smoke

    out = run_smoke(str(tmp_path / "run"))
    assert obs_report.run_gate(out, BASELINE) == 0


# --------------------------------------------- anomaly events in training

def _event_cfg(out, **overrides):
    """2-device CPU-mesh trainer at the monitor's tightest cadence."""
    from gtopkssgd_tpu.trainer import TrainConfig

    kw = dict(dnn="resnet20", batch_size=4, nworkers=2,
              compression="gtopk_layerwise", density=0.01, seed=42,
              max_epochs=1, log_interval=1, obs_interval=1, eval_batches=1,
              out_dir=out)
    kw.update(overrides)
    return TrainConfig(**kw)


def _patch_loss(monkeypatch, scale):
    """Wrap Trainer._loss_fn so the scalar loss becomes loss * scale —
    NaN injects a divergence, 0.0 zeroes every gradient (and therefore
    the achieved density) without touching the trainer's plumbing."""
    from gtopkssgd_tpu.trainer import Trainer

    orig = Trainer._loss_fn

    def poisoned(self, params, batch_stats, carry, batch, rng, train):
        loss, rest = orig(self, params, batch_stats, carry, batch, rng,
                          train)
        return loss * scale, rest

    monkeypatch.setattr(Trainer, "_loss_fn", poisoned)


def test_trainer_nan_loss_event_and_halt_within_one_step(
        tmp_path, monkeypatch):
    """The acceptance property: an injected NaN produces a durably
    written event record AND (with --obs-halt-on error semantics) stops
    the run, both within a single step."""
    from gtopkssgd_tpu.trainer import Trainer

    _patch_loss(monkeypatch, jnp.nan)
    out = str(tmp_path / "run")
    with Trainer(_event_cfg(out, obs_halt_on="error")) as t:
        with pytest.raises(AnomalyHalt) as exc:
            t.train(2)
    assert exc.value.event["rule"] == "nan_loss"
    recs = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    evs = [r for r in recs if r["kind"] == "event"]
    assert evs, "no event record written"
    assert evs[0]["rule"] == "nan_loss"
    assert evs[0]["severity"] == "error"
    assert evs[0]["step"] == 1               # caught within one step
    # the report CLI reads the stream back
    assert obs_report.main(["events", out]) == 0


def test_trainer_density_collapse_event_and_timeline(
        tmp_path, monkeypatch):
    _patch_loss(monkeypatch, 0.0)            # zero grads -> nothing selected
    from gtopkssgd_tpu.trainer import Trainer

    out = str(tmp_path / "run")
    with Trainer(_event_cfg(out, obs_timeline=out)) as t:
        t.train(2)                           # no halt configured: runs on
    recs = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    evs = [r for r in recs if r["kind"] == "event"]
    rules = {r["rule"] for r in evs}
    assert "density_collapse" in rules
    assert "nan_loss" not in rules           # loss 0.0 is finite
    first = min(r["step"] for r in evs if r["rule"] == "density_collapse")
    assert first == 1                        # caught within one step
    # the live timeline was written on exit and carries the marker
    from gtopkssgd_tpu.obs import validate_timeline

    doc = json.load(open(os.path.join(out, "timeline.json")))
    assert validate_timeline(doc) == []
    names = [e.get("name") for e in doc["traceEvents"]]
    assert "event:density_collapse" in names
    assert "dispatch" in names               # Tracer spans flowed through


def test_dist_trainer_halt_exit_code(tmp_path, monkeypatch):
    from gtopkssgd_tpu import dist_trainer

    _patch_loss(monkeypatch, jnp.nan)
    assert HALT_EXIT_CODE == 44              # the watchdog owns 43
    rc = dist_trainer.main([
        "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--num-iters", "2", "--eval-batches", "1", "--log-interval", "1",
        "--obs-halt-on", "error", "--out-dir", str(tmp_path / "run"),
    ])
    assert rc == HALT_EXIT_CODE
    recs = [json.loads(l) for l in
            open(str(tmp_path / "run" / "metrics.jsonl"))]
    assert any(r["kind"] == "event" and r["rule"] == "nan_loss"
               for r in recs)
