"""The reference's experiment grid (SURVEY.md C9: the per-experiment
mpirun shell-script family), as a registry of runnable configs.

Each entry reproduces one of the paper's workload configurations
(arXiv:1901.04359 experiments; batch sizes / epochs are the paper's setup
as reconstructed in SURVEY.md — the reference mount was empty, so exact
script values carry [M] confidence and must be re-checked if the mount is
ever populated). Names follow `<dataset>_<dnn>_<mode>`; every entry maps
to a BASELINE.json config (see experiments/README.md).

Run one:      python -m experiments.run cifar10_resnet20_gtopk
List all:     python -m experiments.run --list
CI-scale:     python -m experiments.run <name> --num-iters 30 --nworkers 2
"""

from __future__ import annotations

from typing import Any, Dict

# kwargs are TrainConfig fields; "nworkers" here is the paper's worker
# count (overridable — a v5e-8 slice would use --nworkers 8).
EXPERIMENTS: Dict[str, Dict[str, Any]] = {
    # --- BASELINE.json config #1: single-worker CPU/1-chip reference ----
    "cifar10_vgg16_single": dict(
        dnn="vgg16", batch_size=128, nworkers=1, compression=None,
        density=0.001, max_epochs=140,
        _desc="VGG-16/CIFAR-10 single worker, plain SGD (PR1 ref config)",
        _baseline="#1",
    ),
    # --- paper grid, CIFAR-10 ------------------------------------------
    "cifar10_vgg16_gtopk": dict(
        dnn="vgg16", batch_size=128, nworkers=4, compression="gtopk",
        density=0.001, max_epochs=140,
        _desc="VGG-16/CIFAR-10, 4-worker gTop-k rho=0.001",
        _baseline="#1/#2 family",
    ),
    "cifar10_resnet20_gtopk": dict(
        dnn="resnet20", batch_size=128, nworkers=4, compression="gtopk",
        density=0.001, max_epochs=140,
        _desc="ResNet-20/CIFAR-10, 4-worker gTop-k rho=0.001",
        _baseline="#2",
    ),
    "cifar10_resnet20_gtopk_warmup": dict(
        dnn="resnet20", batch_size=128, nworkers=4, compression="gtopk",
        density=0.001, max_epochs=140, warmup_epochs=4,
        dense_warmup_epochs=4,
        _desc="ResNet-20/CIFAR-10, 4-worker gTop-k with the warm-up "
              "trick (epochs 0-3: LR ramps up AND communication stays "
              "dense, concurrently; top-k starts at epoch 4 — removes "
              "the sparse cold-start ramp)",
        _baseline="#2 warm-up variant",
    ),
    "cifar10_resnet20_dense": dict(
        dnn="resnet20", batch_size=128, nworkers=4, compression="dense",
        density=1.0, max_epochs=140,
        _desc="ResNet-20/CIFAR-10, 4-worker dense-psum baseline",
        _baseline="#2 baseline",
    ),
    "cifar10_resnet20_allgather": dict(
        dnn="resnet20", batch_size=128, nworkers=4, compression="allgather",
        density=0.001, max_epochs=140,
        _desc="ResNet-20/CIFAR-10, 4-worker Top-k allgather (DGC baseline)",
        _baseline="#2 topk-baseline",
    ),
    # --- paper grid, ImageNet ------------------------------------------
    "imagenet_resnet50_gtopk": dict(
        dnn="resnet50", batch_size=32, nworkers=16, compression="gtopk",
        density=0.001, max_epochs=90, dtype="bfloat16",
        _desc="ResNet-50/ImageNet, 16-worker gTop-k rho=0.001 "
              "(north-star workload)",
        _baseline="#3",
    ),
    "imagenet_resnet50_dense": dict(
        dnn="resnet50", batch_size=32, nworkers=16, compression="dense",
        density=1.0, max_epochs=90, dtype="bfloat16",
        _desc="ResNet-50/ImageNet, 16-worker dense-psum baseline",
        _baseline="#3 baseline",
    ),
    "imagenet_alexnet_gtopk": dict(
        dnn="alexnet", batch_size=64, nworkers=16, compression="gtopk",
        density=0.001, max_epochs=95, dtype="bfloat16",
        _desc="AlexNet/ImageNet, 16-worker gTop-k rho=0.001",
        _baseline="#3",
    ),
    # --- paper grid, language/speech -----------------------------------
    "ptb_lstm_gtopk": dict(
        dnn="lstm", batch_size=20, nworkers=4, compression="gtopk",
        density=0.001, max_epochs=40,
        _desc="2-layer LSTM/PTB, 4-worker gTop-k (non-conv flat-gradient "
              "stress; clip-before-compress path)",
        _baseline="#4",
    ),
    "an4_lstm_gtopk": dict(
        dnn="lstman4", batch_size=8, nworkers=4, compression="gtopk",
        density=0.001, max_epochs=100,
        _desc="BiLSTM-CTC/AN4, 4-worker gTop-k rho=0.001",
        _baseline="paper workload 6",
    ),
    # --- TPU extension (NOT reference parity): hierarchical two-level ---
    # Dense psum inside each 4-chip ICI slice, gTop-k across slices — the
    # pod-scale idiom SURVEY.md §5 names for multislice/DCN runs.
    "imagenet_resnet50_gtopk_hier": dict(
        dnn="resnet50", batch_size=32, nworkers=16, compression="gtopk_hier",
        hier_ici=4, density=0.001, max_epochs=90, dtype="bfloat16",
        _desc="ResNet-50/ImageNet, 16 workers as 4 ICI slices x 4: dense "
              "within slice, gTop-k across (TPU extension)",
        _baseline="extension",
    ),
    # --- TPU extension (NOT reference parity): layer-wise selection -----
    # Per-layer top-k_l + per-layer error feedback (arXiv:1911.08772
    # lineage); the flat [N] gradient never materializes, un-serializing
    # the selection from the backward epilogues. Same gTop-k hypercube on
    # the wire.
    "imagenet_resnet50_gtopk_layerwise": dict(
        dnn="resnet50", batch_size=32, nworkers=16,
        compression="gtopk_layerwise", density=0.001, max_epochs=90,
        dtype="bfloat16",
        _desc="ResNet-50/ImageNet, 16-worker layer-wise gTop-k rho=0.001 "
              "(TPU extension)",
        _baseline="extension",
    ),
    "cifar10_resnet20_gtopk_layerwise": dict(
        dnn="resnet20", batch_size=128, nworkers=4,
        compression="gtopk_layerwise", density=0.001, max_epochs=140,
        _desc="ResNet-20/CIFAR-10, 4-worker layer-wise gTop-k rho=0.001 "
              "(TPU extension; measured 2.2x lower cold-start loss than "
              "flat gtopk — convergence_resnet20_layerwise artifact)",
        _baseline="extension",
    ),
    # --- the measured recommended configuration ------------------------
    # Round-4 1200-step identical-seed 3-arm head-to-head
    # (convergence_resnet20_recommended1200_cpu_mesh2.jsonl): flat gTop-k
    # + DGC momentum correction matches dense step-for-step to 90% of
    # the dense loss drop (300 vs 300 steps; gtopk+warmup needs 450) and
    # ends with the LOWEST val loss of the three arms (2e-05 vs dense
    # 4e-05, warmup 5e-05; val_top1 saturates at 1.0 for ALL arms on the
    # synthetic eval — the decision rests on val loss + steps), no
    # warm-up phase needed. Same winner as every shorter-budget A/B
    # (0.73 vs 0.59 val_top1 at 200 steps, warmup_ab artifact). This is
    # the config the README tells a reference user to run.
    "cifar10_resnet20_gtopk_recommended": dict(
        dnn="resnet20", batch_size=128, nworkers=4, compression="gtopk",
        momentum_correction=True, density=0.001, max_epochs=140,
        _desc="RECOMMENDED: ResNet-20/CIFAR-10, 4-worker gTop-k "
              "rho=0.001 + DGC momentum correction — dense-parity "
              "val accuracy at the measured 1200-step horizon, no "
              "warm-up phase needed",
        _baseline="#2 recommended variant",
    ),
}

# BASELINE.json config #5 (density sweep) is a benchmark, not a training
# run — it lives in benchmarks/sweep.py; experiments/run.py forwards it.
SWEEP_NAME = "resnet50_density_sweep"
