"""Launch one registered experiment (reference C9: the mpirun scripts).

    python -m experiments.run cifar10_resnet20_gtopk
    python -m experiments.run --list
    python -m experiments.run imagenet_resnet50_gtopk --nworkers 8 \
        --num-iters 100          # scale to the hardware at hand / CI

Overrides mirror dist_trainer flags; anything not overridden runs with the
paper's exact configuration from the registry.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from experiments import EXPERIMENTS, SWEEP_NAME


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser("experiments.run")
    ap.add_argument("name", nargs="?", help="experiment name (see --list)")
    ap.add_argument("--list", action="store_true", dest="list_all")
    ap.add_argument("--nworkers", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--max-epochs", type=int, default=None)
    ap.add_argument("--num-iters", type=int, default=None,
                    help="fixed step count instead of the full epoch run")
    ap.add_argument("--eval-batches", type=int, default=None)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--log-interval", type=int, default=None)
    args = ap.parse_args(argv)

    if args.list_all or not args.name:
        width = max(len(n) for n in EXPERIMENTS)
        for name, spec in EXPERIMENTS.items():
            print(f"{name:<{width}}  [{spec['_baseline']:>14}]  "
                  f"{spec['_desc']}")
        print(f"{SWEEP_NAME:<{width}}  [{'#5':>14}]  density sweep "
              "{1, 0.01, 0.001, 0.0001} x ResNet-50 -> benchmarks/sweep.py")
        return 0

    if args.name == SWEEP_NAME:
        from benchmarks import sweep  # noqa: F401  (its main reads argv)

        sys.argv = ["sweep.py", "--dnn", "resnet50",
                    "--densities", "1", "0.01", "0.001", "0.0001"]
        sweep.main()
        return 0

    if args.name not in EXPERIMENTS:
        ap.error(f"unknown experiment {args.name!r} (try --list)")
    spec = {k: v for k, v in EXPERIMENTS[args.name].items()
            if not k.startswith("_")}
    for field in ("nworkers", "batch_size", "max_epochs", "data_dir",
                  "out_dir", "eval_batches", "log_interval"):
        v = getattr(args, field)
        if v is not None:
            spec[field] = v

    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    with Trainer(TrainConfig(**spec)) as trainer:
        if args.resume:
            restored = trainer.restore()
            trainer.logger.info("resume: %s",
                                "restored" if restored else "fresh")
        if args.num_iters is not None:
            stats = trainer.train(args.num_iters)
            stats.update(trainer.test())
        else:
            stats = trainer.fit()
        trainer.logger.info("done: %s", stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
