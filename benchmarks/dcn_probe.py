"""Measure the sparse collectives across a REAL process boundary.

Round-2's scaling projection (scaling_model.py) argued "gtopk/hier win ~2x
once the reduction crosses DCN" from a bandwidth model with ZERO measured
cross-process bytes (VERDICT round-2 weak #8). This probe anchors it: two
actual processes over ``jax.distributed`` on localhost TCP (the same
machinery — gRPC transport, cross-process XLA collectives — a real
multi-host TPU pod uses over DCN), timing at ResNet-50 gradient size:

  * dense psum of the f32[N] gradient          (the O(N) baseline),
  * the gTop-k hypercube at k = ceil(rho*N)    (O(k log P)),
  * the DGC allgather union                    (O(k P)),

plus the derived constants the projection needs: effective cross-process
bandwidth (from the dense transfer) and the per-round sparse constant.

Honesty notes, recorded in the artifact: (1) localhost TCP is not DCN —
the MEASURED quantity is the real serialization + transport + rendezvous
cost of the exact collective programs at the exact sizes, which is the
constant the bandwidth-only model guessed at; absolute Gbit/s on a
datacenter NIC will differ, so the artifact stores both the raw times and
the bandwidth to re-scale. (2) This host has ONE CPU core, so the two
processes timeshare — compute-side inflation hits BOTH modes equally and
the dense:sparse RATIO (bytes-dominated) is the robust readout.

Usage:
  python benchmarks/dcn_probe.py [--n 25557032] [--density 0.001]
Writes benchmarks/results/dcn_probe_2proc.json and re-emits the
scaling-model curve with the measured cross-process bandwidth.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmarks", "results")

WORKER = r"""
import json
import os
import sys
import time

sys.path.insert(0, sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
from gtopkssgd_tpu.utils.settings import _default_cache_dir
jax.config.update("jax_compilation_cache_dir", _default_cache_dir())
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
coord, pid = sys.argv[1], int(sys.argv[2])
cfg = json.loads(sys.argv[3])
try:
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=pid)
except Exception as e:
    print("DISTRIBUTED-UNSUPPORTED:", e)
    raise SystemExit(99)

import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gtopkssgd_tpu.parallel import make_mesh, sparse_allreduce

n, k = cfg["n"], cfg["k"]
reps, warmup = cfg["reps"], cfg["warmup"]
mesh = make_mesh(2)
sharding = NamedSharding(mesh, P("dp"))

# Global [2, ...] arrays assembled from each process's local [1, ...] row
# (1 device per process). vals/idx model a realistic top-k set.
rng = np.random.default_rng(7 + pid)


def dp_global(local):
    return jax.make_array_from_process_local_data(sharding, local)


dense_in = dp_global(rng.standard_normal((1, n)).astype(np.float32))
vals_in = dp_global(rng.standard_normal((1, k)).astype(np.float32))
idx_in = dp_global(rng.choice(n, size=(1, k), replace=False)
                   .astype(np.int32))


def dense_fn(x):
    return lax.psum(x[0], "dp")[None]


def gtopk_fn(vals, idx):
    gv, gi, _ = sparse_allreduce("gtopk", vals[0], idx[0], k=k, n=n,
                                 axis_name="dp", axis_size=2)
    return gv[None], gi[None]


def allgather_fn(vals, idx):
    # allgather returns the DENSE scattered result (every pick lands,
    # no global index set) — see optimizer.update's needs_repair=False arm.
    dense, _, _ = sparse_allreduce("allgather", vals[0], idx[0], k=k, n=n,
                                   axis_name="dp", axis_size=2)
    return dense[None]


def timed(fn, in_specs, out_specs, args):
    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False))
    for _ in range(warmup):
        jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


res = {
    "dense_psum_s": timed(dense_fn, (P("dp"),), P("dp"), (dense_in,)),
    "gtopk_s": timed(gtopk_fn, (P("dp"), P("dp")), (P("dp"), P("dp")),
                     (vals_in, idx_in)),
    "allgather_s": timed(allgather_fn, (P("dp"), P("dp")),
                         P("dp"), (vals_in, idx_in)),
}
if pid == 0:
    print("PROBE-RESULT " + json.dumps(res))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_probe(n: int, k: int, reps: int, warmup: int) -> dict:
    import tempfile

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)
    cfg = json.dumps({"n": n, "k": k, "reps": reps, "warmup": warmup})

    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as fh:
            fh.write(WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, script, f"localhost:{port}", str(pid),
                 cfg, REPO],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for pid in (0, 1)
        ]
        outs = [p.communicate(timeout=1200)[0] for p in procs]
    for p, out in zip(procs, outs):
        if p.returncode == 99:
            raise SystemExit("jax build lacks CPU cross-process collectives:"
                             f"\n{out}")
        if p.returncode != 0:
            raise SystemExit(f"worker failed rc={p.returncode}:\n{out}")
    line = next(l for l in outs[0].splitlines()
                if l.startswith("PROBE-RESULT "))
    return json.loads(line[len("PROBE-RESULT "):])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=25_557_032,
                    help="gradient length (default: ResNet-50)")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args()

    import math

    k = max(1, math.ceil(args.density * args.n))
    timings = run_probe(args.n, k, args.reps, args.warmup)

    # Derived constants for the projection. Dense psum at p=2 moves ~1x
    # the buffer per device (ring factor 2(p-1)/p = 1), so effective
    # cross-process bandwidth = 4n bytes / measured time.
    dense_bytes = 4 * args.n
    eff_gbps = dense_bytes * 8 / timings["dense_psum_s"] / 1e9
    sparse_bytes = 8 * k  # one round of [vals f32; idx i32]
    report = {
        "what": ("2-process jax.distributed collectives over localhost "
                 "TCP at ResNet-50 gradient size — the measured "
                 "cross-process anchor for scaling_model.py (see module "
                 "docstring for the honesty notes: 1-core timesharing, "
                 "localhost != datacenter NIC)"),
        "n": args.n, "k": k, "reps": args.reps,
        "dense_psum_ms": round(timings["dense_psum_s"] * 1e3, 3),
        "gtopk_ms": round(timings["gtopk_s"] * 1e3, 3),
        "allgather_ms": round(timings["allgather_s"] * 1e3, 3),
        "gtopk_vs_dense": round(
            timings["dense_psum_s"] / timings["gtopk_s"], 2),
        "allgather_vs_dense": round(
            timings["dense_psum_s"] / timings["allgather_s"], 2),
        "measured_cross_process_gbps": round(eff_gbps, 3),
        "dense_bytes_per_device": dense_bytes,
        "sparse_bytes_per_round": sparse_bytes,
    }

    # Re-emit the projection with the measured cross-process constant as
    # the DCN bandwidth so the curve has one real anchor point on it.
    report_curve = []
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "scaling_model", os.path.join(REPO, "benchmarks",
                                      "scaling_model.py"))
    sm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sm)
    kw = dict(n=args.n, k=k, compute_ms=60.1, overhead_ms=5.4,
              ici_gbps=1600.0, dcn_gbps=eff_gbps, ici_size=16, batch=128)
    for p in (16, 32, 64, 256):
        for mode in ("dense", "gtopk", "allgather", "gtopk_hier"):
            report_curve.append(sm.project(mode, p, **kw))
    report["projection_with_measured_dcn_gbps"] = report_curve

    os.makedirs(RESULTS, exist_ok=True)
    out = os.path.join(RESULTS, "dcn_probe_2proc.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({k: v for k, v in report.items()
                      if k != "projection_with_measured_dcn_gbps"}))


if __name__ == "__main__":
    main()
