"""Measure the sparse collectives across a REAL process boundary.

Round-2's scaling projection (scaling_model.py) argued "gtopk/hier win ~2x
once the reduction crosses DCN" from a bandwidth model with ZERO measured
cross-process bytes (VERDICT round-2 weak #8). This probe anchors it: two
actual processes over ``jax.distributed`` on localhost TCP (the same
machinery — gRPC transport, cross-process XLA collectives — a real
multi-host TPU pod uses over DCN), timing at ResNet-50 gradient size:

  * dense psum of the f32[N] gradient          (the O(N) baseline),
  * the gTop-k hypercube at k = ceil(rho*N)    (O(k log P)),
  * the DGC allgather union                    (O(k P)),

plus the derived constants the projection needs: effective cross-process
bandwidth (from the dense transfer) and the per-round sparse constant.

Honesty notes, recorded in the artifact: (1) localhost TCP is not DCN —
the MEASURED quantity is the real serialization + transport + rendezvous
cost of the exact collective programs at the exact sizes, which is the
constant the bandwidth-only model guessed at; absolute Gbit/s on a
datacenter NIC will differ, so the artifact stores both the raw times and
the bandwidth to re-scale. (2) This host has ONE CPU core, so the two
processes timeshare — compute-side inflation hits BOTH modes equally and
the dense:sparse RATIO (bytes-dominated) is the robust readout.

Usage:
  python benchmarks/dcn_probe.py [--n 25557032] [--density 0.001]
Writes benchmarks/results/dcn_probe_2proc.json and re-emits the
scaling-model curve with the measured cross-process bandwidth.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmarks", "results")

WORKER = r"""
import json
import os
import sys
import time

sys.path.insert(0, sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
from gtopkssgd_tpu.utils.settings import _default_cache_dir
jax.config.update("jax_compilation_cache_dir", _default_cache_dir())
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
coord, pid = sys.argv[1], int(sys.argv[2])
cfg = json.loads(sys.argv[3])
try:
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=cfg.get("procs", 2),
                               process_id=pid)
except Exception as e:
    print("DISTRIBUTED-UNSUPPORTED:", e)
    raise SystemExit(99)

import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gtopkssgd_tpu.parallel import make_mesh, sparse_allreduce

n, k = cfg["n"], cfg["k"]
reps, warmup = cfg["reps"], cfg["warmup"]
nproc = cfg.get("procs", 2)
mesh = make_mesh(nproc)
sharding = NamedSharding(mesh, P("dp"))

# Global [2, ...] arrays assembled from each process's local [1, ...] row
# (1 device per process). vals/idx model a realistic top-k set.
rng = np.random.default_rng(7 + pid)


def dp_global(local):
    return jax.make_array_from_process_local_data(sharding, local)


dense_in = dp_global(rng.standard_normal((1, n)).astype(np.float32))
vals_in = dp_global(rng.standard_normal((1, k)).astype(np.float32))
idx_in = dp_global(rng.choice(n, size=(1, k), replace=False)
                   .astype(np.int32))


def dense_fn(x):
    return lax.psum(x[0], "dp")[None]


def gtopk_fn(vals, idx):
    gv, gi, _ = sparse_allreduce("gtopk", vals[0], idx[0], k=k, n=n,
                                 axis_name="dp", axis_size=nproc)
    return gv[None], gi[None]


def allgather_fn(vals, idx):
    # allgather returns the DENSE scattered result (every pick lands,
    # no global index set) — see optimizer.update's needs_repair=False arm.
    dense, _, _ = sparse_allreduce("allgather", vals[0], idx[0], k=k, n=n,
                                   axis_name="dp", axis_size=nproc)
    return dense[None]


def timed(fn, in_specs, out_specs, args, reps_override=None):
    r = reps_override or reps
    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False))
    for _ in range(warmup):
        jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(r):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / r


res = {
    "dense_psum_s": timed(dense_fn, (P("dp"),), P("dp"), (dense_in,)),
    "gtopk_s": timed(gtopk_fn, (P("dp"), P("dp")), (P("dp"), P("dp")),
                     (vals_in, idx_in)),
    "allgather_s": timed(allgather_fn, (P("dp"), P("dp")),
                         P("dp"), (vals_in, idx_in)),
}

# Message-size sweep of the same psum program: separates the per-message
# latency term (alpha) from the bandwidth term (beta) that a single-size
# measurement conflates. Small sizes are latency-dominated; the big end
# recovers the bandwidth the fixed-size probe measured.
sweep = []
for sz in cfg.get("sweep_sizes", []):
    x = dp_global(rng.standard_normal((1, sz)).astype(np.float32))
    # More reps at small sizes (cheap, latency-noisy), fewer at large.
    r = max(3, min(40, int(2e8 / (4 * sz))))
    t = timed(dense_fn, (P("dp"),), P("dp"), (x,), reps_override=r)
    sweep.append({"n": sz, "bytes": 4 * sz, "psum_s": t, "reps": r})
res["sweep"] = sweep
if pid == 0:
    print("PROBE-RESULT " + json.dumps(res))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_probe(n: int, k: int, reps: int, warmup: int,
              sweep_sizes=(), procs: int = 2) -> dict:
    import tempfile

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)
    cfg = json.dumps({"n": n, "k": k, "reps": reps, "warmup": warmup,
                      "sweep_sizes": list(sweep_sizes), "procs": procs})

    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as fh:
            fh.write(WORKER)
        worker_procs = [
            subprocess.Popen(
                [sys.executable, script, f"localhost:{port}", str(pid),
                 cfg, REPO],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for pid in range(procs)
        ]
        outs = [p.communicate(timeout=2400)[0] for p in worker_procs]
    for p, out in zip(worker_procs, outs):
        if p.returncode == 99:
            raise SystemExit("jax build lacks CPU cross-process collectives:"
                             f"\n{out}")
        if p.returncode != 0:
            raise SystemExit(f"worker failed rc={p.returncode}:\n{out}")
    line = next(l for l in outs[0].splitlines()
                if l.startswith("PROBE-RESULT "))
    return json.loads(line[len("PROBE-RESULT "):])


def fit_alpha_beta(sweep: list) -> dict:
    """Decompose t(bytes) = alpha + bytes/beta from the message-size sweep.

    A single-size measurement conflates the per-message latency term
    (rendezvous + serialization setup, what the gtopk tree pays log2(P)
    times regardless of k) with the bandwidth term (what dense pays over
    the full gradient). Plain OLS is the WRONG estimator here: the
    largest (100 MB) point owns the slope and drives the intercept
    negative, losing the very latency floor the sweep exists to measure
    (observed: measured 3.6 ms small-message plateau, OLS intercept
    clamped to 0). Physical fit instead:

      alpha = mean time over the latency plateau — the sizes whose time
              is within 1.5x of the fastest sweep point (transfer cost
              invisible next to the floor);
      beta  = asymptotic bulk rate from the LARGEST point after
              subtracting alpha.

    Mid-size residuals are reported; they run FASTER than the asymptote
    predicts (effective rate falls with size: buffer effects + the
    1-core host paying the psum's local adds), so using the large-size
    beta is the conservative choice for the DCN projection.
    """
    pts = sorted(sweep, key=lambda r: r["bytes"])
    floor = min(p["psum_s"] for p in pts)
    plateau = [p["psum_s"] for p in pts if p["psum_s"] <= 1.5 * floor]
    alpha = sum(plateau) / len(plateau)
    big = pts[-1]
    beta_Bps = big["bytes"] / max(big["psum_s"] - alpha, 1e-9)
    beta_gbps = beta_Bps * 8 / 1e9
    fitted = [alpha + p["bytes"] / beta_Bps for p in pts]
    return {
        "alpha_ms": round(alpha * 1e3, 4),
        "beta_gbps": round(beta_gbps, 3),
        "plateau_points": len(plateau),
        "points": [
            {"bytes": p["bytes"], "measured_ms": round(p["psum_s"] * 1e3, 4),
             "fitted_ms": round(f * 1e3, 4)}
            for p, f in zip(pts, fitted)],
        "note": ("t(bytes) = alpha + bytes*8/beta_gbps/1e9; alpha = "
                 "measured small-message plateau (the per-round floor "
                 "the gtopk tree pays regardless of k), beta = "
                 "large-transfer asymptote (what dense pays over the "
                 "full gradient); mid-size points run faster than the "
                 "fit — see fit_alpha_beta docstring"),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=25_557_032,
                    help="gradient length (default: ResNet-50)")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--procs", type=int, default=2,
                    help="process count (pow2; 1-core host timeshares)")
    ap.add_argument("--sweep-sizes", type=int, nargs="*",
                    default=[256, 4096, 65536, 1 << 20, 4 << 20, 25_557_032],
                    help="psum sweep lengths (f32 elements) for the "
                         "alpha/beta fit; empty disables the sweep")
    ap.add_argument("--refit", action="store_true",
                    help="recompute alpha/beta + the projection from the "
                         "sweep points already stored in the artifact "
                         "(no re-measurement)")
    args = ap.parse_args()

    import math

    k = max(1, math.ceil(args.density * args.n))
    if args.refit:
        # Re-derive everything from the artifact's OWN parameters — the
        # CLI defaults must not leak into a refit of a capture taken at
        # different n/procs (that would recompute bandwidth and the
        # projection from mismatched sizes and overwrite the artifact
        # with them).
        refit_path = os.path.join(
            RESULTS, f"dcn_probe_{args.procs}proc.json")
        with open(refit_path) as fh:
            prev = json.load(fh)
        if "alpha_beta_fit" not in prev:
            raise SystemExit(
                f"{refit_path} has no alpha_beta_fit sweep points "
                "(pre-round-4 artifact?) — re-run the probe to capture "
                "a sweep before refitting")
        pts = prev["alpha_beta_fit"]["points"]
        args.n = prev["n"]
        args.reps = prev["reps"]
        args.procs = prev.get("procs", 2)
        k = prev["k"]
        timings = {
            "dense_psum_s": prev["dense_psum_ms"] / 1e3,
            "gtopk_s": prev["gtopk_ms"] / 1e3,
            "allgather_s": prev["allgather_ms"] / 1e3,
            "sweep": [{"n": p["bytes"] // 4, "bytes": p["bytes"],
                       "psum_s": p["measured_ms"] / 1e3, "reps": 0}
                      for p in pts],
        }
    else:
        timings = run_probe(args.n, k, args.reps, args.warmup,
                            sweep_sizes=args.sweep_sizes, procs=args.procs)

    # Derived constants for the projection. A bandwidth-optimal dense
    # allreduce moves 2(p-1)/p x the buffer per device (= 1x at p=2), so
    # effective cross-process bandwidth = ring bytes / measured time.
    dense_bytes = 4 * args.n
    ring_bytes = 2 * (args.procs - 1) / args.procs * dense_bytes
    eff_gbps = ring_bytes * 8 / timings["dense_psum_s"] / 1e9
    sparse_bytes = 8 * k  # one round of [vals f32; idx i32]
    report = {
        "what": (f"{args.procs}-process jax.distributed collectives over "
                 "localhost TCP at ResNet-50 gradient size — the measured "
                 "cross-process anchor for scaling_model.py (see module "
                 "docstring for the honesty notes: 1-core timesharing, "
                 "localhost != datacenter NIC)"),
        "n": args.n, "k": k, "reps": args.reps, "procs": args.procs,
        "dense_psum_ms": round(timings["dense_psum_s"] * 1e3, 3),
        "gtopk_ms": round(timings["gtopk_s"] * 1e3, 3),
        "allgather_ms": round(timings["allgather_s"] * 1e3, 3),
        "gtopk_vs_dense": round(
            timings["dense_psum_s"] / timings["gtopk_s"], 2),
        "allgather_vs_dense": round(
            timings["dense_psum_s"] / timings["allgather_s"], 2),
        "measured_cross_process_gbps": round(eff_gbps, 3),
        "dense_bytes_per_device": dense_bytes,
        "sparse_bytes_per_round": sparse_bytes,
    }
    if timings.get("sweep"):
        report["alpha_beta_fit"] = fit_alpha_beta(timings["sweep"])
        # Axis-keyed form of the same fit, in the calib-artifact "axes"
        # schema (obs/calib.py write_artifact): a localhost probe only
        # crosses the process boundary — the slow "dcn" hop — so the
        # honest section carries exactly that one axis. ledger.
        # load_alpha_beta prefers axis-keyed artifacts at equal P, and
        # planner_inputs prices the dcn hop from this entry.
        fit = report["alpha_beta_fit"]
        report["axes"] = {"dcn": {
            "alpha_ms": fit["alpha_ms"],
            "beta_gbps": fit["beta_gbps"],
            "n_samples": len(fit["points"]),
            "identifiable": "alpha_beta",
        }}

    # Per-round rows: the deterministic round -> (src, dst, axis) join
    # of the gtopk merge tree at this P (obs/linkmap.py), with rank 0's
    # measured gtopk span carved per round in proportion to the modeled
    # wire time — the probe-side seed of the link weather map.
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from gtopkssgd_tpu.obs import linkmap as _linkmap
    mine = _linkmap.rank_rounds(
        _linkmap.round_peers("gtopk", args.procs), 0)
    fit = report.get("alpha_beta_fit", {})
    weights = _linkmap.round_weights(
        mine, sparse_bytes,
        alpha_ms=fit.get("alpha_ms") or 0.1,
        beta_gbps=fit.get("beta_gbps") or max(eff_gbps, 1e-9))
    carved = _linkmap.carve_rounds(report["gtopk_ms"], weights)
    report["round_rows"] = [
        {"round": rd["round"], "axis": rd["axis"], "phase": rd["phase"],
         "src": rd["src"], "dst": rd["dst"],
         "link": _linkmap.link_key(rd["axis"], rd["src"], rd["dst"]),
         "t_ms": round(t, 4)}
        for rd, t in zip(mine, carved)]

    # Re-emit the projection with the measured cross-process constant as
    # the DCN bandwidth so the curve has one real anchor point on it.
    report_curve = []
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "scaling_model", os.path.join(REPO, "benchmarks",
                                      "scaling_model.py"))
    sm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sm)
    fit = report.get("alpha_beta_fit", {})
    kw = dict(n=args.n, k=k, compute_ms=60.1, overhead_ms=5.4,
              ici_gbps=1600.0,
              dcn_gbps=fit.get("beta_gbps", eff_gbps),
              dcn_alpha_ms=fit.get("alpha_ms", 0.0),
              ici_size=16, batch=128)
    for p in (16, 32, 64, 256):
        for mode in ("dense", "gtopk", "allgather", "gtopk_hier"):
            report_curve.append(sm.project(mode, p, **kw))
    report["projection_with_measured_dcn_gbps"] = report_curve

    os.makedirs(RESULTS, exist_ok=True)
    # Per-procs filename: a --procs 4 run must not overwrite the
    # canonical 2-process anchor that PARITY/README/time_to_quality cite.
    out = os.path.join(RESULTS, f"dcn_probe_{args.procs}proc.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({k: v for k, v in report.items()
                      if k != "projection_with_measured_dcn_gbps"}))


if __name__ == "__main__":
    main()
