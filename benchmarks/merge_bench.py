"""Sparse merge-accumulate cost on real hardware — the evidence for/against
a fused Pallas merge kernel.

SURVEY.md §2 (native table) and §7 step 6 name a "sparse merge-accumulate"
kernel as on the critical path of every gTop-k tree round (the reference did
this merge host-side in numpy inside allreducer.py::gtopk_sparse_allreduce).
The TPU rebuild's per-round merge is `ops.topk.merge_sparse_sets` — an XLA
program (concat 2k -> argsort by index -> adjacent duplicate sum -> top_k).
This benchmark measures, at the reference's real (N, k) operating points:

  * `merge`       — merge_sparse_sets itself, one tree round's on-device cost;
  * `merge_chain` — log2(32) = 5 chained merges, a whole 32-worker tree's
                    merge work as XLA sees it (collectives excluded — one
                    chip — so this is the pure compute side of the tree);
  * `merge_argsort_topk` — the round-1 formulation (argsort + jnp.take
                    gathers, lax.top_k reselect), kept as the measured
                    justification for the carried-sort rewrite;
  * `dense_scatter` — the naive alternative (scatter both sets into a dense
                    f32[N] + exact top_k over N), to show why the sort-based
                    sparse formulation was chosen.

The verdict this artifact encodes: whether the XLA merge is already cheap
relative to its train step (ResNet-50's measured fused step is ~55-65 ms at
batch 128 — bench.py), i.e. whether a hand-fused Pallas merge kernel could
buy anything measurable.

Run:  python -m benchmarks.merge_bench [--out PATH] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import jax.numpy as jnp

from gtopkssgd_tpu.ops import merge_sparse_sets, scatter_add_dense, topk_abs
from gtopkssgd_tpu.ops.topk import k_for_density
from gtopkssgd_tpu.utils import (
    sync_round_trip_seconds,
    timed_window,
    true_sync,
)

SIZES = {
    "resnet20-270k": 272_474,
    "resnet50-25.6M": 25_557_032,
    "vgg16-61M": 61_090_496,
}
DENSITIES = (0.001, 0.01)
CHAIN_ROUNDS = 5  # log2(32): the paper's cluster size


def _random_sets(n: int, k: int, count: int):
    """`count` distinct sparse sets with disjoint-ish random indices —
    the honest case for the merge (round-1 lesson: replicated inputs are
    the duplicate-heavy cheapest case)."""
    sets = []
    for i in range(count):
        kk = jax.random.PRNGKey(i)
        idx = jax.random.randint(kk, (k,), 0, n, jnp.int32)
        vals = jax.random.normal(jax.random.fold_in(kk, 1), (k,), jnp.float32)
        sets.append((vals, idx))
    return sets


def _time(fn, args, min_seconds: float):
    out = fn(*args)
    rtt = sync_round_trip_seconds(out)

    def chunk(c):
        o = out
        for _ in range(c):
            o = fn(*args)
        true_sync(o)

    return timed_window(chunk, rtt, min_seconds, 4)


def time_merge(n: int, k: int, min_seconds: float):
    (va, ia), (vb, ib) = _random_sets(n, k, 2)
    fn = jax.jit(lambda a, b, c, d: merge_sparse_sets(a, b, c, d, k, n))
    return _time(fn, (va, ia, vb, ib), min_seconds)


def time_merge_chain(n: int, k: int, min_seconds: float):
    sets = _random_sets(n, k, CHAIN_ROUNDS + 1)

    def chain(first, rest):
        v, i = first
        for rv, ri in rest:
            v, i = merge_sparse_sets(v, i, rv, ri, k, n)
        return v, i

    fn = jax.jit(chain)
    return _time(fn, (sets[0], sets[1:]), min_seconds)


def _merge_argsort_topk(va, ia, vb, ib, k, n):
    """Round-1 merge formulation, retained for comparison only."""
    from jax import lax

    cat_idx = jnp.concatenate([ia, ib])
    cat_val = jnp.concatenate([va, vb])
    order = jnp.argsort(cat_idx)
    si = jnp.take(cat_idx, order)
    sv = jnp.take(cat_val, order)
    dup = jnp.concatenate([jnp.zeros((1,), bool), si[1:] == si[:-1]])
    next_dup = jnp.concatenate([dup[1:], jnp.zeros((1,), bool)])
    summed = sv + jnp.where(next_dup, jnp.roll(sv, -1), 0.0)
    merged_val = jnp.where(dup, 0.0, summed)
    merged_idx = jnp.where(dup, n, si).astype(jnp.int32)
    _, sel = lax.top_k(jnp.abs(merged_val), k)
    return jnp.take(merged_val, sel), jnp.take(merged_idx, sel)


def time_merge_argsort(n: int, k: int, min_seconds: float):
    (va, ia), (vb, ib) = _random_sets(n, k, 2)
    fn = jax.jit(lambda a, b, c, d: _merge_argsort_topk(a, b, c, d, k, n))
    return _time(fn, (va, ia, vb, ib), min_seconds)


def time_dense_scatter(n: int, k: int, min_seconds: float):
    (va, ia), (vb, ib) = _random_sets(n, k, 2)

    def dense_merge(va, ia, vb, ib):
        d = scatter_add_dense(n, ia, va) + scatter_add_dense(n, ib, vb)
        return topk_abs(d, k)

    fn = jax.jit(dense_merge)
    return _time(fn, (va, ia, vb, ib), min_seconds)


VARIANTS = {
    "merge": time_merge,
    "merge_chain5": time_merge_chain,
    "merge_argsort_topk": time_merge_argsort,
    "dense_scatter": time_dense_scatter,
}

PLAN_WORKERS = (8, 16, 32)


def plan_rows(sizes: dict, densities) -> list:
    """Model-side balanced-vs-tree schedule comparison at the same
    operating points (no mesh needed — these are the planner's own
    inputs: comm_bytes_per_step for per-rank wire volume, the scaling
    model for projected ms). One row per (size, density, P) so BENCH
    rounds carry the crossover evidence next to the measured merge cost:
    balanced wire is O(k) flat in P, the tree's O(k log P), so
    bytes_ratio < 1 from P=8 up at these shapes."""
    from benchmarks.scaling_model import predict
    from gtopkssgd_tpu.parallel import balanced_cap, comm_bytes_per_step
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    # Price with exactly what the planner scores with (the committed
    # dcn_probe alpha-beta fit when present, documented fallbacks else).
    inp = planner_inputs()
    model = dict(ici_gbps=inp["ici_gbps"], dcn_gbps=inp["beta_gbps"],
                 dcn_alpha_ms=inp["alpha_ms"], ici_size=1)
    rows = []
    for label, n in sizes.items():
        for rho in densities:
            k = k_for_density(n, rho)
            for p in PLAN_WORKERS:
                tree_b = comm_bytes_per_step("gtopk", n, k, p)
                bal_b = comm_bytes_per_step(
                    "gtopk", n, k, p, schedule="balanced")
                rows.append({
                    "size": label, "n": n, "density": rho, "k": k,
                    "p": p, "cap": balanced_cap(k, p, n),
                    "tree_wire_bytes": tree_b,
                    "balanced_wire_bytes": bal_b,
                    "bytes_ratio": round(bal_b / max(tree_b, 1), 4),
                    "tree_ms_model": round(
                        predict("gtopk", p, n=n, k=k, **model), 4),
                    "balanced_ms_model": round(
                        predict("gtopk_balanced", p, n=n, k=k, **model),
                        4),
                })
    return rows


FORECAST_WORKERS = (256, 1024)
# (tree label, ici_size): flat dp prices every hop on the slow DCN
# link; the pod tree keeps 16-chip ICI domains local and pays DCN only
# across slices (scaling_model's slice split) — the two axis trees
# ROADMAP item 3 asks the evidence rows to span.
FORECAST_TREES = (("flat", 1), ("pod", 16))


def forecast_rows(sizes: dict, densities) -> list:
    """Scale-out forecast evidence rows (ROADMAP item 3): modeled comm
    ms at P in {256, 1024} across two axis trees x two wire schedules,
    priced from the planner's own inputs (obs/forecast.py grid over the
    committed fit artifact), with uncertainty columns from the fit's
    Theil-Sen residual when the artifact records one (probe-era
    artifacts don't — their bands are honestly absent/0). One row per
    (size, density, P, schedule, tree); the per-P recommended plan and
    the tree->balanced crossover ride each (size, density) group."""
    from gtopkssgd_tpu.obs import forecast as _forecast
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    inp = planner_inputs()
    fit = {"alpha_ms": inp["alpha_ms"], "beta_gbps": inp["beta_gbps"],
           "ici_gbps": inp["ici_gbps"], "resid_ms": inp.get("resid_ms"),
           "fit_source": inp.get("fit_source")}
    rows = []
    for label, n in sizes.items():
        for rho in densities:
            k = k_for_density(n, rho)
            params = {"mode": "gtopk", "n": n, "k": k, "codec": "fp32"}
            grid = _forecast.grid_rows(
                params, fit, compute_ms=0.0,
                targets=FORECAST_WORKERS, trees=FORECAST_TREES)
            recs = _forecast.recommend(grid)
            cross = _forecast.crossover_p(
                params, fit, p_max=max(FORECAST_WORKERS),
                trees=FORECAST_TREES)
            for r in grid:
                rows.append({
                    "size": label, "n": n, "density": rho, "k": k,
                    "p": r["p"], "plan": r["plan"],
                    "wire_mode": r["wire_mode"],
                    "ici_size": r["ici_size"], "msgs": r["msgs"],
                    "comm_ms_model": r["comm_ms"],
                    "comm_ms_lo": r["step_ms_lo"],
                    "comm_ms_hi": r["step_ms_hi"],
                    "band_ms": r["band_ms"],
                    "recommended": r["plan"] == recs[r["p"]]["plan"],
                    "crossover_p": cross,
                    "fit_source": fit.get("fit_source"),
                })
    return rows


BUCKET_ALPHAS_MS = (0.1, 5.0, 22.0)   # ICI-class, mid, measured-DCN latency
BUCKET_MODELS = ("resnet50", "vgg16")
BUCKET_DENSITY = 0.001


def _model_leaf_sizes(dnn: str):
    """Param leaf sizes in jax.tree flatten order — the exact axis the
    optimizer's bucket plan partitions — via eval_shape (no weights are
    materialized, so this is milliseconds even for the 25M-param net)."""
    import jax.numpy as jnp

    from gtopkssgd_tpu.models import get_model
    model, spec = get_model(dnn)
    x = jnp.zeros((1,) + spec.example_shape, jnp.float32)
    var = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x, train=False))
    return tuple(int(l.size) for l in jax.tree_util.tree_leaves(var["params"]))


def bucket_rows(p: int = 32) -> list:
    """Bucketing evidence rows (parallel.bucketing): per-leaf vs
    DP-bucketed modeled comm ms across the alpha sweep. One row per
    (model, alpha): the DP's chosen B, its modeled ms, and the two
    degenerate partitions (B=1 single merge, B=L per-leaf) — showing the
    latency-bound regime (alpha=22 ms DCN: B collapses toward 1, per-leaf
    pays L*alpha) and the bandwidth-bound one (alpha=0.1 ms ICI-class:
    larger B wins back bucket-local index bits)."""
    from gtopkssgd_tpu.parallel import bucketing, plan_buckets
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    beta = planner_inputs()["beta_gbps"]
    rows = []
    for dnn in BUCKET_MODELS:
        sizes = _model_leaf_sizes(dnn)
        for alpha in BUCKET_ALPHAS_MS:
            kw = dict(p=p, codec="fp32", alpha_ms=alpha, beta_gbps=beta)

            def _ms(spec):
                plan = plan_buckets(sizes, BUCKET_DENSITY,
                                    buckets=spec, **kw)
                return plan, bucketing.partition_cost_ms(plan, **kw)

            auto, auto_ms = _ms("auto")
            _, leaf_ms = _ms("leaf")
            _, b1_ms = _ms(1)
            rows.append({
                "model": dnn, "n_leaves": len(sizes), "n": sum(sizes),
                "density": BUCKET_DENSITY, "p": p,
                "alpha_ms": alpha, "beta_gbps": beta,
                "auto_n_buckets": auto.n_buckets,
                "auto_ms_model": round(auto_ms, 4),
                "b1_ms_model": round(b1_ms, 4),
                "leaf_ms_model": round(leaf_ms, 4),
                "leaf_over_auto": round(leaf_ms / max(auto_ms, 1e-9), 4),
            })
    return rows


PIPELINE_WORKERS = (8, 32)
PIPELINE_BS = tuple(range(1, 9))


def pipeline_rows() -> list:
    """Overlapped-pipeline evidence rows (parallel.bucketing): modeled
    serial-vs-overlapped wall-clock span per (model, alpha, P, B). Each
    order gets its own DP boundaries (serial pricing sums merge cost,
    overlap prices the per-stage max(T_select, T_merge)), then the TRUE
    span formula — sum(sel+merge) serial; fill + sum of interior maxes +
    drain overlapped — so the row is the honest A/B 'auto' compares. The
    sweep shows where pipelining pays: at alpha=0.1 ms (ICI-class) the
    overlapped span dips below serial B=1 from small B on, while at the
    measured-DCN alpha=22 ms the per-bucket latency term dwarfs anything
    selection can hide and serial B=1 stays cheapest."""
    from gtopkssgd_tpu.parallel import bucketing, plan_buckets
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    beta = planner_inputs()["beta_gbps"]
    rows = []
    for dnn in BUCKET_MODELS:
        sizes = _model_leaf_sizes(dnn)
        for alpha in BUCKET_ALPHAS_MS:
            for p in PIPELINE_WORKERS:
                kw = dict(p=p, codec="fp32", alpha_ms=alpha,
                          beta_gbps=beta)
                for b in PIPELINE_BS:

                    def _span(pipe):
                        plan = plan_buckets(sizes, BUCKET_DENSITY,
                                            buckets=b, pipeline=pipe,
                                            **kw)
                        return bucketing.pipeline_span_ms(plan, **kw)

                    ser, ovl = _span("serial"), _span("overlap")
                    rows.append({
                        "model": dnn, "density": BUCKET_DENSITY,
                        "p": p, "alpha_ms": alpha, "beta_gbps": beta,
                        "n_buckets": b,
                        "serial_span_ms": round(ser, 4),
                        "overlap_span_ms": round(ovl, 4),
                        "overlap_speedup": round(ser / max(ovl, 1e-9),
                                                 4),
                    })
    return rows


def main():
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--min-seconds", type=float, default=1.0)
    args = ap.parse_args()

    device = jax.devices()[0].device_kind.replace(" ", "_")
    sizes = dict(list(SIZES.items())[:1]) if args.quick else SIZES
    densities = DENSITIES[:1] if args.quick else DENSITIES
    min_s = 0.3 if args.quick else args.min_seconds

    rows = []
    for label, n in sizes.items():
        for rho in densities:
            k = k_for_density(n, rho)
            for name, timer in VARIANTS.items():
                try:
                    sec, steps = timer(n, k, min_s)
                    err = None
                except Exception as e:  # record, don't abort the sweep
                    sec, steps, err = None, 0, f"{type(e).__name__}: {e}"
                rows.append({
                    "size": label, "n": n, "density": rho, "k": k,
                    "variant": name, "ms": (
                        round(sec * 1e3, 4) if sec is not None else None),
                    "steps_timed": steps, "error": err,
                })
                ms = f"{sec * 1e3:9.3f} ms" if sec is not None else "FAILED"
                print(f"{label:16s} rho={rho:<6g} {name:14s} {ms}",
                      flush=True)

    result = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "chain_rounds": CHAIN_ROUNDS,
        "rows": rows,
        # Comm-planner evidence rows: balanced-vs-tree wire volume and
        # modeled ms per (size, density, P) — the full grid even under
        # --quick, since these are model-side (milliseconds to compute).
        "plan_rows": plan_rows(SIZES, DENSITIES),
        # Bucketing evidence rows: per-leaf vs DP-bucketed modeled comm
        # ms across the alpha sweep — also model-side, full grid always.
        "bucket_rows": bucket_rows(),
        # Pipeline evidence rows: serial-vs-overlapped modeled span per
        # (model, alpha, P, B) — model-side, full grid always.
        "pipeline_rows": pipeline_rows(),
        # Scale-out forecast evidence rows: modeled P in {256, 1024}
        # across axis trees with uncertainty columns (ROADMAP item 3) —
        # model-side, full grid always.
        "forecast_rows": forecast_rows(SIZES, DENSITIES),
    }
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results", f"merge_bench_{device}.json",
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
