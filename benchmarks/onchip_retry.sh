#!/usr/bin/env bash
# Keep trying to drain onchip_queue.sh until it succeeds once.
#
# The axon tunnel wedges unpredictably (rounds 2 and 3 both lost their
# mid-round window). This wrapper probes the backend on a loop and fires
# the full queue at the FIRST window it finds; after one successful drain
# it exits. A wedged probe leaves a hung daemon thread behind in that
# python process only — each probe is its own process, so retries stay
# clean.
#
# Usage: bash benchmarks/onchip_retry.sh [outdir=/tmp/onchip_queue] [max_tries=40]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/onchip_queue}
MAX=${2:-40}
log() { echo "[onchip_retry $(date -u +%H:%M:%S)] $*"; }

mkdir -p "$OUT"
for try in $(seq 1 "$MAX"); do
    log "attempt $try/$MAX: probe"
    # Structured probe: same bounded-wait init as before, but every
    # attempt leaves a JSONL record (timestamp, attempt, elapsed, error
    # tail) in $OUT/backend_probe.jsonl — the rounds-2/3 post-mortems
    # had to reconstruct exactly this from shell timestamps.
    if python benchmarks/backend_probe.py --timeout 180 \
        --attempt "$try" --log "$OUT/backend_probe.jsonl"
    then
        log "backend alive; draining queue"
        # Bound the drain: a tunnel that wedges MID-drain (rounds 2+3
        # failure mode) would otherwise hang this loop forever and
        # silently miss the next window. A full healthy drain is ~60-90
        # min (longer with the round-5 mfu + resnet50 stages; 3.5h cap).
        timeout 12600 bash benchmarks/onchip_queue.sh "$OUT"
        rc=$?
        log "queue rc=$rc"
        if [ "$rc" -eq 0 ]; then
            log "queue drained; done"
            exit 0
        fi
    else
        log "backend dead/hung"
    fi
    sleep 300
done
log "gave up after $MAX attempts"
exit 4
