"""All six reference workloads end-to-end on the real chip.

The unit/oracle tests prove every model family trains on the virtual CPU
mesh; this benchmark proves the same through the PRODUCTION Trainer on
actual TPU silicon — model build, synthetic data pipeline, prefetch,
jitted train step with compression, eval — and records throughput per
workload (samples/sec through trainer.train, host pipeline included;
bench.py remains the device-step-only headline).

Writes benchmarks/results/workloads_<device>.json.

Run:  python -m benchmarks.workloads_bench [--steps 20] [--quick]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time

import jax

# (dnn, per-chip batch, extra config) — batch sizes pick the paper's
# per-worker values where they fit one chip comfortably.
WORKLOADS = [
    ("vgg16", 128, {}),
    ("resnet20", 128, {}),
    ("alexnet", 64, {"dtype": "bfloat16"}),
    ("resnet50", 64, {"dtype": "bfloat16"}),
    ("lstm", 20, {}),
    ("lstman4", 8, {}),
]


def bench_workload(dnn: str, batch: int, extra: dict, steps: int):
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    t0 = time.perf_counter()
    with Trainer(TrainConfig(
        dnn=dnn, batch_size=batch, nworkers=1, compression="gtopk",
        density=0.001, max_epochs=1, log_interval=10 ** 9,
        eval_batches=1, **extra,
    )) as t:
        build_s = time.perf_counter() - t0
        warm = t.train(3)           # compile + warm
        run = t.train(steps)        # timed window (train() fences state)
        ev = t.test()
    return {
        "dnn": dnn,
        "batch_size": batch,
        "steps": steps,
        "samples_per_sec": round(run["throughput"], 2),
        "step_ms": round(run["wall"] / steps * 1e3, 2),
        "loss_finite": math.isfinite(run["loss"]),
        "eval_keys": sorted(ev.keys()),
        "build_seconds": round(build_s, 1),
        "compile_seconds": round(warm["wall"], 1),
        **{k: extra[k] for k in extra},
    }


def measure_h2d_mbps() -> float:
    """Measured host->device bandwidth — context for the samples/sec
    numbers: on this environment's TUNNELED chip H2D runs at ~45 MB/s
    (vs GB/s on a real TPU host), so input-bound rows here are bounded by
    the tunnel, not the framework. This is why the pipelines ship uint8."""
    import numpy as np
    import jax.numpy as jnp
    from gtopkssgd_tpu.utils import true_sync

    x = np.zeros((32, 224, 224, 3), np.float32)
    true_sync(jnp.asarray(x))  # warm
    t0 = time.perf_counter()
    true_sync(jnp.asarray(x))
    return x.nbytes / 1e6 / (time.perf_counter() - t0)


def main():
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    device = jax.devices()[0].device_kind.replace(" ", "_")
    workloads = WORKLOADS[:2] if args.quick else WORKLOADS
    steps = 5 if args.quick else args.steps

    rows = []
    for dnn, batch, extra in workloads:
        try:
            row = bench_workload(dnn, batch, extra, steps)
        except Exception as e:  # record, keep sweeping
            row = {"dnn": dnn, "batch_size": batch,
                   "error": f"{type(e).__name__}: {e}"}
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results", f"workloads_{device}.json",
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump({"device_kind": jax.devices()[0].device_kind,
                   "backend": jax.default_backend(),
                   "mode": "gtopk rho=0.001, nworkers=1, synthetic data",
                   "h2d_mbytes_per_sec": round(measure_h2d_mbps(), 1),
                   "note": "samples/sec includes the host pipeline and "
                           "H2D transfer; see measure_h2d_mbps docstring",
                   "rows": rows}, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
