#!/usr/bin/env bash
# Run every on-chip measurement back to back in ONE tunnel window.
#
# The axon TPU tunnel wedges unpredictably (died mid-round in rounds 2 AND
# 3); when it is up, the priority is to drain the whole measurement queue
# before touching anything else. Each stage is its own Python process (one
# process holds the device at a time; a crash or wedge in one stage does
# not take the rest down — later stages will fail fast on the dead
# backend via init_backend_with_deadline and leave their absence visible).
#
# Usage: bash benchmarks/onchip_queue.sh [outdir=/tmp/onchip_queue]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/onchip_queue}
mkdir -p "$OUT"
# Clear stage outputs from any previous (possibly wedged) drain: stages
# run front-to-back, so a fresh drain re-measures everything anyway, and
# leftovers must not be mistaken for this drain's results by the
# assemble stage (it also applies its own staleness filter).
rm -f "$OUT"/bench_bs*.json "$OUT"/mfu_ablation.jsonl "$OUT"/*.log
log() { echo "[onchip_queue $(date -u +%H:%M:%S)] $*"; }

log "probe"
python - <<'EOF' || { echo "backend dead; aborting queue"; exit 3; }
from gtopkssgd_tpu.utils import init_backend_with_deadline
raise SystemExit(0 if init_backend_with_deadline(120) else 1)
EOF

log "bench bs=128"
python bench.py --batch-size 128 > "$OUT/bench_bs128.json" 2> "$OUT/bench_bs128.log"
log "bench bs=128 rc=$? $(tail -c 200 "$OUT/bench_bs128.json")"

log "bench bs=256"
python bench.py --batch-size 256 > "$OUT/bench_bs256.json" 2> "$OUT/bench_bs256.log"
log "bench bs=256 rc=$?"

log "bench bs=512 (bf16-BN halves activation bytes; a larger batch may now pay)"
python bench.py --batch-size 512 > "$OUT/bench_bs512.json" 2> "$OUT/bench_bs512.log"
log "bench bs=512 rc=$?"

log "bench bs=256 s2d stem"
python bench.py --batch-size 256 --s2d --compression gtopk \
    > "$OUT/bench_bs256_s2d.json" 2> "$OUT/bench_bs256_s2d.log"
log "bench s2d rc=$?"

log "bench bs=128 momentum-correction (the recommended-config candidate's step cost)"
python bench.py --batch-size 128 --momentum-correction \
    > "$OUT/bench_bs128_corr.json" 2> "$OUT/bench_bs128_corr.log"
log "bench corr rc=$?"

log "assemble committed bench artifact from whatever stages succeeded"
# Round number is derived from the newest committed bench_r<N> artifact
# (same round on re-assembly from this dir, else N+1) — see derive_round.
python benchmarks/assemble_bench_artifact.py --queue-dir "$OUT"
log "assemble rc=$?"

log "mfu ablation ladder (round-5 verdict #3: decompose the 0.26 dense MFU by ablation; profiler op-attribution is dead on this platform)"
python benchmarks/mfu_ablation.py > "$OUT/mfu_ablation.jsonl" 2> "$OUT/mfu_ablation.log"
log "mfu ablation rc=$?"

log "convergence (5 arms)"
python benchmarks/convergence_run.py --dnn resnet20 --steps 1200 \
    --modes dense,gtopk,allgather,gtopk_layerwise,gtopk+corr \
    --density 0.001 > "$OUT/convergence.log" 2>&1
log "convergence rc=$?"

log "resnet20 HARD-task convergence (round-5 verdict #4: accuracy-discriminative arms on silicon — the easy task pins every arm at val_top1=1.0)"
python benchmarks/convergence_run.py --dnn resnet20 --steps 1200 \
    --batch-size 32 --modes dense,gtopk,gtopk+corr --density 0.001 \
    --synth-hard --eval-batches 16 > "$OUT/convergence_hard.log" 2>&1
log "hard-task rc=$?"

log "steps_per_dispatch payoff A/B (round-4 weak #5: the feature's target regime is ms-scale chip steps; measured neutral on CPU meshes)"
python -m gtopkssgd_tpu.dist_trainer --dnn resnet20 --compression gtopk \
    --density 0.001 --batch-size 32 --num-iters 400 --eval-batches 1 \
    --steps-per-dispatch 1 > "$OUT/spd1.log" 2>&1
log "spd=1 rc=$? $(grep -o "'throughput': [0-9.]*" "$OUT/spd1.log" | tail -1)"
python -m gtopkssgd_tpu.dist_trainer --dnn resnet20 --compression gtopk \
    --density 0.001 --batch-size 32 --num-iters 400 --eval-batches 1 \
    --steps-per-dispatch 20 > "$OUT/spd20.log" 2>&1
log "spd=20 rc=$? $(grep -o "'throughput': [0-9.]*" "$OUT/spd20.log" | tail -1)"

log "resnet50 synthetic-imagenet convergence (round-5 verdict #5: first ImageNet-workload convergence evidence; 25.6M params => the auto policy routes selection through approx_max_k, so this is ALSO the production approx path's first convergence run)"
python benchmarks/convergence_run.py --dnn resnet50 --steps 1500 --chunk 50 \
    --batch-size 64 --modes dense,gtopk+corr --density 0.001 \
    --eval-batches 8 > "$OUT/convergence_resnet50.log" 2>&1
log "resnet50 rc=$?"

log "vgg16 convergence (~23 s/step on the host CPU mesh; before an4 — it carries the exact-vs-approx A/B)"
# gtopk+corr auto-routes selection to approx_max_k at 15M params — the
# first conv-net convergence through the production approx path; the
# +exact arm is the same config through exact lax.top_k, making this the
# exact-vs-approx convergence A/B (round-3 verdict weak #4).
python benchmarks/convergence_run.py --dnn vgg16 --steps 600 --chunk 25 \
    --batch-size 32 --modes dense,gtopk+corr,gtopk+corr+exact \
    --density 0.001 \
    --eval-batches 16 > "$OUT/convergence_vgg16.log" 2>&1
log "vgg16 rc=$?"

log "an4 convergence (chip-only: ~70 s/step on the 1-core host CPU mesh)"
python benchmarks/convergence_run.py --dnn lstman4 --steps 200 --chunk 20 \
    --batch-size 8 --modes dense,gtopk --density 0.001 \
    --eval-batches 8 > "$OUT/convergence_an4.log" 2>&1
log "an4 rc=$?"

log "queue done"
