"""Structured backend-liveness probe: one JSONL record per attempt.

The round-2/3 post-mortems had to reconstruct WHEN the axon tunnel died
from shell-log timestamps around an opaque ``rc=3`` — the probes knew
(attempt number, how long init blocked, what the first jax call raised)
and threw it away. This probe keeps the exact liveness semantics of
``utils.init_backend_with_deadline`` (init on a daemon thread, bounded
wait; a hung PJRT client creation cannot be cancelled, only abandoned)
but records every attempt as one JSONL line:

    {"kind": "backend_probe", "time": ..., "attempt": 3, "timeout_s": 180,
     "elapsed_s": 180.0, "alive": false, "hung": true}

plus ``backend``/``device_count`` when init succeeds and the exception
tail when it errors. Unlike init_backend_with_deadline (which reports an
ERRORING init as "alive" so the caller's own jax call surfaces the real
message), the probe classifies an init error as NOT alive — a retry loop
must not fire a multi-hour queue drain at a backend that raises.

Exit code: 0 alive, 3 dead/hung (bench.py's dead-tunnel convention).

Usage (from onchip_retry.sh):
  python benchmarks/backend_probe.py --timeout 180 --attempt "$try" \
      --log "$OUT/backend_probe.jsonl"
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, Optional

DEAD_RC = 3


def make_record(alive: bool, timeout_s: float, elapsed_s: float,
                attempt: Optional[int] = None, **extra) -> Dict[str, Any]:
    rec: Dict[str, Any] = {
        "kind": "backend_probe",
        "time": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "alive": bool(alive),
        "timeout_s": float(timeout_s),
        "elapsed_s": round(float(elapsed_s), 3),
    }
    if attempt is not None:
        rec["attempt"] = int(attempt)
    rec.update(extra)
    return rec


def run_probe(timeout_s: float = 180.0,
              attempt: Optional[int] = None) -> Dict[str, Any]:
    """Probe THIS process's jax backend with a bounded wait."""
    holder: Dict[str, Any] = {}
    done = threading.Event()

    def _init():
        try:
            import jax

            holder["device_count"] = int(jax.device_count())
            holder["backend"] = jax.default_backend()
        except Exception as e:
            holder["error"] = "".join(
                traceback.format_exception_only(type(e), e)).strip()[-500:]
        finally:
            done.set()

    t0 = time.monotonic()
    threading.Thread(target=_init, daemon=True,
                     name="backend-probe-init").start()
    finished = done.wait(timeout_s)
    return make_record(
        alive=finished and "error" not in holder,
        timeout_s=timeout_s,
        elapsed_s=time.monotonic() - t0,
        attempt=attempt,
        hung=not finished,
        **{k: holder[k] for k in ("backend", "device_count", "error")
           if k in holder},
    )


def append_jsonl(rec: Dict[str, Any], path: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "backend_probe",
        description="Probe jax backend liveness; emit one JSONL record.")
    ap.add_argument("--timeout", type=float, default=180.0)
    ap.add_argument("--attempt", type=int, default=None,
                    help="retry-loop attempt number, recorded verbatim")
    ap.add_argument("--log", default=None,
                    help="append the record to this JSONL file as well "
                         "as printing it")
    args = ap.parse_args(argv)
    rec = run_probe(args.timeout, attempt=args.attempt)
    print(json.dumps(rec, sort_keys=True), flush=True)
    if args.log:
        append_jsonl(rec, args.log)
    return 0 if rec["alive"] else DEAD_RC


if __name__ == "__main__":
    raise SystemExit(main())
