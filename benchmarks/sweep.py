"""Density sweep + timing breakdown (BASELINE.json configs #3/#5; the
reference ran this as a family of mpirun scripts over --density values).

Usage:
  python benchmarks/sweep.py --dnn resnet20 --densities 1 0.01 0.001 0.0001
  python benchmarks/sweep.py --breakdown --dnn resnet20

Writes one JSON line per point to stdout and (optionally) a JSONL file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gtopkssgd_tpu.benchmark import (
    BenchConfig,
    measure_breakdown,
    measure_throughput,
)


def main():
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet20")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=30,
                    help="per-phase step count (--breakdown mode only; "
                         "throughput mode uses --min-seconds windows)")
    ap.add_argument("--min-seconds", type=float, default=2.0,
                    help="throughput-mode timed window per point")
    ap.add_argument("--densities", type=float, nargs="+",
                    default=[1.0, 0.01, 0.001, 0.0001])
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--topk-method", default="auto")
    ap.add_argument("--breakdown", action="store_true",
                    help="per-phase decomposition instead of fused step")
    ap.add_argument("--hier-ici", type=int, default=0,
                    help="> 0: also sweep gtopk_hier with this many devices "
                         "per ICI slice")
    ap.add_argument("--out", default=None, help="append JSONL here too")
    args = ap.parse_args()

    cfg = BenchConfig(
        dnn=args.dnn, batch_size=args.batch_size, steps=args.steps,
        min_seconds=args.min_seconds, dtype=args.dtype,
        topk_method=args.topk_method,
        hier_ici=max(1, args.hier_ici),
    )
    fh = open(args.out, "a") if args.out else None
    points = [("dense", 1.0)] + [("gtopk", d) for d in args.densities
                                 if d < 1.0]
    points += [("allgather", d) for d in args.densities if d < 1.0]
    if args.hier_ici > 1:
        points += [("gtopk_hier", d) for d in args.densities if d < 1.0]
    for mode, density in points:
        fn = measure_breakdown if args.breakdown else measure_throughput
        rec = fn(cfg, mode, density)
        rec["dnn"] = cfg.dnn
        line = json.dumps(rec)
        print(line)
        sys.stdout.flush()
        if fh:
            fh.write(line + "\n")
            fh.flush()
    if fh:
        fh.close()


if __name__ == "__main__":
    main()
