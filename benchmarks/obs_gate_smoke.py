"""Gate smoke: the canonical tiny CPU run behind the obs regression gate.

ONE place defines the run that the committed baseline
(benchmarks/results/obs_gate_baseline_cpu.json) describes: a few
gtopk_layerwise steps of resnet20 on a 2-way CPU mesh with per-layer
telemetry and the recall audit on. Both consumers import it:

  tests/test_obs.py         runs it in-process and asserts
                            ``report gate`` exits 0 against the committed
                            baseline — the tier-1 drift gate.
  this file as a script     regenerates the run and, with
                            --write-baseline, re-stamps the baseline's
                            expectations (after an INTENTIONAL behavior
                            change; review the JSON diff like code).

Tolerances live in the baseline, not here: tight (5%) on structurally
deterministic counters (sent_elems, wire_bytes, achieved_density — fixed
by k and the layer shapes), loose on value-dependent statistics (norms,
m(k), recall) that may wobble with compiler version or thread count.

Usage:
  python benchmarks/obs_gate_smoke.py                  # run + gate
  python benchmarks/obs_gate_smoke.py --write-baseline # regenerate
  python benchmarks/obs_gate_smoke.py --only goodput   # one sub-smoke,
                                       # gated against the SUBSET of the
                                       # committed checks its kinds own
  python benchmarks/obs_gate_smoke.py --only goodput --write-baseline
                                       # re-stamp ONLY that subset's
                                       # expectations back into the
                                       # committed baseline (all other
                                       # checks untouched)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "results",
    "obs_gate_baseline_cpu.json")

SMOKE_STEPS = 4

# Sub-smoke registry: name -> the metrics kinds its grafted record(s)
# carry, i.e. exactly the committed baseline checks that ``--only NAME``
# runs and (with --write-baseline) re-stamps. The main canonical run
# always executes — it hosts the grafted records the gate reads.
SMOKES = {
    "recovery": ("inject", "recovery"),
    "twostage": ("twostage",),
    "codec": ("codec",),
    "plan": ("plan",),
    "bucket": ("bucket",),
    "overlap": ("overlap",),
    "calib": ("calib", "regress"),
    "mem": ("mem",),
    "critpath": ("critpath",),
    "goodput": ("goodput",),
    "linkmap": ("linkmap",),
    "forecast": ("forecast",),
    "elastic": ("resize",),
    "lint": ("lint",),
}
# Sub-smokes a selected one cannot run without: the plan A/B reuses the
# codec smoke's fp32 arms as its tree baseline.
SMOKE_DEPS = {"plan": ("codec",)}


def _selected(name: str, only) -> bool:
    return (only is None or name == only
            or name in SMOKE_DEPS.get(only, ()))


def smoke_config(out_dir: str):
    """The canonical gate-smoke TrainConfig. Any field change here
    invalidates the committed baseline — regenerate it in the same
    commit (--write-baseline)."""
    from gtopkssgd_tpu.trainer import TrainConfig

    return TrainConfig(
        dnn="resnet20",
        batch_size=4,
        nworkers=2,
        compression="gtopk_layerwise",
        density=0.01,
        seed=42,
        max_epochs=1,
        log_interval=2,
        eval_batches=1,
        obs_layers=True,
        obs_audit_interval=2,
        obs_interval=2,
        out_dir=out_dir,
    )


def run_recovery_smoke(out_dir: str) -> str:
    """Injected-fault recovery sub-run: same canonical model/compression
    (so it reuses the persistent compile cache), 3 steps with a NaN
    injected at step 2 and ``nan_loss=skip`` claiming the anomaly. The
    run must exit 0 — the recovery path turning a would-be exit 44 into
    a completed run IS the property under test. Returns its run dir
    (a subdir, so ``resolve_paths`` on the parent never sees it)."""
    from gtopkssgd_tpu import dist_trainer

    rec_dir = os.path.join(out_dir, "recovery")
    rc = dist_trainer.main([
        "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--seed", "42", "--num-iters", "3", "--eval-batches", "1",
        "--log-interval", "1", "--obs-interval", "1",
        "--obs-halt-on", "error",
        "--inject", "nan_grad@2", "--recover-policy", "nan_loss=skip",
        "--out-dir", rec_dir,
    ])
    if rc != 0:
        raise RuntimeError(
            f"recovery smoke exited {rc} (expected 0: the nan_loss=skip "
            f"policy should claim the injected NaN)")
    return rec_dir


def run_twostage_smoke(out_dir: str) -> dict:
    """Exact-vs-twostage A/B on the fused p=1 threshold path (the ISSUE-6
    tentpole's consumer): two tiny flat-gtopk sub-runs differing ONLY in
    --topk-method, each with the recall audit on and two steps traced for
    the paper's T_compute/T_select/T_comm split. Returns the fields the
    main run logs as ONE "twostage" record so the drift gate can pin

      audit_recall_twostage      twostage tau keeps a SUPERSET of the
                                 exact top-k (tau_twostage <= tau_exact),
                                 so the audited recall floor is ~1.0
      select_frac_regression     max(0, frac_select_twostage -
                                 frac_select_exact): one-sided "T_select
                                 fraction no worse than exact" evidence

    On a platform without usable op traces the frac fields are omitted
    (same degradation as run_smoke's attr_error path)."""
    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs.trace_attr import attribute, capture
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    per_method: dict = {}
    for method in ("exact", "twostage"):
        sub = os.path.join(out_dir, f"twostage_ab_{method}")
        cfg = TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=1,
            compression="gtopk", density=0.01, seed=42,
            max_epochs=1, log_interval=2, eval_batches=1,
            obs_interval=1, obs_audit_interval=2,
            topk_method=method, out_dir=sub)
        with Trainer(cfg) as t:
            t.train(2)  # audit fires at step 2 (obs_audit_interval=2)
            trace_dir = os.path.join(sub, "trace")
            try:
                with capture(trace_dir):
                    t.train(2)
                frac = attribute(trace_dir, mode=method).get("frac_select")
            except Exception:  # platform without usable op traces
                frac = None
        recs, _ = report.load_records(sub)
        audited = [r["audit_recall"] for r in recs
                   if r.get("kind") == "obs"
                   and float(r.get("audit_recall", -1.0)) >= 0.0]
        per_method[method] = {
            "audit_recall": max(audited) if audited else -1.0,
            "frac_select": frac,
        }
    rec = {
        "audit_recall_exact": per_method["exact"]["audit_recall"],
        "audit_recall_twostage": per_method["twostage"]["audit_recall"],
    }
    fs_e = per_method["exact"]["frac_select"]
    fs_t = per_method["twostage"]["frac_select"]
    if fs_e is not None and fs_t is not None:
        rec["frac_select_exact"] = fs_e
        rec["frac_select_twostage"] = fs_t
        rec["select_frac_ratio"] = round(fs_t / max(fs_e, 1e-9), 4)
        rec["select_frac_regression"] = round(max(0.0, fs_t - fs_e), 6)
    return rec


def run_codec_smoke(out_dir: str) -> dict:
    """int8-vs-fp32 wire-codec A/B (the ISSUE-7 tentpole's consumer):
    four tiny flat-gtopk sub-runs — codec x density over {fp32, int8} x
    {0.001, 0.01} — differing ONLY in those two fields, each with the
    recall audit on. Returns the fields the main run logs as ONE "codec"
    record so the drift gate can pin the PR's acceptance numbers:

      wire_ratio_rho001        int8/fp32 measured wire_bytes at rho=1e-3
                               (the DCN regime k): ~0.32, i.e. >=3x
      dcn_excess_rho001        max(0, ratio - 1/3): one-sided ">=3x
                               reduction" evidence, exactly 0.0
      wire_excess_rho01        max(0, ratio@rho=0.01 - 0.30): the gate
                               smoke's own density meets the same bar
      audit_recall_int8        audited recall under the lossy codec
                               (flat gtopk reselects the exact top-k of
                               the dequantized merge, so the floor is
                               ~1.0 — well above the 0.95 acceptance)
      residual_norm_int8       error feedback stays bounded with the
                               quantization error folded in
      ledger_bytes_ratio_int8  obs/ledger.py's modeled-vs-measured wire
                               bytes on the int8 sub-run: ~1.0 means the
                               codec-aware model explains the achieved
                               bytes (the "ledger-audited" acceptance)

    The ratios divide two structurally deterministic counters (byte
    counts are fixed by k, n and the codec bit budget), so tolerances in
    the baseline are tight; the one-sided excess fields are exact."""
    from gtopkssgd_tpu.obs import ledger, report
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    measured: dict = {}
    int8_records = None
    for rho in (0.001, 0.01):
        for codec in ("fp32", "int8"):
            sub = os.path.join(
                out_dir, f"codec_ab_{codec}_rho{rho:g}".replace(".", "p"))
            cfg = TrainConfig(
                dnn="resnet20", batch_size=4, nworkers=2,
                compression="gtopk", density=rho, seed=42,
                max_epochs=1, log_interval=2, eval_batches=1,
                obs_interval=1, obs_audit_interval=2,
                wire_codec=codec, out_dir=sub)
            with Trainer(cfg) as t:
                t.train(2)  # audit fires at step 2 (obs_audit_interval)
            recs, _ = report.load_records(sub)
            obs = [r for r in recs if r.get("kind") == "obs"]
            wire = [float(r["wire_bytes"]) for r in obs
                    if isinstance(r.get("wire_bytes"), (int, float))]
            audited = [float(r["audit_recall"]) for r in obs
                       if float(r.get("audit_recall", -1.0)) >= 0.0]
            res = [float(r["residual_norm"]) for r in obs
                   if isinstance(r.get("residual_norm"), (int, float))]
            measured[(codec, rho)] = {
                "wire_bytes": sum(wire) / len(wire) if wire else 0.0,
                "audit_recall": max(audited) if audited else -1.0,
                "residual_norm": res[-1] if res else -1.0,
            }
            if codec == "int8" and rho == 0.001:
                int8_records = recs
    r001 = (measured[("int8", 0.001)]["wire_bytes"]
            / max(measured[("fp32", 0.001)]["wire_bytes"], 1e-9))
    r01 = (measured[("int8", 0.01)]["wire_bytes"]
           / max(measured[("fp32", 0.01)]["wire_bytes"], 1e-9))
    rec = {
        "wire_codec": "int8",
        "wire_bytes_fp32_rho001": measured[("fp32", 0.001)]["wire_bytes"],
        "wire_bytes_int8_rho001": measured[("int8", 0.001)]["wire_bytes"],
        "wire_bytes_fp32_rho01": measured[("fp32", 0.01)]["wire_bytes"],
        "wire_bytes_int8_rho01": measured[("int8", 0.01)]["wire_bytes"],
        "wire_ratio_rho001": round(r001, 6),
        "wire_ratio_rho01": round(r01, 6),
        "dcn_excess_rho001": round(max(0.0, r001 - 1.0 / 3.0), 6),
        "wire_excess_rho01": round(max(0.0, r01 - 0.30), 6),
        "dcn_reduction_x": round(1.0 / max(r001, 1e-9), 4),
        "audit_recall_int8": measured[("int8", 0.001)]["audit_recall"],
        "recall_floor_breach": round(max(
            0.0, 0.95 - measured[("int8", 0.001)]["audit_recall"]), 6),
        "residual_norm_int8": measured[("int8", 0.001)]["residual_norm"],
    }
    # The ledger audit: join the int8 sub-run's achieved wire_bytes
    # against the codec-aware comm model (obs/ledger.py reads wire_codec
    # from the manifest). Mean ratio ~1.0 IS the acceptance evidence
    # that the measured reduction matches the modeled one.
    rows = [r for r in ledger.ledger_rows(int8_records or [])
            if r.get("source") == "wire_bytes"
            and isinstance(r.get("ratio"), (int, float))]
    if rows:
        rec["ledger_bytes_ratio_int8"] = round(
            sum(float(r["ratio"]) for r in rows) / len(rows), 6)
        rec["ledger_rows_int8"] = len(rows)
    return rec


def run_plan_smoke(out_dir: str, codec_rec: dict) -> dict:
    """Balanced-vs-tree comm-planner A/B (the ISSUE-9 tentpole's
    consumer): two tiny flat-gtopk sub-runs pinned to the Ok-Topk
    balanced schedule (--comm-plan balanced) at the codec smoke's two
    densities; the tree arms are REUSED from the codec smoke's fp32
    sub-runs (same config except the pin, and their auto plan resolves
    to the tree at this shape), so the A/B costs two runs, not four.
    Returns the fields the main run logs as ONE "plan" record:

      wire_ratio_rho001/rho01    balanced/tree measured wire_bytes. At
                                 p=2 the balanced schedule's 2p-1=3
                                 capped messages cost MORE than the
                                 tree's single full exchange (~2.25x:
                                 3*cap/k with cap=ceil(1.5k/2)) — the
                                 planner's whole point is that this is
                                 shape-dependent; the crossover at
                                 p>=8 is pinned model-side in
                                 tests/test_planner.py and
                                 benchmarks/merge_bench.py
      recall_floor_breach        max(0, 0.95 - audited recall) under the
                                 balanced schedule: exactly 0.0 (the
                                 capped scatter drops nothing at these
                                 shapes and repair is exact)
      ledger_bytes_ratio_balanced  obs/ledger.py modeled-vs-measured
                                 wire bytes on a balanced sub-run: ~1.0
                                 means the plan-keyed model explains
                                 the balanced wire exactly

    The ratios divide structurally deterministic byte counters, so the
    baseline pins them tight; the breach field is exact."""
    from gtopkssgd_tpu.obs import ledger, report
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    tree_bytes = {0.001: codec_rec["wire_bytes_fp32_rho001"],
                  0.01: codec_rec["wire_bytes_fp32_rho01"]}
    measured: dict = {}
    bal_records = None
    for rho in (0.001, 0.01):
        sub = os.path.join(
            out_dir, f"plan_ab_balanced_rho{rho:g}".replace(".", "p"))
        cfg = TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=2,
            compression="gtopk", density=rho, seed=42,
            max_epochs=1, log_interval=2, eval_batches=1,
            obs_interval=1, obs_audit_interval=2,
            comm_plan="balanced", out_dir=sub)
        with Trainer(cfg) as t:
            t.train(2)  # audit fires at step 2 (obs_audit_interval)
        recs, _ = report.load_records(sub)
        obs = [r for r in recs if r.get("kind") == "obs"]
        wire = [float(r["wire_bytes"]) for r in obs
                if isinstance(r.get("wire_bytes"), (int, float))]
        audited = [float(r["audit_recall"]) for r in obs
                   if float(r.get("audit_recall", -1.0)) >= 0.0]
        measured[rho] = {
            "wire_bytes": sum(wire) / len(wire) if wire else 0.0,
            "audit_recall": max(audited) if audited else -1.0,
        }
        if rho == 0.001:
            bal_records = recs
    r001 = measured[0.001]["wire_bytes"] / max(tree_bytes[0.001], 1e-9)
    r01 = measured[0.01]["wire_bytes"] / max(tree_bytes[0.01], 1e-9)
    rec = {
        "schedule": "balanced",
        "wire_bytes_balanced_rho001": measured[0.001]["wire_bytes"],
        "wire_bytes_tree_rho001": tree_bytes[0.001],
        "wire_bytes_balanced_rho01": measured[0.01]["wire_bytes"],
        "wire_bytes_tree_rho01": tree_bytes[0.01],
        "wire_ratio_rho001": round(r001, 6),
        "wire_ratio_rho01": round(r01, 6),
        "audit_recall_balanced": measured[0.001]["audit_recall"],
        "recall_floor_breach": round(max(
            0.0, 0.95 - measured[0.001]["audit_recall"]), 6),
    }
    # The ledger audit: the balanced sub-run's achieved wire_bytes
    # against the plan-keyed comm model (obs/ledger.py reads
    # comm_plan_schedule from the manifest). Mean ratio ~1.0 IS the
    # evidence that the (2p-1)*wire_set_bytes(cap, n) accounting
    # matches what the schedule put on the wire.
    rows = [r for r in ledger.ledger_rows(bal_records or [])
            if r.get("source") == "wire_bytes"
            and isinstance(r.get("ratio"), (int, float))]
    if rows:
        rec["ledger_bytes_ratio_balanced"] = round(
            sum(float(r["ratio"]) for r in rows) / len(rows), 6)
        rec["ledger_rows_balanced"] = len(rows)
    return rec


def run_bucket_smoke(out_dir: str) -> dict:
    """Bucketed-vs-per-leaf layerwise A/B (the bucketing tentpole's
    consumer): two tiny gtopk_layerwise sub-runs at the DCN-regime
    density (rho=0.001, p=2, 2 steps) differing ONLY in --buckets —
    'leaf' (one merge per param leaf, B=L) vs 'auto' (the alpha-beta DP,
    which at the committed ~22 ms alpha collapses resnet20's 65 leaves
    to B=1). Returns the fields the main run logs as ONE "bucket"
    record so the drift gate can pin the PR's acceptance numbers:

      collective_ratio           leaf/auto per-step sparse-merge count
                                 from the collective_count telemetry
                                 (structural: L=65 over B=1). The
                                 acceptance bar is >=3x fewer merges
      collective_floor_breach    max(0, 3 - ratio): one-sided ">=3x"
                                 evidence, exactly 0.0
      audit_recall_bucketed      audited recall on the bucketed arm
                                 (per-bucket exact top-k audit), floor
                                 0.95
      ledger_bytes_ratio_bucketed  obs/ledger.py modeled-vs-measured
                                 wire bytes on the bucketed arm: ~1.0
                                 means the bucket-summed model explains
                                 the achieved bytes

    Counts and byte counters are structural (fixed by the leaf shapes
    and the DP's boundaries), so the baseline pins them tight."""
    from gtopkssgd_tpu.obs import ledger, report
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    measured: dict = {}
    auto_records = None
    for buckets in ("leaf", "auto"):
        sub = os.path.join(out_dir, f"bucket_ab_{buckets}")
        cfg = TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=2,
            compression="gtopk_layerwise", density=0.001, seed=42,
            max_epochs=1, log_interval=2, eval_batches=1,
            obs_interval=1, obs_audit_interval=2,
            buckets=buckets, out_dir=sub)
        with Trainer(cfg) as t:
            t.train(2)  # audit fires at step 2 (obs_audit_interval)
            n_buckets = t._bucket_plan.n_buckets
        recs, _ = report.load_records(sub)
        obs = [r for r in recs if r.get("kind") == "obs"]
        coll = [float(r["collective_count"]) for r in obs
                if isinstance(r.get("collective_count"), (int, float))]
        wire = [float(r["wire_bytes"]) for r in obs
                if isinstance(r.get("wire_bytes"), (int, float))]
        audited = [float(r["audit_recall"]) for r in obs
                   if float(r.get("audit_recall", -1.0)) >= 0.0]
        measured[buckets] = {
            "n_buckets": n_buckets,
            "collective_count": max(coll) if coll else 0.0,
            "wire_bytes": sum(wire) / len(wire) if wire else 0.0,
            "audit_recall": max(audited) if audited else -1.0,
        }
        if buckets == "auto":
            auto_records = recs
    ratio = (measured["leaf"]["collective_count"]
             / max(measured["auto"]["collective_count"], 1e-9))
    wire_ratio = (measured["auto"]["wire_bytes"]
                  / max(measured["leaf"]["wire_bytes"], 1e-9))
    rec = {
        "buckets": "auto",
        "n_buckets_leaf": measured["leaf"]["n_buckets"],
        "n_buckets_auto": measured["auto"]["n_buckets"],
        "collective_count_leaf": measured["leaf"]["collective_count"],
        "collective_count_auto": measured["auto"]["collective_count"],
        "collective_ratio": round(ratio, 4),
        "collective_floor_breach": round(max(0.0, 3.0 - ratio), 6),
        "wire_bytes_leaf": measured["leaf"]["wire_bytes"],
        "wire_bytes_auto": measured["auto"]["wire_bytes"],
        "wire_ratio_auto_leaf": round(wire_ratio, 6),
        "audit_recall_bucketed": measured["auto"]["audit_recall"],
        "recall_floor_breach": round(max(
            0.0, 0.95 - measured["auto"]["audit_recall"]), 6),
    }
    # The ledger audit: the bucketed arm's achieved wire_bytes against
    # the bucket-summed comm model (obs/ledger.py reads the manifest's
    # bucket_sizes/bucket_ks and prices each bucket over its OWN local
    # index space). Mean ratio ~1.0 IS the evidence that the bucketed
    # wire accounting matches what the schedule put on the wire.
    rows = [r for r in ledger.ledger_rows(auto_records or [])
            if r.get("source") == "wire_bytes"
            and isinstance(r.get("ratio"), (int, float))]
    if rows:
        rec["ledger_bytes_ratio_bucketed"] = round(
            sum(float(r["ratio"]) for r in rows) / len(rows), 6)
        rec["ledger_rows_bucketed"] = len(rows)
    return rec


def run_overlap_smoke(out_dir: str) -> dict:
    """Pipelined-vs-serial A/B (the overlapped-pipeline tentpole's
    consumer): for each codec in {fp32, int8:64}, two tiny bucketed
    gtopk_layerwise sub-runs (p=2, 2 steps, --buckets 4) differing ONLY
    in --pipeline — 'serial' (the paper's barrier-pinned sequential
    chain) vs 'overlap' (double-buffered stages). Returns the fields
    the main run logs as ONE "overlap" record so the drift gate pins
    the PR's acceptance numbers:

      bit_delta_fp32 / bit_delta_int8   max |serial - overlap| over
                                 EVERY param, error-feedback residual,
                                 and telemetry leaf after 2 steps.
                                 optimization_barrier is the identity,
                                 so these are EXACTLY 0.0 — any epsilon
                                 means the overlap reordered arithmetic
      audit_recall_overlap       worst audited recall across the two
                                 overlapped arms, floor 0.95
      overlap_frac               measured (not modeled) hidden-comm
                                 fraction: a profiler capture of the
                                 overlapped fp32 arm through
                                 obs.trace_attr.attribute — the 2-way
                                 CPU mesh runs its lanes on separate
                                 threads, so real cross-lane
                                 concurrency shows up even here
      overlap_frac_positive      1.0 iff overlap_frac > 0 (the
                                 "overlap is real, not modeled-only"
                                 acceptance pin)
      crossover_n_buckets        model-side DP pin at the ResNet-50
                                 crossover (alpha=0.1 ms, P=8, committed
                                 beta): overlap pricing must choose
                                 B > 1 where serial pricing collapses
                                 to B=1, and 'auto' must pick overlap

    The bit-identity comparison is the strongest structural pin in the
    file: both arms share seed, data order, and boundaries, so every
    leaf of (params, opt_state) — residuals and counters included —
    must agree bit-for-bit."""
    import jax
    import numpy as np

    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs.trace_attr import attribute, capture
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    def _arm(codec: str, pipe: str):
        sub = os.path.join(
            out_dir, f"overlap_ab_{codec.split(':')[0]}_{pipe}")
        cfg = TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=2,
            compression="gtopk_layerwise", density=0.01, seed=42,
            max_epochs=1, log_interval=2, eval_batches=1,
            obs_interval=1, obs_audit_interval=2,
            wire_codec=codec, buckets="4", pipeline=pipe, out_dir=sub)
        frac = None
        with Trainer(cfg) as t:
            t.train(2)
            leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(
                (t.state.params, t.state.opt_state))]
            if codec == "fp32" and pipe == "overlap":
                # The measured-overlap evidence: capture the pipelined
                # dispatch and attribute it — op-event interval unions
                # across the two device lanes.
                trace_dir = os.path.join(sub, "trace")
                with capture(trace_dir):
                    t.train(2)
                frac = attribute(
                    trace_dir, mode=cfg.compression).get("overlap_frac")
        recs, _ = report.load_records(sub)
        audited = [float(r["audit_recall"]) for r in recs
                   if r.get("kind") == "obs"
                   and float(r.get("audit_recall", -1.0)) >= 0.0]
        recall = max(audited) if audited else -1.0
        return leaves, recall, frac

    deltas, recalls, frac = {}, [], None
    for codec in ("fp32", "int8:64"):
        s_leaves, _, _ = _arm(codec, "serial")
        o_leaves, recall, f = _arm(codec, "overlap")
        if f is not None:
            frac = f
        recalls.append(recall)
        deltas[codec] = max(
            float(np.max(np.abs(a.astype(np.float64)
                                - b.astype(np.float64))))
            if a.size else 0.0
            for a, b in zip(s_leaves, o_leaves))
    # Model-side crossover pin: at ICI-class alpha the overlap-priced
    # DP must open up B > 1 on real ResNet-50 leaf sizes while serial
    # pricing keeps the single merge, and 'auto' must take the
    # overlapped order (all deterministic — pure cost model).
    from benchmarks.merge_bench import _model_leaf_sizes
    from gtopkssgd_tpu.parallel import plan_buckets
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    sizes = _model_leaf_sizes("resnet50")
    kw = dict(p=8, codec="fp32", alpha_ms=0.1,
              beta_gbps=planner_inputs()["beta_gbps"])
    cross = plan_buckets(sizes, 0.001, buckets="auto",
                         pipeline="overlap", **kw)
    cross_serial = plan_buckets(sizes, 0.001, buckets="auto",
                                pipeline="serial", **kw)
    cross_auto = plan_buckets(sizes, 0.001, buckets="auto",
                              pipeline="auto", **kw)
    recall_min = min(recalls)
    return {
        "pipeline": "overlap",
        "n_buckets": 4.0,
        "bit_delta_fp32": deltas["fp32"],
        "bit_delta_int8": deltas["int8:64"],
        "bit_identity_ok": float(deltas["fp32"] == 0.0
                                 and deltas["int8:64"] == 0.0),
        "audit_recall_overlap": recall_min,
        "recall_floor_breach": round(max(0.0, 0.95 - recall_min), 6),
        "overlap_frac": (round(float(frac), 6)
                         if frac is not None else -1.0),
        "overlap_frac_positive": float(frac is not None and frac > 0),
        "crossover_n_buckets": float(cross.n_buckets),
        "crossover_b_gt1": float(cross.n_buckets > 1),
        "crossover_serial_b1": float(cross_serial.n_buckets == 1),
        "crossover_auto_overlap": float(
            cross_auto.pipeline == "overlap"),
    }


def run_calib_smoke(out_dir: str) -> dict:
    """Self-calibrating comm-model smoke (the ISSUE-13 tentpole's
    consumer): drives obs/calib.py and obs/registry.py against SYNTHETIC
    ground truth — no trainer, no timing noise, so the baseline can pin
    the estimator itself tight. A 32-sample stream generated from the
    exact alpha-beta decomposition (alpha=4 ms, beta=2 Gbps, p=4 gtopk
    tree) with every 10th sample inflated 5x (an injected straggler)
    feeds a CommCalibrator whose reference is the committed ~22 ms
    4-proc probe fit. Returns the fields the main run logs as ONE
    "calib" record:

      alpha_fit_ms / beta_fit_gbps  robust fit over the full stream;
                                 the stragglers must not drag it off
                                 the known constants (tight rtol)
      n_refits / drift_events    structural: 32 samples / window of 8
                                 -> exactly 4 refits; comm_drift_warmup
                                 =2 of them armed -> exactly 2 firings
                                 of comm_model_drift vs the stale probe
      fit_src_is_calib           the end-of-run artifact round-trips
                                 through planner_inputs: next run's
                                 planner would price with THIS run's
                                 measured fit, not the probe — the
                                 obs->planner loop, closed

    Alongside, the registry contract is exercised offline (synthetic
    record streams through report's history/regress CLI paths) and the
    exit codes are pinned as a "regress" record: 2 on an empty
    registry, 0 against itself, 1 on a 10x-worsened loss, 0 from
    history — the same contract ``report gate`` follows."""
    import json as _json

    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs import registry as _registry
    from gtopkssgd_tpu.obs.calib import CommCalibrator, message_count
    from gtopkssgd_tpu.obs.events import AnomalyMonitor
    from gtopkssgd_tpu.parallel.planner import planner_inputs

    true_alpha, true_beta = 4.0, 2.0
    p, wire_mode = 4, "gtopk"
    msgs = message_count(wire_mode, p)
    mon = AnomalyMonitor(halt_on=None)
    cal = CommCalibrator(
        wire_mode, p,
        baseline={"alpha_ms": 21.8594, "beta_gbps": 0.6,
                  "fit_source": "dcn_probe_4proc.json"},
        monitor=mon, refit_interval=8, min_samples=4)
    n_refits = 0
    for i in range(32):
        b = 200_000 + 40_000 * (i % 8)
        t = msgs * (true_alpha + (b / msgs) * 8e-6 / true_beta)
        if i % 10 == 0:
            t *= 5.0  # injected straggler: the fit must ride through
        if cal.observe(i, b, t) is not None:
            n_refits += 1
    fit = cal.final_fit()
    calib_dir = os.path.join(out_dir, "calib_probe")
    art = cal.write_artifact(calib_dir, manifest={"config_hash": "smoke"})
    inputs = planner_inputs(calib_dir)
    src_ok = (art is not None
              and inputs.get("fit_source") == os.path.basename(art))

    # Registry exit-code contract on synthetic runs (subdirs, so
    # resolve_paths on the parent never sees their metrics.jsonl).
    def _write_run(name: str, loss: float) -> str:
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        recs = [
            {"kind": "manifest", "time": 100.0, "rank": 0,
             "config_hash": "calib_smoke", "git_sha": "0" * 7},
            {"kind": "train", "time": 101.0, "rank": 0, "step": 1,
             "loss": loss},
            {"kind": "train", "time": 103.0, "rank": 0, "step": 5,
             "loss": loss},
            {"kind": "calib", "time": 103.5, "rank": 0, "step": 5,
             "alpha_fit_ms": fit["alpha_ms"],
             "beta_fit_gbps": fit["beta_gbps"],
             "n_samples": fit["n_samples"]},
        ]
        with open(os.path.join(d, "metrics.jsonl"), "w") as fh:
            for r in recs:
                fh.write(_json.dumps(r) + "\n")
        return d

    reg_dir = os.path.join(out_dir, "calib_registry")
    run_a = _write_run("calib_run_a", loss=1.5)
    rc_empty = report.run_regress(run_a, reg_dir)
    recs_a, _ = report.load_records(run_a)
    _registry.append_run(reg_dir, _registry.run_summary(recs_a))
    rc_pass = report.run_regress(run_a, reg_dir)
    rc_fail = report.run_regress(_write_run("calib_run_b", loss=15.0),
                                 reg_dir)
    rc_history = report.run_history(reg_dir)
    return {
        "alpha_fit_ms": fit["alpha_ms"],
        "beta_fit_gbps": fit["beta_gbps"],
        "alpha_true_ms": true_alpha,
        "beta_true_gbps": true_beta,
        "resid_ms": fit["resid_ms"],
        "n_samples": float(fit["n_samples"]),
        "n_refits": float(n_refits),
        "drift_events": float(mon.summary().get("comm_model_drift", 0)),
        "fit_src_is_calib": 1.0 if src_ok else 0.0,
        "planner_alpha_ms": inputs["alpha_ms"],
        "regress_rc_empty": float(rc_empty),
        "regress_rc_pass": float(rc_pass),
        "regress_rc_fail": float(rc_fail),
        "history_rc": float(rc_history),
    }


def run_mem_smoke(out_dir: str) -> dict:
    """Compile/memory-plane smoke (the ISSUE-14 tentpole's consumer):
    two instrumented sub-runs of the canonical model under ``--obs-mem``
    (both reuse the persistent compile cache), returning the fields the
    main run logs as ONE "mem" record:

      clean leg (4 steps)        mem_rc==0; exactly ONE "compile" record
                                 (one dispatch shape for the whole run —
                                 the committed-at-init sharding fix);
                                 recompile_count pinned at 0 after
                                 warmup; live-bytes stable across the
                                 sampled windows; peak_hbm_bytes in the
                                 manifest, equal to the compile record's
                                 estimate, and carried into the registry
                                 line (regress vs itself exits 0);
                                 ``report mem`` / ``report compile``
                                 round-trip the records (exit 0)
      storm leg (reshape@3)      the injected second dispatch shape
                                 retraces the step: recompile_count
                                 lands at exactly 1, recompile_storm
                                 fires with warmup 0, --obs-halt-on
                                 warn exits 44 — with BOTH shapes'
                                 compile accounting on disk before the
                                 halt (record-before-rule)"""
    import json as _json

    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs import registry as _registry

    canon = [
        "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
        "--obs-interval", "1", "--obs-mem", "--obs-mem-interval", "1",
    ]

    def _recs(d):
        with open(os.path.join(d, "metrics.jsonl")) as fh:
            return [_json.loads(line) for line in fh]

    mem_dir = os.path.join(out_dir, "memwatch")
    reg_dir = os.path.join(out_dir, "mem_registry")
    mem_rc = dist_trainer.main(canon + [
        "--num-iters", "4", "--registry", reg_dir, "--out-dir", mem_dir])
    recs = _recs(mem_dir)
    manifest = next(r for r in recs if r["kind"] == "manifest")
    shapes = [r for r in recs if r["kind"] == "compile"
              and r.get("event") is None]
    mems = [r for r in recs if r["kind"] == "mem"]
    live = [r["live_bytes"] for r in mems if r.get("live_bytes")]
    peak = manifest.get("peak_hbm_bytes", 0) or 0
    peak_matches = (len(shapes) == 1
                    and shapes[0].get("peak_hbm_bytes") == peak)
    entries, _bad = _registry.load_registry(reg_dir)
    reg_stats = (entries[-1].get("stats", {}) if entries else {})
    reg_has_fields = ("peak_hbm_bytes" in reg_stats
                      and "recompile_count" in reg_stats)

    storm_dir = os.path.join(out_dir, "memstorm")
    storm_rc = dist_trainer.main(canon + [
        "--num-iters", "5", "--inject", "reshape@3",
        "--obs-recompile-warmup", "0", "--obs-halt-on", "warn",
        "--out-dir", storm_dir])
    storm_recs = _recs(storm_dir)
    storm_recompiles = [r for r in storm_recs if r["kind"] == "compile"
                        and r.get("event") == "recompile"]
    storm_shapes = [r for r in storm_recs if r["kind"] == "compile"
                    and r.get("event") is None]
    storm_events = [r for r in storm_recs if r["kind"] == "event"
                    and r.get("rule") == "recompile_storm"]
    return {
        "mem_rc": float(mem_rc),
        "compile_records": float(len(shapes)),
        "recompile_count": float(max(
            (r.get("recompile_count", 0) for r in mems), default=0)),
        "mem_samples": float(len(mems)),
        "live_ratio": (max(live) / min(live)) if live else 0.0,
        "peak_hbm_bytes": float(peak),
        "peak_matches_compile": 1.0 if peak_matches else 0.0,
        "registry_has_mem_fields": 1.0 if reg_has_fields else 0.0,
        "mem_report_rc": float(report.run_mem(mem_dir)),
        "compile_report_rc": float(report.run_compile(mem_dir)),
        "mem_regress_rc": float(report.run_regress(mem_dir, reg_dir)),
        "storm_rc": float(storm_rc),
        "storm_recompile_count": float(
            max((r.get("recompile_count", 0) for r in storm_recompiles),
                default=0)),
        "storm_events": float(len(storm_events)),
        "storm_shapes": float(len(storm_shapes)),
    }


def run_goodput_smoke(out_dir: str) -> dict:
    """Goodput-ledger smoke (the goodput tentpole's consumer): a clean
    and a chaos leg of the canonical run under the default ledger
    (``--obs-goodput``), returning the fields the main run logs as ONE
    "goodput" record so the drift gate can pin the PR's acceptance
    numbers:

      clean leg (4 steps)        rc==0; the end-of-run record is final;
                                 CONSERVATION by measurement — the
                                 taxonomy explains the wall clock:
                                 clean_other_frac pinned <= 0.05 (atol)
                                 and clean_conservation_err ~ 0 (the
                                 |wall - sum(categories+other)| residual
                                 is a construction invariant)
      chaos leg (6 steps)        nan_grad@2 claimed by nan_loss=skip,
                                 slow_rank:0:0.2@3-4, preempt@5: each
                                 injected fault must land in its
                                 DESIGNATED badput category —
                                 chaos_wasted_hit   the skipped step's
                                                    wall in `wasted`
                                                    (n_wasted_steps>=1)
                                 chaos_wait_hit     the injected 0.2 s
                                                    sleeps in `wait`
                                 chaos_ckpt_hit     the emergency save
                                                    in `ckpt`
                                 chaos_rc           the preemption exits
                                                    45 WITH the final
                                                    goodput record on
                                                    disk first
                                                    (record-before-exit)

    The hit fields are one-sided indicators (1.0 exact); the clean-leg
    fracs are timing-dependent, so only the conservation remainder is
    pinned (loose atol), never the split itself."""
    import json as _json

    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs import goodput as _goodput

    canon = [
        "--dnn", "resnet20", "--batch-size", "4", "--nworkers", "2",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
        "--obs-interval", "1", "--obs-goodput-interval", "2",
    ]

    def _final_goodput(d):
        with open(os.path.join(d, "metrics.jsonl")) as fh:
            recs = [_json.loads(line) for line in fh]
        finals = [r for r in recs if r.get("kind") == "goodput"
                  and r.get("final")]
        return finals[-1] if finals else None

    clean_dir = os.path.join(out_dir, "goodput_clean")
    clean_rc = dist_trainer.main(canon + [
        "--num-iters", "4", "--out-dir", clean_dir])
    clean = _final_goodput(clean_dir) or {}

    chaos_dir = os.path.join(out_dir, "goodput_chaos")
    chaos_rc = dist_trainer.main(canon + [
        "--num-iters", "6",
        "--inject", "nan_grad@2,slow_rank:0:0.2@3-4,preempt@5",
        "--recover-policy", "nan_loss=skip",
        "--out-dir", chaos_dir])
    chaos = _final_goodput(chaos_dir) or {}

    def _s(rec, cat):
        return float(rec.get(f"{cat}_s", 0.0))

    return {
        "clean_rc": float(clean_rc),
        "clean_final": float(bool(clean.get("final"))),
        "clean_goodput_frac": float(clean.get("goodput_frac", -1.0)),
        "clean_other_frac": float(clean.get("other_frac", 1.0)),
        "clean_conservation_err": (
            round(_goodput.conservation_error(clean), 9) if clean
            else -1.0),
        "chaos_rc": float(chaos_rc),
        "chaos_final": float(bool(chaos.get("final"))),
        "chaos_n_wasted": float(chaos.get("n_wasted_steps", 0)),
        "chaos_wasted_hit": float(_s(chaos, "wasted") > 0.0
                                  and chaos.get("n_wasted_steps", 0) >= 1),
        # two injected 0.2 s sleeps; >= 0.15 tolerates clock slop while
        # still requiring at least one to have been accounted as wait
        "chaos_wait_hit": float(_s(chaos, "wait") >= 0.15),
        "chaos_ckpt_hit": float(_s(chaos, "ckpt") > 0.0),
        "chaos_wait_s": round(_s(chaos, "wait"), 6),
        "chaos_wasted_s": round(_s(chaos, "wasted"), 6),
        "chaos_conservation_err": (
            round(_goodput.conservation_error(chaos), 9) if chaos
            else -1.0),
    }


def run_linkmap_smoke(out_dir: str) -> dict:
    """Link-level weather-map smoke (the linkmap tentpole's consumer):
    a clean and a slow-link leg of a SYNTHETIC p=4 gtopk tree fleet —
    no trainer, no timing noise, so the baseline can pin the carve,
    the fleet merge, and the degradation rule exactly. Every rank runs
    its own LinkMap writing a real per-rank shard
    (metrics.rank{r}.jsonl), exactly the layout ``report linkmap``
    merges in production. Returns the fields the main run logs as ONE
    "linkmap" record:

      clean leg (4 windows)      every rank observes its exactly-modeled
                                 span, so after the carve every link's
                                 EWMA is identical: clean_max_dev_x
                                 (max |vs_median - 1| over the merged
                                 rows) is exactly 0, n_links is the
                                 tree's 4 distinct pairs, and
                                 ``report linkmap`` exits 0 — the
                                 no-false-positive pin
      slow leg (6 windows)       the degraded pair comes from the SAME
                                 resilience grammar production uses:
                                 parse_inject("slow_rank:2:...") names
                                 rank 2, and the slow link is the pair
                                 (2, 2^1)=(2,3) — both endpoints of a
                                 slow link measure the stall, so both
                                 ranks' spans are inflated. The carve
                                 spreads each rank's inflation over its
                                 2 rounds, the endpoint-mean merge
                                 concentrates it on dcn:2-3 (t0+d/2 vs
                                 t0+d/4 on the adjacent pairs), so the
                                 fleet-median rule must name EXACTLY
                                 the injected pair: slow_worst_src=2,
                                 slow_worst_dst=3 (atol 0). Feeding the
                                 merged map to an AnomalyMonitor at
                                 x=1.5/windows=3 with halt_on=warn must
                                 fire link_degraded on window 3 and
                                 halt — with the event record already
                                 durable in the shard (slow_fired,
                                 durable_before_halt, halt_exit_ok all
                                 exactly 1)

    Everything here is deterministic arithmetic (synthetic spans, exact
    carve, EWMA of a constant stream), so the baseline pins the ratio
    fields tight and the indicator fields exact."""
    from gtopkssgd_tpu.obs import linkmap as _linkmap
    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs.events import (AnomalyHalt, AnomalyMonitor,
                                          HALT_EXIT_CODE, Thresholds)
    from gtopkssgd_tpu.resilience.inject import parse_inject
    from gtopkssgd_tpu.utils.metrics import MetricsLogger

    p, wire_mode = 4, "gtopk"
    wire = 400_000.0
    delay_ms = 50.0

    def _modeled_span(rank: int) -> float:
        mine = _linkmap.rank_rounds(
            _linkmap.round_peers(wire_mode, p), rank)
        return sum(_linkmap.round_weights(mine, wire))

    def _fleet_ewma(maps: dict) -> dict:
        merged: dict = {}
        for lm in maps.values():
            for key, v in lm.ewma_by_link().items():
                merged.setdefault(key, []).append(v)
        return {k: sum(vs) / len(vs) for k, vs in merged.items()}

    # ---- clean leg: exactly-modeled spans, zero deviation expected.
    clean_dir = os.path.join(out_dir, "linkmap_clean")
    loggers = {r: MetricsLogger(out_dir=clean_dir, rank=r, shard=True)
               for r in range(p)}
    maps = {r: _linkmap.LinkMap(wire_mode, p, rank=r,
                                metrics=loggers[r])
            for r in range(p)}
    for step in range(1, 5):
        for rank, lm in maps.items():
            lm.observe(step, t_comm_ms=_modeled_span(rank),
                       wire_bytes=wire)
    for log in loggers.values():
        log.close()
    clean_recs, _ = report.load_records(clean_dir)
    clean_sum = _linkmap.summarize_linkmap(clean_recs)
    clean_max_dev = max(
        (abs(float(r["vs_median_x"]) - 1.0) for r in clean_sum["rows"]
         if isinstance(r.get("vs_median_x"), (int, float))),
        default=-1.0)
    clean_rc = report.run_linkmap([clean_dir])

    # ---- slow leg: the injected pair, the fleet rule, the halt.
    fault = parse_inject("slow_rank:2:0.05s@1-6")[0]
    slow_rank = int(fault.args[0])
    slow_peer = slow_rank ^ 1
    slow_dir = os.path.join(out_dir, "linkmap_slow")
    loggers = {r: MetricsLogger(out_dir=slow_dir, rank=r, shard=True)
               for r in range(p)}
    maps = {r: _linkmap.LinkMap(wire_mode, p, rank=r,
                                metrics=loggers[r])
            for r in range(p)}
    mon = AnomalyMonitor(
        thresholds=Thresholds(link_degraded_x=1.5,
                              link_degraded_windows=3),
        metrics=loggers[0], halt_on="warn")
    halted = 0.0
    try:
        for step in range(1, 7):
            for rank, lm in maps.items():
                t = _modeled_span(rank)
                if rank in (slow_rank, slow_peer):
                    t += delay_ms
                lm.observe(step, t_comm_ms=t, wire_bytes=wire)
            mon.observe_links(step, _fleet_ewma(maps))
    except AnomalyHalt:
        halted = float(HALT_EXIT_CODE == 44)
    for log in loggers.values():
        log.close()
    ev = next((e for e in mon.events if e["rule"] == "link_degraded"),
              None)
    slow_recs, _ = report.load_records(slow_dir)
    durable = any(r.get("kind") == "event"
                  and r.get("rule") == "link_degraded"
                  for r in slow_recs)
    slow_sum = _linkmap.summarize_linkmap(slow_recs)
    worst = slow_sum.get("worst") or {}
    slow_rc = report.run_linkmap([slow_dir])
    lo, hi = sorted((slow_rank, slow_peer))
    return {
        "clean_rc": float(clean_rc),
        "clean_links": float(clean_sum["n_links"]),
        "clean_max_dev_x": round(float(clean_max_dev), 6),
        "slow_fired": float(ev is not None),
        "slow_halted": halted,
        "durable_before_halt": float(durable),
        "slow_worst_src": float(worst.get("src", -1)),
        "slow_worst_dst": float(worst.get("dst", -1)),
        "slow_worst_is_injected_pair": float(
            worst.get("src") == lo and worst.get("dst") == hi),
        "slow_vs_median_x": (round(float(ev["value"]), 6)
                             if ev else -1.0),
        "slow_report_rc": float(slow_rc),
    }


def run_forecast_smoke(out_dir: str) -> dict:
    """Scale-out forecast smoke (the forecast tentpole's consumer):
    a clean and a drifted leg of a SYNTHETIC p=4 gtopk run — no
    trainer, no timing noise, so the baseline can pin the hindcast
    arithmetic, the per-target recommendation strings, and the
    forecast_drift halt contract exactly. Both legs write real
    metrics shards (the layout ``report forecast`` reads) through a
    live StepForecaster. Returns the fields the main run logs as ONE
    "forecast" record:

      clean leg (1 capture)      the critpath wall is CONSTRUCTED as
                                 compute + select + modeled comm x
                                 degrade (same predict_comm_ms the
                                 forecaster prices with), so the
                                 hindcast error is exactly 1.0 — the
                                 model-explains-its-own-run ceiling
                                 pin (clean_err_x, atol 1e-6). The
                                 durable record re-read from the shard
                                 parameterizes ``report forecast``
                                 (clean_rc 0) and carries the per-P
                                 grid (clean_n_rows) plus the exact
                                 recommendation indicators the regress
                                 plane pins as strings
      drift leg (3 captures)     the wall is 10x the model's
                                 prediction, so each capture's
                                 hindcast error (~10x) exceeds
                                 forecast_drift_x=4.0; the streak
                                 fires forecast_drift on capture 3
                                 with halt_on=warn — with the
                                 forecast AND event records already
                                 durable in the shard (drift_fired,
                                 durable_before_halt, drift_halted
                                 all exactly 1, drift_windows exactly
                                 3)

    Everything here is deterministic arithmetic (synthetic budgets,
    the fitted-model identity, an EWMA of a constant stream), so the
    baseline pins the ratio fields tight and the indicators exact."""
    from gtopkssgd_tpu.obs import forecast as _forecast
    from gtopkssgd_tpu.obs import report
    from gtopkssgd_tpu.obs.events import (AnomalyHalt, AnomalyMonitor,
                                          HALT_EXIT_CODE, Thresholds)
    from gtopkssgd_tpu.obs.ledger import predict_comm_ms, wire_mode_for
    from gtopkssgd_tpu.utils.metrics import MetricsLogger

    params = {"mode": "gtopk", "p": 4, "n": 1_000_000, "k": 10_000,
              "codec": "fp32", "schedule": "tree",
              "bucketing": "concat", "buckets": None, "ici_size": 1}
    fit = {"alpha_ms": 0.5, "beta_gbps": 8.0, "resid_ms": 0.02,
           "fit_source": "smoke"}
    compute_ms, select_ms = 10.0, 2.0
    # One degraded link among four: degrade_factor = mean/median = 1.25.
    links = [{"ewma_ms": 1.0}, {"ewma_ms": 1.0},
             {"ewma_ms": 1.0}, {"ewma_ms": 2.0}]
    degrade = _forecast.degrade_factor(links)
    wm = wire_mode_for(params["mode"], params["schedule"],
                       params["bucketing"])
    comm = predict_comm_ms(wm, params["p"], n=params["n"],
                           k=params["k"], alpha_ms=fit["alpha_ms"],
                           beta_gbps=fit["beta_gbps"],
                           codec=params["codec"])
    pred_ms = compute_ms + select_ms + comm * degrade

    def _critpath(wall_ms: float) -> dict:
        return {"wall_us": wall_ms * 1e3,
                "t_compute_us": compute_ms * 1e3,
                "t_select_us": select_ms * 1e3}

    # ---- clean leg: measured == modeled, so the hindcast is exact.
    clean_dir = os.path.join(out_dir, "forecast_clean")
    log = MetricsLogger(out_dir=clean_dir, rank=0, shard=True)
    fc = _forecast.StepForecaster(params, baseline=fit, metrics=log)
    fc.note_calib({"alpha_fit_ms": fit["alpha_ms"],
                   "beta_fit_gbps": fit["beta_gbps"],
                   "resid_ms": fit["resid_ms"]})
    fc.note_linkmap({"links": links})
    fc.note_critpath(_critpath(pred_ms))
    rec = fc.observe(step=1)
    log.close()
    clean_recs, _ = report.load_records(clean_dir)
    clean_durable = any(r.get("kind") == "forecast" for r in clean_recs)
    clean_rc = report.run_forecast([clean_dir])

    # ---- drift leg: reality 10x the model -> streak -> fire -> halt.
    drift_dir = os.path.join(out_dir, "forecast_drift")
    log = MetricsLogger(out_dir=drift_dir, rank=0, shard=True)
    mon = AnomalyMonitor(
        thresholds=Thresholds(forecast_drift_x=4.0,
                              forecast_drift_windows=3),
        metrics=log, halt_on="warn")
    fcd = _forecast.StepForecaster(params, baseline=fit, metrics=log,
                                   monitor=mon)
    fcd.note_linkmap({"links": links})
    halted = 0.0
    try:
        for step in range(1, 4):
            fcd.note_critpath(_critpath(10.0 * pred_ms))
            fcd.observe(step)
    except AnomalyHalt:
        halted = float(HALT_EXIT_CODE == 44)
    log.close()
    ev = next((e for e in mon.events if e["rule"] == "forecast_drift"),
              None)
    drift_recs, _ = report.load_records(drift_dir)
    n_forecast = sum(1 for r in drift_recs
                     if r.get("kind") == "forecast")
    durable = any(r.get("kind") == "event"
                  and r.get("rule") == "forecast_drift"
                  for r in drift_recs)
    return {
        "clean_err_x": float(rec["hindcast_err_x"]),
        "clean_rc": float(clean_rc),
        "clean_durable": float(clean_durable),
        "clean_n_rows": float(len(rec["rows"])),
        "clean_degrade_x": round(float(rec["degrade_x"]), 6),
        "clean_rec_p256_balanced": float(
            str(rec.get("rec_p256", "")).startswith("balanced")),
        "clean_rec_p1024_balanced": float(
            str(rec.get("rec_p1024", "")).startswith("balanced")),
        "clean_has_crossover": float(rec.get("crossover_p")
                                     is not None),
        "clean_band_p256_ms": round(
            float(rec["step_ms_hi_p256"] - rec["step_ms_p256"]), 6),
        "drift_fired": float(ev is not None),
        "drift_halted": halted,
        "drift_windows": float(ev["windows"]) if ev else -1.0,
        "drift_err_x": (round(float(ev["value"]), 6)
                        if ev else -1.0),
        "durable_before_halt": float(durable),
        "drift_n_forecast_records": float(n_forecast),
    }


def run_elastic_smoke(out_dir: str) -> dict:
    """Elastic-fleet smoke (the elastic tentpole's consumer): three
    resize loops of the canonical run under ``--elastic``
    (resilience/elastic.py), each closed end-to-end — drain, durable
    "resize" record, exit 46, relaunch in a FRESH out_dir (ckpt +
    elastic.json copied over, exactly the supervisor contract) at the
    new --nworkers. Returns the fields the main run logs as ONE
    "resize" record so the drift gate can pin the PR's acceptance
    numbers:

      shrink leg (2 -> 1)        resize@3:1 drains at step 3, saves,
                                 logs exactly ONE "resize" record
                                 (old_p=2, new_p=1, reason=inject,
                                 drained_step=3) and exits 46; the
                                 relaunch restores at P=1 (residual
                                 folded 2 -> 1) and completes (rc 0)
                                 with the SAME lineage_id at
                                 resize_epoch 1. Both registry lines
                                 carry the lineage, so history renders
                                 ONE lineage with 2 runs and
                                 pick_baseline joins the post-resize
                                 segment to the pre-resize entry
                                 across the config_hash change
      grow leg (1 -> 2)          resize@3:2 -> 46 -> relaunch at P=2:
                                 the comm stack re-derives at the new
                                 size for free, pinned by the
                                 post-resize "plan" record scoring at
                                 p=2 (at p=1 no plan decision exists
                                 to score)
      evict leg                  the decision function: a synthetic
                                 3-rank fleet view whose rank 0 sits
                                 far below the median goodput_frac
                                 (dominant badput: wait) with a
                                 persistent-straggler row — advise()
                                 names rank 0, eviction_decision
                                 returns new_p=2 with the straggler
                                 corroborated, and refuses at
                                 min_fleet=3 (never below the floor);
                                 the fleet arithmetic pins the exact
                                 recovered goodput fraction. The loop
                                 then closes in the trainer: a 2-way
                                 run with injected 0.2 s straggler
                                 stalls and evict_rank:0@3 resizes
                                 with reason=evict (evicted_ranks=[0])
                                 -> 46 -> relaunch at P=1 completes,
                                 and the post-resize goodput_frac
                                 exceeds the straggler-burdened
                                 pre-resize one (one-sided indicator)

    Exit codes, record counts, lineage identity, and the synthetic
    fleet arithmetic are structural (exact pins); the real-timing
    goodput comparison enters only as the one-sided indicator."""
    import json as _json
    import shutil

    from gtopkssgd_tpu import dist_trainer
    from gtopkssgd_tpu.obs import goodput as _goodput
    from gtopkssgd_tpu.obs import registry as _registry
    from gtopkssgd_tpu.resilience import eviction_decision

    canon = [
        "--dnn", "resnet20", "--batch-size", "4",
        "--compression", "gtopk_layerwise", "--density", "0.01",
        "--seed", "42", "--eval-batches", "1", "--log-interval", "1",
        "--obs-interval", "1",
    ]

    def _recs(d):
        with open(os.path.join(d, "metrics.jsonl")) as fh:
            return [_json.loads(line) for line in fh]

    def _relaunch_dir(src: str, dst: str) -> str:
        """The supervisor contract: a FRESH out_dir seeded with the
        checkpoint tree and the lineage file (reusing the old out_dir
        would corrupt its registry summary — run_summary keys on the
        FIRST manifest in the stream)."""
        os.makedirs(dst, exist_ok=True)
        shutil.copytree(os.path.join(src, "ckpt"),
                        os.path.join(dst, "ckpt"))
        shutil.copy2(os.path.join(src, "elastic.json"),
                     os.path.join(dst, "elastic.json"))
        return dst

    def _final_goodput_frac(d) -> float:
        finals = [r for r in _recs(d) if r.get("kind") == "goodput"
                  and r.get("final")]
        return float(finals[-1].get("goodput_frac", -1.0)) if finals \
            else -1.0

    # ---- shrink leg: 2 -> 1 with the registry lineage join.
    reg_dir = os.path.join(out_dir, "elastic_registry")
    shrink_a = os.path.join(out_dir, "elastic_shrink")
    shrink_rc = dist_trainer.main(canon + [
        "--nworkers", "2", "--elastic", "--inject", "resize@3:1",
        "--num-iters", "6", "--registry", reg_dir,
        "--out-dir", shrink_a])
    resizes = [r for r in _recs(shrink_a) if r.get("kind") == "resize"]
    rz = resizes[-1] if resizes else {}
    shrink_b = _relaunch_dir(shrink_a,
                             os.path.join(out_dir, "elastic_shrink_post"))
    resume_rc = dist_trainer.main(canon + [
        "--nworkers", "1", "--elastic", "--resume",
        "--num-iters", "6", "--registry", reg_dir,
        "--out-dir", shrink_b])
    with open(os.path.join(shrink_b, "elastic.json")) as fh:
        lineage_b = _json.load(fh)
    entries, _bad = _registry.load_registry(reg_dir)
    lineages = {e.get("lineage_id") for e in entries
                if e.get("lineage_id")}
    joined = (_registry.pick_baseline(entries[-1], entries[:-1])
              if len(entries) >= 2 else None)
    hist = _registry.history_rows(
        entries, config_hash=entries[0].get("config_hash")) \
        if entries else []

    # ---- grow leg: 1 -> 2, the comm stack re-derived at the new P.
    grow_a = os.path.join(out_dir, "elastic_grow")
    grow_rc = dist_trainer.main(canon + [
        "--nworkers", "1", "--elastic", "--inject", "resize@3:2",
        "--num-iters", "6", "--out-dir", grow_a])
    grow_b = _relaunch_dir(grow_a,
                           os.path.join(out_dir, "elastic_grow_post"))
    grow_resume_rc = dist_trainer.main(canon + [
        "--nworkers", "2", "--elastic", "--resume",
        "--num-iters", "6", "--out-dir", grow_b])
    grow_plans = [r for r in _recs(grow_b) if r.get("kind") == "plan"]
    grow_plan_p = float(grow_plans[-1].get("p", -1)) if grow_plans \
        else -1.0

    # ---- evict leg, decision half: synthetic 3-rank fleet view with
    # exact arithmetic (no timing noise) — rank 0 far below the median,
    # wait-dominated, persistent per the straggler plane.
    by_rank = {
        0: {"goodput_frac": 0.45, "goodput_s": 45.0, "wait_s": 55.0,
            "wall_s": 100.0},
        1: {"goodput_frac": 0.92, "goodput_s": 92.0, "wait_s": 8.0,
            "wall_s": 100.0},
        2: {"goodput_frac": 0.95, "goodput_s": 95.0, "wait_s": 5.0,
            "wall_s": 100.0},
    }
    merged = {
        "goodput_by_rank": by_rank,
        "stragglers": [{"slowest_rank": 0, "persistent": True,
                        "ewma_lag_s": 0.4}],
    }
    decision = eviction_decision(merged, p=3, min_fleet=1,
                                 margin=0.02) or {}
    refused = eviction_decision(merged, p=3, min_fleet=3, margin=0.02)
    pre_fleet = _goodput.fleet_decomposition(by_rank) or {}
    post_fleet = _goodput.fleet_decomposition(
        {r: d for r, d in by_rank.items()
         if r != decision.get("rank")}) or {}
    fleet_gain = (float(post_fleet.get("goodput_frac", 0.0))
                  - float(pre_fleet.get("goodput_frac", 0.0)))

    # ---- evict leg, trainer half: the straggler-burdened pre-resize
    # run (injected 0.2 s stalls) evicts rank 0 -> 46 -> the clean
    # post-resize run's goodput_frac must exceed it.
    evict_a = os.path.join(out_dir, "elastic_evict")
    evict_rc = dist_trainer.main(canon + [
        "--nworkers", "2", "--elastic",
        "--inject", "slow_rank:0:0.2@1-2,evict_rank:0@3",
        "--num-iters", "6", "--out-dir", evict_a])
    ev_resizes = [r for r in _recs(evict_a) if r.get("kind") == "resize"]
    ev = ev_resizes[-1] if ev_resizes else {}
    pre_frac = _final_goodput_frac(evict_a)
    evict_b = _relaunch_dir(evict_a,
                            os.path.join(out_dir, "elastic_evict_post"))
    evict_resume_rc = dist_trainer.main(canon + [
        "--nworkers", "1", "--elastic", "--resume",
        "--num-iters", "6", "--out-dir", evict_b])
    post_frac = _final_goodput_frac(evict_b)

    return {
        "shrink_rc": float(shrink_rc),
        "shrink_resize_records": float(len(resizes)),
        "shrink_old_p": float(rz.get("old_p", -1)),
        "shrink_new_p": float(rz.get("new_p", -1)),
        "shrink_reason_inject": float(rz.get("reason") == "inject"),
        "shrink_drained_step": float(rz.get("drained_step", -1)),
        "shrink_resume_rc": float(resume_rc),
        "lineage_stable": float(
            bool(rz.get("lineage_id"))
            and lineage_b.get("lineage_id") == rz.get("lineage_id")),
        "resize_epoch_resume": float(lineage_b.get("resize_epoch", -1)),
        "registry_lineages": float(len(lineages)),
        "registry_runs": float(len(entries)),
        "regress_lineage_join": float(
            joined is not None
            and joined.get("lineage_id") == entries[-1].get("lineage_id")
            and joined.get("config_hash")
            != entries[-1].get("config_hash")),
        "history_rows_joined": float(len(hist)),
        "grow_rc": float(grow_rc),
        "grow_resume_rc": float(grow_resume_rc),
        "grow_post_plan_p": grow_plan_p,
        "advise_rank": float(decision.get("rank", -1)),
        "decision_new_p": float(decision.get("new_p", -1)),
        "decision_persistent": float(
            bool(decision.get("persistent_straggler"))),
        "decision_min_fleet_refused": float(refused is None),
        "fleet_gain_frac": round(fleet_gain, 6),
        "evict_rc": float(evict_rc),
        "evict_reason_evict": float(ev.get("reason") == "evict"),
        "evict_evicted_rank": float(
            (ev.get("evicted_ranks") or [-1])[0]),
        "evict_resume_rc": float(evict_resume_rc),
        "evict_goodput_pre": round(pre_frac, 6),
        "evict_goodput_post": round(post_frac, 6),
        "evict_goodput_improved": float(post_frac > pre_frac),
    }


def run_smoke(out_dir: str, only=None) -> str:
    """Train the canonical run; returns the run dir (metrics.jsonl inside).

    ``only`` (a SMOKES name) restricts the sub-smokes to that one (plus
    its SMOKE_DEPS); the canonical main run still executes — it hosts
    the grafted records — but only the selected smoke's records enter
    the stream, matching the subset gate main() builds for ``--only``.

    After the baseline steps, two more run under the profiler
    (obs.trace_attr.capture — Python tracer off, so op events survive)
    and the paper's T_compute/T_select/T_comm split of that trace is
    logged as an "attr" record, putting the decomposition itself under
    the drift gate's frac checks. Finally the run's own records are
    fleet-merged (obs/fleet.py) and logged back as "fleet" records: on
    this single-process run the merge is a 1-rank fleet, so n_ranks is
    exactly 1 and every skew_max exactly 0 — structural invariants the
    baseline pins, putting the merge path itself under the drift gate.

    Before all that, a chaos sub-run (run_recovery_smoke) exercises the
    resilience path — injected NaN claimed by a skip policy — and its
    inject/recovery records are grafted into this run's stream, so the
    baseline also pins recovery structure (one firing, one recovery,
    final_status=completed). The twostage and codec A/B sub-runs graft
    one summary record each the same way ("twostage", "codec")."""
    from gtopkssgd_tpu.obs import fleet, report
    from gtopkssgd_tpu.obs.trace_attr import attribute, capture
    from gtopkssgd_tpu.trainer import Trainer

    # Chaos sub-run first (its own Trainer, its own subdir), then the
    # main run re-logs ONLY the resilience records so the baseline can
    # pin recovery structure without the sub-run's train/obs rows
    # polluting the main run's value statistics. The twostage A/B runs
    # the same way: its sub-runs live in subdirs and only the single
    # summary record enters this run's stream.
    rec_dir = (run_recovery_smoke(out_dir)
               if _selected("recovery", only) else None)
    twostage_rec = (run_twostage_smoke(out_dir)
                    if _selected("twostage", only) else None)
    codec_rec = (run_codec_smoke(out_dir)
                 if _selected("codec", only) else None)
    plan_rec = (run_plan_smoke(out_dir, codec_rec)
                if _selected("plan", only) else None)
    bucket_rec = (run_bucket_smoke(out_dir)
                  if _selected("bucket", only) else None)
    overlap_rec = (run_overlap_smoke(out_dir)
                   if _selected("overlap", only) else None)
    calib_rec = (run_calib_smoke(out_dir)
                 if _selected("calib", only) else None)
    mem_rec = (run_mem_smoke(out_dir)
               if _selected("mem", only) else None)
    goodput_rec = (run_goodput_smoke(out_dir)
                   if _selected("goodput", only) else None)
    linkmap_rec = (run_linkmap_smoke(out_dir)
                   if _selected("linkmap", only) else None)
    forecast_rec = (run_forecast_smoke(out_dir)
                    if _selected("forecast", only) else None)
    elastic_rec = (run_elastic_smoke(out_dir)
                   if _selected("elastic", only) else None)
    critpath_rec = critpath_real = None
    if _selected("critpath", only):
        critpath_rec, critpath_real = run_critpath_smoke(out_dir)

    cfg = smoke_config(out_dir)
    with Trainer(cfg) as t:
        t.train(SMOKE_STEPS)
        trace_dir = os.path.join(out_dir, "trace")
        try:
            with capture(trace_dir):
                t.train(2)
            rec = attribute(trace_dir, mode=cfg.compression)
        except Exception as e:  # platform without usable op traces
            t.metrics.log("attr_error", error=str(e)[:200])
        else:
            t.metrics.log("attr", flush=True, **{
                k: v for k, v in rec.items() if v is not None})
        # The metrics file is line-buffered, so everything logged above
        # is already readable mid-run; merge obs records only (train
        # records at log_interval=2 over 6 steps give 3 more rows each
        # but no extra coverage).
        merged = fleet.merge([out_dir], kinds=("obs",))
        for row in merged["rows"]:
            t.metrics.log("fleet", **fleet.row_record(row))
        # Graft the chaos sub-run's inject/recovery records into this
        # run's stream (re-stamped time/rank) so the gate's structural
        # recovery checks (exactly one firing, n_recoveries, completed)
        # read from the same metrics.jsonl as everything else.
        if rec_dir is not None:
            rec_records, _ = report.load_records(rec_dir)
            for r in rec_records:
                if r.get("kind") in ("inject", "recovery"):
                    t.metrics.log(r["kind"], **{
                        k: v for k, v in r.items()
                        if k not in ("kind", "time", "rank")})
        # Same graft for the twostage A/B evidence: the gate pins the
        # audited recall floor and the one-sided T_select regression.
        if twostage_rec is not None:
            t.metrics.log("twostage", **twostage_rec)
        # And the wire-codec A/B: int8-vs-fp32 wire-bytes ratios, the
        # one-sided >=3x DCN-reduction evidence, the audited recall
        # floor under the lossy codec, and the ledger's modeled-vs-
        # measured bytes ratio.
        if codec_rec is not None:
            t.metrics.log("codec", **codec_rec)
        # And the comm-planner A/B: balanced-vs-tree measured wire
        # ratios, the recall floor under the balanced schedule, and the
        # plan-keyed ledger's modeled-vs-measured bytes ratio. (The
        # trainer already logged this run's own "plan" decision record,
        # whose plan_is_default=1.0 the baseline pins — defaults keep
        # the historical tree wire.)
        if plan_rec is not None:
            t.metrics.log("plan", **plan_rec)
        # And the bucketing A/B: leaf-vs-auto collective counts (the
        # one-sided >=3x fewer-merges evidence), the audited recall
        # floor on the bucketed arm, and the bucket-summed ledger's
        # modeled-vs-measured bytes ratio.
        if bucket_rec is not None:
            t.metrics.log("bucket", **bucket_rec)
        # And the overlapped-pipeline A/B: exact-zero serial-vs-overlap
        # bit-identity deltas (fp32 + int8), the measured overlap_frac
        # from the pipelined arm's trace capture, the recall floor, and
        # the model-side DP crossover pin (B>1 under overlap pricing at
        # ResNet-50/alpha=0.1). Durable evidence -> flush=True.
        if overlap_rec is not None:
            t.metrics.log("overlap", flush=True, **overlap_rec)
        # And the calibration smoke: the robust fit pinned against its
        # synthetic ground truth, the exact refit/drift-firing counts,
        # the closed obs->planner artifact round-trip, and (as a
        # separate "regress" record) the registry CLI's exit-code
        # contract. Both kinds are durable -> flush=True.
        if calib_rec is not None:
            _regress_keys = ("regress_rc_empty", "regress_rc_pass",
                             "regress_rc_fail", "history_rc")
            t.metrics.log("calib", flush=True, **{
                k: v for k, v in calib_rec.items()
                if k not in _regress_keys})
            t.metrics.log("regress", flush=True, **{
                k: v for k, v in calib_rec.items() if k in _regress_keys})
        # And the compile/memory-plane smoke: one-executable discipline
        # on the clean leg (recompile_count 0, one compile record, the
        # manifest's peak-HBM matched and registry-carried) and the full
        # storm chain on the chaos leg (reshape -> retrace -> exactly
        # one recompile -> exit 44).
        if mem_rec is not None:
            t.metrics.log("mem", **mem_rec)
        # And the goodput smoke: the clean leg's conservation pins
        # (other_frac <= 0.05, construction-invariant remainder ~0) and
        # the chaos leg's fault-to-category indicators (skip -> wasted,
        # slow_rank -> wait, emergency save -> ckpt, preempt -> 45 with
        # the final record durable first). Durable -> flush=True.
        if goodput_rec is not None:
            t.metrics.log("goodput", flush=True, **goodput_rec)
        # And the linkmap smoke: the clean fleet's zero-deviation pin
        # (no false positives), the slow leg naming exactly the
        # injected pair (slow_rank inject grammar -> worst link), and
        # the link_degraded fire/halt contract with the event record
        # durable before the raise. Durable evidence -> flush=True.
        if linkmap_rec is not None:
            t.metrics.log("linkmap", flush=True, **linkmap_rec)
        # And the forecast smoke: the clean leg's exact hindcast
        # ceiling (measured == modeled -> err 1.0), the per-target
        # recommendation indicators and resid-derived band, and the
        # drifted leg's forecast_drift fire/halt contract with the
        # forecast + event records durable before the raise.
        # Durable evidence -> flush=True.
        if forecast_rec is not None:
            t.metrics.log("forecast", flush=True, **forecast_rec)
        # And the elastic smoke: three closed resize loops (shrink,
        # grow, evict) — exit-46 contract, exactly-one durable resize
        # record, lineage identity across the relaunch, the registry's
        # lineage join, the post-resize plan re-scored at the new P,
        # and the eviction decision's exact synthetic-fleet arithmetic
        # with the one-sided post-eviction goodput indicator.
        # Durable evidence -> flush=True.
        if elastic_rec is not None:
            t.metrics.log("resize", flush=True, **elastic_rec)
        # And the critical-path smoke: one REAL per-step stage-interval
        # record from the overlap arm (so the registry's wait_frac /
        # crit_stage_modal path runs on gate data) plus the summary the
        # baseline pins — the >=90%-coverage floor breach (exact), the
        # synthetic skewed arm's wait share, and the clean/skewed
        # critpath_shift firing counts with the exit-44 halt contract.
        # Durable evidence -> flush=True on both.
        if critpath_real is not None:
            t.metrics.log("critpath", flush=True, **critpath_real)
        if critpath_rec is not None:
            t.metrics.log("critpath", flush=True, **critpath_rec)
        # Static-analysis gate: run graftlint in-process over the
        # package + benchmarks against the committed repo baseline and
        # record the counts; the gate pins non_baselined at exactly 0,
        # so a new invariant violation fails the same drift gate as a
        # numeric regression.
        if _selected("lint", only):
            t.metrics.log("lint", **run_lint_smoke())
    return out_dir


def run_critpath_smoke(out_dir: str) -> tuple:
    """Distributed-critical-path smoke (the critpath tentpole's
    consumer): two tiny p=2 arms differing ONLY in --pipeline (serial
    vs overlap), each with --obs-critpath at every-step cadence so the
    trainer's own capture gate logs durable per-step stage-interval
    records, plus a deterministic synthetic skewed/clean pair for the
    fields real timing can't pin. Returns (summary_record,
    real_record): the summary the gate pins and one real per-step
    record from the overlap arm grafted into the main stream (so the
    registry's wait_frac/crit_stage_modal path runs on gate data).

      crit_frac                min over every logged record of the
                               single-rank chain walk's coverage of
                               that record's measured step wall —
                               gap-filled attribution must explain
                               the whole captured dispatch
      crit_frac_floor_breach   max(0, 0.90 - crit_frac): the PR's
                               >=90%-coverage acceptance pin, exact
      n_records                total critpath records across both
                               arms (2 steps x 2 arms)
      wait_frac_skewed         synthetic barrier-stall rank record
                               (fixture geometry): exactly 0.8
      crit_stage_skewed_wait   1.0 iff the joined 2-rank skewed step's
                               global critical stage is "wait"
      shift_events_clean       critpath_shift firings on a 6-step
                               constant-stage stream: exactly 0
      shift_events_skewed      firings on compute x3 -> wait x3 at
                               the default 3-window threshold:
                               exactly 1
      halt_exit_ok             1.0 iff halt_on="warn" raises
                               AnomalyHalt on that shift and the
                               halt exit code contract is 44
    """
    from gtopkssgd_tpu.obs import critpath, report
    from gtopkssgd_tpu.obs.events import (AnomalyHalt, AnomalyMonitor,
                                          HALT_EXIT_CODE, Thresholds)
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    fracs = {}
    n_records = 0
    real_rec = None
    for pipe in ("serial", "overlap"):
        sub = os.path.join(out_dir, f"critpath_{pipe}")
        cfg = TrainConfig(
            dnn="resnet20", batch_size=4, nworkers=2,
            compression="gtopk_layerwise", density=0.01, seed=42,
            max_epochs=1, log_interval=2, eval_batches=1,
            obs_interval=1, wire_codec="fp32", buckets="4",
            pipeline=pipe, out_dir=sub,
            obs_critpath=True, obs_calib_interval=1)
        with Trainer(cfg) as t:
            t.train(2)
        recs, _ = report.load_records(sub)
        cps = [r for r in recs if r.get("kind") == "critpath"]
        n_records += len(cps)
        arm_fracs = []
        for cp in cps:
            res = critpath.critical_path({0: cp["segments"]})
            arm_fracs.append(res["crit_frac"])
        fracs[pipe] = min(arm_fracs) if arm_fracs else 0.0
        if pipe == "overlap" and cps:
            real_rec = {k: v for k, v in cps[-1].items()
                        if k not in ("kind", "time", "rank")}
    crit_frac = min(fracs.values()) if fracs else 0.0

    # ---- deterministic synthetic pair (fixture geometry): real CPU
    # timing can't pin wait shares or shift counts, hand-built segment
    # sets can, and they run the SAME join/rule code paths.
    stalled = [{"stage": "compute", "t0_us": 0.0, "t1_us": 100.0},
               {"stage": "wait", "t0_us": 100.0, "t1_us": 900.0},
               {"stage": "comm", "t0_us": 900.0, "t1_us": 1000.0}]
    skew_rec = critpath.build_record(stalled)
    joined = critpath.critical_path({0: list(stalled), 1: list(stalled)})

    clean_mon = AnomalyMonitor()
    for step in range(1, 7):
        clean_mon.observe_critpath(step, crit_stage="compute")
    shift_clean = sum(e["rule"] == "critpath_shift"
                      for e in clean_mon.events)
    skew_mon = AnomalyMonitor(
        thresholds=Thresholds(critpath_shift_windows=3))
    for step, stage in enumerate(["compute"] * 3 + ["wait"] * 3, 1):
        skew_mon.observe_critpath(step, crit_stage=stage)
    shift_skew = sum(e["rule"] == "critpath_shift"
                     for e in skew_mon.events)
    halt_ok = 0.0
    halt_mon = AnomalyMonitor(
        thresholds=Thresholds(critpath_shift_windows=3), halt_on="warn")
    try:
        for step, stage in enumerate(["compute"] * 3 + ["wait"] * 3, 1):
            halt_mon.observe_critpath(step, crit_stage=stage)
    except AnomalyHalt:
        halt_ok = float(HALT_EXIT_CODE == 44)

    summary = {
        "n_records": float(n_records),
        "crit_frac": round(float(crit_frac), 6),
        "crit_frac_serial": round(float(fracs.get("serial", 0.0)), 6),
        "crit_frac_overlap": round(float(fracs.get("overlap", 0.0)), 6),
        "crit_frac_floor_breach": round(max(0.0, 0.90 - crit_frac), 6),
        "wait_frac_skewed": skew_rec["wait_frac"],
        "crit_stage_skewed_wait": float(joined["crit_stage"] == "wait"),
        "shift_events_clean": float(shift_clean),
        "shift_events_skewed": float(shift_skew),
        "halt_exit_ok": halt_ok,
    }
    return summary, real_rec


def run_lint_smoke() -> dict:
    """Graftlint finding counts for the shipped tree, as a gate record.

    Uses the analysis engine directly (no subprocess, no jax) with the
    repo-root baseline, scanning the same paths CI lints:
    gtopkssgd_tpu/ and benchmarks/.
    """
    from gtopkssgd_tpu.analysis import ALL_RULES, load_baseline, run

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = run(
        [os.path.join(repo, "gtopkssgd_tpu"),
         os.path.join(repo, "benchmarks")],
        rules=ALL_RULES,
        baseline=load_baseline(
            os.path.join(repo, "graftlint_baseline.json")),
        root=repo)
    return {
        "files_scanned": result.files_scanned,
        "non_baselined": len(result.findings),
        "baselined": len(result.baselined),
        "suppressed": len(result.suppressed),
        "stale_baseline": len(result.stale_baseline),
    }


def _write_subset_baseline(out_dir: str, name: str) -> str:
    """Extract the committed baseline checks the named sub-smoke owns
    (by kind; layer checks never belong to a sub-smoke) into a derived
    subset file inside the run dir. Manifest pins are dropped — the
    subset run's manifest is the main run's, and those pins belong to
    the full gate."""
    with open(BASELINE) as fh:
        base = json.load(fh)
    kinds = set(SMOKES[name])
    checks = [c for c in base.get("checks", [])
              if c.get("layer") is None and c.get("kind") in kinds]
    if not checks:
        raise SystemExit(
            f"--only {name}: the committed baseline has no checks with "
            f"kind in {sorted(kinds)} — add the check specs to "
            f"{os.path.basename(BASELINE)} first, then re-stamp their "
            f"expectations with --only {name} --write-baseline")
    sub = {
        "description": (f"{name} subset of {os.path.basename(BASELINE)} "
                        "(derived per run; not committed)"),
        "checks": checks,
    }
    path = os.path.join(out_dir, f"gate_subset_{name}.json")
    with open(path, "w") as fh:
        json.dump(sub, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def _merge_subset_baseline(restamped_path: str) -> None:
    """Fold a re-stamped subset back into the committed baseline:
    each subset check replaces the committed check with the same
    identity (report._check_id), everything else — other checks, their
    order, the manifest pins — is untouched. This is what makes
    ``--only NAME --write-baseline`` safe: it can only move the
    expectations the named sub-smoke owns."""
    from gtopkssgd_tpu.obs.report import _check_id

    with open(restamped_path) as fh:
        restamped = {_check_id(c): c for c in json.load(fh)["checks"]}
    with open(BASELINE) as fh:
        base = json.load(fh)
    merged = 0
    for i, check in enumerate(base.get("checks", [])):
        new = restamped.pop(_check_id(check), None)
        if new is not None:
            base["checks"][i] = new
            merged += 1
    # A subset check absent from the committed list can only mean the
    # committed file changed under us; append rather than drop it.
    base["checks"].extend(restamped.values())
    with open(BASELINE, "w") as fh:
        json.dump(base, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"merged {merged + len(restamped)} re-stamped check(s) "
          f"into {BASELINE}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "obs_gate_smoke",
        description="Run the canonical obs-gate smoke and gate (or "
                    "regenerate) the committed baseline.")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-stamp the committed baseline's expectations "
                         "from this run instead of failing on drift")
    ap.add_argument("--only", choices=sorted(SMOKES), default=None,
                    help="run ONE sub-smoke (plus its dependencies) and "
                         "gate just the baseline checks its kinds own; "
                         "with --write-baseline, merge only those "
                         "re-stamped checks back into the committed "
                         "baseline")
    ap.add_argument("--out-dir", default=None,
                    help="keep the run here (default: a temp dir)")
    args = ap.parse_args(argv)

    # Same in-process CPU-mesh workaround as tests/conftest.py: this
    # host's sitecustomize overrides JAX_PLATFORMS, so an env var alone
    # would silently dial the accelerator tunnel.
    from gtopkssgd_tpu.utils import enable_compilation_cache, force_cpu_mesh

    force_cpu_mesh(smoke_config("ignored").nworkers)
    enable_compilation_cache()

    out = args.out_dir or tempfile.mkdtemp(prefix="obs_gate_smoke_")
    os.makedirs(out, exist_ok=True)

    from gtopkssgd_tpu.obs import report

    if args.only:
        subset = _write_subset_baseline(out, args.only)
        run_smoke(out, only=args.only)
        write = subset + ".new" if args.write_baseline else None
        rc = report.run_gate(out, subset, write=write)
        if write and os.path.exists(write):
            _merge_subset_baseline(write)
        return rc

    run_smoke(out)
    write = BASELINE if args.write_baseline else None
    return report.run_gate(out, BASELINE, write=write)


if __name__ == "__main__":
    raise SystemExit(main())
