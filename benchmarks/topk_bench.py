"""Top-k strategy sweep on real hardware — the evidence behind `auto`.

The reference leans on `torch.topk`'s CUDA kernel (SURVEY.md §2 native
table: the #1 custom-kernel obligation). The TPU rebuild has five
strategies (ops/topk.py, ops/pallas_topk.py); this benchmark measures all
of them at the reference's real problem sizes:

    N = 2.7e5   (ResNet-20 CIFAR scale)
    N = 2.5e7   (ResNet-50 ImageNet scale)
    N = 6.1e7   (AlexNet/VGG-16 scale)

with k = ceil(rho * N) at rho in {0.001, 0.01}, and writes a JSON artifact
(benchmarks/results/topk_bench_<device>.json) so the choice of the
production method is reproducible, not folklore. Timing uses the same
discipline as the main benchmark: back-to-back dispatch, one D2H fence
(true_sync — block_until_ready lies on the tunneled TPU), fixed round trip
subtracted, window >> round trip.

Run:  python -m benchmarks.topk_bench [--out PATH] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import jax.numpy as jnp

from gtopkssgd_tpu.ops.topk import k_for_density, select_topk
from gtopkssgd_tpu.utils import (
    sync_round_trip_seconds,
    timed_window,
    true_sync,
)

SIZES = {
    "resnet20-270k": 272_474,
    "resnet50-25.6M": 25_557_032,
    "vgg16-61M": 61_090_496,
}
DENSITIES = (0.001, 0.01)
METHODS = ("exact", "blockwise", "threshold", "approx", "pallas")


def time_method(method: str, n: int, k: int, min_seconds: float = 1.0):
    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)

    if method == "pallas":
        from gtopkssgd_tpu.ops.pallas_topk import pallas_topk_abs

        interpret = jax.default_backend() != "tpu"
        fn = jax.jit(lambda v: pallas_topk_abs(v, k, interpret=interpret))
    else:
        fn = jax.jit(lambda v: select_topk(v, k, method=method))

    out = fn(x)
    rtt = sync_round_trip_seconds(out)

    def chunk(c):
        o = out
        for _ in range(c):
            o = fn(x)
        true_sync(o)

    return timed_window(chunk, rtt, min_seconds, 4)


def main():
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="one size, one density, short windows")
    ap.add_argument("--min-seconds", type=float, default=1.0)
    args = ap.parse_args()

    device = jax.devices()[0].device_kind.replace(" ", "_")
    sizes = dict(list(SIZES.items())[:1]) if args.quick else SIZES
    densities = DENSITIES[:1] if args.quick else DENSITIES
    min_s = 0.3 if args.quick else args.min_seconds

    rows = []
    for label, n in sizes.items():
        for rho in densities:
            k = k_for_density(n, rho)
            for method in METHODS:
                try:
                    sec, steps = time_method(method, n, k, min_s)
                    err = None
                except Exception as e:  # record, don't abort the sweep
                    sec, steps, err = None, 0, f"{type(e).__name__}: {e}"
                rows.append({
                    "size": label, "n": n, "density": rho, "k": k,
                    "method": method, "ms": (
                        round(sec * 1e3, 4) if sec is not None else None),
                    "steps_timed": steps, "error": err,
                })
                ms = f"{sec * 1e3:9.3f} ms" if sec is not None else "FAILED"
                print(f"{label:16s} rho={rho:<6g} {method:10s} {ms}",
                      flush=True)

    result = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "pallas_interpret": jax.default_backend() != "tpu",
        "rows": rows,
    }
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results", f"topk_bench_{device}.json",
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
