"""Top-k strategy sweep on real hardware — the evidence behind `auto`.

The reference leans on `torch.topk`'s CUDA kernel (SURVEY.md §2 native
table: the #1 custom-kernel obligation). The TPU rebuild has six
strategies (ops/topk.py, ops/pallas_topk.py); this benchmark measures all
of them at the reference's real problem sizes:

    N = 2.7e5   (ResNet-20 CIFAR scale)
    N = 2.5e7   (ResNet-50 ImageNet scale)
    N = 6.1e7   (AlexNet/VGG-16 scale)

with k = ceil(rho * N) at rho in {0.001, 0.01}, and writes a JSON artifact
(benchmarks/results/topk_bench_<device>.json) so the choice of the
production method is reproducible, not folklore. Each selection row also
carries `recall_vs_exact` (exact-vs-method index recall on the same random
vector) so approximate methods (approx, twostage, simrecall) are compared
on both axes at once. `tau_*` rows time the tau-only API (ops.select_tau,
what compress_by_threshold consumes at p=1) — no (vals, idx) set, no
gather — with recall measured on the threshold MASK |x| >= tau (>= the
index-set recall by the superset property).

Timing uses the same discipline as the main benchmark: back-to-back
dispatch, one D2H fence (true_sync — block_until_ready lies on the
tunneled TPU), fixed round trip subtracted, window >> round trip.

`--cpu-fallback` is the dead-tunnel mode bench.py invokes when the
accelerator backend cannot initialize: it forces the in-process CPU mesh
BEFORE any backend touch (this host's sitecustomize overrides
JAX_PLATFORMS, so the config API is the only reliable override), runs the
quick sweep with the Pallas kernels in interpret mode, tags the artifact
`"backend": "cpu_fallback"`, and appends the one-pass counting evidence
(largest compiled op is 1xN for the fused/bucketize counting pass vs 8xN
for the vmapped 8-reduction it replaced) plus wire-codec microbench rows
(`codec_rows`: bytes/elem, roundtrip error, recall-after-quantization vs
exact for fp32/int8/fp8 — parallel/codec.py) so BENCH rounds carry
fresh, comparable selection data even with no chip attached.
Interpret-mode ms are NOT device numbers — recall columns, codec byte
ratios and op-size assertions are the meaningful fields there.

Run:  python -m benchmarks.topk_bench [--out PATH] [--quick] [--cpu-fallback]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES = {
    "resnet20-270k": 272_474,
    "resnet50-25.6M": 25_557_032,
    "vgg16-61M": 61_090_496,
}
DENSITIES = (0.001, 0.01)
METHODS = ("exact", "blockwise", "threshold", "approx", "pallas",
           "twostage")
# The tau-only consumers (compress_by_threshold at p=1) care about these.
TAU_METHODS = ("exact", "threshold", "twostage")


def _selector(method: str, k: int, interpret: bool):
    import jax

    from gtopkssgd_tpu.ops.pallas_topk import pallas_topk_abs
    from gtopkssgd_tpu.ops.topk import (
        select_tau, select_topk, twostage_topk_abs,
    )

    if method == "pallas":
        return jax.jit(lambda v: pallas_topk_abs(v, k, interpret=interpret))
    if method == "twostage" and interpret:
        # Exercise the fused kernel (not the XLA reference) even off-TPU.
        return jax.jit(lambda v: twostage_topk_abs(
            v, k, use_pallas=True, interpret=True))
    if method.startswith("tau_"):
        return jax.jit(lambda v: select_tau(v, k, method[4:]))
    return jax.jit(lambda v: select_topk(v, k, method=method))


def time_method(method: str, n: int, k: int, min_seconds: float = 1.0,
                interpret: bool = False):
    import jax
    import jax.numpy as jnp

    from gtopkssgd_tpu.utils import (
        sync_round_trip_seconds,
        timed_window,
        true_sync,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    fn = _selector(method, k, interpret)
    out = fn(x)
    rtt = sync_round_trip_seconds(out)

    def chunk(c):
        o = out
        for _ in range(c):
            o = fn(x)
        true_sync(o)

    return timed_window(chunk, rtt, min_seconds, 4)


def recall_vs_exact(method: str, n: int, k: int, interpret: bool) -> float:
    """Index recall (tau rows: mask recall) of `method` against exact
    top-k on the same vector the timing loop used."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from gtopkssgd_tpu.ops.topk import topk_abs

    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    _, exact_idx = topk_abs(x, k)
    exact_idx = np.asarray(exact_idx)
    out = _selector(method, k, interpret)(x)
    if method.startswith("tau_"):
        tau = float(out)
        hit = np.abs(np.asarray(x)[exact_idx]) >= tau
        return float(hit.mean())
    _, idx = out
    return float(
        len(set(np.asarray(idx).tolist()) & set(exact_idx.tolist())) / k)


def one_pass_evidence(n: int) -> dict:
    """Committed proof that the counting pass reads x ONCE.

    Compares the largest operand/result element count in the compiled
    HLO of the production count_fn (ops.topk.bucketize_counts — the XLA
    twin of the fused Pallas counting kernel) against the vmapped
    8-reduction it replaced: the old formulation materializes/loops an
    8xN compare, the single-pass one never exceeds 1xN. Returns the op
    sizes plus the boolean the gate asserts."""
    import jax
    import jax.numpy as jnp

    from gtopkssgd_tpu.ops.topk import bucketize_counts

    x = jnp.ones((n,), jnp.float32)
    thr = jnp.linspace(0.1, 0.9, 8)

    def vmap8(mag, t):
        return jax.vmap(lambda tt: jnp.sum((mag >= tt).astype(jnp.int32)))(t)

    def max_elems(fn):
        txt = jax.jit(fn).lower(x, thr).compile().as_text()
        best = 0
        for m in re.finditer(r"\b(?:f32|s32|s64|pred|u32|u8|s8)\[([\d,]+)\]",
                             txt):
            elems = 1
            for d in m.group(1).split(","):
                if d:
                    elems *= int(d)
            best = max(best, elems)
        return best

    single = max_elems(bucketize_counts)
    vmapped = max_elems(vmap8)
    return {
        "n": n,
        "bucketize_max_op_elems": single,
        "vmap8_max_op_elems": vmapped,
        "bucketize_passes_over_x": round(single / n, 2),
        "vmap8_passes_over_x": round(vmapped / n, 2),
        "single_pass": bool(single <= 2 * n < vmapped),
    }


def codec_rows(n: int, min_seconds: float = 0.3) -> list:
    """Wire-codec encode/decode microbench: bytes/elem on the wire,
    encode->decode roundtrip value error, and selection recall AFTER
    quantization (top-2k candidates requantized, top-k reselected from
    the dequantized magnitudes, recalled against the exact top-k — the
    merge-then-reselect operation every tree round performs on decoded
    values). fp32 rows pin the identity: 8 bytes/elem, zero error,
    recall 1."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from gtopkssgd_tpu.ops.topk import k_for_density, topk_abs
    from gtopkssgd_tpu.parallel import get_codec, roundtrip_aligned
    from gtopkssgd_tpu.utils import (
        sync_round_trip_seconds,
        timed_window,
        true_sync,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    rows = []
    for rho in DENSITIES:
        k = k_for_density(n, rho)
        ev, ei = topk_abs(x, k)
        exact_idx = set(np.asarray(ei).tolist())
        cv, ci = topk_abs(x, 2 * k)
        for name in ("fp32", "int8", "fp8"):
            c = get_codec(name)
            fn = jax.jit(lambda v, i: c.decode(
                c.encode(v, i, n=n), k=k, n=n))
            out = fn(ev, ei)
            rtt = sync_round_trip_seconds(out)

            def chunk(reps):
                o = out
                for _ in range(reps):
                    o = fn(ev, ei)
                true_sync(o)

            sec, steps = timed_window(chunk, rtt, min_seconds, 4)
            vq = np.asarray(roundtrip_aligned(c, ev, ei, n=n))
            evn = np.asarray(ev)
            rel_err = float(np.linalg.norm(vq - evn)
                            / max(np.linalg.norm(evn), 1e-12))
            # recall after quantization: reselect k of 2k candidates
            # from dequantized magnitudes
            cq = np.asarray(roundtrip_aligned(c, cv, ci, n=n))
            keep = np.argsort(-np.abs(cq), kind="stable")[:k]
            requant_idx = set(np.asarray(ci)[keep].tolist())
            recall = len(requant_idx & exact_idx) / k
            rows.append({
                "n": n, "density": rho, "k": k, "codec": c.name,
                "bytes_per_elem": round(c.wire_set_bytes(k, n) / k, 3),
                "wire_ratio_vs_fp32": round(
                    c.wire_set_bytes(k, n) / (8 * k), 4),
                "roundtrip_rel_err": round(rel_err, 6),
                "recall_after_quantization": round(recall, 4),
                "roundtrip_ms": round(sec * 1e3, 4),
                "steps_timed": steps,
            })
            print(f"codec {c.name:8s} rho={rho:<6g} "
                  f"{rows[-1]['bytes_per_elem']:6.2f} B/elem "
                  f"err={rel_err:.5f} recall={recall:.4f}", flush=True)
    return rows


def run_sweep(quick: bool, min_seconds: float, interpret: bool,
              with_recall: bool = True):
    from gtopkssgd_tpu.ops.topk import k_for_density

    sizes = dict(list(SIZES.items())[:1]) if quick else SIZES
    densities = DENSITIES[:1] if quick else DENSITIES
    rows = []
    for label, n in sizes.items():
        for rho in densities:
            k = k_for_density(n, rho)
            for method in METHODS + tuple(
                    f"tau_{m}" for m in TAU_METHODS):
                try:
                    sec, steps = time_method(
                        method, n, k, min_seconds, interpret)
                    rec = (recall_vs_exact(method, n, k, interpret)
                           if with_recall else None)
                    err = None
                except Exception as e:  # record, don't abort the sweep
                    sec, steps, rec = None, 0, None
                    err = f"{type(e).__name__}: {e}"
                rows.append({
                    "size": label, "n": n, "density": rho, "k": k,
                    "method": method, "ms": (
                        round(sec * 1e3, 4) if sec is not None else None),
                    "recall_vs_exact": (
                        round(rec, 4) if rec is not None else None),
                    "steps_timed": steps, "error": err,
                })
                ms = f"{sec * 1e3:9.3f} ms" if sec is not None else "FAILED"
                rc = f" recall={rec:.4f}" if rec is not None else ""
                print(f"{label:16s} rho={rho:<6g} {method:13s} {ms}{rc}",
                      flush=True)
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="one size, one density, short windows")
    ap.add_argument("--cpu-fallback", action="store_true",
                    help="dead-tunnel mode: force the CPU mesh before "
                         "backend init, quick sweep, interpret-mode "
                         "kernels, provenance-tagged artifact")
    ap.add_argument("--min-seconds", type=float, default=1.0)
    args = ap.parse_args(argv)

    if args.cpu_fallback:
        # Must run before ANY jax backend touch: sitecustomize overrides
        # JAX_PLATFORMS on this host, so only the config API sticks.
        from gtopkssgd_tpu.utils import force_cpu_mesh

        force_cpu_mesh(1)
        args.quick = True

    import jax

    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    device = jax.devices()[0].device_kind.replace(" ", "_")
    interpret = jax.default_backend() != "tpu"
    min_s = 0.3 if (args.quick or args.cpu_fallback) else args.min_seconds

    rows = run_sweep(args.quick, min_s, interpret)

    result = {
        "device_kind": jax.devices()[0].device_kind,
        "backend": ("cpu_fallback" if args.cpu_fallback
                    else jax.default_backend()),
        "pallas_interpret": interpret,
        "rows": rows,
    }
    if args.cpu_fallback:
        result["one_pass_evidence"] = one_pass_evidence(
            list(SIZES.values())[0])
        # Wire-codec evidence rides the same artifact: bytes/elem,
        # roundtrip error and recall-after-quantization are
        # backend-independent (deterministic packing), so the dead-tunnel
        # artifact still carries fresh codec numbers.
        result["codec_rows"] = codec_rows(list(SIZES.values())[0])

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results",
        f"topk_bench_{'cpu_fallback' if args.cpu_fallback else device}.json",
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"wrote {out}")
    return out


if __name__ == "__main__":
    main()
