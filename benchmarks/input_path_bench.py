"""Host input pipeline vs chip consumption rate (VERDICT round-2 missing #3).

The reference trained ImageNet through torchvision's multi-worker
DataLoader on local disk (SURVEY.md C8 — "the reference's input path was
its luxury"); round 2 verified this repo's loaders against real-format
fixtures but never measured whether the host can FEED the chip. This
benchmark closes that: it generates a synthetic ImageFolder of real JPEGs
(PIL-encoded, ImageNet-like 500x375), then measures the production decode
+ augment + prefetch path end to end:

  1. bare decode+augment rate of ImageNetDataset.epoch (images/s),
  2. the same stream through utils.Prefetcher with a simulated consumer
     step (the Trainer's actual IO overlap mechanism),
  3. the synthetic-fallback generator rate (what bench.py/convergence
     runs actually use),

and compares against the chip's demand (ResNet-50 v5e bs=128: measured
~18.9 ms/step -> ~6.8k img/s/chip; bs=256 at 0.243 MFU -> ~2k img/s).

This host has ONE CPU core, so the absolute number is the per-core rate;
a real TPU VM host (e.g. v5e: 112 vCPU per 4 chips) parallelizes decode
across workers, so the artifact reports both the measured per-core rate
and the cores needed to match the chip — the honest "fix or document"
outcome for SURVEY §7 hard-part #5.

Usage:
  python benchmarks/input_path_bench.py [--images 2000] [--batch 128]
Writes benchmarks/results/input_path_<host>.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")

# Chip demand anchors from committed on-chip measurements
# (benchmarks/results/bench_r2_TPU_v5_lite.json): ResNet-50 bf16.
CHIP_DEMAND = {
    "resnet50_v5e_bs128": round(128 / 18.9e-3),   # ~6772 img/s
    "resnet50_v5e_bs256": round(256 / 124.5e-3),  # ~2056 img/s (dense bs256)
}


def generate_imagefolder(root: str, n_images: int, n_classes: int,
                         seed: int) -> float:
    """Write n_images JPEGs in ImageFolder layout; returns encode rate."""
    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(seed)
    t0 = time.perf_counter()
    for i in range(n_images):
        cls = i % n_classes
        # ~10% into val/ so the center-crop eval path is measurable too
        split = "val" if i % 10 == 9 else "train"
        cdir = os.path.join(root, split, f"class{cls:04d}")
        os.makedirs(cdir, exist_ok=True)
        # ImageNet-like dimensions and busy content (noise compresses
        # badly -> realistic decode cost, ~25-60 KB each at q=85)
        arr = rng.integers(0, 255, (375, 500, 3), dtype=np.uint8)
        Image.fromarray(arr).save(
            os.path.join(cdir, f"img{i:06d}.jpg"), quality=85)
    return n_images / (time.perf_counter() - t0)


def measure_decode_rate(root: str, batch: int, seconds: float,
                        train: bool, decode_workers: int = 0) -> dict:
    from gtopkssgd_tpu.data.imagenet import ImageNetDataset

    ds = ImageNetDataset(split="train" if train else "val",
                         batch_size=batch, data_dir=root, seed=0,
                         decode_workers=decode_workers)
    assert not ds.synthetic, "generator did not produce a readable folder"
    try:
        it = iter(ds)
        if decode_workers:
            next(it)  # spawn+import cost paid outside the timed window
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            b = next(it)
            n += len(b["label"])
        dt = time.perf_counter() - t0
    finally:
        ds.close()
    return {"images_per_sec": round(n / dt, 1), "images": n,
            "seconds": round(dt, 2), "decode_workers": decode_workers}


def measure_prefetched_rate(root: str, batch: int, seconds: float,
                            step_ms: float) -> dict:
    """The Trainer's real overlap: a Prefetcher worker assembles batches
    while the consumer 'computes' (sleeps step_ms, standing in for the
    chip). Reported rate is what the consumer actually sustains."""
    from gtopkssgd_tpu.data.imagenet import ImageNetDataset
    from gtopkssgd_tpu.utils import Prefetcher

    ds = ImageNetDataset(split="train", batch_size=batch, data_dir=root,
                         seed=0)
    it = iter(ds)
    pf = Prefetcher(lambda: next(it), depth=2)
    try:
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            b = next(pf)
            time.sleep(step_ms / 1e3)
            n += len(b["label"])
        dt = time.perf_counter() - t0
    finally:
        pf.close()
    return {"images_per_sec": round(n / dt, 1), "images": n,
            "seconds": round(dt, 2), "simulated_step_ms": step_ms}


def generate_cifar_pickles(root: str, seed: int) -> None:
    """Full-size real-format CIFAR-10: 5 train pickles x 10k + test_batch,
    the exact cifar-10-batches-py layout _load_real parses."""
    import pickle

    import numpy as np

    rng = np.random.default_rng(seed)
    out = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(out, exist_ok=True)
    for name, n in [(f"data_batch_{i}", 10_000) for i in range(1, 6)] + [
            ("test_batch", 10_000)]:
        d = {b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
             b"labels": rng.integers(0, 10, n).tolist()}
        with open(os.path.join(out, name), "wb") as fh:
            pickle.dump(d, fh)


def measure_cifar_epoch(root: str, batch: int) -> dict:
    """ONE FULL EPOCH (50k images) through the real-pickle CIFAR path with
    production augmentation — the 'beyond fixture scale' evidence for C8:
    real pickle parse, real pad/crop/flip (C++ when built), full pass."""
    from gtopkssgd_tpu.data.cifar import CIFAR10Dataset

    ds = CIFAR10Dataset(split="train", batch_size=batch, data_dir=root,
                        seed=0)
    assert not ds.synthetic
    t0 = time.perf_counter()
    n = sum(len(b["label"]) for b in ds.epoch(0))
    dt = time.perf_counter() - t0
    from gtopkssgd_tpu import native

    return {"images": n, "seconds": round(dt, 2),
            "images_per_sec": round(n / dt, 1),
            "native_augment": native.available()}


def measure_synth_rate(batch: int, seconds: float) -> dict:
    from gtopkssgd_tpu.data.imagenet import ImageNetDataset

    ds = ImageNetDataset(split="train", batch_size=batch, data_dir=None,
                         seed=0)
    assert ds.synthetic
    n, t0 = 0, time.perf_counter()
    it = iter(ds)
    while time.perf_counter() - t0 < seconds:
        b = next(it)
        n += len(b["label"])
    dt = time.perf_counter() - t0
    return {"images_per_sec": round(n / dt, 1), "images": n}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=2000)
    ap.add_argument("--classes", type=int, default=20)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--decode-workers", type=int, default=2,
                    help="pool size for the pooled-rate arm (on a 1-core "
                         "host expect parity at best; the arm exists to "
                         "measure pool overhead and to scale on real "
                         "hosts)")
    ap.add_argument("--keep-dir", default="",
                    help="reuse/keep the generated folder here")
    args = ap.parse_args()

    root = args.keep_dir or tempfile.mkdtemp(prefix="synth_imagenet_")
    made = not os.path.isdir(os.path.join(root, "train"))
    try:
        if made:
            print(f"[input_path] generating {args.images} JPEGs in {root}",
                  flush=True)
            enc_rate = generate_imagefolder(root, args.images, args.classes,
                                            seed=0)
        else:
            enc_rate = None
        decode_train = measure_decode_rate(root, args.batch, args.seconds,
                                           train=True)
        decode_eval = measure_decode_rate(root, args.batch, args.seconds,
                                          train=False)
        decode_pooled = measure_decode_rate(
            root, args.batch, args.seconds, train=True,
            decode_workers=args.decode_workers)
        prefetched = measure_prefetched_rate(root, args.batch, args.seconds,
                                             step_ms=18.9)
        synth = measure_synth_rate(args.batch, min(args.seconds, 10.0))
        print("[input_path] generating full-size CIFAR pickles", flush=True)
        generate_cifar_pickles(root, seed=0)
        cifar_epoch = measure_cifar_epoch(root, 32)
    finally:
        if not args.keep_dir:
            shutil.rmtree(root, ignore_errors=True)

    ncores = os.cpu_count() or 1
    per_core = decode_train["images_per_sec"] / ncores
    report = {
        "what": ("real-JPEG ImageFolder decode+augment+prefetch rate vs "
                 "chip demand; see module docstring for the 1-core "
                 "scaling caveat"),
        "host_cores": ncores,
        "n_images": args.images,
        "batch": args.batch,
        "jpeg_encode_rate_img_s": (round(enc_rate, 1) if enc_rate else None),
        "decode_augment_train": decode_train,
        "decode_centercrop_eval": decode_eval,
        "decode_augment_train_pooled": decode_pooled,
        "prefetched_with_18.9ms_consumer": prefetched,
        "synthetic_generator": synth,
        "cifar_real_pickles_full_epoch": cifar_epoch,
        "chip_demand_img_s": CHIP_DEMAND,
        "cores_needed_for_bs128_chip": math.ceil(
            CHIP_DEMAND["resnet50_v5e_bs128"] / max(per_core, 1e-9)),
        "cores_needed_for_bs256_chip": math.ceil(
            CHIP_DEMAND["resnet50_v5e_bs256"] / max(per_core, 1e-9)),
        "conclusion": None,  # filled below
    }
    deficit128 = (decode_train["images_per_sec"]
                  < CHIP_DEMAND["resnet50_v5e_bs128"])
    report["conclusion"] = (
        f"measured {decode_train['images_per_sec']} img/s/core single-core "
        f"PIL decode+augment ({'BELOW' if deficit128 else 'above'} the "
        f"~{CHIP_DEMAND['resnet50_v5e_bs128']} img/s one v5e chip demands "
        f"at bs=128); a real TPU host amortizes this across "
        f"{report['cores_needed_for_bs128_chip']} cores' worth of decode "
        f"workers (v5e hosts ship 112 vCPU per 4 chips = 28/chip), and "
        f"the Prefetcher overlap already hides decode behind the step "
        f"whenever rate*cores >= demand"
    )
    os.makedirs(RESULTS, exist_ok=True)
    out = os.path.join(RESULTS, "input_path_1core_host.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({k: report[k] for k in
                      ("decode_augment_train", "prefetched_with_18.9ms_consumer",
                       "cores_needed_for_bs128_chip", "conclusion")}))


if __name__ == "__main__":
    main()
