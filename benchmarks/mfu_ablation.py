"""Dense-MFU ablation ladder (round-4 verdict weak #2 / next-round #3).

The round-3 bench artifact put DENSE ResNet-50 at MFU 0.23-0.26 on the
v5e — the sparse-vs-dense ratio compares two slow configurations, and
the profiler attributes no op time on this platform
(mfu_investigation_r3.md), so decomposition has to come from ablation:
time a LADDER of configurations, each isolating one suspect, and read
the gap structure off the deltas.

Rungs (all ResNet-50, synthetic ImageNet shapes, bf16 compute unless the
rung says otherwise):

  fwd          — forward pass only (train=True BN statistics included):
                 the MXU-resident floor of the workload.
  fwd_bwd      — + backward: adds the transposed convs; the fwd->fwd_bwd
                 MFU drop isolates backward-pass inefficiency.
  full         — + SGD momentum update: the full dense production step
                 (bench.py's dense arm); fwd_bwd->full isolates the
                 optimizer/epilogue cost.
  bf16_params  — full step with the PARAMS also cast to bfloat16
                 ("bf16-everywhere"): halves weight HBM reads; isolates
                 the cost of f32 master weights on the step.
  bf16_input   — full step with the input batch staged as bf16 (halves
                 activation bytes into the stem conv).
  s2d          — full step with the space-to-depth stem (4x4x12 conv on
                 2x2 pixel blocks): isolates the 7x7/2 stem's padding
                 waste on the MXU.
  batch ladder — full step at bs 128/256/512: fixed-cost amortization +
                 better MXU tiling at larger batch.

Each rung prints one JSON line; the assembled artifact goes to
benchmarks/results/mfu_ablation_<device>.json. XLA-flag variants run as
child processes (flags bind at backend init), driven by --xla-variant.

Usage:
  python benchmarks/mfu_ablation.py                 # full ladder + artifact
  python benchmarks/mfu_ablation.py --rungs fwd,full --batch-sizes 128
  python benchmarks/mfu_ablation.py --rung full --batch-size 256
                                                    # child mode (one line)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gtopkssgd_tpu.exit_codes import EXIT_BENCH_TUNNEL_DEAD  # noqa: E402

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")

# XLA flag variants worth one measurement each (child processes; a flag
# that regresses or no-ops is a result too). Kept short deliberately:
# each costs a fresh backend init + compile in the tunnel window.
XLA_VARIANTS = {
    "latency_hiding_sched": "--xla_tpu_enable_latency_hiding_scheduler=true",
    "vmem_128k": "--xla_tpu_scoped_vmem_limit_kib=131072",
}


def _measure_rung(rung: str, batch_size: int, min_seconds: float,
                  dnn: str = "resnet50") -> dict:
    """Time one rung with the shared honest discipline (timed_window +
    true_sync D2H fence, rtt subtracted — utils/timers.py) and XLA's own
    cost_analysis FLOPs, exactly like benchmark.measure_throughput."""
    import jax
    import jax.numpy as jnp
    import optax

    from gtopkssgd_tpu.benchmark import (
        BenchConfig,
        _compiled_flops,
        _peak_flops_per_chip,
        _setup,
        time_compiled_step,
    )

    cfg = BenchConfig(dnn=dnn, batch_size=batch_size,
                      s2d=(rung == "s2d"))
    model, spec, variables, _, shape = _setup(cfg, None, 1.0)
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, shape)
    y = jax.random.randint(rng, (batch_size,), 0, classes)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})
    if rung == "bf16_params":
        params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
    if rung == "bf16_input":
        x = x.astype(jnp.bfloat16)

    def loss_fn(params, bstats, x):
        out = model.apply(
            {"params": params, "batch_stats": bstats}, x, train=True,
            mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(0)})
        logits, nbs = out
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, nbs["batch_stats"]

    if rung == "fwd":
        def step(state, x):
            params, bstats, mom = state
            loss, nbs = loss_fn(params, bstats, x)
            return (params, nbs, mom), loss
    elif rung == "fwd_bwd":
        def step(state, x):
            params, bstats, mom = state
            (loss, nbs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, bstats, x)
            # grads must stay live or XLA dead-code-eliminates the
            # backward; fold them into the carried state cheaply.
            probe = jax.tree.map(lambda g: g.sum(), grads)
            return (params, nbs, probe), loss
    else:  # full / bf16_params / bf16_input / s2d: fwd+bwd+momentum SGD
        def step(state, x):
            params, bstats, mom = state
            (loss, nbs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, bstats, x)
            mom = jax.tree.map(lambda m, g: 0.9 * m + g, mom, grads)
            params = jax.tree.map(
                lambda p, m: p - (0.1 * m).astype(p.dtype), params, mom)
            return (params, nbs, mom), loss

    if rung in ("fwd", "fwd_bwd"):
        # no optimizer state on these rungs; a token scalar tree keeps the
        # carried-state structure uniform without 100 MB of dead HBM
        mom0 = jax.tree.map(lambda a: jnp.zeros((), a.dtype), params)
    else:
        mom0 = jax.tree.map(jnp.zeros_like, params)
    state = (params, bstats, mom0)
    from gtopkssgd_tpu.utils import safe_donate

    fn = jax.jit(step, donate_argnums=safe_donate(0))
    compiled = fn.lower(state, x).compile()
    flops = _compiled_flops(compiled)
    sec, steps, _ = time_compiled_step(compiled, state, x, min_seconds)
    peak = _peak_flops_per_chip()
    achieved = flops / sec if flops else None
    return {
        "rung": rung,
        "batch_size": batch_size,
        "sec_per_step": round(sec, 6),
        "images_per_sec": round(batch_size / sec, 2),
        "steps_timed": steps,
        "flops_per_step": flops,
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "mfu": round(achieved / peak, 4) if achieved and peak else None,
        "device_kind": jax.devices()[0].device_kind,
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


def _run_child(rung: str, batch_size: int, extra_flag: str,
               min_seconds: float, dnn: str = "resnet50",
               cpu: bool = False) -> dict:
    """One rung in a child interpreter with XLA_FLAGS extended — flags
    bind at backend init, so in-process variants are impossible."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra_flag).strip()
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.abspath(__file__), "--rung", rung,
           "--batch-size", str(batch_size), "--dnn", dnn,
           "--min-seconds", str(min_seconds)]
    if cpu:
        cmd.append("--cpu")
    try:
        out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                             timeout=900)
    except subprocess.TimeoutExpired as e:
        # A wedged tunnel must cost one error row, not the whole ladder's
        # artifact (the already-measured rows still get written).
        return {"rung": rung, "batch_size": batch_size,
                "xla_flags": extra_flag,
                "error": f"child timed out after {e.timeout:.0f}s "
                         "(wedged backend?)"}
    if out.returncode != 0:
        return {"rung": rung, "batch_size": batch_size,
                "xla_flags": extra_flag, "error": out.stderr[-500:]}
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        # RC=0 with garbage stdout happens: a child that died in a C
        # extension after printing warnings, or a wrapper that swallowed
        # the JSON line. Same policy as the timeout above — one error row,
        # not a crashed ladder.
        return {"rung": rung, "batch_size": batch_size,
                "xla_flags": extra_flag,
                "error": "malformed child stdout: "
                         + out.stdout.strip()[-300:]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", default="",
                    help="child mode: measure ONE rung and print one line")
    ap.add_argument("--dnn", default="resnet50")
    ap.add_argument("--rungs",
                    default="fwd,fwd_bwd,full,bf16_params,bf16_input,s2d")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--batch-sizes", default="128,256,512",
                    help="extra 'full' rungs at these batch sizes")
    ap.add_argument("--min-seconds", type=float, default=2.0)
    ap.add_argument("--skip-xla-variants", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the host CPU backend (harness smoke / CI; "
                         "same sitecustomize workaround as "
                         "convergence_run --platform cpu8)")
    args = ap.parse_args()

    if args.rung:  # child mode: one rung, one JSON line
        if args.cpu:
            from gtopkssgd_tpu.utils import force_cpu_mesh

            force_cpu_mesh(1)
        else:
            from bench import _fail_fast_if_backend_dead

            _fail_fast_if_backend_dead()
        from gtopkssgd_tpu.utils import enable_compilation_cache

        enable_compilation_cache()
        row = _measure_rung(args.rung, args.batch_size, args.min_seconds,
                            dnn=args.dnn)
        print(json.dumps(row))
        return

    # Parent mode NEVER initializes a backend: libtpu is single-process-
    # exclusive, so a parent holding the chip would doom every variant
    # child to a dead backend init. Each rung runs in its own child (the
    # persistent compile cache keeps repeat compiles cheap); the first
    # child's fail-fast doubles as the dead-tunnel probe.
    work = []
    for rung in [r.strip() for r in args.rungs.split(",") if r.strip()]:
        if rung == "s2d" and args.dnn != "resnet50":
            continue  # s2d is a resnet50 stem transform
        work.append((rung, args.batch_size, "", None))
    for bs in [int(b) for b in args.batch_sizes.split(",") if b]:
        if bs != args.batch_size:  # args.batch_size ran as the 'full' rung
            work.append(("full", bs, "", None))
    if not args.skip_xla_variants and not args.cpu:
        # TPU-only flags: meaningless (or fatal) on the CPU backend
        for name, flag in XLA_VARIANTS.items():
            work.append(("full", args.batch_size, flag, name))

    rows, errors_in_a_row, aborted = [], 0, None
    for rung, bs, flag, variant in work:
        row = _run_child(rung, bs, flag, args.min_seconds, dnn=args.dnn,
                         cpu=args.cpu)
        if variant:
            row["variant"] = variant
        rows.append(row)
        print(json.dumps(row), flush=True)
        errors_in_a_row = errors_in_a_row + 1 if "error" in row else 0
        if errors_in_a_row >= 2:
            # Two consecutive dead children = the tunnel wedged mid-ladder
            # (rounds-2/3 failure mode); stop burning the uptime window —
            # the measured rows still get written below, and the nonzero
            # exit tells the queue/retry loop the drain was incomplete.
            aborted = (f"2 consecutive child failures at rung {rung!r} — "
                       "backend dead/wedged; remaining "
                       f"{len(work) - len(rows)} rungs skipped")
            print(json.dumps({"aborted": aborted}), file=sys.stderr)
            break

    device = next((r["device_kind"].replace(" ", "_") for r in rows
                   if "device_kind" in r), "unknown")
    os.makedirs(RESULTS_DIR, exist_ok=True)
    out_path = os.path.join(RESULTS_DIR, f"mfu_ablation_{device}.json")
    art = {
        "dnn": args.dnn,
        "what": ("dense ResNet-50 MFU ablation ladder — see module "
                 "docstring for rung definitions; deltas between rungs "
                 "attribute the MFU gap, replacing the op-level profiler "
                 "this platform does not provide"),
        "rows": rows,
    }
    if aborted:
        art["aborted"] = aborted
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({"artifact": out_path, "rows": len(rows)}))
    if aborted:
        raise SystemExit(EXIT_BENCH_TUNNEL_DEAD)


if __name__ == "__main__":
    main()
