"""Multi-chip throughput projection from single-chip measurements.

Only ONE real TPU chip is reachable from this environment, so multi-chip
performance cannot be measured directly. This tool does the next honest
thing: it combines

  * the MEASURED single-chip step decomposition (compute time from
    bench.py / sweep.py on the real chip),
  * a link-aware bandwidth model of the per-device communication volume,
    matching the complexity classes the collectives implement (dense
    ring O(N), DGC allgather O(kP), gtopk O(k log P), hier O(N on ICI +
    k log(P/S) on DCN)) — an independent model, deliberately NOT
    `comm_bytes_per_step` (that reports the paper's volume convention;
    this one needs per-link assignment and ring-transfer factors), and
  * published per-chip interconnect bandwidths,

into a projected images/sec/chip vs P curve for each reduction mode —
the same complexity-table analysis the paper used to argue for gTop-k on
1 GbE (arXiv:1901.04359 §3), re-parameterized for TPU links. The model is
deliberately simple (bandwidth-cost, no latency/overlap terms) and
labeled as a projection everywhere; its purpose is design guidance
(where does sparsity pay?) and judging transparency, not a benchmark.

Key structural fact it surfaces: on ICI (hundreds of GB/s) a dense psum
of ResNet-50's 102 MB gradient costs ~1 ms — comparable to gtopk's
selection overhead — so sparsification buys little inside a slice. On
DCN (tens of Gbit/s shared per host) the same dense reduction costs tens
of ms and gTop-k's O(k log P) wins by an order of magnitude; the
hierarchical mode keeps the dense hop on ICI and sends only the sparse
set over DCN.

Usage:
  python -m benchmarks.scaling_model                    # defaults
  python -m benchmarks.scaling_model --compute-ms 60.1 \
      --n 25557032 --density 0.001 --batch 128 \
      --ici-gbps 400 --dcn-gbps 25 --overhead-ms 5.4
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# The implemented tree's own round count (pow2: log2; ragged: masked
# fold/unfold around the 2^m block) — imported from the collectives so
# model and implementation cannot drift.
from gtopkssgd_tpu.parallel import tree_rounds as _tree_rounds  # noqa: E402
from gtopkssgd_tpu.parallel import get_codec as _get_codec  # noqa: E402
from gtopkssgd_tpu.parallel import balanced_cap as _balanced_cap  # noqa: E402


def _ring_allreduce_bytes(n_bytes: int, p: int) -> float:
    """Bandwidth-optimal dense allreduce moves 2(p-1)/p x the buffer per
    device — 0 at p=1 (no collective), ~2x asymptotically."""
    return 2.0 * (p - 1) / p * n_bytes


def project(mode: str, p: int, *, n: int, k: int, compute_ms: float,
            overhead_ms: float, ici_gbps: float, dcn_gbps: float,
            ici_size: int, batch: int, dcn_alpha_ms: float = 0.0,
            codec: str = "fp32") -> dict:
    """Projected step time at P devices for one reduction mode.

    Comm cost = messages x per-message latency + bytes / link-bandwidth
    on the link each phase actually crosses. For flat modes every P is
    assumed to sit behind the slower of the two links when P exceeds one
    ICI domain (`ici_size` chips): conservative for ICI-only pods,
    realistic for multislice.

    ``dcn_alpha_ms`` is the fitted per-message latency of the slow link
    (dcn_probe.py's alpha_beta_fit). At alpha=0 and P inside one slice
    this reduces to the round-2 bandwidth-only model. ICI latency is
    kept at 0 — microseconds-class, invisible next to ms-scale DCN
    terms.

    Topology consistency (round-4 review): when P spans slices, EVERY
    mode decomposes into an intra-slice phase on ICI plus an inter-slice
    phase on DCN — charging flat modes DCN latency on intra-slice hops
    while the hier mode gets slice-aware accounting would rig the
    comparison. Phase shapes: dense = ring within the slice + ring over
    the n_slices slice aggregates (a topology-aware dense allreduce, the
    decomposition XLA itself applies to multislice meshes); gtopk = the
    hypercube's first log2(s) rounds pair intra-slice partners, the last
    log2(n_slices) rounds cross DCN; allgather = gather s*k within the
    slice, then pull the other slices' (p-s)*k over DCN.
    """
    comm_ms = predict(mode, p, n=n, k=k, ici_gbps=ici_gbps,
                      dcn_gbps=dcn_gbps, ici_size=ici_size,
                      dcn_alpha_ms=dcn_alpha_ms, codec=codec)
    extra = 0.0 if mode == "dense" else overhead_ms
    step_ms = compute_ms + extra + comm_ms
    return {
        "mode": mode,
        "p": p,
        "codec": codec,
        "comm_ms": round(comm_ms, 3),
        "step_ms": round(step_ms, 3),
        "images_per_sec_per_chip": round(batch / step_ms * 1e3, 1),
    }


def predict(mode: str, p: int, *, n: int, k: int, ici_gbps: float,
            dcn_gbps: float, ici_size: int,
            dcn_alpha_ms: float = 0.0, codec: str = "fp32",
            buckets=None) -> float:
    """Predicted comm_ms alone — the comm-model ledger's entry point
    (obs/ledger.py joins this against measured per-step T_comm). Same
    model as project(), with the compute/overhead/throughput bookkeeping
    stripped: the ledger compares communication, the only term the
    alpha-beta model actually predicts. Unrounded (ratio math should not
    inherit display rounding); map gtopk_layerwise to gtopk on the wire
    exactly as project() documents.

    ``codec`` sets the per-set sparse payload
    (parallel.codec.WireCodec.wire_set_bytes — packed values + bf16
    block scales + Elias-Fano bitpacked indices; fp32 identity = the
    historical 8 bytes/element). Every sparse exchange — ICI and DCN
    rounds alike — ships codec bytes, because the tree encodes every
    round; the hier mode's dense intra-slice psum stays 4n fp32.

    ``buckets`` — ((n_b, k_b), ...) from a layerwise BucketPlan
    (gtopkssgd_tpu.parallel.bucketing) — prices the bucketed wire as B
    independent merges of this mode, each over its bucket-local index
    space, summed. That is exactly what the bucketed optimizer path
    issues, so the ledger's bucketed rows reconcile against the same
    per-merge model as everything else."""
    if buckets:
        return sum(
            predict(mode, p, n=int(n_b), k=int(k_b), ici_gbps=ici_gbps,
                    dcn_gbps=dcn_gbps, ici_size=ici_size,
                    dcn_alpha_ms=dcn_alpha_ms, codec=codec)
            for n_b, k_b in buckets)
    # The layerwise mode's wire cost IS gtopk's: the layerwise K differs
    # from ceil(rho*N) only by the +1-per-tiny-leaf ceil rounding (<1%
    # for ResNet-50 at rho=1e-3).
    wire_mode = "gtopk" if mode == "gtopk_layerwise" else mode
    set_bytes = _get_codec(codec).wire_set_bytes(k, n)
    ici_Bps = ici_gbps * 1e9 / 8
    dcn_Bps = dcn_gbps * 1e9 / 8
    s = min(ici_size, p)
    # ceil, not floor: p=24 with 16-chip slices IS a 2-slice job that
    # crosses DCN (a floor would model it as one all-ICI slice and
    # charge zero DCN cost). Ragged counts are first-class: non-pow2 axes
    # run the masked hypercube in-tree (parallel.collectives._merge_tree),
    # log2(m) + 2 rounds with m = 2^floor(log2 x) — modeled by
    # _tree_rounds (the implementation's own round count).
    n_slices = max(1, math.ceil(p / s))
    dcn_rounds = _tree_rounds(n_slices)
    if wire_mode == "dense":
        return (_ring_allreduce_bytes(4 * n, s) / ici_Bps * 1e3
                + _ring_allreduce_bytes(4 * n, n_slices) / dcn_Bps * 1e3
                + 2 * (n_slices - 1) * dcn_alpha_ms)
    if wire_mode == "gtopk":
        # Split the flat tree's tree_rounds(p) by the link each round
        # actually crosses: hypercube rounds whose XOR bit stays inside a
        # slice pair ICI neighbors; larger bits — and the ragged
        # fold/unfold, which spans slices whenever p > s — cross DCN.
        # (p=24, s=16: 6 rounds total = 4 ICI + fold/unfold on DCN; a
        # tree_rounds(s)+tree_rounds(n_slices) split drops one DCN round
        # at exactly those ragged shapes.)
        total_rounds = _tree_rounds(p)
        if n_slices == 1:
            ici_rounds, flat_dcn_rounds = total_rounds, 0
        else:
            m = 1 << (p.bit_length() - 1)
            # floor(log2) via bit_length, not int(math.log2(...)): s is
            # whatever --ici-size the user typed, and the float path
            # silently truncates non-powers-of-two (and can misround at
            # large exact powers); hypercube rounds pair by XOR bit, so
            # floor(log2) is the intended count for ragged s too.
            ici_rounds = min(m, s).bit_length() - 1
            flat_dcn_rounds = total_rounds - ici_rounds
        return (ici_rounds * set_bytes / ici_Bps * 1e3
                + flat_dcn_rounds * (set_bytes / dcn_Bps * 1e3
                                     + dcn_alpha_ms))
    if wire_mode == "gtopk_balanced":
        # Ok-Topk split-and-reduce (parallel.collectives
        # balanced_gtopk_allreduce): p-1 scatter ppermutes + a p-slice
        # allgather, each moving ONE cap-of-n encoded set — O(k) volume
        # vs the tree's O(k log p), paid for with O(p) message count.
        # Link split mirrors allgather's: of each phase's p-1 partner
        # hops, s-1 stay inside the slice, the rest cross DCN; every
        # DCN hop pays the fitted per-message alpha (the term that makes
        # the planner prefer the tree on latency-bound fabrics).
        cap_bytes = _get_codec(codec).wire_set_bytes(
            _balanced_cap(k, p, n), n)
        ici_hops = 2 * (s - 1) + 1   # scatter + gather + own-set share
        dcn_hops = 2 * (p - s)
        return (ici_hops * cap_bytes / ici_Bps * 1e3
                + dcn_hops * (cap_bytes / dcn_Bps * 1e3 + dcn_alpha_ms))
    if wire_mode == "allgather":
        return ((set_bytes * s) / ici_Bps * 1e3
                + (set_bytes * (p - s)) / dcn_Bps * 1e3
                + (n_slices - 1) * dcn_alpha_ms)
    if wire_mode == "gtopk_hier":
        return (_ring_allreduce_bytes(4 * n, s) / ici_Bps * 1e3
                + dcn_rounds * (set_bytes / dcn_Bps * 1e3
                                + dcn_alpha_ms))
    raise ValueError(mode)


def main():
    ap = argparse.ArgumentParser()
    # Defaults = the committed ResNet-50 measurements from TPU v5e
    # (bench.py / breakdown artifacts): 60.1 ms fwd+bwd+apply at b128,
    # 5.4 ms measured gtopk overhead (compress + residual + scatter).
    ap.add_argument("--compute-ms", type=float, default=60.1)
    ap.add_argument("--overhead-ms", type=float, default=5.4)
    ap.add_argument("--n", type=int, default=25_557_032)
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--batch", type=int, default=128)
    # v5e: 4 ICI links/chip at ~100 GB/s-class aggregate; DCN per host
    # measured in tens of Gbit/s. Both overridable — the CONCLUSION
    # (dense wins on ICI, sparse wins on DCN) is insensitive to 2x
    # errors in either.
    ap.add_argument("--ici-gbps", type=float, default=1600.0,
                    help="aggregate ICI Gbit/s per chip")
    ap.add_argument("--dcn-gbps", type=float, default=25.0,
                    help="effective DCN Gbit/s per host")
    ap.add_argument("--ici-size", type=int, default=16,
                    help="chips per ICI domain (slice)")
    ap.add_argument("--dcn-alpha-ms", type=float, default=0.0,
                    help="fitted per-message DCN latency (dcn_probe.py "
                         "alpha_beta_fit.alpha_ms); 0 = bandwidth-only")
    ap.add_argument("--wire-codec", default="fp32",
                    help="sparse payload codec (parallel.codec grammar: "
                         "fp32 | int8[:BLOCK] | fp8[:BLOCK])")
    ap.add_argument("--ps", type=int, nargs="+",
                    default=[1, 4, 16, 32, 64, 256])
    args = ap.parse_args()

    k = max(1, math.ceil(args.density * args.n))
    kw = dict(n=args.n, k=k, compute_ms=args.compute_ms,
              overhead_ms=args.overhead_ms, ici_gbps=args.ici_gbps,
              dcn_gbps=args.dcn_gbps, ici_size=args.ici_size,
              batch=args.batch, dcn_alpha_ms=args.dcn_alpha_ms,
              codec=args.wire_codec)
    print(json.dumps({"model": ("latency+bandwidth projection (see "
                                "docstring; alpha=0 => bandwidth-only)"),
                      "k": k, **{a: getattr(args, a)
                                 for a in ("compute_ms", "overhead_ms",
                                           "n", "density", "batch",
                                           "ici_gbps", "dcn_gbps",
                                           "ici_size", "dcn_alpha_ms")}}))
    for p in args.ps:
        for mode in ("dense", "gtopk", "gtopk_balanced", "allgather",
                     "gtopk_hier"):
            print(json.dumps(project(mode, p, **kw)))


if __name__ == "__main__":
    main()
