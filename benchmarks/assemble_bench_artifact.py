"""Assemble the round's committed bench artifact from a queue drain.

onchip_queue.sh writes one driver-format JSON per bench stage into its
outdir (bench_bs128.json, bench_bs256.json, bench_bs512.json,
bench_bs256_s2d.json, bench_bs128_corr.json). This tool folds the ones
that succeeded into one benchmarks/results/bench_r<N>_<device>.json in
the same shape as bench_r3_TPU_v5_lite.json (bs-keyed blocks + reading),
so the committed artifact exists the moment the window closes instead of
depending on a by-hand consolidation step surviving the tunnel's mood.

Usage:
  python benchmarks/assemble_bench_artifact.py --round 4 \
      [--queue-dir /tmp/onchip_queue] [--reading "..."]
"""

from __future__ import annotations

import argparse
import json
import os

RESULTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "results")

# stage filename -> artifact block key
STAGES = {
    "bench_bs128.json": "bs128",
    "bench_bs256.json": "bs256",
    "bench_bs512.json": "bs512",
    "bench_bs256_s2d.json": "bs256_s2d",
    "bench_bs128_corr.json": "bs128_corr",
}


def load_stage(path: str):
    """A stage file holds bench.py's one-line driver JSON (or garbage /
    nothing if the stage died); return the parsed dict or None."""
    try:
        with open(path) as fh:
            text = fh.read().strip()
        if not text:
            return None
        # bench.py prints exactly one JSON object; tolerate stray
        # warning lines before it by taking the last line that parses.
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return None
    except (OSError, json.JSONDecodeError):
        return None


def _newest_stage_mtime(queue_dir: str) -> float:
    mt = 0.0
    for fname in STAGES:
        try:
            mt = max(mt, os.path.getmtime(os.path.join(queue_dir, fname)))
        except OSError:
            pass
    return mt


def derive_round(queue_dir: str) -> int:
    """Default round number when --round is omitted: one past the newest
    committed bench_r<N> artifact — UNLESS that artifact was assembled
    from the SAME drain (exact queue_dir match AND the same
    newest-stage mtime), in which case re-assembling (e.g. a --reading
    pass) belongs to the same round. A new drain rewrites the stage
    files, so its mtime differs and the round advances — the counter
    can never pin."""
    import glob
    import re

    best_n, best_path = 0, None
    for path in glob.glob(os.path.join(RESULTS, "bench_r*.json")):
        m = re.search(r"bench_r(\d+)", path)
        if m and int(m.group(1)) > best_n:
            best_n, best_path = int(m.group(1)), path
    if best_path:
        try:
            with open(best_path) as fh:
                prev = json.load(fh)
            if (prev.get("queue_dir") == queue_dir
                    and prev.get("newest_stage_mtime")
                    == _newest_stage_mtime(queue_dir)):
                return best_n
        except (OSError, json.JSONDecodeError):
            pass
    return best_n + 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=None,
                    help="default: derived from the newest committed "
                         "bench_r<N> artifact (same round when that "
                         "artifact came from this queue dir, else N+1)")
    ap.add_argument("--queue-dir", default="/tmp/onchip_queue")
    ap.add_argument("--max-stage-age-hours", type=float, default=6.0,
                    help="stages older than this relative to the NEWEST "
                         "stage are treated as leftovers from a previous "
                         "drain and excluded (a wedged drain can leave "
                         "stale files behind)")
    ap.add_argument("--what", default=None)
    ap.add_argument("--reading", default="",
                    help="the human verdict on the numbers; append later "
                         "with --reading once the blocks are inspected")
    args = ap.parse_args()
    if args.round is None:
        args.round = derive_round(args.queue_dir)

    mtimes = {}
    for fname in STAGES:
        try:
            mtimes[fname] = os.path.getmtime(
                os.path.join(args.queue_dir, fname))
        except OSError:
            pass
    newest = max(mtimes.values(), default=0.0)

    blocks = {}
    missing, stale = [], []
    for fname, key in STAGES.items():
        if fname in mtimes and (
                newest - mtimes[fname] > args.max_stage_age_hours * 3600):
            stale.append(fname)
            continue
        stage = load_stage(os.path.join(args.queue_dir, fname))
        if stage is None:
            missing.append(fname)
        else:
            blocks[key] = stage
    if not blocks:
        raise SystemExit(f"no parseable bench stage in {args.queue_dir} "
                         f"(missing/failed: {missing}, stale: {stale})")

    device = next(iter(blocks.values())).get("device_kind", "unknown")
    out = os.path.join(
        RESULTS, f"bench_r{args.round}_{device.replace(' ', '_')}.json")
    artifact = {
        "what": args.what or (
            f"Round-{args.round} on-chip capture assembled from the "
            f"queue drain ({len(blocks)} of {len(STAGES)} stages; "
            f"missing/failed: {missing or 'none'}; stale/excluded: "
            f"{stale or 'none'}). Measurement discipline: bench.py "
            "measure_throughput (>=2s windows, D2H fence on the full "
            "updated state, XLA cost_analysis FLOPs)."),
        "provenance": f"assembled from {args.queue_dir} by "
                      "assemble_bench_artifact.py",
        "queue_dir": args.queue_dir,
        "newest_stage_mtime": newest,
        **blocks,
    }
    if args.reading:
        artifact["reading"] = args.reading
    # Keep any reading a previous assembly pass already recorded.
    elif os.path.exists(out):
        try:
            with open(out) as fh:
                old = json.load(fh)
            if "reading" in old:
                artifact["reading"] = old["reading"]
        except (OSError, json.JSONDecodeError):
            pass

    os.makedirs(RESULTS, exist_ok=True)
    with open(out, "w") as fh:
        json.dump(artifact, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"wrote": out, "round": args.round,
                      "blocks": sorted(blocks), "missing": missing,
                      "stale": stale}))


if __name__ == "__main__":
    main()
