"""Compose measured factors into the paper's actual claim: time-to-quality.

BASELINE.md's second north-star row is *time-to-76%-top-1* — a product of

    time_to_quality(mode, P) =
        steps_to_quality(mode)            [measured: convergence artifacts]
      x step_time(mode, P)                [measured at P=1: bench_r* artifact;
                                           comm term: scaling_model anchored
                                           at the dcn_probe alpha/beta fit]

The repo measures all three factors separately (round-3 verdict missing #5:
"never composes them into the one number the paper's claim is actually
about"); this script multiplies them out per reduction mode at P = 8/16/32
and writes benchmarks/results/time_to_quality_composed.json.

What is measured vs projected, stated plainly:
  * steps_to_quality — MEASURED: steps to 90% of the dense loss drop,
    identical-seed multi-worker real-collective runs (convergence_*
    artifacts; 2- or 8-way — each row names its source).
    The CPU-mesh runs use small batches; what transfers to the composition
    is the mode-relative step-count ratio, not the absolute count.
  * single-chip step time — MEASURED on the TPU chip (bench_r* artifact):
    dense step ms = the compute term; gtopk minus dense = the p=1 sparse
    overhead term.
  * comm term vs P — PROJECTED by scaling_model.py (latency+bandwidth
    model), anchored at the dcn_probe alpha/beta fit where present. One
    real chip is all this environment has; the projection is labeled as
    such everywhere it appears.

Usage:
  python benchmarks/time_to_quality.py            # defaults from artifacts
  python benchmarks/time_to_quality.py --quality 0.9 --ps 8 16 32
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import math
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmarks", "results")

# Convergence-artifact base mode -> the collective actually on the wire.
# Arm suffixes (+warmup, +corr, +exact/approx/... — convergence_run.py's
# arm syntax) change selection or schedule, never the wire format, so the
# wire mode is derived from the base mode and every suffix combination is
# covered automatically.
BASE_WIRE_MODE = {
    "dense": "dense",
    "gtopk": "gtopk",
    "gtopk_layerwise": "gtopk",
    "allgather": "allgather",
    "gtopk_hier": "gtopk_hier",
}


def wire_mode(mode: str):
    return BASE_WIRE_MODE.get(mode.split("+")[0])


def _load_scaling_model():
    spec = importlib.util.spec_from_file_location(
        "scaling_model", os.path.join(REPO, "benchmarks",
                                      "scaling_model.py"))
    sm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sm)
    return sm


def latest_bench_artifact() -> tuple[str, dict]:
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    path = bench.latest_bench_artifact_path()
    if path is None:
        raise SystemExit("no bench_r*.json artifact to read step times from")
    with open(path) as fh:
        return path, json.load(fh)


def steps_to_quality(paths: list[str], quality: float,
                     density: float, synth_hard: bool = False) -> dict:
    """mode -> (steps, source artifact) from convergence report rows.

    Only rows at the requested sparse density (or dense, density=1.0)
    enter: a rho=0.01 run converges far faster than rho=0.001 and must
    not leak into a rho=0.001 composition. Same rule for the task
    variant: the hard synthetic task is calibrated to produce DIFFERENT
    steps-to-quality, so easy- and hard-task artifacts must never mix in
    one composition — reports carry a synth_hard marker (absent = easy,
    the pre-round-5 capture default) and only the requested variant
    enters.
    """
    key = f"steps_to_{quality}_of_dense_drop"
    out = {}
    for path in paths:
        try:
            with open(path) as fh:
                rows = [json.loads(l) for l in fh if l.strip()]
        except OSError:
            continue
        report = next((r for r in rows if r.get("kind") == "report"), None)
        if not report:
            continue
        if bool(report.get("synth_hard", False)) != synth_hard:
            continue
        # The dense arm FROM THE SAME artifact is each sparse mode's
        # fair baseline: the 90%-of-drop target is defined by that run's
        # own identical-seed dense curve at that horizon. Pairing a
        # sparse mode with a different artifact's dense arm (harder or
        # easier target) biases the ratio.
        dense_here = next(
            (m.get(key) for m in report.get("modes", [])
             if m["mode"] == "dense" and m.get(key) is not None), None)
        for m in report.get("modes", []):
            steps = m.get(key)
            if steps is None:
                continue
            if m.get("density") not in (density, 1.0):
                continue
            mode = m["mode"]
            # Prefer the longest-horizon artifact per mode (a 1200-step
            # run supersedes a 600-step one); on a horizon TIE prefer
            # the report with more arms (more internally-comparable
            # context measured under one code state) — and RECORD the
            # conflict so a tie never silently picks a side (two
            # same-horizon artifacts can disagree across data-regime
            # changes; the composed artifact must show that).
            prev = out.get(mode)
            horizon = report.get("steps", 0)
            arms = len(report.get("modes", []))
            # regime context rides along so a recorded conflict shows
            # WHETHER the disagreement crosses worker regimes (the
            # round-4 450-vs-1100 warmup "conflict" paired 2x16 against
            # 8x4 — same global batch, different tree depth and
            # per-device BN batch; that is a regime difference, not a
            # measurement error)
            regime = {"nworkers": report.get("nworkers"),
                      "batch_size": report.get("batch_size")}
            cand = {"steps": steps, "src": os.path.basename(path),
                    "horizon": horizon, "arms": arms, **regime,
                    "dense_steps": dense_here, "conflicts": [],
                    "regime_variants": []}
            ckeys = ("steps", "src", "horizon", "nworkers", "batch_size")

            def classify(winner, loser):
                """Same-regime disagreement = a measurement CONFLICT;
                cross-regime disagreement = a regime VARIANT. The round-4
                450-vs-1100 warmup "conflict" was re-measured under
                round-5 code at the disputed 8x4 regime and REPRODUCED
                BIT-FOR-BIT (convergence_resnet20_warmup1200r5_cpu_mesh8
                vs the round-3 capture: dense 450/900, warmup 1100,
                identical final losses) — steps-to-quality genuinely
                depends on the worker regime (tree depth, per-device BN
                batch), so cross-regime disagreement is information, not
                error."""
                entry = {k: loser[k] for k in ckeys}
                same_regime = (winner["nworkers"] == loser["nworkers"] and
                               winner["batch_size"] == loser["batch_size"])
                key = "conflicts" if same_regime else "regime_variants"
                winner[key].append(entry)

            if prev is None:
                out[mode] = cand
            elif (horizon, arms) > (prev["horizon"], prev["arms"]):
                # inherited entries re-classify against the NEW winner's
                # regime (an entry that was same-regime for the old
                # winner may be cross-regime for this one, and vice
                # versa)
                for entry in (prev["conflicts"] + prev["regime_variants"]):
                    classify(cand, entry)
                classify(cand, prev)
                out[mode] = cand
            elif horizon == prev["horizon"] and steps != prev["steps"]:
                classify(prev, cand)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quality", default="0.9",
                    help="fraction of the dense loss drop that defines "
                         "'quality' (must exist as steps_to_<q>_of_dense_"
                         "drop in the artifacts)")
    ap.add_argument("--ps", type=int, nargs="+", default=[8, 16, 32])
    ap.add_argument("--batch-key", default="bs128",
                    help="which bench artifact block supplies step times")
    ap.add_argument("--convergence-glob",
                    default="convergence_resnet20_*cpu_mesh*",
                    help="one workload family only: steps-to-quality is "
                         "judged against that family's own dense arm "
                         "(mesh2 + mesh8 artifacts mix safely — each "
                         "mode's ratio pairs with its own artifact's "
                         "dense arm)")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--synth-hard", action="store_true",
                    help="compose from HARD-task convergence artifacts "
                         "(reports marked synth_hard) instead of the "
                         "easy-task captures; the two tasks' "
                         "steps-to-quality are not comparable and never "
                         "mix")
    ap.add_argument("--ici-size", type=int, default=16)
    ap.add_argument("--ici-gbps", type=float, default=1600.0)
    ap.add_argument("--out", default=os.path.join(
        RESULTS, "time_to_quality_composed.json"))
    args = ap.parse_args()

    bench_path, bench = latest_bench_artifact()
    block = bench[args.batch_key]
    compute_ms = block["dense_step_ms"]
    overhead_ms = block["gtopk_step_ms"] - block["dense_step_ms"]
    n = block["num_params"]
    batch = block["batch_size_per_chip"]
    k = max(1, math.ceil(args.density * n))
    # The DGC recursion costs extra per step; when the corr bench block
    # exists (onchip_queue's bench_bs128_corr stage), +corr rows use its
    # own measured overhead instead of inheriting plain gtopk's.
    corr_block = bench.get(f"{args.batch_key}_corr")
    corr_overhead_ms = (
        corr_block["gtopk_step_ms"] - corr_block["dense_step_ms"]
        if corr_block else None)

    conv_paths = sorted(glob.glob(
        os.path.join(RESULTS, args.convergence_glob + ".jsonl")))
    steps = steps_to_quality(conv_paths, args.quality, args.density,
                             synth_hard=args.synth_hard)
    for mode, rec in sorted(steps.items()):
        for c in rec["conflicts"]:
            print(f"# NOTE {mode}: using {rec['steps']} steps from "
                  f"{rec['src']}; {c['src']} (same/shorter horizon) "
                  f"measured {c['steps']} — conflict recorded in the "
                  "artifact rows")
    if "dense" not in steps:
        raise SystemExit(f"no dense steps_to_{args.quality} row found in "
                         f"{len(conv_paths)} convergence artifacts")

    # Comm constants: the dcn_probe fit when present, else the published
    # defaults scaling_model documents.
    dcn_gbps, dcn_alpha_ms, dcn_src, fit = 25.0, 0.0, "default", None
    probe_path = os.path.join(RESULTS, "dcn_probe_2proc.json")
    if os.path.exists(probe_path):
        with open(probe_path) as fh:
            probe = json.load(fh)
        fit = probe.get("alpha_beta_fit")
        if fit:
            dcn_gbps = fit["beta_gbps"]
            dcn_alpha_ms = fit["alpha_ms"]
            dcn_src = "dcn_probe_2proc.json alpha_beta_fit"
        else:
            dcn_gbps = probe["measured_cross_process_gbps"]
            dcn_src = "dcn_probe_2proc.json (bandwidth only)"

    # Alpha reconciliation (round-4 verdict weak #3 / next-round #8): the
    # 2-proc fit says alpha=3.66 ms, the 4-proc fit 21.9 ms — a 6x gap
    # that is the 1-core host's self-contention signature (P processes
    # timeshare one core, so per-message latency includes scheduler
    # queueing that grows superlinearly with P), not a property of any
    # network. Neither number is a NIC alpha. The honest composition
    # BRACKETS: every row is computed at the 2-proc anchor AND at the
    # alpha=0 bandwidth-only floor, and the quotable headline is the
    # per-row MIN — whichever end is less favorable to that mode at that
    # P (the direction is shape-dependent: at bandwidth-dominated slice
    # counts zeroing alpha helps dense more than gtopk and the anchor is
    # the conservative end, e.g. the committed p=32 rows).
    alpha_bracket = {"floor_alpha0": 0.0,
                     "anchor_2proc_ms": dcn_alpha_ms if fit else None}
    probe4_path = os.path.join(RESULTS, "dcn_probe_4proc.json")
    if os.path.exists(probe4_path):
        with open(probe4_path) as fh:
            fit4 = json.load(fh).get("alpha_beta_fit") or {}
        alpha_bracket["contended_4proc_ms"] = fit4.get("alpha_ms")

    sm = _load_scaling_model()
    kw = dict(n=n, k=k, compute_ms=compute_ms, overhead_ms=overhead_ms,
              ici_gbps=args.ici_gbps, dcn_gbps=dcn_gbps,
              dcn_alpha_ms=dcn_alpha_ms, ici_size=args.ici_size,
              batch=batch)

    kw0 = {**kw, "dcn_alpha_ms": 0.0}  # bandwidth-only floor of the bracket
    table = []
    for p in args.ps:
        dense_proj = sm.project("dense", p, **kw)
        dense_proj0 = sm.project("dense", p, **kw0)
        for mode, rec in sorted(steps.items()):
            wire = wire_mode(mode)
            if wire is None:
                print(f"# dropping mode {mode!r}: unknown base wire mode")
                continue
            # dense pays no selection overhead; sparse modes pay the
            # measured p=1 overhead (inside project's `extra`); +corr
            # rows use the corr bench block's own overhead when the
            # on-chip queue has measured it.
            if "+corr" in mode and corr_overhead_ms is not None:
                ov, ov_src = corr_overhead_ms, f"{args.batch_key}_corr bench block"
            else:
                ov = kw["overhead_ms"]
                ov_src = (f"{args.batch_key} gtopk block (corr step cost "
                          "unmeasured on-chip)"
                          if "+corr" in mode else f"{args.batch_key} block")
            proj = sm.project(wire, p, **{**kw, "overhead_ms": ov})
            proj0 = sm.project(wire, p, **{**kw0, "overhead_ms": ov})
            t_min = rec["steps"] * proj["step_ms"] / 1e3 / 60
            t_min0 = rec["steps"] * proj0["step_ms"] / 1e3 / 60
            # Ratio vs the SAME artifact's dense arm (fair target);
            # falls back to the longest-horizon dense arm if the source
            # artifact had no dense row reaching the quality.
            dense_steps = rec["dense_steps"] or steps["dense"]["steps"]
            dense_t_min = dense_steps * dense_proj["step_ms"] / 1e3 / 60
            dense_t_min0 = dense_steps * dense_proj0["step_ms"] / 1e3 / 60
            vs = round(dense_t_min / t_min, 3) if t_min else None
            vs0 = round(dense_t_min0 / t_min0, 3) if t_min0 else None
            table.append({
                "p": p,
                "mode": mode,
                "wire_mode": wire,
                "steps_to_quality": rec["steps"],
                "steps_source": rec["src"],
                "steps_regime": {"nworkers": rec["nworkers"],
                                 "batch_size": rec["batch_size"]},
                "dense_steps_same_artifact": rec["dense_steps"],
                "conflicting_measurements": rec["conflicts"] or None,
                "regime_variants": rec["regime_variants"] or None,
                "overhead_source": ov_src,
                "step_ms_projected": proj["step_ms"],
                "comm_ms_projected": proj["comm_ms"],
                "time_to_quality_min": round(t_min, 2),
                "vs_dense_time": vs,
                "vs_dense_time_alpha0": vs0,
                # the quotable number: the bracket end less favorable to
                # this mode (see alpha reconciliation note above)
                "vs_dense_time_conservative": (
                    min(vs, vs0) if vs is not None and vs0 is not None
                    else vs or vs0),
            })

    report = {
        "what": ("composed time-to-quality projection: measured "
                 "steps-to-quality x (measured single-chip step time + "
                 "modeled comm term vs P). PROJECTION — one real chip; "
                 "see module docstring for which factor is measured vs "
                 "modeled"),
        "quality": f"{args.quality} of dense loss drop",
        "density": args.density,
        "factors": {
            "bench_artifact": os.path.basename(bench_path),
            "batch_block": args.batch_key,
            "compute_ms_measured": compute_ms,
            "sparse_overhead_ms_measured": round(overhead_ms, 3),
            "dcn_gbps": dcn_gbps,
            "dcn_alpha_ms": dcn_alpha_ms,
            "dcn_constants_source": dcn_src,
            "dcn_alpha_bracket": {
                **alpha_bracket,
                "note": ("the 2-proc and 4-proc localhost fits disagree "
                         "~6x on alpha — the 1-core host's "
                         "self-contention signature, not a NIC property; "
                         "every row therefore carries vs_dense_time at "
                         "the 2-proc anchor AND at the alpha=0 "
                         "bandwidth-only floor, and "
                         "vs_dense_time_conservative = min of the two — "
                         "whichever end is less favorable to the mode at "
                         "that P (the direction depends on how many "
                         "per-message latencies each mode pays at that "
                         "slice shape; quote ONLY the conservative "
                         "column)"),
            },
            "ici_gbps": args.ici_gbps,
            "ici_size": args.ici_size,
            "steps_note": ("steps_to_quality measured on multi-worker "
                           "CPU-mesh real-collective runs (ResNet-20 "
                           "scale; 2- or 8-way — steps_source names the "
                           "artifact, which records nworkers); the "
                           "mode-relative ratio is the transferable "
                           "quantity. vs_dense_time pairs each mode "
                           "with the dense arm of its OWN source "
                           "artifact (dense_steps_same_artifact) — the "
                           "quality target is defined per-artifact by "
                           "that run's identical-seed dense curve. "
                           "conflicting_measurements lists same-horizon "
                           "artifacts that disagree"),
        },
        "table": table,
    }
    out = args.out
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    hdr = f"{'P':>4} {'mode':<16} {'steps':>6} {'step_ms':>9} " \
          f"{'t_qual_min':>11} {'vs dense':>9} {'conserv.':>9}"
    print(hdr)
    for row in table:
        print(f"{row['p']:>4} {row['mode']:<16} "
              f"{row['steps_to_quality']:>6} "
              f"{row['step_ms_projected']:>9.2f} "
              f"{row['time_to_quality_min']:>11.2f} "
              f"{row['vs_dense_time']:>9.3f} "
              f"{row['vs_dense_time_conservative']:>9.3f}")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
