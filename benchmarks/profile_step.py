"""Profile the dense ResNet-50 train step and rank its time sinks.

Round-2 verdict weak #3: dense MFU 0.243 at bs=256 was "mediocre and
unexamined", and the dense baseline is the denominator of every ratio this
project reports. This tool captures a ``jax.profiler`` trace of the exact
benchmark step (same program as bench.py via benchmark.measure_throughput's
setup), parses the chrome-trace events host-side, and emits the top ops by
accumulated device time — the evidence needed to attack input-layout
transposes / BN / small-channel convs, or to write the measured-ceiling
note if nothing is attackable.

Usage (on the chip):
  python benchmarks/profile_step.py [--dnn resnet50] [--batch-size 256] \
      [--mode dense] [--steps 20]
Writes benchmarks/results/profile_<dnn>_<mode>_<device>.json (op table)
and leaves the raw trace under --trace-dir for TensorBoard.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def capture_trace(args, trace_dir: str) -> dict:
    import jax

    from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    cfg = BenchConfig(dnn=args.dnn, batch_size=args.batch_size,
                      min_seconds=0.5, density=args.density,
                      dtype=args.dtype)
    # One measured warm pass builds + compiles + runs the program and
    # returns the throughput context for the artifact.
    stats = measure_throughput(cfg, args.mode,
                               1.0 if args.mode == "dense" else args.density)
    # Second short pass under the profiler: reuse of the jit cache makes
    # this pure execution, which is what we want on the trace.
    with jax.profiler.trace(trace_dir):
        measure_throughput(cfg, args.mode,
                           1.0 if args.mode == "dense" else args.density)
    return stats


def parse_trace(trace_dir: str, top: int = 40) -> dict:
    """Aggregate device-lane durations from the chrome trace.

    Lane layout on this platform (device pid's thread names): "Steps"
    (one event per device program execution, numeric names), "XLA
    Modules" (module executions), "XLA Ops" (per-op detail). MEASURED
    LIMITATION of the tunneled axon platform: the main (shard_map'd
    train-step) module appears ONLY in the Steps lane — the Modules/Ops
    lanes carry just the small host-built jits (convert/threefry/...),
    so per-op attribution inside the train step is NOT available here
    (see benchmarks/results/profile_resnet50_*_TPU_v5_lite.json). We
    report both: the Steps-lane execution histogram (the honest
    device-time record) and the op table for whatever modules the
    profiler did attribute."""
    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise SystemExit(f"no trace found under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    pnames = {e.get("pid"): e.get("args", {}).get("name", "")
              for e in events if e.get("name") == "process_name"}
    device_pids = {pid for pid, name in pnames.items()
                   if any(t in name.lower()
                          for t in ("tpu", "device", "xla", "/device"))}
    tnames = {(e.get("pid"), e.get("tid")): e.get("args", {}).get("name", "")
              for e in events if e.get("name") == "thread_name"}

    def lane(e):
        return tnames.get((e.get("pid"), e.get("tid")), "")

    def device_us(e):
        ps = e.get("args", {}).get("device_duration_ps")
        return float(ps) / 1e6 if ps else float(e.get("dur", 0.0))

    step_durs, agg, count, cat = [], collections.defaultdict(float), \
        collections.defaultdict(int), collections.defaultdict(float)
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        ln = lane(e)
        if ln == "Steps":
            step_durs.append(device_us(e))
        elif ln == "XLA Ops":
            a = e.get("args", {})
            us = device_us(e)
            agg[e.get("name", "?")] += us
            count[e.get("name", "?")] += 1
            cat[a.get("hlo_category", "?")] += us
    op_total = sum(agg.values())
    step_durs.sort(reverse=True)
    # Histogram of program executions: the main train step dominates the
    # tail of repeated near-identical durations.
    buckets = collections.Counter(round(d / 1000, 1) for d in step_durs)
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top]
    return {
        "trace_file": os.path.relpath(path, trace_dir),
        "steps_lane": {
            "executions": len(step_durs),
            "total_device_ms": round(sum(step_durs) / 1000, 1),
            "largest_ms": [round(d / 1000, 2) for d in step_durs[:10]],
            "top_duration_ms_histogram": {
                f"{ms}ms": n for ms, n in buckets.most_common(12)
            },
        },
        "attributed_op_us_total": round(op_total, 1),
        "attribution_note": (
            "per-op detail covers only the small helper jits on this "
            "platform; the train-step module is visible only as Steps-"
            "lane executions"),
        "hlo_category_us": {k: round(v, 1) for k, v in
                            sorted(cat.items(), key=lambda kv: -kv[1])},
        "top_ops": [
            {"name": n[:160], "total_us": round(us, 1), "calls": count[n],
             "pct": round(100 * us / op_total, 2) if op_total else None}
            for n, us in rows
        ],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--mode", default="dense")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--trace-dir", default="/tmp/gtopk_profile")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--parse-only", action="store_true",
                    help="skip capture; parse an existing --trace-dir")
    ap.add_argument("--kind", default="",
                    help="device-kind tag for the output filename "
                         "(default: live device kind, or 'parsed' with "
                         "--parse-only)")
    args = ap.parse_args()

    import jax

    if args.parse_only:
        stats = {}
    else:
        os.makedirs(args.trace_dir, exist_ok=True)
        stats = capture_trace(args, args.trace_dir)
    table = parse_trace(args.trace_dir, args.top)
    report = {
        "what": ("device-time op ranking of the benchmark step, parsed "
                 "from the jax.profiler chrome trace"),
        "dnn": args.dnn, "mode": args.mode,
        "batch_size": args.batch_size, "dtype": args.dtype,
        "throughput_context": {
            k: stats.get(k) for k in
            ("images_per_sec_per_chip", "sec_per_step", "mfu",
             "achieved_tflops_per_chip", "flops_per_step")
        } if stats else None,
        **table,
    }
    os.makedirs(RESULTS, exist_ok=True)
    kind = args.kind or (
        jax.devices()[0].device_kind.replace(" ", "_")
        if not args.parse_only else "parsed")
    out = os.path.join(
        RESULTS, f"profile_{args.dnn}_{args.mode}_{kind}.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"out": out,
                      "steps_lane": report["steps_lane"],
                      "top5": report["top_ops"][:5]}))


if __name__ == "__main__":
    main()
