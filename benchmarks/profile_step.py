"""Profile the dense ResNet-50 train step and rank its time sinks.

Round-2 verdict weak #3: dense MFU 0.243 at bs=256 was "mediocre and
unexamined", and the dense baseline is the denominator of every ratio this
project reports. This tool captures a ``jax.profiler`` trace of the exact
benchmark step (same program as bench.py via benchmark.measure_throughput's
setup), parses the chrome-trace events host-side, and emits the top ops by
accumulated device time — the evidence needed to attack input-layout
transposes / BN / small-channel convs, or to write the measured-ceiling
note if nothing is attackable.

Usage (on the chip):
  python benchmarks/profile_step.py [--dnn resnet50] [--batch-size 256] \
      [--mode dense] [--steps 20]
Writes benchmarks/results/profile_<dnn>_<mode>_<device>.json (op table)
and leaves the raw trace under --trace-dir for TensorBoard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gtopkssgd_tpu.obs.trace_attr import attribute, format_attr, op_ranking

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")

# The op-ranking parser this tool grew up around now lives in
# obs.trace_attr (shared with the gate smoke, bench.py --attr-trace, and
# the report CLI); the alias keeps the historical entry point importable.
parse_trace = op_ranking


def capture_trace(args, trace_dir: str) -> dict:
    import jax

    from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    cfg = BenchConfig(dnn=args.dnn, batch_size=args.batch_size,
                      min_seconds=0.5, density=args.density,
                      dtype=args.dtype)
    # One measured warm pass builds + compiles + runs the program and
    # returns the throughput context for the artifact.
    stats = measure_throughput(cfg, args.mode,
                               1.0 if args.mode == "dense" else args.density)
    # Second short pass under the profiler: reuse of the jit cache makes
    # this pure execution, which is what we want on the trace.
    with jax.profiler.trace(trace_dir):
        measure_throughput(cfg, args.mode,
                           1.0 if args.mode == "dense" else args.density)
    return stats


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--mode", default="dense")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--trace-dir", default="/tmp/gtopk_profile")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--parse-only", action="store_true",
                    help="skip capture; parse an existing --trace-dir")
    ap.add_argument("--kind", default="",
                    help="device-kind tag for the output filename "
                         "(default: live device kind, or 'parsed' with "
                         "--parse-only)")
    args = ap.parse_args()

    import jax

    if args.parse_only:
        stats = {}
    else:
        os.makedirs(args.trace_dir, exist_ok=True)
        stats = capture_trace(args, args.trace_dir)
    table = parse_trace(args.trace_dir, args.top)
    attr = attribute(args.trace_dir, mode=args.mode)
    report = {
        "what": ("device-time op ranking of the benchmark step, parsed "
                 "from the jax.profiler chrome trace"),
        "dnn": args.dnn, "mode": args.mode,
        "batch_size": args.batch_size, "dtype": args.dtype,
        "throughput_context": {
            k: stats.get(k) for k in
            ("images_per_sec_per_chip", "sec_per_step", "mfu",
             "achieved_tflops_per_chip", "flops_per_step")
        } if stats else None,
        # The paper's three-term split of the same trace (obs.trace_attr;
        # self-time op classification, or annotation buckets on platforms
        # that propagate them to device lanes).
        "attr": attr,
        **table,
    }
    os.makedirs(RESULTS, exist_ok=True)
    kind = args.kind or (
        jax.devices()[0].device_kind.replace(" ", "_")
        if not args.parse_only else "parsed")
    out = os.path.join(
        RESULTS, f"profile_{args.dnn}_{args.mode}_{kind}.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"out": out,
                      "steps_lane": report["steps_lane"],
                      "top5": report["top_ops"][:5]}))
    print(format_attr(attr))


if __name__ == "__main__":
    main()
