"""Profile the dense ResNet-50 train step and rank its time sinks.

Round-2 verdict weak #3: dense MFU 0.243 at bs=256 was "mediocre and
unexamined", and the dense baseline is the denominator of every ratio this
project reports. This tool captures a ``jax.profiler`` trace of the exact
benchmark step (same program as bench.py via benchmark.measure_throughput's
setup), parses the chrome-trace events host-side, and emits the top ops by
accumulated device time — the evidence needed to attack input-layout
transposes / BN / small-channel convs, or to write the measured-ceiling
note if nothing is attackable.

Usage (on the chip):
  python benchmarks/profile_step.py [--dnn resnet50] [--batch-size 256] \
      [--mode dense] [--steps 20]
Writes benchmarks/results/profile_<dnn>_<mode>_<device>.json (op table)
and leaves the raw trace under --trace-dir for TensorBoard.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def capture_trace(args, trace_dir: str) -> dict:
    import jax

    from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    cfg = BenchConfig(dnn=args.dnn, batch_size=args.batch_size,
                      min_seconds=0.5, density=args.density,
                      dtype=args.dtype)
    # One measured warm pass builds + compiles + runs the program and
    # returns the throughput context for the artifact.
    stats = measure_throughput(cfg, args.mode,
                               1.0 if args.mode == "dense" else args.density)
    # Second short pass under the profiler: reuse of the jit cache makes
    # this pure execution, which is what we want on the trace.
    with jax.profiler.trace(trace_dir):
        measure_throughput(cfg, args.mode,
                           1.0 if args.mode == "dense" else args.density)
    return stats


def parse_trace(trace_dir: str, top: int = 40) -> dict:
    """Aggregate device-lane event durations by op name from the chrome
    trace (.trace.json.gz). Host threads are excluded by keeping only
    processes whose name mentions the device / XLA lanes."""
    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise SystemExit(f"no trace found under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    # pid -> process name, from metadata events
    pnames = {e.get("pid"): e.get("args", {}).get("name", "")
              for e in events if e.get("name") == "process_name"}
    device_pids = {pid for pid, name in pnames.items()
                   if any(t in name.lower()
                          for t in ("tpu", "device", "xla", "/device"))}
    agg = collections.defaultdict(float)
    count = collections.defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        dur = float(e["dur"])  # microseconds
        agg[name] += dur
        count[name] += 1
        total += dur
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top]
    return {
        "trace_file": os.path.relpath(path, trace_dir),
        "total_device_us": round(total, 1),
        "top_ops": [
            {"name": n[:160], "total_us": round(us, 1),
             "calls": count[n],
             "pct": round(100 * us / total, 2) if total else None}
            for n, us in rows
        ],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--mode", default="dense")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--trace-dir", default="/tmp/gtopk_profile")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--parse-only", action="store_true",
                    help="skip capture; parse an existing --trace-dir")
    args = ap.parse_args()

    import jax

    if args.parse_only:
        stats = {}
    else:
        os.makedirs(args.trace_dir, exist_ok=True)
        stats = capture_trace(args, args.trace_dir)
    table = parse_trace(args.trace_dir, args.top)
    report = {
        "what": ("device-time op ranking of the benchmark step, parsed "
                 "from the jax.profiler chrome trace"),
        "dnn": args.dnn, "mode": args.mode,
        "batch_size": args.batch_size, "dtype": args.dtype,
        "throughput_context": {
            k: stats.get(k) for k in
            ("images_per_sec_per_chip", "sec_per_step", "mfu",
             "achieved_tflops_per_chip", "flops_per_step")
        } if stats else None,
        **table,
    }
    os.makedirs(RESULTS, exist_ok=True)
    kind = (jax.devices()[0].device_kind.replace(" ", "_")
            if not args.parse_only else "parsed")
    out = os.path.join(
        RESULTS, f"profile_{args.dnn}_{args.mode}_{kind}.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"out": out,
                      "total_device_us": report["total_device_us"],
                      "top5": report["top_ops"][:5]}))


if __name__ == "__main__":
    main()
