"""On-hardware convergence curves: dense vs gtopk vs allgather, same seed.

The reference's top-level correctness gate is convergence-as-test (SURVEY.md
§4: "does it still reach baseline accuracy at rho=0.001") — its paper
figures are accuracy-vs-epoch curves per workload. The CI suite proves the
same property cheaply on an 8-way virtual CPU mesh
(tests/test_convergence.py); this runner produces the committed
on-hardware artifact: identical-seed training runs per compression mode on
the real chip, loss sampled every ``--chunk`` steps, held-out eval at the
end, one JSONL row per sample.

Steps-to-threshold uses ONE shared absolute reference for every mode (the
dense run's first sampled loss, falling back to the max across modes), so
the cross-mode comparison is like-for-like; per-mode "fraction of my own
first sample" would compare different absolute loss levels whenever early
transients differ between modes.

Data is the deterministic synthetic CIFAR stand-in (learnable class-mean
signal — data/cifar.py) unless ``--data-dir`` points at the real pickles;
with one chip the gtopk collective is a no-op but error-feedback
select/repair runs at full production semantics, which is exactly the
convergence-relevant machinery (the multi-device collective itself is
oracle-tested and convergence-tested 8-way in CI).

Usage:
  python benchmarks/convergence_run.py --dnn resnet20 --steps 1200 \
      --modes dense,gtopk,allgather --density 0.001
Writes benchmarks/results/convergence_<dnn>_<device>.jsonl
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

THRESHOLD_FRACS = (0.5, 0.2, 0.1, 0.02)


def max_epochs_for(args) -> int:
    """Epochs the --steps budget spans — mode-independent, computed ONCE.

    max_epochs drives the LR schedule; leaving it at 1 for a multi-epoch
    fixed-step run would degenerate the CIFAR decay boundaries to step 0
    (constant LR). steps_per_epoch is pure shard arithmetic — one rank-0
    dataset through the SAME helper the Trainer uses
    (trainer.py::shard_steps_per_epoch), no throwaway Trainer build.
    """
    from gtopkssgd_tpu.data import get_dataset
    from gtopkssgd_tpu.trainer import TrainConfig, shard_steps_per_epoch

    rcfg = TrainConfig(
        dnn=args.dnn, batch_size=args.batch_size,
        nworkers=args.nworkers or jax.device_count(),
        data_dir=args.data_dir,
    ).resolved()
    ds = get_dataset(rcfg.dataset, split="train", batch_size=rcfg.batch_size,
                     rank=0, nworkers=rcfg.nworkers,
                     data_dir=rcfg.data_dir or None, seed=args.seed)
    spe = shard_steps_per_epoch(ds, rcfg.batch_size, rcfg.nsteps_update)
    return max(1, math.ceil(args.steps / spe))


def run_mode(args, mode: str, density: float, max_epochs: int,
             stream=None):
    """Train one mode; returns (curve_rows, summary) — steps-to-threshold
    is computed later in main() against the shared reference. When
    ``stream`` is given, every curve row is also appended+flushed to it as
    it is measured: a multi-mode run is tens of minutes of compute, and a
    timeout/preemption mid-run must not lose the modes already measured
    (learned the hard way — a 50-minute 3-mode run died in mode 3 with
    nothing on disk)."""
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    # Arm syntax: a compression mode optionally tagged with mitigation
    # suffixes — "gtopk+warmup" (1 dense-warmup epoch) and/or
    # "gtopk+corr" (DGC momentum correction) — so the verdict's arm set
    # {dense, gtopk, gtopk+warmup, layerwise, correction} is expressible
    # from the CLI without bespoke flags per arm.
    parts = mode.split("+")
    base_mode, extra = parts[0], {}
    for tag in parts[1:]:
        if tag == "warmup":
            extra["dense_warmup_epochs"] = 1
        elif tag == "corr":
            extra["momentum_correction"] = True
        elif tag in ("exact", "approx", "blockwise", "pallas", "simrecall"):
            # Selection-kernel A/B arms (round-3 verdict weak #4: no
            # conv-net had converged through the production approx path;
            # "gtopk+approx" forces the kernel the >2^20-param auto
            # route uses, at any model size). "simrecall" is the
            # CPU-runnable pessimistic stand-in for approx (the CPU
            # backend lowers approx_max_k to an exact top-k, so +approx
            # arms on the CPU mesh silently test exact selection —
            # ops/topk.py::simrecall_topk_abs).
            extra["topk_method"] = tag
        elif tag in ("int8wire", "fp8wire"):
            # Wire-codec A/B arms: "gtopk+int8wire" runs the identical
            # schedule with the quantized on-wire codec so the verdict
            # can pin the final-loss delta of codec error (which folds
            # into the error-feedback residual) against the fp32 wire.
            extra["wire_codec"] = tag[:-4]
        else:
            raise SystemExit(f"unknown arm suffix {tag!r} in {mode!r} "
                             "(know: warmup, corr, exact, approx, "
                             "blockwise, pallas, simrecall, int8wire, "
                             "fp8wire)")
    density = 1.0 if base_mode in ("dense", "none") else density
    cfg = TrainConfig(
        dnn=args.dnn,
        batch_size=args.batch_size,
        nworkers=args.nworkers or jax.device_count(),
        compression=base_mode,
        density=density,
        seed=args.seed,
        max_epochs=max_epochs,
        log_interval=10_000_000,  # curve sampling happens here, not in logs
        eval_batches=args.eval_batches,
        data_dir=args.data_dir,
        dtype=args.dtype,
        synth_hard=args.synth_hard,
        **extra,
    )
    curve, losses = [], []
    with Trainer(cfg) as trainer:
        done = 0
        while done < args.steps:
            n = min(args.chunk, args.steps - done)
            stats = trainer.train(n)
            done += n
            losses.append(stats["loss"])
            row = {
                "mode": mode, "density": density, "step": done,
                "loss": round(stats["loss"], 5),
                "throughput": round(stats["throughput"], 1),
            }
            curve.append(row)
            if stream is not None:
                stream.write(json.dumps(row) + "\n")
                stream.flush()
            print(f"  {mode:10s} step {done:5d}  loss {stats['loss']:.4f}",
                  flush=True)
        ev = trainer.test()
    final = sum(losses[-3:]) / min(3, len(losses))  # smooth tail
    summary = {"mode": mode, "density": density,
               "final_loss": round(final, 5),
               **{k: round(float(v), 5) for k, v in ev.items()}}
    return curve, summary


DROP_FRACS = (0.5, 0.8, 0.9, 0.98)


def _first_step_rolling_below(curve, thr: float):
    """First step at which the ROLLING-3 mean of sampled losses is <= thr
    (None if never, and None for an empty curve). train(n) reports only
    the chunk's last micro-step loss, so a single-sample criterion
    rewards transient dips (and forgives rebounds); the 3-sample window
    is the same smoothing final_loss uses. The window must be FULL — a
    truncated window at the curve's start would re-admit exactly the
    single-sample dip the smoothing exists to reject — so the earliest
    reportable crossing is the window-th sample."""
    steps = [r["step"] for r in curve]
    losses = [r["loss"] for r in curve]
    w = min(3, len(losses))
    if w == 0:
        return None
    return next(
        (steps[i] for i in range(w - 1, len(losses))
         if sum(losses[i - w + 1:i + 1]) / w <= thr),
        None,
    )


def steps_to_drop_fracs(curve, drop_target: dict):
    """Steps to cover each fraction of the DENSE arm's achieved
    improvement (start -> final). The absolute thresholds of
    steps_to_thresholds suit CIFAR (loss -> ~0), but are meaningless for
    workloads with a high irreducible loss floor — PTB's LM loss bottoms
    out near 4.3, so "0.5x the initial loss" never happens and every
    field is null (the round-3 LSTM artifact's original rows). Measuring
    against the dense drop asks the comparable question on every
    workload: how fast does each mode cover the improvement dense
    achieves on the same budget?"""
    start, total = drop_target["start"], drop_target["drop"]
    return {
        f"steps_to_{frac}_of_dense_drop":
            _first_step_rolling_below(curve, start - frac * total)
        for frac in DROP_FRACS
    }


def steps_to_thresholds(curve, reference_loss: float):
    """Steps to cross absolute fractions of the shared reference loss
    (the dense curve's first sample; see _first_step_rolling_below for
    the rolling-window rule)."""
    return {
        f"steps_to_{frac}x_ref":
            _first_step_rolling_below(curve, reference_loss * frac)
        for frac in THRESHOLD_FRACS
    }


def attach_thresholds(summaries, curves):
    """(Re)compute both threshold families onto the summary rows in place:
    absolute fractions of the shared reference loss AND fractions of the
    dense arm's achieved drop. Returns the shared reference loss. Stale
    steps_to_* keys are replaced wholesale so --recompute never leaves a
    mixed-method row."""
    dense = next(
        (s for s in summaries if s["mode"] in ("dense", "none")), None)
    firsts = {m: c[0]["loss"] for m, c in curves.items() if c}
    if not firsts:
        raise SystemExit("no curve rows at all — nothing to threshold")
    ref = firsts.get(dense["mode"]) if dense else None
    if ref is None:
        ref = max(firsts.values())
    drop_target = None
    if dense is not None and curves.get(dense["mode"]):
        dstart = curves[dense["mode"]][0]["loss"]
        drop_target = {"start": dstart,
                       "drop": dstart - dense["final_loss"]}
    for s in summaries:
        for key in [k for k in s if k.startswith("steps_to")]:
            del s[key]
        s.update(steps_to_thresholds(curves[s["mode"]], ref))
        if drop_target is not None and drop_target["drop"] > 0:
            s.update(steps_to_drop_fracs(curves[s["mode"]], drop_target))
        if dense is not None:
            s["final_loss_vs_dense"] = round(
                s["final_loss"] / max(dense["final_loss"], 1e-9), 4)
    return ref


def _write_tail(fh, summaries, report):
    """Summary + report serialization shared by fresh runs and --recompute
    so both always emit the same artifact shape."""
    for s in summaries:
        fh.write(json.dumps({**s, "kind": "summary"}) + "\n")
    fh.write(json.dumps({**report, "kind": "report"}) + "\n")


def recompute_report(path: str) -> dict:
    """Rebuild the summary/report rows of an existing artifact from its
    own curve rows (e.g. after a threshold-method change), preserving
    measured fields (final_loss, eval metrics, provenance notes) and
    replacing only the derived steps_to_* columns."""
    import collections

    with open(path) as fh:
        rows = [json.loads(line) for line in fh if line.strip()]
    curves = collections.defaultdict(list)
    summaries, report, extras = [], None, []
    for r in rows:
        kind = r.pop("kind", None)
        if kind == "summary":
            summaries.append(r)
        elif kind == "report":
            report = r
        elif kind is None and "step" in r and "loss" in r and "mode" in r:
            curves[r["mode"]].append(r)
        else:
            # Pass provenance rows through byte-identically: re-add the
            # kind tag only if the row actually had one.
            extras.append({**r, "kind": kind} if kind is not None else r)
    if report is None or not summaries:
        raise SystemExit(f"{path}: no report/summary rows to recompute")
    ref = attach_thresholds(summaries, curves)
    report["modes"] = summaries
    report["threshold_reference_loss"] = round(ref, 5)
    report["recomputed"] = ("steps_to_* columns rebuilt from the stored "
                            "curve rows by --recompute; measured fields "
                            "untouched")
    # Same crash-durability rule as main(): never truncate the only copy
    # of a measured artifact — write a sibling and rename on success.
    partial = path + ".recompute"
    with open(partial, "w") as fh:
        for mode_rows in curves.values():
            for r in mode_rows:
                fh.write(json.dumps(r) + "\n")
        for r in extras:
            fh.write(json.dumps(r) + "\n")
        _write_tail(fh, summaries, report)
    os.replace(partial, path)
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet20")
    ap.add_argument("--steps", type=int, default=1200)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--modes", default="dense,gtopk,allgather")
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--nworkers", type=int, default=0)
    ap.add_argument("--eval-batches", type=int, default=16)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--out", default="")
    ap.add_argument("--synth-hard", action="store_true",
                    help="synthetic CIFAR: the discriminative variant "
                         "(weak spatial class signal + 10%% train label "
                         "noise) so arms can SEPARATE on val accuracy — "
                         "the easy task pins every arm at val_top1=1.0 "
                         "(round-4 verdict: accuracy parity was "
                         "unfalsifiable)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="compute dtype for every arm (the bench headline "
                         "runs bfloat16; a bf16-vs-f32 convergence A/B "
                         "backs that config's correctness)")
    ap.add_argument("--recompute", default="",
                    help="rebuild an existing artifact's steps_to_* "
                         "columns from its stored curve rows, then exit "
                         "(no training, no device)")
    ap.add_argument("--platform", default="", choices=["", "cpu8", "cpu2"],
                    help="cpu8/cpu2 = force an 8- or 2-way virtual CPU "
                         "mesh in-process (this machine's sitecustomize "
                         "overrides JAX_PLATFORMS at interpreter start, "
                         "so an env-var-only 'cpu' silently dials the "
                         "accelerator tunnel — same workaround as "
                         "tests/conftest.py; cpu2 is the measured-fastest "
                         "long-run config on this 1-core host)")
    args = ap.parse_args()

    if args.recompute:
        print(json.dumps(recompute_report(args.recompute)))
        return

    if args.platform in ("cpu8", "cpu2"):
        from gtopkssgd_tpu.utils import force_cpu_mesh

        force_cpu_mesh(int(args.platform[3:]))

    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    epochs = max_epochs_for(args)
    device_tag = (f"cpu_mesh{args.platform[3:]}" if args.platform else
                  jax.devices()[0].device_kind.replace(" ", "_"))
    # The dtype is an artifact dimension: a bf16 run must not clobber the
    # f32 capture of the same dnn/device.
    dtype_tag = "" if args.dtype == "float32" else "_bf16"
    hard_tag = "_hard" if args.synth_hard else ""
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results",
        f"convergence_{args.dnn}{dtype_tag}{hard_tag}_{device_tag}.jsonl",
    )
    # Stream to a .partial sibling and rename on success: crash-durability
    # for THIS run's rows without truncating a previous complete artifact
    # at time zero (a re-run that dies in mode 1 must not destroy the last
    # good capture).
    partial = out + ".partial"
    curves, summaries = {}, []
    with open(partial, "w") as fh:
        # Self-describing artifact: the same manifest header metrics.jsonl
        # carries (config hash over the argparse namespace, backend, git
        # sha). --recompute passes it through untouched as an extras row.
        from gtopkssgd_tpu.obs.manifest import run_manifest

        fh.write(json.dumps(
            {**run_manifest(vars(args)), "kind": "manifest"}) + "\n")
        fh.flush()
        for mode in args.modes.split(","):
            mode = mode.strip()
            print(f"[convergence] {args.dnn} {mode} rho={args.density} "
                  f"steps={args.steps} epochs={epochs}", flush=True)
            curve, summary = run_mode(args, mode, args.density, epochs,
                                      stream=fh)
            curves[mode] = curve
            summaries.append(summary)

        # Both threshold families (absolute-reference + dense-drop) live
        # in attach_thresholds, shared with --recompute.
        ref = attach_thresholds(summaries, curves)

        report = {"dnn": args.dnn, "steps": args.steps,
                  "batch_size": args.batch_size, "dtype": args.dtype,
                  "synth_hard": args.synth_hard,
                  "device_kind": jax.devices()[0].device_kind,
                  "nworkers": args.nworkers or jax.device_count(),
                  "threshold_reference_loss": round(ref, 5),
                  "modes": summaries}
        _write_tail(fh, summaries, report)
    os.replace(partial, out)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
