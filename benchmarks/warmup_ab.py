"""Cold-start mitigation A/B through the production Trainer — the generator
for benchmarks/results/warmup_ab_cpu_mesh8.json.

Round 2 committed that artifact without its generator; this script makes
every arm reproducible and adds the ``restore_rejected_u_ablation`` arm the
momentum-correction masking NOTE in optimizer.py cites: identical to
``momentum_correction_cold_start`` except a locally-picked but
globally-rejected coordinate's velocity u is RESTORED alongside its repaired
residual value (``TrainConfig.restore_rejected_u`` → the optimizer's
``_restore_rejected_u`` ablation knob). The shipped semantics mask u at the
LOCAL selection; this arm measures the alternative so the design choice is
backed by a committed number, not a claim.

Protocol (unchanged from the round-2 capture): 8-way SPMD over a virtual CPU
mesh (REAL collectives), ResNet-20 / synthetic CIFAR, rho=0.001, batch
4/worker, 200 steps, identical seed; loss sampled every 25 steps, held-out
eval at the end.

Usage:
  python benchmarks/warmup_ab.py --arms restore_rejected_u_ablation
Arms merge into the existing artifact (existing entries are preserved).

The 8-way virtual CPU mesh is forced IN-SCRIPT (not via the shell): this
machine's sitecustomize registers the tunneled accelerator plugin at
interpreter start and overrides JAX_PLATFORMS, so an env-var-only
``JAX_PLATFORMS=cpu`` silently ends up dialing the tunnel — and blocks
forever when it is down (learned the hard way; same workaround as
tests/conftest.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gtopkssgd_tpu.utils import force_cpu_mesh  # noqa: E402

force_cpu_mesh(8)

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")
ARTIFACT = os.path.join(RESULTS, "warmup_ab_cpu_mesh8.json")

# arm name -> TrainConfig overrides on the shared base config
ARMS = {
    "cold_start": {},
    "dense_warmup_1_epoch": {"dense_warmup_epochs": 1},
    "layerwise_cold_start": {"compression": "gtopk_layerwise"},
    "layerwise_dense_warmup_1_epoch": {
        "compression": "gtopk_layerwise", "dense_warmup_epochs": 1},
    "momentum_correction_cold_start": {"momentum_correction": True},
    "layerwise_momentum_correction_cold_start": {
        "compression": "gtopk_layerwise", "momentum_correction": True},
    "restore_rejected_u_ablation": {
        "momentum_correction": True, "restore_rejected_u": True},
    # Task-5 diagnostic (round-3): is the layerwise x correction deficit
    # caused by local masking chopping tiny-leaf velocities every step?
    "layerwise_restore_rejected_u_ablation": {
        "compression": "gtopk_layerwise", "momentum_correction": True,
        "restore_rejected_u": True},
}


def run_arm(name: str, args) -> dict:
    from gtopkssgd_tpu.trainer import TrainConfig, Trainer

    kw = dict(
        dnn="resnet20", nworkers=8, compression="gtopk",
        density=args.density, batch_size=4, seed=args.seed,
        log_interval=10_000_000, eval_batches=args.eval_batches,
    )
    kw.update(ARMS[name])
    cfg = TrainConfig(**kw)
    # Same max_epochs-from-steps arithmetic as convergence_run.py so the LR
    # schedule sees the true epoch span instead of a constant LR.
    from gtopkssgd_tpu.data import get_dataset
    from gtopkssgd_tpu.trainer import shard_steps_per_epoch

    rcfg = cfg.resolved()
    ds = get_dataset(rcfg.dataset, split="train", batch_size=rcfg.batch_size,
                     rank=0, nworkers=rcfg.nworkers, seed=args.seed)
    spe = shard_steps_per_epoch(ds, rcfg.batch_size, rcfg.nsteps_update)
    cfg.max_epochs = max(1, math.ceil(args.steps / spe))

    losses = []
    with Trainer(cfg) as trainer:
        done = 0
        while done < args.steps:
            n = min(25, args.steps - done)
            stats = trainer.train(n)
            done += n
            losses.append(round(stats["loss"], 3))
            print(f"  {name:42s} step {done:4d} loss {stats['loss']:.4f}",
                  flush=True)
        ev = trainer.test()
    return {"losses_every_25_steps": losses,
            "val_top1": round(float(ev.get("val_top1", 0.0)), 3),
            "val_loss": round(float(ev.get("val_loss", float("nan"))), 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", default="restore_rejected_u_ablation",
                    help=f"comma list from {sorted(ARMS)}")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--eval-batches", type=int, default=16)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()

    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    doc = {}
    if os.path.exists(ARTIFACT):
        with open(ARTIFACT) as fh:
            doc = json.load(fh)

    for name in args.arms.split(","):
        name = name.strip()
        if name not in ARMS:
            raise SystemExit(f"unknown arm {name!r}; pick from {sorted(ARMS)}")
        print(f"[warmup_ab] arm={name} steps={args.steps} "
              f"rho={args.density}", flush=True)
        # Merge INTO any existing entry: curated fields added by hand
        # (e.g. the 'note' explanations the optimizer docstrings cite)
        # survive a re-measurement instead of being silently dropped.
        entry = doc.get(name, {})
        entry.update(run_arm(name, args))
        doc[name] = entry

    tmp = ARTIFACT + ".partial"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, ARTIFACT)
    print(json.dumps({k: v for k, v in doc.items()
                      if isinstance(v, dict) and "val_top1" in v}))


if __name__ == "__main__":
    main()
