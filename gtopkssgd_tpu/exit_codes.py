"""Single-source process exit-code registry.

Drivers, retry loops, and the multihost test harness classify finished
runs by return code WITHOUT parsing logs, so these values are a
cross-tool contract. Every ``sys.exit`` / ``SystemExit`` / ``os._exit``
literal in the tree must come from here — enforced statically by
graftlint's ``exit-code`` rule (``python -m gtopkssgd_tpu.analysis``),
which also rejects ``*_EXIT_CODE`` constants minted outside this module
and collisions inside it.

This module is import-cost-free (no jax, no package deps): the analyzer
reads it by AST and the consumers (watchdog, events, preempt, bench
scripts) import it at process start.
"""

from __future__ import annotations

EXIT_OK = 0                  # run completed
EXIT_ERROR = 1               # generic failure (uncaught exception,
                             # SystemExit("message"), lint findings)
EXIT_USAGE = 2               # CLI usage / unreadable input (argparse's
                             # own convention; report gate I/O errors)
EXIT_BENCH_TUNNEL_DEAD = 3   # benchmark harness: accelerator backend
                             # failed to initialize inside its timeout
                             # (benchmarks/mfu_ablation.py; the historic
                             # BENCH_r02-r05 dead-tunnel signature)
EXIT_STALL = 43              # dispatch-stall watchdog fired
                             # (obs/watchdog.py: a dispatched step made
                             # no host-visible progress by the deadline)
EXIT_ANOMALY_HALT = 44       # --obs-halt-on anomaly fail-fast
                             # (obs/events.py AnomalyHalt)
EXIT_PREEMPTED = 45          # SIGTERM/SIGINT intercepted, emergency
                             # checkpoint durable; relaunch with
                             # --resume (resilience/preempt.py)
EXIT_RESIZE_RESTART = 46     # coordinated elastic resize: state drained
                             # + checkpointed, lineage file rewritten;
                             # relaunch with --resume --elastic on the
                             # new process set (resilience/elastic.py) —
                             # distinct from 45, which means "this
                             # process was told to die", not "the fleet
                             # is re-forming"
EXIT_MULTIHOST_SKIP = 99     # multi-process probe unsupported on this
                             # build (tests/test_multihost.py,
                             # benchmarks/dcn_probe.py: designed skip,
                             # not a failure)

REGISTRY = {
    EXIT_OK: "run completed",
    EXIT_ERROR: "generic failure",
    EXIT_USAGE: "CLI usage error / unreadable input",
    EXIT_BENCH_TUNNEL_DEAD: "benchmark backend init timeout "
                            "(dead accelerator tunnel)",
    EXIT_STALL: "dispatch-stall watchdog fired",
    EXIT_ANOMALY_HALT: "anomaly monitor fail-fast (--obs-halt-on)",
    EXIT_PREEMPTED: "preempted after emergency checkpoint "
                    "(resume with --resume)",
    EXIT_RESIZE_RESTART: "elastic resize: checkpoint + lineage durable "
                         "(relaunch with --resume --elastic on new P)",
    EXIT_MULTIHOST_SKIP: "multi-process probe unsupported: "
                         "designed skip",
}


def describe(code: int) -> str:
    """Human name for an exit code (unknown codes say so — the lint
    rule should have made them impossible)."""
    return REGISTRY.get(code, f"unregistered exit code {code}")
