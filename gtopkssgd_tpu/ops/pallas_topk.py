"""Pallas TPU kernels for large-N magnitude top-k (the reference's
`torch.topk` CUDA obligation — SURVEY.md §2 native table, §7 step 6).

Two kernel families share one VMEM-block scan skeleton:

1. **Threshold counting** ("threshold-estimate + compact", the strategy
   SURVEY.md names): exact top-k over a flat f32[N] needs a selection
   threshold tau = the k-th largest |x|. We find tau by monotone
   multisection — each round evaluates ``count(|x| >= t)`` for 8 candidate
   thresholds — then compact the <= cap surviving elements and run one
   small exact `lax.top_k` over them (see ops.topk.threshold_topk_abs).
   XLA would issue 8 separate N-element reductions (8 HBM passes); the
   kernel fuses them into ONE pass — read a VMEM block once, compare
   against all 8 thresholds, accumulate 8 counts. The TPU grid is
   sequential per core, so cross-block accumulation into the same output
   block is safe (standard grid-accumulation pattern).

2. **Fused two-stage stage 1** (generalized two-stage approximate top-k,
   arXiv:2506.04165 lineage): the same one-pass block scan instead emits
   per-bucket partial top-k' candidates — bucket = (sublane-group, lane),
   top-1 per bucket, L = grid * groups * 128 buckets total — AND the same
   8-threshold counts, AND reads ``grad + residual`` as two operands so
   the error-feedback accumulate (compression.py's ``acc = grad +
   residual``) fuses into the selection's HBM pass instead of costing its
   own N-sized read+write. Stage 2 (a small exact `lax.top_k` over the
   <= L candidates) runs outside the kernel in ops.topk.twostage_topk_abs.
   Missing a true top-k element requires it to collide with a LARGER
   element in its bucket, so expected recall ~= 1 - k/(2L); the default
   oversample (ops.topk.TWOSTAGE_OVERSAMPLE) targets recall >= 0.95, and
   error feedback provably absorbs the misses (arXiv:1911.08772 — the
   same argument that justifies the `approx` method).

`lax.top_k` itself cannot lower inside a Pallas TPU kernel (verified:
NotImplementedError in the pinned jax), which is exactly why both
families keep the selection *reduction* (counts / per-bucket maxima) in
the kernel and the final small reselect outside it.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

NUM_THRESHOLDS = 8
# One grid step processes BLOCK_ROWS x 128 elements from VMEM.
BLOCK_ROWS = 2048
_LANES = 128
_BLOCK = BLOCK_ROWS * _LANES


def _count_kernel(thr_ref, x_ref, out_ref):
    """Accumulate counts of |x_block| >= thr for all 8 thresholds.

    thr_ref: SMEM (NUM_THRESHOLDS,) f32 — candidate thresholds.
    x_ref:   VMEM (BLOCK_ROWS, 128) f32 — this grid step's block (|x|,
             pre-padded with -1 which no threshold >= 0 counts).
    out_ref: SMEM (1, NUM_THRESHOLDS) i32 — running counts (same block for
             every grid step: sequential accumulation; scalar stores must
             target SMEM on TPU).
    """
    first = pl.program_id(0) == 0
    mag = x_ref[:]

    def body(i, _):
        t = thr_ref[i]
        c = jnp.sum((mag >= t).astype(jnp.int32))
        prev = jnp.where(first, 0, out_ref[0, i])  # SMEM: scalar ops only
        out_ref[0, i] = prev + c
        return 0

    jax.lax.fori_loop(0, NUM_THRESHOLDS, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def multi_threshold_count(
    mag: Array, thresholds: Array, *, interpret: bool = False
) -> Array:
    """counts[i] = #{ j : mag[j] >= thresholds[i] } in ONE memory pass.

    mag: f32[N] (non-negative; callers pass |x|). thresholds: f32[8].
    """
    n = mag.shape[0]
    nblocks = max(1, -(-n // _BLOCK))
    padded = nblocks * _BLOCK
    # Pad with -1: strictly below any threshold >= 0, so never counted.
    mag2 = jnp.pad(mag, (0, padded - n), constant_values=-1.0)
    mag2 = mag2.reshape(nblocks * BLOCK_ROWS, _LANES)
    counts = pl.pallas_call(
        _count_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (BLOCK_ROWS, _LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, NUM_THRESHOLDS), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, NUM_THRESHOLDS), jnp.int32),
        interpret=interpret,
    )(thresholds, mag2)
    return counts[0]


def pallas_topk_abs(x: Array, k: int, *, interpret: bool = False
                    ) -> Tuple[Array, Array]:
    """Exact (up to boundary ties) magnitude top-k using the Pallas counting
    kernel for threshold search. Same contract as ops.topk.topk_abs."""
    from gtopkssgd_tpu.ops.topk import threshold_topk_abs

    return threshold_topk_abs(
        x, k,
        count_fn=functools.partial(multi_threshold_count, interpret=interpret),
    )


# --------------------------------------------------------------------------
# Fused two-stage stage 1: per-bucket candidates (+ optional counts,
# + optional error-feedback residual) in one HBM pass over the gradient.
# --------------------------------------------------------------------------


def _make_stage1_kernel(n: int, groups: int,
                        with_residual: bool, with_counts: bool):
    """Build the stage-1 kernel for a given flat length / bucket layout.

    Buckets: each grid block's (BLOCK_ROWS, 128) tile is split into
    `groups` row-groups of rpg = BLOCK_ROWS/groups sublanes; one bucket is
    (row-group, lane) — rpg elements at stride 128 in the flat order, so
    contiguous layer slices spread across 128 lanes (adjacent flat indices
    land in different buckets). The kernel emits each bucket's max-|acc|
    element (signed value + global flat index) as a candidate. Everything
    is a lane-aligned max/select reduction — no in-kernel top-k, which
    cannot lower on TPU (module docstring).

    Padding/tail: elements with global index >= n get magnitude -1, which
    loses to every real element (real magnitudes are >= 0). A bucket that
    is ENTIRELY padding emits its first slot: index >= n (the caller
    sentinels it) and value 0 (the wrapper zero-pads the operands).
    """
    rpg = BLOCK_ROWS // groups

    def kernel(*refs):
        refs = list(refs)
        thr_ref = refs.pop(0) if with_counts else None
        g_ref = refs.pop(0)
        r_ref = refs.pop(0) if with_residual else None
        val_ref, idx_ref = refs[0], refs[1]
        cnt_ref = refs[2] if with_counts else None

        i = pl.program_id(0)
        acc = g_ref[:]
        if with_residual:
            # The error-feedback accumulate, fused into the selection's
            # read of the gradient block — acc never hits HBM.
            acc = acc + r_ref[:]
        rows = lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, _LANES), 0)
        lanes = lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, _LANES), 1)
        eidx = i * _BLOCK + rows * _LANES + lanes
        mag = jnp.where(eidx < n, jnp.abs(acc), -1.0)

        if with_counts:
            # Same accumulation pattern as _count_kernel, sharing this
            # pass's read of the block (grid is sequential per core).
            first = i == 0

            def cbody(t, _):
                c = jnp.sum((mag >= thr_ref[t]).astype(jnp.int32))
                prev = jnp.where(first, 0, cnt_ref[0, t])
                cnt_ref[0, t] = prev + c
                return 0

            lax.fori_loop(0, NUM_THRESHOLDS, cbody, 0)

        # Per-bucket argmax via reshape: (groups, rpg, 128), reduce the
        # middle (row-within-group) axis. First-max-row tie rule keeps
        # the winner deterministic (lax.top_k's lowest-index-first class).
        mag3 = mag.reshape(groups, rpg, _LANES)
        acc3 = acc.reshape(groups, rpg, _LANES)
        mx = jnp.max(mag3, axis=1)  # (groups, 128)
        riota = lax.broadcasted_iota(jnp.int32, (groups, rpg, _LANES), 1)
        win = jnp.min(
            jnp.where(mag3 == mx[:, None, :], riota, rpg), axis=1)
        val = jnp.sum(
            jnp.where(riota == win[:, None, :], acc3, 0.0), axis=1)
        grow = lax.broadcasted_iota(jnp.int32, (groups, _LANES), 0)
        lane2 = lax.broadcasted_iota(jnp.int32, (groups, _LANES), 1)
        gidx = i * _BLOCK + (grow * rpg + win) * _LANES + lane2
        val_ref[:] = val
        idx_ref[:] = gidx

    return kernel


@functools.partial(jax.jit, static_argnames=("groups", "interpret"))
def fused_stage1_candidates(
    grad: Array,
    thresholds: Optional[Array] = None,
    residual: Optional[Array] = None,
    *,
    groups: int = 8,
    interpret: bool = False,
) -> Tuple[Array, Array, Optional[Array]]:
    """One fused pass over `grad` (+ `residual`): per-bucket candidates.

    Returns (cand_val f32[L], cand_idx i32[L], counts i32[8] | None) with
    L = nblocks * groups * 128 buckets. `groups` must divide BLOCK_ROWS.
    Candidate indices >= n mark padding buckets (value 0). When
    `thresholds` (f32[8]) is given, the same pass also accumulates the
    multisection counts `#{|grad+residual| >= thr}` — the _count_kernel
    obligation — without a second read of x. When `residual` is given,
    the kernel reads grad and residual and forms acc = grad + residual
    in VMEM: the error-feedback accumulate costs no extra HBM pass and
    the flat [N] accumulator is never materialized.
    """
    n = grad.shape[0]
    if BLOCK_ROWS % groups != 0:
        raise ValueError(f"groups={groups} must divide {BLOCK_ROWS}")
    nblocks = max(1, -(-n // _BLOCK))
    padded = nblocks * _BLOCK
    with_counts = thresholds is not None
    with_residual = residual is not None

    def tile(v):
        return jnp.pad(v, (0, padded - n)).reshape(
            nblocks * BLOCK_ROWS, _LANES)

    vmem_spec = pl.BlockSpec(
        (BLOCK_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    operands, in_specs = [], []
    if with_counts:
        operands.append(thresholds)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    operands.append(tile(grad))
    in_specs.append(vmem_spec)
    if with_residual:
        operands.append(tile(residual))
        in_specs.append(vmem_spec)

    cand_spec = pl.BlockSpec(
        (groups, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_shape = [
        jax.ShapeDtypeStruct((nblocks * groups, _LANES), grad.dtype),
        jax.ShapeDtypeStruct((nblocks * groups, _LANES), jnp.int32),
    ]
    out_specs = [cand_spec, cand_spec]
    if with_counts:
        out_shape.append(
            jax.ShapeDtypeStruct((1, NUM_THRESHOLDS), jnp.int32))
        out_specs.append(pl.BlockSpec(
            (1, NUM_THRESHOLDS), lambda i: (0, 0),
            memory_space=pltpu.SMEM))

    out = pl.pallas_call(
        _make_stage1_kernel(n, groups, with_residual, with_counts),
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    cand_val = out[0].reshape(-1)
    cand_idx = out[1].reshape(-1)
    counts = out[2][0] if with_counts else None
    return cand_val, cand_idx, counts


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_multi_threshold_count(
    grad: Array,
    thresholds: Array,
    residual: Optional[Array] = None,
    *,
    interpret: bool = False,
) -> Array:
    """multi_threshold_count over |grad + residual| without materializing
    the accumulator: counts[i] = #{ j : |grad[j]+residual[j]| >= thr[i] }
    in one fused pass over both operands. With residual=None this is
    multi_threshold_count(|grad|, ...)."""
    if residual is None:
        return multi_threshold_count(
            jnp.abs(grad), thresholds, interpret=interpret)
    n = grad.shape[0]
    nblocks = max(1, -(-n // _BLOCK))
    padded = nblocks * _BLOCK

    def tile(v):
        return jnp.pad(v, (0, padded - n)).reshape(
            nblocks * BLOCK_ROWS, _LANES)

    def kernel(thr_ref, g_ref, r_ref, out_ref):
        i = pl.program_id(0)
        first = i == 0
        rows = lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, _LANES), 0)
        lanes = lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, _LANES), 1)
        eidx = i * _BLOCK + rows * _LANES + lanes
        mag = jnp.where(eidx < n, jnp.abs(g_ref[:] + r_ref[:]), -1.0)

        def body(t, _):
            c = jnp.sum((mag >= thr_ref[t]).astype(jnp.int32))
            prev = jnp.where(first, 0, out_ref[0, t])
            out_ref[0, t] = prev + c
            return 0

        lax.fori_loop(0, NUM_THRESHOLDS, body, 0)

    vmem_spec = pl.BlockSpec(
        (BLOCK_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    counts = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  vmem_spec, vmem_spec],
        out_specs=pl.BlockSpec(
            (1, NUM_THRESHOLDS), lambda i: (0, 0),
            memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, NUM_THRESHOLDS), jnp.int32),
        interpret=interpret,
    )(thresholds, tile(grad), tile(residual))
    return counts[0]
