"""Pallas TPU kernel for large-N magnitude top-k (torch.topk CUDA parity).

Status: the dedicated kernel is not implemented yet; `select_topk(...,
method="pallas")` raises with a pointer to the supported methods. The lax
formulations in ops/topk.py ("exact"/"blockwise") are the production paths
until profiling on hardware justifies the hand-written kernel (SURVEY.md §7
build-order step 6).
"""

from __future__ import annotations

from typing import Tuple

import jax

Array = jax.Array


def pallas_topk_abs(x: Array, k: int) -> Tuple[Array, Array]:
    raise NotImplementedError(
        "the Pallas top-k kernel is not implemented yet; use "
        "method='blockwise' (exact, TPU-friendly) or 'exact'"
    )
