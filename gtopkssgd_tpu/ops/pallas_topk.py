"""Pallas TPU kernels for large-N magnitude top-k (the reference's
`torch.topk` CUDA obligation — SURVEY.md §2 native table, §7 step 6).

Design ("threshold-estimate + compact", the strategy SURVEY.md names):
exact top-k over a flat f32[N] needs a selection threshold tau = the k-th
largest |x|. We find tau by monotone multisection — each round evaluates
``count(|x| >= t)`` for 8 candidate thresholds — then compact the <= cap
surviving elements and run one small exact `lax.top_k` over them (see
ops.topk.threshold_topk_abs for the full algorithm).

The hot primitive is the counting pass: 8 thresholds x one full read of x.
XLA would issue 8 separate N-element reductions (8 HBM passes); the Pallas
kernel below fuses them into ONE pass — read a VMEM block once, compare
against all 8 thresholds, accumulate 8 counts. The TPU grid is sequential
per core, so cross-block accumulation into the same output block is safe
(standard grid-accumulation pattern).

`lax.top_k` itself cannot lower inside a Pallas TPU kernel (verified:
NotImplementedError in the pinned jax), which is exactly why the kernel
computes threshold counts instead of doing in-kernel selection.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

NUM_THRESHOLDS = 8
# One grid step processes BLOCK_ROWS x 128 elements from VMEM.
BLOCK_ROWS = 2048
_LANES = 128
_BLOCK = BLOCK_ROWS * _LANES


def _count_kernel(thr_ref, x_ref, out_ref):
    """Accumulate counts of |x_block| >= thr for all 8 thresholds.

    thr_ref: SMEM (NUM_THRESHOLDS,) f32 — candidate thresholds.
    x_ref:   VMEM (BLOCK_ROWS, 128) f32 — this grid step's block (|x|,
             pre-padded with -1 which no threshold >= 0 counts).
    out_ref: SMEM (1, NUM_THRESHOLDS) i32 — running counts (same block for
             every grid step: sequential accumulation; scalar stores must
             target SMEM on TPU).
    """
    first = pl.program_id(0) == 0
    mag = x_ref[:]

    def body(i, _):
        t = thr_ref[i]
        c = jnp.sum((mag >= t).astype(jnp.int32))
        prev = jnp.where(first, 0, out_ref[0, i])  # SMEM: scalar ops only
        out_ref[0, i] = prev + c
        return 0

    jax.lax.fori_loop(0, NUM_THRESHOLDS, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def multi_threshold_count(
    mag: Array, thresholds: Array, *, interpret: bool = False
) -> Array:
    """counts[i] = #{ j : mag[j] >= thresholds[i] } in ONE memory pass.

    mag: f32[N] (non-negative; callers pass |x|). thresholds: f32[8].
    """
    n = mag.shape[0]
    nblocks = max(1, -(-n // _BLOCK))
    padded = nblocks * _BLOCK
    # Pad with -1: strictly below any threshold >= 0, so never counted.
    mag2 = jnp.pad(mag, (0, padded - n), constant_values=-1.0)
    mag2 = mag2.reshape(nblocks * BLOCK_ROWS, _LANES)
    counts = pl.pallas_call(
        _count_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (BLOCK_ROWS, _LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, NUM_THRESHOLDS), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, NUM_THRESHOLDS), jnp.int32),
        interpret=interpret,
    )(thresholds, mag2)
    return counts[0]


def pallas_topk_abs(x: Array, k: int, *, interpret: bool = False
                    ) -> Tuple[Array, Array]:
    """Exact (up to boundary ties) magnitude top-k using the Pallas counting
    kernel for threshold search. Same contract as ops.topk.topk_abs."""
    from gtopkssgd_tpu.ops.topk import threshold_topk_abs

    return threshold_topk_abs(
        x, k,
        count_fn=functools.partial(multi_threshold_count, interpret=interpret),
    )
