"""Device-side sparse primitives: top-k selection and sparse-set algebra.

TPU-native replacement for the reference's reliance on the `torch.topk` CUDA
kernel (used in compression.py::TopKCompressor.compress of hclhkbu/gtopkssgd)
and on numpy-side sparse merging inside allreducer.py::gtopk_sparse_allreduce.
Everything here is shape-static and jit-friendly.
"""

from gtopkssgd_tpu.ops.topk import (
    topk_abs,
    blockwise_topk_abs,
    approx_topk_abs,
    threshold_topk_abs,
    simrecall_topk_abs,
    twostage_topk_abs,
    bucketize_counts,
    select_topk,
    select_tau,
    k_for_density,
    merge_sparse_sets,
    scatter_add_dense,
    membership_mask,
    SENTINEL_DTYPE,
    TWOSTAGE_OVERSAMPLE,
)

__all__ = [
    "topk_abs",
    "blockwise_topk_abs",
    "approx_topk_abs",
    "threshold_topk_abs",
    "simrecall_topk_abs",
    "twostage_topk_abs",
    "bucketize_counts",
    "select_topk",
    "select_tau",
    "k_for_density",
    "merge_sparse_sets",
    "scatter_add_dense",
    "membership_mask",
    "SENTINEL_DTYPE",
    "TWOSTAGE_OVERSAMPLE",
]
