"""Top-k selection and sparse (index, value) set algebra, shape-static for XLA.

Reference parity: the reference compressor (compression.py::TopKCompressor in
hclhkbu/gtopkssgd) calls `torch.topk(|acc|, k)` on GPU over the flat gradient
(N up to ~1e8 for ResNet-50) and the allreducer merges (index, value) pairs in
numpy on the host. Here both live on the TPU:

  * `topk_abs`           -- exact magnitude top-k via `lax.top_k` (one shot).
  * `blockwise_topk_abs` -- exact two-stage top-k: per-block candidates then a
                            global reselect.  Much friendlier to the TPU VPU
                            for large N because each `lax.top_k` call runs on
                            a short row of a 2-D batch instead of one huge
                            vector. Used by default for N above a threshold.
  * `approx_topk_abs`    -- `lax.approx_max_k` (TPU-optimized, recall<1);
                            opt-in, changes semantics slightly.
  * `twostage_topk_abs`  -- generalized two-stage approximate top-k
                            (arXiv:2506.04165): one pass emitting per-bucket
                            max candidates (Pallas-fused with the error-
                            feedback accumulate on TPU), then a small exact
                            reselect. Recall ~= 1 - k/(2L); misses stay in
                            the residual (arXiv:1911.08772).
  * `select_tau`         -- tau-only API: the k-th |value| threshold without
                            materializing a k-sized (vals, idx) set, for
                            threshold-mask consumers (compress_by_threshold).
  * `merge_sparse_sets`  -- the per-round merge of the gTop-k tree: sparse sum
                            of two k-sized unique-index sets, then reselect.

Sparse sets are a pair of arrays `(values f32[k], indices i32[k])` with unique
indices; padding slots use `index == n` (one past the end) with value 0 so a
`scatter(..., mode='drop')` ignores them.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

SENTINEL_DTYPE = jnp.int32

Array = jax.Array


def k_for_density(n: int, density: float) -> int:
    """k = max(1, ceil(density * n)) — matches the reference's k choice."""
    return max(1, int(math.ceil(float(density) * n)))


def topk_abs(x: Array, k: int) -> Tuple[Array, Array]:
    """Exact top-k of |x| over a flat vector. Returns (signed values, indices).

    Indices are int32. Output is ordered by descending |value| (ties broken by
    `lax.top_k`'s deterministic lowest-index-first rule, which is what makes
    the SPMD-symmetric gtopk merge produce identical results on every device).
    """
    mag = jnp.abs(x)
    _, idx = lax.top_k(mag, k)
    idx = idx.astype(SENTINEL_DTYPE)
    vals = jnp.take(x, idx, mode="fill", fill_value=0)
    return vals, idx


def blockwise_topk_abs(x: Array, k: int, num_blocks: int = 0) -> Tuple[Array, Array]:
    """Exact top-k of |x| using a two-stage (per-block, then global) select.

    Stage 1 reshapes the flat N-vector into (B, ceil(N/B)) rows and takes the
    top-min(k, row) of each row in one batched `lax.top_k`; stage 2 reselects
    the global top-k among the <= B*k candidates. Exactness: every global
    top-k element is necessarily in its own block's top-k.

    This is the lax formulation of the two-stage kernel strategy listed in
    SURVEY.md §2 (native obligations table) for the `torch.topk` replacement;
    the Pallas version lives in `ops/pallas_topk.py`.
    """
    n = x.shape[0]
    if num_blocks <= 0:
        # Heuristic: rows of ~64k elements keep each top-k call cheap while
        # stage 2 stays small (B * k candidates).
        num_blocks = max(1, n // 65536)
    block = -(-n // num_blocks)  # ceil
    padded = block * num_blocks
    kb = min(k, block)
    xp = jnp.pad(x, (0, padded - n))
    mag = jnp.abs(xp).reshape(num_blocks, block)
    # In-block positions of per-block candidates.
    _, pos = lax.top_k(mag, kb)  # (B, kb)
    base = (jnp.arange(num_blocks, dtype=SENTINEL_DTYPE) * block)[:, None]
    cand_idx = (pos.astype(SENTINEL_DTYPE) + base).reshape(-1)
    cand_val = jnp.take(xp, cand_idx).reshape(-1)
    # Padding elements are 0 and sort last; mask them to sentinel after select.
    _, sel = lax.top_k(jnp.abs(cand_val), k)
    idx = jnp.take(cand_idx, sel)
    vals = jnp.take(cand_val, sel)
    oob = idx >= n
    idx = jnp.where(oob, n, idx).astype(SENTINEL_DTYPE)
    vals = jnp.where(oob, 0.0, vals)
    return vals, idx


def approx_topk_abs(x: Array, k: int, recall_target: float = 0.95) -> Tuple[Array, Array]:
    """TPU-optimized approximate top-k (`lax.approx_max_k`). Opt-in only:
    recall < 1 slightly changes gTop-k semantics (still convergent thanks to
    error feedback, but document any use in experiments)."""
    mag = jnp.abs(x)
    _, idx = lax.approx_max_k(mag, k, recall_target=recall_target)
    idx = idx.astype(SENTINEL_DTYPE)
    vals = jnp.take(x, idx, mode="fill", fill_value=0)
    return vals, idx


def bucketize_counts(mag: Array, thr: Array) -> Array:
    """counts[i] = #{ j : mag[j] >= thr[i] } for all 8 thresholds in ONE
    logical pass over `mag` (the XLA analogue of the fused Pallas
    counting kernel; previously this was a vmapped 8-reduction = 8 HBM
    passes). Sort the thresholds, bucketize every magnitude with one
    `searchsorted`, histogram the bucket ids, and read each threshold's
    count as a suffix sum: mag >= thr_sorted[i]  iff  its bucket id
    (#thresholds <= mag) is > i."""
    nthr = thr.shape[0]
    order = jnp.argsort(thr)
    ts = jnp.take(thr, order)
    bucket = jnp.searchsorted(ts, mag, side="right")  # #{ts <= mag_j}
    hist = jnp.zeros((nthr + 1,), jnp.int32).at[bucket].add(1)
    ge = jnp.cumsum(hist[::-1])[::-1]  # ge[i] = #{bucket >= i}
    counts_sorted = ge[1:]  # threshold i (sorted) needs bucket >= i+1
    return jnp.zeros((nthr,), jnp.int32).at[order].set(counts_sorted)


def threshold_topk_abs(x: Array, k: int, count_fn=None) -> Tuple[Array, Array]:
    """Magnitude top-k by threshold multisection + compaction ("threshold-
    estimate + compact", SURVEY.md §2 native-obligations table).

    Algorithm (all shape-static, 4 + ~3 passes over x):
      1. tau search: maintain a bracket [lo, hi] with count(|x| >= lo) >= k;
         4 rounds of 8-way geometric multisection (counts via `count_fn` —
         one fused Pallas pass per round on TPU, see ops.pallas_topk).
      2. compact every element with |x| >= lo into `cap` slots by cumsum +
         scatter (cap = max(2k, k + 4096)).
      3. one exact `lax.top_k` over the <= cap candidates.

    Exact whenever the survivor count fits in `cap` — always, in practice,
    after 4 refinement rounds on continuous-valued gradients (the bracket
    is ~0.4% wide). Degenerate distributions (k-th-magnitude value repeated
    beyond cap times, or k exceeding the number of nonzeros) fall back to
    index-order tie-breaking among boundary values, which error feedback
    absorbs (same tie-arbitrariness class as lax.top_k's index rule).
    """
    n = x.shape[0]
    if k >= n:
        return topk_abs(x, k)
    if count_fn is None:
        count_fn = bucketize_counts
    mag = jnp.abs(x)
    maxv = jnp.max(mag)
    lo = jnp.zeros((), x.dtype)
    hi = maxv
    for _ in range(4):
        lo_eff = jnp.maximum(lo, maxv * 1e-12 + 1e-30)
        r = (lo_eff / (hi + 1e-30)) ** (1.0 / 9.0)
        powers = jnp.arange(1, 9, dtype=x.dtype)
        thr = hi * r ** powers  # 8 candidates strictly inside (lo, hi)
        counts = count_fn(mag, thr)
        ge = counts >= k
        lo = jnp.maximum(lo, jnp.max(jnp.where(ge, thr, lo)))
        hi = jnp.minimum(hi, jnp.min(jnp.where(ge, hi, thr)))
    tau = lo
    cap = min(n, max(2 * k, k + 4096))
    selected = mag >= tau
    pos = jnp.cumsum(selected.astype(jnp.int32)) - 1
    slot = jnp.where(selected, pos, cap)  # cap = dropped (mode='drop')
    buf_v = jnp.zeros((cap,), x.dtype).at[slot].set(x, mode="drop")
    buf_i = jnp.full((cap,), n, SENTINEL_DTYPE).at[slot].set(
        jnp.arange(n, dtype=SENTINEL_DTYPE), mode="drop"
    )
    _, sel = lax.top_k(jnp.abs(buf_v), k)
    return jnp.take(buf_v, sel), jnp.take(buf_i, sel)


def simrecall_topk_abs(x: Array, k: int,
                       recall: float = 0.95) -> Tuple[Array, Array]:
    """CPU-runnable pessimistic model of `lax.approx_max_k` selection.

    Purpose (round-4 verdict missing #2): the production `auto` policy
    routes every model above AUTO_APPROX_THRESHOLD params through
    `approx_max_k` at recall_target=0.95, but its convergence impact
    cannot be measured on the CPU backend — XLA lowers approx_max_k to an
    EXACT top-k there, so every CPU convergence artifact silently tested
    exact selection. This selector simulates the approximation in a way
    that is exact-backend-independent: take the exact top-(k+pad), drop
    each of the true top-k elements independently with probability
    1-recall, and backfill the freed slots from ranks k..k+pad in rank
    order.

    Pessimism argument: approx_max_k's recall_target is a lower-bound
    target (measured recall is typically above it) and its misses are
    biased toward the SMALLEST magnitudes in the set (they fall off the
    bitonic reduction's per-lane maxima); here misses hit every rank —
    including the largest — uniformly at rate 1-recall, and replacements
    come from strictly lower ranks. A convergence result that survives
    this selector bounds the real approx path from below.

    Determinism: the drop pattern is seeded from the DATA (bitcasts of
    sum(x) AND sum(|x|) folded into a fixed key — the second statistic
    breaks the sign-symmetric collisions the first is blind to), so
    identical-seed A/B runs reproduce exactly, while the dropped set
    still varies step to step as the gradient changes — mirroring how
    approx_max_k's misses depend on the value layout. Degenerate edge: if more than `pad` of the top-k are
    dropped, the tail of the result re-admits dropped elements (sorted
    after the backfill ranks) — slightly less pessimistic there, and only
    relevant at k below ~100 where pad saturates its floor.
    """
    n = x.shape[0]
    pad = max(16, int(math.ceil(k * (1.0 - recall) * 4)))
    m = min(n, k + pad)
    vals, idx = topk_abs(x, m)  # exact top-m, descending |value|
    key = jax.random.fold_in(
        jax.random.PRNGKey(0x51AEC),
        lax.bitcast_convert_type(
            jnp.sum(x, dtype=jnp.float32), jnp.int32),
    )
    # Second statistic: sum(x) alone is blind to sign-symmetric changes
    # (any rearrangement or sign flip preserving the sum replays the same
    # drop pattern); sum(|x|) breaks that degeneracy, and cancellation-
    # heavy gradients keep a near-constant sum(x) while |x| mass moves.
    key = jax.random.fold_in(
        key,
        lax.bitcast_convert_type(
            jnp.sum(jnp.abs(x), dtype=jnp.float32), jnp.int32),
    )
    ranks = jnp.arange(m, dtype=jnp.int32)
    dropped = (ranks < k) & (jax.random.uniform(key, (m,)) > recall)
    # Survivors keep their rank as sort key; dropped ranks sort last, so
    # the first k slots are survivors followed by backfill ranks k..m.
    order = jnp.where(dropped, m + ranks, ranks)
    _, out_val, out_idx = lax.sort((order, vals, idx), num_keys=1,
                                   is_stable=True)
    return out_val[:k], out_idx[:k]


# Stage-1 bucket count target: L ~= TWOSTAGE_OVERSAMPLE * k buckets. With
# top-1-per-bucket selection over a random placement, the expected recall
# is ~= 1 - (k-1)/(2L) (a true top-k element is only lost to a LARGER
# element sharing its bucket, and ranks above it are uniform over buckets)
# -> ~0.97 at oversample 16, comfortably above the 0.95 audit floor.
TWOSTAGE_OVERSAMPLE = 16


def _twostage_pallas_groups(n: int, k: int, oversample: int) -> int:
    """Row-groups per (BLOCK_ROWS, 128) tile for the Pallas stage-1 pass.

    Miss probability is governed by the bucket SIZE (rpg = BLOCK_ROWS /
    groups elements per bucket), not the raw bucket count: tail padding
    inflates L without shrinking the buckets real elements live in. Keep
    rpg <= n/(oversample*k) so expected misses stay ~k/(2*oversample)
    (padding-heavy buckets only get safer). Power-of-two divisor of
    BLOCK_ROWS; at groups == BLOCK_ROWS every element is its own bucket
    and the method degenerates to exact."""
    from gtopkssgd_tpu.ops.pallas_topk import BLOCK_ROWS, _BLOCK, _LANES

    nblocks = max(1, -(-n // _BLOCK))
    target_rpg = max(1, n // max(1, oversample * k))
    g = 1
    while BLOCK_ROWS // g > target_rpg and g < BLOCK_ROWS:
        g *= 2
    while nblocks * g * _LANES < k and g < BLOCK_ROWS:
        g *= 2
    return g


def _twostage_candidates(
    x: Array,
    k: int,
    *,
    residual: Optional[Array] = None,
    oversample: int = TWOSTAGE_OVERSAMPLE,
    use_pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Stage 1 of the two-stage select: per-bucket max-|acc| candidates
    (cand_val f32[L], cand_idx i32[L]) with acc = x (+ residual), L >= k.
    Candidate indices >= n mark padding buckets (value 0)."""
    n = x.shape[0]
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas or interpret:
        from gtopkssgd_tpu.ops.pallas_topk import fused_stage1_candidates

        groups = _twostage_pallas_groups(n, k, oversample)
        interp = (jax.default_backend() != "tpu"
                  if interpret is None else interpret)
        cand_val, cand_idx, _ = fused_stage1_candidates(
            x, residual=residual, groups=groups, interpret=interp)
        return cand_val, cand_idx
    # XLA reference: reshape to (b, L) so bucket j holds flat indices
    # {j, L+j, 2L+j, ...} — the stride-L interleave decorrelates
    # contiguous layer slices — and take one argmax per column. Same
    # bucket-top-1 semantics as the kernel, different bucket membership.
    acc = x if residual is None else x + residual
    L = max(k, min(n, oversample * k))
    b = -(-n // L)
    accp = jnp.pad(acc, (0, b * L - n))
    mat = accp.reshape(b, L)
    rows = jnp.arange(b, dtype=SENTINEL_DTYPE)[:, None]
    cols = jnp.arange(L, dtype=SENTINEL_DTYPE)[None, :]
    mag = jnp.where(rows * L + cols < n, jnp.abs(mat), -1.0)
    win = jnp.argmax(mag, axis=0)  # first max row: deterministic ties
    cand_idx = (win.astype(SENTINEL_DTYPE) * L
                + jnp.arange(L, dtype=SENTINEL_DTYPE))
    cand_val = jnp.take_along_axis(mat, win[None, :], axis=0)[0]
    return cand_val, cand_idx


def twostage_topk_abs(
    x: Array,
    k: int,
    *,
    residual: Optional[Array] = None,
    oversample: int = TWOSTAGE_OVERSAMPLE,
    use_pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Generalized two-stage approximate magnitude top-k (arXiv:2506.04165
    lineage; the gTop-k-ready variant of `blockwise_topk_abs`).

    Stage 1 reads x ONCE and keeps only each bucket's max-|acc| element
    (L ~= oversample*k buckets); stage 2 exactly reselects the top-k of
    the <= L candidates. Unlike `blockwise_topk_abs` (per-block top-k,
    exact, but a large `lax.top_k` per block), stage 1 here is a pure
    max/argmax reduction — on TPU it runs as the fused Pallas kernel
    (ops.pallas_topk.fused_stage1_candidates) which also folds the
    error-feedback accumulate `x + residual` into the same HBM pass, so
    the flat [N] accumulator is never materialized.

    Approximation: a true top-k element is missed only when a LARGER
    element shares its bucket — expected recall ~= 1 - (k-1)/(2L)
    (~0.97 at the default oversample). Error feedback absorbs misses
    (arXiv:1911.08772), the same argument that admits `approx`.

    `residual`, when given, is added to x INSIDE the selection pass;
    returned values are read from acc = x + residual.
    """
    n = x.shape[0]
    if k >= n:
        acc = x if residual is None else x + residual
        vals, idx = topk_abs(acc, n)
        if k > n:
            vals = jnp.pad(vals, (0, k - n))
            idx = jnp.pad(idx, (0, k - n), constant_values=n)
        return vals, idx
    cand_val, cand_idx = _twostage_candidates(
        x, k, residual=residual, oversample=oversample,
        use_pallas=use_pallas, interpret=interpret)
    _, sel = lax.top_k(jnp.abs(cand_val), k)
    idx = jnp.take(cand_idx, sel)
    vals = jnp.take(cand_val, sel)
    oob = idx >= n
    idx = jnp.where(oob, n, idx).astype(SENTINEL_DTYPE)
    vals = jnp.where(oob, 0.0, vals)
    return vals, idx


def _threshold_tau(x: Array, k: int, count_fn=None) -> Array:
    """tau for the threshold family without building an index set: the
    same multisection bracket as `threshold_topk_abs`, then compact the
    surviving MAGNITUDES (no values, no indices, no gather) and read the
    k-th largest. Degenerate tie behavior (survivors > cap) matches
    threshold_topk_abs by construction — same bracket, same cap."""
    n = x.shape[0]
    mag = jnp.abs(x)
    if k >= n:
        return jnp.min(mag)
    if count_fn is None:
        count_fn = bucketize_counts
    maxv = jnp.max(mag)
    lo = jnp.zeros((), x.dtype)
    hi = maxv
    for _ in range(4):
        lo_eff = jnp.maximum(lo, maxv * 1e-12 + 1e-30)
        r = (lo_eff / (hi + 1e-30)) ** (1.0 / 9.0)
        powers = jnp.arange(1, 9, dtype=x.dtype)
        thr = hi * r ** powers
        counts = count_fn(mag, thr)
        ge = counts >= k
        lo = jnp.maximum(lo, jnp.max(jnp.where(ge, thr, lo)))
        hi = jnp.minimum(hi, jnp.min(jnp.where(ge, hi, thr)))
    cap = min(n, max(2 * k, k + 4096))
    selected = mag >= lo
    pos = jnp.cumsum(selected.astype(jnp.int32)) - 1
    slot = jnp.where(selected, pos, cap)
    buf_m = jnp.zeros((cap,), x.dtype).at[slot].set(mag, mode="drop")
    return lax.top_k(buf_m, k)[0][k - 1]


def select_tau(
    x: Array,
    k: int,
    method: str = "auto",
    *,
    residual: Optional[Array] = None,
) -> Array:
    """The selection threshold tau — the smallest magnitude the configured
    kernel would select — WITHOUT materializing a k-sized (vals, idx) set
    or gathering values. Threshold-mask consumers (TopKCompressor.
    compress_by_threshold, the p=1 paths in optimizer.py) build their
    keep mask as |acc| >= tau directly from this scalar.

    Per method, tau equals min(|vals|) of the (vals, idx) set the
    corresponding `select_topk` would return — the existing mask
    semantics (boundary ties all pass; for approximate kernels the mask
    is a superset of the index set, recall >= the kernel's) carry over
    unchanged. For `twostage`, tau is the k-th largest CANDIDATE
    magnitude, which is >= the value of overall rank k+misses, so the
    mask |acc| >= tau still contains every candidate the two-stage
    reselect would keep.

    `residual`, when given, is the error-feedback residual: tau is
    computed over acc = x + residual (fused into the stage-1/counting
    kernel pass for twostage/pallas; folded by XLA otherwise).
    """
    n = x.shape[0]
    if method == "auto":
        method = _resolve_auto(n)
    if method == "twostage":
        if k >= n:
            acc = x if residual is None else x + residual
            return jnp.min(jnp.abs(acc))
        cand_val, _ = _twostage_candidates(x, k, residual=residual)
        return lax.top_k(jnp.abs(cand_val), k)[0][k - 1]
    acc = x if residual is None else x + residual
    if k >= n:
        return jnp.min(jnp.abs(acc))
    if method == "exact":
        return lax.top_k(jnp.abs(acc), k)[0][k - 1]
    if method == "approx":
        vals, _ = lax.approx_max_k(jnp.abs(acc), k, recall_target=0.95)
        return jnp.min(vals)
    if method == "blockwise":
        num_blocks = max(1, n // 65536)
        block = -(-n // num_blocks)
        kb = min(k, block)
        mag = jnp.abs(jnp.pad(acc, (0, block * num_blocks - n)))
        cand = lax.top_k(mag.reshape(num_blocks, block), kb)[0]
        return lax.top_k(cand.reshape(-1), k)[0][k - 1]
    if method == "threshold":
        return _threshold_tau(acc, k)
    if method == "pallas":
        from gtopkssgd_tpu.ops.pallas_topk import (
            fused_multi_threshold_count,
        )

        interp = jax.default_backend() != "tpu"
        # The count rounds read grad (+ residual) through the fused
        # kernel; only the final compaction touches the folded acc.
        count_fn = lambda _mag, thr: fused_multi_threshold_count(
            x, thr, residual, interpret=interp)
        return _threshold_tau(acc, k, count_fn=count_fn)
    if method == "simrecall":
        vals, _ = simrecall_topk_abs(acc, k)
        return jnp.min(jnp.abs(vals))
    raise ValueError(f"unknown topk method {method!r}")


_METHODS = {
    "exact": lambda x, k: topk_abs(x, k),
    "blockwise": lambda x, k: blockwise_topk_abs(x, k),
    "approx": lambda x, k: approx_topk_abs(x, k),
    "threshold": lambda x, k: threshold_topk_abs(x, k),
    "simrecall": lambda x, k: simrecall_topk_abs(x, k),
    "twostage": lambda x, k: twostage_topk_abs(x, k),
}

# Above this N, "auto" switches from exact lax.top_k to an approximate
# kernel. Measured on the real TPU v5e chip (benchmarks/results/
# topk_bench_TPU_v5_lite.json; regenerate with
# `python benchmarks/topk_bench.py` on hardware — the committed rows
# predate the twostage kernel, whose on-chip columns land at the next
# tunnel revival; CPU-fallback rows carry interpret-mode recall in the
# meantime, benchmarks/results/topk_bench_cpu_fallback.json):
#
#     N      rho    exact    blockwise  threshold  approx   pallas
#     272k   0.001  0.40 ms   0.37 ms    3.25 ms   0.16 ms  3.26 ms
#     25.6M  0.001  75.4 ms  144.1 ms  319.0 ms    1.27 ms  309 ms
#     61M    0.001  196  ms  952   ms  736   ms    3.32 ms  736 ms
#
# exact is fine at CIFAR scale but catastrophic at ImageNet scale (75 ms
# against a 60 ms ResNet-50 train step); approx_max_k (the TPU-native
# bitonic partial reduction, arXiv:2206.14286) is ~60x faster at the sizes
# that matter. Its recall_target=0.95 slightly changes which elements are
# selected — safe here because error feedback keeps every missed element
# in the residual for the next step (the same argument that justifies
# top-k sparsification itself, arXiv:1911.08772), and the gtopk tree merge
# (merge_sparse_sets) stays EXACT, so replicas remain in lockstep. Force
# --topk-method exact to reproduce the reference's exact-selection
# semantics at any size.
#
# `twostage` targets the same >AUTO_APPROX_THRESHOLD regime as approx but
# additionally fuses the error-feedback accumulate into its single
# stage-1 pass and feeds the tau-only path (select_tau) — the properties
# the p=1 threshold-mask pipeline needs. GTOPK_AUTO_TWOSTAGE=1 makes
# `auto` prefer it over approx at large N; flip the default only with
# fresh on-chip twostage rows from benchmarks/topk_bench.py.
AUTO_APPROX_THRESHOLD = 1 << 20
AUTO_TWOSTAGE = os.environ.get("GTOPK_AUTO_TWOSTAGE", "") == "1"


def _resolve_auto(n: int) -> str:
    """The `auto` policy, shared by select_topk and select_tau."""
    if n <= AUTO_APPROX_THRESHOLD:
        return "exact"
    return "twostage" if AUTO_TWOSTAGE else "approx"


def select_topk(
    x: Array,
    k: int,
    method: str = "auto",
    *,
    residual: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """Dispatch on top-k strategy.

    "auto" picks exact `lax.top_k` for small N (cost is noise there) and
    an approximate kernel above AUTO_APPROX_THRESHOLD — see the measured
    table above; do not change the policy without re-running
    benchmarks/topk_bench.py on hardware.

    `residual`, when given, selects over acc = x + residual; the
    `twostage` method folds the add into its fused stage-1 pass (the
    accumulator is never materialized), every other method folds it in
    XLA before selecting. Returned values are read from acc either way.
    """
    if method == "auto":
        method = _resolve_auto(x.shape[0])
    if method == "twostage":
        return twostage_topk_abs(x, k, residual=residual)
    if residual is not None:
        x = x + residual
    if method == "pallas":
        from gtopkssgd_tpu.ops.pallas_topk import pallas_topk_abs

        return pallas_topk_abs(x, k, interpret=jax.default_backend() != "tpu")
    try:
        fn = _METHODS[method]
    except KeyError:
        raise ValueError(f"unknown topk method {method!r}") from None
    return fn(x, k)


def merge_sparse_sets(
    vals_a: Array,
    idx_a: Array,
    vals_b: Array,
    idx_b: Array,
    k: int,
    n: int,
) -> Tuple[Array, Array]:
    """Sparse-sum two unique-index sets and reselect the top-k by magnitude.

    This is one round of the gTop-k tree (allreducer.py::gtopk_sparse_allreduce
    in the reference, Algorithm 2 of arXiv:1901.04359): concatenate the two
    (value, index) lists, sum duplicated indices, take top-k of the <=2k
    candidates.  Both partners of a `ppermute` exchange call this on the same
    multiset (in different concatenation order), and the result is
    order-canonical, so all devices stay in lockstep without a re-broadcast:

      * pairs are sorted by index, so slot layout is order-independent;
      * duplicate (real) indices appear at most twice because each input set
        has unique real indices; the pair is summed into its first slot and
        the second slot is voided to the sentinel. Sentinel (padding) slots
        may repeat more than twice but always carry value 0, so the
        run-length-2 assumption only ever drops zeros;
      * the final `lax.top_k` then sees identical (value, index) arrays on
        both partners and its tie-breaking is deterministic.

    Returns (values, indices) of the merged set, descending by |value|.

    Implementation note (measured on TPU v5e — the committed artifact is
    benchmarks/results/merge_bench_TPU_v5_lite.json, `merge` vs
    `merge_argsort_topk` rows): both stages are multi-operand `lax.sort`
    calls that carry the payload through the sort instead of `argsort` +
    `jnp.take` — gathers are the slow path on TPU, and even the final
    k-selection is faster as a carried sort over the 2k candidates than
    as `lax.top_k` + two takes at large k. Per round: 1.27 -> 0.18 ms at
    k=25.6e3 (ResNet-50 rho=0.001), 11.5 -> 1.7 ms at k=2.6e5, 2.7 ->
    0.37 ms at k=61e3 (VGG-16) — 5-7x at ImageNet-scale N. At CIFAR
    scale (k<=2.7e3) both formulations sit at 0.12-0.16 ms and the
    difference is below relevance either way. Stage-2 tie-breaking on
    equal |value| is stable over the stage-1 canonical (index-sorted)
    order, i.e. lowest-index-first — the same rule `lax.top_k` applies,
    so determinism across partners is unchanged.
    """
    cat_idx = jnp.concatenate([idx_a, idx_b])
    cat_val = jnp.concatenate([vals_a, vals_b])
    # Canonical order: sort by index, values carried through the sort;
    # equal (duplicate) indices become adjacent.
    si, sv = lax.sort((cat_idx, cat_val), num_keys=1, is_stable=True)
    dup = jnp.concatenate([jnp.zeros((1,), bool), si[1:] == si[:-1]])
    next_dup = jnp.concatenate([dup[1:], jnp.zeros((1,), bool)])
    summed = sv + jnp.where(next_dup, jnp.roll(sv, -1), 0.0)
    merged_val = jnp.where(dup, 0.0, summed)
    merged_idx = jnp.where(dup, n, si).astype(SENTINEL_DTYPE)
    # Reselect: ascending sort on -|value| with (value, index) carried,
    # then keep the first k.
    _, out_val, out_idx = lax.sort(
        (-jnp.abs(merged_val), merged_val, merged_idx),
        num_keys=1, is_stable=True,
    )
    return out_val[:k], out_idx[:k]


def scatter_add_dense(n: int, idx: Array, vals: Array, dtype=jnp.float32) -> Array:
    """Densify a sparse set: zeros(n).at[idx].add(vals), dropping sentinel
    slots (idx == n falls out of range and `mode='drop'` ignores it)."""
    return jnp.zeros((n,), dtype).at[idx].add(vals.astype(dtype), mode="drop")


def membership_mask(query_idx: Array, set_idx: Array) -> Array:
    """bool[len(query_idx)]: is each query index present in `set_idx`?

    Used for the error-feedback repair step: values selected locally but
    rejected globally go back into the residual (`add_residuals` in the
    reference compressor). Sentinel queries (== n) report membership iff the
    set also carries the sentinel, but callers always mask by value anyway.
    """
    sorted_set = jnp.sort(set_idx)
    pos = jnp.searchsorted(sorted_set, query_idx)
    pos = jnp.clip(pos, 0, set_idx.shape[0] - 1)
    return jnp.take(sorted_set, pos) == query_idx
