"""The package's single reduction-mode vocabulary (reference --compression
flag / allreducer mode switch). Every dispatch table — the compressor
registry, the sparse-allreduce dispatch, the optimizer, and the comm-volume
model — keys off these tuples so a new mode string cannot be added to one
table and silently missed by another.
"""

DENSE_MODES = (None, "none", "dense")
GTOPK_MODES = ("gtopk",)
ALLGATHER_MODES = ("allgather", "topk", "topkA", "topk_allgather")

ALL_MODES = DENSE_MODES + GTOPK_MODES + ALLGATHER_MODES
SPARSE_MODES = GTOPK_MODES + ALLGATHER_MODES
