"""The package's single reduction-mode vocabulary (reference --compression
flag / allreducer mode switch). Every dispatch table — the compressor
registry, the sparse-allreduce dispatch, the optimizer, and the comm-volume
model — keys off these tuples so a new mode string cannot be added to one
table and silently missed by another.
"""

DENSE_MODES = (None, "none", "dense")
GTOPK_MODES = ("gtopk",)
ALLGATHER_MODES = ("allgather", "topk", "topkA", "topk_allgather")
# Hierarchical two-level reduction (TPU extension, not reference parity —
# SURVEY.md §5 "distributed communication backend" names it as the natural
# TPU idiom): dense psum within an ICI slice, gTop-k hypercube across
# slices (the DCN hop, where bandwidth is scarce and sparsity pays).
HIER_MODES = ("gtopk_hier",)
# Layer-wise local selection (TPU extension, arXiv:1911.08772 lineage):
# per-layer top-k_l with k_l = ceil(rho * n_l) instead of one global top-k
# over the flattened gradient. The LOCAL stage never materializes the
# [N] flat gradient (the measured serial-tail cost of the flat path on a
# TPU core — benchmarks/results/fused_variants_TPU_v5_lite.json); the
# GLOBAL stage is the unchanged gTop-k hypercube over the concatenated
# per-layer sets, so the communicated set is still a magnitude top-K of
# the union.
LAYERWISE_MODES = ("gtopk_layerwise",)

ALL_MODES = (DENSE_MODES + GTOPK_MODES + ALLGATHER_MODES + HIER_MODES
             + LAYERWISE_MODES)
SPARSE_MODES = GTOPK_MODES + ALLGATHER_MODES + HIER_MODES + LAYERWISE_MODES

# Wire-schedule vocabulary (the plan layer, parallel/planner.py). A mode
# fixes the SEMANTICS (what set is applied, what repair contract holds);
# a schedule fixes the WIRE ALGORITHM that realizes it. Only the gtopk
# family has more than one realization today: the hypercube 'tree' vs
# the Ok-Topk 'balanced' split-and-reduce (arXiv:2201.07598). The other
# entries name each remaining mode's single historical algorithm so a
# CommPlan is always fully specified.
SCHEDULES = ("psum", "tree", "balanced", "allgather")

# Pipeline vocabulary (the execution-order axis of the bucketed layerwise
# wire, PR 15). A schedule fixes the wire algorithm of ONE merge; the
# pipeline fixes how the B bucket merges interleave with the B bucket
# selections inside a step: 'serial' is the paper's strictly sequential
# T_select + T_comm (bucket b+1's selection waits on bucket b's merge —
# the bit-identity oracle), 'overlap' cuts that dependence so bucket
# b+1's selection is issued while bucket b's ppermute rounds are in
# flight (Ok-Topk-style pipelining, arXiv:2201.07598). Both apply the
# same values in the same order, so results are bit-identical; only the
# exposed wall-clock differs. The user-facing spec grammar adds 'auto'
# (bucketing.parse_pipeline), which resolves to one of these two.
PIPELINES = ("serial", "overlap")


def default_schedule(mode: str) -> str:
    """The hand-picked historical wire schedule for `mode` — what every
    run used before the planner existed, and what the planner must keep
    choosing at defaults (no silent behavior change)."""
    if mode in DENSE_MODES:
        return "psum"
    if mode in ALLGATHER_MODES:
        return "allgather"
    if mode in GTOPK_MODES + HIER_MODES + LAYERWISE_MODES:
        return "tree"
    raise ValueError(f"unknown mode {mode!r}")
