"""Resilience subsystem: detect-and-recover for long synchronous runs.

The paper's regime (arXiv:1901.04059) is many-hour synchronous training
across many low-bandwidth workers — exactly where NaN steps, stragglers,
preemptions, and corrupt checkpoints kill runs. PRs 1-4 built detection
(stall watchdog exit 43, AnomalyMonitor halt exit 44, fleet straggler
analytics) that can only STOP a run; this package makes the run survive,
with a deterministic fault-injection layer so every recovery path is
provable in CI on a CPU mesh:

  inject.py  — step-keyed ``--inject SPEC`` fault injection
      (``nan_grad@120``, ``slow_rank:2:2.5s@50-60``, ``preempt@200``,
      ``loader_raise@75``, ``corrupt_ckpt@latest``): perturbs gradients,
      timing, signals, and checkpoint bytes deterministically, logging
      each firing as an "inject" record.
  policy.py  — ``--recover-policy`` maps AnomalyMonitor rules to
      recovery actions instead of exit 44: *skip* (discard the update,
      keep the pre-step state — residual included — under a
      consecutive-skip budget), *rollback* (restore the last good
      checkpoint with per-rule retry budgets and exponential backoff),
      *degrade* (fall back from sparse to dense allreduce, re-entering
      sparse after a cooldown). Every action is a registered "recovery"
      record.
  elastic.py — elastic fleet resize (``--elastic``): a membership
      change (preemption, straggler eviction via goodput ``advise()``,
      or an injected ``resize@K:NEWP``) drains to a step boundary,
      emergency-saves, re-partitions the dp-sharded error-feedback
      residual onto the new P (grow = zero rows, shrink = masked-fold
      addition conserving pending gradient mass), rewrites the
      ``elastic.json`` lineage file, logs a durable "resize" record,
      and exits 46 for the supervisor to relaunch at the new size —
      one logical run, one registry lineage.
  preempt.py — SIGTERM/SIGINT preemption guard (flag-setting handlers;
      the trainer turns the flag into a forced step-granular emergency
      save then ``Preempted`` -> exit 45; 43=stall and 44=halt stay
      reserved) plus the shared ``retry_call`` backoff helper used for
      ``jax.distributed.initialize`` and data-loader setup.

Checkpoint integrity (config-hash + treedef-digest sidecars, verified on
restore with fallback to the previous step) lives with the checkpoint
code in utils/checkpoint.py; error-feedback correctness under recovery
(arXiv:1911.08772 ties convergence to the residual dynamics, so a
recovery that drops or duplicates residual state is silently wrong) is
what the skip/rollback semantics here are designed around.
"""

from gtopkssgd_tpu.resilience.elastic import (
    ResizeRestart,
    eviction_decision,
    load_lineage,
    mint_lineage_id,
    repartition_buffer,
    repartition_residual,
    write_lineage,
)
from gtopkssgd_tpu.resilience.inject import (
    Fault,
    FaultInjector,
    InjectedLoaderError,
    parse_inject,
)
from gtopkssgd_tpu.resilience.policy import (
    ActionSpec,
    RecoveryManager,
    describe_policy,
    parse_policy,
)
from gtopkssgd_tpu.resilience.preempt import (
    PREEMPT_EXIT_CODE,
    Preempted,
    PreemptionGuard,
    retry_call,
)

__all__ = [
    "PREEMPT_EXIT_CODE",
    "ActionSpec",
    "Fault",
    "FaultInjector",
    "InjectedLoaderError",
    "Preempted",
    "PreemptionGuard",
    "RecoveryManager",
    "ResizeRestart",
    "describe_policy",
    "eviction_decision",
    "load_lineage",
    "mint_lineage_id",
    "parse_inject",
    "parse_policy",
    "repartition_buffer",
    "repartition_residual",
    "retry_call",
    "write_lineage",
]
