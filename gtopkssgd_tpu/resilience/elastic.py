"""Elastic fleet: resize the dp mesh without losing a step.

The paper's premise (arXiv:1901.04059) is a slow, unreliable network;
the production extreme of that premise is a fleet whose MEMBERSHIP is
unreliable — spot capacity preempted mid-run, a persistently slow host
worth evicting, capacity arriving late. PRs 5-19 made a membership
change survivable (exit 45 + --resume restores the SAME P); this module
makes it a bounded-cost *resize*: the run drains to a step boundary,
emergency-saves through the existing integrity-sidecar path, and
relaunches on a DIFFERENT process set with nothing lost.

The resize protocol (trainer._resize_now + dist_trainer):

  trigger            preemption signal (PreemptionGuard), an eviction
                     decision (``eviction_decision`` below, fed by the
                     fleet merge's per-rank goodput + straggler EWMA),
                     or an injected ``resize@K:NEWP`` fault
  drain              the trigger is only acted on at the train loop's
                     iteration boundary, where the state is whole
  save               orbax force=True at the drained step; the
                     integrity sidecar additionally records the
                     residual's partition width (``meta.residual_p``)
                     so the restoring side knows the OLD P without
                     guessing from shapes
  lineage            ``elastic.json`` in out_dir is atomically
                     rewritten with resize_epoch+1 and the new P, and
                     one fsync'd "resize" metrics record lands —
                     BEFORE any process exits
  exit 46            ResizeRestart -> EXIT_RESIZE_RESTART. The relaunch
                     contract mirrors preempt-45: an external
                     supervisor re-invokes dist_trainer with --resume
                     --elastic and the new --nworkers;
                     jax.distributed.initialize then runs on the new
                     process set, and Trainer.__init__ re-derives the
                     whole comm stack at the new P for free (the PR 9
                     planner re-scores the CommPlan, the PR 11
                     bucketing DP re-runs, the PR 13 calibrator
                     re-fits — all are functions of P)

State re-partitioning: every replicated leaf (params, momentum, step)
restores shape-identically. The one P-shaped leaf is the error-feedback
residual ([P, ...] sharded P('dp')); ``repartition_residual`` re-splits
it host-side. Growing appends zero rows (a new worker starts with an
empty residual, exactly like step 0); shrinking FOLDS each orphaned row
into a surviving one by addition — the same masked-fold move
parallel/collectives.py uses for non-pow2 merges (extra m+t sends its
set down to participant t), iterated for arbitrary shrink factors. The
fold is the error-feedback-correct choice: the residual is exactly the
gradient mass not yet applied, so adding orphaned rows into survivors
conserves the pending mass column-for-column — nothing is silently
dropped, mirroring how rejected picks fold back after every merge
(arXiv:1911.08772 ties convergence to precisely this bookkeeping).
Re-partitioning is a state-redistribution problem of the kind
arXiv:2112.01075 decomposes into portable collectives; at the
checkpoint boundary the whole exchange degenerates to this host-side
gather + re-split.

Lineage continuity: ``lineage_id`` is minted once per LOGICAL run and
carried across every resize via ``elastic.json`` (copied next to the
checkpoint dir into each relaunch's out_dir); the run manifest and
registry entry carry lineage_id/resize_epoch so ``report history`` and
``report regress`` join the pre/post segments into one trajectory
(obs/registry.py).
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Any, Dict, Mapping, Optional, Sequence

import numpy as np

LINEAGE_FILE = "elastic.json"


class ResizeRestart(RuntimeError):
    """Raised by the trainer once the resize checkpoint + lineage file
    + durable "resize" record are on disk; dist_trainer maps it to
    EXIT_RESIZE_RESTART (46) so the supervisor relaunches the fleet at
    the new P with --resume --elastic."""


# ------------------------------------------------------------- lineage

def mint_lineage_id() -> str:
    """Fresh lineage id for a LOGICAL run (stable across resizes)."""
    return uuid.uuid4().hex[:16]


def lineage_path(out_dir: str) -> str:
    return os.path.join(out_dir, LINEAGE_FILE)


def load_lineage(out_dir: Optional[str]) -> Optional[Dict[str, Any]]:
    """The lineage state carried into this run, or None for a fresh
    (or non-elastic) start. Malformed files read as None — a torn
    lineage must not kill a resume that the checkpoint itself allows;
    the run then starts a new lineage, which history renders as two."""
    if not out_dir:
        return None
    try:
        with open(lineage_path(out_dir)) as fh:
            rec = json.load(fh)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and rec.get("lineage_id") else None


def write_lineage(out_dir: str, **fields: Any) -> Dict[str, Any]:
    """Atomically write ``elastic.json`` (tmp + fsync + replace — the
    same no-torn-sidecar discipline as checkpoint integrity files).
    Returns the record written."""
    os.makedirs(out_dir, exist_ok=True)
    path = lineage_path(out_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(fields, fh, sort_keys=True)
        fh.write("\n")
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass
    os.replace(tmp, path)
    return dict(fields)


# ------------------------------------------------------- repartitioning

def repartition_buffer(buf: np.ndarray, new_p: int) -> np.ndarray:
    """Re-split one per-device buffer [old_p, ...] onto new_p rows.

    Grow: surviving rows are copied bit-exactly; new workers get zero
    rows (an empty residual, exactly like step 0 — their first top-k
    round starts accumulating from live gradients).

    Shrink: orphaned row r folds into survivor r % new_p by addition —
    the iterated form of the collectives' masked fold (extra m+t sends
    its set down to participant t). Column sums are conserved in exact
    arithmetic, so no pending gradient mass is dropped; fp32 rounding
    on the adds is the same bounded perturbation every error-feedback
    merge already absorbs.
    """
    buf = np.asarray(buf)
    if buf.ndim < 1:
        raise ValueError("residual buffer must carry a leading [P] dim")
    old_p = buf.shape[0]
    if new_p < 1:
        raise ValueError(f"new_p must be >= 1, got {new_p}")
    if new_p == old_p:
        return buf.copy()
    if new_p > old_p:
        out = np.zeros((new_p,) + buf.shape[1:], dtype=buf.dtype)
        out[:old_p] = buf
        return out
    out = buf[:new_p].copy()
    for r in range(new_p, old_p):
        out[r % new_p] += buf[r]
    return out


def repartition_residual(residual: Any, new_p: int) -> Any:
    """Tree-mapped ``repartition_buffer`` over any residual layout: the
    flat [P, N] leaf (gtopk), the per-leaf tuple (gtopk_layerwise), or
    the {"v": ..., "u": ...} dict (momentum correction)."""
    import jax

    return jax.tree.map(
        lambda b: repartition_buffer(np.asarray(b), new_p), residual)


# ------------------------------------------------------------- eviction

def eviction_decision(merged: Mapping[str, Any], *, p: int,
                      min_fleet: int = 1, margin: float = 0.1
                      ) -> Optional[Dict[str, Any]]:
    """Decide whether the merged fleet view justifies evicting a rank.

    ``merged`` is obs/fleet.py ``merge()``'s dict. The goodput ledger's
    ``advise()`` names the rank whose goodput_frac sits furthest below
    the fleet median by more than ``margin`` (the ROADMAP item-1
    eviction hint); the straggler rows corroborate with the per-rank
    EWMA-lag persistence verdict when they cover the same rank. Returns
    None (no eviction) for a healthy fleet, a fleet already at
    ``min_fleet``, or a single-rank fleet — shrinking below min_fleet
    can never be advised. Otherwise:

      {rank, new_p, reason: "evict", source, goodput_frac,
       fleet_median_frac, dominant_badput, persistent_straggler}
    """
    from gtopkssgd_tpu.obs import goodput as _goodput

    if p - 1 < max(1, min_fleet):
        return None
    by_rank = merged.get("goodput_by_rank") or {}
    hint = _goodput.advise(by_rank, margin=margin)
    if hint is None:
        return None
    rank = int(hint["rank"])
    persistent = any(
        row.get("slowest_rank") == rank and row.get("persistent")
        for row in merged.get("stragglers") or [])
    return {
        "rank": rank,
        "new_p": p - 1,
        "reason": "evict",
        "source": "goodput_advise",
        "goodput_frac": hint.get("goodput_frac"),
        "fleet_median_frac": hint.get("fleet_median_frac"),
        "dominant_badput": hint.get("dominant_badput"),
        "persistent_straggler": bool(persistent),
    }


def surviving_ranks(old_p: int, evicted: Sequence[int]) -> list:
    """The ranks that re-form the fleet after evicting ``evicted`` —
    the relaunch contract renumbers them densely in order."""
    gone = set(int(r) for r in evicted)
    return [r for r in range(old_p) if r not in gone]
