"""Preemption handling + the shared retry/backoff helper.

Cloud TPU/GPU capacity is preemptible: the scheduler sends SIGTERM and
gives the process a grace window. The reference's answer was losing the
run (and, on resume from an epoch checkpoint, silently dropping the
error-feedback residuals — SURVEY.md §5). Here the contract is:

  signal -> PreemptionGuard handler sets a flag (handlers must be
  async-signal-safe: no I/O, no device sync) -> the train loop checks
  the flag at its next iteration boundary -> forced step-granular
  emergency checkpoint (orbax, force=True) -> ``Preempted`` ->
  dist_trainer exits PREEMPT_EXIT_CODE (45; 43=stall and 44=anomaly
  halt stay reserved). ``--resume`` then restores the emergency step
  and fast-forwards the data stream mid-epoch, so the resumed loss
  trace is the uninterrupted one.

``retry_call`` is the shared transient-failure helper (exponential
backoff, bounded attempts) wrapped around ``jax.distributed.initialize``
(coordinator races at pod startup) and data-loader setup/fetch (NFS
blips; also how injected loader_raise faults are absorbed).
"""

from __future__ import annotations

import signal
import time
from typing import Any, Callable, Optional, Tuple, Type

# Exit code for a preemption-triggered shutdown after the emergency
# save. Single source: gtopkssgd_tpu/exit_codes.py (EXIT_STALL = stall
# watchdog, EXIT_ANOMALY_HALT = anomaly halt, EXIT_PREEMPTED = this),
# re-exported under the historical name every consumer already imports.
from gtopkssgd_tpu.exit_codes import EXIT_PREEMPTED as PREEMPT_EXIT_CODE


class Preempted(RuntimeError):
    """Raised by the trainer once the emergency checkpoint is durable;
    dist_trainer maps it to PREEMPT_EXIT_CODE."""


class PreemptionGuard:
    """Flag-setting SIGTERM/SIGINT handlers with restore-on-close.

    Installed by dist_trainer (NOT by Trainer.__init__ — a library
    object must not silently steal the host process's signal disposition;
    tests and notebooks embedding a Trainer keep their handlers). The
    handler only sets a flag: everything stateful (the device sync, the
    orbax write) happens on the train loop thread at the next iteration
    boundary, step-granular by construction."""

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT),
                 logger=None):
        self.signals = signals
        self.logger = logger
        self.triggered = False
        self.signum: Optional[int] = None
        self._old: dict = {}
        self._installed = False

    def _handler(self, signum, frame):
        self.triggered = True
        self.signum = signum

    def install(self) -> "PreemptionGuard":
        """Idempotent; a non-main thread (signal.signal raises there)
        degrades to an inert guard rather than failing the run."""
        if self._installed:
            return self
        try:
            for sig in self.signals:
                self._old[sig] = signal.signal(sig, self._handler)
            self._installed = True
        except ValueError:
            if self.logger is not None:
                self.logger.warning(
                    "preemption guard: not on the main thread; signals "
                    "not intercepted")
        return self

    def close(self) -> None:
        """Restore the original handlers (pytest's own SIGINT handling,
        a parent harness's SIGTERM trap)."""
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.close()


def retry_call(fn: Callable[[], Any], *, retries: int = 3,
               delay: float = 0.5, backoff: float = 2.0,
               exceptions: Tuple[Type[BaseException], ...] = (Exception,),
               logger=None, desc: str = "call") -> Any:
    """Call ``fn`` with up to ``retries`` retries on ``exceptions``,
    sleeping delay * backoff**attempt between tries. The final failure
    re-raises the original exception — callers see the true error, with
    the retry history in the log."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            if attempt >= retries:
                raise
            wait = delay * (backoff ** attempt)
            attempt += 1
            if logger is not None:
                logger.warning(
                    "%s failed (%s: %s); retry %d/%d in %.2gs",
                    desc, type(e).__name__, e, attempt, retries, wait)
            time.sleep(wait)
