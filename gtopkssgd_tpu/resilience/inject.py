"""Deterministic, step-keyed fault injection (``--inject SPEC``).

Chaos testing a distributed trainer is only useful when the chaos is
reproducible: a fault keyed to wall-clock or randomness gives every CI
run a different failure and no way to bisect. Here faults are keyed to
the OPTIMIZER STEP, so the same spec produces the same perturbation at
the same point of the same data stream on every run — on a CPU mesh in
CI just as on a pod.

Spec grammar (comma-separated faults)::

    SPEC  := FAULT ("," FAULT)*
    FAULT := KIND (":" ARG)* "@" WHEN
    WHEN  := STEP | STEP "-" STEP | "latest"       (steps are 1-based)

Kinds:

  nan_grad@K          poison the params fed to step K's dispatch (first
                      leaf multiplied by NaN): loss and gradients go NaN
                      exactly like a real numerical blow-up, and the
                      anomaly monitor's nan_loss rule sees it at the next
                      sync. Point faults fire ONCE (consumed), so a
                      skip-recovery that rewinds the step counter does
                      not re-trigger them; a range (``@2-99``) re-fires
                      every step in the window (how the skip-budget
                      exhaustion path is exercised).
  slow_rank:R:DUR@A-B sleep DUR (e.g. ``2.5s`` or ``0.1``) before each
                      step in [A, B] on the process with index R — a
                      deterministic persistent straggler.
  loader_raise@K      raise InjectedLoaderError from the host batch
                      fetch at step K, once; the trainer's retry_call
                      wrapper absorbs it (consumed on first raise, so
                      the retry succeeds).
  preempt@K           deliver SIGTERM to this process right after step
                      K's dispatch — the real signal, through the real
                      PreemptionGuard handler, so the emergency-save
                      path is tested end to end.
  corrupt_ckpt@latest truncate the files of the LATEST checkpoint step
                      right before the next restore() — exercises
                      integrity verification and the fallback to the
                      previous step.
  reshape@K           halve the per-shard batch axis of step K's host
                      batch before device transfer — a NEW dispatch
                      shape, so the jitted step retraces and the
                      executable cache grows (the deterministic input
                      for obs/memwatch.py's recompile_storm rule).
                      Point faults fire once; the next dispatch is back
                      to the canonical shape. A range re-fires per step
                      in the window (sustained storm).
  resize@K:NEWP       elastic-resize request at the step-K boundary:
                      the trainer drains, emergency-saves, rewrites the
                      lineage file for NEWP workers, and unwinds via
                      ResizeRestart -> exit 46 (resilience/elastic.py).
                      WHEN carries the target fleet size (point fault
                      only — a fleet cannot re-form per-step). Requires
                      --elastic; without it the firing records and
                      warns but training continues.
  evict_rank:R@K      eviction-resize request at the step-K boundary:
                      the chaos stand-in for a goodput-advised
                      straggler eviction — same drain/save/exit-46
                      path as resize with reason=evict, new_p = P-1,
                      evicted_ranks=[R]. Point fault only; requires
                      --elastic.

Every firing logs one fsync'd "inject" record (fault, step, detail), so
``report recovery`` can line injected faults up against the recovery
actions they provoked.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Any, List, Optional, Tuple

KINDS = ("nan_grad", "slow_rank", "loader_raise", "preempt", "corrupt_ckpt",
         "reshape", "resize", "evict_rank")

# WHEN == "latest" sentinel (corrupt_ckpt: fires at the next restore).
LATEST = -1


class InjectedLoaderError(IOError):
    """The loader_raise fault; retried away by resilience.retry_call."""


@dataclasses.dataclass
class Fault:
    kind: str
    start: int           # first step of the window (LATEST for @latest)
    end: int             # last step (== start for point faults)
    args: Tuple[str, ...] = ()
    fired: int = 0       # firings so far; point faults are consumed at 1

    @property
    def point(self) -> bool:
        return self.start == self.end

    def window(self, prev: int, new: int) -> Optional[int]:
        """The step in (prev, new] this fault fires for, or None. Point
        faults never re-fire (a skip-recovery rewinds the step counter
        past an already-consumed fault); range faults fire once per
        dispatch while the window overlaps."""
        if self.start == LATEST:
            return None
        if self.point and self.fired:
            return None
        lo, hi = max(self.start, prev + 1), min(self.end, new)
        return lo if lo <= hi else None

    def spec(self) -> str:
        if self.kind == "resize":
            # canonical grammar puts the target P after the step:
            # resize@K:NEWP (args holds NEWP; see parse_inject)
            return f"resize@{self.start}:{self.args[0]}"
        head = ":".join((self.kind,) + self.args)
        if self.start == LATEST:
            return f"{head}@latest"
        if self.point:
            return f"{head}@{self.start}"
        return f"{head}@{self.start}-{self.end}"


def _parse_duration(text: str) -> float:
    seconds = float(text[:-1] if text.endswith("s") else text)
    if seconds < 0:
        raise ValueError(f"negative duration {text!r}")
    return seconds


def parse_inject(spec: str) -> List[Fault]:
    """Parse an ``--inject`` spec; raises ValueError with the offending
    fragment on any malformed input (fail at argparse time, not at step
    K three hours in)."""
    faults: List[Fault] = []
    for frag in (f.strip() for f in spec.split(",") if f.strip()):
        if "@" not in frag:
            raise ValueError(
                f"inject fault {frag!r} has no '@WHEN' (grammar: "
                "KIND[:ARG...]@STEP|A-B|latest)")
        head, _, when = frag.rpartition("@")
        parts = head.split(":")
        kind, args = parts[0], tuple(parts[1:])
        if kind not in KINDS:
            raise ValueError(
                f"unknown inject kind {kind!r} (known: {', '.join(KINDS)})")
        if when == "latest":
            if kind != "corrupt_ckpt":
                raise ValueError(
                    f"@latest only applies to corrupt_ckpt, not {kind!r}")
            start = end = LATEST
        elif kind == "resize":
            # resize@K:NEWP — the WHEN carries the target fleet size,
            # so the generic STEP|A-B parse below does not apply.
            if args:
                raise ValueError(
                    f"resize takes no ':' args before '@'; the target P "
                    f"goes after the step (resize@K:NEWP), got {frag!r}")
            lo, sep, newp = when.partition(":")
            try:
                start = end = int(lo)
                new_p = int(newp) if sep else 0
            except ValueError:
                raise ValueError(
                    f"inject fault {frag!r}: resize WHEN must be "
                    "STEP:NEW_P (e.g. resize@3:1)") from None
            if not sep or start < 1 or new_p < 1:
                raise ValueError(
                    f"inject fault {frag!r}: resize needs STEP >= 1 "
                    "and NEW_P >= 1 (grammar resize@K:NEWP)")
            args = (str(new_p),)
        else:
            lo, sep, hi = when.partition("-")
            try:
                start = int(lo)
                end = int(hi) if sep else start
            except ValueError:
                raise ValueError(
                    f"inject fault {frag!r}: WHEN must be STEP, A-B, or "
                    "latest") from None
            if start < 1 or end < start:
                raise ValueError(
                    f"inject fault {frag!r}: bad step window "
                    f"[{start}, {end}]")
            if kind == "corrupt_ckpt":
                raise ValueError(
                    "corrupt_ckpt is keyed to restore time; use "
                    "corrupt_ckpt@latest")
        if kind == "slow_rank":
            if len(args) != 2:
                raise ValueError(
                    f"slow_rank needs RANK:DURATION args, got {frag!r}")
            int(args[0])
            _parse_duration(args[1])
        elif kind == "evict_rank":
            if len(args) != 1:
                raise ValueError(
                    f"evict_rank needs a RANK arg, got {frag!r}")
            try:
                rank = int(args[0])
            except ValueError:
                raise ValueError(
                    f"evict_rank RANK must be an int, got {frag!r}"
                ) from None
            if rank < 0:
                raise ValueError(
                    f"evict_rank RANK must be >= 0, got {frag!r}")
            if start != end:
                raise ValueError(
                    f"evict_rank is a point fault (a fleet re-forms "
                    f"once, not per-step), got {frag!r}")
        elif kind == "resize":
            pass  # args minted from the WHEN parse above
        elif args:
            raise ValueError(f"{kind} takes no ':' args, got {frag!r}")
        faults.append(Fault(kind=kind, start=start, end=end, args=args))
    if not faults:
        raise ValueError(f"empty inject spec {spec!r}")
    return faults


class FaultInjector:
    """Holds the parsed fault list and exposes one hook per injection
    point; the trainer calls each hook with the host step window
    (prev, new] of the dispatch being prepared or retired. Hooks that
    hit no active fault are O(#faults) comparisons — negligible against
    a training step."""

    def __init__(self, spec: str, metrics=None, logger=None, rank: int = 0):
        self.faults = parse_inject(spec)
        self.metrics = metrics
        self.logger = logger
        self.rank = rank

    def _record(self, fault: Fault, step: int, **extra: Any) -> None:
        fault.fired += 1
        if self.logger is not None:
            self.logger.warning("inject: %s fired at step %d",
                                fault.spec(), step)
        if self.metrics is not None:
            self.metrics.log("inject", flush=True, fault=fault.kind,
                             step=step, spec=fault.spec(), **extra)

    def _active(self, kind: str, prev: int, new: int):
        for f in self.faults:
            if f.kind != kind:
                continue
            at = f.window(prev, new)
            if at is not None:
                yield f, at

    # ------------------------------------------------------------- hooks
    def sleep_if_slow(self, prev: int, new: int) -> float:
        """Pre-dispatch: the slow_rank straggler. Returns seconds slept."""
        slept = 0.0
        for f, at in self._active("slow_rank", prev, new):
            if int(f.args[0]) != self.rank:
                continue
            dur = _parse_duration(f.args[1])
            self._record(f, at, seconds=dur)
            time.sleep(dur)
            slept += dur
        return slept

    def check_loader(self, prev: int, new: int) -> None:
        """Inside the host batch fetch: loader_raise. Consumed on the
        first raise, so the surrounding retry_call's retry succeeds."""
        for f, at in self._active("loader_raise", prev, new):
            self._record(f, at)
            raise InjectedLoaderError(
                f"injected loader failure at step {at}")

    def poison_params(self, state, prev: int, new: int):
        """Pre-dispatch: nan_grad. Multiplies the first params leaf by
        NaN so the dispatched step computes a NaN loss/gradients — the
        same HLO as a clean step (no retrace), and the caller's pre-
        poison snapshot stays the clean state a skip restores."""
        hit = False
        for f, at in self._active("nan_grad", prev, new):
            self._record(f, at)
            hit = True
        if not hit:
            return state
        import jax

        leaves, treedef = jax.tree.flatten(state.params)
        leaves[0] = leaves[0] * float("nan")
        return state._replace(params=jax.tree.unflatten(treedef, leaves))

    def reshape_batch(self, batch, prev: int, new: int, axis: int = 2):
        """Pre-transfer: reshape. Halves the per-shard batch axis of the
        assembled host batch dict (numpy leaves, [P, nsteps, B, ...] —
        ``axis`` indexes B; the trainer passes 3 when steps_per_dispatch
        stacks an extra axis). A changed dispatch shape forces the
        jitted step to retrace — the deterministic recompile chaos
        input. Loss stays a batch mean, so training arithmetic survives
        the smaller step; a 1-sample batch cannot halve and the fault
        downgrades to a no-op record."""
        for f, at in self._active("reshape", prev, new):
            dim = min(v.shape[axis] for v in batch.values())
            if dim < 2:
                self._record(f, at, batch_axis=axis, from_dim=dim,
                             to_dim=dim)
                continue
            half = dim // 2
            self._record(f, at, batch_axis=axis, from_dim=dim, to_dim=half)
            cut = (slice(None),) * axis + (slice(0, half),)
            batch = {k: v[cut] for k, v in batch.items()}
        return batch

    def maybe_preempt(self, prev: int, new: int, guard=None) -> None:
        """Post-dispatch: preempt. Sends this process a REAL SIGTERM so
        the PreemptionGuard handler and the emergency-save path run
        exactly as under an external preemption. Requires an installed
        guard — without one the default handler would hard-kill the
        process, so the fault downgrades to a warning."""
        for f, at in self._active("preempt", prev, new):
            if guard is None:
                if self.logger is not None:
                    self.logger.warning(
                        "inject: preempt@%d skipped — no PreemptionGuard "
                        "installed (run via dist_trainer)", at)
                continue
            self._record(f, at)
            os.kill(os.getpid(), signal.SIGTERM)

    def pending_resize(self, prev: int, new: int) -> Optional[int]:
        """Step-boundary check: resize@K:NEW_P. Returns the target
        fleet size when a resize fault fires in (prev, new], else None.
        The durable "inject" record lands here, BEFORE the trainer's
        drain/save/unwind — the process exits 46 shortly after."""
        for f, at in self._active("resize", prev, new):
            new_p = int(f.args[0])
            self._record(f, at, new_p=new_p)
            return new_p
        return None

    def pending_evict(self, prev: int, new: int) -> Optional[int]:
        """Step-boundary check: evict_rank:R@K — the chaos stand-in for
        a goodput-advised straggler eviction. Returns the rank to
        evict, else None."""
        for f, at in self._active("evict_rank", prev, new):
            rank = int(f.args[0])
            self._record(f, at, evicted_rank=rank)
            return rank
        return None

    def maybe_corrupt_ckpt(self, directory: Optional[str]) -> bool:
        """Restore-time: corrupt_ckpt@latest. Truncates every payload
        file of the latest checkpoint step so orbax's restore raises
        while the step directory still lists — the exact shape of a
        half-written checkpoint after a mid-save kill."""
        fired = False
        for f in self.faults:
            if f.kind != "corrupt_ckpt" or f.fired:
                continue
            if not directory or not os.path.isdir(directory):
                continue
            step_dirs = sorted(
                (int(name), os.path.join(directory, name))
                for name in os.listdir(directory) if name.isdigit())
            if not step_dirs:
                continue
            step, target = step_dirs[-1]
            n = corrupt_checkpoint_dir(target)
            self._record(f, step, files=n)
            fired = True
        return fired

    def summary(self):
        """{kind: firings} over the injector's lifetime."""
        out = {}
        for f in self.faults:
            if f.fired:
                out[f.kind] = out.get(f.kind, 0) + f.fired
        return out


def corrupt_checkpoint_dir(step_dir: str, keep_bytes: int = 16) -> int:
    """Truncate every file over 64 bytes under one checkpoint step dir
    (shared by the injector and tests); returns files corrupted."""
    n = 0
    for root, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                if os.path.getsize(path) > 64:
                    with open(path, "r+b") as fh:
                        fh.truncate(keep_bytes)
                    n += 1
            except OSError:
                continue
    return n
