"""Recovery policies (``--recover-policy``): AnomalyMonitor rules mapped
to actions instead of exit 44.

Policy grammar (comma-separated rules)::

    POLICY := RULE "=" ACTION [":" BUDGET [":" PARAM]] ("," ...)*

RULE is any AnomalyMonitor rule (nan_loss, loss_spike, density_collapse,
residual_blowup, residual_age_runaway, straggler_persistent). Actions:

  skip      discard the just-dispatched update: restore the pre-step
            state snapshot (params, momentum, step count, and the
            error-feedback residual — bit-identical, which matters
            because arXiv:1911.08772 ties convergence to the residual;
            a recovery that zeroes or advances it is silently wrong).
            BUDGET (default 3) bounds CONSECUTIVE skips: a fault that
            persists through N skipped steps is not transient, and the
            claim is refused so the existing halt semantics (exit 44)
            take over. A clean observed step resets the counter.
  rollback  restore the last good checkpoint and replay from it.
            BUDGET (default 2) bounds total rollbacks per rule; PARAM
            (default 0.5) is the backoff base in seconds, doubling per
            use (0.5, 1, 2, ...). With no checkpoint to roll back to
            the claim escalates to the halt path.
  degrade   swap the sparse collective for the dense-allreduce train
            step (same optimizer state treedef — the dense path is the
            warm-up branch of the SAME compiled update, selected by a
            huge warmup_dense_steps), re-entering sparse after a
            cooldown of PARAM steps (default 50). BUDGET (default 3)
            bounds degrade episodes.

The RecoveryManager is the bridge between the monitor and the trainer:
``claim(event)`` (installed as AnomalyMonitor.recovery) answers "will
recovery handle this?" synchronously inside the monitor's emit — a True
suppresses the halt — and queues the action; the trainer applies queued
actions at the end of the same loop iteration, where it owns the state
snapshot and the data iterators. Every action logs one fsync'd
"recovery" record, and the end-of-run summary record (action="summary",
final_status, n_recoveries) is what the gate smoke's structural checks
and ``report recovery`` read.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

ACTIONS = ("skip", "rollback", "degrade")

# Known monitor rules — validated at parse time so a typo'd rule fails
# at argparse, not by silently never matching at 3am.
RULES = ("nan_loss", "loss_spike", "density_collapse", "residual_blowup",
         "residual_age_runaway", "straggler_persistent")

_DEFAULT_BUDGET = {"skip": 3, "rollback": 2, "degrade": 3}
_DEFAULT_PARAM = {"skip": 0.0, "rollback": 0.5, "degrade": 50.0}


@dataclasses.dataclass(frozen=True)
class ActionSpec:
    rule: str
    action: str
    budget: int
    param: float     # rollback: backoff base seconds; degrade: cooldown steps

    def describe(self) -> str:
        bits = f"{self.rule}={self.action}:{self.budget}"
        if self.action == "rollback":
            return bits + f":backoff={self.param:g}s"
        if self.action == "degrade":
            return bits + f":cooldown={self.param:g}"
        return bits


def parse_policy(spec: str) -> Dict[str, ActionSpec]:
    """Parse a ``--recover-policy`` spec into {rule: ActionSpec}."""
    out: Dict[str, ActionSpec] = {}
    for frag in (f.strip() for f in spec.split(",") if f.strip()):
        if "=" not in frag:
            raise ValueError(
                f"recovery rule {frag!r} has no '=' (grammar: "
                "rule=action[:budget[:param]])")
        rule, _, rest = frag.partition("=")
        rule = rule.strip()
        if rule not in RULES:
            raise ValueError(
                f"unknown anomaly rule {rule!r} (known: {', '.join(RULES)})")
        if rule in out:
            raise ValueError(f"rule {rule!r} mapped twice in {spec!r}")
        parts = rest.split(":")
        action = parts[0].strip()
        if action not in ACTIONS:
            raise ValueError(
                f"unknown recovery action {action!r} for rule {rule!r} "
                f"(known: {', '.join(ACTIONS)})")
        try:
            budget = (int(parts[1]) if len(parts) > 1 and parts[1]
                      else _DEFAULT_BUDGET[action])
            param = (float(parts[2]) if len(parts) > 2 and parts[2]
                     else _DEFAULT_PARAM[action])
        except ValueError:
            raise ValueError(
                f"recovery rule {frag!r}: budget must be int, param "
                "float") from None
        if len(parts) > 3:
            raise ValueError(f"recovery rule {frag!r} has extra ':' parts")
        if budget < 1:
            raise ValueError(f"recovery rule {frag!r}: budget must be >= 1")
        out[rule] = ActionSpec(rule=rule, action=action, budget=budget,
                               param=param)
    if not out:
        raise ValueError(f"empty recovery policy {spec!r}")
    return out


def describe_policy(spec: Optional[str]) -> str:
    """One-line human description for the dist_trainer startup print."""
    if not spec:
        return "none (anomalies halt per --obs-halt-on)"
    return "  ".join(s.describe() for s in parse_policy(spec).values())


class RecoveryManager:
    """Budget accounting + the claim/apply handshake with the trainer.

    claim() runs inside AnomalyMonitor._emit (synchronously, before the
    halt decision); apply happens later in the same trainer iteration
    via pop_pending(). A claim is refused (-> normal halt semantics)
    when the rule is unmapped or its budget is exhausted."""

    def __init__(self, policy: Dict[str, ActionSpec], metrics=None,
                 logger=None):
        self.policy = dict(policy)
        self.metrics = metrics
        self.logger = logger
        self.pending: List[Tuple[Dict[str, Any], ActionSpec]] = []
        self.consecutive_skips = 0
        self.rollback_uses: Dict[str, int] = {}
        self.degrade_episodes = 0
        self.degraded = False
        self.n_recoveries = 0
        self.actions: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- claim
    def budget_left(self, spec: ActionSpec) -> int:
        if spec.action == "skip":
            return spec.budget - self.consecutive_skips
        if spec.action == "rollback":
            return spec.budget - self.rollback_uses.get(spec.rule, 0)
        return spec.budget - self.degrade_episodes

    def claim(self, event: Dict[str, Any]) -> bool:
        """AnomalyMonitor.recovery hook: True suppresses the halt and
        queues the action for the trainer's apply phase."""
        spec = self.policy.get(str(event.get("rule")))
        if spec is None:
            return False
        if self.budget_left(spec) <= 0:
            if self.logger is not None:
                self.logger.error(
                    "recovery: %s budget exhausted for rule %s — "
                    "declining claim (halt semantics apply)",
                    spec.action, spec.rule)
            return False
        if spec.action == "degrade" and self.degraded:
            # Already on the dense fallback; nothing further to do, but
            # the claim stands (the degraded run is the recovery).
            return True
        self.pending.append((dict(event), spec))
        return True

    def pop_pending(self) -> List[Tuple[Dict[str, Any], ActionSpec]]:
        out, self.pending = self.pending, []
        return out

    def note_ok(self) -> None:
        """A step was observed clean: transient-fault counters reset."""
        self.consecutive_skips = 0

    # ------------------------------------------------------------ record
    def record(self, action: str, step: int, rule: Optional[str] = None,
               **extra: Any) -> None:
        """Log one recovery action (fsync'd — the run may die on the
        very next step, and the action taken IS the diagnosis)."""
        rec = {"action": action, "step": step, **extra}
        if rule is not None:
            rec["rule"] = rule
        self.actions.append(rec)
        self.n_recoveries += 1
        if self.logger is not None:
            self.logger.warning("recovery: %s at step %d (%s)",
                                action, step, rule or "-")
        if self.metrics is not None:
            self.metrics.log("recovery", flush=True, **rec)
