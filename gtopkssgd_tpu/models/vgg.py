"""VGG-16 for CIFAR-10 (reference C7: vgg.py, the CIFAR VGG variant).

The reference trains VGG-16 on CIFAR-10 as one of its two CIFAR workloads
(paper §experiments). This is the standard CIFAR adaptation of configuration
D: 13 conv layers with BatchNorm+ReLU, five 2x2 max-pools down to 1x1x512,
and a compact classifier head (512 -> 512 -> classes) instead of the
4096-wide ImageNet head.

TPU notes: NHWC layout, 3x3 convs in ``dtype`` (bfloat16-ready for the MXU).
BatchNorm emits activations in ``dtype`` so the inter-conv tensors stay
half-width in HBM; flax still computes the mean/variance reductions in
float32 (``force_float32_reductions``), so statistic precision is unchanged.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

# Configuration D feature stack; 'M' = 2x2 max pool.
_CFG_D: Sequence[Union[int, str]] = (
    64, 64, "M",
    128, 128, "M",
    256, 256, 256, "M",
    512, 512, 512, "M",
    512, 512, 512, "M",
)


class VGG16(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        for v in _CFG_D:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.BatchNorm(use_running_average=not train,
                                 dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # (B, 512) after five pools on 32x32
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
