"""AlexNet for ImageNet (reference C7 — one of the two ImageNet workloads;
the reference likely pulled it from torchvision, SURVEY.md C7 [M]).

Standard single-tower AlexNet (the torchvision variant): five conv layers,
three max pools, 4096-4096-classes classifier with dropout. No BatchNorm —
exactly why the paper uses it as the "huge flat gradient" stress case
(~61M params, dominated by the first FC layer's 38M).

TPU notes: NHWC, compute dtype plumbed for bfloat16; the big FC layers are
pure MXU matmuls.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = lambda f, k, s=1, p=0: nn.Conv(
            f, (k, k), strides=s, padding=p, dtype=self.dtype
        )
        x = nn.relu(conv(64, 11, 4, 2)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(192, 5, 1, 2)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, 3, 1, 1)(x))
        x = nn.relu(conv(256, 3, 1, 1)(x))
        x = nn.relu(conv(256, 3, 1, 1)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))  # (B, 256*6*6)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
