"""Model zoo (reference C7: vgg.py / resnet.py / lstm.py / lstman4.py).

The reference ships one PyTorch nn.Module file per network family and the
trainer instantiates them by the ``--dnn`` flag string. Here each family is a
flax.linen module designed TPU-first: NHWC layouts (XLA's native conv layout),
``dtype`` plumbed through so the whole forward can run in bfloat16 on the MXU
with float32 params, and recurrent models built on ``lax.scan`` cells instead
of cuDNN.

``get_model(dnn)`` mirrors the reference's flag-string dispatch; the returned
``ModelSpec`` also carries the example input shape the trainer/benchmarks use.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import flax.linen as nn

from gtopkssgd_tpu.models.alexnet import AlexNet
from gtopkssgd_tpu.models.lstm import PTBLSTM
from gtopkssgd_tpu.models.lstman4 import DeepSpeechAN4
from gtopkssgd_tpu.models.resnet import ResNetCIFAR, ResNetImageNet
from gtopkssgd_tpu.models.vgg import VGG16


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """A zoo entry: constructor, canonical dataset, example input shape
    (without batch dim), and whether the model is recurrent (the trainer
    branches for BPTT carry + clip-before-compress)."""

    name: str
    build: Callable[..., nn.Module]
    dataset: str
    example_shape: Tuple[int, ...]
    recurrent: bool = False
    has_batchnorm: bool = True


_ZOO: Dict[str, ModelSpec] = {}


def _register(spec: ModelSpec) -> None:
    _ZOO[spec.name] = spec


_register(ModelSpec("vgg16", VGG16, "cifar10", (32, 32, 3)))
_register(
    ModelSpec(
        "resnet20",
        lambda **kw: ResNetCIFAR(depth=20, **kw),
        "cifar10",
        (32, 32, 3),
    )
)
_register(
    ModelSpec(
        "resnet56",
        lambda **kw: ResNetCIFAR(depth=56, **kw),
        "cifar10",
        (32, 32, 3),
    )
)
_register(
    ModelSpec(
        "resnet50",
        ResNetImageNet,
        "imagenet",
        (224, 224, 3),
    )
)
_register(
    ModelSpec(
        "alexnet",
        AlexNet,
        "imagenet",
        (224, 224, 3),
        has_batchnorm=False,
    )
)
_register(
    ModelSpec(
        "lstm",
        PTBLSTM,
        "ptb",
        (35,),  # BPTT window of token ids
        recurrent=True,
        has_batchnorm=False,
    )
)
_register(
    ModelSpec(
        "lstman4",
        DeepSpeechAN4,
        "an4",
        (200, 161),  # (time frames, spectrogram bins)
        recurrent=True,
    )
)


def get_model(dnn: str, **kwargs: Any) -> Tuple[nn.Module, ModelSpec]:
    """Build a zoo model by its reference ``--dnn`` flag string.

    ``space_to_depth`` is accepted for every model so each entry point
    (trainer CLI, benchmark) can forward its flag unconditionally, but it
    is a resnet50-only stem transform: any other model rejects a truthy
    value with a clean error here rather than a constructor TypeError
    deep in flax."""
    try:
        spec = _ZOO[dnn]
    except KeyError:
        raise ValueError(
            f"unknown dnn {dnn!r}; available: {sorted(_ZOO)}"
        ) from None
    if not kwargs.get("space_to_depth", True):
        kwargs.pop("space_to_depth")  # falsy = default stem everywhere
    elif "space_to_depth" in kwargs and dnn != "resnet50":
        raise ValueError(
            f"--s2d is a resnet50 stem transform; --dnn {dnn} "
            "does not take it")
    return spec.build(**kwargs), spec


def available_models():
    return sorted(_ZOO)


__all__ = [
    "get_model",
    "available_models",
    "ModelSpec",
    "VGG16",
    "ResNetCIFAR",
    "ResNetImageNet",
    "AlexNet",
    "PTBLSTM",
    "DeepSpeechAN4",
]
