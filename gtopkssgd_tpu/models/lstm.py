"""2-layer LSTM language model for PTB (reference C7: the PTB LSTM workload).

The reference's PTB model is the classic Zaremba et al. "medium" LM the
paper's LSTM-PTB workload uses: embedding -> 2x LSTM -> tied-size softmax,
trained with BPTT over fixed windows, hidden state carried (and detached)
across windows, gradient-norm clipping BEFORE compression (SURVEY.md §3.4).

TPU-native: the recurrence is a ``flax.linen.RNN`` over
``OptimizedLSTMCell`` — an ``lax.scan`` whose per-step matmuls XLA fuses
onto the MXU, replacing cuDNN. The carry is an explicit pytree the trainer
threads through the jitted step (functional BPTT; "detach" is free because
the carry re-enters as a fresh traced input each window).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

Carry = Tuple  # ((c, h) per layer)


class PTBLSTM(nn.Module):
    vocab_size: int = 10000
    hidden_size: int = 650
    num_layers: int = 2
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    def initial_carry(self, batch_size: int) -> Carry:
        zeros = lambda: (
            jnp.zeros((batch_size, self.hidden_size), self.dtype),
            jnp.zeros((batch_size, self.hidden_size), self.dtype),
        )
        return tuple(zeros() for _ in range(self.num_layers))

    @nn.compact
    def __call__(
        self,
        tokens,  # i32[B, T]
        carry: Optional[Carry] = None,
        *,
        train: bool = False,
    ):
        """Returns (logits f32[B, T, vocab], final_carry)."""
        if carry is None:
            carry = self.initial_carry(tokens.shape[0])
        x = nn.Embed(self.vocab_size, self.hidden_size, dtype=self.dtype)(tokens)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        new_carry = []
        for layer in range(self.num_layers):
            rnn = nn.RNN(
                nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype),
                return_carry=True,
            )
            c, x = rnn(x, initial_carry=carry[layer])
            new_carry.append(c)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype)(x)
        return logits.astype(jnp.float32), tuple(new_carry)
