"""ResNets: CIFAR ResNet-20/56 and ImageNet ResNet-50 (reference C7:
resnet.py — the reference trains ResNet-20/CIFAR-10 and ResNet-50/ImageNet).

Two families, faithful to the original papers the reference used:

  * ``ResNetCIFAR`` — He et al.'s CIFAR design: 3x3 stem, three stages of
    basic blocks at widths 16/32/64, depth = 6n+2 (n=3 -> ResNet-20,
    n=9 -> ResNet-56), global average pool, linear head.
  * ``ResNetImageNet`` — the bottleneck design: 7x7/2 stem + 3x3/2 max pool,
    stages [3,4,6,3] at widths 256/512/1024/2048 for ResNet-50.

TPU notes: NHWC, compute in ``dtype`` (bfloat16 on the MXU). BatchNorm
emits activations in ``dtype`` too — flax computes the mean/variance
reductions in float32 regardless (``force_float32_reductions``), so this
costs no statistic precision, while a float32 BatchNorm output would force
every inter-conv activation tensor to flow through HBM at twice the bytes.
Params stay float32 (flax default ``param_dtype``), so gradient/optimizer/
compressor dtypes are unchanged. Projection (option-B) shortcuts on shape
change.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, dtype=self.dtype
        )
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=1)(y)
        y = norm()(y)
        if x.shape[-1] != self.filters or self.strides != 1:
            x = conv(self.filters, (1, 1), strides=self.strides)(x)
            x = norm()(x)
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    filters: int  # output width (4x the inner width)
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, dtype=self.dtype
        )
        inner = self.filters // 4
        y = conv(inner, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(inner, (3, 3), strides=self.strides, padding=1)(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if x.shape[-1] != self.filters or self.strides != 1:
            x = conv(self.filters, (1, 1), strides=self.strides)(x)
            x = norm()(x)
        return nn.relu(x + y)


class ResNetCIFAR(nn.Module):
    depth: int = 20  # 6n+2
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if (self.depth - 2) % 6 != 0:
            raise ValueError("CIFAR ResNet depth must be 6n+2")
        n = (self.depth - 2) // 6
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, width in enumerate((16, 32, 64)):
            for block in range(n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(width, strides, self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class ResNetImageNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    dtype: Any = jnp.float32
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if self.space_to_depth:
            # MXU-friendly stem: the 7x7/2 conv on [224,224,3] runs at
            # C_in=3 against a 128-lane systolic array (>97% of the
            # input operand is padding). Rearranging 2x2 pixel blocks
            # into channels ([B,230,230,3] -> [B,115,115,12]) and
            # convolving 4x4/VALID is the SAME linear map as an 8x8/2
            # conv whose kernel's last row/col is free (a superset of
            # the 7x7: pad 3+3 keeps the original pad-3 window
            # alignment), at 4x the input channel width. The standard
            # MLPerf-class TPU ResNet-50 transform; exact-equivalence
            # with the 7x7 stem is pinned in
            # tests/test_models.py::test_space_to_depth_stem_equivalence.
            b, h, w, c = x.shape
            assert h % 2 == 0 and w % 2 == 0, (
                f"--s2d needs even input H/W (2x2 pixel blocks), got "
                f"{h}x{w}")
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            x = x.reshape(b, (h + 6) // 2, 2, (w + 6) // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5)
            x = x.reshape(b, (h + 6) // 2, (w + 6) // 2, 4 * c)
            x = nn.Conv(64, (4, 4), padding="VALID", use_bias=False,
                        dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False,
                        dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, blocks in enumerate(self.stage_sizes):
            width = 256 * (2 ** stage)
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(width, strides, self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
