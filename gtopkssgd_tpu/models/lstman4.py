"""Bidirectional LSTM + CTC speech model for AN4 (reference C7: lstman4.py,
deepspeech.pytorch lineage — SequenceWise batchnorm + BatchRNN stacks).

Architecture (DeepSpeech-2 style, sized down for AN4's ~1h of audio):
a 2-layer strided conv front-end over the (time, freq) spectrogram, a stack
of bidirectional LSTM layers with sequence-wise BatchNorm between them, and
a per-frame linear head over the character vocabulary, trained with CTC
(the reference needed the native warp-ctc CUDA lib for this; here the loss
is `optax.ctc_loss`, pure XLA — see gtopkssgd_tpu.trainer).

TPU-native: the BiLSTM is two `lax.scan` directions (`flax.linen.Bidirectional`),
convs NHWC in the compute dtype.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

# Default char vocabulary size: blank + ' + A..Z + space + padding slots,
# matching deepspeech-style English char models (29 labels incl. blank at 0).
AN4_NUM_CHARS = 29


class SequenceWiseBatchNorm(nn.Module):
    """BatchNorm over the collapsed (batch*time) dim — the reference model's
    `SequenceWise(nn.BatchNorm1d)` trick, which normalizes per-feature over
    every frame in the batch."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):  # x: [B, T, F]
        b, t, f = x.shape
        y = x.reshape(b * t, f)
        y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32)(y)
        return y.reshape(b, t, f)


class DeepSpeechAN4(nn.Module):
    num_chars: int = AN4_NUM_CHARS
    rnn_hidden: int = 512
    rnn_layers: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        """x: f32[B, T, F] log-spectrograms. Returns per-frame logits
        f32[B, T', num_chars] with T' = T/4 (two stride-2 convs in time)."""
        b = x.shape[0]
        y = x[..., None]  # [B, T, F, 1]
        y = nn.Conv(32, (11, 41), strides=(2, 2), padding=((5, 5), (20, 20)),
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32)(y)
        y = nn.hard_tanh(y)
        y = nn.Conv(32, (11, 21), strides=(2, 2), padding=((5, 5), (10, 10)),
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32)(y)
        y = nn.hard_tanh(y)
        # [B, T', F', 32] -> [B, T', F'*32]
        y = y.reshape(b, y.shape[1], -1)
        for layer in range(self.rnn_layers):
            if layer > 0:
                y = SequenceWiseBatchNorm()(y, train=train)
            bi = nn.Bidirectional(
                nn.RNN(nn.OptimizedLSTMCell(self.rnn_hidden, dtype=self.dtype)),
                nn.RNN(nn.OptimizedLSTMCell(self.rnn_hidden, dtype=self.dtype)),
                merge_fn=lambda a, b: a + b,  # sum-merge keeps width constant
            )
            y = bi(y)
        y = SequenceWiseBatchNorm()(y, train=train)
        logits = nn.Dense(self.num_chars, dtype=self.dtype)(y)
        return logits.astype(jnp.float32)

    @staticmethod
    def output_length(input_length):
        """Frame count after the two stride-2 convs (for CTC input lengths).
        Each conv: out = (in + 2*pad - kernel)//stride + 1 with pad=5, k=11."""
        t1 = (input_length - 1) // 2 + 1
        return (t1 - 1) // 2 + 1
