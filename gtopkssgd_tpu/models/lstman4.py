"""Bidirectional LSTM + CTC speech model for AN4 (reference C7: lstman4.py,
deepspeech.pytorch lineage — SequenceWise batchnorm + BatchRNN stacks).

Architecture (DeepSpeech-2 style, sized down for AN4's ~1h of audio):
a 2-layer strided conv front-end over the (time, freq) spectrogram, a stack
of bidirectional LSTM layers with per-feature BatchNorm between them (flax
BatchNorm over [B, T, F] reduces over batch*time — exactly the reference's
`SequenceWise(nn.BatchNorm1d)` semantics), and a per-frame linear head over
the character vocabulary, trained with CTC (the reference needed the native
warp-ctc CUDA lib; here the loss is `optax.ctc_loss`, pure XLA — see
gtopkssgd_tpu.trainer).

Variable-length batches: pass ``input_lengths`` (pre-conv frame counts) and
the recurrences honor them — in particular the backward direction of each
BiLSTM starts at the true end of the utterance, not the padded tail
(``flax.linen.RNN(seq_lengths=...)``). BatchNorm statistics still include
padded frames (padding is zeros; acceptable bias, documented).

TPU-native: the BiLSTM is two `lax.scan` directions, convs NHWC in the
compute dtype.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

# Blank at 0, then apostrophe, A..Z, space — deepspeech English labels (29).
AN4_NUM_CHARS = 29


class DeepSpeechAN4(nn.Module):
    num_chars: int = AN4_NUM_CHARS
    rnn_hidden: int = 512
    rnn_layers: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, input_lengths=None, *, train: bool = False):
        """x: f32[B, T, F] log-spectrograms; input_lengths: i32[B] valid
        pre-conv frame counts (None = all T valid). Returns per-frame logits
        f32[B, T', num_chars] with T' = output_length(T)."""
        b = x.shape[0]
        norm = lambda: nn.BatchNorm(use_running_average=not train,
                                    dtype=jnp.float32)
        y = x[..., None]  # [B, T, F, 1]
        y = nn.Conv(32, (11, 41), strides=(2, 2), padding=((5, 5), (20, 20)),
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.hard_tanh(norm()(y))
        y = nn.Conv(32, (11, 21), strides=(2, 2), padding=((5, 5), (10, 10)),
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.hard_tanh(norm()(y))
        # [B, T', F', 32] -> [B, T', F'*32]
        y = y.reshape(b, y.shape[1], -1)
        seq_lengths = (
            None if input_lengths is None else self.output_length(input_lengths)
        )
        for layer in range(self.rnn_layers):
            if layer > 0:
                # Per-feature stats over batch*time: the reference's
                # SequenceWise(BatchNorm1d) — flax reduces all non-feature
                # axes of [B, T, F], which is the same computation.
                y = norm()(y)
            bi = nn.Bidirectional(
                nn.RNN(nn.OptimizedLSTMCell(self.rnn_hidden, dtype=self.dtype)),
                nn.RNN(nn.OptimizedLSTMCell(self.rnn_hidden, dtype=self.dtype)),
                merge_fn=lambda a, b: a + b,  # sum-merge keeps width constant
            )
            y = bi(y, seq_lengths=seq_lengths)
        y = norm()(y)
        logits = nn.Dense(self.num_chars, dtype=self.dtype)(y)
        return logits.astype(jnp.float32)

    @staticmethod
    def output_length(input_length):
        """Frame count after the two stride-2 convs (for CTC input lengths).
        Each conv: out = (in + 2*pad - kernel)//stride + 1 with pad=5, k=11."""
        t1 = (input_length - 1) // 2 + 1
        return (t1 - 1) // 2 + 1
