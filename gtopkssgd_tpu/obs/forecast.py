"""Scale-out forecast plane: a trace-calibrated digital twin of the run.

Four PRs of measurement made the comm plane observable — per-axis
alpha/beta fits (obs/calib.py), the per-link weather map (obs/linkmap.py),
per-stage critical-path budgets (obs/critpath.py), and the goodput
badput taxonomy (obs/goodput.py) — but none of it could *predict*, and
ROADMAP item 3 asks for exactly that: evidence rows at modeled
P ∈ {256, 1024} across axis trees, at the scale where the paper's O(k)
vs O(k log P) distinction (arXiv:1901.04359 §3) actually decides
feasibility. With the accelerator tunnel dead, an analytic model in the
spirit of the portable collective decompositions of arXiv:2112.01075 is
the only honest way to extend the evidence plane past the 2-proc CPU
captures this repo can run — PROVIDED the model is first validated
against the run it was fitted on.

That validation is the **hindcast**: predict THIS run's own step time
from its calibrated fit, its measured compute/select stage budgets, and
its link weather (degraded links priced at their measured multiple, not
the fleet median), then compare against the step time the critpath
records actually measured. The symmetric error factor
``max(pred/meas, meas/pred)`` is logged as a durable ``forecast``
record (fsync'd BEFORE the ``forecast_drift`` rule can raise — same
contract as every durable surface) and gate-pinned on the CPU capture.
A model that hindcasts at 1.1x has earned the right to forecast; one
that drifts past the bound fails fast exactly like ``comm_model_drift``.

The **forecast** then sweeps a grid of (P target, wire schedule, axis
tree), pricing each cell with the same ``predict_comm_ms`` /
``scaling_model.predict`` the planner uses — the run's fitted
alpha/beta, its codec, its bucket partition — and composes predicted
step time and goodput fraction from the measured per-step budgets.
Uncertainty bands come from the Theil-Sen fit's ``resid_ms`` (the
median absolute per-message residual the calibrator already records):
band = messages(schedule, P) x resid_ms, so a latency-noisy fabric
honestly widens the O(P)-message balanced schedule's band faster than
the O(log P) tree's. Committed dcn_probe artifacts predate resid_ms and
carry none — their bands degrade to 0/absent rather than inventing a
noise floor.

Per P target the cheapest cell becomes the recommendation (an exact
string like "balanced@pod", regress-pinned in the registry: a silent
flip of the P=256 recommendation under the same config must fail), and
a powers-of-two scan finds the crossover P where the balanced schedule
overtakes the tree — the single number ROADMAP item 3's feasibility
argument turns on.

Pure-arithmetic module: no jax, importable everywhere the report CLI
runs. The live ``StepForecaster`` rides the calibrator's capture
cadence (--obs-forecast in the trainer); the offline
``summarize_forecast`` rebuilds the same view from any metrics.jsonl.
"""

from __future__ import annotations

from typing import (Any, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

from gtopkssgd_tpu.obs.calib import _ratio_x, message_count
from gtopkssgd_tpu.obs.ledger import (
    DEFAULT_DCN_GBPS,
    DEFAULT_ICI_GBPS,
    _manifest_params,
    load_alpha_beta,
    predict_comm_ms,
    wire_mode_for,
)

# Modeled worker counts (ROADMAP item 3's evidence targets): one pod
# row, one multi-pod row, one "would the paper's regime hold" row.
DEFAULT_TARGETS = (32, 256, 1024)

# Modeled axis trees as (name, ici_size): "flat" prices every hop on
# the slow DCN link (the degenerate topology the repo's multi-process
# CPU runs — and the committed dcn_probe — actually measure); "pod"
# prices 16-chip ICI domains with only the cross-slice hops on DCN
# (scaling_model.py's default slice size). The grid is open: callers
# can pass any (name, ici_size) list.
AXIS_TREES = (("flat", 1), ("pod", 16))

# Wire schedules the planner chooses between (parallel/planner.py
# candidate_plans): the O(k log P) hypercube tree vs Ok-Topk's O(k)
# balanced split-and-reduce.
SCHEDULES = ("tree", "balanced")

# EWMA smoothing for the live budgets — matches linkmap's default.
_EWMA_ALPHA = 0.3

_EPS = 1e-9


def plan_key(schedule: str, tree: str) -> str:
    """The exact recommendation string the registry regress-pins,
    e.g. "tree@pod" / "balanced@flat"."""
    return f"{schedule}@{tree}"


def degrade_factor(links: Any) -> float:
    """Fleet degradation multiplier from per-link EWMA latencies:
    sum(link prices) / (n x fleet median) — i.e. every link priced at
    its MEASURED multiple of the median instead of flattening the fleet
    to one homogeneous link. 1.0 for an empty/homogeneous map; a fleet
    with one 4x link among eight reads ~1.4x, which is exactly the
    factor a schedule touching every link pays. Accepts a {key: ewma_ms}
    mapping, a linkmap record's ``links`` list, or a bare sequence of
    latencies."""
    if isinstance(links, Mapping):
        vals = [float(v) for v in links.values()
                if isinstance(v, (int, float)) and not isinstance(v, bool)]
    else:
        vals = []
        for item in links or ():
            if isinstance(item, Mapping):
                item = item.get("ewma_ms")
            if isinstance(item, (int, float)) and not isinstance(item, bool):
                vals.append(float(item))
    if not vals:
        return 1.0
    s = sorted(vals)
    mid = len(s) // 2
    med = s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])
    if med <= _EPS:
        return 1.0
    return (sum(vals) / len(vals)) / med


def _clean_params(params: Mapping[str, Any]) -> Dict[str, Any]:
    """Normalize a ledger ``_manifest_params``-shaped dict (or a hand
    dict) into the keys the grid needs."""
    return {
        "mode": str(params.get("mode") or "gtopk"),
        "n": int(params["n"]),
        "k": int(params.get("k") or params["n"]),
        "codec": str(params.get("codec") or "fp32"),
        "bucketing": str(params.get("bucketing") or "concat"),
        "buckets": params.get("buckets"),
    }


def _cell_comm_ms(params: Mapping[str, Any], fit: Mapping[str, Any],
                  p: int, schedule: str, ici_size: int
                  ) -> Tuple[str, float]:
    """(wire_mode, modeled comm_ms) of one grid cell — the same
    predict_comm_ms / scaling_model.predict path the planner prices
    candidate plans with, at the forecast target's P and topology."""
    wm = wire_mode_for(params["mode"], schedule, params.get("bucketing"))
    comm = predict_comm_ms(
        wm, int(p), n=params["n"], k=params["k"],
        alpha_ms=float(fit.get("alpha_ms") or 0.0),
        beta_gbps=float(fit.get("beta_gbps") or DEFAULT_DCN_GBPS),
        ici_gbps=float(fit.get("ici_gbps") or DEFAULT_ICI_GBPS),
        ici_size=max(1, int(ici_size)), codec=params["codec"],
        buckets=params.get("buckets"))
    return wm, comm


def grid_rows(params: Mapping[str, Any], fit: Mapping[str, Any], *,
              compute_ms: float, select_ms: float = 0.0,
              degrade_x: float = 1.0,
              targets: Sequence[int] = DEFAULT_TARGETS,
              trees: Sequence[Tuple[str, int]] = AXIS_TREES
              ) -> List[dict]:
    """The forecast grid: one row per (P target, schedule, axis tree).

    step_ms = compute + select + comm x degrade_x, with comm priced by
    the run's own fitted alpha/beta at the cell's topology. The
    uncertainty band is messages x resid_ms (the Theil-Sen noise floor
    per slow-link message) — absent resid_ms (probe-era artifacts) the
    band is 0 rather than invented. goodput_frac is the predicted
    productive fraction compute/step — select and comm are badput under
    the goodput taxonomy, and nothing is clamped: a comm-dominated cell
    honestly reads a tiny fraction. Cells whose (wire_mode, ici_size)
    duplicate an earlier schedule at the same P (dense runs, where
    "balanced" maps back to the same wire) are skipped."""
    params = _clean_params(params)
    resid = fit.get("resid_ms")
    resid = (float(resid)
             if isinstance(resid, (int, float)) and resid > 0 else 0.0)
    rows: List[dict] = []
    for p in targets:
        p = int(p)
        seen: set = set()
        for schedule in SCHEDULES:
            for tree, ici_size in trees:
                wm, comm = _cell_comm_ms(params, fit, p, schedule,
                                         ici_size)
                if (wm, ici_size) in seen:
                    continue
                seen.add((wm, ici_size))
                comm_deg = comm * max(0.0, float(degrade_x))
                step_ms = float(compute_ms) + float(select_ms) + comm_deg
                msgs = message_count(wm, p, ici_size=max(1, int(ici_size)))
                band = msgs * resid
                rows.append({
                    "p": p, "schedule": schedule, "tree": tree,
                    "plan": plan_key(schedule, tree),
                    "ici_size": int(ici_size), "wire_mode": wm,
                    "msgs": msgs,
                    "comm_ms": round(comm, 6),
                    "comm_degraded_ms": round(comm_deg, 6),
                    "step_ms": round(step_ms, 6),
                    "band_ms": round(band, 6),
                    "step_ms_lo": round(step_ms - band, 6),
                    "step_ms_hi": round(step_ms + band, 6),
                    "goodput_frac": (round(float(compute_ms) / step_ms, 6)
                                     if step_ms > 0 else None),
                })
    return rows


def recommend(rows: Iterable[Mapping[str, Any]]) -> Dict[int, dict]:
    """{P: cheapest row} by mid-band step_ms; ties break toward the
    lexicographically first plan key so the pick — and therefore the
    regress-pinned string — is deterministic."""
    best: Dict[int, dict] = {}
    for row in sorted(rows, key=lambda r: (str(r.get("plan")))):
        p = int(row["p"])
        cur = best.get(p)
        if cur is None or row["step_ms"] < cur["step_ms"]:
            best[p] = dict(row)
    return best


def crossover_p(params: Mapping[str, Any], fit: Mapping[str, Any], *,
                compute_ms: float = 0.0, select_ms: float = 0.0,
                degrade_x: float = 1.0, p_max: int = 1024,
                trees: Sequence[Tuple[str, int]] = AXIS_TREES
                ) -> Optional[int]:
    """Smallest power-of-two P (2..p_max) from which the balanced
    schedule's best tree beats the hypercube tree's AT EVERY LARGER
    scanned P too — the O(k) vs O(k log P) crossover the paper's
    scaling argument turns on, required to be sustained (a pod-sized
    fleet where every balanced hop is free ICI can win a single small-P
    cell without the regime actually flipping). None when the tree
    holds at scale (latency-priced fabrics: the balanced schedule's
    O(P) messages each pay alpha)."""
    params = _clean_params(params)
    balanced_wins: List[Tuple[int, bool]] = []
    p = 2
    while p <= max(2, int(p_max)):
        by_schedule: Dict[str, float] = {}
        for schedule in SCHEDULES:
            best = None
            for _, ici_size in trees:
                _, comm = _cell_comm_ms(params, fit, p, schedule,
                                        ici_size)
                if best is None or comm < best:
                    best = comm
            by_schedule[schedule] = (float(compute_ms) + float(select_ms)
                                     + best * max(0.0, float(degrade_x)))
        balanced_wins.append(
            (p, by_schedule["balanced"] < by_schedule["tree"]))
        p *= 2
    cross: Optional[int] = None
    for p, wins in balanced_wins:
        if wins:
            if cross is None:
                cross = p
        else:
            cross = None
    return cross


def hindcast(critpath_records: Iterable[Mapping[str, Any]],
             comm_model_ms: float, *, degrade_x: float = 1.0,
             spd: int = 1) -> Optional[dict]:
    """Predicted vs measured step time over a run's own critpath
    records — the model's validation against the reality it was fitted
    on.

    Per capture (spanning ``spd`` optimizer steps), predicted =
    measured compute + select stage budgets + spd x modeled comm x
    degrade_x; measured = the record's wall. The comm + wait the model
    must explain is exactly what the prediction replaces — wait is a
    skew symptom the degrade factor prices, not a budget to copy
    through. Returns {n, pred_ms, meas_ms, err_x} with err the
    symmetric factor max(pred/meas, meas/pred) over the means, or None
    with no usable records."""
    preds: List[float] = []
    meas: List[float] = []
    spd = max(1, int(spd))
    for rec in critpath_records:
        wall = rec.get("wall_us")
        comp = rec.get("t_compute_us")
        if not isinstance(wall, (int, float)) or wall <= 0 \
                or not isinstance(comp, (int, float)):
            continue
        sel = rec.get("t_select_us")
        sel = float(sel) if isinstance(sel, (int, float)) else 0.0
        pred_us = (float(comp) + sel
                   + spd * float(comm_model_ms) * 1e3
                   * max(0.0, float(degrade_x)))
        preds.append(pred_us / 1e3 / spd)
        meas.append(float(wall) / 1e3 / spd)
    if not preds:
        return None
    pred_ms = sum(preds) / len(preds)
    meas_ms = sum(meas) / len(meas)
    return {
        "n": len(preds),
        "pred_ms": round(pred_ms, 6),
        "meas_ms": round(meas_ms, 6),
        "err_x": round(_ratio_x(pred_ms, meas_ms) or 1.0, 6),
    }


def _flat_record(hc: Mapping[str, Any], rows: Sequence[dict],
                 recs: Mapping[int, dict], fit: Mapping[str, Any], *,
                 compute_ms: float, select_ms: float,
                 comm_model_ms: float, degrade_x: float,
                 cross_p: Optional[int]) -> Dict[str, Any]:
    """The durable ``forecast`` record body: flat per-P fields (so the
    generic exporter maps them straight onto gtopk_forecast_* gauges
    and the registry regress-pins the rec_p* strings) plus the full
    grid under ``rows`` for offline readers."""
    rec: Dict[str, Any] = {
        "hindcast_err_x": hc["err_x"],
        "hindcast_pred_ms": hc["pred_ms"],
        "hindcast_meas_ms": hc["meas_ms"],
        "n_hindcast": hc["n"],
        "compute_ms": round(float(compute_ms), 6),
        "select_ms": round(float(select_ms), 6),
        "comm_model_ms": round(float(comm_model_ms), 6),
        "degrade_x": round(float(degrade_x), 6),
        "alpha_ms": round(float(fit.get("alpha_ms") or 0.0), 6),
        "beta_gbps": round(float(fit.get("beta_gbps")
                                 or DEFAULT_DCN_GBPS), 6),
    }
    resid = fit.get("resid_ms")
    if isinstance(resid, (int, float)) and resid > 0:
        rec["resid_ms"] = round(float(resid), 6)
    if fit.get("fit_source"):
        rec["fit_source"] = str(fit["fit_source"])
    if cross_p is not None:
        rec["crossover_p"] = int(cross_p)
    for p, row in sorted(recs.items()):
        rec[f"rec_p{p}"] = row["plan"]
        rec[f"step_ms_p{p}"] = row["step_ms"]
        rec[f"step_ms_lo_p{p}"] = row["step_ms_lo"]
        rec[f"step_ms_hi_p{p}"] = row["step_ms_hi"]
        if row.get("goodput_frac") is not None:
            rec[f"goodput_frac_p{p}"] = row["goodput_frac"]
    rec["rows"] = list(rows)
    return rec


class StepForecaster:
    """The live forecaster: rides the calibrator's capture cadence.

    Fed the SAME surfaces the trainer already produces — each capture's
    critpath record (stage budgets + measured wall), each calib refit
    (live alpha/beta/resid), each linkmap snapshot (link weather) —
    and, once per capture, composes them into one durable ``forecast``
    record: the hindcast error against this run plus the per-P-target
    grid. The record is written flush=True BEFORE the monitor's
    ``forecast_drift`` rule observes the error, so a drift halt can
    never lose the evidence that triggered it (the linkmap/goodput
    durable-before-halt contract).

    ``params`` is a ledger ``_manifest_params``-shaped dict (the run's
    mode/n/k/codec/schedule/bucketing/buckets); ``baseline`` the
    planner's inputs ({alpha_ms, beta_gbps, ici_gbps, fit_source}) the
    fit starts from until the first calib refit arrives."""

    def __init__(self, params: Mapping[str, Any], *,
                 baseline: Optional[Mapping[str, Any]] = None,
                 targets: Sequence[int] = DEFAULT_TARGETS,
                 trees: Sequence[Tuple[str, int]] = AXIS_TREES,
                 metrics=None, monitor=None,
                 ewma_alpha: float = _EWMA_ALPHA):
        self.params = dict(params)
        self.p = max(1, int(params.get("p") or 1))
        self.schedule = params.get("schedule")
        self.targets = tuple(int(t) for t in targets)
        self.trees = tuple((str(nm), int(sz)) for nm, sz in trees)
        self.metrics = metrics
        self.monitor = monitor
        self.ewma_alpha = float(ewma_alpha)
        base = dict(baseline) if baseline else {}
        self.fit: Dict[str, Any] = {
            "alpha_ms": base.get("alpha_ms"),
            "beta_gbps": base.get("beta_gbps"),
            "ici_gbps": base.get("ici_gbps"),
            "resid_ms": base.get("resid_ms"),
            "fit_source": base.get("fit_source"),
        }
        # Per-step EWMA budgets from critpath captures; None until the
        # first capture (the first sample SEEDS the EWMA rather than
        # being smoothed toward an invented zero) — observe() has
        # nothing honest to say before.
        self.compute_ms: Optional[float] = None
        self.select_ms: Optional[float] = None
        self.meas_ms: Optional[float] = None
        self.degrade_x: float = 1.0
        self.n_obs = 0
        self.records: List[dict] = []

    # ------------------------------------------------------------ feeds
    def _ewma(self, cur: Optional[float], new: float) -> float:
        if cur is None:
            return new
        return cur + self.ewma_alpha * (new - cur)

    def note_critpath(self, cp: Mapping[str, Any], spd: int = 1) -> None:
        """Fold one critpath record's stage budgets (per optimizer
        step) into the EWMA state; ``spd`` is the steps the capture
        spanned."""
        spd = max(1, int(spd))
        wall = cp.get("wall_us")
        comp = cp.get("t_compute_us")
        if not isinstance(wall, (int, float)) or wall <= 0 \
                or not isinstance(comp, (int, float)):
            return
        sel = cp.get("t_select_us")
        sel = float(sel) if isinstance(sel, (int, float)) else 0.0
        self.compute_ms = self._ewma(self.compute_ms,
                                     float(comp) / 1e3 / spd)
        self.select_ms = self._ewma(self.select_ms, sel / 1e3 / spd)
        self.meas_ms = self._ewma(self.meas_ms, float(wall) / 1e3 / spd)

    def note_calib(self, rec: Mapping[str, Any]) -> None:
        """Adopt a calib refit's live fit (alpha_fit_ms/beta_fit_gbps,
        plus its resid_ms noise floor) — the forecast reprices itself
        from measured reality the moment the calibrator does."""
        a, b = rec.get("alpha_fit_ms"), rec.get("beta_fit_gbps")
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and b > 0:
            self.fit["alpha_ms"] = float(a)
            self.fit["beta_gbps"] = float(b)
            self.fit["fit_source"] = "calib"
        r = rec.get("resid_ms")
        if isinstance(r, (int, float)) and r >= 0:
            self.fit["resid_ms"] = float(r)

    def note_linkmap(self, rec: Mapping[str, Any]) -> None:
        """Update the degradation multiplier from a weather-map
        snapshot: links priced at their measured multiple of the
        median."""
        links = rec.get("links")
        if links:
            self.degrade_x = degrade_factor(links)

    # ---------------------------------------------------------- observe
    def observe(self, step: int) -> Optional[dict]:
        """One capture -> durable ``forecast`` record, then the
        ``forecast_drift`` rule. None until a critpath budget exists
        (no honest hindcast without a measured step). May raise
        AnomalyHalt through the monitor — after the record is on
        disk."""
        if self.compute_ms is None or self.meas_ms is None:
            return None
        wm = wire_mode_for(self.params.get("mode") or "gtopk",
                           self.schedule, self.params.get("bucketing"))
        fit = {
            "alpha_ms": self.fit.get("alpha_ms") or 0.0,
            "beta_gbps": self.fit.get("beta_gbps") or DEFAULT_DCN_GBPS,
            "ici_gbps": self.fit.get("ici_gbps") or DEFAULT_ICI_GBPS,
            "resid_ms": self.fit.get("resid_ms"),
            "fit_source": self.fit.get("fit_source"),
        }
        comm_model_ms = predict_comm_ms(
            wm, self.p, n=int(self.params["n"]),
            k=int(self.params.get("k") or self.params["n"]),
            alpha_ms=float(fit["alpha_ms"]),
            beta_gbps=float(fit["beta_gbps"]),
            ici_gbps=float(fit["ici_gbps"]),
            ici_size=max(1, int(self.params.get("ici_size") or 1)),
            codec=str(self.params.get("codec") or "fp32"),
            buckets=self.params.get("buckets"))
        pred_ms = (self.compute_ms + self.select_ms
                   + comm_model_ms * self.degrade_x)
        hc = {
            "n": 1,
            "pred_ms": round(pred_ms, 6),
            "meas_ms": round(self.meas_ms, 6),
            "err_x": round(_ratio_x(pred_ms, self.meas_ms) or 1.0, 6),
        }
        rows = grid_rows(self.params, fit,
                         compute_ms=self.compute_ms,
                         select_ms=self.select_ms,
                         degrade_x=self.degrade_x,
                         targets=self.targets, trees=self.trees)
        recs = recommend(rows)
        cross = crossover_p(self.params, fit,
                            compute_ms=self.compute_ms,
                            select_ms=self.select_ms,
                            degrade_x=self.degrade_x,
                            p_max=max(self.targets) if self.targets
                            else 1024,
                            trees=self.trees)
        rec = _flat_record(hc, rows, recs, fit,
                           compute_ms=self.compute_ms,
                           select_ms=self.select_ms,
                           comm_model_ms=comm_model_ms,
                           degrade_x=self.degrade_x, cross_p=cross)
        rec["step"] = int(step)
        self.n_obs += 1
        rec["n_obs"] = self.n_obs
        self.records.append(rec)
        # Record FIRST (fsync'd), then the rule — a drift halt must not
        # lose the forecast that triggered it.
        if self.metrics is not None:
            self.metrics.log("forecast", flush=True, **rec)
        if self.monitor is not None:
            self.monitor.observe_forecast(int(step),
                                          err_x=hc["err_x"])
        return rec


# --------------------------------------------------------------- offline
def _last_of(records: Sequence[Mapping[str, Any]], kind: str
             ) -> Optional[dict]:
    out = None
    for rec in records:
        if rec.get("kind") == kind:
            out = rec
    return dict(out) if out is not None else None


def summarize_forecast(records: Iterable[Mapping[str, Any]], *,
                       search_dir: Optional[str] = None,
                       nprocs: Optional[int] = None,
                       targets: Optional[Sequence[int]] = None,
                       trees: Sequence[Tuple[str, int]] = AXIS_TREES,
                       spd: int = 1) -> dict:
    """The ``report forecast`` view from any record stream.

    A run that shipped live ``forecast`` records is summarized from its
    LAST one (source "record" — what the run itself durably said).
    Otherwise the summary is rebuilt offline from the same evidence the
    live path composes: manifest params, the last calib refit (else the
    fit-artifact lookup ``load_alpha_beta(search_dir, nprocs)``, else
    planner defaults), mean critpath budgets, and the last weather-map
    snapshot (source "stream"). Returns {"rows": [], "reason": ...}
    when the stream cannot parameterize the model — a report must say
    why it is empty, not guess."""
    records = [r for r in records if isinstance(r, Mapping)]
    targets = (tuple(int(t) for t in targets)
               if targets else DEFAULT_TARGETS)
    last = _last_of(records, "forecast")
    if last is not None:
        recs = {}
        for key, val in last.items():
            if key.startswith("rec_p") and key[5:].isdigit():
                recs[int(key[5:])] = {
                    "plan": str(val),
                    "step_ms": last.get(f"step_ms_p{key[5:]}"),
                    "step_ms_lo": last.get(f"step_ms_lo_p{key[5:]}"),
                    "step_ms_hi": last.get(f"step_ms_hi_p{key[5:]}"),
                    "goodput_frac": last.get(
                        f"goodput_frac_p{key[5:]}"),
                }
        return {
            "source": "record",
            "rows": list(last.get("rows") or ()),
            "recs": recs,
            "hindcast": {
                "n": last.get("n_hindcast"),
                "pred_ms": last.get("hindcast_pred_ms"),
                "meas_ms": last.get("hindcast_meas_ms"),
                "err_x": last.get("hindcast_err_x"),
            },
            "crossover_p": last.get("crossover_p"),
            "fit": {
                "alpha_ms": last.get("alpha_ms"),
                "beta_gbps": last.get("beta_gbps"),
                "resid_ms": last.get("resid_ms"),
                "fit_source": last.get("fit_source"),
            },
            "degrade_x": last.get("degrade_x"),
            "record": last,
        }
    manifest = _last_of(records, "manifest")
    params = _manifest_params(manifest)
    if params is None:
        return {"rows": [], "recs": {}, "hindcast": None,
                "reason": ("no forecast records and no manifest to "
                           "parameterize the model from")}
    # Fit: the run's own last refit wins; an artifact (calib_fit /
    # dcn_probe) is the next-best measured truth; defaults are last.
    calib = _last_of(records, "calib")
    if calib is not None and isinstance(calib.get("alpha_fit_ms"),
                                        (int, float)):
        fit = {"alpha_ms": float(calib["alpha_fit_ms"]),
               "beta_gbps": float(calib.get("beta_fit_gbps")
                                  or DEFAULT_DCN_GBPS),
               "resid_ms": calib.get("resid_ms"),
               "fit_source": "calib-record"}
    else:
        art = load_alpha_beta(search_dir=search_dir, nprocs=nprocs)
        if art is not None:
            fit = {"alpha_ms": art["alpha_ms"],
                   "beta_gbps": art["beta_gbps"],
                   "resid_ms": art.get("resid_ms"),
                   "fit_source": art["source"]}
        else:
            fit = {"alpha_ms": 0.1, "beta_gbps": DEFAULT_DCN_GBPS,
                   "resid_ms": None, "fit_source": "defaults"}
    lm = _last_of(records, "linkmap")
    degrade = degrade_factor(lm.get("links")) if lm else 1.0
    crit = [r for r in records if r.get("kind") == "critpath"]
    if not crit:
        return {"rows": [], "recs": {}, "hindcast": None, "fit": fit,
                "reason": ("no critpath records — the forecast needs "
                           "measured compute/select budgets (run with "
                           "--obs-critpath)")}
    spd = max(1, int(spd))
    comps = [float(r["t_compute_us"]) / 1e3 / spd for r in crit
             if isinstance(r.get("t_compute_us"), (int, float))]
    sels = [float(r["t_select_us"]) / 1e3 / spd for r in crit
            if isinstance(r.get("t_select_us"), (int, float))]
    compute_ms = sum(comps) / len(comps) if comps else 0.0
    select_ms = sum(sels) / len(sels) if sels else 0.0
    wm = wire_mode_for(params["mode"], params.get("schedule"),
                       params.get("bucketing"))
    comm_model_ms = predict_comm_ms(
        wm, params["p"], n=params["n"], k=params["k"],
        alpha_ms=float(fit["alpha_ms"]),
        beta_gbps=float(fit["beta_gbps"]),
        codec=params["codec"], buckets=params.get("buckets"))
    hc = hindcast(crit, comm_model_ms, degrade_x=degrade, spd=spd)
    rows = grid_rows(params, fit, compute_ms=compute_ms,
                     select_ms=select_ms, degrade_x=degrade,
                     targets=targets, trees=trees)
    recs = recommend(rows)
    cross = crossover_p(params, fit, compute_ms=compute_ms,
                        select_ms=select_ms, degrade_x=degrade,
                        p_max=max(targets), trees=trees)
    return {
        "source": "stream",
        "rows": rows,
        "recs": recs,
        "hindcast": hc,
        "crossover_p": cross,
        "fit": fit,
        "degrade_x": round(degrade, 6),
        "comm_model_ms": round(comm_model_ms, 6),
        "compute_ms": round(compute_ms, 6),
        "select_ms": round(select_ms, 6),
    }


def format_forecast(summary: Mapping[str, Any]) -> str:
    """The ``report forecast`` text: hindcast line (the model's earned
    credibility), the per-P grid with uncertainty columns, the
    recommendation per target, and the tree->balanced crossover."""
    rows = summary.get("rows") or []
    if not rows:
        return ("forecast: " + str(summary.get(
            "reason", "no forecast evidence in this stream")))
    lines: List[str] = []
    fit = summary.get("fit") or {}
    src = fit.get("fit_source") or "?"
    lines.append(
        f"forecast: fit alpha_ms={fit.get('alpha_ms')} "
        f"beta_gbps={fit.get('beta_gbps')} "
        f"resid_ms={fit.get('resid_ms')} [{src}]  "
        f"(from {summary.get('source', '?')})")
    hc = summary.get("hindcast")
    if hc and isinstance(hc.get("err_x"), (int, float)):
        lines.append(
            f"hindcast: predicted {hc.get('pred_ms')} ms vs measured "
            f"{hc.get('meas_ms')} ms over n={hc.get('n')} capture(s) "
            f"-> err {float(hc['err_x']):.2f}x")
    dx = summary.get("degrade_x")
    if isinstance(dx, (int, float)) and abs(float(dx) - 1.0) > 1e-6:
        lines.append(f"link degradation multiplier: {float(dx):.3f}x "
                     "(links priced at their measured multiple)")
    header = ["p", "plan", "wire", "step_ms", "lo", "hi", "comm_ms",
              "goodput"]
    table: List[List[str]] = []
    for r in sorted(rows, key=lambda r: (int(r.get("p", 0)),
                                         str(r.get("plan")))):
        gp = r.get("goodput_frac")
        table.append([
            str(r.get("p")), str(r.get("plan")),
            str(r.get("wire_mode", "?")),
            f"{float(r.get('step_ms', 0.0)):.3f}",
            f"{float(r.get('step_ms_lo', 0.0)):.3f}",
            f"{float(r.get('step_ms_hi', 0.0)):.3f}",
            f"{float(r.get('comm_ms', 0.0)):.3f}",
            ("-" if not isinstance(gp, (int, float))
             else f"{float(gp):.3f}"),
        ])
    cols = [max(len(str(row[i])) for row in [header] + table)
            for i in range(len(header))]
    for row in [header, ["-" * w for w in cols]] + table:
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(row, cols)))
    recs = summary.get("recs") or {}
    for p in sorted(recs):
        r = recs[p]
        step = r.get("step_ms")
        lo, hi = r.get("step_ms_lo"), r.get("step_ms_hi")
        band = ""
        if isinstance(lo, (int, float)) and isinstance(hi, (int, float)):
            band = f" [{float(lo):.3f}, {float(hi):.3f}]"
        lines.append(f"recommendation P={p}: {r.get('plan')} "
                     f"(step {step} ms{band})")
    cross = summary.get("crossover_p")
    if cross is not None:
        lines.append(f"crossover: balanced overtakes tree at P={cross}")
    else:
        lines.append("crossover: none in range (tree holds — the "
                     "balanced schedule's O(P) messages each pay the "
                     "fitted alpha)")
    return "\n".join(lines)
