"""Online comm-model calibrator: live alpha/beta from the ledger stream.

Every scheduling decision in the stack — the planner's tree-vs-balanced
choice (parallel/planner.py) and the bucketing DP (parallel/bucketing.py)
— is priced off a STATIC ``dcn_probe`` fit that cannot be refreshed
while the accelerator tunnel is dead. The ledger (obs/ledger.py) already
joins measured per-step comm time and wire bytes against that model;
this module turns the same stream into a live {alpha_ms, beta_gbps}
estimate, so the comm model calibrates itself on whatever fabric a run
actually lands on.

The estimator is the alpha-beta decomposition ``predict_comm_ms`` prices
with, inverted: one merge under a schedule launches ``msgs`` slow-link
messages (tree rounds, the balanced schedule's 2(p-1) hops, ...), so

    t_ms / msgs  =  alpha_ms  +  (wire_bytes / msgs) * 8e-6 / beta_gbps

is a straight line in (bytes-per-message, ms-per-message) space
REGARDLESS of schedule or worker count — samples from different plans
regress the same two constants. The fit is Theil-Sen (median of pairwise
slopes, intercept from the median residual): a straggler-inflated sample
is a point-outlier, and the median survives up to ~29% of them where a
least-squares line would be dragged arbitrarily far (pinned under 10%
injected stragglers in tests/test_calibration.py). When the observed
bytes barely vary the slope is unidentifiable; the fit degrades honestly
to alpha-only (beta held at the baseline) instead of hallucinating a
bandwidth from noise.

Per refit window the calibrator logs one ``"calib"`` record (fsync'd —
the fit is a diagnosis that must survive a hard kill), feeds the
AnomalyMonitor's ``comm_model_drift`` rule with the fit-vs-planner
divergence (so ``--obs-halt-on`` covers a comm model gone stale like any
other anomaly), and at end of run writes a ``dcn_probe``-compatible
``calib_fit_{P}proc.json`` artifact that ``ledger.load_alpha_beta`` /
``planner_inputs`` consume on the next run — closing the obs->planner
loop: the planner and the bucketing DP reprice themselves from measured
reality instead of a stale probe.
"""

from __future__ import annotations

import json
import math
import os
import statistics
from typing import Any, Dict, List, Mapping, Optional, Tuple

from gtopkssgd_tpu.obs.ledger import (
    DEFAULT_DCN_GBPS,
    _tree_rounds_fallback,
)
from gtopkssgd_tpu.obs import linkmap as _linkmap

# ICI fallback bandwidth for the per-axis split/fit baseline (same
# value parallel/planner.py prices un-measured ici hops with).
_DEFAULT_ICI_GBPS = 1600.0

# bytes -> ms conversion at 1 Gbps: t_ms = bytes * 8 / (beta_gbps * 1e9)
# * 1e3 = bytes * _MS_PER_BYTE_AT_1GBPS / beta_gbps.
_MS_PER_BYTE_AT_1GBPS = 8e-6

# Relative spread of bytes-per-message below which the slope (and so
# beta) is treated as unidentifiable and the fit degrades to alpha-only.
_MIN_X_SPREAD = 0.05

# Newest samples used per fit: Theil-Sen is O(n^2) pairs, and recent
# samples describe the fabric NOW (the whole point of live calibration).
_FIT_WINDOW = 256


def message_count(wire_mode: str, p: int, *, ici_size: int = 1) -> int:
    """Slow-link message launches of ONE merge under ``wire_mode`` — the
    alpha multiplier of exactly the decomposition ``predict_comm_ms``
    prices, so inverting it recovers the same constants the planner
    consumes. 0 at p<=1 (nothing on the wire to calibrate from)."""
    p = int(p)
    if p <= 1:
        return 0
    if wire_mode == "dense":
        return 2 * (p - 1)
    if wire_mode == "gtopk_balanced":
        return 2 * (p - 1)
    if wire_mode == "allgather":
        return p - 1
    if wire_mode == "gtopk_hier":
        return _tree_rounds_fallback(max(1, p // max(1, int(ici_size))))
    # gtopk / gtopk_layerwise hypercube tree
    return _tree_rounds_fallback(p)


def _finite(x: Any) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def fit_alpha_beta(samples,
                   baseline_beta_gbps: float = DEFAULT_DCN_GBPS
                   ) -> Optional[Dict[str, Any]]:
    """Robust {alpha_ms, beta_gbps} from (msgs, wire_bytes, t_comm_ms)
    triples. Theil-Sen over per-message-normalized points; None below 2
    usable samples. ``identifiable`` reports whether the byte spread
    supported a slope ("alpha_beta") or the fit held beta at
    ``baseline_beta_gbps`` ("alpha_only"). ``resid_ms`` is the median
    absolute residual in ms-per-message — the fit's noise floor."""
    pts: List[Tuple[float, float]] = []
    for msgs, wire_bytes, t_ms in samples:
        if (not _finite(msgs) or msgs <= 0 or not _finite(wire_bytes)
                or wire_bytes <= 0 or not _finite(t_ms) or t_ms <= 0):
            continue
        pts.append((float(wire_bytes) / msgs, float(t_ms) / msgs))
    if len(pts) < 2:
        return None
    pts.sort()
    xs = [x for x, _ in pts]
    x_med = statistics.median(xs)
    spread = ((max(xs) - min(xs)) / x_med) if x_med > 0 else 0.0
    slope = None
    if spread >= _MIN_X_SPREAD:
        slopes = []
        for i in range(len(pts)):
            xi, yi = pts[i]
            for xj, yj in pts[i + 1:]:
                if xj > xi:
                    slopes.append((yj - yi) / (xj - xi))
        if slopes:
            slope = statistics.median(slopes)
    if slope is None or slope <= 0:
        # Slope unidentifiable (constant bytes, or noise produced a
        # non-physical negative): hold beta at the baseline, fit alpha.
        beta = float(baseline_beta_gbps) or DEFAULT_DCN_GBPS
        slope_used = _MS_PER_BYTE_AT_1GBPS / beta
        identifiable = "alpha_only"
    else:
        beta = _MS_PER_BYTE_AT_1GBPS / slope
        slope_used = slope
        identifiable = "alpha_beta"
    alpha = max(0.0, statistics.median(
        [y - slope_used * x for x, y in pts]))
    resid = statistics.median(
        [abs(y - (alpha + slope_used * x)) for x, y in pts])
    return {"alpha_ms": float(alpha), "beta_gbps": float(beta),
            "n_samples": len(pts), "resid_ms": float(resid),
            "identifiable": identifiable}


def load_fit_file(path: str) -> Dict[str, Any]:
    """Explicit fit-artifact loader (the ``--comm-model-fit PATH``
    override): any dcn_probe / calib_fit shaped JSON. Raises ValueError
    on a file without a usable ``alpha_beta_fit`` — an explicit flag
    must fail at startup, never silently fall back."""
    with open(path) as fh:
        doc = json.load(fh)
    fit = doc.get("alpha_beta_fit") or {}
    alpha, beta = fit.get("alpha_ms"), fit.get("beta_gbps")
    if not _finite(alpha) or not _finite(beta) or beta <= 0:
        raise ValueError(
            f"{path}: no usable alpha_beta_fit "
            "(need numeric alpha_ms and beta_gbps > 0)")
    return {"alpha_ms": float(alpha), "beta_gbps": float(beta),
            "source": os.path.basename(path)}


def _ratio_x(fit: Optional[float], ref: Optional[float]
             ) -> Optional[float]:
    """Symmetric divergence factor max(fit/ref, ref/fit), floored at
    1e-6 per side so a collapsed-to-zero fit reads as a huge (finite)
    drift rather than a JSON-breaking inf."""
    if not _finite(fit) or not _finite(ref):
        return None
    a, b = max(float(fit), 1e-6), max(float(ref), 1e-6)
    return max(a / b, b / a)


class CommCalibrator:
    """Online fitter over the run's own measured (wire_bytes, t_comm)
    samples.

    ``wire_mode``/``p`` fix the message-count normalization (the
    schedule that actually runs — CommPlan.wire_mode); ``baseline`` is
    the planner's committed inputs ({alpha_ms, beta_gbps, fit_source},
    i.e. ``planner_inputs``'s dict) that drift is measured against;
    ``metrics`` a MetricsLogger (or None for in-memory use); ``monitor``
    an AnomalyMonitor fed through ``observe_comm_model`` on every refit.
    A refit runs every ``refit_interval`` NEW samples once
    ``min_samples`` have accumulated."""

    def __init__(self, wire_mode: str, p: int, *,
                 baseline: Optional[Mapping[str, Any]] = None,
                 metrics=None, monitor=None,
                 refit_interval: int = 4, min_samples: int = 4,
                 fit_window: int = _FIT_WINDOW,
                 max_samples: int = 4096, ici_size: int = 1):
        self.wire_mode = str(wire_mode)
        self.p = int(p)
        self.ici_size = max(1, int(ici_size))
        self.msgs = message_count(self.wire_mode, self.p,
                                  ici_size=self.ici_size)
        self.baseline = dict(baseline) if baseline else {}
        self.metrics = metrics
        self.monitor = monitor
        self.refit_interval = max(1, int(refit_interval))
        self.min_samples = max(2, int(min_samples))
        self.fit_window = max(2, int(fit_window))
        self.max_samples = max(self.fit_window, int(max_samples))
        # (msgs, wire_bytes, t_comm_ms) triples, oldest first.
        self.samples: List[Tuple[int, float, float]] = []
        # Per-axis sample pools: each blended sample is split per mesh
        # axis by the weather map's proportional carve (the rank-0 view
        # of the schedule — symmetric for the modes we run), so hier's
        # ici and dcn hops accumulate SEPARATE (msgs, bytes, t) pools
        # and refit/write_artifact can price each hop from its own
        # measured fit. For single-axis modes the "dcn" pool mirrors
        # the blended one (and its fit matches the blended fit).
        self._axis_rounds = _linkmap.rank_rounds(
            _linkmap.round_peers(self.wire_mode, self.p,
                                 ici_size=self.ici_size), 0)
        self.axis_samples: Dict[str, List[Tuple[int, float, float]]] = {}
        # Last per-axis refit fits, keyed by axis name.
        self.axis_fits: Dict[str, Dict[str, Any]] = {}
        # Samples measured under an OVERLAPPED pipeline, kept apart:
        # their t_comm is the exposed (partially hidden) span, so the
        # per-message alpha-beta inversion does not hold for them —
        # folding them in would bias the serial fit low. Tagged via
        # observe(..., overlapped=True), counted in the calib record,
        # never fitted.
        self.overlap_samples: List[Tuple[int, float, float]] = []
        # First completed fit — the "startup fit" drift is reported
        # against (did the fabric change DURING the run?).
        self.startup_fit: Optional[Dict[str, Any]] = None
        self.fits: List[Dict[str, Any]] = []
        self._pending = 0

    def observe(self, step: int, wire_bytes: float, t_comm_ms: float,
                msgs: Optional[int] = None,
                overlapped: bool = False) -> Optional[Dict[str, Any]]:
        """Ingest one measured sample; returns the ``calib`` record when
        this sample completed a refit window, else None. ``msgs``
        overrides the per-merge message count (bucketed runs: B merges
        per step multiply it). ``overlapped`` tags a sample measured
        under the overlapped bucket pipeline: its t_comm is the exposed
        span with part of the wire time hidden under selection, so it
        is retained separately (``overlap_samples``) and NEVER enters
        the serial alpha-beta fit. Raises AnomalyHalt through the
        monitor when a refit's drift reaches the halt severity — after
        the calib record is durably written."""
        m = self.msgs if msgs is None else int(msgs)
        if (m <= 0 or not _finite(wire_bytes) or wire_bytes <= 0
                or not _finite(t_comm_ms) or t_comm_ms <= 0):
            return None
        if overlapped:
            self.overlap_samples.append(
                (m, float(wire_bytes), float(t_comm_ms)))
            if len(self.overlap_samples) > self.max_samples:
                del self.overlap_samples[
                    :len(self.overlap_samples) - self.max_samples]
            return None
        self.samples.append((m, float(wire_bytes), float(t_comm_ms)))
        if len(self.samples) > self.max_samples:
            del self.samples[:len(self.samples) - self.max_samples]
        self._split_axes(m, float(wire_bytes), float(t_comm_ms))
        self._pending += 1
        if (self._pending >= self.refit_interval
                and len(self.samples) >= self.min_samples):
            return self.refit(step)
        return None

    def _split_axes(self, msgs: int, wire_bytes: float,
                    t_comm_ms: float) -> None:
        """Split one blended sample per mesh axis via the weather map's
        proportional carve and append to the per-axis pools. The axis
        message count scales with any caller msgs override (bucketed
        runs launch B merges per sample)."""
        mine = self._axis_rounds
        if not mine:
            return
        weights = _linkmap.round_weights(
            mine, wire_bytes,
            beta_gbps=(self.baseline.get("beta_gbps")
                       or DEFAULT_DCN_GBPS),
            ici_gbps=(self.baseline.get("ici_gbps")
                      or _DEFAULT_ICI_GBPS))
        carved = _linkmap.carve_rounds(t_comm_ms, weights)
        per_round_bytes = wire_bytes / len(mine)
        scale = msgs / self.msgs if self.msgs > 0 else 1.0
        agg: Dict[str, List[float]] = {}
        for rd, t_ms in zip(mine, carved):
            a = agg.setdefault(rd["axis"], [0.0, 0.0, 0.0])
            a[0] += 1.0
            a[1] += per_round_bytes
            a[2] += t_ms
        for axis, (n_rounds, b, t) in agg.items():
            pool = self.axis_samples.setdefault(axis, [])
            pool.append((max(1, round(n_rounds * scale)), b, t))
            if len(pool) > self.max_samples:
                del pool[:len(pool) - self.max_samples]

    def _fit_axes(self, window: Optional[int]
                  ) -> Dict[str, Dict[str, Any]]:
        """Per-axis alpha/beta fits over the newest ``window`` samples
        of each pool (None = all). Only axes whose pool supports a fit
        appear; ici pools fall back to the ici baseline bandwidth when
        the slope is unidentifiable."""
        out: Dict[str, Dict[str, Any]] = {}
        for axis in sorted(self.axis_samples):
            pool = self.axis_samples[axis]
            if window is not None:
                pool = pool[-window:]
            if len(pool) < self.min_samples:
                continue
            base_beta = (
                (self.baseline.get("ici_gbps") or _DEFAULT_ICI_GBPS)
                if axis == _linkmap.AXIS_ICI
                else (self.baseline.get("beta_gbps")
                      or DEFAULT_DCN_GBPS))
            fit = fit_alpha_beta(pool, baseline_beta_gbps=base_beta)
            if fit is not None:
                out[axis] = fit
        return out

    def refit(self, step: int) -> Optional[Dict[str, Any]]:
        """Fit over the newest window, log the ``calib`` record
        (flush=True), feed the drift rule. None below min data."""
        fit = fit_alpha_beta(
            self.samples[-self.fit_window:],
            baseline_beta_gbps=(self.baseline.get("beta_gbps")
                                or DEFAULT_DCN_GBPS))
        if fit is None:
            return None
        self._pending = 0
        base_a = self.baseline.get("alpha_ms")
        base_b = self.baseline.get("beta_gbps")
        rec: Dict[str, Any] = {
            "step": int(step),
            "alpha_fit_ms": round(fit["alpha_ms"], 6),
            "beta_fit_gbps": round(fit["beta_gbps"], 6),
            "n_samples": fit["n_samples"],
            "resid_ms": round(fit["resid_ms"], 6),
            "identifiable": fit["identifiable"],
            "wire_mode": self.wire_mode,
            "p": self.p,
        }
        if self.overlap_samples:
            # Visible evidence the exclusion worked: how many tagged
            # overlapped samples were kept OUT of this serial fit.
            rec["n_overlap_excluded"] = len(self.overlap_samples)
        if self.baseline.get("fit_source") is not None:
            rec["planner_fit_source"] = self.baseline["fit_source"]
        da, db = _ratio_x(fit["alpha_ms"], base_a), _ratio_x(
            fit["beta_gbps"], base_b)
        if da is not None:
            rec["drift_alpha_x"] = round(da, 6)
        if db is not None:
            rec["drift_beta_x"] = round(db, 6)
        # Per-axis fits ride the same record under dotted keys (the
        # registry flattens them as alpha_ms.<axis> stats): for hier
        # this prices the ici and dcn hops separately; for single-axis
        # modes the dcn fit mirrors the blended one.
        self.axis_fits = self._fit_axes(self.fit_window)
        for axis, axfit in sorted(self.axis_fits.items()):
            rec[f"alpha_ms.{axis}"] = round(axfit["alpha_ms"], 6)
            rec[f"beta_gbps.{axis}"] = round(axfit["beta_gbps"], 6)
            rec[f"n_samples.{axis}"] = axfit["n_samples"]
        if self.startup_fit is None:
            self.startup_fit = dict(fit)
        else:
            sa = _ratio_x(fit["alpha_ms"], self.startup_fit["alpha_ms"])
            sb = _ratio_x(fit["beta_gbps"], self.startup_fit["beta_gbps"])
            if sa is not None:
                rec["drift_alpha_startup_x"] = round(sa, 6)
            if sb is not None:
                rec["drift_beta_startup_x"] = round(sb, 6)
        self.fits.append(rec)
        # Record FIRST (fsync'd), then the rule — a drift halt must not
        # lose the fit that triggered it.
        if self.metrics is not None:
            self.metrics.log("calib", flush=True, **rec)
        if self.monitor is not None and (base_a is not None
                                         or base_b is not None):
            self.monitor.observe_comm_model(
                int(step), fit["alpha_ms"], fit["beta_gbps"],
                ref_alpha_ms=base_a, ref_beta_gbps=base_b,
                fit_source=self.baseline.get("fit_source"))
        return rec

    def final_fit(self) -> Optional[Dict[str, Any]]:
        """Fit over every retained sample (not just the last window) —
        what the end-of-run artifact records."""
        return fit_alpha_beta(
            self.samples,
            baseline_beta_gbps=(self.baseline.get("beta_gbps")
                                or DEFAULT_DCN_GBPS))

    def final_axis_fits(self) -> Dict[str, Dict[str, Any]]:
        """Per-axis fits over every retained sample — the artifact's
        ``axes`` section."""
        return self._fit_axes(None)

    def write_artifact(self, out_dir: str, *,
                       manifest: Optional[Mapping[str, Any]] = None,
                       nprocs: Optional[int] = None) -> Optional[str]:
        """Write the dcn_probe-compatible ``calib_fit_{P}proc.json``
        (atomic rename) that ``ledger.load_alpha_beta`` — and so
        ``planner_inputs`` on the next run — consumes. ``manifest``
        stamps run provenance (config_hash, git_sha, headline flags).
        Returns the path, or None when too few samples ever arrived."""
        fit = self.final_fit()
        if fit is None:
            return None
        procs = int(nprocs if nprocs is not None else self.p)
        provenance: Dict[str, Any] = {}
        for key in ("config_hash", "git_sha", "compression", "density",
                    "wire_codec", "nworkers", "comm_plan_schedule"):
            if manifest is not None and manifest.get(key) is not None:
                provenance[key] = manifest[key]
        beta = round(fit["beta_gbps"], 3)
        if beta <= 0:  # sub-milli-Gbps fabric: keep full precision
            beta = fit["beta_gbps"]
        payload = {
            "procs": procs,
            "source": "obs/calib.py",
            "wire_mode": self.wire_mode,
            "n_samples": len(self.samples),
            "provenance": provenance,
            "alpha_beta_fit": {
                "alpha_ms": round(fit["alpha_ms"], 4),
                "beta_gbps": beta,
                "n_samples": fit["n_samples"],
                "resid_ms": round(fit["resid_ms"], 6),
                "identifiable": fit["identifiable"],
                "note": ("t(bytes) = alpha + bytes*8/beta_gbps/1e9; "
                         "fitted in-run from measured (wire_bytes, "
                         "t_comm) samples, Theil-Sen per-message "
                         "normalization (obs/calib.py)"),
            },
        }
        # Per-axis section (ici/dcn today, arbitrary axis names later):
        # ledger.load_alpha_beta surfaces it and planner_inputs prices
        # hier's two hops from the two measured fits instead of the
        # blended one. Only axes with a usable fit appear.
        axes = {}
        for axis, axfit in sorted(self.final_axis_fits().items()):
            axes[axis] = {
                "alpha_ms": round(axfit["alpha_ms"], 4),
                "beta_gbps": (round(axfit["beta_gbps"], 3)
                              if axfit["beta_gbps"] > 1e-3
                              else axfit["beta_gbps"]),
                "n_samples": axfit["n_samples"],
                "resid_ms": round(axfit["resid_ms"], 6),
                "identifiable": axfit["identifiable"],
            }
        if axes:
            payload["axes"] = axes
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"calib_fit_{procs}proc.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
        return path
