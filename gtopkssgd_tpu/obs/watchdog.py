"""Dispatch stall watchdog — fail fast with a diagnostic, never hang blind.

The failure mode this exists for (BENCH_r04/r05): a step is dispatched to
a tunneled accelerator, the tunnel dies, and the next host-side read of a
device value blocks FOREVER inside the PJRT client — the run spends its
whole uptime window hung with zero diagnostics. A blocked C-extension call
cannot be interrupted from Python, so the only honest remedy is a monitor
THREAD that notices the main thread has been waiting too long, emits a
structured diagnostic record (last completed step, phase means, backend
info), and fails the process fast so the retry loop gets the window back.

Protocol (trainer.train wires this up):

    wd.arm("train_step", step=s)      # entering a region that must make
                                      # progress within deadline_s
    wd.heartbeat(step=s)              # progress proof — resets the clock
                                      # (call AFTER a blocking device read,
                                      # not after an async dispatch: an
                                      # enqueue succeeding proves nothing)
    wd.disarm()                       # leaving the region

Device/backend info is captured EAGERLY at construction: querying a wedged
backend from the monitor thread could itself hang.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

# Exit code for a detected stall — distinct from generic failure so the
# driver/retry loop can classify hung-tunnel runs without parsing logs.
# Single source: gtopkssgd_tpu/exit_codes.py (re-exported here under the
# historical name every consumer already imports).
from gtopkssgd_tpu.exit_codes import EXIT_STALL as STALL_EXIT_CODE


def _device_info() -> Dict[str, object]:
    try:
        import jax

        dev = jax.devices()[0]
        return {
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", "?"),
            "device_count": jax.device_count(),
            "process_index": jax.process_index(),
        }
    except Exception as e:  # backend not initialized / already dead
        return {"error": repr(e)}


def _default_on_stall(record: Dict[str, object]) -> None:
    """Last-resort action: dump the diagnostic to stderr and hard-exit.
    os._exit, not sys.exit — the main thread is blocked in a C call and
    will never run an exception handler or atexit hook."""
    print("STALL WATCHDOG: " + json.dumps(record), file=sys.stderr,
          flush=True)
    os._exit(STALL_EXIT_CODE)


class StallWatchdog:
    """Monitor thread that fires when an armed region exceeds its deadline.

    ``on_stall(record)`` is called ONCE (from the monitor thread) with the
    structured diagnostic; the default dumps it to stderr and hard-exits
    with STALL_EXIT_CODE. ``diagnostics`` is an optional zero-arg callable
    whose dict is merged into the record at fire time (the trainer passes
    its span phase-means through here) — it must only touch host-side
    state, never the device."""

    def __init__(
        self,
        deadline_s: float,
        *,
        on_stall: Optional[Callable[[Dict[str, object]], None]] = None,
        diagnostics: Optional[Callable[[], Dict[str, object]]] = None,
        poll_s: Optional[float] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.poll_s = poll_s if poll_s is not None else min(
            1.0, self.deadline_s / 4)
        self._on_stall = on_stall or _default_on_stall
        self._diagnostics = diagnostics
        self.device_info = _device_info()
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._label: Optional[str] = None
        self._armed_step: Optional[int] = None
        self._last_step: Optional[int] = None
        self._fired = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-stall-watchdog")
        self._thread.start()

    # ------------------------------------------------------------- control
    def arm(self, label: str, step: Optional[int] = None) -> None:
        with self._lock:
            self._armed_at = time.monotonic()
            self._label = label
            self._armed_step = step

    def heartbeat(self, step: Optional[int] = None) -> None:
        """Progress proof: resets the deadline clock; records the last
        step known complete. No-op when disarmed."""
        with self._lock:
            if step is not None:
                self._last_step = step
            if self._armed_at is not None:
                self._armed_at = time.monotonic()

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None
            self._label = None

    @contextmanager
    def watch(self, label: str, step: Optional[int] = None):
        self.arm(label, step)
        try:
            yield self
        finally:
            self.disarm()

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2 * self.poll_s + 1.0)

    # ------------------------------------------------------------- monitor
    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                armed_at = self._armed_at
                label = self._label
                armed_step = self._armed_step
                last_step = self._last_step
            if armed_at is None or self._fired.is_set():
                continue
            waited = time.monotonic() - armed_at
            if waited < self.deadline_s:
                continue
            record: Dict[str, object] = {
                "kind": "stall",
                "time": time.time(),
                "label": label,
                "waited_s": round(waited, 3),
                "deadline_s": self.deadline_s,
                "armed_step": armed_step,
                "last_completed_step": last_step,
                "device": self.device_info,
            }
            if self._diagnostics is not None:
                try:
                    record.update(self._diagnostics() or {})
                except Exception as e:
                    record["diagnostics_error"] = repr(e)
            self._fired.set()
            self._on_stall(record)
