"""Link-level comm observability: the per-(axis, peer) network weather map.

Every comm-plane surface before this one modeled the fleet as ONE
homogeneous link — a single {alpha_ms, beta_gbps} fit per run. But the
schedules themselves are deterministic round sequences with known peer
pairs (parallel/collectives.py): the hypercube tree exchanges rank a
with a^bit in round bit, the Ok-Topk balanced schedule ships round s
from rank r to (r+s) mod p, and the hierarchical plan runs its ICI
hypercube inside each slice before the cross-slice DCN tree. So the
round index -> (src, dst, axis) join costs nothing — it comes from the
plan, not from guesswork — and recording it turns "some rank is slow"
into "the dcn hop between ranks 2 and 5 degraded at step 340".

The decomposition mirrors critpath's wait-split: each profiled
collective's measured span is carved into per-round intervals in
proportion to each round's MODELED wire time (alpha + bytes/beta, with
the ICI rounds priced at the ICI bandwidth), exactly as ``wait_split``
carves a comm interval into wire vs skew-wait. Per (axis, undirected
peer pair) the carved round times feed EWMA latency/bandwidth
estimates — the live weather map. One durable "linkmap" record per
capture (the calibrator cadence) makes the map survive a hard kill;
``report linkmap`` joins the per-rank records into the fleet view, and
the ``link_degraded`` anomaly rule (obs/events.py) watches for one
link's EWMA pulling away from the fleet median.

Pure-arithmetic module: no jax, importable everywhere the report CLI
runs.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Mesh-axis names of the two-level topology today; the schema is a free
# string so N-level plans can name arbitrary axes later.
AXIS_ICI = "ici"
AXIS_DCN = "dcn"

# ms per byte at 1 Gbps — mirrors obs/calib.py's _MS_PER_BYTE_AT_1GBPS
# (kept local so calib can import this module without a cycle).
MS_PER_BYTE_AT_1GBPS = 8e-6

# Default per-axis pricing used only to WEIGHT the proportional carve
# (ledger.DEFAULT_* values; the carve is scale-free in the measured
# span, so these only set the ici:dcn round ratio).
_CARVE_ALPHA_MS = 0.1
_CARVE_DCN_GBPS = 25.0
_CARVE_ICI_GBPS = 1600.0

_EPS_MS = 1e-9


def link_key(axis: str, a: int, b: int) -> str:
    """Canonical undirected link name, e.g. "dcn:2-5". Exchanges are
    keyed by the physical hop, not the message direction."""
    lo, hi = (a, b) if a <= b else (b, a)
    return f"{axis}:{lo}-{hi}"


def parse_link_key(key: str) -> Tuple[str, int, int]:
    """Inverse of link_key: "dcn:2-5" -> ("dcn", 2, 5)."""
    axis, _, pair = str(key).partition(":")
    lo, _, hi = pair.partition("-")
    return axis, int(lo), int(hi)


def _tree_pair_rounds(ranks: Sequence[int]) -> List[List[Tuple[int, int]]]:
    """Round-ordered (src, dst) pairs of the hypercube merge tree over
    the given participants — the exact order parallel.collectives
    ``_merge_tree`` executes: ragged fold, log2(m) hypercube exchange
    rounds, ragged unfold. Hypercube rounds are bidirectional
    exchanges; one (lo, hi) pair per physical link per round."""
    q = len(ranks)
    if q <= 1:
        return []
    m = 1 << (q.bit_length() - 1)
    if m == q:
        m = q if q & (q - 1) == 0 else m
    e = q - m
    rounds: List[List[Tuple[int, int]]] = []
    if e:
        rounds.append([(ranks[m + t], ranks[t]) for t in range(e)])
    bit = 1
    while bit < m:
        rounds.append([(ranks[a], ranks[a ^ bit])
                       for a in range(m) if a < (a ^ bit)])
        bit <<= 1
    if e:
        rounds.append([(ranks[t], ranks[m + t]) for t in range(e)])
    return rounds


def round_peers(wire_mode: str, p: int, *,
                ici_size: int = 1) -> List[dict]:
    """The deterministic round -> (axis, peer pairs) schedule of one
    collective, straight from the plan (parallel/collectives.py):

      gtopk / tree      ragged fold + hypercube exchanges + unfold over
                        all p ranks, every round on the dcn axis
      gtopk_balanced    the Ok-Topk split-and-reduce: p-1 scatter
                        rounds (round s: r -> (r+s) mod p) then p-1
                        gather rounds with the same offsets (the
                        owner-block all_gather), all dcn
      gtopk_hier        the ICI hypercube inside each contiguous slice
                        (axis "ici"), then the cross-slice merge tree
                        with ici_size parallel lanes per slice pair
                        (axis "dcn")
      dense             ring all-reduce: 2(p-1) neighbor rounds
      allgather         p-1 ring rounds

    Returns [{"round": i, "axis": str, "phase": str,
    "pairs": [(src, dst), ...]}, ...]; empty at p <= 1."""
    if p <= 1:
        return []
    rounds: List[dict] = []

    def _add(axis: str, phase: str, pairs: List[Tuple[int, int]]) -> None:
        rounds.append({"round": len(rounds), "axis": axis,
                       "phase": phase, "pairs": pairs})

    if wire_mode == "gtopk_balanced":
        for s in range(1, p):
            _add(AXIS_DCN, "scatter", [(r, (r + s) % p) for r in range(p)])
        for s in range(1, p):
            _add(AXIS_DCN, "gather", [(r, (r + s) % p) for r in range(p)])
    elif wire_mode == "gtopk_hier" and ici_size > 1 and p % ici_size == 0:
        n_slices = p // ici_size
        slices = [[s * ici_size + j for j in range(ici_size)]
                  for s in range(n_slices)]
        for pairs in _tree_pair_rounds(list(range(ici_size))):
            # The same intra-slice exchange runs in every slice at once.
            flat = [(base[a], base[b]) for base in slices
                    for a, b in pairs]
            _add(AXIS_ICI, "ici_psum", flat)
        for pairs in _tree_pair_rounds(list(range(n_slices))):
            # Cross-slice hop: ici_size parallel lanes between the
            # corresponding members of the two slices.
            flat = [(slices[sa][j], slices[sb][j])
                    for sa, sb in pairs for j in range(ici_size)]
            _add(AXIS_DCN, "cross_slice", flat)
    elif wire_mode in ("dense", "psum"):
        for s in range(2 * (p - 1)):
            phase = "reduce_scatter" if s < p - 1 else "allgather"
            _add(AXIS_DCN, phase, [(r, (r + 1) % p) for r in range(p)])
    elif wire_mode == "allgather":
        for s in range(1, p):
            _add(AXIS_DCN, "allgather", [(r, (r + s) % p) for r in range(p)])
    else:  # gtopk and any tree-shaped fallback
        for pairs in _tree_pair_rounds(list(range(p))):
            _add(AXIS_DCN, "tree", pairs)
    return rounds


def rank_rounds(rounds: Iterable[dict], rank: int) -> List[dict]:
    """The one-rank view of a round schedule: for every round the rank
    participates in, {"round", "axis", "phase", "peer", "src", "dst"}.
    The peer is the other endpoint; src/dst keep the schedule's message
    direction (hypercube exchanges are recorded lo->hi)."""
    mine: List[dict] = []
    for rd in rounds:
        for src, dst in rd["pairs"]:
            if rank == src or rank == dst:
                mine.append({
                    "round": rd["round"], "axis": rd["axis"],
                    "phase": rd.get("phase", "?"),
                    "peer": dst if rank == src else src,
                    "src": src, "dst": dst,
                })
                break  # one message per rank per round in every schedule
    return mine


def round_weights(mine: Sequence[dict], wire_bytes: float, *,
                  alpha_ms: float = _CARVE_ALPHA_MS,
                  beta_gbps: float = _CARVE_DCN_GBPS,
                  ici_gbps: float = _CARVE_ICI_GBPS) -> List[float]:
    """Modeled wire ms of each of one rank's rounds — the carve
    weights. Bytes split uniformly over the rank's rounds; each round
    priced alpha + bytes * 8e-6 / beta(axis), with the ici rounds at
    the ici bandwidth. Only the RATIO matters to the carve."""
    if not mine:
        return []
    per_round = max(0.0, float(wire_bytes)) / len(mine)
    out = []
    for rd in mine:
        beta = ici_gbps if rd["axis"] == AXIS_ICI else beta_gbps
        out.append(alpha_ms
                   + per_round * MS_PER_BYTE_AT_1GBPS / max(beta, 1e-9))
    return out


def carve_rounds(t_comm_ms: float,
                 weights: Sequence[float]) -> List[float]:
    """Carve one measured comm span into per-round times in proportion
    to the modeled weights — the same proportional split critpath's
    ``wait_split`` applies to wire vs wait, here applied round-wise.
    Slack (measured > modeled) and compression (measured < modeled)
    both scale every round by the same factor, so the carve conserves
    the measured span exactly: sum(result) == t_comm_ms."""
    total = sum(weights)
    if total <= 0.0 or not weights:
        n = max(1, len(weights))
        return [max(0.0, float(t_comm_ms)) / n] * len(weights)
    scale = max(0.0, float(t_comm_ms)) / total
    return [w * scale for w in weights]


class LinkMap:
    """One rank's live link weather map.

    Feed it the measured comm span of a profiled dispatch (the same
    (wire_bytes, t_comm_ms) sample the calibrator sees) and it carves
    the span over the schedule's rounds, folds each round into the
    per-(axis, peer) EWMA latency/bandwidth estimates, writes ONE
    durable "linkmap" record (flush=True — the map must survive a hard
    kill), and only then feeds the monitor's ``link_degraded`` rule —
    so the durable evidence always precedes a halt raise."""

    def __init__(self, wire_mode: str, p: int, *, rank: int = 0,
                 ici_size: int = 1, ewma_alpha: float = 0.3,
                 alpha_ms: float = _CARVE_ALPHA_MS,
                 beta_gbps: float = _CARVE_DCN_GBPS,
                 ici_gbps: float = _CARVE_ICI_GBPS,
                 metrics=None, monitor=None):
        self.wire_mode = str(wire_mode)
        self.p = int(p)
        self.rank = int(rank)
        self.ici_size = int(ici_size)
        self.ewma_alpha = float(ewma_alpha)
        self.alpha_ms = float(alpha_ms)
        self.beta_gbps = float(beta_gbps)
        self.ici_gbps = float(ici_gbps)
        self.metrics = metrics
        self.monitor = monitor
        self.rounds = round_peers(self.wire_mode, self.p,
                                  ici_size=self.ici_size)
        self.mine = rank_rounds(self.rounds, self.rank)
        # link key -> {axis, src, dst, ewma_ms, ewma_gbps, n}
        self.links: Dict[str, dict] = {}
        self.n_observations = 0

    def observe(self, step: int, *, t_comm_ms: float,
                wire_bytes: float) -> Optional[dict]:
        """One profiled sample -> carve, EWMA update, durable record,
        then the anomaly rule (which may raise AnomalyHalt — after the
        record is already on disk). Returns the record, or None when
        the schedule has no rounds (p <= 1)."""
        if not self.mine:
            return None
        weights = round_weights(self.mine, wire_bytes,
                                alpha_ms=self.alpha_ms,
                                beta_gbps=self.beta_gbps,
                                ici_gbps=self.ici_gbps)
        carved = carve_rounds(t_comm_ms, weights)
        per_round_bytes = max(0.0, float(wire_bytes)) / len(self.mine)
        a = self.ewma_alpha
        round_rows = []
        for rd, t_ms in zip(self.mine, carved):
            key = link_key(rd["axis"], self.rank, rd["peer"])
            gbps = (per_round_bytes * MS_PER_BYTE_AT_1GBPS
                    / max(t_ms, _EPS_MS))
            link = self.links.get(key)
            if link is None:
                link = {"axis": rd["axis"],
                        "src": min(self.rank, rd["peer"]),
                        "dst": max(self.rank, rd["peer"]),
                        "ewma_ms": t_ms, "ewma_gbps": gbps, "n": 0}
                self.links[key] = link
            else:
                link["ewma_ms"] += a * (t_ms - link["ewma_ms"])
                link["ewma_gbps"] += a * (gbps - link["ewma_gbps"])
            link["n"] += 1
            round_rows.append({"round": rd["round"], "axis": rd["axis"],
                               "src": rd["src"], "dst": rd["dst"],
                               "t_ms": round(t_ms, 6)})
        self.n_observations += 1
        rec = self.record(step)
        rec["rounds"] = round_rows
        rec["t_comm_ms"] = round(float(t_comm_ms), 6)
        rec["wire_bytes"] = float(wire_bytes)
        if self.metrics is not None:
            self.metrics.log("linkmap", flush=True, step=step, **rec)
        if self.monitor is not None:
            # AFTER the durable write: the rule may raise AnomalyHalt.
            self.monitor.observe_links(step, self.ewma_by_link())
        return rec

    def ewma_by_link(self) -> Dict[str, float]:
        return {key: link["ewma_ms"]
                for key, link in sorted(self.links.items())}

    def record(self, step: int) -> dict:
        """The weather-map snapshot: every link's EWMAs plus the
        worst-link summary fields the watch/fleet surfaces read."""
        links = [{"link": key, **{k: (round(v, 6)
                                      if isinstance(v, float) else v)
                                  for k, v in link.items()}}
                 for key, link in sorted(self.links.items())]
        rec = {"wire_mode": self.wire_mode, "p": self.p,
               "ici_size": self.ici_size, "n_links": len(links),
               "n_rounds": len(self.mine),
               "n_obs": self.n_observations, "links": links}
        worst = worst_link(links)
        if worst is not None:
            med = _median([l["ewma_ms"] for l in links])
            rec.update({
                "worst_link": worst["link"], "worst_axis": worst["axis"],
                "worst_src": worst["src"], "worst_dst": worst["dst"],
                "worst_ewma_ms": round(float(worst["ewma_ms"]), 6),
                "median_ewma_ms": round(med, 6),
                "worst_over_median_x": round(
                    float(worst["ewma_ms"]) / max(med, _EPS_MS), 6),
            })
        return rec


def _median(vals: Sequence[float]) -> float:
    s = sorted(float(v) for v in vals)
    if not s:
        return 0.0
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def worst_link(links: Sequence[dict]) -> Optional[dict]:
    """The link with the highest EWMA latency; ties break toward the
    lexicographically first key so the pick is deterministic."""
    best = None
    for link in sorted(links, key=lambda l: str(l.get("link"))):
        if not isinstance(link.get("ewma_ms"), (int, float)):
            continue
        if best is None or link["ewma_ms"] > best["ewma_ms"]:
            best = link
    return best


def linkmap_rows(records: Iterable[dict]) -> List[dict]:
    """Join "linkmap" records (one stream or a fleet's concatenated
    shards) into one per-link table: each observing rank contributes
    its LAST record's EWMA for the link, and endpoints average — both
    ends of a slow hop see it, one end of a slow rank does. Rows sorted
    by key, each {link, axis, src, dst, n_ranks, n_obs, ewma_ms,
    ewma_gbps, vs_median_x}."""
    # (link, observing rank) -> latest link snapshot
    latest: Dict[Tuple[str, int], dict] = {}
    obs_count: Dict[Tuple[str, int], int] = {}
    for rec in records:
        if rec.get("kind") not in (None, "linkmap"):
            continue
        if not isinstance(rec.get("links"), list):
            continue
        rank = int(rec.get("rank", 0) or 0)
        for link in rec["links"]:
            key = str(link.get("link"))
            if not key or not isinstance(link.get("ewma_ms"),
                                         (int, float)):
                continue
            latest[(key, rank)] = link
            obs_count[(key, rank)] = int(link.get("n", 1))
    by_link: Dict[str, List[Tuple[int, dict]]] = {}
    for (key, rank), link in latest.items():
        by_link.setdefault(key, []).append((rank, link))
    rows: List[dict] = []
    for key in sorted(by_link):
        contrib = by_link[key]
        ewma_ms = sum(float(l["ewma_ms"]) for _, l in contrib) / len(contrib)
        gbps = [float(l["ewma_gbps"]) for _, l in contrib
                if isinstance(l.get("ewma_gbps"), (int, float))]
        axis, src, dst = parse_link_key(key)
        rows.append({
            "link": key, "axis": axis, "src": src, "dst": dst,
            "n_ranks": len(contrib),
            "n_obs": sum(obs_count.get((key, r), 0) for r, _ in contrib),
            "ewma_ms": round(ewma_ms, 6),
            "ewma_gbps": (round(sum(gbps) / len(gbps), 6)
                          if gbps else None),
        })
    med = _median([r["ewma_ms"] for r in rows])
    for r in rows:
        r["vs_median_x"] = round(r["ewma_ms"] / max(med, _EPS_MS), 4)
    return rows


def summarize_linkmap(records: Iterable[dict]) -> dict:
    """{rows, worst, median_ewma_ms, n_links, axes} over a record
    stream — the joined fleet weather map plus the per-axis fit lines
    (from the stream's last calib record carrying dotted per-axis
    keys, e.g. "alpha_ms.dcn")."""
    records = list(records)
    rows = linkmap_rows(records)
    axes: Dict[str, dict] = {}
    for rec in records:
        if rec.get("kind") != "calib":
            continue
        for field, val in rec.items():
            if not isinstance(val, (int, float)):
                continue
            for prefix in ("alpha_ms.", "beta_gbps."):
                if field.startswith(prefix):
                    axis = field[len(prefix):]
                    axes.setdefault(axis, {})[prefix[:-1]] = float(val)
    return {
        "rows": rows,
        "worst": worst_link(rows),
        "median_ewma_ms": _median([r["ewma_ms"] for r in rows]),
        "n_links": len(rows),
        "axes": axes,
    }


def format_linkmap(summary: dict) -> str:
    """The ``report linkmap`` text: per-link table, worst-link line,
    axis-level fit lines."""
    rows = summary["rows"]
    if not rows:
        return ("linkmap: no linkmap records (run with --obs-linkmap, "
                "or the shards predate the link plane)")
    widths_rows = []
    for r in rows:
        widths_rows.append([
            r["link"], r["axis"], str(r["n_ranks"]), str(r["n_obs"]),
            f"{r['ewma_ms']:.4f}",
            ("-" if r["ewma_gbps"] is None else f"{r['ewma_gbps']:.4f}"),
            f"{r['vs_median_x']:.2f}x",
        ])
    header = ["link", "axis", "n_ranks", "n_obs", "ewma_ms",
              "ewma_gbps", "vs_median"]
    cols = [max(len(str(row[i])) for row in [header] + widths_rows)
            for i in range(len(header))]
    lines = [f"linkmap: {len(rows)} link(s)  median_ewma_ms="
             f"{summary['median_ewma_ms']:.4f}"]
    for row in [header, ["-" * w for w in cols]] + widths_rows:
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(row, cols)))
    worst = summary.get("worst")
    if worst is not None:
        lines.append(
            f"worst link: {worst['link']} "
            f"(ewma {float(worst['ewma_ms']):.4f} ms, "
            f"{float(worst.get('vs_median_x', 0.0)):.2f}x the fleet "
            "median)")
    for axis in sorted(summary.get("axes", {})):
        fit = summary["axes"][axis]
        lines.append(
            f"axis {axis}: alpha_ms={fit.get('alpha_ms')} "
            f"beta_gbps={fit.get('beta_gbps')} (per-axis calib fit)")
    return "\n".join(lines)


def axis_breakdown(wire_mode: str, p: int, *, ici_size: int = 1,
                   wire_bytes: float, t_comm_ms: float,
                   alpha_ms: float = _CARVE_ALPHA_MS,
                   beta_gbps: float = _CARVE_DCN_GBPS,
                   ici_gbps: float = _CARVE_ICI_GBPS,
                   rank: int = 0) -> Dict[str, dict]:
    """Split one blended (wire_bytes, t_comm_ms) sample per axis by the
    same proportional carve the weather map uses: {axis: {wire_bytes,
    t_ms, msgs}}. This is how the calibrator turns its one blended
    measurement into per-axis sample pools — hier's ici and dcn hops
    each get their modeled share of the measured span."""
    mine = rank_rounds(round_peers(wire_mode, p, ici_size=ici_size), rank)
    if not mine:
        return {}
    weights = round_weights(mine, wire_bytes, alpha_ms=alpha_ms,
                            beta_gbps=beta_gbps, ici_gbps=ici_gbps)
    carved = carve_rounds(t_comm_ms, weights)
    per_round_bytes = max(0.0, float(wire_bytes)) / len(mine)
    out: Dict[str, dict] = {}
    for rd, t_ms in zip(mine, carved):
        ax = out.setdefault(rd["axis"],
                            {"wire_bytes": 0.0, "t_ms": 0.0, "msgs": 0})
        ax["wire_bytes"] += per_round_bytes
        ax["t_ms"] += t_ms
        ax["msgs"] += 1
    return out
