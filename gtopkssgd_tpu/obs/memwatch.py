"""Compile- and memory-plane observability — the space-plane sibling of
trace_attr.py.

The obs stack measures the TIME plane (trace_attr's T_compute/T_select/
T_comm), the WIRE plane (ledger + calib), and the QUALITY plane (recall
audits); this module lights up the remaining dark plane: what the
compiled program costs in HBM, whether the hot step keeps its one
executable, and whether device memory is drifting. ROADMAP items 4
(elastic dp-mesh resize) and 5 (dp×tp Transformer) are memory-bound
decisions — resizing P or adding a tp axis changes per-device footprint
— and memory-bounded collective scheduling (arXiv:2112.01075) needs the
measurement before any planning against it.

Three layers, all host-side and sync-free (every read piggybacks on a
sync the train loop already pays):

  * Extraction helpers — ``cost_summary`` / ``memory_summary`` normalize
    ``compiled.cost_analysis()`` (dict OR list-of-dict across jax
    versions) and ``compiled.memory_analysis()`` (CompiledMemoryStats)
    into flat numeric dicts. ``compiled_flops`` is the ONE code path for
    XLA flop counts — benchmark.py's MFU consumes it, so bench and obs
    cannot drift. The peak-HBM estimate is the standard decomposition
    arguments + outputs + temps + generated code − aliased bytes.
  * ``CompileWatch`` — tracks a jitted callable's executable-cache size
    (``_cache_size()``; a ``jax.monitoring`` event listener counts
    backend compile events as a corroborating fast path where
    available). The first poll adopts the current size as baseline (the
    initial trace is a compile, not a REcompile); later growth is a
    recompile.
  * ``MemWatch`` — the trainer-facing facade: per-dispatch-shape compile
    accounting (one fsync'd "compile" record each, AOT lower/compile
    keyed by ``batch_shape_key``), recompile records + the
    ``recompile_storm`` rule via ``AnomalyMonitor.observe_compile``, and
    sampled live memory ("mem" records: ``jax.live_arrays()`` count and
    bytes by dtype + per-device ``memory_stats()`` where the backend
    exposes them — CPU returns none and the watch degrades to
    live_arrays-only) feeding the ``device_mem_leak`` / ``hbm_headroom``
    rules via ``observe_memory``.

Record-before-rule ordering (same contract as calib.py's refit): every
record is durably written BEFORE the monitor sees the sample, so a halt
can never lose the evidence that triggered it.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional, Tuple

# ------------------------------------------------------------ extraction

# cost_analysis keys -> record field names. XLA spells "bytes accessed"
# with a space; records use identifier-safe names (exporter families,
# report columns).
_COST_KEYS = (("flops", "flops"), ("bytes accessed", "bytes_accessed"))

# CompiledMemoryStats attributes -> record field names (device-side
# sizes only; the host_* mirror fields are zero off-TPU and noise on).
_MEM_ATTRS = (
    ("temp_size_in_bytes", "temp_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def cost_summary(compiled) -> Dict[str, float]:
    """Normalized ``cost_analysis()``: ``{"flops", "bytes_accessed"}``
    with only finite positive values; {} when the backend exposes
    nothing. Accepts both the dict and list-of-dict return shapes."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: Dict[str, float] = {}
    for key, name in _COST_KEYS:
        try:
            val = float(cost.get(key, -1.0))
        except (TypeError, ValueError):
            continue
        if val > 0 and math.isfinite(val):
            out[name] = val
    return out


def compiled_flops(compiled) -> Optional[float]:
    """Per-step FLOPs as XLA counts them (cost_analysis), None if
    absent. The single flop-count code path: benchmark.py's MFU and the
    "compile" records both read this."""
    return cost_summary(compiled).get("flops")


def memory_summary(compiled) -> Dict[str, int]:
    """Normalized ``memory_analysis()``: the device-side byte sizes plus
    the derived ``peak_hbm_bytes`` estimate (arguments + outputs + temps
    + generated code − aliased bytes); {} when the backend exposes no
    memory analysis."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: Dict[str, int] = {}
    for attr, name in _MEM_ATTRS:
        val = getattr(mem, attr, None)
        if isinstance(val, (int, float)) and math.isfinite(val) and val >= 0:
            out[name] = int(val)
    if out:
        peak = (out.get("argument_bytes", 0) + out.get("output_bytes", 0)
                + out.get("temp_bytes", 0)
                + out.get("generated_code_bytes", 0)
                - out.get("alias_bytes", 0))
        out["peak_hbm_bytes"] = max(int(peak), 0)
    return out


def batch_shape_key(tree) -> str:
    """Stable text key of a pytree's leaf shapes/dtypes — the identity
    of a dispatch shape. Two batches with the same key hit the same
    executable; a new key is a retrace. Long keys (a whole train-state
    pytree lists hundreds of leaves) collapse to a digest so a "compile"
    record stays a line, not a page."""
    import hashlib

    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = getattr(leaf, "dtype", None)
        parts.append("x".join(str(int(s)) for s in shape)
                      + ":" + str(dtype))
    key = ";".join(parts)
    if len(key) > 160:
        digest = hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]
        key = f"sha1:{digest}:{len(parts)}leaves"
    return key


def compile_record(compiled=None, *, shape_key: str = "",
                   lower_s: Optional[float] = None,
                   compile_s: Optional[float] = None) -> Dict[str, Any]:
    """One "compile" record body: the normalized cost/memory summaries
    plus lowering/compile wall times and the dispatch-shape key."""
    rec: Dict[str, Any] = {"shape_key": str(shape_key)}
    if lower_s is not None:
        rec["lower_s"] = round(float(lower_s), 6)
    if compile_s is not None:
        rec["compile_s"] = round(float(compile_s), 6)
    if compiled is not None:
        rec.update(cost_summary(compiled))
        rec.update(memory_summary(compiled))
    return rec


# --------------------------------------------------------- recompile watch
class CompileWatch:
    """Executable-cache growth detector for one jitted callable.

    ``_cache_size()`` is the source of truth (it counts the compiled
    entries the dispatch path actually consults); a ``jax.monitoring``
    event listener corroborates with a backend-compile event count where
    the API exists. Both degrade to None/0 silently — a watch must never
    take down training."""

    def __init__(self, fn, use_monitoring: bool = True):
        self.fn = fn
        self.last: Optional[int] = None
        self.compile_events = 0
        self._listener = None
        if use_monitoring:
            self._install_listener()

    def _install_listener(self) -> None:
        def _on_event(event, **kw):
            if "compile" in str(event):
                self.compile_events += 1

        try:
            import jax.monitoring

            jax.monitoring.register_event_listener(_on_event)
            self._listener = _on_event
        except Exception:
            self._listener = None

    def cache_size(self) -> Optional[int]:
        try:
            return int(self.fn._cache_size())
        except Exception:
            return None

    def poll(self) -> Optional[Tuple[int, int]]:
        """(entries grown, current size) when the cache grew since the
        last poll, else None. The first successful poll adopts the
        current size as the baseline."""
        size = self.cache_size()
        if size is None:
            return None
        if self.last is None:
            self.last = size
            return None
        if size > self.last:
            grown = size - self.last
            self.last = size
            return (grown, size)
        self.last = size
        return None

    def close(self) -> None:
        if self._listener is not None:
            try:
                from jax._src import monitoring as _monitoring

                _monitoring._unregister_event_listener_by_callback(
                    self._listener)
            except Exception:
                pass
            self._listener = None


# ------------------------------------------------------- live-memory reads
def live_array_summary() -> Dict[str, Any]:
    """Host view of every live device buffer this process holds:
    ``live_count`` / ``live_bytes`` totals plus a ``live_bytes_<dtype>``
    breakdown. {} when the runtime refuses the enumeration."""
    import jax

    try:
        arrays = jax.live_arrays()
    except Exception:
        return {}
    total = 0
    by_dtype: Dict[str, int] = {}
    for arr in arrays:
        try:
            nbytes = int(arr.nbytes)
            dtype = str(arr.dtype)
        except Exception:
            continue
        total += nbytes
        by_dtype[dtype] = by_dtype.get(dtype, 0) + nbytes
    out: Dict[str, Any] = {"live_count": len(arrays),
                           "live_bytes": int(total)}
    for dtype in sorted(by_dtype):
        out[f"live_bytes_{dtype}"] = int(by_dtype[dtype])
    return out


def device_memory_summary() -> Dict[str, int]:
    """Allocator stats summed over addressable devices (bytes_in_use /
    peak_bytes_in_use / bytes_limit where the backend reports them,
    plus how many devices did). {} on backends without memory_stats
    (CPU) — the live-memory watch then runs on live_arrays alone."""
    import jax

    try:
        devices = jax.local_devices()
    except Exception:
        return {}
    totals: Dict[str, int] = {}
    reporting = 0
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        reporting += 1
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            val = stats.get(key)
            if isinstance(val, (int, float)) and math.isfinite(val):
                totals[key] = totals.get(key, 0) + int(val)
    if reporting:
        totals["devices_reporting"] = reporting
    return totals


# ----------------------------------------------------------------- facade
class MemWatch:
    """Trainer-facing compile/memory watch (``--obs-mem``).

    ``account(fn, *args)`` AOT-lowers and compiles ``fn`` at the args'
    shapes, logs one fsync'd "compile" record, and memoizes by shape key
    — one record per distinct dispatch shape for the life of the run.
    ``attach(fn)`` arms the CompileWatch on the jitted step;
    ``poll(step, fn=..., args=...)`` is the sync-point hook: accounts a
    never-seen dispatch shape, logs a "compile" recompile record per
    cache growth, samples live memory every ``mem_interval`` steps, and
    feeds the monitor (observe_compile / observe_memory) AFTER each
    record is durably written — so an AnomalyHalt raised here never
    loses its evidence. Everything degrades to a logger warning; the
    watch must never take down training."""

    def __init__(self, metrics=None, monitor=None, mem_interval: int = 50,
                 logger=None):
        self.metrics = metrics
        self.monitor = monitor
        self.mem_interval = max(1, int(mem_interval))
        self.logger = logger
        self.watch: Optional[CompileWatch] = None
        self.recompile_count = 0
        # shape_key -> its "compile" record (memo: one AOT compile and
        # one record per distinct dispatch shape).
        self.shapes: Dict[str, Dict[str, Any]] = {}
        self._last_mem_step: Optional[int] = None

    # ------------------------------------------------- compile accounting
    def account(self, fn, *args, shape_key: Optional[str] = None,
                step: int = 0, log: bool = True) -> Optional[Dict[str, Any]]:
        """AOT lower+compile ``fn`` at ``args``' shapes (ShapeDtypeStructs
        welcome — nothing executes) and build one "compile" record;
        memoized per shape key. Returns the record (also when memoized),
        or None when the backend refuses."""
        key = batch_shape_key(args) if shape_key is None else str(shape_key)
        if key in self.shapes:
            return self.shapes[key]
        try:
            t0 = time.perf_counter()
            lowered = fn.lower(*args)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception as e:
            if self.logger is not None:
                self.logger.warning("compile accounting failed: %s", e)
            return None
        rec = compile_record(compiled, shape_key=key,
                             lower_s=t1 - t0, compile_s=t2 - t1)
        rec["step"] = int(step)
        rec["shape_index"] = len(self.shapes)
        self.shapes[key] = rec
        if log:
            self.log_compile(rec)
        return rec

    def log_compile(self, rec: Dict[str, Any]) -> None:
        """Durably write one "compile" record (fsync'd — compile
        evidence must survive the halt it may be about to trigger)."""
        if self.metrics is not None:
            self.metrics.log("compile", flush=True, **rec)

    @property
    def peak_hbm_bytes(self) -> Optional[int]:
        """The largest per-shape peak-HBM estimate seen so far (what the
        manifest stamps)."""
        peaks = [rec.get("peak_hbm_bytes") for rec in self.shapes.values()]
        peaks = [p for p in peaks if isinstance(p, (int, float))]
        return int(max(peaks)) if peaks else None

    # --------------------------------------------------------- sync hook
    def attach(self, fn) -> None:
        """Arm the recompile watch on the jitted step callable."""
        self.watch = CompileWatch(fn)

    def poll(self, step: int, fn=None, args=None) -> None:
        """Sync-point hook (the step is already synced; no device reads
        beyond live_arrays/memory_stats). May raise AnomalyHalt via the
        monitor — after every record is durably written."""
        if fn is not None and args is not None:
            key = batch_shape_key(args)
            if key not in self.shapes:
                self.account(fn, *args, shape_key=key, step=step)
        self._poll_recompile(step)
        if (self._last_mem_step is None
                or step - self._last_mem_step >= self.mem_interval):
            self._last_mem_step = int(step)
            self.sample(step)

    def _poll_recompile(self, step: int) -> None:
        if self.watch is None:
            return
        growth = self.watch.poll()
        if growth is not None:
            grown, size = growth
            self.recompile_count += grown
            rec = {
                "event": "recompile", "step": int(step),
                "cache_size": int(size),
                "recompile_count": int(self.recompile_count),
                "compile_events": int(self.watch.compile_events),
            }
            if self.metrics is not None:
                self.metrics.log("compile", flush=True, **rec)
        if self.monitor is not None and self.watch.last is not None:
            self.monitor.observe_compile(
                step, cache_size=self.watch.last,
                grew=growth is not None)

    # ------------------------------------------------------- mem sampling
    def sample(self, step: int) -> Dict[str, Any]:
        """One live-memory window: "mem" record (sampled — not fsync'd)
        then the leak/headroom rules."""
        rec: Dict[str, Any] = {"step": int(step)}
        rec.update(live_array_summary())
        rec.update(device_memory_summary())
        in_use, limit = rec.get("bytes_in_use"), rec.get("bytes_limit")
        if in_use and limit:
            rec["headroom_frac"] = round(float(in_use) / float(limit), 6)
        rec["recompile_count"] = int(self.recompile_count)
        if self.metrics is not None:
            self.metrics.log("mem", **rec)
        if self.monitor is not None:
            self.monitor.observe_memory(
                step, live_bytes=rec.get("live_bytes"),
                bytes_in_use=in_use, bytes_limit=limit)
        return rec

    def close(self) -> None:
        if self.watch is not None:
            self.watch.close()
            self.watch = None
