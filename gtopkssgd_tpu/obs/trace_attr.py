"""Chrome-trace parsing and the paper's T_compute/T_select/T_comm split.

The paper's entire argument is a measured three-term decomposition of the
step time (arXiv:1901.04359 §5): forward/backward compute, top-k
selection, and the sparse collective. This module turns a ``jax.profiler``
chrome trace into that decomposition, promoted out of
``benchmarks/profile_step.py``'s ad-hoc parser so every consumer (the
profile tool, the gate smoke, bench.py, the report CLI, tests) shares one
implementation.

Two attribution sources, in preference order:

  spans — device-lane events named by the ``Tracer``/``TraceAnnotation``
      scopes the trainer and benchmark already emit ("train/step",
      "bench/compress", "bench/comm", ...). On TPU the runtime propagates
      annotations onto the device lanes, so when enough device time is
      covered by annotated scopes the named buckets are the ground truth.
  ops — fallback op-level classifier over per-op device events: sort /
      top-k → select; all-reduce / all-gather / all-to-all /
      collective-permute / reduce-scatter → comm; everything else
      (fusions, convolutions, dots, loop bookkeeping) → compute. This is
      the path that works on XLA:CPU traces, where op events carry
      ``args.hlo_op`` on the runtime's executor threads and annotations
      stay host-side.

The spans-vs-ops choice is made PER CLASS, not globally: a partially
annotated capture (say only the comm scopes propagated to the device
lanes) keeps span truth for the classes the annotations cover and the
op classifier for the rest (``source`` = "mixed"); before PR 15 one
thin class silently dragged all three onto the op classifier.

Overlap measurement (PR 15): the three per-class sums assume the terms
are disjoint in time — exactly the assumption the overlapped bucket
pipeline breaks. ``attribute`` therefore also reports ``overlap_frac``:
the wall-clock interval union of comm events intersected with the union
of non-comm (compute+select) events, as a fraction of the comm union —
the fraction of communication time HIDDEN under other work. 0.0 on a
strictly serial schedule; > 0 once the pipelined stage loop actually
interleaves. Computed from raw (ts, dur) wall intervals across all
device lanes (cross-lane concurrency is the point), from the op events
when any exist, else from the annotated device spans.

Durations are SELF times: a structural op (``while``, ``call``) nests its
children on the same lane, so summing raw ``dur`` double-counts; each
lane is resolved with an interval-nesting stack (sort by (ts, -end),
subtract same-lane child durations) before bucketing. Validated against
XLA:CPU traces where ``while`` wraps the gtopk hypercube's
collective-permutes: the loop's self time drops to bookkeeping while the
collectives keep their own.

``capture()`` is the capture-side helper: ``jax.profiler.trace``'s
default options enable the Python tracer, which on a trainer-sized
program floods the trace (~1M events) until the XLA op events are
DROPPED; the context manager here runs a ProfilerSession with
``python_tracer_level=0`` so op-level attribution survives.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Tuple

# ------------------------------------------------------------ classifiers

# Op-name prefixes per bucket, matched against the HLO instruction name
# (lowercased, suffix digits and all: prefix match handles "sort.42" and
# "all-reduce-start"). Order matters only in that select/comm are carved
# out of the default-compute bucket. NOTE "reduce-window" is pooling
# (compute), which is why the select patterns are exact-ish prefixes and
# not a substring match on "top".
_SELECT_PREFIXES = ("sort", "top-k", "topk", "top_k", "partial-sort")
_COMM_PREFIXES = (
    "all-reduce", "all-gather", "all-to-all", "alltoall",
    "collective-permute", "reduce-scatter", "collective-broadcast",
    "allreduce", "allgather", "send", "recv", "partition-id",
)

# Span-path components per bucket, for annotation-named device events
# (and for bucketing host-side span means). Checked in this order so
# "train/step/compress" lands in select even though "step" would match
# compute.
_SPAN_BUCKET_PATTERNS = (
    ("select", ("compress", "select", "topk", "top_k")),
    ("comm", ("comm", "allreduce", "all_reduce", "allgather")),
    ("compute", ("forward_backward", "apply", "step", "train", "dispatch",
                 "throughput", "fwd", "bwd")),
)

TERMS = ("compute", "select", "comm")


def classify_op(name: str) -> str:
    """Bucket one HLO op name: 'select' | 'comm' | 'compute'."""
    n = name.lower()
    for p in _SELECT_PREFIXES:
        if n.startswith(p):
            return "select"
    for p in _COMM_PREFIXES:
        if n.startswith(p):
            return "comm"
    # Fusions that carry their root op in the name (TPU fusion naming).
    if "fusion" in n:
        for p in _SELECT_PREFIXES:
            if p in n:
                return "select"
        for p in _COMM_PREFIXES:
            if p in n:
                return "comm"
    return "compute"


def classify_span(path: str) -> Optional[str]:
    """Bucket a Tracer span path ('bench/compress' → 'select'); None when
    no component matches any bucket (an unrecognized host phase like
    'io' must not pollute the three-term split)."""
    segs = path.lower().split("/")
    for bucket, pats in _SPAN_BUCKET_PATTERNS:
        for seg in segs:
            for p in pats:
                if p in seg:
                    return bucket
    return None


# -------------------------------------------------------------- trace IO

def find_trace_file(path: str) -> str:
    """Resolve a capture dir (or a direct file path) to the newest
    ``*.trace.json.gz`` under it — the layout jax.profiler exports
    (<dir>/plugins/profile/<ts>/<host>.trace.json.gz)."""
    if os.path.isfile(path):
        return path
    paths = glob.glob(
        os.path.join(path, "**", "*.trace.json.gz"), recursive=True)
    paths += glob.glob(
        os.path.join(path, "**", "*.trace.json"), recursive=True)
    if not paths:
        raise FileNotFoundError(f"no chrome trace found under {path}")
    return max(paths, key=os.path.getmtime)


def load_trace(path: str) -> dict:
    """Load a chrome-trace JSON document (plain or gzipped)."""
    path = find_trace_file(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        return json.load(fh)


def lane_index(events: Iterable[dict]) -> Tuple[Dict, Dict]:
    """(pid → process name, (pid, tid) → thread name) from metadata."""
    pnames, tnames = {}, {}
    for e in events:
        if e.get("name") == "process_name":
            pnames[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tnames[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    return pnames, tnames


def device_pids(pnames: Dict) -> set:
    """Processes that look like accelerator devices (the profile_step
    heuristic, shared)."""
    return {pid for pid, name in pnames.items()
            if any(t in name.lower()
                   for t in ("tpu", "device", "xla", "/device"))}


def _event_us(e: dict) -> float:
    """Duration in µs, preferring the profiler's exact device time."""
    ps = e.get("args", {}).get("device_duration_ps")
    return float(ps) / 1e6 if ps else float(e.get("dur", 0.0))


def _is_op_event(e: dict, dev_pids: set, tnames: Dict) -> bool:
    """Per-op device event: carries args.hlo_op (XLA:CPU executor
    threads) or sits in a device pid's "XLA Ops" lane (TPU)."""
    if e.get("ph") != "X":
        return False
    if "hlo_op" in e.get("args", {}):
        return True
    return (e.get("pid") in dev_pids
            and tnames.get((e.get("pid"), e.get("tid"))) == "XLA Ops")


def self_durations_us(events: List[dict]) -> List[float]:
    """Self time (dur minus same-lane nested children) per event, in the
    input order. Caller groups events by lane; this resolves the nesting
    with the (ts, -end) stack sweep."""
    order = sorted(
        range(len(events)),
        key=lambda i: (float(events[i].get("ts", 0.0)),
                       -(float(events[i].get("ts", 0.0))
                         + float(events[i].get("dur", 0.0)))))
    selfs = [0.0] * len(events)
    stack: List[List] = []  # [end_ts, child_dur_sum, index]
    for i in order:
        e = events[i]
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        while stack and ts >= stack[-1][0] - 1e-9:
            end, child, j = stack.pop()
            selfs[j] = max(0.0, float(events[j].get("dur", 0.0)) - child)
        if stack:
            stack[-1][1] += dur
        stack.append([ts + dur, 0.0, i])
    while stack:
        end, child, j = stack.pop()
        selfs[j] = max(0.0, float(events[j].get("dur", 0.0)) - child)
    return selfs


# ------------------------------------------------------------ attribution

def _interval_union(intervals: List[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Merge (start, end) intervals into a sorted disjoint union."""
    merged: List[List[float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [(s, e) for s, e in merged]


def _intersection_us(a: List[Tuple[float, float]],
                     b: List[Tuple[float, float]]) -> float:
    """Total overlap length of two disjoint sorted interval unions."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_fraction(comm_iv: List[Tuple[float, float]],
                     other_iv: List[Tuple[float, float]]) -> float:
    """Fraction of the comm wall-clock union hidden under non-comm
    work: |union(comm) ∩ union(other)| / |union(comm)|. 0.0 when no
    comm intervals exist."""
    comm_u = _interval_union(comm_iv)
    comm_len = sum(e - s for s, e in comm_u)
    if comm_len <= 0:
        return 0.0
    return _intersection_us(comm_u, _interval_union(other_iv)) / comm_len


def attribute(trace, mode: Optional[str] = None,
              min_span_coverage: float = 0.5,
              stage_intervals: bool = False,
              wire_us: Optional[float] = None) -> dict:
    """The paper's decomposition from a chrome trace.

    ``trace`` is a capture dir, a trace file path, or an already-loaded
    chrome-trace dict. Returns a flat record (no 'kind' key — callers log
    it as kind="attr"): t_{compute,select,comm}_us self-time totals,
    frac_* over their sum, the per-class span/ops choice (``source`` =
    "spans" when every class with data uses annotated device spans
    covering ≥ min_span_coverage of that class's op time, "ops" when
    none does, "mixed" otherwise, with the per-class pick in
    ``source_{term}``), the measured ``overlap_frac`` (see module
    docstring), op counts, and the top ops per bucket (strings; the
    report CLI prints them, aggregation ignores them).

    ``stage_intervals=True`` additionally attaches ``rec["critpath"]``:
    the compact per-step stage-interval record (obs/critpath.py) built
    from the same per-class raw wall intervals the overlap measurement
    uses, with the comm span wait-split against ``wire_us`` (the
    ledger-modeled wire time for this step's bytes; None = no model =
    the whole comm span stays ``comm``). Callers pop it and log it as
    its own durable ``critpath`` record — it never rides the attr row.
    """
    trace_file = None
    if isinstance(trace, str):
        trace_file = find_trace_file(trace)
        doc = load_trace(trace_file)
    else:
        doc = trace
    events = doc.get("traceEvents", [])
    pnames, tnames = lane_index(events)
    dev_pids = device_pids(pnames)

    # Group op events per lane, then bucket their self times.
    lanes: Dict[Tuple, List[dict]] = collections.defaultdict(list)
    for e in events:
        if _is_op_event(e, dev_pids, tnames):
            lanes[(e.get("pid"), e.get("tid"))].append(e)
    op_us = {t: 0.0 for t in TERMS}
    op_top: Dict[str, Dict[str, float]] = {t: collections.defaultdict(float)
                                           for t in TERMS}
    # Raw wall (start, end) intervals per bucket, across ALL lanes —
    # the overlap measurement wants wall-clock concurrency (two lanes
    # busy at once), which self times deliberately erase.
    op_iv: Dict[str, List[Tuple[float, float]]] = {t: [] for t in TERMS}
    n_ops = 0
    for lane_events in lanes.values():
        selfs = self_durations_us(lane_events)
        for e, us in zip(lane_events, selfs):
            # device_duration_ps would be exact, but self-time nesting is
            # computed on the lane's wall durations — stay consistent.
            name = e.get("name", "?")
            bucket = classify_op(name)
            op_us[bucket] += us
            op_top[bucket][name] += us
            ts = float(e.get("ts", 0.0))
            # Self time for the interval length: a structural op
            # (while/call) must not blanket its children's window with
            # its own class. Anchored at ts — the self fragments of a
            # wrapper may sit later in its window, an approximation
            # that only matters for the wrappers' bookkeeping slivers.
            if us > 0:
                op_iv[bucket].append((ts, ts + us))
            n_ops += 1

    # Annotation-named DEVICE events (TPU propagates TraceAnnotations to
    # device lanes; op events themselves are excluded above).
    span_us = {t: 0.0 for t in TERMS}
    span_iv: Dict[str, List[Tuple[float, float]]] = {t: [] for t in TERMS}
    n_spans = 0
    for e in events:
        if (e.get("ph") != "X" or e.get("pid") not in dev_pids
                or _is_op_event(e, dev_pids, tnames)):
            continue
        lane = tnames.get((e.get("pid"), e.get("tid")), "")
        if lane in ("Steps", "XLA Modules", "XLA Ops"):
            continue
        bucket = classify_span(str(e.get("name", "")))
        if bucket is not None:
            span_us[bucket] += _event_us(e)
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
            if dur > 0:
                span_iv[bucket].append((ts, ts + dur))
            n_spans += 1

    # Per-CLASS source selection: use a class's annotated spans when
    # they exist and cover at least min_span_coverage of that class's
    # op time (or the op classifier saw nothing for it); fall back to
    # ops for the others. Classes with no data in EITHER source don't
    # vote on the overall label.
    use_spans_t = {
        t: (span_us[t] > 0
            and (op_us[t] == 0
                 or span_us[t] >= min_span_coverage * op_us[t]))
        for t in TERMS}
    chosen = {t: span_us[t] if use_spans_t[t] else op_us[t] for t in TERMS}
    total = sum(chosen.values())
    votes = [use_spans_t[t] for t in TERMS
             if span_us[t] > 0 or op_us[t] > 0]
    source = ("spans" if votes and all(votes)
              else "ops" if not any(votes) else "mixed")

    # Measured comm overlap: wall-interval union of the comm class vs
    # the union of everything else, from the same per-class source the
    # decomposition chose (ops when any exist — spans can blanket a
    # whole step on partially-annotated captures).
    iv = op_iv if n_ops > 0 else span_iv
    ofrac = overlap_fraction(
        iv["comm"], [x for t in TERMS if t != "comm" for x in iv[t]])

    rec = {}
    if stage_intervals:
        # Lazy import: critpath imports this module at module level for
        # the interval algebra; the reverse edge stays call-time only.
        from gtopkssgd_tpu.obs import critpath
        budget = float("inf") if wire_us is None else float(wire_us)
        fine = critpath.stage_segments(iv, budget, fill_gaps=True)
        # Coarse segments for the chain/timeline (compact durable
        # record); exact per-stage totals from the fine list.
        rec["critpath"] = critpath.build_record(
            critpath.coarsen(fine, min_us=500.0),
            totals=critpath.stage_totals(fine))
    rec = {
        **rec,
        "mode": mode,
        "source": source,
        "n_op_events": n_ops,
        "n_span_events": n_spans,
        "t_total_us": round(total, 1),
        "overlap_frac": round(ofrac, 6),
    }
    if trace_file is not None:
        rec["trace_file"] = trace_file
    for t in TERMS:
        rec[f"t_{t}_us"] = round(chosen[t], 1)
        rec[f"frac_{t}"] = round(chosen[t] / total, 6) if total else 0.0
        rec[f"source_{t}"] = "spans" if use_spans_t[t] else "ops"
    for t in TERMS:
        rows = sorted(op_top[t].items(), key=lambda kv: -kv[1])[:3]
        rec[f"top_{t}_ops"] = ", ".join(
            f"{n[:48]}={us / 1e3:.2f}ms" for n, us in rows)
    return rec


def host_span_means(trace) -> Dict[str, float]:
    """Mean µs per annotation path over HOST lanes — the Tracer's view of
    the same names, for correlating against the device split."""
    doc = load_trace(trace) if isinstance(trace, str) else trace
    events = doc.get("traceEvents", [])
    pnames, tnames = lane_index(events)
    dev_pids = device_pids(pnames)
    acc: Dict[str, List[float]] = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or e.get("pid") in dev_pids:
            continue
        if _is_op_event(e, dev_pids, tnames):
            continue
        name = str(e.get("name", ""))
        if classify_span(name) is not None or "/" in name:
            acc[name].append(float(e.get("dur", 0.0)))
    return {n: sum(v) / len(v) for n, v in acc.items() if v}


# ------------------------------------------------- profile_step's ranking

def op_ranking(trace_dir: str, top: int = 40) -> dict:
    """Aggregate device-lane durations from the chrome trace.

    The op-ranking table benchmarks/profile_step.py has always emitted
    (moved here verbatim so the profile tool and this module share one
    parser; its output stays byte-compatible). Lane layout on the
    tunneled axon TPU platform (device pid's thread names): "Steps" (one
    event per device program execution), "XLA Modules", "XLA Ops"
    (per-op detail) — with the measured limitation that the main
    shard_map'd train-step module appears ONLY in the Steps lane there,
    so the op table covers just the small host-built jits."""
    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise SystemExit(f"no trace found under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    pnames = {e.get("pid"): e.get("args", {}).get("name", "")
              for e in events if e.get("name") == "process_name"}
    dev_pids = {pid for pid, name in pnames.items()
                if any(t in name.lower()
                       for t in ("tpu", "device", "xla", "/device"))}
    tnames = {(e.get("pid"), e.get("tid")): e.get("args", {}).get("name", "")
              for e in events if e.get("name") == "thread_name"}

    def lane(e):
        return tnames.get((e.get("pid"), e.get("tid")), "")

    step_durs, agg, count, cat = [], collections.defaultdict(float), \
        collections.defaultdict(int), collections.defaultdict(float)
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        ln = lane(e)
        if ln == "Steps":
            step_durs.append(_event_us(e))
        elif ln == "XLA Ops":
            a = e.get("args", {})
            us = _event_us(e)
            agg[e.get("name", "?")] += us
            count[e.get("name", "?")] += 1
            cat[a.get("hlo_category", "?")] += us
    op_total = sum(agg.values())
    step_durs.sort(reverse=True)
    # Histogram of program executions: the main train step dominates the
    # tail of repeated near-identical durations.
    buckets = collections.Counter(round(d / 1000, 1) for d in step_durs)
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top]
    return {
        "trace_file": os.path.relpath(path, trace_dir),
        "steps_lane": {
            "executions": len(step_durs),
            "total_device_ms": round(sum(step_durs) / 1000, 1),
            "largest_ms": [round(d / 1000, 2) for d in step_durs[:10]],
            "top_duration_ms_histogram": {
                f"{ms}ms": n for ms, n in buckets.most_common(12)
            },
        },
        "attributed_op_us_total": round(op_total, 1),
        "attribution_note": (
            "per-op detail covers only the small helper jits on this "
            "platform; the train-step module is visible only as Steps-"
            "lane executions"),
        "hlo_category_us": {k: round(v, 1) for k, v in
                            sorted(cat.items(), key=lambda kv: -kv[1])},
        "top_ops": [
            {"name": n[:160], "total_us": round(us, 1), "calls": count[n],
             "pct": round(100 * us / op_total, 2) if op_total else None}
            for n, us in rows
        ],
    }


# ---------------------------------------------------------------- capture

@contextmanager
def capture(log_dir: str):
    """Profiler capture tuned for attribution: Python tracer OFF.

    ``jax.profiler.trace``'s defaults include the Python tracer, which on
    a trainer-sized program emits ~1M host events and makes the profiler
    DROP the XLA op events attribution needs (measured on XLA:CPU). The
    TraceAnnotation scopes the Tracer emits survive with the Python
    tracer off — they ride the host tracer. Falls back to the public
    jax.profiler.trace if the session API is unavailable."""
    import jax

    jax.devices()  # the profiler needs an initialized backend
    os.makedirs(log_dir, exist_ok=True)
    try:
        from jax._src.lib import xla_client  # noqa: private, pinned jaxlib

        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:
        with jax.profiler.trace(log_dir):
            yield
        return
    try:
        yield
    finally:
        sess.stop_and_export(log_dir)


def format_attr(rec: dict) -> str:
    """Render one attr record as the paper's decomposition table."""
    header = ["term", "time_ms", "frac", "src"]
    rows = []
    for t in TERMS:
        us = float(rec.get(f"t_{t}_us", 0.0))
        rows.append([f"T_{t}", f"{us / 1e3:.3f}",
                     f"{float(rec.get(f'frac_{t}', 0.0)):.4f}",
                     str(rec.get(f"source_{t}", rec.get("source", "?")))])
    widths = [max(len(r[i]) for r in [header] + rows)
              for i in range(len(header))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths))
             for r in [header, ["-" * w for w in widths]] + rows]
    head = (f"[attr] source={rec.get('source')}"
            + (f"  mode={rec['mode']}" if rec.get("mode") else "")
            + f"  total={float(rec.get('t_total_us', 0.0)) / 1e3:.3f}ms"
            + f"  op_events={rec.get('n_op_events')}"
            + (f"  overlap_frac={float(rec['overlap_frac']):.4f}"
               if rec.get("overlap_frac") is not None else ""))
    tops = [f"  top {t}: {rec[f'top_{t}_ops']}"
            for t in TERMS if rec.get(f"top_{t}_ops")]
    return "\n".join([head] + lines + tops)
