"""Unified observability subsystem (the paper's measured decomposition,
made first-class).

The paper's entire argument is a measured decomposition — compute vs.
selection vs. communication time and the sparsity achieved on the wire
(arXiv:1901.04359; arXiv:1911.08772 ties convergence to the error-feedback
residual dynamics). This package turns the repo's scattered primitives
(host timers, a bare jsonl logger, a --profile-dir flag) into one layer:

  counters.py — on-device training-health counters computed INSIDE the
      jitted step (achieved density, top-k threshold tau, pre/post
      compression gradient norms, error-feedback residual norm, wire
      bytes) and carried out through the optimizer state, so compression
      quality is a per-step metric for every mode.
  tracing.py  — span API emitting BOTH host-side records (metrics.jsonl /
      TimingStats) and jax.profiler.TraceAnnotation scopes, so device
      traces and host timelines correlate on the same names.
  watchdog.py — dispatch stall watchdog: a monitor thread that detects a
      dispatched step failing to become ready within a deadline (the
      BENCH_r05 dead-tunnel mode), emits a structured diagnostic and
      fails fast instead of hanging.
  trace_attr.py — chrome-trace parser shared with benchmarks/
      profile_step.py: buckets device-lane self times into the paper's
      T_compute/T_select/T_comm decomposition (annotation names when the
      platform propagates them to device lanes, an op-name classifier —
      sort/top-k → select, collectives → comm — as the fallback), plus
      the capture() helper that keeps op events in the trace by running
      the profiler with the Python tracer off.
  timeline.py — host-side Chrome-trace/Perfetto export
      (``--obs-timeline``): every Tracer span as a duration event,
      telemetry as counter tracks, anomaly events and watchdog stalls as
      instant markers — one file correlating host and device phases.
  events.py   — online anomaly monitor over the per-step telemetry:
      NaN/Inf loss, EWMA loss spikes, achieved-density collapse vs. rho,
      residual-norm blow-up, residual-age runaway; severity-tagged
      "event" records (fsync'd) and optional ``--obs-halt-on``
      fail-fast (exit 44).
  report.py   — ``python -m gtopkssgd_tpu.obs.report`` aggregates one or
      two metrics.jsonl runs into per-kind/per-metric summaries (incl.
      per-layer breakdown tables from "layers" records), a side-by-side
      regression-triage comparison, and a ``gate`` subcommand diffing a
      run against a committed baseline JSON with per-field tolerances
      (nonzero exit on regression — the tier-1 drift gate), and the
      ``attr`` / ``events`` / ``timeline`` subcommands over the three
      modules above.
  manifest.py — run-manifest header (config hash, resolved headline
      flags, mesh shape, jax/backend versions, git sha, process index /
      coordinator for multi-host) written as the first record of every
      metrics file so runs are self-describing.
  fleet.py    — cross-host layer: multi-process runs shard metrics per
      rank (metrics.rank{r}.jsonl); the merger aligns records by
      (kind, step) across ranks into per-step min/median/max/std rows
      with a per-rank skew vector, validates shards via each manifest's
      config_hash, and attributes the per-step slowest rank (persistent
      vs transient via an EWMA of rank lag — the straggler_persistent
      anomaly rule, so --obs-halt-on covers it).
  ledger.py   — comm-model ledger: joins measured per-step T_comm (attr
      records) and wire bytes (obs counters) against the alpha-beta
      scaling model (benchmarks/scaling_model.predict, fed by
      dcn_probe's fitted alpha/beta when present) into
      predicted-vs-measured ratio rows.
  exporter.py — live OpenMetrics endpoint (``--obs-export-port``):
      stdlib http.server thread serving the latest value of every
      metric field at localhost:PORT/metrics; wired in as the
      MetricsLogger sink.
  calib.py    — online comm-model calibrator (``--obs-calib``): fits
      {alpha_ms, beta_gbps} live from the run's own measured
      (wire_bytes, t_comm) samples with an outlier-robust Theil-Sen
      estimator, logs "calib" records per refit window, feeds the
      comm_model_drift anomaly rule, and writes a dcn_probe-compatible
      calib_fit_{P}proc.json artifact at end of run that the planner
      consumes next run — the obs->planner loop, closed.
  memwatch.py — compile- and memory-plane watch (``--obs-mem``): AOT
      compile accounting (one fsync'd "compile" record per distinct
      dispatch shape — cost/memory analysis, lower/compile wall times,
      peak-HBM estimate stamped into the manifest; benchmark.py's MFU
      consumes the same cost extraction), a jit executable-cache
      recompile watch feeding the recompile_storm rule, and sampled
      live-memory "mem" records (jax.live_arrays + per-device
      memory_stats where the backend exposes them) feeding the
      device_mem_leak / hbm_headroom rules.
  registry.py — append-only cross-run registry (``--registry DIR``):
      one runs.jsonl line per run (manifest subset + steps/sec, comm
      ratio, fitted alpha/beta, recall floor, wire bytes/step); read
      back offline via ``report history`` (trend table keyed by
      config_hash) and ``report regress`` (current run vs registry
      baseline under per-field rtol checks, gate exit contract).

Per-layer counters (counters.LAYER_FIELDS, flag-gated): achieved
density, tau, pre/post-compression norms, error-feedback residual norm
and mean residual AGE (steps since a coordinate last shipped), and the
mass-capture ratio m(k) = ||selected||^2/||acc||^2 whose per-layer skew
explains top-k convergence gaps (arXiv:1911.08772) — plus a sampled
exact-vs-production top-k recall audit reusing ops.topk's exact path as
ground truth.
"""

from gtopkssgd_tpu.obs.calib import (
    CommCalibrator,
    fit_alpha_beta,
    load_fit_file,
    message_count,
)
from gtopkssgd_tpu.obs.counters import (
    LAYER_FIELDS,
    TELEMETRY_FIELDS,
    keep_tau,
    layer_names,
    make_telemetry,
    mass_ratio,
    selected_tau,
    sent_count,
    telemetry_scalars,
    topk_recall,
    tree_l2,
    zero_telemetry,
)
from gtopkssgd_tpu.obs.events import (
    HALT_EXIT_CODE,
    AnomalyHalt,
    AnomalyMonitor,
    Thresholds,
)
from gtopkssgd_tpu.obs.exporter import MetricsExporter
from gtopkssgd_tpu.obs.manifest import (
    config_hash,
    coordinator_address,
    git_sha,
    run_manifest,
)
from gtopkssgd_tpu.obs.memwatch import (
    CompileWatch,
    MemWatch,
    batch_shape_key,
    compiled_flops,
    cost_summary,
    memory_summary,
)
from gtopkssgd_tpu.obs.timeline import (
    TimelineRecorder,
    timeline_from_records,
    validate_timeline,
)
from gtopkssgd_tpu.obs.tracing import Tracer
from gtopkssgd_tpu.obs.watchdog import StallWatchdog

__all__ = [
    "HALT_EXIT_CODE",
    "LAYER_FIELDS",
    "TELEMETRY_FIELDS",
    "AnomalyHalt",
    "AnomalyMonitor",
    "CommCalibrator",
    "CompileWatch",
    "MemWatch",
    "MetricsExporter",
    "Thresholds",
    "TimelineRecorder",
    "Tracer",
    "StallWatchdog",
    "batch_shape_key",
    "compiled_flops",
    "config_hash",
    "coordinator_address",
    "cost_summary",
    "fit_alpha_beta",
    "git_sha",
    "keep_tau",
    "memory_summary",
    "layer_names",
    "load_fit_file",
    "make_telemetry",
    "mass_ratio",
    "message_count",
    "run_manifest",
    "selected_tau",
    "sent_count",
    "telemetry_scalars",
    "timeline_from_records",
    "topk_recall",
    "tree_l2",
    "validate_timeline",
    "zero_telemetry",
]
