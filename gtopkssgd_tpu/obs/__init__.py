"""Unified observability subsystem (the paper's measured decomposition,
made first-class).

The paper's entire argument is a measured decomposition — compute vs.
selection vs. communication time and the sparsity achieved on the wire
(arXiv:1901.04359; arXiv:1911.08772 ties convergence to the error-feedback
residual dynamics). This package turns the repo's scattered primitives
(host timers, a bare jsonl logger, a --profile-dir flag) into one layer:

  counters.py — on-device training-health counters computed INSIDE the
      jitted step (achieved density, top-k threshold tau, pre/post
      compression gradient norms, error-feedback residual norm, wire
      bytes) and carried out through the optimizer state, so compression
      quality is a per-step metric for every mode.
  tracing.py  — span API emitting BOTH host-side records (metrics.jsonl /
      TimingStats) and jax.profiler.TraceAnnotation scopes, so device
      traces and host timelines correlate on the same names.
  watchdog.py — dispatch stall watchdog: a monitor thread that detects a
      dispatched step failing to become ready within a deadline (the
      BENCH_r05 dead-tunnel mode), emits a structured diagnostic and
      fails fast instead of hanging.
  report.py   — ``python -m gtopkssgd_tpu.obs.report`` aggregates one or
      two metrics.jsonl runs into per-kind/per-metric summaries and a
      side-by-side regression-triage comparison.
"""

from gtopkssgd_tpu.obs.counters import (
    TELEMETRY_FIELDS,
    keep_tau,
    make_telemetry,
    selected_tau,
    sent_count,
    tree_l2,
    zero_telemetry,
)
from gtopkssgd_tpu.obs.tracing import Tracer
from gtopkssgd_tpu.obs.watchdog import StallWatchdog

__all__ = [
    "TELEMETRY_FIELDS",
    "Tracer",
    "StallWatchdog",
    "keep_tau",
    "make_telemetry",
    "selected_tau",
    "sent_count",
    "tree_l2",
    "zero_telemetry",
]
