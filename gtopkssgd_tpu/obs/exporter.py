"""Live OpenMetrics export: scrape the run instead of tailing its files.

``MetricsExporter`` keeps the LATEST numeric value of every metrics
record field it observes and serves them as OpenMetrics text on a
localhost HTTP port — `curl localhost:9100/metrics` (or a Prometheus
scraper pointed at it) answers "what is this run doing right now"
without shell access to the metrics dir. Off by default; enabled with
``--obs-export-port`` (Trainer wires ``observe`` in as the
MetricsLogger sink, so export sees exactly the records the shard gets,
including on non-writing ranks).

Zero new dependencies: stdlib ``http.server`` ThreadingHTTPServer on a
daemon thread, bound to 127.0.0.1 only (export is a local diagnostic,
not a network service — put a real scraper's relabeling/auth in front if
it must leave the host). Sink errors are swallowed by MetricsLogger, so
a wedged exporter can never take down training.

Exposition format follows the OpenMetrics text spec: gauge families
named ``gtopk_<kind>_<field>``, record string fields become labels
(e.g. fleet rows' ``src``/``field``), ``rank`` is always a label, body
ends with ``# EOF``.
"""

from __future__ import annotations

import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
# Record fields that never become samples or labels.
_META_FIELDS = {"kind", "time"}
# Label values are clipped so a pathological record (a long message
# string) cannot bloat every scrape forever.
_MAX_LABEL_LEN = 120


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    return (value.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_value(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class MetricsExporter:
    """Latest-value store + HTTP endpoint.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    exposed as ``.port`` after ``start()``. ``observe(rec)`` matches the
    MetricsLogger sink signature. Thread-safe: observe happens on the
    training thread, scrapes on the server's handler threads.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 prefix: str = "gtopk"):
        self.host = host
        self.port = port
        self.prefix = _sanitize(prefix)
        self._lock = threading.Lock()
        # {(family, labels-tuple): value}; insertion order groups scrapes.
        self._samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            float] = {}
        # family -> wall time of its newest observation. The
        # latest-value store serves stale gauges forever (a dead rank
        # looks healthy on scrape); the per-family
        # ``<prefix>_scrape_age_seconds`` gauge derived from this map
        # is how a scraper tells "fresh" from "fossil".
        self._family_seen: Dict[str, float] = {}
        self._n_records = 0
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- ingest
    def observe(self, rec: Dict[str, Any]) -> None:
        """MetricsLogger sink: fold one record into the latest-value
        store. String fields become labels shared by every numeric field
        of the record (so a fleet row's min/max land under
        src=…,field=… labels); numeric fields become gauge samples."""
        kind = rec.get("kind")
        if not isinstance(kind, str) or not kind:
            return
        labels = [("rank", str(rec.get("rank", 0)))]
        numeric = {}
        for key, val in rec.items():
            if key in _META_FIELDS or key == "rank":
                continue
            if isinstance(val, bool):
                numeric[key] = 1.0 if val else 0.0
            elif isinstance(val, (int, float)) and math.isfinite(val):
                numeric[key] = float(val)
            elif isinstance(val, str):
                labels.append((_sanitize(key), val[:_MAX_LABEL_LEN]))
        label_key = tuple(sorted(labels))
        stamp = rec.get("time")
        stamp = (float(stamp) if isinstance(stamp, (int, float))
                 and math.isfinite(stamp) else time.time())
        # Weather-map records additionally fan out their per-link list
        # into <prefix>_link_* families with (link, axis, src, dst)
        # labels — the scrapeable form of `report linkmap`.
        link_samples = []
        if kind == "linkmap" and isinstance(rec.get("links"), list):
            for link in rec["links"]:
                if not isinstance(link, dict):
                    continue
                link_labels = tuple(sorted(
                    [("rank", str(rec.get("rank", 0)))]
                    + [(name, str(link.get(name, ""))[:_MAX_LABEL_LEN])
                       for name in ("link", "axis", "src", "dst")]))
                for field in ("ewma_ms", "ewma_gbps", "n"):
                    val = link.get(field)
                    if isinstance(val, (int, float)) and math.isfinite(val):
                        link_samples.append(
                            (f"{self.prefix}_link_{_sanitize(field)}",
                             link_labels, float(val)))
        with self._lock:
            self._n_records += 1
            for field, val in numeric.items():
                family = f"{self.prefix}_{_sanitize(kind)}_{_sanitize(field)}"
                self._samples[(family, label_key)] = val
                self._family_seen[family] = stamp
            for family, lk, val in link_samples:
                self._samples[(family, lk)] = val
                self._family_seen[family] = stamp

    # ------------------------------------------------------------- expose
    def scrape(self, now: Optional[float] = None) -> str:
        """The OpenMetrics exposition body (also what GET /metrics
        serves): `# TYPE` line per family, samples grouped under it,
        terminated by `# EOF`. Every family additionally gets a
        ``<prefix>_scrape_age_seconds{family=...}`` gauge — seconds
        since its newest observation — because the latest-value store
        otherwise serves a dead rank's last gauges forever and it looks
        healthy. ``now`` overrides the clock (tests)."""
        with self._lock:
            samples = dict(self._samples)
            seen = dict(self._family_seen)
            n = self._n_records
        now = time.time() if now is None else float(now)
        by_family: Dict[str, list] = {}
        for (family, labels), val in samples.items():
            by_family.setdefault(family, []).append((labels, val))
        lines = []
        meta_family = f"{self.prefix}_exporter_records_observed"
        lines.append(f"# TYPE {meta_family} gauge")
        lines.append(f"{meta_family} {n}")
        if seen:
            age_family = f"{self.prefix}_scrape_age_seconds"
            lines.append(f"# TYPE {age_family} gauge")
            for family in sorted(seen):
                age = max(0.0, now - seen[family])
                lines.append(
                    f'{age_family}{{family="{_escape_label(family)}"}} '
                    f"{_fmt_value(age)}")
        for family in sorted(by_family):
            lines.append(f"# TYPE {family} gauge")
            for labels, val in sorted(by_family[family]):
                if labels:
                    body = ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in labels)
                    lines.append(f"{family}{{{body}}} {_fmt_value(val)}")
                else:
                    lines.append(f"{family} {_fmt_value(val)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "MetricsExporter":
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter.scrape().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-exporter",
            daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
